"""Telemetry-driven autotuning (tuning/, docs/autotuning.md).

The load-bearing contract is COLD-START IDENTITY: with an empty store
(or TX_TUNE=off) every consumer — serving coalescer/bucket range,
racing schedule, fit placement — must behave bitwise identically to
the static defaults. The tuned paths are then checked against a
hand-seeded store, and the override block (`tx tune --set`) must
round-trip through a fresh policy.
"""
import json
import os
import subprocess
import sys

import pytest

from transmogrifai_tpu.observability.store import (ProfileStore,
                                                   persist_process_profiles)
from transmogrifai_tpu.tuning.model import (DEFAULT, INTERPOLATED,
                                            RECORDED, CostModel)
from transmogrifai_tpu.tuning.policy import TuningPolicy, tuning_enabled
from transmogrifai_tpu.tuning.registry import (KNOBS, STATIC_DEFAULTS,
                                               static_default)


def _seed_store(path, records):
    ProfileStore(path).record_profiles(records)
    return path


def _bucket_rec(calls, wall, compile_s, rows=0):
    return {"calls": calls, "wall_seconds": wall,
            "compile_seconds": compile_s,
            "execute_seconds": max(wall - compile_s, 0.0),
            "rows": rows}


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

class TestRegistry:
    def test_static_defaults_cover_every_knob(self):
        assert set(STATIC_DEFAULTS) == {k.name for k in KNOBS}
        assert STATIC_DEFAULTS["serving.target_batch"] == 64
        assert STATIC_DEFAULTS["serving.min_bucket"] == 8
        assert STATIC_DEFAULTS["serving.max_bucket"] == 8192
        assert STATIC_DEFAULTS["search.eta"] == 3

    def test_static_default_unknown_knob_raises(self):
        with pytest.raises(KeyError):
            static_default("serving.nope")

    def test_consumers_import_the_registry_defaults(self):
        from transmogrifai_tpu.plans.common import (DEFAULT_MAX_BUCKET,
                                                    DEFAULT_MIN_BUCKET)
        from transmogrifai_tpu.serving.server import _DEFAULT_TARGET
        assert _DEFAULT_TARGET == STATIC_DEFAULTS["serving.target_batch"]
        assert DEFAULT_MIN_BUCKET == STATIC_DEFAULTS["serving.min_bucket"]
        assert DEFAULT_MAX_BUCKET == STATIC_DEFAULTS["serving.max_bucket"]


# ---------------------------------------------------------------------------
# cost model
# ---------------------------------------------------------------------------

class TestCostModel:
    def test_recorded_lookup_is_per_call_mean(self, tmp_path):
        path = _seed_store(str(tmp_path / "s.json"), {
            "score:b64": _bucket_rec(4, 2.0, 1.2)})
        m = CostModel.from_store(path)
        est = m.predict("score", bucket=64)
        assert est.confidence == RECORDED
        assert est.wall == pytest.approx(0.5)
        assert est.compile == pytest.approx(0.3)
        assert est.execute == pytest.approx(0.2)
        assert est.calls == 4

    def test_empty_store_is_default_confidence(self, tmp_path):
        m = CostModel.from_store(str(tmp_path / "absent.json"))
        est = m.predict("score", bucket=64)
        assert est.confidence == DEFAULT and not est.known()
        assert est.wall is None

    def test_log_space_interpolation_between_buckets(self, tmp_path):
        # wall(8)=0.1, wall(64)=0.8 -> at b16 (log2=4, one third of the
        # way from 3 to 6) the log-log line gives 0.1^(2/3)*0.8^(1/3)
        path = _seed_store(str(tmp_path / "s.json"), {
            "score:b8": _bucket_rec(1, 0.1, 0.0),
            "score:b64": _bucket_rec(1, 0.8, 0.0)})
        est = CostModel.from_store(path).predict("score", bucket=16)
        assert est.confidence == INTERPOLATED
        assert est.wall == pytest.approx(0.1 ** (2 / 3) * 0.8 ** (1 / 3),
                                         rel=1e-6)

    def test_single_point_nearest_neighbor(self, tmp_path):
        path = _seed_store(str(tmp_path / "s.json"), {
            "score:b32": _bucket_rec(2, 0.4, 0.0)})
        est = CostModel.from_store(path).predict("score", bucket=128)
        assert est.confidence == INTERPOLATED
        assert est.wall == pytest.approx(0.2)

    def test_family_totals_aggregate(self, tmp_path):
        path = _seed_store(str(tmp_path / "s.json"), {
            "family:A": _bucket_rec(2, 2.0, 1.0),
            "family:B": _bucket_rec(2, 4.0, 3.0)})
        fam = CostModel.from_store(path).family_totals()
        assert fam.calls == 4
        assert fam.wall == pytest.approx(1.5)
        assert fam.compile == pytest.approx(1.0)

    def test_placement_records_parse(self, tmp_path):
        path = _seed_store(str(tmp_path / "s.json"), {
            "placement:SanityChecker:device": _bucket_rec(
                2, 1.0, 0.6, rows=100),
            "placement:bad": _bucket_rec(1, 1.0, 0.0)})
        recs = CostModel.from_store(path).placement_records()
        assert set(recs) == {("SanityChecker", "device")}
        assert recs[("SanityChecker", "device")]["seconds"] \
            == pytest.approx(1.0)

    def test_reserved_keys_are_invisible(self, tmp_path):
        path = str(tmp_path / "s.json")
        _seed_store(path, {"score:b8": _bucket_rec(1, 0.1, 0.0)})
        m = CostModel.from_store(path)
        assert "_schema" not in m.records
        assert set(m.recorded_buckets("score")) == {8}


# ---------------------------------------------------------------------------
# cold-start identity: the contract the whole layer hangs on
# ---------------------------------------------------------------------------

class TestColdStartIdentity:
    def test_empty_store_every_decision_is_the_static_default(self):
        policy = TuningPolicy()            # conftest points at a tmp store
        for d in policy.decisions(max_wait_ms=5.0, max_batch=256):
            if d.knob in STATIC_DEFAULTS:
                assert d.chosen == STATIC_DEFAULTS[d.knob], d.knob
            assert d.source == "default", d.knob
            assert not d.tuned(), d.knob

    def test_tx_tune_off_disables_a_populated_store(self, tmp_path,
                                                    monkeypatch):
        path = _seed_store(str(tmp_path / "s.json"), {
            "score:b8": _bucket_rec(4, 0.4, 0.39),
            "score:b64": _bucket_rec(4, 0.5, 0.45),
            "family:GBT": _bucket_rec(3, 9.0, 6.0)})
        monkeypatch.setenv("TX_TUNE", "off")
        assert not tuning_enabled()
        policy = TuningPolicy(path=path)
        for d in policy.decisions():
            if d.knob in STATIC_DEFAULTS:
                assert d.chosen == STATIC_DEFAULTS[d.knob], d.knob
            assert d.source == "disabled", d.knob

    def test_server_cold_store_matches_static_defaults(self):
        from transmogrifai_tpu.serving.server import (_DEFAULT_TARGET,
                                                      ServeConfig,
                                                      ServingServer)
        server = ServingServer(ServeConfig(sentinel=False))
        assert server._target_decision.chosen == _DEFAULT_TARGET
        assert server._target_decision.source == "default"
        # plan-cache key stays the untuned (None, None) pair
        assert server.plan_buckets == (None, None)
        assert server.prewarm() == {}

    def test_racing_cold_store_is_the_classic_ladder(self):
        from transmogrifai_tpu.evaluators import \
            BinaryClassificationEvaluator
        from transmogrifai_tpu.selector.racing import \
            RacingCrossValidation
        r = RacingCrossValidation(BinaryClassificationEvaluator())
        assert r.eta == 3
        assert r.min_fidelity == pytest.approx(1.0 / 9.0)

    def test_racing_tx_tune_off_is_the_classic_ladder(self, tmp_path,
                                                      monkeypatch):
        path = _seed_store(str(tmp_path / "s.json"), {
            "family:GBT": _bucket_rec(3, 9.0, 8.5)})
        monkeypatch.setenv("TX_PROFILE_STORE", path)
        monkeypatch.setenv("TX_TUNE", "off")
        from transmogrifai_tpu.evaluators import \
            BinaryClassificationEvaluator
        from transmogrifai_tpu.selector.racing import \
            RacingCrossValidation
        r = RacingCrossValidation(BinaryClassificationEvaluator())
        assert (r.eta, r.min_fidelity) == (3, pytest.approx(1.0 / 9.0))

    def test_racing_caller_args_always_win(self, tmp_path, monkeypatch):
        path = _seed_store(str(tmp_path / "s.json"), {
            "family:GBT": _bucket_rec(3, 9.0, 8.5)})
        monkeypatch.setenv("TX_PROFILE_STORE", path)
        from transmogrifai_tpu.evaluators import \
            BinaryClassificationEvaluator
        from transmogrifai_tpu.selector.racing import \
            RacingCrossValidation
        r = RacingCrossValidation(BinaryClassificationEvaluator(),
                                  eta=4, min_fidelity=0.25)
        assert (r.eta, r.min_fidelity) == (4, 0.25)
        assert r.tuning_decisions == []

    def test_placement_cold_store_stays_optimistic_device(self):
        from transmogrifai_tpu.plans.placement import (PlacementPolicy,
                                                       reset_placement)
        reset_placement()
        try:
            policy = PlacementPolicy("auto")
            assert policy.margin == pytest.approx(1.0)

            class DevStage:
                def supports_device_fit(self):
                    return True

            where, reason = policy.decide_fit(DevStage(), 100)
            assert where == "device"
            assert "no record yet" in reason
        finally:
            reset_placement()


# ---------------------------------------------------------------------------
# tuned decisions from a seeded store
# ---------------------------------------------------------------------------

class TestTunedDecisions:
    def test_target_batch_largest_bucket_inside_budget(self, tmp_path):
        # per-call execute: b8 1ms, b64 4ms, b256 20ms; 5ms budget
        # -> 64 is the largest fit
        path = _seed_store(str(tmp_path / "s.json"), {
            "score:b8": _bucket_rec(10, 0.01, 0.0),
            "score:b64": _bucket_rec(10, 0.04, 0.0),
            "score:b256": _bucket_rec(10, 0.2, 0.0)})
        d = TuningPolicy(path=path).target_batch(max_wait_ms=5.0,
                                                 max_batch=256)
        assert d.chosen == 64 and d.source == "model"
        assert d.predicted_chosen == pytest.approx(0.004)

    def test_target_batch_nothing_fits_falls_back(self, tmp_path):
        path = _seed_store(str(tmp_path / "s.json"), {
            "score:b8": _bucket_rec(1, 5.0, 0.0)})
        d = TuningPolicy(path=path).target_batch(max_wait_ms=1.0,
                                                 max_batch=64)
        assert d.chosen == STATIC_DEFAULTS["serving.target_batch"]
        assert d.source == "default"

    def test_bucket_range_spans_recorded_shapes(self, tmp_path):
        path = _seed_store(str(tmp_path / "s.json"), {
            "score:b16": _bucket_rec(2, 0.1, 0.0),
            "score:b64": _bucket_rec(2, 0.2, 0.0)})
        lo, hi = TuningPolicy(path=path).bucket_range(max_batch=256)
        assert lo.chosen == 16 and lo.source == "model"
        # the cap grows the top so the serve cap stays reachable
        assert hi.chosen == 256

    def test_prewarm_set_is_the_recorded_buckets(self, tmp_path):
        path = _seed_store(str(tmp_path / "s.json"), {
            "score:b8": _bucket_rec(2, 0.1, 0.05),
            "score:b32": _bucket_rec(2, 0.2, 0.1),
            "score:b512": _bucket_rec(2, 0.9, 0.4)})
        d = TuningPolicy(path=path).prewarm_buckets(max_batch=256)
        assert d.chosen == (8, 32)      # 512 is over the serve cap
        assert d.source == "model"

    def test_racing_schedule_compile_dominated_gets_shallow(
            self, tmp_path):
        path = _seed_store(str(tmp_path / "s.json"), {
            "family:GBT": _bucket_rec(2, 20.0, 19.8)})
        eta, mf, decs = TuningPolicy(path=path).racing_schedule()
        # per-rung compile dominates: the cheapest ladder has the
        # FEWEST rungs (depth 1)
        assert mf == pytest.approx(1.0 / eta)
        assert all(d.source == "model" for d in decs)
        assert decs[0].predicted_chosen <= decs[0].predicted_default

    def test_racing_schedule_tie_prefers_the_static_ladder(
            self, tmp_path):
        # zero recorded seconds -> every candidate predicts 0.0: the
        # deterministic tiebreak must keep (3, 1/9)
        path = _seed_store(str(tmp_path / "s.json"), {
            "family:Z": _bucket_rec(2, 0.0, 0.0)})
        eta, mf, _ = TuningPolicy(path=path).racing_schedule()
        assert (eta, mf) == (3, pytest.approx(1.0 / 9.0))

    def test_server_tuned_store_moves_the_target(self, tmp_path,
                                                 monkeypatch):
        path = _seed_store(str(tmp_path / "s.json"), {
            "score:b8": _bucket_rec(10, 0.001, 0.0),
            "score:b16": _bucket_rec(10, 0.002, 0.0)})
        monkeypatch.setenv("TX_PROFILE_STORE", path)
        from transmogrifai_tpu.serving.server import (ServeConfig,
                                                      ServingServer)
        server = ServingServer(ServeConfig(max_wait_ms=5.0,
                                           sentinel=False))
        assert server._target_decision.source == "model"
        assert server.plan_buckets[0] == 8


# ---------------------------------------------------------------------------
# overrides: tx tune --set / --reset honored by a fresh process
# ---------------------------------------------------------------------------

class TestOverrides:
    def test_override_round_trip_and_coercion(self, tmp_path):
        path = str(tmp_path / "s.json")
        ProfileStore(path).set_tuning_override("serving.target_batch",
                                               "32")
        d = TuningPolicy(path=path).target_batch(5.0, 256)
        assert d.chosen == 32 and isinstance(d.chosen, int)
        assert d.source == "override" and d.tuned()
        ProfileStore(path).clear_tuning_overrides(
            "serving.target_batch")
        d2 = TuningPolicy(path=path).target_batch(5.0, 256)
        assert d2.chosen == STATIC_DEFAULTS["serving.target_batch"]
        assert d2.source == "default"

    def test_prewarm_override_parses_lists_and_strings(self, tmp_path):
        path = str(tmp_path / "s.json")
        ProfileStore(path).set_tuning_override("serving.prewarm",
                                               "64,8")
        d = TuningPolicy(path=path).prewarm_buckets(max_batch=256)
        assert d.chosen == (8, 64) and d.source == "override"

    def test_tx_tune_off_ignores_overrides(self, tmp_path,
                                           monkeypatch):
        path = str(tmp_path / "s.json")
        ProfileStore(path).set_tuning_override("serving.target_batch",
                                               16)
        monkeypatch.setenv("TX_TUNE", "off")
        d = TuningPolicy(path=path).target_batch(5.0, 256)
        assert d.chosen == STATIC_DEFAULTS["serving.target_batch"]
        assert d.source == "disabled"

    def test_override_honored_by_fresh_subprocess(self, tmp_path):
        path = str(tmp_path / "s.json")
        ProfileStore(path).set_tuning_override("search.eta", 4)
        code = (
            "import json, os\n"
            "os.environ['TX_PROFILE_STORE'] = %r\n"
            "from transmogrifai_tpu.tuning.policy import TuningPolicy\n"
            "eta, mf, _ = TuningPolicy().racing_schedule()\n"
            "print(json.dumps({'eta': eta, 'mf': mf}))\n" % path)
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True,
                              timeout=120,
                              env=dict(os.environ,
                                       JAX_PLATFORMS="cpu"))
        assert proc.returncode == 0, proc.stderr
        got = json.loads(proc.stdout.strip().splitlines()[-1])
        assert got["eta"] == 4


# ---------------------------------------------------------------------------
# tx tune CLI
# ---------------------------------------------------------------------------

class TestTuneCli:
    def _run(self, args, capsys):
        from transmogrifai_tpu.cli.tune import main
        rc = main(["tune"] + args)
        return rc, capsys.readouterr().out

    def test_table_renders_every_knob(self, tmp_path, capsys):
        rc, out = self._run(["--store", str(tmp_path / "s.json")],
                            capsys)
        assert rc == 0
        for knob in STATIC_DEFAULTS:
            assert knob in out
        assert "prepare.placement_seed" in out

    def test_explain_renders_every_reason(self, tmp_path, capsys):
        rc, out = self._run(["--store", str(tmp_path / "s.json"),
                             "--explain"], capsys)
        assert rc == 0
        assert out.count("why:") == 14   # one per decision

    def test_set_then_json_then_reset(self, tmp_path, capsys):
        store = str(tmp_path / "s.json")
        rc, out = self._run(["--store", store, "--set",
                             "serving.target_batch=32"], capsys)
        assert rc == 0 and "set serving.target_batch" in out
        rc, out = self._run(["--store", store, "--format", "json"],
                            capsys)
        doc = json.loads(out)
        assert doc["overrides"] == {"serving.target_batch": 32}
        chosen = {d["knob"]: d for d in doc["decisions"]}
        assert chosen["serving.target_batch"]["chosen"] == 32
        assert chosen["serving.target_batch"]["source"] == "override"
        rc, _ = self._run(["--store", store, "--reset"], capsys)
        assert rc == 0
        assert ProfileStore(store).tuning_overrides() == {}

    def test_unknown_knob_is_an_error(self, tmp_path, capsys):
        rc, out = self._run(["--store", str(tmp_path / "s.json"),
                             "--set", "serving.bogus=1"], capsys)
        assert rc == 2 and "error:" in out

    def test_disabled_banner(self, tmp_path, capsys, monkeypatch):
        monkeypatch.setenv("TX_TUNE", "off")
        rc, out = self._run(["--store", str(tmp_path / "s.json")],
                            capsys)
        assert rc == 0 and "DISABLED" in out


# ---------------------------------------------------------------------------
# placement seeding (satellite: record_fit persists; seeds never
# double-count)
# ---------------------------------------------------------------------------

class _SeededStage:
    def supports_device_fit(self):
        return True


class TestPlacementSeeding:
    def test_host_only_seed_places_host_on_first_fit(self, tmp_path,
                                                     monkeypatch):
        path = _seed_store(str(tmp_path / "s.json"), {
            "placement:_SeededStage:host": _bucket_rec(
                2, 0.2, 0.0, rows=50)})
        monkeypatch.setenv("TX_PROFILE_STORE", path)
        from transmogrifai_tpu.plans.placement import (PlacementPolicy,
                                                       reset_placement)
        reset_placement()
        try:
            where, reason = PlacementPolicy("auto").decide_fit(
                _SeededStage(), 100)
            assert where == "host"
            assert "cross-run seed" in reason
        finally:
            reset_placement()

    def test_seed_comparison_prefers_recorded_cheaper_side(
            self, tmp_path, monkeypatch):
        path = _seed_store(str(tmp_path / "s.json"), {
            "placement:_SeededStage:device": _bucket_rec(
                2, 2.0, 0.0, rows=50),
            "placement:_SeededStage:host": _bucket_rec(
                2, 0.2, 0.0, rows=50)})
        monkeypatch.setenv("TX_PROFILE_STORE", path)
        from transmogrifai_tpu.plans.placement import (PlacementPolicy,
                                                       reset_placement)
        reset_placement()
        try:
            where, reason = PlacementPolicy("auto").decide_fit(
                _SeededStage(), 100)
            assert where == "host" and "cross-run seed" in reason
        finally:
            reset_placement()

    def test_process_local_record_wins_over_seed(self, tmp_path,
                                                 monkeypatch):
        path = _seed_store(str(tmp_path / "s.json"), {
            "placement:_SeededStage:host": _bucket_rec(
                2, 0.2, 0.0, rows=50)})
        monkeypatch.setenv("TX_PROFILE_STORE", path)
        from transmogrifai_tpu.plans.placement import (PlacementPolicy,
                                                       reset_placement)
        reset_placement()
        try:
            policy = PlacementPolicy("auto")
            PlacementPolicy.record_fit(_SeededStage(), "device",
                                       0.001, 0.0, 100)
            where, _ = policy.decide_fit(_SeededStage(), 100)
            assert where == "device"     # measured beats seeded
        finally:
            reset_placement()

    def test_record_fit_persists_and_seeds_never_do(self, tmp_path,
                                                    monkeypatch):
        path = _seed_store(str(tmp_path / "s.json"), {
            "placement:_SeededStage:host": _bucket_rec(
                2, 0.2, 0.0, rows=50)})
        monkeypatch.setenv("TX_PROFILE_STORE", path)
        from transmogrifai_tpu.plans.placement import (PlacementPolicy,
                                                       placement_report,
                                                       reset_placement)
        reset_placement()
        try:
            policy = PlacementPolicy("auto")
            policy.decide_fit(_SeededStage(), 100)   # loads the seed
            PlacementPolicy.record_fit(_SeededStage(), "device",
                                       0.5, 0.1, 100)
            # the report (and so the persisted records) carries ONLY
            # what this process measured, never the loaded seed
            rows = placement_report()
            assert [(r["stage"], r["placement"]) for r in rows] \
                == [("_SeededStage", "device")]
            persist_process_profiles(path)
            rec = ProfileStore(path).profiles(
                "placement:_SeededStage:host")
            # host seconds unchanged: seed was not re-persisted
            assert rec["placement:_SeededStage:host"]["wall_seconds"] \
                == pytest.approx(0.2)
        finally:
            reset_placement()


# ---------------------------------------------------------------------------
# store hardening: schema, key cap, compaction (satellite)
# ---------------------------------------------------------------------------

class TestStoreHardening:
    def test_schema_stamp(self, tmp_path):
        path = str(tmp_path / "s.json")
        ProfileStore(path).record_profiles(
            {"score:b8": _bucket_rec(1, 0.1, 0.0)})
        meta = ProfileStore(path).meta()
        assert meta["schema"] == 1
        assert meta["compacted"] is None

    def test_key_cap_merges_out_lowest_calls_loudly(self, tmp_path,
                                                    monkeypatch):
        monkeypatch.setenv("TX_PROFILE_KEY_CAP", "3")
        path = str(tmp_path / "s.json")
        store = ProfileStore(path)
        store.record_profiles({
            f"score:b{2 ** i}": _bucket_rec(i + 1, float(i + 1), 0.0)
            for i in range(6)})          # 6 keys, cap 3
        kept = store.profiles()
        assert len(kept) == 3
        # deterministic order: lowest calls out first -> the three
        # highest-calls records survive
        assert set(kept) == {"score:b8", "score:b16", "score:b32"}
        marker = store.meta()["compacted"]
        assert marker["keys"] == 3
        assert marker["calls"] == 1 + 2 + 3
        # no cost mass lost: kept + marker == everything written
        total = sum(r["calls"] for r in kept.values()) \
            + marker["calls"]
        assert total == sum(range(1, 7))

    def test_reserved_keys_never_accepted_from_writers(self, tmp_path):
        path = str(tmp_path / "s.json")
        store = ProfileStore(path)
        store.record_profiles({"_schema": {"calls": 9},
                               "score:b8": _bucket_rec(1, 0.1, 0.0)})
        assert store.meta()["schema"] == 1       # not clobbered
        assert set(store.profiles()) == {"score:b8"}

    def test_concurrent_writers_lose_nothing(self, tmp_path):
        """Two subprocesses each merge N distinct keys through the
        flock'd read-merge-write: every record survives and the file
        stays valid JSON (the satellite's teeth)."""
        path = str(tmp_path / "s.json")
        n = 20
        code = (
            "import sys\n"
            "from transmogrifai_tpu.observability.store import "
            "ProfileStore\n"
            "store = ProfileStore(%r)\n"
            "tag = sys.argv[1]\n"
            "for i in range(%d):\n"
            "    store.record_profiles({f'score:{tag}{i}:b8': "
            "{'calls': 1, 'wall_seconds': 0.01, "
            "'compile_seconds': 0.0, 'execute_seconds': 0.01, "
            "'rows': 8}})\n" % (path, n))
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        procs = [subprocess.Popen([sys.executable, "-c", code, tag],
                                  env=env, stdout=subprocess.PIPE,
                                  stderr=subprocess.PIPE)
                 for tag in ("a", "b")]
        for p in procs:
            _, err = p.communicate(timeout=240)
            assert p.returncode == 0, err.decode()
        with open(path, encoding="utf-8") as fh:
            json.load(fh)                        # never torn
        profiles = ProfileStore(path).profiles()
        for tag in ("a", "b"):
            for i in range(n):
                key = f"score:{tag}{i}:b8"
                assert key in profiles, f"lost {key}"
                assert profiles[key]["calls"] == 1


# ---------------------------------------------------------------------------
# autotune trail (bench writes it; the store must round-trip it)
# ---------------------------------------------------------------------------

class TestAutotuneTrail:
    def test_record_autotune_round_trips(self, tmp_path):
        path = str(tmp_path / "s.json")
        doc = {"decisions": [{"knob": "search.eta", "chosen": 4}],
               "axes_no_worse": 3}
        ProfileStore(path).record_autotune(doc)
        got = ProfileStore(path).load()["autotune"]
        assert got["axes_no_worse"] == 3
        assert got["decisions"][0]["knob"] == "search.eta"
        assert "time" in got
