"""Value -> feature-type conversion syntax (types/conversions.py;
reference features/.../types/package.scala:42-152 implicit enrichments
used inside extract functions)."""
import pytest

from transmogrifai_tpu.types import (
    Binary, FeatureTypeError, PickList, Real, RealNN, Text,
    to_binary, to_date, to_date_list, to_email, to_geolocation,
    to_integral, to_multi_pick_list, to_op_vector, to_pick_list,
    to_real, to_real_nn, to_text,
)


class TestTextFamily:
    def test_to_text(self):
        assert isinstance(to_text("abc"), Text)
        assert to_text("abc").value == "abc"
        assert to_text(None).is_empty

    def test_to_email_pick_list(self):
        assert to_email("a@b.co").value == "a@b.co"
        assert isinstance(to_pick_list("m"), PickList)


class TestNumerics:
    def test_to_real(self):
        r = to_real(2)
        assert isinstance(r, Real) and r.value == 2.0
        assert to_real(None).is_empty

    def test_to_real_unwraps_features(self):
        assert to_real(Real(2.5)).value == 2.5
        assert to_real(RealNN(1.0)).value == 1.0

    def test_to_real_nn_default(self):
        assert to_real_nn(None, default=7.0).value == 7.0
        assert to_real_nn(3.0).value == 3.0

    def test_to_real_nn_empty_raises(self):
        with pytest.raises(FeatureTypeError):
            to_real_nn(None)

    def test_to_integral_date(self):
        assert to_integral(5).value == 5
        assert to_date(1234).value == 1234

    def test_to_binary_numeric_semantics(self):
        # JDoubleConversions.toBinary: v != 0 (package.scala:106)
        assert to_binary(2.0).value is True
        assert to_binary(0).value is False
        assert to_binary(True).value is True
        assert to_binary(None).is_empty
        assert isinstance(to_binary(1), Binary)


class TestCollections:
    def test_lists_sets_vectors(self):
        assert to_multi_pick_list({"a", "b"}).value == frozenset({"a", "b"})
        assert list(to_date_list([1, 2]).value) == [1, 2]
        assert to_geolocation([37.7, -122.4, 5.0]).value[0] == 37.7
        assert to_op_vector([1.0, 0.0]).value.shape == (2,)


class TestNumpyScalars:
    def test_numpy_scalars_convert(self):
        import numpy as np
        assert to_binary(np.int64(2)).value is True
        assert to_binary(np.bool_(True)).value is True
        assert to_binary(np.float64(0.0)).value is False
        assert to_real(np.float32(1.5)).value == pytest.approx(1.5)

    def test_function_names_match_exports(self):
        from transmogrifai_tpu.types import conversions as c
        for name in c.__all__:
            assert getattr(c, name).__name__ == name
