"""Feature type system tests (reference: features/src/test/.../types/)."""
import math

import numpy as np
import pytest

from transmogrifai_tpu import types as T
from transmogrifai_tpu.types import FeatureTypeError


class TestNumerics:
    def test_real(self):
        assert T.Real(3.5).value == 3.5
        assert T.Real(None).is_empty
        assert T.Real(float("nan")).is_empty
        assert T.Real(3).value == 3.0

    def test_real_nn_rejects_empty(self):
        with pytest.raises(FeatureTypeError):
            T.RealNN(None)
        assert T.RealNN(1.0).value == 1.0

    def test_binary(self):
        assert T.Binary(True).value is True
        assert T.Binary(0.0).value is False
        assert T.Binary(None).is_empty
        with pytest.raises(FeatureTypeError):
            T.Binary(2.0)

    def test_integral(self):
        assert T.Integral(7).value == 7
        assert T.Integral(7.0).value == 7
        with pytest.raises(FeatureTypeError):
            T.Integral(7.5)

    def test_date_hierarchy(self):
        assert issubclass(T.DateTime, T.Date)
        assert issubclass(T.Date, T.Integral)
        assert issubclass(T.Currency, T.Real)
        assert issubclass(T.Percent, T.Real)


class TestText:
    def test_text(self):
        assert T.Text("abc").value == "abc"
        assert T.Text(None).is_empty
        # reference semantics: Text(Some("")) is non-empty (Text.scala:48)
        assert not T.Text("").is_empty

    def test_email_parts(self):
        e = T.Email("joe@example.com")
        assert e.prefix == "joe"
        assert e.domain == "example.com"
        assert T.Email("not-an-email").prefix is None

    def test_url(self):
        u = T.URL("https://example.com/x")
        assert u.is_valid and u.domain == "example.com" \
            and u.protocol == "https"
        assert not T.URL("gopher://x").is_valid

    def test_base64(self):
        b = T.Base64("aGVsbG8=")
        assert b.as_string() == "hello"

    def test_categorical_markers(self):
        assert issubclass(T.PickList, T.Categorical)
        assert issubclass(T.ComboBox, T.Categorical)
        assert issubclass(T.Country, T.Location)


class TestCollections:
    def test_vector(self):
        v = T.OPVector([1.0, 2.0])
        assert v.value.tolist() == [1.0, 2.0]
        assert T.OPVector(None).is_empty
        assert v.combine(T.OPVector([3.0])).value.tolist() == [1, 2, 3]

    def test_lists_sets(self):
        assert T.TextList(["a", "b"]).value == ("a", "b")
        assert T.MultiPickList({"x", "y"}).value == frozenset({"x", "y"})
        assert len(T.DateList(None)) == 0

    def test_geolocation(self):
        g = T.Geolocation((37.77, -122.42, 1.0))
        assert g.lat == pytest.approx(37.77)
        sphere = g.to_unit_sphere()
        back = T.Geolocation.from_unit_sphere(*sphere)
        assert back.lat == pytest.approx(g.lat)
        assert back.lon == pytest.approx(g.lon)
        with pytest.raises(FeatureTypeError):
            T.Geolocation((200.0, 0.0, 1.0))


class TestMaps:
    def test_text_map(self):
        m = T.TextMap({"a": "x", "b": None})
        assert m.value == {"a": "x"}

    def test_real_map(self):
        m = T.RealMap({"a": 1, "b": 2.5})
        assert m["a"] == 1.0 and m["b"] == 2.5

    def test_prediction(self):
        p = T.Prediction.build(1.0, raw_prediction=[0.2, 0.8],
                               probability=[0.3, 0.7])
        assert p.prediction == 1.0
        assert p.raw_prediction.tolist() == [0.2, 0.8]
        assert p.probability.tolist() == [0.3, 0.7]
        with pytest.raises(FeatureTypeError):
            T.Prediction({"probability_0": 0.3})

    def test_registry_counts(self):
        names = {t.__name__ for t in T.all_feature_types()}
        expected = {
            "Real", "RealNN", "Binary", "Integral", "Percent", "Currency",
            "Date", "DateTime", "Text", "Email", "Base64", "Phone", "ID",
            "URL", "TextArea", "PickList", "ComboBox", "Country", "State",
            "PostalCode", "City", "Street", "OPVector", "TextList",
            "DateList", "DateTimeList", "MultiPickList", "Geolocation",
            "TextMap", "EmailMap", "Base64Map", "PhoneMap", "IDMap",
            "URLMap", "TextAreaMap", "PickListMap", "ComboBoxMap",
            "BinaryMap", "IntegralMap", "RealMap", "PercentMap",
            "CurrencyMap", "DateMap", "DateTimeMap", "MultiPickListMap",
            "CountryMap", "StateMap", "CityMap", "PostalCodeMap",
            "StreetMap", "GeolocationMap", "Prediction",
        }
        assert expected <= names
        assert len(expected) == 52
