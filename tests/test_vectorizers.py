"""Vectorizer tests (reference analogues: core/src/test/.../
RealVectorizerTest, OpOneHotVectorizerTest, SmartTextVectorizerTest,
VectorsCombinerTest, DateToUnitCircleTransformerTest, TransmogrifierTest)."""
import numpy as np
import pytest

from transmogrifai_tpu.features.builder import FeatureBuilder
from transmogrifai_tpu.features.columns import Dataset, FeatureColumn
from transmogrifai_tpu.ops import (BinaryVectorizer, DateToUnitCircleVectorizer,
                                   IntegralVectorizer, MultiPickListVectorizer,
                                   OneHotVectorizer, RealVectorizer,
                                   SmartTextVectorizer, TextHashVectorizer,
                                   VectorsCombiner, tokenize, transmogrify)
from transmogrifai_tpu.types import (Binary, Date, Integral, MultiPickList,
                                     PickList, Real, Text)
from transmogrifai_tpu.utils.vector_meta import NULL_INDICATOR, OTHER_INDICATOR


def _feat(name, ftype):
    return FeatureBuilder.of(name, ftype).extract(
        lambda r: r.get(name)).as_predictor()


class TestRealVectorizer:
    def test_mean_impute_and_null_tracking(self):
        age = _feat("age", Real)
        fare = _feat("fare", Real)
        ds = Dataset({
            "age": FeatureColumn.from_values(Real, [10.0, None, 30.0]),
            "fare": FeatureColumn.from_values(Real, [1.0, 2.0, 3.0])})
        est = RealVectorizer().set_input(age, fare)
        model = est.fit(ds)
        out = model.transform_columns([ds["age"], ds["fare"]])
        # age: mean(10,30)=20 imputed at row 1; null col lights up
        np.testing.assert_allclose(
            out.data, [[10, 0, 1, 0], [20, 1, 2, 0], [30, 0, 3, 0]])
        cols = out.metadata.columns
        assert cols[1].indicator_value == NULL_INDICATOR
        assert cols[0].parent_feature_name == "age"
        assert out.metadata.size == 4

    def test_constant_fill(self):
        age = _feat("age", Real)
        ds = Dataset({"age": FeatureColumn.from_values(Real, [None, 5.0])})
        model = RealVectorizer(fill_with_mean=False, fill_value=-1.0,
                               track_nulls=False).set_input(age).fit(ds)
        out = model.transform_columns([ds["age"]])
        np.testing.assert_allclose(out.data, [[-1.0], [5.0]])


class TestIntegralVectorizer:
    def test_mode_impute(self):
        sib = _feat("sib", Integral)
        ds = Dataset({"sib": FeatureColumn.from_values(
            Integral, [1, 1, 2, None])})
        model = IntegralVectorizer().set_input(sib).fit(ds)
        out = model.transform_columns([ds["sib"]])
        np.testing.assert_allclose(
            out.data, [[1, 0], [1, 0], [2, 0], [1, 1]])


class TestBinaryVectorizer:
    def test_false_fill(self):
        b = _feat("b", Binary)
        ds = Dataset({"b": FeatureColumn.from_values(
            Binary, [True, False, None])})
        out = BinaryVectorizer().set_input(b).transform_columns([ds["b"]])
        np.testing.assert_allclose(out.data, [[1, 0], [0, 0], [0, 1]])


class TestOneHotVectorizer:
    def test_topk_other_null(self):
        sex = _feat("sex", PickList)
        vals = ["m"] * 5 + ["f"] * 3 + ["x"] + [None]
        ds = Dataset({"sex": FeatureColumn.from_values(PickList, vals)})
        model = OneHotVectorizer(top_k=2, min_support=2).set_input(sex).fit(ds)
        assert model.categories == [["m", "f"]]
        out = model.transform_columns([ds["sex"]])
        assert out.width == 4  # m, f, OTHER, NULL
        np.testing.assert_allclose(out.data[0], [1, 0, 0, 0])
        np.testing.assert_allclose(out.data[8], [0, 0, 1, 0])  # "x" -> OTHER
        np.testing.assert_allclose(out.data[9], [0, 0, 0, 1])  # None -> NULL
        ivals = [c.indicator_value for c in out.metadata.columns]
        assert ivals == ["m", "f", OTHER_INDICATOR, NULL_INDICATOR]
        # indicator group covers all 4 columns of the pivot
        groups = out.metadata.indicator_groups()
        assert groups[("sex", "sex")] == [0, 1, 2, 3]

    def test_min_support_filters(self):
        c = _feat("c", PickList)
        vals = ["a"] * 5 + ["b"]  # b below min_support
        ds = Dataset({"c": FeatureColumn.from_values(PickList, vals)})
        model = OneHotVectorizer(top_k=5, min_support=2).set_input(c).fit(ds)
        assert model.categories == [["a"]]


class TestMultiPickListVectorizer:
    def test_multi_hot(self):
        tags = _feat("tags", MultiPickList)
        ds = Dataset({"tags": FeatureColumn.from_values(
            MultiPickList,
            [{"a", "b"}, {"a"}, set(), {"a"}, {"b"}, {"a", "b"}])})
        model = MultiPickListVectorizer(
            top_k=5, min_support=1).set_input(tags).fit(ds)
        out = model.transform_columns([ds["tags"]])
        assert out.width == 4
        row0 = dict(zip(
            [c.indicator_value for c in out.metadata.columns], out.data[0]))
        assert row0["a"] == 1 and row0["b"] == 1
        assert out.data[2][3] == 1.0  # empty set -> NULL indicator


class TestSmartTextVectorizer:
    def test_pivot_low_cardinality(self):
        t = _feat("t", Text)
        vals = (["red"] * 6 + ["blue"] * 5) * 2
        ds = Dataset({"t": FeatureColumn.from_values(Text, vals)})
        model = SmartTextVectorizer(max_cardinality=5).set_input(t).fit(ds)
        assert model.strategies[0][0] == "pivot"
        out = model.transform_columns([ds["t"]])
        assert out.width == 4  # red, blue, OTHER, NULL

    def test_hash_high_cardinality(self):
        t = _feat("t", Text)
        vals = [f"token{i} common" for i in range(40)]
        ds = Dataset({"t": FeatureColumn.from_values(Text, vals)})
        model = SmartTextVectorizer(max_cardinality=10,
                                    num_hashes=16).set_input(t).fit(ds)
        assert model.strategies[0][0] == "hash"
        out = model.transform_columns([ds["t"]])
        assert out.width == 17  # 16 hash buckets + null indicator
        # "common" token hashes to the same bucket in every row
        common_cols = np.sum(np.all(out.data[:, :16] >= 1.0, axis=0))
        assert common_cols >= 1

    def test_tokenize(self):
        assert tokenize("Hello, World! x") == ["hello", "world", "x"]
        assert tokenize(None) == []
        assert tokenize("a bb ccc", min_token_length=2) == ["bb", "ccc"]


class TestDateVectorizer:
    def test_unit_circle(self):
        d = _feat("d", Date)
        noon = 12 * 3600 * 1000
        ds = Dataset({"d": FeatureColumn.from_values(
            Date, [0, noon, None])})
        out = DateToUnitCircleVectorizer(
            time_period="HourOfDay").set_input(d).transform_columns([ds["d"]])
        np.testing.assert_allclose(out.data[0], [0.0, 1.0], atol=1e-12)
        np.testing.assert_allclose(out.data[1], [0.0, -1.0], atol=1e-12)
        np.testing.assert_allclose(out.data[2], [0.0, 0.0])  # missing

    def test_day_of_week(self):
        d = _feat("d", Date)
        # 1970-01-01 was a Thursday; phase = 3/7
        ds = Dataset({"d": FeatureColumn.from_values(Date, [0])})
        out = DateToUnitCircleVectorizer(
            time_period="DayOfWeek").set_input(d).transform_columns([ds["d"]])
        phase = 2 * np.pi * 3 / 7
        np.testing.assert_allclose(
            out.data[0], [np.sin(phase), np.cos(phase)], atol=1e-12)


class TestVectorsCombiner:
    def test_concat_and_metadata_flatten(self):
        r = _feat("r", Real)
        p = _feat("p", PickList)
        ds = Dataset({
            "r": FeatureColumn.from_values(Real, [1.0, 2.0]),
            "p": FeatureColumn.from_values(PickList, ["a", "b"])})
        rv = RealVectorizer(track_nulls=False).set_input(r)
        pv = OneHotVectorizer(top_k=2, min_support=1,
                              track_nulls=False).set_input(p)
        ds2 = rv.fit(ds).transform_dataset(ds)
        ds2 = ds2.with_column(rv.get_output().name,
                              ds2[rv.get_output().name])
        pvm = pv.fit(ds)
        ds2 = pvm.transform_dataset(ds2)
        comb = VectorsCombiner().set_input(rv.get_output(), pv.get_output())
        out = comb.transform_columns(
            [ds2[rv.get_output().name], ds2[pv.get_output().name]])
        assert out.width == 1 + 3
        parents = [c.parent_feature_name for c in out.metadata.columns]
        assert parents == ["r", "p", "p", "p"]


class TestTransmogrify:
    def test_mixed_types_one_vector(self):
        feats = [_feat("age", Real), _feat("n", Integral),
                 _feat("ok", Binary), _feat("sex", PickList),
                 _feat("note", Text)]
        combined = transmogrify(feats)
        ds = Dataset({
            "age": FeatureColumn.from_values(Real, [20.0, None, 40.0]),
            "n": FeatureColumn.from_values(Integral, [1, 2, 2]),
            "ok": FeatureColumn.from_values(Binary, [True, None, False]),
            "sex": FeatureColumn.from_values(PickList, ["m", "f", "m"]),
            "note": FeatureColumn.from_values(Text, ["hi there", None, "yo"]),
        })
        from transmogrifai_tpu.workflow import Workflow
        # drive through the workflow engine: transmogrify is a sub-DAG
        wf = Workflow().set_result_features(combined).set_input_dataset(ds)
        model = wf.train()
        out = model.score(ds, keep_intermediate=True)[combined.name]
        assert out.n_rows == 3
        assert out.width == out.metadata.size
        parents = {c.parent_feature_name for c in out.metadata.columns}
        assert parents == {"age", "n", "ok", "sex", "note"}

    def test_vector_passthrough(self):
        from transmogrifai_tpu.types import OPVector
        v = _feat("v", OPVector)
        r = _feat("x", Real)
        out = transmogrify([v, r])
        assert out.origin_stage.operation_name == "combineVector"
