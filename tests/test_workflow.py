"""Workflow engine tests (reference: core/src/test/.../OpWorkflowTest.scala:61)."""
import numpy as np
import pytest

from transmogrifai_tpu.evaluators import BinaryClassificationEvaluator
from transmogrifai_tpu.features.builder import FeatureBuilder
from transmogrifai_tpu.features.columns import Dataset, FeatureColumn
from transmogrifai_tpu.models import LogisticRegression
from transmogrifai_tpu.ops import transmogrify
from transmogrifai_tpu.types import Binary, PickList, Real, RealNN


def _toy_records(rng, n=200):
    recs = []
    for i in range(n):
        x = rng.normal()
        cat = rng.choice(["a", "b", "c"])
        boost = {"a": 1.0, "b": -1.0, "c": 0.0}[cat]
        y = float(x + boost + 0.3 * rng.normal() > 0)
        recs.append({"x": x, "cat": str(cat), "flag": bool(x > 1),
                     "label": y})
    return recs


def _pipeline():
    label = FeatureBuilder.real_nn("label").extract(
        lambda r: r["label"]).as_response()
    x = FeatureBuilder.real("x").extract(lambda r: r["x"]).as_predictor()
    cat = FeatureBuilder.pick_list("cat").extract(
        lambda r: r["cat"]).as_predictor()
    flag = FeatureBuilder.binary("flag").extract(
        lambda r: r["flag"]).as_predictor()
    fv = transmogrify([x, cat, flag])
    pred = LogisticRegression().set_input(label, fv).get_output()
    return label, fv, pred


class TestWorkflowTrainScore:
    def test_end_to_end(self, rng):
        recs = _toy_records(rng)
        label, fv, pred = _pipeline()
        from transmogrifai_tpu.workflow import Workflow
        wf = Workflow().set_result_features(pred).set_input_records(recs)
        # stages derived from the DAG: vectorizers + combiner + LR
        names = {type(s).__name__ for s in wf.stages()}
        assert "LogisticRegression" in names
        assert "VectorsCombiner" in names

        model = wf.train()
        # after training every origin stage is a transformer/model
        from transmogrifai_tpu.stages.base import Estimator
        assert not any(isinstance(s, Estimator) for s in model.stages())

        scored = model.score(recs)
        assert pred.name in scored.column_names
        ev = BinaryClassificationEvaluator()
        scored2, metrics = model.score_and_evaluate(recs, ev)
        assert metrics.AuROC > 0.85
        assert ev.label_col == "label"
        assert ev.prediction_col == pred.name

    def test_score_without_label(self, rng):
        recs = _toy_records(rng)
        label, fv, pred = _pipeline()
        from transmogrifai_tpu.workflow import Workflow
        model = (Workflow().set_result_features(pred)
                 .set_input_records(recs).train())
        unlabeled = [{k: v for k, v in r.items() if k != "label"}
                     for r in recs[:10]]
        scored = model.score(unlabeled)
        assert scored.n_rows == 10
        preds = scored[pred.name].data
        assert np.all((preds == 0) | (preds == 1))

    def test_dataset_input(self, rng):
        recs = _toy_records(rng, n=100)
        label, fv, pred = _pipeline()
        ds = Dataset({
            "label": FeatureColumn.from_values(
                RealNN, [r["label"] for r in recs]),
            "x": FeatureColumn.from_values(Real, [r["x"] for r in recs]),
            "cat": FeatureColumn.from_values(
                PickList, [r["cat"] for r in recs]),
            "flag": FeatureColumn.from_values(
                Binary, [r["flag"] for r in recs])})
        from transmogrifai_tpu.workflow import Workflow
        model = (Workflow().set_result_features(pred)
                 .set_input_dataset(ds).train())
        scored = model.score(ds)
        assert scored.n_rows == 100

    def test_missing_raw_feature_raises(self, rng):
        label, fv, pred = _pipeline()
        ds = Dataset({"x": FeatureColumn.from_values(Real, [1.0])})
        from transmogrifai_tpu.workflow import Workflow
        wf = Workflow().set_result_features(pred).set_input_dataset(ds)
        with pytest.raises(KeyError):
            wf.train()

    def test_compute_data_up_to(self, rng):
        recs = _toy_records(rng, n=50)
        label, fv, pred = _pipeline()
        from transmogrifai_tpu.workflow import Workflow
        model = (Workflow().set_result_features(pred)
                 .set_input_records(recs).train())
        partial = model.compute_data_up_to(fv, recs[:5])
        assert fv.name in partial.column_names
        assert pred.name not in partial.column_names
