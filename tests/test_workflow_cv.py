"""Workflow-level CV tests (reference OpWorkflowCVTest.scala:59,
FitStagesUtil.cutDAG:305): the in-CV DAG segment — every label-consuming
ancestor of the ModelSelector, e.g. SanityChecker — must be refit inside
each fold so validation metrics carry no fold leakage."""
import numpy as np
import pytest

from transmogrifai_tpu.checkers import SanityChecker
from transmogrifai_tpu.models.base import ClassifierModel, Predictor
from transmogrifai_tpu.evaluators import BinaryClassificationEvaluator
from transmogrifai_tpu.features.builder import FeatureBuilder
from transmogrifai_tpu.models import LogisticRegression
from transmogrifai_tpu.ops import transmogrify
from transmogrifai_tpu.selector import (BinaryClassificationModelSelector,
                                        SelectedModel)
from transmogrifai_tpu.workflow import Workflow
from transmogrifai_tpu.workflow.workflow import cut_dag


class _CountingSanityChecker(SanityChecker):
    fit_calls = 0

    def fit_columns(self, cols):
        type(self).fit_calls += 1
        return super().fit_columns(cols)


def _records(rng, n=160):
    recs = []
    for i in range(n):
        xs = rng.normal(size=5)
        y = float(xs[0] + 0.8 * rng.normal() > 0)
        rec = {f"x{j}": float(xs[j]) for j in range(5)}
        rec["label"] = y
        recs.append(rec)
    return recs


def _pipeline(checker_cls=SanityChecker):
    label = FeatureBuilder.real_nn("label").extract(
        lambda r: r["label"]).as_response()
    xs = [FeatureBuilder.real(f"x{j}").extract(
        lambda r, j=j: r[f"x{j}"]).as_predictor() for j in range(5)]
    fv = transmogrify(xs)
    checked = checker_cls(check_sample=1.0).set_input(label, fv).get_output()
    selector = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=3, stratify=True, splitter=None,
        models=[(LogisticRegression(max_iter=25),
                 [{"reg_param": r} for r in (0.01, 0.1)])])
    pred = selector.set_input(label, checked).get_output()
    return label, pred, selector


def test_cut_dag_identifies_in_cv_segment():
    label, pred, selector = _pipeline()
    ms, during = cut_dag([label, pred])
    assert ms is selector
    names = {type(s).__name__ for layer in during for s in layer}
    # the SanityChecker consumes (response, predictor vector) -> in-CV
    assert "SanityChecker" in names


def test_cut_dag_no_selector():
    label = FeatureBuilder.real_nn("label").extract(
        lambda r: r["label"]).as_response()
    x = FeatureBuilder.real("x0").extract(
        lambda r: r["x0"]).as_predictor()
    fv = transmogrify([x])
    pred = LogisticRegression().set_input(label, fv).get_output()
    ms, during = cut_dag([label, pred])
    assert ms is None and during == []


def test_workflow_cv_refits_checker_per_fold(rng):
    recs = _records(rng)
    _CountingSanityChecker.fit_calls = 0
    label, pred, selector = _pipeline(_CountingSanityChecker)
    model = (Workflow().set_result_features(label, pred)
             .set_input_records(recs).with_workflow_cv().train())
    # 3 in-fold refits + 1 final full-data fit
    assert _CountingSanityChecker.fit_calls == 4
    sel = [s for s in model.stages() if isinstance(s, SelectedModel)][0]
    assert np.isfinite(sel.summary.best_validation_metric)
    # the preset winner skipped in-selector validation but kept results
    assert len(sel.summary.validation_results) == 2


def test_workflow_cv_changes_validation_metric(rng):
    """Per-fold SanityChecker refits change the validation metric vs the
    naive full-data-checker path (VERDICT r2 item 5 'Done'): with many
    noise features hovering around the min-correlation prune threshold,
    full-data pruning (which sees validation folds' labels) keeps a
    different set than leakage-free per-fold pruning."""
    n, d_noise = 160, 24
    Xn = rng.normal(size=(n, d_noise))
    recs = []
    for i in range(n):
        y = float(Xn[i, 0] * 0.4 + rng.normal() > 0)
        rec = {f"x{j}": float(Xn[i, j]) for j in range(d_noise)}
        rec["label"] = y
        recs.append(rec)

    def pipeline():
        label = FeatureBuilder.real_nn("label").extract(
            lambda r: r["label"]).as_response()
        xs = [FeatureBuilder.real(f"x{j}").extract(
            lambda r, j=j: r[f"x{j}"]).as_predictor()
            for j in range(d_noise)]
        fv = transmogrify(xs)
        checked = SanityChecker(min_correlation=0.08).set_input(
            label, fv).get_output()
        selector = BinaryClassificationModelSelector.with_cross_validation(
            num_folds=3, stratify=True, splitter=None,
            models=[(LogisticRegression(max_iter=25),
                     [{"reg_param": r} for r in (0.01, 0.1)])])
        pred = selector.set_input(label, checked).get_output()
        return label, pred

    def run(workflow_cv):
        label, pred = pipeline()
        wf = (Workflow().set_result_features(label, pred)
              .set_input_records(recs))
        if workflow_cv:
            wf = wf.with_workflow_cv()
        model = wf.train()
        sel = [s for s in model.stages()
               if isinstance(s, SelectedModel)][0]
        return sel.summary

    naive = run(False)
    wcv = run(True)
    assert naive.best_validation_metric != wcv.best_validation_metric
    # both searched the same grid and scoring still works end-to-end
    assert len(naive.validation_results) == len(wcv.validation_results)


def test_workflow_cv_imbalanced_with_balancer():
    """In-search balancing (reference OpValidator.applyDAG:250-252):
    the selector's DataBalancer now resamples every fold's train and
    validation rows inside the workflow-CV search. On 10:1 imbalanced
    data the search must complete, keep every fold's metric finite,
    and the final balanced refit must detect the minority class."""
    from transmogrifai_tpu.selector.splitters import DataBalancer
    rng = np.random.default_rng(7)
    recs = []
    for i in range(440):
        xs = rng.normal(size=5)
        # ~9% positives, signal on x0
        y = float(xs[0] > 1.3)
        rec = {f"x{j}": float(xs[j]) for j in range(5)}
        rec["label"] = y
        recs.append(rec)
    assert 0.05 < np.mean([r["label"] for r in recs]) < 0.18
    label = FeatureBuilder.real_nn("label").extract(
        lambda r: r["label"]).as_response()
    xs = [FeatureBuilder.real(f"x{j}").extract(
        lambda r, j=j: r[f"x{j}"]).as_predictor() for j in range(5)]
    fv = transmogrify(xs)
    checked = SanityChecker(check_sample=1.0).set_input(
        label, fv).get_output()
    selector = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=3, stratify=True,
        splitter=DataBalancer(sample_fraction=0.4, seed=3),
        models=[(LogisticRegression(max_iter=25),
                 [{"reg_param": r} for r in (0.01, 0.1)])])
    pred = selector.set_input(label, checked).get_output()
    model = (Workflow().set_result_features(pred)
             .set_input_records(recs).with_workflow_cv().train())
    sel_model = [s for s in model.stages()
                 if isinstance(s, SelectedModel)][0]
    for r in sel_model.summary.validation_results:
        assert all(np.isfinite(v) for v in r.metric_values), r
    scored = model.score(recs)
    pred_labels = scored[pred.name].data
    y = np.array([r["label"] for r in recs])
    # balanced refit must not collapse to the majority class
    assert pred_labels[y == 1].mean() > 0.5
    assert (pred_labels == y).mean() > 0.85


class _PickyModel(ClassifierModel):
    """Scores the strong feature only if its train labels were balanced
    (otherwise a constant score) — a probe for whether the search saw
    balanced or raw folds."""

    def __init__(self, balanced=True, uid=None):
        super().__init__(uid=uid)
        self.balanced = balanced

    def predict_raw(self, X):
        s = X[:, 0] if self.balanced else np.zeros(len(X))
        return np.stack([-s, s], axis=1)


class _WeakModel(ClassifierModel):
    def predict_raw(self, X):
        s = X[:, 1]
        return np.stack([-s, s], axis=1)


class _BalancePicky(Predictor):
    def fit_arrays(self, X, y):
        return _PickyModel(balanced=bool(0.3 <= np.mean(y) <= 0.7))


class _Weak(Predictor):
    def fit_arrays(self, X, y):
        return _WeakModel()


def test_insearch_balancing_flips_winner():
    """In-search DataBalancer changes candidate RANKING, not just the
    final refit (reference ModelSelector.scala:140-152 +
    OpValidator.applyDAG:250-252): a model that exploits the strong
    feature only on balanced train data loses the stratify-only search
    (5% positives -> constant scores -> AuPR ~= prevalence) but wins
    the balanced search (~40% positives -> near-perfect AuPR)."""
    from transmogrifai_tpu.selector.splitters import DataBalancer
    rng = np.random.default_rng(11)
    recs = []
    for i in range(600):
        y = float(rng.random() < 0.05)
        recs.append({"x0": y + 0.2 * rng.normal(),     # strong signal
                     "x1": y + 2.0 * rng.normal(),     # weak signal
                     "label": y})

    def run(splitter):
        label = FeatureBuilder.real_nn("label").extract(
            lambda r: r["label"]).as_response()
        xs = [FeatureBuilder.real(n).extract(
            lambda r, n=n: r[n]).as_predictor() for n in ("x0", "x1")]
        fv = transmogrify(xs)
        checked = SanityChecker(check_sample=1.0).set_input(
            label, fv).get_output()
        selector = BinaryClassificationModelSelector.with_cross_validation(
            num_folds=3, stratify=True, splitter=splitter,
            models=[(_BalancePicky(), [{}]), (_Weak(), [{}])])
        pred = selector.set_input(label, checked).get_output()
        model = (Workflow().set_result_features(pred)
                 .set_input_records(recs).with_workflow_cv().train())
        sel = [s for s in model.stages()
               if isinstance(s, SelectedModel)][0]
        return sel.summary.best_model_name

    assert run(None) == "_Weak"
    assert run(DataBalancer(sample_fraction=0.4, seed=3)) == "_BalancePicky"


def test_r5_tree_flags_compose_end_to_end(rng, monkeypatch):
    """The r5 tree flags — TX_TREE_DEPTH=mask, TX_TREE_EDGES=fold,
    TX_TREE_SUB=1 — must compose: one end-to-end search with ALL of
    them on, plus an in-search balancer, still trains, scores and
    reaches sane quality. Combinations are where flag interactions
    regress (each flag's own parity is covered by its unit tests)."""
    from transmogrifai_tpu.models import GBTClassifier
    from transmogrifai_tpu.selector.splitters import DataBalancer
    monkeypatch.setenv("TX_TREE_DEPTH", "mask")
    monkeypatch.setenv("TX_TREE_EDGES", "fold")
    monkeypatch.setenv("TX_TREE_SUB", "1")
    recs = []
    for i in range(400):
        y = float(rng.random() < 0.25)
        recs.append({"x0": y * 1.5 + rng.normal(),
                     "x1": y - 1.2 * rng.normal(),
                     "x2": float(rng.normal()),
                     "label": y})
    label = FeatureBuilder.real_nn("label").extract(
        lambda r: r["label"]).as_response()
    xs = [FeatureBuilder.real(n).extract(
        lambda r, n=n: r[n]).as_predictor() for n in ("x0", "x1", "x2")]
    fv = transmogrify(xs)
    selector = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=2, stratify=True,
        splitter=DataBalancer(sample_fraction=0.4, seed=7),
        models=[(GBTClassifier(num_rounds=4),
                 [{"max_depth": 2}, {"max_depth": 3}])])
    pred = selector.set_input(label, fv).get_output()
    model = (Workflow().set_result_features(label, pred)
             .set_input_records(recs).train())
    sel = [s for s in model.stages() if isinstance(s, SelectedModel)][0]
    assert np.isfinite(sel.summary.best_validation_metric)
    assert sel.summary.best_validation_metric > 0.5   # AuPR >> 0.25 base
    scored = model.score(recs[:20])
    assert scored[pred.name].data.shape == (20,)
