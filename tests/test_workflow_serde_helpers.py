"""Importable extract fns for serializability tests."""


def extract_x(r):
    return r["x"]
