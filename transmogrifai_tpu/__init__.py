"""transmogrifai_tpu: a TPU-native AutoML framework for structured data.

Type-safe feature pipelines, automated feature engineering/validation and
XLA-compiled model selection — the capability surface of TransmogrifAI
(reference at /root/reference) re-designed for JAX/XLA on TPU.
"""
__version__ = "0.1.0"

from .features import (Dataset, Feature, FeatureBuilder, FeatureColumn,
                       FeatureGeneratorStage)
from . import types

__all__ = ["Dataset", "Feature", "FeatureBuilder", "FeatureColumn",
           "FeatureGeneratorStage", "types", "__version__"]
