"""Static analysis of COMPILED plans — the HLO-level counterpart of
the AST-level ``lint`` package.

``tx lint`` judges the Python a developer wrote; this package judges
the StableHLO/HLO programs XLA will actually run: every plan bucket
program is AOT-lowered (``jax.jit(...).lower()`` — no execution, no
device) and audited for op/fusion/byte features, host transfers,
precision widening and padding waste, plus the canonical IR
fingerprint that keys saved-model artifact identity. See
docs/plan_audit.md.
"""
from .audit import (AuditResult, PlanAudit, audit_demo, audit_model,
                    audit_prepare_plan, audit_scoring_plan,
                    plan_fingerprint, process_ir_features)
from .cache import AuditCache, kernel_source_hash, model_content_hash
from .hlo import ModuleStats, canonical_fingerprint, normalize_module, \
    parse_module
from .rules import audit_findings, lint_audits, occupancy_findings, \
    verify_classification

__all__ = [
    "AuditCache", "AuditResult", "ModuleStats", "PlanAudit",
    "audit_demo", "audit_findings", "audit_model",
    "audit_prepare_plan", "audit_scoring_plan", "canonical_fingerprint",
    "kernel_source_hash", "lint_audits", "model_content_hash",
    "normalize_module", "occupancy_findings", "parse_module",
    "plan_fingerprint", "process_ir_features", "verify_classification",
]
