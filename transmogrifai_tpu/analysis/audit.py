"""The plan IR auditor: static HLO-level analysis of compiled plans.

Every program the system ships — the ScoringPlan's per-bucket fused
scoring programs and the PreparePlan's fused segment programs — is
AOT-lowered here via ``jax.jit(...).lower()`` (no execution, no device;
works under ``JAX_PLATFORMS=cpu``) and walked into a :class:`PlanAudit`
per (plan, bucket): op-kind histogram, fusion count, constant/
parameter/output byte sizes, dtype census, host-transfer and
dynamic-shape inventories, and the canonical IR fingerprint
(analysis/hlo.py). The audit is simultaneously

- a correctness gate: the TX-P rule family (analysis/rules.py) runs
  over the audits with lint severities and exit codes,
- the cost-model-v2 feature source: per-bucket op/fusion/byte features
  merge into the ProfileStore ``profiles`` block
  (``persist_process_profiles``), and
- the AOT artifact identity: ``plan_fingerprint`` is recorded into
  save_model metadata and verified on load (``plan_fingerprint_drift``
  telemetry on mismatch).

Audits are content-hash cached (analysis/cache.py) over (model
content, transitive kernel sources, jax version, platform) — a warm
``tx audit`` run re-lowers nothing.
"""
from __future__ import annotations

import hashlib
import json
import logging
import os
import re
import tempfile
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from .cache import (AuditCache, kernel_source_hash, model_content_hash,
                    resolve_cache_path)
from .hlo import ModuleStats, canonical_fingerprint, parse_module

_log = logging.getLogger(__name__)

__all__ = ["PlanAudit", "AuditResult", "audit_scoring_plan",
           "audit_prepare_plan", "audit_model", "audit_demo",
           "plan_fingerprint", "process_ir_features",
           "record_plan_fingerprint", "verify_plan_fingerprint",
           "AUDIT_SIDECAR", "demo_model_dir"]

#: schema stamp baked into every audit cache key — bump on any change
#: to the PlanAudit document shape
AUDIT_SCHEMA = 1

#: model-dir sidecar carrying the save-time canonical fingerprint
AUDIT_SIDECAR = "plan-fingerprint.json"

#: fusion instruction in optimized HLO text: ``%x = ty fusion(...)``
_FUSION_RE = re.compile(r"=\s*[a-z0-9\[\]{},* ]+\bfusion\(")

#: the --demo scoring-plan bucket range: small enough that every
#: bucket lowers + compiles inside the repo-gate budget, wide enough
#: to exercise the ladder
DEMO_MIN_BUCKET, DEMO_MAX_BUCKET = 8, 64


@dataclass
class PlanAudit:
    """The lowered-IR feature record of ONE (plan, bucket) program."""
    plan: str                   # "score" | "prepare"
    label: str                  # "b8" | "seg0:b512"
    bucket: int
    op_histogram: Dict[str, int] = field(default_factory=dict)
    fusions: int = -1           # -1: not compiled (lowering-only audit)
    constant_bytes: int = 0
    parameter_bytes: int = 0
    output_bytes: int = 0
    dtype_census: Dict[str, int] = field(default_factory=dict)
    host_transfer_ops: List[str] = field(default_factory=list)
    dynamic_shape_ops: List[str] = field(default_factory=list)
    param_widths: Dict[str, int] = field(default_factory=dict)
    body_widths: Dict[str, int] = field(default_factory=dict)
    fingerprint: str = ""
    stages: List[str] = field(default_factory=list)

    @property
    def n_ops(self) -> int:
        return sum(self.op_histogram.values())

    def to_json(self) -> dict:
        return {
            "plan": self.plan, "label": self.label,
            "bucket": self.bucket, "opHistogram": dict(self.op_histogram),
            "fusions": self.fusions,
            "bytes": {"constants": self.constant_bytes,
                      "parameters": self.parameter_bytes,
                      "outputs": self.output_bytes},
            "dtypeCensus": dict(self.dtype_census),
            "hostTransferOps": list(self.host_transfer_ops),
            "dynamicShapeOps": list(self.dynamic_shape_ops),
            "paramWidths": dict(self.param_widths),
            "bodyWidths": dict(self.body_widths),
            "fingerprint": self.fingerprint,
            "stages": list(self.stages),
        }

    @classmethod
    def from_json(cls, d: dict) -> "PlanAudit":
        b = d.get("bytes", {})
        return cls(plan=d["plan"], label=d["label"],
                   bucket=int(d["bucket"]),
                   op_histogram={k: int(v) for k, v in
                                 d.get("opHistogram", {}).items()},
                   fusions=int(d.get("fusions", -1)),
                   constant_bytes=int(b.get("constants", 0)),
                   parameter_bytes=int(b.get("parameters", 0)),
                   output_bytes=int(b.get("outputs", 0)),
                   dtype_census={k: int(v) for k, v in
                                 d.get("dtypeCensus", {}).items()},
                   host_transfer_ops=list(d.get("hostTransferOps", ())),
                   dynamic_shape_ops=list(d.get("dynamicShapeOps", ())),
                   param_widths={k: int(v) for k, v in
                                 d.get("paramWidths", {}).items()},
                   body_widths={k: int(v) for k, v in
                                d.get("bodyWidths", {}).items()},
                   fingerprint=d.get("fingerprint", ""),
                   stages=list(d.get("stages", ())))


@dataclass
class AuditResult:
    """One audit run's output: the per-(plan, bucket) records plus the
    classification-drift findings (TX-P05) that only the live plan can
    produce. Store-dependent rules (TX-P03/P04) are evaluated FRESH by
    the caller — recorded occupancy must never be masked by a cache."""
    audits: List[PlanAudit] = field(default_factory=list)
    findings: List = field(default_factory=list)
    stats: Dict[str, int] = field(default_factory=dict)
    model_dir: Optional[str] = None


# ---------------------------------------------------------------------------
# per-process IR-feature registry (persist_process_profiles reads it)
# ---------------------------------------------------------------------------

_PROCESS_IR: Dict[str, dict] = {}


def _register_ir(audit: PlanAudit) -> None:
    key = (f"score:{audit.label}" if audit.plan == "score"
           else f"prepare:{audit.label.replace(':', ':')}")
    if audit.plan == "prepare":
        key = f"prepare:{audit.label}"
    _PROCESS_IR[key] = {
        "ops": audit.n_ops,
        "fusions": audit.fusions,
        "constant_bytes": audit.constant_bytes,
        "parameter_bytes": audit.parameter_bytes,
        "output_bytes": audit.output_bytes,
        "fingerprint": audit.fingerprint,
    }


def process_ir_features() -> Dict[str, dict]:
    """Per-bucket IR features audited so far in this process, keyed
    like the ProfileStore ``profiles`` block (``score:b8``,
    ``prepare:seg0:b512``) — ``persist_process_profiles`` merges them
    under each record's ``ir`` field (cost-model-v2 training data)."""
    return {k: dict(v) for k, v in _PROCESS_IR.items()}


# ---------------------------------------------------------------------------
# lowering drivers
# ---------------------------------------------------------------------------

def _env() -> Tuple[str, str]:
    import jax
    return jax.__version__, jax.default_backend()


def _audit_lowered(lowered, *, plan: str, label: str, bucket: int,
                   stages: Sequence[str], compiled: bool) -> PlanAudit:
    """Walk one ``jax.stages.Lowered`` into a PlanAudit."""
    text = lowered.as_text()
    stats: ModuleStats = parse_module(text)
    jax_version, platform = _env()
    fusions = -1
    if compiled:
        try:
            fusions = len(_FUSION_RE.findall(lowered.compile().as_text()))
        except Exception as e:  # pragma: no cover - backend quirk
            _log.warning("audit: compiled-HLO fusion count unavailable "
                         "for %s:%s (%s: %s)", plan, label,
                         type(e).__name__, e)
    audit = PlanAudit(
        plan=plan, label=label, bucket=bucket,
        op_histogram=stats.op_histogram, fusions=fusions,
        constant_bytes=stats.constant_bytes,
        parameter_bytes=stats.parameter_bytes,
        output_bytes=stats.output_bytes,
        dtype_census=stats.dtype_census,
        host_transfer_ops=stats.host_transfer_ops,
        dynamic_shape_ops=stats.dynamic_shape_ops,
        param_widths=stats.param_widths,
        body_widths=stats.body_widths,
        fingerprint=canonical_fingerprint(text, jax_version, platform),
        stages=list(stages))
    _register_ir(audit)
    return audit


def audit_scoring_plan(plan, buckets: Optional[Sequence[int]] = None,
                       compiled: bool = True) -> List[PlanAudit]:
    """Lower every bucket program of a compiled :class:`ScoringPlan`
    (serving/plan.py) and audit each. A plan whose stages all fell
    back to host numpy has no device program — empty list."""
    plan.compile()
    if not getattr(plan, "_device_steps", None):
        return []
    stage_names = [type(s).__name__ for s, _, _ in plan._device_steps]
    out = []
    for bucket in (buckets if buckets is not None else plan.buckets()):
        lowered = plan.lower_bucket(int(bucket))
        out.append(_audit_lowered(
            lowered, plan="score", label=f"b{int(bucket)}",
            bucket=int(bucket), stages=stage_names, compiled=compiled))
    return out


def audit_prepare_plan(plan, compiled: bool = True) -> List[PlanAudit]:
    """Audit every fused segment program a :class:`PreparePlan`
    executed (plans/prepare.py records an audit handle per segment —
    the jitted fn + its input avals + the buckets it dispatched)."""
    import jax
    import numpy as np
    out = []
    for handle in getattr(plan, "audit_handles", ()):
        for bucket in handle["buckets"]:
            avals = tuple(
                jax.ShapeDtypeStruct((bucket,) + tuple(shape), dtype)
                for shape, dtype in handle["in_avals"])
            mask = jax.ShapeDtypeStruct((bucket,), np.float64)
            lowered = handle["fn"].lower(avals, mask)
            out.append(_audit_lowered(
                lowered, plan="prepare",
                label=f"{handle['label']}:b{bucket}", bucket=bucket,
                stages=list(handle["stages"]), compiled=compiled))
    return out


# ---------------------------------------------------------------------------
# model-level audit (cache-fronted)
# ---------------------------------------------------------------------------

def _digest(*parts: str) -> str:
    return hashlib.sha1("|".join(parts).encode()).hexdigest()


def _content_key(model_key: str, kernel_hash: str, compiled: bool,
                 bucket_spec: str) -> str:
    jax_version, platform = _env()
    return _digest(f"schema{AUDIT_SCHEMA}", model_key, kernel_hash,
                   jax_version, platform, f"compiled={compiled}",
                   bucket_spec)


def _stage_modules_from_doc(model_dir: str) -> List[str]:
    """Stage modules of a saved model WITHOUT loading it — the audit
    cache key must be computable on the warm path from file content
    alone."""
    from ..stages.base import stage_class_by_name
    try:
        with open(os.path.join(model_dir, "op-model.json"),
                  encoding="utf-8") as fh:
            doc = json.load(fh)
        mods = set()
        for sd in doc.get("stages", ()):
            try:
                mods.add(stage_class_by_name(sd["className"]).__module__)
            except Exception:
                pass
        return sorted(mods)
    except (OSError, ValueError, KeyError):
        return []


def audit_model(model, model_dir: Optional[str] = None,
                min_bucket: Optional[int] = None,
                max_bucket: Optional[int] = None,
                buckets: Optional[Sequence[int]] = None,
                compiled: bool = True,
                cache_path: Optional[str] = None,
                precise_kernel_hash: bool = True) -> AuditResult:
    """Audit a fitted model's scoring programs, through the audit
    cache when ``model_dir`` names its saved directory (content
    identity). ``precise_kernel_hash`` keys the cache by the
    call-graph closure of the model's stage modules (lint/callgraph
    summaries); off, it keys by every package source (conservative,
    cheaper)."""
    from ..serving.plan import ScoringPlan
    kwargs = {}
    if min_bucket is not None:
        kwargs["min_bucket"] = min_bucket
    if max_bucket is not None:
        kwargs["max_bucket"] = max_bucket

    cache = AuditCache(resolve_cache_path(cache_path)
                       if model_dir else None)
    cache.load()
    key = label_pfx = None
    if model_dir:
        mods = _stage_modules_from_doc(model_dir) \
            if precise_kernel_hash else None
        khash = kernel_source_hash(stage_modules=mods)
        mkey = model_content_hash(model_dir)
        bucket_spec = (f"min={min_bucket},max={max_bucket}"
                       if buckets is None
                       else ",".join(str(b) for b in buckets))
        key = _content_key(mkey, khash, compiled, bucket_spec)
        label_pfx = f"model:{mkey[:12]}"
        hit = cache.get(f"{label_pfx}:score", key)
        if hit is not None:
            audits = [PlanAudit.from_json(d) for d in hit["audits"]]
            for a in audits:
                _register_ir(a)
            from ..lint.findings import LintFinding
            return AuditResult(
                audits=audits,
                findings=[LintFinding.from_json(d)
                          for d in hit["findings"]],
                stats=dict(cache.stats), model_dir=model_dir)

    plan = ScoringPlan(model, **kwargs).compile()
    audits = audit_scoring_plan(plan, buckets=buckets,
                                compiled=compiled)
    from .rules import verify_classification
    findings = verify_classification(plan)
    if key is not None:
        cache.put(f"{label_pfx}:score", key,
                  {"audits": [a.to_json() for a in audits],
                   "findings": [f.to_json() for f in findings]})
        cache.save()
    return AuditResult(audits=audits, findings=findings,
                       stats=dict(cache.stats), model_dir=model_dir)


# ---------------------------------------------------------------------------
# canonical plan fingerprint (save/load metadata)
# ---------------------------------------------------------------------------

def plan_fingerprint(model) -> str:
    """The model's canonical AOT artifact key: the min-bucket scoring
    program's IR fingerprint (every other bucket derives from the same
    composition — any kernel/weight change moves this key). A plan
    with no device program keys on that fact, still environment-
    stamped."""
    from ..serving.plan import ScoringPlan
    plan = ScoringPlan(model).compile()
    if not getattr(plan, "_device_steps", None):
        jax_version, platform = _env()
        return f"xla:{platform}:jax-{jax_version}:no-device-program"
    audits = audit_scoring_plan(plan, buckets=[plan.min_bucket],
                                compiled=False)
    return audits[0].fingerprint


def _fingerprint_enabled() -> bool:
    return os.environ.get("TX_PLAN_FINGERPRINT", "on") not in (
        "off", "0")


def _fingerprint_via_cache(model, model_dir: str) -> str:
    """Compute (or fetch) the model's canonical fingerprint through
    the audit cache — the load_model verify path is pure hashing when
    nothing changed since save."""
    cache = AuditCache(resolve_cache_path(None))
    cache.load()
    mkey = model_content_hash(model_dir)
    khash = kernel_source_hash()        # whole-package: no model needed
    key = _content_key(mkey, khash, False, "fingerprint")
    label = f"fp:{mkey[:16]}"
    hit = cache.get(label, key)
    if hit is not None:
        return hit["fingerprint"]
    fp = plan_fingerprint(model)
    cache.put(label, key, {"fingerprint": fp})
    cache.save()
    return fp


def record_plan_fingerprint(model, staging_dir: str,
                            lattice: Optional[Sequence[int]] = None
                            ) -> None:
    """save_model hook: compute the canonical fingerprint and write it
    as the ``plan-fingerprint.json`` sidecar (+ seed the audit cache so
    the load-side verify is a pure cache hit). ``lattice`` records the
    bucket lattice the saving plan dispatched on (None = the default
    power-of-two ladder) — informational identity only: the canonical
    fingerprint is bucket-invariant, so a lattice change never trips
    ``plan_fingerprint_drift`` (docs/ragged_batching.md). Best-effort —
    a model whose plan cannot compile saves without a fingerprint,
    loudly."""
    if not _fingerprint_enabled():
        return
    try:
        fp = _fingerprint_via_cache(model, staging_dir)
        jax_version, platform = _env()
        doc = {"schema": 1, "fingerprint": fp,
               "jax": jax_version, "platform": platform,
               "lattice": ([int(b) for b in lattice]
                           if lattice else None)}
        with open(os.path.join(staging_dir, AUDIT_SIDECAR), "w",
                  encoding="utf-8") as fh:
            json.dump(doc, fh, indent=1)
    except Exception as e:
        _log.warning(
            "plan fingerprint not recorded (%s: %s); the saved model "
            "carries no AOT artifact identity", type(e).__name__, e)


def verify_plan_fingerprint(model, model_dir: str) -> Optional[bool]:
    """load_model hook: recompute the canonical fingerprint in THIS
    environment (cache-fronted) and compare against the save-time
    sidecar. Mismatch = the lowered program changed since save (kernel
    edit, jax upgrade, platform move) — counted loudly as
    ``plan_fingerprint_drift``, never an error (groundwork for AOT
    artifact validation). Returns True/False on verify, None when the
    model carries no fingerprint or verification is disabled."""
    if not _fingerprint_enabled():
        return None
    sidecar = os.path.join(model_dir, AUDIT_SIDECAR)
    try:
        with open(sidecar, encoding="utf-8") as fh:
            saved = json.load(fh)
    except (OSError, ValueError):
        return None
    try:
        current = _fingerprint_via_cache(model, model_dir)
    except Exception as e:
        _log.warning("plan fingerprint not verifiable (%s: %s)",
                     type(e).__name__, e)
        return None
    expected = saved.get("fingerprint")
    if current == expected:
        return True
    from ..runtime import telemetry
    telemetry.count("plan_fingerprint_drift")
    telemetry.event("plan_fingerprint_drift", model_dir=model_dir,
                    saved=expected, current=current)
    _log.warning(
        "plan fingerprint drift: model %s was saved with %s but lowers "
        "to %s in this environment — the compiled scoring program "
        "changed since save (kernel edit / jax upgrade / platform "
        "move); scores may differ from the saving build",
        model_dir, expected, current)
    return False


# ---------------------------------------------------------------------------
# the --demo workload (repo-gate target)
# ---------------------------------------------------------------------------

def demo_model_dir(cache_root: Optional[str] = None) -> str:
    """Where the trained demo model lives: keyed by package version +
    kernel sources, so a kernel edit retrains instead of auditing a
    stale artifact."""
    from ..utils.version import version_info
    root = cache_root or os.path.join(tempfile.gettempdir(),
                                      "tx-audit-demo")
    key = _digest(str(version_info().to_json()),
                  kernel_source_hash())[:12]
    return os.path.join(root, key, "model")


def _train_demo(model_dir: str):
    """Train the synthetic-Titanic-style demo pipeline (the
    ``tx score --bench`` workload) under the compiled prepare path and
    save it; returns (model, prepare_plan)."""
    from ..cli.score import _tiny_pipeline
    from ..plans.prepare import last_prepare_plan
    model, _records = _tiny_pipeline()
    prep = last_prepare_plan()
    os.makedirs(os.path.dirname(model_dir), exist_ok=True)
    model.save(model_dir)
    return model, prep


def audit_demo(cache_path: Optional[str] = None,
               cache_root: Optional[str] = None,
               compiled: bool = True,
               fresh: bool = False) -> AuditResult:
    """The self-contained repo-gate audit: train (once — the model
    persists under the tempdir, content-keyed) the demo pipeline,
    audit its scoring buckets AND its prepare segment programs, all
    through the audit cache. Warm path: pure hashing + cache reads,
    no training, no lowering."""
    model_dir = demo_model_dir(cache_root)
    cache = AuditCache(resolve_cache_path(cache_path))
    cache.load()
    khash = kernel_source_hash()
    have_model = os.path.isdir(model_dir) and not fresh
    key = None
    if have_model:
        mkey = model_content_hash(model_dir)
        key = _content_key(
            mkey, khash, compiled,
            f"demo:min={DEMO_MIN_BUCKET},max={DEMO_MAX_BUCKET}")
        score_hit = cache.get("demo:score", key)
        prep_hit = cache.get("demo:prepare", key)
        if score_hit is not None and prep_hit is not None:
            audits = ([PlanAudit.from_json(d)
                       for d in score_hit["audits"]]
                      + [PlanAudit.from_json(d)
                         for d in prep_hit["audits"]])
            for a in audits:
                _register_ir(a)
            from ..lint.findings import LintFinding
            return AuditResult(
                audits=audits,
                findings=[LintFinding.from_json(d)
                          for d in score_hit["findings"]],
                stats=dict(cache.stats), model_dir=model_dir)

    # cold: (re)train so the prepare segments are capturable, then
    # audit the LOADED model — cold and warm runs audit byte-identical
    # artifacts
    model, prep = _train_demo(model_dir)
    from ..workflow.persistence import load_model
    from ..serving.plan import ScoringPlan
    loaded = load_model(model_dir)
    plan = ScoringPlan(loaded, min_bucket=DEMO_MIN_BUCKET,
                       max_bucket=DEMO_MAX_BUCKET).compile()
    score_audits = audit_scoring_plan(plan, compiled=compiled)
    from .rules import verify_classification
    findings = verify_classification(plan)
    prep_audits = (audit_prepare_plan(prep, compiled=compiled)
                   if prep is not None else [])
    mkey = model_content_hash(model_dir)
    key = _content_key(
        mkey, khash, compiled,
        f"demo:min={DEMO_MIN_BUCKET},max={DEMO_MAX_BUCKET}")
    cache.put("demo:score", key,
              {"audits": [a.to_json() for a in score_audits],
               "findings": [f.to_json() for f in findings]})
    cache.put("demo:prepare", key,
              {"audits": [a.to_json() for a in prep_audits],
               "findings": []})
    cache.save()
    return AuditResult(audits=score_audits + prep_audits,
                       findings=findings, stats=dict(cache.stats),
                       model_dir=model_dir)
