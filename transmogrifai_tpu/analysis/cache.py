"""Incremental audit cache + transitive kernel-source hashing.

Audits are pure functions of (model content, the transitive kernel
sources the plan composes, jax version, platform) — so they cache
exactly like file lints (lint/engine.LintCache): content-hash keyed,
checksummed per entry, poisoned whole on tamper, schema-bumped on
format change. A warm ``tx audit`` run re-lowers NOTHING.

The kernel-source half reuses the lint layer wholesale: file summaries
come through :class:`~..lint.engine.LintCache` (already warm after any
lint run) and the transitive closure walks
:mod:`~..lint.callgraph` call edges from the plan's stage modules —
editing a kernel in ``ops/`` (or any helper it calls) changes the hash
and invalidates the cached audit of every plan that uses it, while an
edit to an unrelated module invalidates nothing.
"""
from __future__ import annotations

import hashlib
import json
import os
import sys
import tempfile
from typing import Dict, Iterable, List, Optional, Sequence

__all__ = ["AuditCache", "kernel_source_hash", "default_cache_path",
           "model_content_hash"]

#: the package root — the default kernel-source search tree
_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def default_cache_path() -> str:
    """Stable audit-cache location under the system tempdir
    (``TX_AUDIT_CACHE`` overrides; ``off``/``0`` disables)."""
    env = os.environ.get("TX_AUDIT_CACHE")
    if env:
        return env
    h = hashlib.sha1(_PKG_ROOT.encode()).hexdigest()[:12]
    return os.path.join(tempfile.gettempdir(), f"txaudit-{h}.json")


def resolve_cache_path(cache_path: Optional[str]) -> Optional[str]:
    if cache_path is not None:
        return cache_path or None
    env = os.environ.get("TX_AUDIT_CACHE")
    if env in ("off", "0"):
        return None
    return default_cache_path()


def _entry_checksum(entry: dict) -> str:
    raw = json.dumps({k: entry[k] for k in ("key", "doc")},
                     sort_keys=True)
    return hashlib.sha1(raw.encode()).hexdigest()[:16]


class AuditCache:
    """On-disk audit cache: label -> (content key, audit document).
    Same integrity contract as the lint cache: schema bumps are
    routine invalidation, a checksum mismatch on ANY entry poisons
    the whole document (discard + loud stderr + ``poisoned`` stat)."""

    SCHEMA = 1

    def __init__(self, path: Optional[str]):
        self.path = path            # None = disabled
        self.entries: Dict[str, dict] = {}
        self.stats = {"hits": 0, "misses": 0, "poisoned": 0}

    def load(self) -> None:
        if not self.path or not os.path.exists(self.path):
            return
        try:
            with open(self.path, encoding="utf-8") as fh:
                doc = json.load(fh)
        except (OSError, ValueError):
            self._poison("unreadable/corrupt JSON")
            return
        if not isinstance(doc, dict) or doc.get("schema") != self.SCHEMA:
            return
        entries = doc.get("audits")
        if not isinstance(entries, dict):
            self._poison("missing audit table")
            return
        for label, entry in entries.items():
            if (not isinstance(entry, dict)
                    or entry.get("sum") != _entry_checksum(entry)):
                self._poison(f"checksum mismatch for {label}")
                return
        self.entries = entries

    def _poison(self, why: str) -> None:
        self.entries = {}
        self.stats["poisoned"] += 1
        print(f"tx-audit: WARNING: cache poisoned ({why}) — "
              f"discarding {self.path} and re-lowering everything",
              file=sys.stderr)

    def get(self, label: str, key: str) -> Optional[dict]:
        entry = self.entries.get(label)
        if entry is not None and entry.get("key") == key:
            self.stats["hits"] += 1
            return entry["doc"]
        self.stats["misses"] += 1
        return None

    def put(self, label: str, key: str, doc: dict) -> None:
        entry = {"key": key, "doc": doc}
        entry["sum"] = _entry_checksum(entry)
        self.entries[label] = entry

    def save(self) -> None:
        if not self.path:
            return
        doc = {"schema": self.SCHEMA, "audits": self.entries}
        tmp = self.path + ".tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(doc, fh)
            os.replace(tmp, self.path)
        except OSError:  # pragma: no cover - read-only tempdir
            pass


# ---------------------------------------------------------------------------
# transitive kernel-source hashing (reuses lint callgraph summaries)
# ---------------------------------------------------------------------------

def _file_hashes(roots: Sequence[str]) -> Dict[str, str]:
    """relpath -> sha1(content) for every .py file under ``roots``."""
    from ..lint.engine import iter_py_files
    out: Dict[str, str] = {}
    for f in iter_py_files(list(roots)):
        try:
            with open(f, encoding="utf-8") as fh:
                src = fh.read()
        except OSError:
            continue
        rel = os.path.relpath(f, os.path.commonpath(
            [os.path.abspath(r) for r in roots]) if roots else f)
        out[rel] = hashlib.sha1(src.encode()).hexdigest()
    return out


def _closure_files(roots: Sequence[str], stage_modules: Iterable[str],
                   cache_path: Optional[str] = None) -> List[str]:
    """Source files transitively reachable (via call edges) from any
    function defined in ``stage_modules`` — the kernel closure of a
    plan. Module names match on suffix so both ``ops.numeric`` and
    ``transmogrifai_tpu.ops.numeric`` spellings resolve."""
    from ..lint.engine import build_project_graph
    graph = build_project_graph(list(roots), cache_path=cache_path)
    mods = {m.split(".")[-1]: m for m in stage_modules}
    want = set()
    for f in graph.functions.values():
        fm = f.mod
        for short, full in mods.items():
            if fm == full or fm.endswith("." + short) or fm == short \
                    or full.endswith("." + fm):
                want.add(f.gid)
    # BFS over outgoing call edges
    seen = set(want)
    frontier = list(want)
    while frontier:
        gid = frontier.pop()
        for e in graph.edges_from(gid):
            if e.dst not in seen and e.dst in graph.functions:
                seen.add(e.dst)
                frontier.append(e.dst)
    return sorted({graph.functions[g].path for g in seen
                   if g in graph.functions})


#: memoized whole-package hash (the no-argument fast path save/load
#: fingerprinting hits on EVERY save_model/load_model): the installed
#: package's sources do not change mid-process, so hash once
_DEFAULT_HASH: List[str] = []


def kernel_source_hash(roots: Optional[Sequence[str]] = None,
                       stage_modules: Optional[Iterable[str]] = None,
                       lint_cache_path: Optional[str] = None) -> str:
    """Content hash of the transitive kernel sources.

    With ``stage_modules`` (the plan's stage classes' modules) the hash
    covers exactly the call-graph closure of those modules — the files
    whose edits can change the lowered program. Without it (or when the
    closure resolves to nothing, e.g. stages defined in a test body)
    the hash conservatively covers every file under ``roots``."""
    default_call = roots is None and not stage_modules
    if default_call and _DEFAULT_HASH:
        return _DEFAULT_HASH[0]
    roots = list(roots) if roots else [_PKG_ROOT]
    hashes = _file_hashes(roots)
    files: Optional[List[str]] = None
    if stage_modules:
        try:
            closure = _closure_files(roots, stage_modules,
                                     cache_path=lint_cache_path)
            if closure:
                rels = set()
                common = os.path.commonpath(
                    [os.path.abspath(r) for r in roots])
                for p in closure:
                    rels.add(os.path.relpath(os.path.abspath(p), common))
                files = sorted(r for r in rels if r in hashes)
        except Exception:       # closure is an optimization, not truth
            files = None
    if not files:
        files = sorted(hashes)
    h = hashlib.sha1()
    for rel in files:
        h.update(rel.encode())
        h.update(hashes[rel].encode())
    digest = h.hexdigest()
    if default_call:
        _DEFAULT_HASH[:] = [digest]
    return digest


def model_content_hash(model_dir: str) -> str:
    """sha1 over the model's identity files (``op-model.json`` +
    ``arrays.npz``) — sidecars (drift fingerprints, the audit
    fingerprint itself) deliberately excluded so writing them does not
    move the key."""
    h = hashlib.sha1()
    for name in ("op-model.json", "arrays.npz"):
        p = os.path.join(model_dir, name)
        try:
            with open(p, "rb") as fh:
                h.update(name.encode())
                h.update(fh.read())
        except OSError:
            h.update(f"{name}:absent".encode())
    return h.hexdigest()
