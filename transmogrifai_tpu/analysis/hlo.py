"""Lowered-module text analysis: the StableHLO walker behind the plan
auditor (docs/plan_audit.md).

``jax.jit(fn).lower(*avals)`` emits a StableHLO module as TEXT — a
stable, line-oriented MLIR dialect — without executing anything and
without a device ("A Learned Performance Model for TPUs" uses exactly
these module-level features as its cost-model inputs). This module
parses that text into :class:`ModuleStats` (op-kind histogram, dtype
census, parameter/constant/output byte sizes, host-transfer and
dynamic-shape inventories) and computes the **canonical IR
fingerprint**: a content hash of the normalized module keyed by jax
version + platform — the artifact-identity key the ROADMAP AOT item
needs, replacing the positional pickle fingerprint of
``plans/prepare._state_fingerprint`` for identity purposes.

Normalization strips only NON-SEMANTIC noise (location metadata and
the pointer-valued ``backend_config`` blobs host callbacks embed), so
two lowerings of the same program in the same environment hash
bitwise-identically, and ANY kernel-source change that alters the
emitted program changes the key.
"""
from __future__ import annotations

import hashlib
import re
from dataclasses import dataclass, field
from typing import Dict, List, Tuple

__all__ = ["ModuleStats", "parse_module", "normalize_module",
           "canonical_fingerprint", "DTYPE_BYTES"]

#: element byte widths of the dtypes jax lowers to (i1 rounds up to a
#: byte — XLA packs predicates per-byte on every real backend)
DTYPE_BYTES: Dict[str, int] = {
    "i1": 1, "i2": 1, "i4": 1, "i8": 1, "ui8": 1,
    "i16": 2, "ui16": 2, "bf16": 2, "f16": 2,
    "i32": 4, "ui32": 4, "f32": 4,
    "i64": 8, "ui64": 8, "f64": 8, "c64": 8,
    "c128": 16, "index": 8,
    "f8E4M3FN": 1, "f8E5M2": 1, "f8E4M3B11FNUZ": 1,
}

_TENSOR_RE = re.compile(r"tensor<([^<>]*)>")
_OP_RE = re.compile(r"^(?:%[\w.#:]+(?:,\s*%[\w.#:]+)*\s*=\s*)?"
                    r"([a-z_]+\.[a-z0-9_]+)\b")
_ARG_RE = re.compile(r"%arg\d+: tensor<([^<>]*)>")
_TARGET_RE = re.compile(r"custom_call\s+@([\w.$-]+)"
                        r"|call_target_name\s*=\s*\"([^\"]+)\"")
_LOC_RE = re.compile(r"\s*loc\([^()]*\)")
_BACKEND_CFG_RE = re.compile(r"backend_config\s*=\s*\"[0-9]+\"")
_MODULE_NAME_RE = re.compile(r"^module @\S+")

#: custom_call targets that ARE host transfers (python callbacks,
#: host send/recv shims) — a plain custom_call (e.g. a sharding
#: annotation or an XLA library kernel) is device-side and stays out
_HOST_TARGET_RE = re.compile(r"callback|host|py_func|infeed|outfeed",
                             re.IGNORECASE)
#: op names that move data across the host boundary by definition
_HOST_OPS = ("stablehlo.infeed", "stablehlo.outfeed",
             "stablehlo.send", "stablehlo.recv")
#: shape-dynamic stablehlo ops (result extent depends on runtime
#: values); dynamic_slice/dynamic_update_slice are static-SHAPE and
#: deliberately excluded
_DYNAMIC_OPS = ("stablehlo.dynamic_reshape", "stablehlo.dynamic_pad",
                "stablehlo.dynamic_broadcast_in_dim",
                "stablehlo.dynamic_iota", "stablehlo.dynamic_gather",
                "stablehlo.real_dynamic_slice",
                "stablehlo.dynamic_conv")


@dataclass
class ModuleStats:
    """Everything the auditor reads out of one lowered module."""
    op_histogram: Dict[str, int] = field(default_factory=dict)
    dtype_census: Dict[str, int] = field(default_factory=dict)
    parameter_bytes: int = 0
    constant_bytes: int = 0
    output_bytes: int = 0
    host_transfer_ops: List[str] = field(default_factory=list)
    dynamic_shape_ops: List[str] = field(default_factory=list)
    #: max float / int element width (bits) seen among PARAMETERS vs
    #: anywhere in the body — the TX-P02 widening comparison inputs
    param_widths: Dict[str, int] = field(default_factory=dict)
    body_widths: Dict[str, int] = field(default_factory=dict)

    @property
    def n_ops(self) -> int:
        return sum(self.op_histogram.values())


def _tensor_bytes(spec: str) -> Tuple[int, str, bool]:
    """(byte size, dtype token, is_dynamic) for one ``tensor<...>``
    spec like ``8x3xf64`` / ``f32`` / ``?x4xf32``."""
    parts = spec.split("x")
    dtype = parts[-1]
    dynamic = False
    n = 1
    for d in parts[:-1]:
        if d == "?":
            dynamic = True
            continue
        try:
            n *= int(d)
        except ValueError:
            return 0, dtype, dynamic
    return n * DTYPE_BYTES.get(dtype, 4), dtype, dynamic


def _width_class(dtype: str) -> Tuple[str, int]:
    """("float"|"int"|"", bits) for the TX-P02 widening comparison."""
    m = re.match(r"^(bf|f|c)(\d+)", dtype)
    if m:
        return "float", int(m.group(2))
    m = re.match(r"^(ui|i)(\d+)$", dtype)
    if m and dtype != "i1":     # predicates are not arithmetic values
        return "int", int(m.group(2))
    return "", 0


def _note_width(widths: Dict[str, int], dtype: str) -> None:
    cls, bits = _width_class(dtype)
    if cls:
        widths[cls] = max(widths.get(cls, 0), bits)


def parse_module(text: str) -> ModuleStats:
    """Walk one StableHLO module's text into :class:`ModuleStats`."""
    stats = ModuleStats()
    in_main = False
    for raw in text.splitlines():
        line = raw.strip()
        if line.startswith("func.func"):
            # parameter/output bytes come from the PUBLIC entry only;
            # private helper funcs would double-count
            in_main = "public" in line
            if in_main:
                for m in _ARG_RE.finditer(line):
                    b, dt, _ = _tensor_bytes(m.group(1))
                    stats.parameter_bytes += b
                    _note_width(stats.param_widths, dt)
                arrow = line.rfind("->")
                if arrow != -1:
                    for m in _TENSOR_RE.finditer(line[arrow:]):
                        b, _, _ = _tensor_bytes(m.group(1))
                        stats.output_bytes += b
            continue
        m = _OP_RE.match(line)
        if m is None:
            continue
        op = m.group(1)
        if op in ("func.return", "stablehlo.return"):
            continue
        stats.op_histogram[op] = stats.op_histogram.get(op, 0) + 1

        # dtype census + widening signal: the op's RESULT type is the
        # last tensor spec on the line
        specs = _TENSOR_RE.findall(line)
        if specs:
            b, dtype, dynamic = _tensor_bytes(specs[-1])
            stats.dtype_census[dtype] = \
                stats.dtype_census.get(dtype, 0) + 1
            _note_width(stats.body_widths, dtype)
            if op == "stablehlo.constant":
                stats.constant_bytes += b
            if dynamic or any("?" in s for s in specs):
                stats.dynamic_shape_ops.append(op)

        if op in _DYNAMIC_OPS and op not in stats.dynamic_shape_ops:
            stats.dynamic_shape_ops.append(op)
        if op in _HOST_OPS:
            stats.host_transfer_ops.append(op)
        elif "custom_call" in op:
            tm = _TARGET_RE.search(line)
            target = (tm.group(1) or tm.group(2)) if tm else ""
            if _HOST_TARGET_RE.search(target or ""):
                stats.host_transfer_ops.append(f"{op}@{target}")
    return stats


def normalize_module(text: str) -> str:
    """Canonical form for fingerprinting: location metadata, pointer-
    valued backend configs and the module's display name are noise;
    everything else (ops, shapes, dtypes, constant DATA) is identity."""
    out: List[str] = []
    for raw in text.splitlines():
        line = raw.strip()
        if not line or line.startswith("#loc"):
            continue
        prev = None
        while prev != line:      # loc() can nest one level per pass
            prev = line
            line = _LOC_RE.sub("", line)
        line = _BACKEND_CFG_RE.sub('backend_config = "<ptr>"', line)
        line = _MODULE_NAME_RE.sub("module @m", line)
        out.append(line)
    return "\n".join(out)


def canonical_fingerprint(text: str, jax_version: str,
                          platform: str) -> str:
    """The canonical artifact key: ``xla:<platform>:jax-<version>:
    <sha256/32>`` over the normalized module. Same program + same
    environment = same key, bitwise, across processes; ANY kernel
    change that alters the emitted program changes it."""
    digest = hashlib.sha256(
        normalize_module(text).encode()).hexdigest()[:32]
    return f"xla:{platform}:jax-{jax_version}:{digest}"
