"""The TX-P rule family: lint findings over lowered plan IR.

AST rules (lint/rules_jax.py) see the Python a developer wrote; these
rules see the StableHLO program XLA will actually run. Both families
emit the same :class:`~..lint.findings.LintFinding` records through the
same catalog, severities and exit codes — ``tx audit`` fails a CI gate
exactly like ``tx lint`` does.

- **TX-P01** host transfer in a lowered scoring program (IR ground
  truth behind TX-J01/TX-X02).
- **TX-P02** precision widening inside a kernel composition — the body
  computes at a wider float/int width than any parameter carries
  (invisible to AST rule TX-J04).
- **TX-P03** bucket-lattice coverage gap vs the ProfileStore's
  recorded occupancy: a recorded shape BEYOND the plan's ladder top
  (every smaller shape pads up to some rung of this ladder — custom
  non-pow2 lattices don't trip false gaps for old pow2 records).
- **TX-P04** padding-waste bound: each record's mean real rows per
  dispatch remapped onto THIS plan's effective rung, ERROR above the
  ``audit.waste_ceiling`` tuning knob (reduces to the classic
  ``padded_rows/real_rows`` on a matching pow2 ladder).
- **TX-P05** classification drift: ``lowering_reason``
  (plans/common.py) disagrees with what actually lowers.

TX-P01/P02/P05 are pure functions of the (cacheable) audits/plan;
TX-P03/P04 read LIVE ProfileStore occupancy and are always evaluated
fresh — recorded traffic must never be masked by an audit cache hit.
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence

from ..lint.findings import LintFinding, rule_severity

__all__ = ["lint_audits", "audit_findings", "verify_classification",
           "occupancy_findings"]


def _finding(rule_id: str, subject: str, message: str,
             hint: Optional[str] = None) -> LintFinding:
    return LintFinding(rule_id=rule_id, message=message,
                       severity=rule_severity(rule_id),
                       subject=subject, hint=hint)


# ---------------------------------------------------------------------------
# audit-only rules (TX-P01 / TX-P02)
# ---------------------------------------------------------------------------

def audit_findings(audits: Sequence) -> List[LintFinding]:
    """TX-P01 + TX-P02 over a batch of :class:`PlanAudit` records —
    deterministic functions of the lowered IR alone."""
    out: List[LintFinding] = []
    for a in audits:
        subject = f"{a.plan}:{a.label}"
        if a.plan == "score" and a.host_transfer_ops:
            ops = ", ".join(sorted(set(a.host_transfer_ops)))
            out.append(_finding(
                "TX-P01", subject,
                f"lowered scoring program for bucket {a.bucket} "
                f"contains host-transfer op(s): {ops} — every dispatch "
                f"of this bucket round-trips through the host",
                hint="replace the callback/infeed with an array kernel "
                     "(transform_arrays) or demote the stage to an "
                     "explicit host fallback phase"))
        for cls in ("float", "int"):
            pw = a.param_widths.get(cls, 0)
            bw = a.body_widths.get(cls, 0)
            if pw and bw > pw:
                out.append(_finding(
                    "TX-P02", subject,
                    f"program body computes at {cls}{bw} while no "
                    f"parameter is wider than {cls}{pw} — a kernel "
                    f"composition widens intermediates beyond the "
                    f"input precision (bucket {a.bucket})",
                    hint=f"pin the intermediate dtype (e.g. "
                         f".astype(inputs' dtype)) inside the kernel, "
                         f"or lower the constant that forces the "
                         f"{cls}{bw} upcast"))
    return out


# ---------------------------------------------------------------------------
# occupancy rules (TX-P03 / TX-P04) — live ProfileStore, never cached
# ---------------------------------------------------------------------------

def _recorded_score_buckets(store) -> Dict[int, dict]:
    """bucket -> accumulated occupancy record, from the store's
    normalized ``score:b<N>`` profile keys."""
    out: Dict[int, dict] = {}
    for key, rec in (store.profiles() or {}).items():
        if not key.startswith("score:b"):
            continue
        try:
            bucket = int(key[len("score:b"):])
        except ValueError:
            continue
        out[bucket] = rec
    return out


def occupancy_findings(audits: Sequence, store=None,
                       waste_ceiling: Optional[float] = None
                       ) -> List[LintFinding]:
    """TX-P03 + TX-P04: the plan's bucket ladder (from the score
    audits) judged against the ProfileStore's RECORDED dispatch
    occupancy. No store / no recorded traffic = vacuously clean."""
    if waste_ceiling is None:
        from ..tuning.registry import STATIC_DEFAULTS
        waste_ceiling = float(STATIC_DEFAULTS["audit.waste_ceiling"])
    ladder = sorted({a.bucket for a in audits if a.plan == "score"})
    if store is None or not ladder:
        return []
    try:
        recorded = _recorded_score_buckets(store)
    except Exception:               # store unreadable: occupancy unknown
        return []
    out: List[LintFinding] = []
    top = ladder[-1]
    for bucket in sorted(recorded):
        rec = recorded[bucket]
        calls = int(rec.get("calls", 0) or 0)
        rows = int(rec.get("rows", 0) or 0)
        # lattice-aware coverage (docs/ragged_batching.md): a recorded
        # bucket BELOW the ladder top always pads up to some rung of
        # THIS plan — only a shape beyond the top rung signals a range
        # this ladder cannot serve without chunking. Custom non-pow2
        # lattices must not trip false gaps for old pow2 records.
        if bucket > top:
            out.append(_finding(
                "TX-P03", f"score:b{bucket}",
                f"recorded dispatch occupancy at bucket {bucket} "
                f"({calls} calls) beyond this plan's ladder top "
                f"{top} (ladder {ladder}) — that batch shape chunks "
                f"or forces an unplanned XLA compile at serve time",
                hint="widen the plan's [min_bucket, max_bucket] range "
                     "(tuning knobs serving.min_bucket/max_bucket) to "
                     "cover the recorded shape, or chunk the batch"))
            continue
        if calls <= 0 or rows <= 0:
            continue                # occupancy unknown — no bound
        # lattice-aware waste: remap the record's mean real rows per
        # dispatch onto THIS ladder's effective rung (for a matching
        # pow2 ladder this reduces exactly to the old
        # calls*bucket/rows bound)
        mean_rows = rows / calls
        eff = next((r for r in ladder if r >= math.ceil(mean_rows)),
                   top)
        waste = eff / mean_rows
        if waste > waste_ceiling:
            out.append(_finding(
                "TX-P04", f"score:b{bucket}",
                f"padding waste {waste:.1f}x at bucket {eff} "
                f"({calls} calls, mean {mean_rows:.1f} real rows "
                f"padding to rung {eff} of ladder {ladder}) exceeds "
                f"the waste ceiling {waste_ceiling:g}x — the device "
                f"spends most of this bucket scoring padding",
                hint="lower serving.min_bucket (or coalesce requests "
                     "— serving/server.py deadline-or-full) so small "
                     "batches stop paying for the full bucket; the "
                     "ceiling is the audit.waste_ceiling tuning knob"))
    return out


# ---------------------------------------------------------------------------
# classification drift (TX-P05) — needs the live plan
# ---------------------------------------------------------------------------

def verify_classification(plan) -> List[LintFinding]:
    """Verify the plan's ``lowering_reason`` classification
    (plans/common.py) against the IR that actually lowers:

    - every "device" stage's kernel must still trace abstractly,
      standalone, at the plan's input avals;
    - every fallback recorded as "no array kernel (transform_arrays)"
      must still LACK an array kernel — a stage that grew
      ``transform_arrays`` since classification is silently
      misclassified and scores on the slow host path.
    """
    import jax
    out: List[LintFinding] = []
    plan.compile()
    if getattr(plan, "_device_steps", None):
        avals, _mask = plan.device_input_avals(plan.min_bucket)
        env = {key: aval for (key, _n, _e), aval
               in zip(plan._host_inputs, avals)}
        for stage, out_name, keys in plan._device_steps:
            name = f"{type(stage).__name__}({out_name})"
            try:
                env[out_name] = jax.eval_shape(
                    lambda *a, s=stage: s.transform_arrays(list(a)),
                    *[env[k] for k in keys])
            except Exception as e:
                out.append(_finding(
                    "TX-P05", f"score:{name}",
                    f"stage {name} is classified 'device' but its "
                    f"kernel fails the abstract trace at the plan's "
                    f"input avals ({type(e).__name__}: {e})",
                    hint="the classification and the kernel drifted "
                         "apart; fix the kernel or let compile() "
                         "demote it explicitly"))
                break               # downstream avals are unknowable
    for step in getattr(plan, "_steps", ()):
        if step.phase == "device":
            continue
        if (step.reason.startswith("no array kernel")
                and step.stage.supports_arrays()):
            name = f"{type(step.stage).__name__}({step.out_name})"
            out.append(_finding(
                "TX-P05", f"score:{name}",
                f"stage {name} was classified as a host fallback "
                f"('{step.reason}') but the stage DOES expose "
                f"transform_arrays now — it scores on the slow host "
                f"path for a stale reason",
                hint="recompile the plan (the classification is "
                     "computed at compile(); a class edit after "
                     "compile leaves it stale)"))
    return out


def lint_audits(audits: Sequence, store=None,
                waste_ceiling: Optional[float] = None,
                plan=None) -> List[LintFinding]:
    """The full TX-P pass: IR rules over ``audits``, occupancy rules
    against ``store``, and (when the live ``plan`` is given)
    classification-drift verification."""
    out = audit_findings(audits)
    out.extend(occupancy_findings(audits, store=store,
                                  waste_ceiling=waste_ceiling))
    if plan is not None:
        out.extend(verify_classification(plan))
    return out
