"""AOT-compiled, persisted plan artifacts (docs/aot_artifacts.md).

PR 12 made restart fast *when a warm snapshot exists*; this package
removes the remaining cold-start compile bill entirely. At
``save_model`` time every ``ScoringPlan`` bucket program (and each
``PreparePlan`` segment program the training run dispatched) is
AOT-compiled (``jax.jit(...).lower().compile()``), serialized
(``jax.experimental.serialize_executable``) and written into the model
directory as a checksummed, manifest-keyed artifact store. At serve
boot the loader deserializes those executables instead of compiling —
zero XLA compiles in the serve process on the happy path.

Validity is keyed exactly like the PR-16 audit layer: (jax version,
platform/backend, machine fingerprint on CPU, the canonical plan
fingerprint, the bucket ladder). ANY mismatch falls back to live
compile loudly — a per-class telemetry counter + event, never a crash,
and bitwise-identical scores either way (the artifact is the same
program the live path would compile).

- :mod:`.store`  — on-disk layout, manifest schema, checksums, staging
- :mod:`.export` — the ``save_model`` hook + ``tx artifacts --export``
- :mod:`.loader` — ``load_or_compile`` (the ONLY sanctioned way for
  serving/CLI code to build a plan: lint rule TX-R06 flags direct
  ``ScoringPlan(...).compile()`` call sites in those trees)
"""
from .store import (ARTIFACT_DIR, MANIFEST_FILE, ARTIFACT_SCHEMA,
                    artifact_dir, read_manifest, env_stamp,
                    export_enabled, load_mode)
from .export import export_model_artifacts, export_scoring_artifacts, \
    export_prepare_artifacts
from .loader import ArtifactsRequired, load_or_compile, \
    load_scoring_artifacts, seed_prepare_registry, prepare_executable, \
    clear_prepare_registry

__all__ = [
    "ARTIFACT_DIR", "MANIFEST_FILE", "ARTIFACT_SCHEMA",
    "artifact_dir", "read_manifest", "env_stamp", "export_enabled",
    "load_mode",
    "export_model_artifacts", "export_scoring_artifacts",
    "export_prepare_artifacts",
    "ArtifactsRequired", "load_or_compile", "load_scoring_artifacts",
    "seed_prepare_registry", "prepare_executable",
    "clear_prepare_registry",
]
