"""Artifact export: AOT-compile + serialize every plan program
(docs/aot_artifacts.md).

``save_model`` calls :func:`export_model_artifacts` on its staging dir
(workflow/persistence.py) — the artifact store rides inside the same
atomic directory swap as the model itself. ``tx artifacts --export``
re-exports an existing model dir for the current environment (the
"platform move" repair path), going through the store's own staged
swap.

Export is best-effort by contract: a program that fails to AOT-compile
or serialize skips its entry loudly (counter + event) and the save
proceeds — a model without artifacts live-compiles exactly as before.
"""
from __future__ import annotations

import json
import logging
import os
import time
from typing import Any, Dict, Optional

from ..runtime import telemetry as _telemetry
from . import store as _store

_log = logging.getLogger(__name__)

__all__ = ["export_model_artifacts", "export_scoring_artifacts",
           "export_prepare_artifacts"]


def _serialize(compiled) -> bytes:
    """One ``jax.stages.Compiled`` -> payload bytes. The in/out pytree
    defs are NOT persisted: both are recomputed deterministically at
    load time from the plan's own avals (loader._tree_defs), so the
    payload is pure XLA executable + jax glue."""
    from jax.experimental import serialize_executable as _se
    payload, _in_tree, _out_tree = _se.serialize(compiled)
    return payload


def _plan_fingerprint_for_export(plan, staging_dir: str) -> str:
    """The canonical plan fingerprint for the manifest. The
    ``plan-fingerprint.json`` sidecar (PR 16, written moments earlier
    in the same save) is authoritative when present — one lowering
    serves both keys; otherwise compute from the already-compiled
    plan's min bucket."""
    from ..analysis.audit import AUDIT_SIDECAR, audit_scoring_plan
    sidecar = os.path.join(staging_dir, AUDIT_SIDECAR)
    try:
        with open(sidecar, encoding="utf-8") as fh:
            fp = json.load(fh).get("fingerprint")
        if fp:
            return str(fp)
    except (OSError, ValueError):
        pass
    return audit_scoring_plan(plan, buckets=[plan.min_bucket],
                              compiled=False)[0].fingerprint


def export_scoring_artifacts(plan, manifest: dict,
                             payloads: Dict[str, bytes]) -> int:
    """AOT-compile + serialize every bucket program of a compiled
    ScoringPlan into ``payloads``/``manifest``. Returns the number of
    bucket entries written."""
    entries: Dict[str, dict] = {}
    for bucket in plan.buckets():
        try:
            compiled = plan.lower_bucket(int(bucket)).compile()
            payload = _serialize(compiled)
        except Exception as e:
            _telemetry.count("serve_aot_export_errors")
            _telemetry.event("serve_aot_export_error", kind="score",
                             bucket=int(bucket),
                             error=f"{type(e).__name__}: {e}")
            _log.warning("AOT export: scoring bucket %d not exported "
                         "(%s: %s)", bucket, type(e).__name__, e)
            continue
        fname = f"score-b{int(bucket)}.bin"
        payloads[fname] = payload
        entries[f"b{int(bucket)}"] = {
            "file": fname, "bucket": int(bucket),
            "sha256": _store.payload_sha256(payload),
            "bytes": len(payload),
        }
    manifest["score"] = entries
    manifest["buckets"] = [int(b) for b in plan.buckets()]
    manifest["nOutputs"] = len(plan._device_outputs)
    manifest["donate"] = bool(plan.donate)
    return len(entries)


def export_prepare_artifacts(prepare_plan, manifest: dict,
                             payloads: Dict[str, bytes]) -> int:
    """Serialize every fused prepare segment program the training run
    dispatched, from the plan's PR-16 audit handles (plans/prepare.py
    records the jitted fn + input avals + buckets + the cross-train
    segment signature digest per segment). Keyed by signature digest:
    a later train whose fitted state fingerprints identically resolves
    the artifact instead of compiling."""
    import jax
    import numpy as np
    entries: Dict[str, dict] = {}
    for handle in getattr(prepare_plan, "audit_handles", ()):
        sig = handle.get("sig_digest")
        if not sig:
            continue            # unfingerprintable segment: no reuse key
        for bucket in handle["buckets"]:
            label = f"{handle['label']}:b{int(bucket)}"
            try:
                avals = tuple(
                    jax.ShapeDtypeStruct((int(bucket),) + tuple(shape),
                                         dtype)
                    for shape, dtype in handle["in_avals"])
                mask = jax.ShapeDtypeStruct((int(bucket),), np.float64)
                compiled = handle["fn"].lower(avals, mask).compile()
                payload = _serialize(compiled)
            except Exception as e:
                _telemetry.count("serve_aot_export_errors")
                _telemetry.event("serve_aot_export_error",
                                 kind="prepare", label=label,
                                 error=f"{type(e).__name__}: {e}")
                _log.warning("AOT export: prepare segment %s not "
                             "exported (%s: %s)", label,
                             type(e).__name__, e)
                continue
            fname = (f"prepare-{handle['label']}-b{int(bucket)}.bin"
                     .replace(":", "-"))
            payloads[fname] = payload
            entries[label] = {
                "file": fname, "bucket": int(bucket), "sig": sig,
                "sha256": _store.payload_sha256(payload),
                "bytes": len(payload),
                "nOutputs": len(handle.get("stages") or ()),
                "inAvals": [[list(shape), np.dtype(dtype).name]
                            for shape, dtype in handle["in_avals"]],
            }
    manifest["prepare"] = entries
    return len(entries)


def export_model_artifacts(model, staging_dir: str,
                           prepare_plan: Any = None) -> Optional[dict]:
    """The ``save_model`` hook: export the model's scoring bucket
    programs (and, when the saving process just trained it, the
    prepare segment programs) into ``<staging_dir>/aot-artifacts``.
    Returns the manifest, or None when export is disabled / the plan
    has no device program. Never raises past the persistence wrapper.
    """
    if not _store.export_enabled():
        return None
    from ..serving.plan import ScoringPlan
    t0 = time.perf_counter()
    plan = ScoringPlan(model).compile()
    if not getattr(plan, "_device_steps", None):
        _telemetry.event("serve_aot_export_skipped",
                         reason="no device program")
        return None
    manifest: Dict[str, Any] = {"schema": _store.ARTIFACT_SCHEMA,
                                "createdAt": time.time()}
    manifest.update(_store.env_stamp())
    manifest["fingerprint"] = _plan_fingerprint_for_export(
        plan, staging_dir)
    payloads: Dict[str, bytes] = {}
    n_score = export_scoring_artifacts(plan, manifest, payloads)
    if prepare_plan is None:
        # the common save-after-train flow: the process-global handle
        # to the prepare plan train() just executed
        from ..plans.prepare import last_prepare_plan
        prepare_plan = last_prepare_plan()
    n_prep = 0
    if prepare_plan is not None and getattr(model, "train_dataset",
                                            None) is not None:
        n_prep = export_prepare_artifacts(prepare_plan, manifest,
                                          payloads)
    if not n_score:
        _telemetry.event("serve_aot_export_skipped",
                         reason="no bucket exported")
        return None
    _store.write_store(staging_dir, manifest, payloads)
    seconds = time.perf_counter() - t0
    _telemetry.count("serve_aot_exports")
    _telemetry.event("serve_aot_exported", buckets=n_score,
                     prepare_segments=n_prep,
                     bytes=sum(len(p) for p in payloads.values()),
                     seconds=round(seconds, 3))
    _log.info("AOT artifacts exported: %d scoring bucket(s), %d "
              "prepare segment(s), %.0f KiB in %.2fs", n_score, n_prep,
              sum(len(p) for p in payloads.values()) / 1024, seconds)
    return manifest
