"""Artifact loading: deserialize instead of compile
(docs/aot_artifacts.md).

:func:`load_or_compile` is the ONE sanctioned way for serving and CLI
code to turn a model into a compiled :class:`~..serving.plan.ScoringPlan`
(lint rule TX-R06 flags direct ``ScoringPlan(...).compile()`` call
sites in those trees). It builds the plan (trace only — building the
jitted fn compiles nothing), then tries to attach the model dir's
AOT-compiled executables per bucket. On the happy path the serve
process never invokes XLA.

Every validity failure falls back to live compile LOUDLY — its own
telemetry counter + one ``serve_aot_fallback`` event — and never
raises (except ``require`` mode, the fleet-replica contract):

==================  =====================================================
fallback class      meaning
==================  =====================================================
``missing``         no artifact store in the model dir (legacy save,
                    export disabled, or crash before manifest)
``jax_version``     artifacts compiled under a different jax
``platform``        different backend, or a different CPU machine
                    fingerprint (XLA:CPU code is host-ISA-specific)
``fingerprint``     canonical plan fingerprint drift — the program
                    this environment lowers differs from the exported
                    one (kernel edit since save)
``bucket_ladder``   this plan dispatches buckets the store does not
                    cover (the tuning knob moved past the exported
                    range) — covered buckets still load; a serving
                    ladder that is a SUBSET of the exported one is the
                    normal healthy case and no fallback at all
``torn``            checksum/deserialize failure on ANY entry — the
                    whole store is discarded (audit-cache poisoning
                    contract), loud stderr
==================  =====================================================
"""
from __future__ import annotations

import collections
import logging
import sys
from typing import Any, Dict, Optional, Tuple

from ..runtime import telemetry as _telemetry
from . import store as _store

_log = logging.getLogger(__name__)

__all__ = ["ArtifactsRequired", "load_or_compile",
           "load_scoring_artifacts", "seed_prepare_registry",
           "prepare_executable", "clear_prepare_registry"]


class ArtifactsRequired(RuntimeError):
    """``TX_AOT_ARTIFACTS=require`` (or ``tx serve --artifacts
    require``) and a model could not load valid artifacts — a fleet
    replica that would otherwise compile in-band refuses to boot."""


def record_aot_fallback(reason: str, model_dir: Optional[str],
                        **fields: Any) -> None:
    """The loud-degradation contract (TX-R01 vocabulary): every
    artifact miss is a counted, evented, logged fallback to live
    compile — visible in metrics_snapshot()['counters'] and the
    warm-restart snapshot."""
    _telemetry.count("serve_aot_fallbacks")
    _telemetry.count(f"serve_aot_fallback_{reason}")
    _telemetry.event("serve_aot_fallback", reason=reason,
                     model_dir=model_dir or "", **fields)
    _log.warning("AOT artifacts unavailable (%s) for %s — falling "
                 "back to live compile%s", reason, model_dir or
                 "<in-memory model>",
                 "".join(f"; {k}={v}" for k, v in fields.items()))


def _poison(model_dir: str, why: str) -> None:
    """Torn/tampered store: discard EVERYTHING (never serve a mix of
    loaded and suspect programs) — the audit-cache poisoning idiom."""
    print(f"tx-artifacts: WARNING: artifact store poisoned ({why}) — "
          f"discarding {_store.artifact_dir(model_dir)} contents and "
          f"live-compiling every bucket", file=sys.stderr)


def _tree_defs(plan, bucket: int, n_outputs: int):
    """Recompute the serialized executable's calling-convention pytree
    defs from the plan itself — deterministic, so they are never
    persisted (export._serialize drops them)."""
    import jax.tree_util as jtu
    inputs, mask = plan.device_input_avals(int(bucket))
    in_tree = jtu.tree_structure(((inputs, mask), {}))
    out_tree = jtu.tree_structure(tuple(range(int(n_outputs))))
    return in_tree, out_tree


def _check_key(plan, manifest: dict) -> Optional[Tuple[str, dict]]:
    """Validity key comparison; ``(fallback_class, detail)`` on the
    first mismatch, None when the store is valid for this process."""
    env = _store.env_stamp()
    if str(manifest.get("jax")) != env["jax"]:
        return "jax_version", {"saved": str(manifest.get("jax")),
                               "current": env["jax"]}
    if str(manifest.get("platform")) != env["platform"]:
        return "platform", {"saved": str(manifest.get("platform")),
                            "current": env["platform"]}
    if str(manifest.get("machine")) != env["machine"]:
        return "platform", {"detail": "machine fingerprint",
                            "saved": str(manifest.get("machine"))[:12],
                            "current": env["machine"][:12]}
    return None


def _current_fingerprint(plan, model_dir: str) -> Optional[str]:
    """The plan's canonical fingerprint in THIS environment, through
    the PR-16 audit cache (pure hashing on a warm boot — the cache was
    seeded at save time)."""
    try:
        from ..analysis.audit import _fingerprint_via_cache
        return _fingerprint_via_cache(plan.model, model_dir)
    except Exception as e:
        _log.warning("AOT artifacts: fingerprint not computable "
                     "(%s: %s)", type(e).__name__, e)
        return None


def load_scoring_artifacts(plan, model_dir: str
                           ) -> Tuple[Optional[Dict[int, Any]],
                                      Optional[dict]]:
    """Deserialize the model dir's scoring executables for ``plan``.
    Returns ``({bucket: Compiled}, manifest)`` on success or
    ``(None, None)`` after a counted fallback. Never raises."""
    manifest, state = _store.read_manifest(model_dir)
    if manifest is None:
        if state == "torn":
            _poison(model_dir, "unreadable manifest")
        record_aot_fallback("torn" if state == "torn" else "missing",
                            model_dir)
        return None, None
    mismatch = _check_key(plan, manifest)
    if mismatch is not None:
        reason, detail = mismatch
        record_aot_fallback(reason, model_dir, **detail)
        return None, None
    # bucket coverage: the store must cover the ladder THIS plan will
    # dispatch. The serving side tunes its ladder to a subrange of the
    # export-time default (tuning/policy.bucket_range), so a SUBSET is
    # the normal healthy case — zero compiles. Buckets the store lacks
    # (tuning knob moved past the exported range, or a hand-edited
    # ladder) degrade loudly: the overlap still loads, the missing
    # buckets live-compile on first dispatch.
    exported = {int(e.get("bucket", 0))
                for e in (manifest.get("score") or {}).values()}
    wanted = [int(b) for b in plan.buckets()]
    missing = [b for b in wanted if b not in exported]
    if missing:
        record_aot_fallback(
            "bucket_ladder", model_dir,
            saved=sorted(exported), current=wanted, missing=missing)
        if len(missing) == len(wanted):
            return None, None
    expected = manifest.get("fingerprint")
    current = _current_fingerprint(plan, model_dir)
    if current is None or current != expected:
        record_aot_fallback("fingerprint", model_dir,
                            saved=str(expected),
                            current=str(current))
        return None, None
    from jax.experimental import serialize_executable as _se
    n_outputs = int(manifest.get("nOutputs", 0))
    execs: Dict[int, Any] = {}
    for label, entry in sorted((manifest.get("score") or {}).items()):
        bucket = int(entry.get("bucket", 0))
        if bucket not in wanted:
            continue            # exported superset: not dispatchable here
        payload = _store.read_payload(model_dir, entry)
        if payload is None:
            _poison(model_dir, f"checksum/read failure on {label}")
            record_aot_fallback("torn", model_dir, entry=label)
            return None, None
        try:
            in_tree, out_tree = _tree_defs(plan, bucket, n_outputs)
            execs[bucket] = _se.deserialize_and_load(
                payload, in_tree, out_tree)
        except Exception as e:
            _poison(model_dir,
                    f"deserialize failure on {label}: "
                    f"{type(e).__name__}")
            record_aot_fallback("torn", model_dir, entry=label,
                                error=f"{type(e).__name__}: {e}")
            return None, None
    if not execs:
        record_aot_fallback("missing", model_dir,
                            detail="manifest has no scoring entries")
        return None, None
    _telemetry.count("serve_aot_loads")
    _telemetry.count("serve_aot_loaded_buckets", len(execs))
    _telemetry.event("serve_aot_loaded", model_dir=model_dir,
                     buckets=sorted(execs))
    return execs, manifest


def load_or_compile(model, model_dir: Optional[str] = None,
                    require: Optional[bool] = None,
                    **plan_kwargs: Any):
    """Build + compile a ScoringPlan for ``model``, attaching the
    model dir's AOT artifacts when valid — THE serving/CLI entry point
    (TX-R06). ``model_dir`` defaults to the dir the model was saved
    to / loaded from (``model.model_dir``); an in-memory model with no
    dir live-compiles silently (there is nothing to have loaded).
    ``require=True`` (or ``TX_AOT_ARTIFACTS=require``) raises
    :class:`ArtifactsRequired` instead of falling back."""
    from ..serving.plan import ScoringPlan
    plan = ScoringPlan(model, **plan_kwargs).compile()  # tx-lint: disable=TX-R06 (this IS the artifact loader)
    mode = _store.load_mode()
    if require is None:
        require = mode == "require"
    if mode == "off":
        return plan
    mdir = model_dir or getattr(model, "model_dir", None)
    if not mdir:
        if require:
            raise ArtifactsRequired(
                "artifacts required but the model has no model dir "
                "to load them from")
        return plan
    if not getattr(plan, "_device_steps", None):
        return plan             # host-only plan: nothing to load
    execs, manifest = load_scoring_artifacts(plan, mdir)
    if execs is None:
        if require:
            raise ArtifactsRequired(
                f"artifacts required but {mdir} has no valid artifact "
                f"store for this environment (see the "
                f"serve_aot_fallback event for the class)")
        return plan
    plan.attach_artifacts(execs, manifest)
    seed_prepare_registry(mdir, manifest=manifest)
    return plan


# ---------------------------------------------------------------------------
# prepare-segment registry (plans/prepare.py consults it per dispatch)
# ---------------------------------------------------------------------------

#: (segment signature digest, bucket) -> deserialized executable.
#: Bounded LRU like the in-process segment cache — a long-lived
#: lifecycle process seeds one model zoo's worth, not unbounded.
_PREPARE_REGISTRY: "collections.OrderedDict[Tuple[str, int], Any]" = \
    collections.OrderedDict()
_PREPARE_REGISTRY_MAX = 128


def prepare_executable(sig_digest: Optional[str],
                       bucket: int) -> Optional[Any]:
    """The AOT executable for one (segment signature, bucket), or
    None — the prepare plan's per-dispatch lookup (plans/prepare.py).
    """
    if sig_digest is None or _store.load_mode() == "off":
        return None
    hit = _PREPARE_REGISTRY.get((sig_digest, int(bucket)))
    if hit is not None:
        _PREPARE_REGISTRY.move_to_end((sig_digest, int(bucket)))
    return hit


def clear_prepare_registry() -> None:
    _PREPARE_REGISTRY.clear()


def seed_prepare_registry(model_dir: str,
                          manifest: Optional[dict] = None) -> int:
    """Deserialize a model dir's prepare-segment artifacts into the
    process registry so the NEXT train of a state-identical workflow
    (the lifecycle retrain path) dispatches without compiling. Torn
    entries are skipped loudly (the scoring store's validity was
    already checked when this is called from load_or_compile).
    Returns the number of executables seeded."""
    if _store.load_mode() == "off":
        return 0
    if manifest is None:
        manifest, _state = _store.read_manifest(model_dir)
        if manifest is None:
            return 0
        if _check_key_env_only(manifest):
            return 0
    import numpy as np
    import jax
    import jax.tree_util as jtu
    from jax.experimental import serialize_executable as _se
    seeded = 0
    for label, entry in sorted((manifest.get("prepare") or {}).items()):
        sig = entry.get("sig")
        bucket = int(entry.get("bucket", 0))
        if not sig or (sig, bucket) in _PREPARE_REGISTRY:
            continue
        payload = _store.read_payload(model_dir, entry)
        if payload is None:
            record_aot_fallback("torn", model_dir, entry=label)
            continue
        try:
            avals = tuple(
                jax.ShapeDtypeStruct((bucket,) + tuple(shape),
                                     np.dtype(dtype))
                for shape, dtype in entry.get("inAvals") or ())
            mask = jax.ShapeDtypeStruct((bucket,), np.float64)
            in_tree = jtu.tree_structure(((avals, mask), {}))
            out_tree = jtu.tree_structure(
                tuple(range(int(entry.get("nOutputs", 0)))))
            ex = _se.deserialize_and_load(payload, in_tree, out_tree)
        except Exception as e:
            record_aot_fallback("torn", model_dir, entry=label,
                                error=f"{type(e).__name__}: {e}")
            continue
        _PREPARE_REGISTRY[(sig, bucket)] = ex
        _PREPARE_REGISTRY.move_to_end((sig, bucket))
        seeded += 1
    while len(_PREPARE_REGISTRY) > _PREPARE_REGISTRY_MAX:
        _PREPARE_REGISTRY.popitem(last=False)
    if seeded:
        _telemetry.count("serve_aot_prepare_seeded", seeded)
        _telemetry.event("serve_aot_prepare_seeded",
                         model_dir=model_dir, executables=seeded)
    return seeded


def _check_key_env_only(manifest: dict) -> bool:
    """True when the manifest's ENVIRONMENT key mismatches this
    process (the plan-independent half of _check_key — what a
    standalone prepare-registry seed can verify)."""
    env = _store.env_stamp()
    return (str(manifest.get("jax")) != env["jax"]
            or str(manifest.get("platform")) != env["platform"]
            or str(manifest.get("machine")) != env["machine"])
