"""Artifact store layout + manifest + integrity (docs/aot_artifacts.md).

One directory per saved model::

    <model_dir>/aot-artifacts/
        manifest.json            # schema, env key, fingerprint, entries
        score-b8.bin             # serialized executable per bucket
        ...
        prepare-seg0-b512.bin    # serialized executable per (segment,
                                 # bucket) the training run dispatched

The manifest is the validity key: (jax version, platform/backend,
machine fingerprint, canonical plan fingerprint, bucket ladder). Every
payload file carries its sha256 in the manifest; the loader verifies
before deserializing and — like the audit cache's poisoning contract
(analysis/cache.py) — ONE bad entry discards the whole store loudly
rather than serving a mix of loaded and tampered programs.

Writes are staged: payloads + manifest land in a sibling
``aot-artifacts.tmp-<pid>`` directory which is swapped in whole (the
``save_model`` rename idiom, workflow/persistence.py) — a crash
mid-export leaves either the previous store or none, never a torn one.
The manifest is written LAST inside the staging dir, so even a torn
staging dir can never present entries without their checksums.
"""
from __future__ import annotations

import hashlib
import json
import logging
import os
import shutil
from typing import Any, Dict, Optional, Tuple

_log = logging.getLogger(__name__)

__all__ = ["ARTIFACT_DIR", "MANIFEST_FILE", "ARTIFACT_SCHEMA",
           "artifact_dir", "manifest_path", "env_stamp",
           "export_enabled", "load_mode", "read_manifest",
           "write_store", "payload_sha256", "read_payload"]

#: artifact directory name inside a saved model dir — a SIDECAR:
#: analysis/cache.model_content_hash keys on op-model.json+arrays.npz
#: only, so writing artifacts never moves the model's content key
ARTIFACT_DIR = "aot-artifacts"
MANIFEST_FILE = "manifest.json"

#: manifest schema — bump on any layout/keying change; a mismatched
#: schema is routine invalidation (live compile), never a guess
ARTIFACT_SCHEMA = 1


def artifact_dir(model_dir: str) -> str:
    return os.path.join(model_dir, ARTIFACT_DIR)


def manifest_path(model_dir: str) -> str:
    return os.path.join(artifact_dir(model_dir), MANIFEST_FILE)


def export_enabled() -> bool:
    """``TX_AOT_EXPORT`` gates the save-side export (default ON —
    saving a model writes its compiled executables alongside it)."""
    return os.environ.get("TX_AOT_EXPORT", "on") not in ("off", "0")


def load_mode() -> str:
    """``TX_AOT_ARTIFACTS`` gates the load side: ``auto`` (default —
    load when present, loud fallback otherwise), ``require`` (a serve
    boot without valid artifacts is an error: fleet replicas must
    never compile in-band), ``off`` (always live-compile)."""
    mode = os.environ.get("TX_AOT_ARTIFACTS", "auto").lower()
    if mode in ("off", "0"):
        return "off"
    if mode == "require":
        return "require"
    return "auto"


def env_stamp() -> Dict[str, str]:
    """The environment half of the artifact key. ``machine`` matters
    on CPU: XLA:CPU emits host-ISA-specific code (utils/jax_setup
    documents the SIGILL hazard), so an artifact compiled on an AVX-512
    host must not load on a host without it."""
    import jax
    from ..utils.jax_setup import _machine_fingerprint
    return {"jax": jax.__version__,
            "platform": jax.default_backend(),
            "machine": _machine_fingerprint()}


def payload_sha256(payload: bytes) -> str:
    return hashlib.sha256(payload).hexdigest()


def read_manifest(model_dir: str) -> Tuple[Optional[dict], str]:
    """``(manifest, "ok")`` or ``(None, reason)`` with reason one of
    ``missing`` (no store / no manifest — the legacy-model-dir case)
    or ``torn`` (unreadable/corrupt/mis-schemad manifest)."""
    path = manifest_path(model_dir)
    if not os.path.exists(path):
        return None, "missing"
    try:
        with open(path, encoding="utf-8") as fh:
            doc = json.load(fh)
    except (OSError, ValueError):
        return None, "torn"
    if not isinstance(doc, dict) or doc.get("schema") != ARTIFACT_SCHEMA:
        return None, "torn"
    return doc, "ok"


def read_payload(model_dir: str, entry: dict) -> Optional[bytes]:
    """One entry's payload bytes, checksum-verified; None on any
    integrity failure (missing file, short read, sha mismatch)."""
    fname = entry.get("file")
    want = entry.get("sha256")
    if not fname or not want:
        return None
    path = os.path.join(artifact_dir(model_dir), os.path.basename(fname))
    try:
        with open(path, "rb") as fh:
            payload = fh.read()
    except OSError:
        return None
    if payload_sha256(payload) != want:
        return None
    return payload


def write_store(model_dir: str, manifest: dict,
                payloads: Dict[str, bytes]) -> str:
    """Stage ``payloads`` + ``manifest`` and swap the store into
    ``<model_dir>/aot-artifacts`` atomically. Returns the final dir."""
    final = artifact_dir(model_dir)
    tmp = f"{final}.tmp-export{os.getpid()}"
    if os.path.isdir(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    for fname, payload in payloads.items():
        fpath = os.path.join(tmp, os.path.basename(fname))
        with open(fpath, "wb") as fh:
            fh.write(payload)
            fh.flush()
            os.fsync(fh.fileno())
    # manifest LAST: a staging dir killed before this line carries no
    # manifest and reads as "missing", never as a torn store
    with open(os.path.join(tmp, MANIFEST_FILE), "w",
              encoding="utf-8") as fh:
        json.dump(manifest, fh, indent=1)
        fh.flush()
        os.fsync(fh.fileno())
    if os.path.isdir(final):
        old = f"{final}.old-export{os.getpid()}"
        if os.path.isdir(old):
            shutil.rmtree(old)
        os.rename(final, old)
        os.rename(tmp, final)
        shutil.rmtree(old)
    else:
        os.rename(tmp, final)
    return final


def manifest_summary(manifest: Optional[dict]) -> Optional[dict]:
    """The small, JSON-able slice of a manifest the serving snapshot
    and metrics carry (serving/state.py, metrics_snapshot)."""
    if not manifest:
        return None
    return {
        "fingerprint": manifest.get("fingerprint"),
        "jax": manifest.get("jax"),
        "platform": manifest.get("platform"),
        "buckets": sorted(int(b) for b in (manifest.get("buckets")
                                           or ())),
        "prepareSegments": len(manifest.get("prepare") or {}),
    }
