"""Automated feature validation (SURVEY §2.6; core/.../preparators/
SanityChecker.scala:236, core/.../filters/RawFeatureFilter.scala:87)."""
from .raw_feature_filter import (ExclusionReason, FeatureDistribution,
                                 RawFeatureFilter, RawFeatureFilterResults,
                                 rewire_without)
from .sanity_checker import (ColumnStatistics, SanityChecker,
                             SanityCheckerModel, SanityCheckerSummary)

__all__ = ["SanityChecker", "SanityCheckerModel", "SanityCheckerSummary",
           "ColumnStatistics", "RawFeatureFilter", "RawFeatureFilterResults",
           "FeatureDistribution", "ExclusionReason", "rewire_without"]
