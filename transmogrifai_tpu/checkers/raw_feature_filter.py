"""RawFeatureFilter: pre-DAG raw-feature exclusion.

TPU-native port of the reference RawFeatureFilter
(core/src/main/scala/com/salesforce/op/filters/{RawFeatureFilter.scala:
87-101,436,477, FeatureDistribution.scala:58, PreparedFeatures.scala,
Summary.scala}): before any stage is fitted, every raw feature's
fill rate and value distribution are computed on the training data (and
optionally on scoring data), and features are excluded when

- training fill rate < ``min_fill``,
- |train fill - score fill| > ``max_fill_difference``,
- fill ratio between train/score > ``max_fill_ratio_diff``,
- Jensen-Shannon divergence between train and score distributions
  > ``max_js_divergence`` (distribution shift),
- the null-indicator correlates with the label above
  ``max_correlation`` (leaky missingness).

Distributions: numeric/date features use a streaming histogram
(utils/histogram.py — the port of the reference's one Java file);
text-like features hash values into ``bins`` buckets
(FeatureDistribution.scala:58).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..features.columns import Dataset, FeatureColumn
from ..features.feature import Feature
from ..ops.vector_utils import stable_hash as _stable_hash
from ..types import FeatureType, OPNumeric
from ..utils.histogram import StreamingHistogram

__all__ = ["RawFeatureFilter", "FeatureDistribution",
           "RawFeatureFilterResults", "ExclusionReason",
           "numeric_histogram_js"]


def numeric_histogram_js(ha: Optional[StreamingHistogram],
                         hb: Optional[StreamingHistogram],
                         bins: int) -> float:
    """JS divergence of two numeric StreamingHistograms over shared
    breakpoints. Shared by the train-time RawFeatureFilter and the
    serve-time drift sentinel (serving/sentinel.py), so "shift" means
    the same thing in both places. Empty histograms compare as 0.0."""
    if ha is None or hb is None or ha.total == 0 or hb.total == 0 \
            or ha.centroids.size == 0 or hb.centroids.size == 0:
        return 0.0
    lo = min(ha.centroids.min(), hb.centroids.min())
    hi = max(ha.centroids.max(), hb.centroids.max())
    if hi <= lo:
        return 0.0
    breaks = np.linspace(lo, hi, bins + 1)[1:-1]
    pa = FeatureDistribution(name="a", distribution=ha.density(breaks))
    pb = FeatureDistribution(name="b", distribution=hb.density(breaks))
    return pa.js_divergence(pb)


@dataclass
class FeatureDistribution:
    """Null count + value histogram of one raw feature
    (reference FeatureDistribution.scala:58)."""
    name: str
    count: int = 0
    nulls: int = 0
    distribution: np.ndarray = field(
        default_factory=lambda: np.zeros(0, dtype=np.float64))
    is_numeric: bool = False

    @property
    def fill_rate(self) -> float:
        return 0.0 if self.count == 0 else 1.0 - self.nulls / self.count

    def js_divergence(self, other: "FeatureDistribution") -> float:
        """Jensen-Shannon divergence of the two normalized histograms
        (reference FeatureDistribution.jsDivergence).

        Empty, zero-count and non-finite histograms (a feature that was
        all-null on one side, a poisoned sketch) return 0.0 — "no
        evidence of shift" — instead of dividing by a zero/NaN bin sum
        and poisoning every downstream threshold comparison."""
        p = np.asarray(self.distribution, dtype=np.float64)
        q = np.asarray(other.distribution, dtype=np.float64)
        if p.size == 0 or q.size == 0 or p.size != q.size:
            return 0.0
        ps, qs = p.sum(), q.sum()
        if not np.isfinite(ps) or not np.isfinite(qs) \
                or ps <= 0 or qs <= 0:
            return 0.0
        p, q = p / ps, q / qs
        m = 0.5 * (p + q)
        with np.errstate(divide="ignore", invalid="ignore"):
            def kl(a, b):
                r = np.where((a > 0) & (b > 0), a * np.log2(a / b), 0.0)
                return float(np.sum(r))
            js = 0.5 * kl(p, m) + 0.5 * kl(q, m)
        # interpolation/rounding can leave js a hair outside [0, 1]
        return min(max(js, 0.0), 1.0) if np.isfinite(js) else 0.0

    def to_json(self) -> dict:
        return {"name": self.name, "count": self.count, "nulls": self.nulls,
                "distribution": self.distribution.tolist(),
                "isNumeric": self.is_numeric, "fillRate": self.fill_rate}

    @classmethod
    def from_json(cls, d: dict) -> "FeatureDistribution":
        return cls(name=d["name"], count=d["count"], nulls=d["nulls"],
                   distribution=np.asarray(d["distribution"],
                                           dtype=np.float64),
                   is_numeric=d["isNumeric"])


@dataclass
class ExclusionReason:
    """(reference ExclusionReasons in RawFeatureFilterResults)"""
    name: str
    reason: str

    def to_json(self) -> dict:
        return {"name": self.name, "reason": self.reason}

    @classmethod
    def from_json(cls, d: dict) -> "ExclusionReason":
        return cls(name=d["name"], reason=d["reason"])


@dataclass
class RawFeatureFilterResults:
    """(reference RawFeatureFilterResults recorded on the workflow)"""
    train_distributions: List[FeatureDistribution] = field(
        default_factory=list)
    score_distributions: List[FeatureDistribution] = field(
        default_factory=list)
    exclusions: List[ExclusionReason] = field(default_factory=list)

    @property
    def excluded_names(self) -> List[str]:
        seen, out = set(), []
        for e in self.exclusions:
            if e.name not in seen:
                seen.add(e.name)
                out.append(e.name)
        return out

    def to_json(self) -> dict:
        return {
            "trainDistributions": [d.to_json()
                                   for d in self.train_distributions],
            "scoreDistributions": [d.to_json()
                                   for d in self.score_distributions],
            "exclusions": [e.to_json() for e in self.exclusions]}

    @classmethod
    def from_json(cls, d: dict) -> "RawFeatureFilterResults":
        return cls(
            train_distributions=[FeatureDistribution.from_json(x)
                                 for x in d.get("trainDistributions", [])],
            score_distributions=[FeatureDistribution.from_json(x)
                                 for x in d.get("scoreDistributions", [])],
            exclusions=[ExclusionReason.from_json(x)
                        for x in d.get("exclusions", [])])


class RawFeatureFilter:
    """(reference RawFeatureFilter.scala:87-101; thresholds are the
    reference defaults)"""

    def __init__(self, min_fill: float = 0.001,
                 max_fill_difference: float = 0.90,
                 max_fill_ratio_diff: float = 20.0,
                 max_js_divergence: float = 0.90,
                 max_correlation: float = 0.9,
                 bins: int = 100,
                 protected_features: Sequence[str] = ()):
        self.min_fill = min_fill
        self.max_fill_difference = max_fill_difference
        self.max_fill_ratio_diff = max_fill_ratio_diff
        self.max_js_divergence = max_js_divergence
        self.max_correlation = max_correlation
        self.bins = bins
        self.protected_features = set(protected_features)

    # -- distribution computation ------------------------------------------
    def _distribution(self, f: Feature, col: FeatureColumn
                      ) -> FeatureDistribution:
        missing = col.is_missing()
        n = col.n_rows
        numeric = issubclass(f.ftype, OPNumeric)
        dist = FeatureDistribution(name=f.name, count=n,
                                   nulls=int(missing.sum()),
                                   is_numeric=numeric)
        if numeric:
            vals = np.asarray(
                [v if v is not None else np.nan for v in col.data],
                dtype=np.float64)
            hist = StreamingHistogram(self.bins)
            hist.update(vals[~np.isnan(vals)])
            dist.distribution = hist.counts.copy()
            dist._histogram = hist  # kept for shared-breakpoint JS
        else:
            counts = np.zeros(self.bins, dtype=np.float64)
            for v, miss in zip(col.data, missing):
                if miss:
                    continue
                if isinstance(v, (set, frozenset, list, tuple)):
                    for e in v:
                        counts[_stable_hash(str(e), self.bins)] += 1
                elif isinstance(v, dict):
                    for k in v:
                        counts[_stable_hash(str(k), self.bins)] += 1
                else:
                    counts[_stable_hash(str(v), self.bins)] += 1
            dist.distribution = counts
        return dist

    def _numeric_js(self, a: FeatureDistribution, b: FeatureDistribution
                    ) -> float:
        """JS divergence of two numeric histograms over shared quantile
        breakpoints (reference compares StreamingHistogram densities)."""
        return numeric_histogram_js(getattr(a, "_histogram", None),
                                    getattr(b, "_histogram", None),
                                    self.bins)

    # -- main entry ---------------------------------------------------------
    def compute_exclusions(
            self, raw_features: Sequence[Feature], train: Dataset,
            score: Optional[Dataset] = None,
            label: Optional[np.ndarray] = None
            ) -> RawFeatureFilterResults:
        """(reference generateFilteredRaw:477 / getFeaturesToExclude:436)"""
        results = RawFeatureFilterResults()
        predictors = [f for f in raw_features if not f.is_response]
        train_dists = {f.name: self._distribution(f, train[f.name])
                       for f in predictors if f.name in train}
        results.train_distributions = list(train_dists.values())
        score_dists: Dict[str, FeatureDistribution] = {}
        if score is not None:
            score_dists = {f.name: self._distribution(f, score[f.name])
                           for f in predictors if f.name in score}
            results.score_distributions = list(score_dists.values())

        def exclude(name: str, reason: str):
            if name not in self.protected_features:
                results.exclusions.append(ExclusionReason(name, reason))

        for f in predictors:
            td = train_dists.get(f.name)
            if td is None:
                continue
            if td.fill_rate < self.min_fill:
                exclude(f.name, f"train fill rate {td.fill_rate:.4f} below "
                                f"minFill {self.min_fill}")
            # leaky missingness: null indicator vs label correlation
            if label is not None and td.nulls > 0 and td.nulls < td.count:
                nulls = train[f.name].is_missing().astype(np.float64)
                y = np.asarray(label, dtype=np.float64)
                if np.std(nulls) > 0 and np.std(y) > 0:
                    c = float(np.corrcoef(nulls, y)[0, 1])
                    if abs(c) > self.max_correlation:
                        exclude(f.name,
                                f"null-indicator label correlation "
                                f"{c:.3f} above maxCorrelation "
                                f"{self.max_correlation}")
            sd = score_dists.get(f.name)
            if sd is None:
                continue
            fill_diff = abs(td.fill_rate - sd.fill_rate)
            if fill_diff > self.max_fill_difference:
                exclude(f.name, f"fill-rate difference {fill_diff:.3f} "
                                f"above maxFillDifference "
                                f"{self.max_fill_difference}")
            rates = sorted([max(td.fill_rate, 1e-12),
                            max(sd.fill_rate, 1e-12)])
            if rates[1] / rates[0] > self.max_fill_ratio_diff:
                exclude(f.name, f"fill-rate ratio {rates[1] / rates[0]:.2f} "
                                f"above maxFillRatioDiff "
                                f"{self.max_fill_ratio_diff}")
            js = self._numeric_js(td, sd) if td.is_numeric \
                else td.js_divergence(sd)
            if js > self.max_js_divergence:
                exclude(f.name, f"train/score JS divergence {js:.3f} above "
                                f"maxJSDivergence {self.max_js_divergence}")
        return results


def rewire_without(result_features: Sequence[Feature],
                   blacklist: Sequence[str]
                   ) -> Tuple[List[Feature], List[Feature]]:
    """Rebuild the DAG without blacklisted raw features
    (reference OpWorkflow.setBlacklist:112). Sequence stages lose the
    blacklisted inputs; fixed-arity stages with a blacklisted input raise
    (as the reference does for non-removable usages).

    Returns (new result features, blacklisted raw features).
    """
    bl = set(blacklist)
    cache: Dict[str, Optional[Feature]] = {}
    removed: List[Feature] = []

    def rebuild(f: Feature) -> Optional[Feature]:
        if f.uid in cache:
            return cache[f.uid]
        if f.is_raw:
            if f.name in bl:
                removed.append(f)
                cache[f.uid] = None
                return None
            cache[f.uid] = f
            return f
        new_parents = []
        dropped = []
        for p in f.parents:
            rp = rebuild(p)
            (new_parents if rp is not None else dropped).append(
                rp if rp is not None else p)
        stage = f.origin_stage

        def reclone() -> Feature:
            """Clone the stage onto the surviving parents, keeping the
            output feature's identity (name + uid) so user-held handles
            stay valid (the reference preserves features through
            setBlacklist rewiring)."""
            clone = type(stage)(**{**stage.get_params(), "uid": stage.uid})
            clone.set_input(*new_parents)
            nf = Feature(name=f.name, ftype=f.ftype,
                         is_response=f.is_response, origin_stage=clone,
                         parents=tuple(new_parents), uid=f.uid)
            clone._output_feature = nf
            return nf

        if not dropped:
            if all(np is op for np, op in zip(new_parents, f.parents)):
                cache[f.uid] = f
                return f
            out = reclone()
            cache[f.uid] = out
            return out
        if getattr(stage, "is_sequence", False) \
                and len(new_parents) >= stage.min_inputs:
            out = reclone()
            cache[f.uid] = out
            return out
        if not new_parents:
            cache[f.uid] = None
            return None
        raise ValueError(
            f"Cannot remove blacklisted features "
            f"{[p.name for p in dropped]} from non-sequence stage "
            f"{type(stage).__name__} feeding {f.name!r} — protect them "
            f"via RawFeatureFilter(protected_features=...) "
            f"(reference OpWorkflow.setBlacklist behavior)")

    new_results = []
    for rf in result_features:
        nf = rebuild(rf)
        if nf is None:
            raise ValueError(
                f"Result feature {rf.name!r} lost all its inputs to the "
                "raw feature filter")
        new_results.append(nf)
    return new_results, removed
