"""SanityChecker: automated feature validation and pruning.

TPU-native port of the reference SanityChecker
(core/src/main/scala/com/salesforce/op/stages/impl/preparators/
SanityChecker.scala:236, fitFn:535, params :61-206, metadata
SanityCheckerMetadata.scala): a BinaryEstimator over (RealNN label,
OPVector features) that computes per-column statistics, label
correlations and categorical association stats, prunes problematic
columns, and emits the full summary. The heavy math runs as XLA kernels
(utils/stats.py): one fused pass for moments + label correlation, and
per-group contingency tables for Cramér's V / chi² / mutual info /
association-rule confidence.

Pruning rules (same thresholds as the reference defaults):
- variance < ``min_variance``                       -> drop column
- |corr(label)| > ``max_correlation``               -> drop (leakage)
- |corr(label)| < ``min_correlation``               -> drop (noise)
- group Cramér's V > ``max_cramers_v``              -> drop whole group
- association rule confidence >= ``max_rule_confidence`` with support
  >= ``min_required_rule_support``                  -> drop whole group

Categorical groups come from the vector metadata's indicator groups —
the one-hot columns of a parent feature form one group and are kept or
removed together (reference group-aware removal).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..features.columns import FeatureColumn
from ..stages.base import AllowLabelAsInput, BinaryEstimator, BinaryModel
from ..types import OPVector, RealNN
from ..utils.stats import col_stats, contingency_stats, correlation_with_label
from ..utils.vector_meta import VectorMetadata

__all__ = ["SanityChecker", "SanityCheckerModel", "SanityCheckerSummary",
           "ColumnStatistics"]

#: labels with more distinct values than this are treated as continuous and
#: categorical association stats are skipped (reference categoricalLabel
#: heuristic in SanityChecker.fitFn)
MAX_LABEL_CARDINALITY = 100


@dataclass
class ColumnStatistics:
    """Per-column record in the summary (reference SanityCheckerMetadata)."""
    name: str
    column_index: int
    variance: float
    mean: float
    min: float
    max: float
    corr_label: float
    cramers_v: Optional[float] = None
    max_rule_confidence: Optional[float] = None
    support: Optional[float] = None
    is_dropped: bool = False
    reasons: List[str] = field(default_factory=list)
    #: provenance from the vector metadata (stable across index
    #: renumbering after pruning; used by ModelInsights matching)
    parent_feature_name: Optional[str] = None
    grouping: Optional[str] = None
    indicator_value: Optional[str] = None
    descriptor_value: Optional[str] = None

    def provenance_key(self) -> tuple:
        return (self.parent_feature_name, self.grouping,
                self.indicator_value, self.descriptor_value)

    def to_json(self) -> dict:
        return {"name": self.name, "columnIndex": self.column_index,
                "variance": self.variance, "mean": self.mean,
                "min": self.min, "max": self.max,
                "corrLabel": self.corr_label, "cramersV": self.cramers_v,
                "maxRuleConfidence": self.max_rule_confidence,
                "support": self.support, "isDropped": self.is_dropped,
                "reasons": list(self.reasons),
                "parentFeatureName": self.parent_feature_name,
                "grouping": self.grouping,
                "indicatorValue": self.indicator_value,
                "descriptorValue": self.descriptor_value}


@dataclass
class SanityCheckerSummary:
    """(reference SanityCheckerSummary metadata)"""
    column_stats: List[ColumnStatistics] = field(default_factory=list)
    dropped: List[str] = field(default_factory=list)
    kept_indices: List[int] = field(default_factory=list)
    sample_size: int = 0

    def to_json(self) -> dict:
        return {"columnStats": [c.to_json() for c in self.column_stats],
                "dropped": list(self.dropped),
                "keptIndices": list(self.kept_indices),
                "sampleSize": self.sample_size}


class SanityChecker(AllowLabelAsInput, BinaryEstimator):
    """(reference SanityChecker.scala:236)"""

    input_types = (RealNN, OPVector)
    output_type = OPVector

    def __init__(self, check_sample: float = 1.0, sample_seed: int = 42,
                 sample_limit: int = 100_000, max_correlation: float = 0.95,
                 min_correlation: float = 0.0, min_variance: float = 1e-5,
                 max_cramers_v: float = 0.95,
                 min_required_rule_support: float = 0.001,
                 max_rule_confidence: float = 1.0,
                 remove_bad_features: bool = True,
                 uid: Optional[str] = None):
        super().__init__(operation_name="sanityChecker", uid=uid)
        self.check_sample = check_sample
        self.sample_seed = sample_seed
        self.sample_limit = sample_limit
        self.max_correlation = max_correlation
        self.min_correlation = min_correlation
        self.min_variance = min_variance
        self.max_cramers_v = max_cramers_v
        self.min_required_rule_support = min_required_rule_support
        self.max_rule_confidence = max_rule_confidence
        self.remove_bad_features = remove_bad_features

    def check_input_constraints(self, features) -> None:
        label, vec = features
        if not label.is_response:
            raise ValueError("SanityChecker input 1 must be the response")
        if vec.is_response:
            raise ValueError("SanityChecker input 2 must not be a response")

    # -- fitting -----------------------------------------------------------
    def fit_columns(self, cols: List[FeatureColumn]) -> "SanityCheckerModel":
        y = np.asarray(cols[0].data, dtype=np.float64)
        X = np.asarray(cols[1].data, dtype=np.float64)
        meta = cols[1].metadata or VectorMetadata(name="features")
        return self._fit_stats(y, X, meta)

    def fit_device(self, arrays, protos) -> "SanityCheckerModel":
        """Compiled-prepare fit (plans/prepare.py): the feature matrix
        arrives as the device array the fused vectorize→combine program
        produced and feeds the stats kernels (utils/stats.py — already
        XLA) WITHOUT the host materialization ``fit_columns`` pays.
        Identical fitted state: the moment/correlation kernels are the
        same jnp programs either way, and the contingency tables are
        integer counts (one-hot indicator sums) — exact in any order."""
        y = np.asarray(arrays[0], dtype=np.float64)  # labels are tiny;
        X = arrays[1]                # the group logic walks them host-side
        meta = (protos[1].metadata if protos and protos[1] is not None
                else None) or VectorMetadata(name="features")
        return self._fit_stats(y, X, meta)

    def _fit_stats(self, y: np.ndarray, X, meta: VectorMetadata
                   ) -> "SanityCheckerModel":
        """Shared fit body; ``X`` may be host numpy OR a device (jax)
        array — the statistics run through the same XLA kernels and
        produce the same model either way."""
        n, d = X.shape

        # sampling (reference checkSample/sampleLimit, fitFn:535)
        target = min(int(np.ceil(n * self.check_sample)), self.sample_limit)
        if target < n:
            rng = np.random.default_rng(self.sample_seed)
            idx = np.sort(rng.choice(n, target, replace=False))
            Xs, ys = X[idx], y[idx]
            sample_size = int(target)
        else:
            Xs, ys = X, y
            sample_size = int(n)

        stats = col_stats(Xs)
        corr = correlation_with_label(Xs, ys)

        names = meta.column_names() if meta.size == d else \
            [f"f{i}" for i in range(d)]
        col_recs = []
        for j in range(d):
            rec = ColumnStatistics(
                name=names[j], column_index=j,
                variance=float(stats.variance[j]), mean=float(stats.mean[j]),
                min=float(stats.min[j]), max=float(stats.max[j]),
                corr_label=float(corr[j]))
            if meta.size == d:
                mc = meta.columns[j]
                rec.parent_feature_name = mc.parent_feature_name
                rec.grouping = mc.grouping
                rec.indicator_value = mc.indicator_value
                rec.descriptor_value = mc.descriptor_value
            col_recs.append(rec)

        def drop(j: int, reason: str):
            col_recs[j].is_dropped = True
            col_recs[j].reasons.append(reason)

        # per-column rules
        for j in range(d):
            if col_recs[j].variance < self.min_variance:
                drop(j, f"variance {col_recs[j].variance:.3g} below "
                        f"minVariance {self.min_variance}")
            c = col_recs[j].corr_label
            if np.isfinite(c):
                if abs(c) > self.max_correlation:
                    drop(j, f"label correlation {c:.3f} above "
                            f"maxCorrelation {self.max_correlation}")
                elif abs(c) < self.min_correlation:
                    drop(j, f"label correlation {c:.3f} below "
                            f"minCorrelation {self.min_correlation}")

        # categorical association rules per indicator group
        labels = np.unique(ys)
        if meta.size == d and 2 <= len(labels) <= MAX_LABEL_CARDINALITY:
            onehot_label = ys[:, None] == labels[None, :]
            groups = meta.indicator_groups()
            # gather every indicator column ONCE (a device X pays one
            # small transfer of the 0/1 indicator block instead of one
            # per column; the sums below are integer counts, so the
            # result is bit-identical to the per-column walk)
            all_idx = sorted({j for idxs in groups.values()
                              for j in idxs})
            local = {j: k for k, j in enumerate(all_idx)}
            Xind = (np.asarray(Xs[:, np.asarray(all_idx)],
                               dtype=np.float64)
                    if all_idx else np.zeros((sample_size, 0)))
            # ALL groups' tables in one matmul: indicator columns are
            # exactly 0/1, so every entry is an integer count — exact
            # in any summation order (bitwise equal to the former
            # per-level broadcast-sum, at a fraction of the cost: this
            # loop was the dominant fit cost on wide categorical data)
            tables_all = Xind.T @ onehot_label.astype(np.float64)
            for group_key, indices in groups.items():
                # contingency: level rows x label cols
                table = tables_all[[local[j] for j in indices], :]
                cs = contingency_stats(table)
                for k, j in enumerate(indices):
                    col_recs[j].cramers_v = cs.cramers_v
                    col_recs[j].max_rule_confidence = \
                        float(cs.max_rule_confidences[k]) \
                        if k < len(cs.max_rule_confidences) else None
                    col_recs[j].support = float(cs.supports[k]) \
                        if k < len(cs.supports) else None
                group_bad = []
                if np.isfinite(cs.cramers_v) and \
                        cs.cramers_v > self.max_cramers_v:
                    group_bad.append(
                        f"group Cramér's V {cs.cramers_v:.3f} above "
                        f"maxCramersV {self.max_cramers_v}")
                strong_rule = (
                    (cs.max_rule_confidences >= self.max_rule_confidence)
                    & (cs.supports >= self.min_required_rule_support))
                if strong_rule.any():
                    group_bad.append(
                        "association rule confidence above "
                        f"maxRuleConfidence {self.max_rule_confidence}")
                for reason in group_bad:
                    for j in indices:
                        drop(j, reason)

        kept = [j for j in range(d) if not col_recs[j].is_dropped] \
            if self.remove_bad_features else list(range(d))
        if not kept:
            raise ValueError(
                "SanityChecker dropped every feature column — relax the "
                "thresholds (minVariance/maxCorrelation/maxCramersV)")
        summary = SanityCheckerSummary(
            column_stats=col_recs,
            dropped=[col_recs[j].name for j in range(d)
                     if col_recs[j].is_dropped],
            kept_indices=kept, sample_size=sample_size)
        model = SanityCheckerModel(
            kept_indices=kept,
            output_metadata=(meta.select(kept) if meta.size == d else None))
        model.summary = summary
        return model


class SanityCheckerModel(AllowLabelAsInput, BinaryModel):
    """Vector slice by kept indices (reference: the fitted SanityChecker
    model behaves like DropIndicesByTransformer)."""

    input_types = (RealNN, OPVector)
    output_type = OPVector
    summary: Optional[SanityCheckerSummary] = None

    def __init__(self, kept_indices: Sequence[int],
                 output_metadata: Optional[VectorMetadata] = None,
                 uid: Optional[str] = None):
        super().__init__(operation_name="sanityChecker", uid=uid)
        self.kept_indices = [int(i) for i in kept_indices]
        self.output_metadata = output_metadata

    def transform_columns(self, cols: List[FeatureColumn]) -> FeatureColumn:
        vec = cols[-1]
        data = np.asarray(vec.data, dtype=np.float64)[:, self.kept_indices]
        meta = self.output_metadata
        if meta is None:
            src = vec.metadata
            meta = (src.select(self.kept_indices) if src is not None
                    and src.size == np.asarray(vec.data).shape[1] else None)
        if meta is None:
            from ..utils.vector_meta import VectorColumnMetadata
            meta = VectorMetadata(
                name=self.get_output().name if self.input_features else "v",
                columns=tuple(VectorColumnMetadata(
                    parent_feature_name="features",
                    parent_feature_type="OPVector")
                    for _ in self.kept_indices))
        return FeatureColumn.vector(data, meta)

    def transform_value(self, *values):
        vec = values[-1]
        arr = np.asarray(vec.value if hasattr(vec, "value") else vec,
                         dtype=np.float64).reshape(1, -1)
        return OPVector(arr[0, self.kept_indices])

    def transform_arrays(self, arrays):
        # column slice by kept indices; the (ignored) label lane rides
        # along so serve-time NaN labels never touch the output
        import jax.numpy as jnp
        return jnp.take(arrays[-1], jnp.asarray(self.kept_indices,
                                                dtype=jnp.int32), axis=1)
