"""CLI codegen (SURVEY §2.14; cli/src/main/scala/com/salesforce/op/cli/)."""
from .gen import generate_project, main

__all__ = ["generate_project", "main"]
