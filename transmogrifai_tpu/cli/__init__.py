"""CLI: project codegen (SURVEY §2.14;
cli/src/main/scala/com/salesforce/op/cli/) + the ``lint`` pre-flight
static analyzer (lint/, docs/lint.md)."""
from .gen import generate_project, main

__all__ = ["generate_project", "main"]
