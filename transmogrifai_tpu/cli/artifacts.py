"""``python -m transmogrifai_tpu.cli artifacts`` — inspect / verify /
re-export a saved model's AOT artifact store (docs/aot_artifacts.md).

Lists the store's validity key (jax version, platform, machine
fingerprint, canonical plan fingerprint, bucket ladder) and every
scoring-bucket / prepare-segment entry with its size and checksum
state. ``--verify`` additionally replays the loader's full validity
check against THIS environment — the answer to "will the serve process
on this host compile, or load?" — and exits 0 valid / 1 invalid /
2 internal error. ``--export`` (re-)compiles and swaps in a fresh
store for the current environment: the repair path after a jax
upgrade, platform move, or kernel edit.

    tx artifacts MODEL_DIR                  # key + entry table
    tx artifacts MODEL_DIR --verify         # would this host load it?
    tx artifacts MODEL_DIR --export         # re-export for this env
    tx artifacts MODEL_DIR --format json    # machine-readable
"""
from __future__ import annotations

import json
import os
import sys
from typing import List, Optional, Tuple

__all__ = ["add_artifacts_parser", "run_artifacts"]


def add_artifacts_parser(sub) -> None:
    ar = sub.add_parser(
        "artifacts",
        help="inspect/verify/re-export a saved model's AOT-compiled "
             "plan artifacts (exit 0 valid / 1 invalid / 2 error)")
    ar.add_argument("model_dir",
                    help="saved model directory (WorkflowModel.save)")
    ar.add_argument("--verify", action="store_true",
                    help="replay the loader's validity check against "
                         "this environment: checksums, jax/platform/"
                         "machine key, bucket ladder, canonical plan "
                         "fingerprint")
    ar.add_argument("--export", action="store_true",
                    help="(re-)export artifacts for the CURRENT "
                         "environment — AOT-compiles every bucket and "
                         "swaps the store in atomically")
    ar.add_argument("--format", choices=["text", "json"],
                    default="text", help="output format (default: text)")


def _entry_rows(model_dir: str, manifest: dict,
                check: bool) -> Tuple[List[tuple], int]:
    """(table rows, bad-entry count). ``check`` re-reads every payload
    through the checksum gate; otherwise the sha column is trusted."""
    from ..artifacts import store as _store
    rows, bad = [], 0
    for kind in ("score", "prepare"):
        for label, entry in sorted((manifest.get(kind) or {}).items()):
            if check:
                ok = _store.read_payload(model_dir, entry) is not None
                bad += 0 if ok else 1
                status = "ok" if ok else "TORN"
            else:
                status = "-"
            rows.append((kind, label, str(entry.get("bucket", "?")),
                         str(entry.get("bytes", "?")),
                         str(entry.get("sha256", ""))[:12], status))
    return rows, bad


def _key_checks(model_dir: str, manifest: dict) -> List[dict]:
    """The loader's validity key, check by check — each dict carries
    ``{check, saved, current, ok}`` (docs/aot_artifacts.md fallback
    matrix)."""
    from ..artifacts import store as _store
    env = _store.env_stamp()
    checks = [
        {"check": "jax_version", "saved": str(manifest.get("jax")),
         "current": env["jax"]},
        {"check": "platform", "saved": str(manifest.get("platform")),
         "current": env["platform"]},
        {"check": "machine", "saved": str(manifest.get("machine")),
         "current": env["machine"]},
    ]
    for c in checks:
        c["ok"] = c["saved"] == c["current"]
    try:
        from ..workflow.persistence import load_model
        model = load_model(model_dir)
        from ..serving.plan import ScoringPlan
        ladder = [int(b) for b in ScoringPlan(model).buckets()]
        exported = sorted(int(e.get("bucket", 0)) for e in
                          (manifest.get("score") or {}).values())
        # subset coverage is the loader's contract: the (possibly
        # tuned) serving ladder must be covered, not equal
        checks.append({"check": "bucket_ladder",
                       "saved": exported, "current": ladder,
                       "ok": set(ladder) <= set(exported)})
        from ..analysis.audit import _fingerprint_via_cache
        fp = _fingerprint_via_cache(model, model_dir)
        checks.append({"check": "fingerprint",
                       "saved": str(manifest.get("fingerprint")),
                       "current": str(fp),
                       "ok": str(manifest.get("fingerprint")) == str(fp)})
    except Exception as e:            # model unloadable != torn store
        checks.append({"check": "model_load",
                       "saved": "-",
                       "current": f"{type(e).__name__}: {e}",
                       "ok": False})
    return checks


def _format_text(model_dir: str, manifest: dict, rows, bad: int,
                 checks: Optional[List[dict]]) -> Tuple[str, int]:
    from ..artifacts.store import manifest_summary
    s = manifest_summary(manifest) or {}
    lines = [f"artifact store: {model_dir}",
             f"  jax={s.get('jax')} platform={s.get('platform')} "
             f"machine={str(manifest.get('machine'))[:12]}",
             f"  fingerprint={s.get('fingerprint')}",
             f"  buckets={s.get('buckets')} "
             f"prepareSegments={s.get('prepareSegments')}",
             ""]
    table = [("kind", "entry", "bucket", "bytes", "sha256", "check")]
    table += [tuple(r) for r in rows]
    widths = [max(len(r[i]) for r in table)
              for i in range(len(table[0]))]
    lines += ["  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip()
              for r in table]
    rc = 0
    if checks is not None:
        lines.append("")
        failed = [c for c in checks if not c["ok"]]
        for c in checks:
            mark = "ok " if c["ok"] else "FAIL"
            lines.append(f"{mark} {c['check']}: saved={c['saved']} "
                         f"current={c['current']}")
        if bad or failed:
            what = [f"{bad} torn entr{'y' if bad == 1 else 'ies'}"] \
                if bad else []
            what += [c["check"] for c in failed]
            lines.append(f"INVALID for this environment "
                         f"({', '.join(what)}) — the serve process "
                         f"would fall back to live compile")
            rc = 1
        else:
            lines.append(f"valid: this environment loads "
                         f"{len(rows)} executable(s), 0 compiles")
    return "\n".join(lines), rc


def run_artifacts(args) -> int:
    from ..utils.jax_setup import pin_platform_from_env
    pin_platform_from_env()
    try:
        from ..artifacts import store as _store
        if args.export:
            # explicit CLI export overrides the save-side env gate
            os.environ["TX_AOT_EXPORT"] = "on"
            from ..artifacts.export import export_model_artifacts
            from ..workflow.persistence import load_model
            model = load_model(args.model_dir)
            manifest = export_model_artifacts(model, args.model_dir)
            if manifest is None:
                print("tx-artifacts: nothing exported (plan has no "
                      "device program)", file=sys.stderr)
                return 2
            n = len(manifest.get("score") or {})
            print(f"exported {n} scoring bucket(s) for "
                  f"jax={manifest.get('jax')} "
                  f"platform={manifest.get('platform')}")
        manifest, state = _store.read_manifest(args.model_dir)
        if manifest is None:
            print(f"tx-artifacts: no artifact store in "
                  f"{args.model_dir} ({state}) — the serve process "
                  f"live-compiles this model "
                  f"(repair: tx artifacts {args.model_dir} --export)",
                  file=sys.stderr)
            return 1
        rows, bad = _entry_rows(args.model_dir, manifest,
                                check=args.verify)
        checks = _key_checks(args.model_dir, manifest) \
            if args.verify else None
        if args.format == "json":
            doc = {
                "modelDir": args.model_dir,
                "manifest": {k: v for k, v in manifest.items()
                             if k not in ("score", "prepare")},
                "entries": [dict(zip(("kind", "entry", "bucket",
                                      "bytes", "sha256", "check"), r))
                            for r in rows],
                "checks": checks,
                "valid": (not bad
                          and all(c["ok"] for c in checks or ()))
                if args.verify else None,
            }
            print(json.dumps(doc, indent=1))
            return 0 if not args.verify or doc["valid"] else 1
        text, rc = _format_text(args.model_dir, manifest, rows, bad,
                                checks)
        print(text)
        return rc
    except BrokenPipeError:  # pragma: no cover
        raise
    except Exception as e:
        print(f"tx-artifacts: internal error: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2
