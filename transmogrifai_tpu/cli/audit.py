"""``python -m transmogrifai_tpu.cli audit`` — static HLO-level audit
of compiled plans (docs/plan_audit.md).

Lowers every bucket program of a model's scoring plan (and, in --demo
mode, the prepare segment programs of a freshly trained demo pipeline)
via ``jax.jit(...).lower()`` — no execution, no devices — and reports
per-bucket op/fusion/byte features, the canonical IR fingerprint, and
the TX-P rule findings. Exit codes match ``tx lint``: 0 clean /
1 findings / 2 internal error.

    tx audit MODEL_DIR                 # audit a saved model's plan
    tx audit --demo                    # self-contained demo workload
    tx audit MODEL_DIR --format json   # machine-readable document
    tx audit MODEL_DIR --fingerprint   # print the canonical key only
"""
from __future__ import annotations

import json
import sys
from typing import List, Optional

__all__ = ["add_audit_parser", "run_audit"]


def add_audit_parser(sub) -> None:
    au = sub.add_parser(
        "audit",
        help="static HLO-level audit of a model's compiled plan "
             "programs (exit 0 clean / 1 findings / 2 internal error)")
    au.add_argument("model_dir", nargs="?", default=None,
                    help="saved model directory (WorkflowModel.save)")
    au.add_argument("--demo", action="store_true",
                    help="audit the self-contained demo pipeline "
                         "(trains once, cached under the tempdir) — "
                         "scoring buckets AND prepare segments")
    au.add_argument("--format", choices=["text", "json"],
                    default="text", help="output format (default: text)")
    au.add_argument("--fingerprint", action="store_true",
                    help="print only the canonical plan fingerprint "
                         "(the AOT artifact identity key) and exit 0")
    au.add_argument("--no-compile", action="store_true",
                    help="lower only, skip the XLA compile step "
                         "(faster; fusion counts report as -1)")
    au.add_argument("--fresh", action="store_true",
                    help="ignore the audit cache (and retrain the "
                         "demo model) — everything re-lowers")
    au.add_argument("--cache", default=None, metavar="FILE",
                    help="audit cache file (default: TX_AUDIT_CACHE "
                         "env or a per-checkout file under the system "
                         "tempdir; 'off' disables)")
    au.add_argument("--store", default=None, metavar="FILE",
                    help="ProfileStore path for the occupancy rules "
                         "TX-P03/TX-P04 and the IR-feature merge "
                         "(default: TX_PROFILE_STORE env or "
                         "BENCH_STATE.json)")
    au.add_argument("--waste-ceiling", type=float, default=None,
                    help="TX-P04 padded/real row ratio ceiling "
                         "(default: the audit.waste_ceiling tuning "
                         "knob)")
    au.add_argument("--no-persist", action="store_true",
                    help="do not merge the per-bucket IR features "
                         "into the ProfileStore profiles block")


def _format_table(audits, findings, stats) -> str:
    rows = [("plan:bucket", "ops", "fus", "const-B", "param-B",
             "out-B", "host", "dyn", "fingerprint")]
    for a in audits:
        rows.append((f"{a.plan}:{a.label}", str(a.n_ops),
                     str(a.fusions) if a.fusions >= 0 else "-",
                     str(a.constant_bytes), str(a.parameter_bytes),
                     str(a.output_bytes), str(len(a.host_transfer_ops)),
                     str(len(a.dynamic_shape_ops)),
                     a.fingerprint.rsplit(":", 1)[-1][:16]))
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    lines = ["  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip()
             for r in rows]
    lines.append("")
    if findings:
        lines.extend(str(f) for f in findings)
        errors = sum(1 for f in findings if f.severity == "error")
        lines.append(f"{len(findings)} finding(s), {errors} error(s)")
    else:
        lines.append(f"clean: {len(audits)} program(s) audited, "
                     f"0 findings")
    if stats:
        lines.append(f"cache: {stats.get('hits', 0)} hit(s), "
                     f"{stats.get('misses', 0)} miss(es)")
    return "\n".join(lines)


def _format_json_doc(audits, findings, stats, model_dir) -> str:
    return json.dumps({
        "modelDir": model_dir,
        "audits": [a.to_json() for a in audits],
        "findings": [f.to_json() for f in findings],
        "summary": {
            "programs": len(audits),
            "findings": len(findings),
            "errors": sum(1 for f in findings
                          if f.severity == "error"),
        },
        "cache": dict(stats or {}),
    }, indent=1)


def run_audit(args) -> int:
    from ..utils.jax_setup import pin_platform_from_env
    pin_platform_from_env()
    try:
        from ..analysis.audit import audit_demo, audit_model, \
            plan_fingerprint
        from ..analysis.rules import audit_findings, occupancy_findings
        from ..observability.store import ProfileStore

        if args.fresh:
            import os
            os.environ.setdefault("TX_AUDIT_CACHE", "off")
        cache_path = args.cache
        if cache_path == "off":
            cache_path = ""
        compiled = not args.no_compile

        if args.demo:
            result = audit_demo(cache_path=cache_path,
                                compiled=compiled, fresh=args.fresh)
        elif args.model_dir:
            from ..workflow.persistence import load_model
            model = load_model(args.model_dir)
            if args.fingerprint:
                print(plan_fingerprint(model))
                return 0
            result = audit_model(model, model_dir=args.model_dir,
                                 compiled=compiled,
                                 cache_path=cache_path)
        else:
            print("tx-audit: give a MODEL_DIR or --demo",
                  file=sys.stderr)
            return 2

        if args.fingerprint:
            score = [a for a in result.audits if a.plan == "score"]
            if not score:
                print("tx-audit: plan has no device program",
                      file=sys.stderr)
                return 2
            print(min(score, key=lambda a: a.bucket).fingerprint)
            return 0

        # IR rules (TX-P01/P02) are pure functions of the audits —
        # cheap, so recomputed; the store-dependent occupancy rules
        # (TX-P03/P04) always run FRESH against the live record,
        # never through the audit cache
        store = ProfileStore(args.store)
        ceiling = args.waste_ceiling
        if ceiling is None:
            from ..tuning.policy import TuningPolicy
            ceiling = float(TuningPolicy(path=store.path)
                            .waste_ceiling().chosen)
        findings: List = list(result.findings)
        findings.extend(audit_findings(result.audits))
        findings.extend(occupancy_findings(
            result.audits, store=store,
            waste_ceiling=ceiling))

        if not args.no_persist:
            from ..analysis.audit import process_ir_features
            store.record_ir_features(process_ir_features())

        if args.format == "json":
            print(_format_json_doc(result.audits, findings,
                                   result.stats, result.model_dir))
        else:
            print(_format_table(result.audits, findings, result.stats))
        return 1 if findings else 0
    except BrokenPipeError:  # pragma: no cover
        raise
    except Exception as e:
        print(f"tx-audit: internal error: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2
