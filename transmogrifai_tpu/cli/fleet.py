"""``tx fleet`` — run a coordinated replica set behind the fleet
router (docs/fleet.md).

One command boots the whole topology: N supervised ``tx serve``
children (serving/fleet.py), each with its own state dir and
ephemeral port, plus the asyncio router front-end (serving/router.py)
on the public port. Clients speak the ordinary JSON-lines serving
protocol to the router and get lane placement, mid-stream failover,
warm takeover after a replica death, and fleet-coherent admission
for free::

    tx fleet --model fraud=/models/fraud --replicas 4 --port 8765

The serve-tuning flags (``--max-wait-ms``, ``--plan-cache``,
``--admission``, ``--artifacts`` ...) are forwarded verbatim to every
replica child.
"""
from __future__ import annotations

import asyncio
import json
import os

__all__ = ["add_fleet_parser", "run_fleet"]


def add_fleet_parser(sub) -> None:
    fl = sub.add_parser(
        "fleet",
        help="serve a replica set behind the fault-tolerant router")
    fl.add_argument("--model", action="append", required=True,
                    metavar="NAME=DIR",
                    help="model to serve on every replica "
                         "(repeatable)")
    fl.add_argument("--replicas", type=int, default=2,
                    help="number of serve child processes")
    fl.add_argument("--host", default="127.0.0.1")
    fl.add_argument("--port", type=int, default=8765,
                    help="router port (children bind ephemeral "
                         "ports; 0 = ephemeral router too)")
    fl.add_argument("--state-root", default=None, metavar="DIR",
                    help="root for per-replica state dirs "
                         "(default: .tx_fleet_state under the cwd)")
    fl.add_argument("--max-wait-ms", type=float, default=5.0,
                    help="per-replica batching window")
    fl.add_argument("--plan-cache", type=int, default=4,
                    help="per-replica plan-cache budget (also feeds "
                         "the router's placement pressure term)")
    fl.add_argument("--admission", choices=["on", "off"],
                    default="on",
                    help="per-replica admission control; the router "
                         "merges the per-replica states")
    fl.add_argument("--artifacts", choices=["auto", "require", "off"],
                    default="auto",
                    help="AOT artifact mode forwarded to replicas — "
                         "'require' keeps rolling deploys "
                         "compile-free by refusing artifact-less "
                         "boots")
    fl.add_argument("--snapshot-interval", type=float, default=10.0,
                    help="per-replica warm-state snapshot cadence "
                         "(seconds); the snapshot is what makes "
                         "takeover warm")
    fl.add_argument("--max-restarts", type=int, default=5,
                    help="per-replica crash-loop breaker threshold")
    fl.add_argument("--restart-window", type=float, default=60.0,
                    help="crash-loop breaker sliding window "
                         "(seconds)")
    fl.add_argument("--max-requests", type=int, default=None,
                    help="router exits after answering this many "
                         "(tests/bench)")
    fl.add_argument("--forward-timeout", type=float, default=30.0,
                    help="per-forward round-trip deadline before the "
                         "lane fails over")


def run_fleet(args) -> int:
    """Boot the replica set, wire its lifecycle callbacks into the
    router, and serve until SIGTERM/SIGINT."""
    from ..serving.fleet import ReplicaManager
    from ..serving.router import FleetRouter, RouterConfig
    from ..tuning.model import CostModel

    state_root = args.state_root or os.path.join(
        os.getcwd(), ".tx_fleet_state")
    serve_args = ["--max-wait-ms", str(args.max_wait_ms),
                  "--plan-cache", str(args.plan_cache),
                  "--admission", args.admission,
                  "--artifacts", args.artifacts,
                  "--snapshot-interval", str(args.snapshot_interval)]
    router = FleetRouter(
        config=RouterConfig(
            plan_budget=int(args.plan_cache),
            forward_timeout=float(args.forward_timeout)),
        cost_model=CostModel.from_store())
    first_model = args.model[0].split("=", 1)[0]
    router.default_model = first_model
    manager = ReplicaManager(
        models=args.model, replicas=args.replicas,
        state_root=state_root, host=args.host,
        serve_args=serve_args,
        max_restarts=args.max_restarts,
        restart_window=args.restart_window,
        on_up=router.register_replica_threadsafe,
        on_down=router.unregister_replica_threadsafe,
        on_draining=router.mark_draining_threadsafe)
    print(json.dumps({"fleet": "starting",
                      "replicas": args.replicas,
                      "state_root": state_root}), flush=True)
    try:
        # start() inside the try: a partial boot (some children
        # spawned, none became ready) must still reach shutdown()
        # below, or the spawned serve processes leak
        manager.start()
        # seed the registry synchronously so the router is ready the
        # moment its loop starts (on_up callbacks fired before the
        # loop existed fall through to direct registration)
        return asyncio.run(router.serve(
            args.host, args.port,
            max_requests=args.max_requests,
            banner_extra={"manager": manager.snapshot()}))
    finally:
        manager.shutdown()
        print(json.dumps({"fleet": "stopped",
                          **manager.snapshot()}), flush=True)
