"""``python -m transmogrifai_tpu.cli journal`` — inspect a search
checkpoint directory (docs/resilience.md).

The operator's view of a crashed run: which families/rungs the journal
already holds, the search fingerprint a resume must match, and the
fold-fit equivalents ``Workflow.train(resume_from=DIR)`` would skip::

    python -m transmogrifai_tpu.cli journal CHECKPOINT_DIR [--format json]
"""
from __future__ import annotations

import json

__all__ = ["add_journal_parser", "run_journal"]


def add_journal_parser(sub) -> None:
    j = sub.add_parser(
        "journal",
        help="inspect a search checkpoint (journal entries, "
             "fingerprint, resume savings)")
    j.add_argument("checkpoint_dir",
                   help="directory passed to ModelSelector("
                        "checkpoint_dir=...) / train(resume_from=...)")
    j.add_argument("--format", choices=["text", "json"], default="text",
                   help="output format (default: text)")


def run_journal(args) -> int:
    from ..runtime.journal import read_journal
    try:
        info = read_journal(args.checkpoint_dir)
    except (FileNotFoundError, ValueError) as e:
        print(f"tx-journal: {e}")
        return 2
    if args.format == "json":
        print(json.dumps(info, indent=1))
        return 0
    fp = info.get("fingerprint") or "?"
    print(f"search journal: {info['path']}")
    print(f"  schema v{info.get('version')}  fingerprint {fp[:16]}…")
    topo = info.get("recordedTopology")
    if topo:
        print(f"  recorded on {topo.get('devices')} device(s), mesh "
              f"{topo.get('mesh')} — resumes on ANY topology to the "
              f"bitwise-identical winner (docs/distributed.md)")
    print(f"  {len(info['entries'])} completed family evaluation(s) "
          f"across rungs {', '.join(info['rungs']) or '-'}")
    for e in sorted(info["entries"],
                    key=lambda e: (e["rung"], e["family"])):
        print(f"    {e['family']:<28} {e['rung']:<11} "
              f"{len(e['cands'])} cand(s) x {e['folds']} fold(s)")
    print(f"  resume would skip {info['resumeSavedFoldFits']} "
          f"candidate-fold fit(s): "
          f"Workflow.train(resume_from={args.checkpoint_dir!r})")
    return 0
