"""``python -m transmogrifai_tpu.cli mesh`` — inspect the search mesh.

The operator's view of the sharded search (docs/distributed.md): which
devices are visible, what mesh the selector would resolve under the
current ``TX_SEARCH_MESH`` policy, and the knobs that change it::

    python -m transmogrifai_tpu.cli mesh [--format json]

Initializes the JAX backend (it enumerates devices) — on a machine
whose ambient backend is a remote-TPU tunnel, pin ``JAX_PLATFORMS``
first if the tunnel may be down.
"""
from __future__ import annotations

import json
import os

__all__ = ["add_mesh_parser", "run_mesh"]


def add_mesh_parser(sub) -> None:
    m = sub.add_parser(
        "mesh",
        help="show visible devices and the search mesh the selector "
             "resolves under TX_SEARCH_MESH")
    m.add_argument("--format", choices=["text", "json"], default="text",
                   help="output format (default: text)")


def run_mesh(args) -> int:
    from ..utils.jax_setup import pin_platform_from_env
    pin_platform_from_env()
    import jax

    from ..parallel.cv import resolve_search_mesh
    devices = jax.devices()
    mesh = resolve_search_mesh("auto")
    info = {
        "platform": devices[0].platform,
        "visibleDevices": len(devices),
        "policy": os.environ.get("TX_SEARCH_MESH", "auto"),
        "dataShards": os.environ.get("TX_SEARCH_DATA_SHARDS", "1"),
        "searchMesh": (None if mesh is None else
                       {str(k): int(v) for k, v in mesh.shape.items()}),
    }
    if args.format == "json":
        print(json.dumps(info, indent=1))
        return 0
    print(f"platform: {info['platform']}  "
          f"visible devices: {info['visibleDevices']}")
    if mesh is None:
        print("search mesh: none (local single-device path) — "
              f"policy TX_SEARCH_MESH={info['policy']!r}")
    else:
        print(f"search mesh: {info['searchMesh']} — the fold x grid "
              f"candidate axis shards over 'models'")
    print("knobs: TX_SEARCH_MESH=auto|off|<n devices>, "
          "TX_SEARCH_DATA_SHARDS=<n> (docs/distributed.md)")
    return 0
