"""``python -m transmogrifai_tpu.cli score`` — batch scoring through the
compiled serving plan (docs/serving.md), plus a self-contained
``--bench`` smoke mode that prints one JSON line:

    {"metric": "score_rows_per_s", "value": ..., ...}

Scoring a saved model over a CSV/Avro file::

    python -m transmogrifai_tpu.cli score --model DIR --input data.csv \\
        --output scores.json [--engine compiled|columnar]

Benchmark (compiled plan vs the per-record ScoreFunction loop; trains a
tiny synthetic pipeline when --model/--input are not given)::

    python -m transmogrifai_tpu.cli score --bench [--rows N]
"""
from __future__ import annotations

import json
import time
from typing import List, Optional

__all__ = ["add_score_parser", "run_score"]


def add_score_parser(sub) -> None:
    sc = sub.add_parser(
        "score",
        help="score records through a saved model's compiled serving "
             "plan (--bench: compiled-vs-loop throughput smoke)")
    sc.add_argument("--model", default=None,
                    help="saved model directory (WorkflowModel.save)")
    sc.add_argument("--input", default=None,
                    help="CSV or Avro (.avro) records to score")
    sc.add_argument("--output", default=None,
                    help="write scores as JSON rows here "
                         "(default: stdout summary only)")
    sc.add_argument("--engine", choices=["compiled", "columnar"],
                    default="compiled",
                    help="scoring engine (default: compiled plan)")
    sc.add_argument("--bench", action="store_true",
                    help="measure compiled-plan vs per-record-loop "
                         "throughput and print one JSON metric line")
    sc.add_argument("--rows", type=int, default=2000,
                    help="benchmark batch size (--bench; default 2000)")
    sc.add_argument("--no-guardrails", action="store_true",
                    help="disable schema admission / output guards / "
                         "breaker (guardrails are ON for CLI scoring; "
                         "docs/serving_guardrails.md)")
    sc.add_argument("--no-sentinel", action="store_true",
                    help="disable the online drift sentinel (no drift "
                         "summary, never exit 2 on drift)")
    sc.add_argument("--drift-warn", type=float, default=None,
                    help="drift sentinel warn threshold (JS divergence)")
    sc.add_argument("--drift-degrade", type=float, default=None,
                    help="drift sentinel degrade threshold — crossing "
                         "it makes the command exit 2")


def _read_records(path: str) -> List[dict]:
    if path.endswith(".avro"):
        from ..readers import AvroProductReader
        return AvroProductReader(path).read_records()
    from ..readers import CSVAutoReader
    return CSVAutoReader(path).read_records()


def _tiny_pipeline(n_rows: int = 400):
    """Train a small synthetic pipeline covering the common feature
    families — the self-contained --bench workload."""
    import numpy as np

    from ..features.builder import FeatureBuilder
    from ..models import LogisticRegression
    from ..ops import transmogrify
    from ..testkit import RandomData, RandomReal, RandomText
    from ..types import PickList, Real, RealNN
    from ..workflow import Workflow

    records = (RandomData(seed=7)
               .with_column("x", RandomReal.normal(0, 1, seed=1))
               .with_column("y", RandomReal.uniform(0, 10, seed=2))
               .with_column("cat", RandomText.picklists(
                   ["a", "b", "c", "d"], seed=3))).records(n_rows)
    rng = np.random.default_rng(4)
    for r in records:
        r["label"] = float((r["x"] or 0) + 0.3 * rng.normal() > 0)
    x = FeatureBuilder.of("x", Real).extract(
        lambda r: r.get("x")).as_predictor()
    y = FeatureBuilder.of("y", Real).extract(
        lambda r: r.get("y")).as_predictor()
    cat = FeatureBuilder.of("cat", PickList).extract(
        lambda r: r.get("cat")).as_predictor()
    label = FeatureBuilder.of("label", RealNN).extract(
        lambda r: r.get("label")).as_response()
    pred = LogisticRegression(reg_param=0.01).set_input(
        label, transmogrify([x, y, cat])).get_output()
    model = (Workflow().set_result_features(pred)
             .set_input_records(records).train(validate="off"))
    return model, records


def _bench(model, records, rows: int) -> dict:
    from ..local import ScoreFunction
    from ..serving import plan_compiles

    batch = (records * (rows // max(len(records), 1) + 1))[:rows]
    fn = ScoreFunction(model)
    # warm: first compiled call pays plan compile + XLA trace
    t0 = time.perf_counter()
    fn.score_batch(batch[:min(16, rows)])
    warm_s = time.perf_counter() - t0
    compiles0 = plan_compiles()
    t0 = time.perf_counter()
    fn.score_batch(batch)
    compiled_s = time.perf_counter() - t0
    repeat0 = plan_compiles()
    fn.score_batch(batch)          # same bucket again: 0 new compiles
    repeat_compiles = plan_compiles() - repeat0
    loop_rows = min(rows, 200)
    t0 = time.perf_counter()
    fn.score_batch(batch[:loop_rows], engine="records")
    loop_s_per_row = (time.perf_counter() - t0) / loop_rows
    value = rows / max(compiled_s, 1e-9)
    loop_rps = 1.0 / max(loop_s_per_row, 1e-9)
    plan = fn._scoring_plan()
    return {
        "metric": "score_rows_per_s",
        "value": round(value, 1),
        "unit": "rows/s",
        "vs_baseline": round(value / loop_rps, 2),
        "loop_rows_per_s": round(loop_rps, 1),
        "speedup": round(value / loop_rps, 2),
        "batch_rows": rows,
        "warmup_seconds": round(warm_s, 3),
        "new_compiles": plan_compiles() - compiles0,
        "repeat_compiles": repeat_compiles,
        "coverage": plan.coverage.to_json() if plan else None,
    }


def run_score(args) -> int:
    from ..utils.jax_setup import pin_platform_from_env
    pin_platform_from_env()
    if args.bench:
        if args.model:
            from ..workflow import WorkflowModel
            model = WorkflowModel.load(args.model)
            records = _read_records(args.input) if args.input else None
            if not records:
                raise ValueError("--bench with --model needs --input")
        else:
            model, records = _tiny_pipeline()
        print(json.dumps(_bench(model, records, args.rows)))
        return 0
    if not args.model or not args.input:
        raise ValueError("score needs --model and --input (or --bench)")
    from ..workflow import WorkflowModel
    model = WorkflowModel.load(args.model)
    records = _read_records(args.input)
    guard_report = None
    drift = None
    t0 = time.perf_counter()
    if args.engine == "compiled" and not (args.no_guardrails
                                          and args.no_sentinel):
        # CLI scoring runs guarded by default: malformed rows are
        # quarantined with reasons instead of crashing the run, and
        # the drift sentinel compares the batch against training
        from ..serving import DriftThresholds
        thresholds = None
        if args.drift_warn is not None or args.drift_degrade is not None:
            d = DriftThresholds()
            thresholds = DriftThresholds(
                warn=args.drift_warn if args.drift_warn is not None
                else d.warn,
                degrade=args.drift_degrade
                if args.drift_degrade is not None else d.degrade)
        # artifact-first (artifacts/loader.py, TX-R06): `tx score` on
        # a saved model deserializes the exported bucket programs —
        # compile-free invocation; loud counted fallback otherwise
        from ..artifacts.loader import load_or_compile
        plan = load_or_compile(model, model_dir=args.model)
        if args.no_guardrails:
            # sentinel only: no admission/breaker, just drift watching
            from ..serving.sentinel import DriftSentinel
            plan.sentinel = DriftSentinel.for_model(
                model, thresholds=thresholds)
        else:
            plan.with_guardrails(sentinel=not args.no_sentinel,
                                 thresholds=thresholds)
        result = plan.score_guarded(records)
        scored, guard_report = result.scored, result
        if not args.no_sentinel:
            drift = plan.drift_report()
    else:
        scored = model.score(records, engine=args.engine)
    dt = time.perf_counter() - t0
    if args.output:
        from ..local.scoring import _unbox
        names = [f.name for f in model.result_features]
        bad_rows = set()
        guard_by_row = {}
        if guard_report is not None:
            for r in (guard_report.quarantined
                      + guard_report.invalidated):
                bad_rows.add(r.row)
                guard_by_row.setdefault(r.row, []).append(r.to_json())
        rows = []
        for i in range(scored.n_rows):
            if i in bad_rows:
                # guarded-out rows ship their reasons, not garbage
                rows.append({**{n: None for n in names},
                             "_guard": guard_by_row[i]})
            else:
                rows.append({n: _unbox(scored[n].boxed(i))
                             for n in names})
        with open(args.output, "w") as fh:
            json.dump(rows, fh)
    print(f"scored {scored.n_rows} rows in {dt:.3f}s "
          f"({scored.n_rows / max(dt, 1e-9):.0f} rows/s, "
          f"engine={args.engine})"
          + (f" -> {args.output}" if args.output else ""))
    if guard_report is not None:
        nq = len(guard_report.quarantined_rows)
        ni = len(guard_report.invalidated_rows)
        print(f"guardrails: {scored.n_rows - nq - ni} ok, "
              f"{nq} quarantined, {ni} invalidated"
              + (" (host fallback)" if guard_report.used_host_fallback
                 else ""))
        for r in (guard_report.quarantined
                  + guard_report.invalidated)[:10]:
            print(f"  row {r.row}: {r.code} [{r.feature}] {r.detail}")
    if drift is not None and drift.get("enabled"):
        worst = drift["features"][0] if drift["features"] else None
        print(f"drift sentinel: status={drift['status']} over "
              f"{drift['rowsSeen']} rows"
              + (f"; worst feature {worst['feature']} "
                 f"js={worst['jsDivergence']:.3f}" if worst else ""))
        if drift["status"] == "degrade":
            print("drift sentinel: DEGRADE threshold crossed — "
                  "scored traffic no longer matches training "
                  "(exit 2; --no-sentinel to ignore)")
            return 2
    return 0
