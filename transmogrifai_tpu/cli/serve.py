"""``python -m transmogrifai_tpu.cli serve`` — the async micro-batching
scoring server (docs/serving_loop.md) over a JSON-lines TCP front end.

Protocol: one JSON object per line on the socket; the server answers
one JSON line per request, in order::

    -> {"record": {"age": 31.0, ...}, "model": "titanic", "tenant": "a"}
    <- {"ok": true, "request_id": "req-1a2b-3", "result": {...}}
    <- {"ok": false, "request_id": "...", "error": "...",
        "kind": "transient"}

Every response echoes a ``request_id`` — generated at admission, or
the client's own ``"id"`` field when supplied — the same id that keys
the request's span tree when tracing is on (``TX_TRACE``,
docs/observability.md). A ``{"metrics": true}`` line is a CONTROL
request: it answers the live metrics snapshot instead of scoring, and
``--metrics-port`` serves the same JSON over HTTP (``GET /``) for
scrapers that should not touch the scoring socket.

``--auto-retrain`` (off by default) arms the self-healing lifecycle:
drift-triggered background retraining with canary validation, atomic
hot-swap between batches, and instant rollback on a post-swap breaker
trip or drift regression (docs/self_healing.md). ``--retrain-budget``,
``--canary-rows`` and ``--swap-policy`` tune it.

Start one process serving a model zoo::

    python -m transmogrifai_tpu.cli serve \\
        --model titanic=/models/titanic --model churn=/models/churn \\
        --port 8765 --max-wait-ms 5 --plan-cache 4

The hot path is the :class:`~transmogrifai_tpu.serving.ServingServer`
coalescing loop: deadline-or-full bucket batching, double-buffered
encode vs dispatch, per-tenant guardrails + breaker + sentinel, LRU
plan cache. ``--max-requests`` exits after N answered requests (CI
smoke); ``--port 0`` binds an ephemeral port (printed on stdout)."""
from __future__ import annotations

import asyncio
import json
import os
from typing import List, Optional

__all__ = ["add_serve_parser", "run_serve", "serve_forever"]


def add_serve_parser(sub) -> None:
    sv = sub.add_parser(
        "serve",
        help="async micro-batching scoring server (JSON lines over "
             "TCP; docs/serving_loop.md)")
    sv.add_argument("--model", action="append", required=True,
                    metavar="[NAME=]DIR",
                    help="saved model directory, optionally named "
                         "(repeatable; the first is the default model)")
    sv.add_argument("--host", default="127.0.0.1")
    sv.add_argument("--port", type=int, default=8765,
                    help="TCP port (0 = ephemeral, printed on stdout)")
    sv.add_argument("--max-wait-ms", type=float, default=5.0,
                    help="deadline half of deadline-or-full coalescing")
    sv.add_argument("--target-batch", type=int, default=None,
                    help="coalescer target batch (default: derived "
                         "from the plan's recorded bucket profile)")
    sv.add_argument("--max-batch", type=int, default=256,
                    help="hard cap on rows per dispatch")
    sv.add_argument("--plan-cache", type=int, default=4,
                    help="LRU budget of resident compiled plans")
    sv.add_argument("--deadline-seconds", type=float, default=None,
                    help="per-batch device dispatch deadline (a hung "
                         "dispatch is orphaned, the batch falls back "
                         "to the host path)")
    sv.add_argument("--no-guardrails", action="store_true",
                    help="disable per-tenant admission/output/breaker "
                         "guardrails (docs/serving_guardrails.md)")
    sv.add_argument("--no-sentinel", action="store_true",
                    help="disable the per-tenant drift sentinel")
    sv.add_argument("--max-requests", type=int, default=None,
                    help="exit after answering N requests (smoke/CI)")
    sv.add_argument("--auto-retrain", action="store_true",
                    help="enable the self-healing lifecycle: on a "
                         "tenant's drift sentinel reaching DEGRADE, "
                         "retrain in the background, canary-validate, "
                         "and atomically hot-swap the compiled plan "
                         "(docs/self_healing.md). OFF by default — "
                         "without it serving behavior is unchanged")
    sv.add_argument("--retrain-budget", type=float, default=120.0,
                    help="wall-clock seconds a background retrain may "
                         "take before it is abandoned (with "
                         "--auto-retrain)")
    sv.add_argument("--canary-rows", type=int, default=64,
                    help="retained ring of recent admitted requests "
                         "used to shadow-score candidates (with "
                         "--auto-retrain)")
    sv.add_argument("--swap-policy", choices=["tenant", "model"],
                    default="tenant",
                    help="hot-swap scope: 'tenant' replaces the plan "
                         "only for the drifted tenant (others keep the "
                         "original entry, bitwise unaffected); 'model' "
                         "replaces the shared cache entry")
    sv.add_argument("--metrics-port", type=int, default=None,
                    help="also serve the live metrics JSON over HTTP "
                         "on this port (GET /; 0 = ephemeral, printed "
                         "on stdout; docs/observability.md)")


def _parse_models(specs: List[str]) -> List[tuple]:
    out = []
    for spec in specs:
        if "=" in spec:
            name, path = spec.split("=", 1)
        else:
            path = spec
            name = os.path.basename(os.path.normpath(path)) or "model"
        out.append((name, path))
    return out


async def serve_forever(server, host: str, port: int,
                        max_requests: Optional[int] = None,
                        ready_cb=None,
                        metrics_port: Optional[int] = None,
                        metrics_ready_cb=None) -> int:
    """Run ``server``'s loop behind a JSON-lines TCP front end until
    cancelled (or ``max_requests`` answers). Importable so tests drive
    the exact CLI path in-process with in-memory models.
    ``metrics_port`` additionally serves the live
    ``server.metrics_snapshot()`` JSON over HTTP."""
    from ..runtime.errors import classify_error
    await server.start()
    answered = {"n": 0}
    done = asyncio.Event()

    async def handle(reader, writer):
        try:
            while True:
                line = await reader.readline()
                if not line:
                    break
                rid = None
                try:
                    msg = json.loads(line)
                    if isinstance(msg, dict) and msg.get("metrics"):
                        # control request: live metrics, no scoring,
                        # does not consume the --max-requests budget
                        out = {"ok": True,
                               "metrics": server.metrics_snapshot()}
                        writer.write((json.dumps(out, default=float)
                                      + "\n").encode())
                        await writer.drain()
                        continue
                    if isinstance(msg, dict) and "id" in msg:
                        rid = str(msg["id"])
                    rid, row = await server.score_with_id(
                        msg.get("record", msg), model=msg.get("model"),
                        tenant=msg.get("tenant", "default"), rid=rid)
                    out = {"ok": True, "request_id": rid, "result": row}
                except asyncio.CancelledError:
                    raise
                except Exception as e:
                    # a bad request/record answers with the classified
                    # error instead of dropping the connection
                    out = {"ok": False, "request_id": rid,
                           "error": f"{type(e).__name__}: {e}",
                           "kind": classify_error(e)}
                writer.write((json.dumps(out, default=float) + "\n")
                             .encode())
                await writer.drain()
                answered["n"] += 1
                if max_requests and answered["n"] >= max_requests:
                    done.set()
                    break
        finally:
            writer.close()

    async def handle_metrics(reader, writer):
        # minimal HTTP/1.1 responder: whatever the request line says,
        # answer the metrics snapshot (a scrape endpoint, not a router)
        try:
            await asyncio.wait_for(reader.readuntil(b"\r\n\r\n"), 5.0)
        except (asyncio.IncompleteReadError, asyncio.TimeoutError,
                asyncio.LimitOverrunError):
            pass
        body = json.dumps(server.metrics_snapshot(),
                          default=float).encode()
        writer.write(b"HTTP/1.1 200 OK\r\n"
                     b"Content-Type: application/json\r\n"
                     b"Content-Length: " + str(len(body)).encode() +
                     b"\r\nConnection: close\r\n\r\n" + body)
        await writer.drain()
        writer.close()

    tcp = await asyncio.start_server(handle, host, port)
    bound = tcp.sockets[0].getsockname()[1]
    http = None
    banner = {"serving": True, "host": host, "port": bound,
              "models": server.plans.names()}
    if metrics_port is not None:
        http = await asyncio.start_server(handle_metrics, host,
                                          metrics_port)
        banner["metrics_port"] = http.sockets[0].getsockname()[1]
        if metrics_ready_cb is not None:
            metrics_ready_cb(banner["metrics_port"])
    print(json.dumps(banner), flush=True)
    if ready_cb is not None:
        ready_cb(bound)
    try:
        if max_requests:
            await done.wait()
        else:
            await asyncio.Event().wait()       # until cancelled
    except asyncio.CancelledError:
        pass
    finally:
        tcp.close()
        await tcp.wait_closed()
        if http is not None:
            http.close()
            await http.wait_closed()
        await server.shutdown()
    print(json.dumps({"served": answered["n"],
                      **server.describe()}, default=float), flush=True)
    return 0


def run_serve(args) -> int:
    from ..observability import persist_process_profiles, trace
    from ..serving.server import ServeConfig, ServingServer
    from ..utils.jax_setup import pin_platform_from_env
    pin_platform_from_env()
    trace.configure_from_env()
    lifecycle = None
    if getattr(args, "auto_retrain", False):
        # the lifecycle is opt-in: without --auto-retrain the config
        # stays None and the loop behaves exactly as before
        from ..serving.lifecycle import LifecycleConfig
        lifecycle = LifecycleConfig(
            retrain_budget_seconds=args.retrain_budget,
            canary_rows=args.canary_rows,
            swap_policy=args.swap_policy)
    config = ServeConfig(
        max_wait_ms=args.max_wait_ms,
        target_batch=args.target_batch,
        max_batch=args.max_batch,
        plan_budget=args.plan_cache,
        deadline_seconds=args.deadline_seconds,
        guardrails=not args.no_guardrails,
        sentinel=not args.no_sentinel,
        lifecycle=lifecycle)
    server = ServingServer(config)
    for name, path in _parse_models(args.model):
        server.add_model(name, path)
    try:
        return asyncio.run(serve_forever(
            server, args.host, args.port,
            max_requests=args.max_requests,
            metrics_port=args.metrics_port))
    finally:
        trace.flush()
        if os.environ.get("TX_PROFILE_PERSIST") == "1":
            # fold this session's measured section/bucket costs into
            # the persisted profile store (docs/observability.md)
            persist_process_profiles()
