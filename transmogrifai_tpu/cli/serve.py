"""``python -m transmogrifai_tpu.cli serve`` — the async micro-batching
scoring server (docs/serving_loop.md) over a JSON-lines TCP front end.

Protocol: one JSON object per line on the socket; the server answers
one JSON line per request, in order::

    -> {"record": {"age": 31.0, ...}, "model": "titanic", "tenant": "a"}
    <- {"ok": true, "request_id": "req-1a2b-3", "result": {...}}
    <- {"ok": false, "request_id": "...", "error": "...",
        "kind": "transient"}
    <- {"ok": false, "request_id": "...", "shed": true,
        "retry_after_ms": 12, "error": "...", "kind": "transient"}

The ``"shed"`` answer is the overload admission controller refusing a
request at the door (docs/admission.md): bounded lane queues, a
cost-model deadline budget, per-tenant fair-queuing quotas and the
brownout state machine all shed through it, the hint derived from
predicted queue drain time. ``--admission=off`` removes the
controller entirely (the pre-admission enqueue edge, byte-for-byte);
``--tenant-weight``/``--tenant-deadline-ms`` shape it.

Every response echoes a ``request_id`` — generated at admission, or
the client's own ``"id"`` field when supplied — the same id that keys
the request's span tree when tracing is on (``TX_TRACE``,
docs/observability.md). A ``{"metrics": true}`` line is a CONTROL
request: it answers the live metrics snapshot instead of scoring, and
``--metrics-port`` serves the same JSON over HTTP (``GET /``) for
scrapers that should not touch the scoring socket.

``--auto-retrain`` (off by default) arms the self-healing lifecycle:
drift-triggered background retraining with canary validation, atomic
hot-swap between batches, and instant rollback on a post-swap breaker
trip or drift regression (docs/self_healing.md). ``--retrain-budget``,
``--canary-rows`` and ``--swap-policy`` tune it.

Start one process serving a model zoo::

    python -m transmogrifai_tpu.cli serve \\
        --model titanic=/models/titanic --model churn=/models/churn \\
        --port 8765 --max-wait-ms 5 --plan-cache 4

The hot path is the :class:`~transmogrifai_tpu.serving.ServingServer`
coalescing loop: deadline-or-full bucket batching, double-buffered
encode vs dispatch, per-tenant guardrails + breaker + sentinel, LRU
plan cache. ``--max-requests`` exits after N answered requests (CI
smoke); ``--port 0`` binds an ephemeral port (printed on stdout).

Preemption tolerance (docs/serving_restart.md): SIGTERM/SIGINT flips
the loop to DRAINING — new requests get a machine-readable
``{"ok": false, "draining": true}`` answer (the reconnecting client
retries against the next incarnation), queued + in-flight requests
finish under ``--drain-timeout``, traces/metrics/profiles flush, the
warm-state snapshot is written, and the process exits 0.
``--resume-state DIR`` restores that snapshot on boot — recompiling +
prewarming exactly the recorded buckets BEHIND the readiness gate
(``{"ready": true}`` control request + the metrics ``process`` block)
before the port binds. ``--supervise`` runs a parent that restarts a
crashed loop under ``RetryPolicy`` backoff with a crash-loop breaker,
handing the snapshot dir to each incarnation."""
from __future__ import annotations

import asyncio
import json
import os
import sys
from typing import List, Optional

__all__ = ["add_serve_parser", "run_serve", "run_supervised",
           "serve_forever"]


def add_serve_parser(sub) -> None:
    sv = sub.add_parser(
        "serve",
        help="async micro-batching scoring server (JSON lines over "
             "TCP; docs/serving_loop.md)")
    sv.add_argument("--model", action="append", required=True,
                    metavar="[NAME=]DIR",
                    help="saved model directory, optionally named "
                         "(repeatable; the first is the default model)")
    sv.add_argument("--host", default="127.0.0.1")
    sv.add_argument("--port", type=int, default=8765,
                    help="TCP port (0 = ephemeral, printed on stdout)")
    sv.add_argument("--max-wait-ms", type=float, default=5.0,
                    help="deadline half of deadline-or-full coalescing")
    sv.add_argument("--target-batch", type=int, default=None,
                    help="coalescer target batch (default: derived "
                         "from the plan's recorded bucket profile)")
    sv.add_argument("--max-batch", type=int, default=256,
                    help="hard cap on rows per dispatch")
    sv.add_argument("--plan-cache", type=int, default=4,
                    help="LRU budget of resident compiled plans")
    sv.add_argument("--deadline-seconds", type=float, default=None,
                    help="per-batch device dispatch deadline (a hung "
                         "dispatch is orphaned, the batch falls back "
                         "to the host path)")
    sv.add_argument("--no-guardrails", action="store_true",
                    help="disable per-tenant admission/output/breaker "
                         "guardrails (docs/serving_guardrails.md)")
    sv.add_argument("--no-sentinel", action="store_true",
                    help="disable the per-tenant drift sentinel")
    sv.add_argument("--admission", choices=["on", "off"], default="on",
                    help="overload admission control "
                         "(docs/admission.md): bounded lane queues "
                         "with retry_after_ms shed answers, cost-model "
                         "deadline admission, per-tenant DRR fair "
                         "queuing, brownout load shedding. "
                         "--admission=off restores the pre-admission "
                         "enqueue edge byte-for-byte")
    sv.add_argument("--tenant-weight", action="append", default=None,
                    metavar="NAME=W",
                    help="fair-queuing weight / quota share for a "
                         "tenant (repeatable; unlisted tenants weigh "
                         "1.0; brownout sheds lower-weight tenants "
                         "first)")
    sv.add_argument("--tenant-deadline-ms", action="append",
                    default=None, metavar="[NAME=]MS",
                    help="per-request completion budget: a request "
                         "whose predicted completion (queue wait + "
                         "encode + dispatch) exceeds it is shed at "
                         "the door (repeatable; a bare MS applies to "
                         "every tenant)")
    sv.add_argument("--max-requests", type=int, default=None,
                    help="exit after answering N requests (smoke/CI)")
    sv.add_argument("--auto-retrain", action="store_true",
                    help="enable the self-healing lifecycle: on a "
                         "tenant's drift sentinel reaching DEGRADE, "
                         "retrain in the background, canary-validate, "
                         "and atomically hot-swap the compiled plan "
                         "(docs/self_healing.md). OFF by default — "
                         "without it serving behavior is unchanged")
    sv.add_argument("--retrain-budget", type=float, default=120.0,
                    help="wall-clock seconds a background retrain may "
                         "take before it is abandoned (with "
                         "--auto-retrain)")
    sv.add_argument("--canary-rows", type=int, default=64,
                    help="retained ring of recent admitted requests "
                         "used to shadow-score candidates (with "
                         "--auto-retrain)")
    sv.add_argument("--swap-policy", choices=["tenant", "model"],
                    default="tenant",
                    help="hot-swap scope: 'tenant' replaces the plan "
                         "only for the drifted tenant (others keep the "
                         "original entry, bitwise unaffected); 'model' "
                         "replaces the shared cache entry")
    sv.add_argument("--metrics-port", type=int, default=None,
                    help="also serve the live metrics JSON over HTTP "
                         "on this port (GET /; 0 = ephemeral, printed "
                         "on stdout; docs/observability.md)")
    sv.add_argument("--drain-timeout", type=float, default=30.0,
                    help="seconds a SIGTERM/SIGINT drain waits for "
                         "queued + in-flight requests before shutdown "
                         "(docs/serving_restart.md)")
    sv.add_argument("--artifacts", choices=["auto", "require", "off"],
                    default=None,
                    help="AOT artifact loading (docs/aot_artifacts.md):"
                         " auto loads each saved model's exported "
                         "executables (zero serve-process compiles) "
                         "with loud fallback to live compile; require "
                         "refuses to boot a model without valid "
                         "artifacts (fleet replicas); off always "
                         "live-compiles (default: TX_AOT_ARTIFACTS "
                         "env, else auto)")
    sv.add_argument("--state-dir", default=None, metavar="DIR",
                    help="write the warm-state snapshot here "
                         "(periodically, at lifecycle commits, and on "
                         "shutdown); defaults to --resume-state's DIR")
    sv.add_argument("--resume-state", default=None, metavar="DIR",
                    help="restore the warm-state snapshot from DIR on "
                         "boot: recompile + prewarm the recorded "
                         "buckets behind the readiness gate, restore "
                         "sentinels/breakers/lifecycle. A torn or "
                         "mismatched snapshot cold-starts loudly")
    sv.add_argument("--snapshot-interval", type=float, default=30.0,
                    help="seconds between periodic snapshot writes "
                         "(with --state-dir/--resume-state; 0 = only "
                         "at lifecycle commits and shutdown)")
    sv.add_argument("--supervise", action="store_true",
                    help="run a supervisor parent that restarts the "
                         "serving child on crash with RetryPolicy "
                         "backoff and a crash-loop breaker")
    sv.add_argument("--max-restarts", type=int, default=5,
                    help="crash-loop breaker: give up after this many "
                         "crashes inside --restart-window seconds")
    sv.add_argument("--restart-window", type=float, default=60.0,
                    help="sliding window (seconds) the crash-loop "
                         "breaker counts crashes over")


def _parse_models(specs: List[str]) -> List[tuple]:
    out = []
    for spec in specs:
        if "=" in spec:
            name, path = spec.split("=", 1)
        else:
            path = spec
            name = os.path.basename(os.path.normpath(path)) or "model"
        out.append((name, path))
    return out


async def serve_forever(server, host: str, port: int,
                        max_requests: Optional[int] = None,
                        ready_cb=None,
                        metrics_port: Optional[int] = None,
                        metrics_ready_cb=None,
                        drain_timeout: float = 30.0,
                        state_manager=None,
                        snapshot_interval: Optional[float] = None,
                        banner_extra: Optional[dict] = None) -> int:
    """Run ``server``'s loop behind a JSON-lines TCP front end until
    cancelled (or ``max_requests`` answers, or a SIGTERM/SIGINT
    drain). Importable so tests drive the exact CLI path in-process
    with in-memory models. ``metrics_port`` additionally serves the
    live ``server.metrics_snapshot()`` JSON over HTTP;
    ``state_manager`` (serving/state.StateManager) arms snapshot
    writes — every ``snapshot_interval`` seconds and at shutdown."""
    from ..runtime.errors import classify_error
    from ..serving.server import ServeDraining, ServeShed
    await server.start()
    answered = {"n": 0}
    done = asyncio.Event()
    stop = asyncio.Event()

    def _draining_answer(rid):
        return {"ok": False, "request_id": rid, "draining": True,
                "error": "ServeDraining: serving loop is draining "
                         "for shutdown; retry against the next "
                         "incarnation",
                "kind": "transient"}

    async def handle(reader, writer):
        try:
            while True:
                line = await reader.readline()
                if not line:
                    break
                if server.draining:
                    # refuse the connection with the machine-readable
                    # answer (the reconnecting client backs off and
                    # resends to the next incarnation), then close it
                    writer.write((json.dumps(_draining_answer(None))
                                  + "\n").encode())
                    await writer.drain()
                    break
                rid = None
                try:
                    msg = json.loads(line)
                    if isinstance(msg, dict) and msg.get("metrics"):
                        # control request: live metrics, no scoring,
                        # does not consume the --max-requests budget
                        out = {"ok": True,
                               "metrics": server.metrics_snapshot()}
                        writer.write((json.dumps(out, default=float)
                                      + "\n").encode())
                        await writer.drain()
                        continue
                    if isinstance(msg, dict) and msg.get("ready"):
                        # readiness-gate control request
                        # (docs/serving_restart.md)
                        out = {"ok": True, "ready": bool(server.ready),
                               "draining": server.draining,
                               "generation": server.restart_generation}
                        writer.write((json.dumps(out) + "\n").encode())
                        await writer.drain()
                        continue
                    if isinstance(msg, dict) and "id" in msg:
                        rid = str(msg["id"])
                    rid, row = await server.score_with_id(
                        msg.get("record", msg), model=msg.get("model"),
                        tenant=msg.get("tenant", "default"), rid=rid)
                    out = {"ok": True, "request_id": rid, "result": row}
                except asyncio.CancelledError:
                    raise
                except ServeDraining:
                    writer.write((json.dumps(_draining_answer(rid))
                                  + "\n").encode())
                    await writer.drain()
                    break
                except ServeShed as e:
                    # overload shed (docs/admission.md): unlike
                    # draining, the server is healthy and the
                    # connection STAYS OPEN — the client honors the
                    # retry hint and resends on the same socket
                    out = {"ok": False, "request_id": rid,
                           "shed": True,
                           "retry_after_ms": e.retry_after_ms,
                           "error": f"{type(e).__name__}: {e}",
                           "kind": classify_error(e)}
                except Exception as e:
                    # a bad request/record answers with the classified
                    # error instead of dropping the connection
                    out = {"ok": False, "request_id": rid,
                           "error": f"{type(e).__name__}: {e}",
                           "kind": classify_error(e)}
                writer.write((json.dumps(out, default=float) + "\n")
                             .encode())
                await writer.drain()
                answered["n"] += 1
                if max_requests and answered["n"] >= max_requests:
                    done.set()
                    break
        finally:
            writer.close()

    async def handle_metrics(reader, writer):
        # minimal HTTP/1.1 responder: whatever the request line says,
        # answer the metrics snapshot (a scrape endpoint, not a router)
        try:
            await asyncio.wait_for(reader.readuntil(b"\r\n\r\n"), 5.0)
        except (asyncio.IncompleteReadError, asyncio.TimeoutError,
                asyncio.LimitOverrunError):
            pass
        body = json.dumps(server.metrics_snapshot(),
                          default=float).encode()
        writer.write(b"HTTP/1.1 200 OK\r\n"
                     b"Content-Type: application/json\r\n"
                     b"Content-Length: " + str(len(body)).encode() +
                     b"\r\nConnection: close\r\n\r\n" + body)
        await writer.drain()
        writer.close()

    tcp = await asyncio.start_server(handle, host, port)
    bound = tcp.sockets[0].getsockname()[1]
    http = None
    banner = {"serving": True, "host": host, "port": bound,
              "models": server.plans.names()}
    if banner_extra:
        banner.update(banner_extra)
    if metrics_port is not None:
        http = await asyncio.start_server(handle_metrics, host,
                                          metrics_port)
        banner["metrics_port"] = http.sockets[0].getsockname()[1]
        if metrics_ready_cb is not None:
            metrics_ready_cb(banner["metrics_port"])
    print(json.dumps(banner), flush=True)
    if ready_cb is not None:
        ready_cb(bound)

    # graceful drain on SIGTERM/SIGINT (docs/serving_restart.md) —
    # only installable on a main-thread loop; in-process test loops
    # (background threads) skip the handlers and use cancellation
    loop = asyncio.get_running_loop()
    sig_installed = []
    try:
        import signal as _signal
        for sig in (_signal.SIGTERM, _signal.SIGINT):
            loop.add_signal_handler(sig, stop.set)
            sig_installed.append(sig)
    except (ValueError, OSError, RuntimeError, NotImplementedError):
        pass

    snap_task = None
    if state_manager is not None and snapshot_interval:
        async def _periodic_snapshots():
            while True:
                await asyncio.sleep(snapshot_interval)
                await loop.run_in_executor(None, state_manager.write)
        snap_task = asyncio.create_task(_periodic_snapshots())

    cancelled = False
    waiters = [asyncio.ensure_future(stop.wait())]
    if max_requests:
        waiters.append(asyncio.ensure_future(done.wait()))
    try:
        await asyncio.wait(waiters,
                           return_when=asyncio.FIRST_COMPLETED)
    except asyncio.CancelledError:
        cancelled = True
    finally:
        for w in waiters:
            w.cancel()
    drain_summary = None
    if not cancelled and stop.is_set():
        # queued + in-flight requests finish (new ones get the
        # draining answer) before anything is torn down
        drain_summary = await server.drain(drain_timeout)
    try:
        if state_manager is not None and not cancelled:
            # final snapshot AFTER the drain: sketches, breakers and
            # counters include every answered request
            await loop.run_in_executor(
                None, lambda: state_manager.write(reason="shutdown"))
    finally:
        for sig in sig_installed:
            try:
                loop.remove_signal_handler(sig)
            except (ValueError, RuntimeError):  # pragma: no cover
                pass
        if snap_task is not None:
            snap_task.cancel()
        tcp.close()
        await tcp.wait_closed()
        if http is not None:
            http.close()
            await http.wait_closed()
        await server.shutdown()
    final = {"served": answered["n"], **server.describe()}
    if drain_summary is not None:
        final["drain"] = drain_summary
    print(json.dumps(final, default=float), flush=True)
    return 0


def run_serve(args) -> int:
    if getattr(args, "supervise", False):
        return run_supervised(args)
    from ..observability import persist_process_profiles, trace
    from ..serving.server import ServeConfig, ServingServer
    from ..utils.jax_setup import pin_platform_from_env
    pin_platform_from_env()
    trace.configure_from_env()
    lifecycle = None
    if getattr(args, "auto_retrain", False):
        # the lifecycle is opt-in: without --auto-retrain the config
        # stays None and the loop behaves exactly as before
        from ..serving.lifecycle import LifecycleConfig
        lifecycle = LifecycleConfig(
            retrain_budget_seconds=args.retrain_budget,
            canary_rows=args.canary_rows,
            swap_policy=args.swap_policy)
    admission_control = None
    if getattr(args, "admission", "on") != "off":
        # overload admission (docs/admission.md); --admission=off
        # leaves this None -> the enqueue edge is byte-identical to
        # a build without the controller
        from ..serving.admission import AdmissionConfig
        weights = {}
        for spec in (getattr(args, "tenant_weight", None) or []):
            name, _, w = spec.partition("=")
            weights[name] = float(w or 1.0)
        deadline = None
        d = {}
        for spec in (getattr(args, "tenant_deadline_ms", None) or []):
            name, sep, ms = spec.partition("=")
            if sep:
                d[name] = float(ms)
            else:
                d["default"] = float(name)
        if d:
            deadline = (d["default"] if set(d) == {"default"}
                        else d)
        admission_control = AdmissionConfig(
            tenant_weights=weights, tenant_deadline_ms=deadline)
    config = ServeConfig(
        max_wait_ms=args.max_wait_ms,
        target_batch=args.target_batch,
        max_batch=args.max_batch,
        plan_budget=args.plan_cache,
        deadline_seconds=args.deadline_seconds,
        guardrails=not args.no_guardrails,
        sentinel=not args.no_sentinel,
        lifecycle=lifecycle,
        admission_control=admission_control)
    if getattr(args, "artifacts", None):
        # the flag wins over the env; set BEFORE any plan resolves so
        # PlanCache.get / prewarm / state restore all see one mode
        os.environ["TX_AOT_ARTIFACTS"] = args.artifacts
    server = ServingServer(config)
    for name, path in _parse_models(args.model):
        server.add_model(name, path)
    # warm-restart wiring (docs/serving_restart.md). Both flags off =
    # no StateManager, no snapshot task: behavior identical to before
    resume_dir = getattr(args, "resume_state", None)
    write_dir = getattr(args, "state_dir", None) or resume_dir
    banner_extra = {}
    if resume_dir:
        from ..serving.state import StateManager
        server.ready = False
        summary = StateManager(server, resume_dir).restore()
        server.ready = True
        print(json.dumps({"resume": summary}, default=float),
              flush=True)
        banner_extra["resume"] = summary.get("mode", "cold")
    state_manager = None
    if write_dir:
        from ..serving.state import StateManager
        state_manager = StateManager(server, write_dir)
        banner_extra["generation"] = server.restart_generation
    # autotuned prewarm (docs/autotuning.md): compile the buckets the
    # cost model says this zoo will hit BEFORE the port binds, so the
    # first live batches skip their compile stall. Cold store or
    # TX_TUNE=off -> empty set -> no-op, boot time unchanged.
    from ..artifacts.store import load_mode
    if load_mode() == "require":
        # fleet-replica contract: resolve every registered model NOW —
        # a model without valid artifacts refuses to boot instead of
        # silently compiling in-band
        from ..artifacts.loader import ArtifactsRequired
        try:
            for name in server.plans.names():
                server.plans.get(name, server.plan_buckets,
                                 server.plan_lattice)
        except ArtifactsRequired as e:
            print(f"tx-serve: {e}", file=sys.stderr)
            return 2
    warmed = server.prewarm()
    if warmed:
        banner_extra["prewarmed"] = warmed
    # which resident models serve from deserialized AOT executables
    # (the boot-visible zero-compile signal, docs/aot_artifacts.md)
    aot_models = sorted(
        key[0] for key, entry in server.plans.resident_entries()
        if getattr(entry.plan, "aot_active", lambda: False)())
    if aot_models:
        banner_extra["artifacts"] = aot_models
    if server._target_decision.tuned() or any(
            d.tuned() for d in server._bucket_decisions):
        banner_extra["tuned"] = {
            "target_batch": server._target_decision.chosen,
            "buckets": [d.chosen for d in server._bucket_decisions]}
    if admission_control is not None:
        banner_extra["admission"] = "on"
    try:
        return asyncio.run(serve_forever(
            server, args.host, args.port,
            max_requests=args.max_requests,
            metrics_port=args.metrics_port,
            drain_timeout=getattr(args, "drain_timeout", 30.0),
            state_manager=state_manager,
            snapshot_interval=getattr(args, "snapshot_interval", None),
            banner_extra=banner_extra))
    finally:
        # the finally (not the happy path) flushes: a SIGTERM drain,
        # a crash, and a clean --max-requests exit all persist the
        # session's traces and measured costs
        trace.flush()
        if os.environ.get("TX_PROFILE_PERSIST") == "1":
            # fold this session's measured section/bucket costs into
            # the persisted profile store (docs/observability.md)
            persist_process_profiles()


def run_supervised(args) -> int:
    """``tx serve --supervise``: a parent that keeps one serving child
    alive across crashes. Child exit 0 (graceful drain, --max-requests)
    ends supervision; a crash restarts the child under
    ``RetryPolicy`` backoff, with ``TX_SERVE_GENERATION`` bumped per
    incarnation (the metrics ``process.restart_generation``) and the
    same argv — so ``--resume-state`` hands the snapshot to each new
    child. A crash-loop breaker gives up after ``--max-restarts``
    crashes inside ``--restart-window`` seconds (exit 1)."""
    import collections
    import signal
    import subprocess
    import sys
    import time as _time
    from ..runtime.retry import RetryPolicy
    cmd = [sys.executable, "-m", "transmogrifai_tpu.cli"] + \
        [a for a in sys.argv[1:] if a != "--supervise"]
    policy = RetryPolicy.from_env()
    window = max(float(getattr(args, "restart_window", 60.0)), 0.001)
    max_restarts = max(int(getattr(args, "max_restarts", 5)), 1)
    crashes = collections.deque()
    state = {"child": None, "stopping": False}

    def _forward(signum, _frame):
        state["stopping"] = True
        child = state["child"]
        if child is not None and child.poll() is None:
            child.send_signal(signum)

    prev = {}
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            prev[sig] = signal.signal(sig, _forward)
        except ValueError:  # pragma: no cover - non-main thread
            pass
    generation = 0
    try:
        while True:
            generation += 1
            env = dict(os.environ,
                       TX_SERVE_GENERATION=str(generation))
            child = subprocess.Popen(cmd, env=env)
            state["child"] = child
            print(json.dumps({"supervisor": "spawned",
                              "generation": generation,
                              "pid": child.pid}), flush=True)
            try:
                rc = child.wait()
            except KeyboardInterrupt:  # pragma: no cover
                state["stopping"] = True
                rc = child.wait()
            if state["stopping"] or rc == 0:
                print(json.dumps({"supervisor": "exit", "code": rc,
                                  "generation": generation}),
                      flush=True)
                return 0 if rc == 0 else rc
            now = _time.monotonic()
            crashes.append(now)
            while crashes and now - crashes[0] > window:
                crashes.popleft()
            print(json.dumps({"supervisor": "crashed", "code": rc,
                              "generation": generation,
                              "crashes_in_window": len(crashes)}),
                  flush=True)
            if len(crashes) >= max_restarts:
                # crash-loop breaker: restarting is making it worse
                print(json.dumps({"supervisor": "crash_loop_breaker",
                                  "crashes": len(crashes),
                                  "window_seconds": window}),
                      flush=True)
                return 1
            delay = policy.delay_for(len(crashes),
                                     f"serve-restart:{generation}")
            _time.sleep(delay)
    finally:
        for sig, handler in prev.items():
            signal.signal(sig, handler)
