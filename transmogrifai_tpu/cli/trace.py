"""``python -m transmogrifai_tpu.cli trace`` — summarize and convert a
span trace (docs/observability.md).

Reads the schema-versioned JSONL a traced run wrote
(``TX_TRACE=/path/trace.jsonl``) and answers the questions the raw
file cannot: where did the time go (top spans by SELF time — own wall
minus child spans), how much of it was XLA compile vs execute (the
sections' recorded split), and what one request actually did (its
critical path: the span tree with durations and the child-coverage
fraction). ``--perfetto`` converts to Chrome ``trace_event`` JSON that
loads straight into ui.perfetto.dev / chrome://tracing.

::

    tx trace /tmp/serve.jsonl                    # summary
    tx trace /tmp/serve.jsonl --request req-...  # one request's path
    tx trace /tmp/serve.jsonl --perfetto out.json
    tx trace /tmp/serve.jsonl --format json
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional

__all__ = ["add_trace_parser", "run_trace", "summarize_trace",
           "critical_path"]


def add_trace_parser(sub) -> None:
    tr = sub.add_parser(
        "trace",
        help="summarize / convert a span trace JSONL "
             "(docs/observability.md)")
    tr.add_argument("file", help="trace JSONL written under TX_TRACE")
    tr.add_argument("--format", choices=["text", "json"],
                    default="text")
    tr.add_argument("--top", type=int, default=10,
                    help="rows in the top-self-time table")
    tr.add_argument("--request", default=None, metavar="TRACE_ID",
                    help="render one trace's span tree + critical "
                         "path (a request id, or any trace id from "
                         "the summary)")
    tr.add_argument("--perfetto", default=None, metavar="OUT_JSON",
                    help="write Chrome/Perfetto trace_event JSON")


def _self_times(records: List[dict]) -> Dict[int, float]:
    """Span id -> self time (own duration minus direct children)."""
    child_sum: Dict[int, float] = {}
    for r in records:
        p = r.get("parent")
        if p is not None:
            child_sum[p] = child_sum.get(p, 0.0) + (r.get("dur") or 0.0)
    return {r["sid"]: max((r.get("dur") or 0.0)
                          - child_sum.get(r["sid"], 0.0), 0.0)
            for r in records}


def summarize_trace(records: List[dict], top: int = 10) -> dict:
    """The ``tx trace`` summary document: span/trace counts, top span
    NAMES by total self time, compile share (section-recorded compile
    seconds vs total root wall), and the request traces present."""
    selfs = _self_times(records)
    by_name: Dict[str, dict] = {}
    for r in records:
        rec = by_name.setdefault(
            r.get("name", "?"),
            {"count": 0, "total_seconds": 0.0, "self_seconds": 0.0})
        rec["count"] += 1
        rec["total_seconds"] += r.get("dur") or 0.0
        rec["self_seconds"] += selfs.get(r["sid"], 0.0)
    roots = [r for r in records if r.get("parent") is None]
    root_wall = sum(r.get("dur") or 0.0 for r in roots)
    compile_s = sum((r.get("attrs") or {}).get("compile_seconds", 0.0)
                    for r in records)
    requests = sorted({r["trace"] for r in records
                       if r.get("name") == "serve.request"})
    events = sum(len(r.get("events") or ()) for r in records)
    return {
        "spans": len(records),
        "traces": len({r.get("trace") for r in records}),
        "root_spans": len(roots),
        "root_wall_seconds": round(root_wall, 6),
        "compile_seconds": round(compile_s, 6),
        "compile_share": round(compile_s / root_wall, 4)
        if root_wall > 0 else 0.0,
        "span_events": events,
        "requests": requests[:200],
        "request_count": len(requests),
        "top_self_time": sorted(
            ({"name": k,
              "count": v["count"],
              "total_seconds": round(v["total_seconds"], 6),
              "self_seconds": round(v["self_seconds"], 6)}
             for k, v in by_name.items()),
            key=lambda r: -r["self_seconds"])[:top],
    }


def critical_path(records: List[dict], trace_id: str) -> dict:
    """One trace rendered as its critical path: the span tree in start
    order with durations, per-span share of the root wall, and the
    root's direct-child coverage (the >=95% acceptance metric). The
    ``path`` list is the chain root -> heaviest child -> ... — the
    sequence that bounds the trace's latency."""
    from ..observability.trace import coverage, span_tree
    roots = span_tree(records, trace_id)
    if not roots:
        raise ValueError(f"no spans for trace {trace_id!r}")
    root = roots[0]
    total = root["span"].get("dur") or 0.0

    def node_row(node, depth):
        s = node["span"]
        return {"depth": depth, "name": s.get("name", "?"),
                "seconds": round(s.get("dur") or 0.0, 6),
                "share": round((s.get("dur") or 0.0) / total, 4)
                if total > 0 else 0.0,
                "attrs": s.get("attrs") or {},
                "events": [e.get("name") for e in
                           (s.get("events") or ())]}

    tree_rows: List[dict] = []

    def walk(node, depth):
        tree_rows.append(node_row(node, depth))
        for c in node["children"]:
            walk(c, depth + 1)

    walk(root, 0)
    path, node = [], root
    while True:
        path.append(node["span"].get("name", "?"))
        if not node["children"]:
            break
        node = max(node["children"],
                   key=lambda c: c["span"].get("dur") or 0.0)
    return {"trace": trace_id,
            "wall_seconds": round(total, 6),
            "coverage": round(coverage(records, trace_id), 4),
            "path": path,
            "tree": tree_rows}


def _print_text(summary: dict, request: Optional[dict]) -> None:
    print(f"{summary['spans']} span(s) in {summary['traces']} "
          f"trace(s); {summary['request_count']} serve request(s); "
          f"{summary['span_events']} span event(s)")
    print(f"root wall {summary['root_wall_seconds']:.4f}s, compile "
          f"{summary['compile_seconds']:.4f}s "
          f"({summary['compile_share']:.1%} of root wall)")
    print("\ntop spans by self time:")
    print(f"  {'name':<32} {'calls':>6} {'self s':>10} {'total s':>10}")
    for row in summary["top_self_time"]:
        print(f"  {row['name']:<32} {row['count']:>6} "
              f"{row['self_seconds']:>10.4f} "
              f"{row['total_seconds']:>10.4f}")
    if request is not None:
        print(f"\nrequest {request['trace']}: "
              f"{request['wall_seconds'] * 1000:.3f}ms wall, child "
              f"coverage {request['coverage']:.1%}")
        print("critical path: " + " -> ".join(request["path"]))
        for row in request["tree"]:
            pad = "  " * row["depth"]
            evs = (f"  events={','.join(row['events'])}"
                   if row["events"] else "")
            print(f"  {pad}{row['name']:<{max(30 - 2 * row['depth'], 8)}}"
                  f" {row['seconds'] * 1000:>9.3f}ms "
                  f"{row['share']:>6.1%}{evs}")


def run_trace(args) -> int:
    from ..observability.trace import read_trace, to_perfetto
    try:
        meta, records = read_trace(args.file)
    except (OSError, ValueError) as e:
        print(f"error: {e}")
        return 2
    if not records:
        print(f"{args.file}: no spans")
        return 1
    summary = summarize_trace(records, top=args.top)
    request = None
    if args.request is not None:
        try:
            request = critical_path(records, args.request)
        except ValueError as e:
            print(f"error: {e}")
            return 2
    if args.perfetto:
        with open(args.perfetto, "w", encoding="utf-8") as fh:
            json.dump(to_perfetto(meta, records), fh)
        summary["perfetto"] = args.perfetto
    if args.format == "json":
        out = {"meta": meta, "summary": summary}
        if request is not None:
            out["request"] = request
        print(json.dumps(out, indent=1, default=str))
    else:
        _print_text(summary, request)
        if args.perfetto:
            print(f"\nperfetto trace written to {args.perfetto} "
                  f"(load at ui.perfetto.dev)")
    return 0
