"""``tx tune``: inspect and override the autotuning decisions.

Renders every :class:`~..tuning.policy.TuningDecision` the
:class:`~..tuning.policy.TuningPolicy` would hand the serving, search
and prepare layers right now — chosen value, static default,
predicted cost both ways, confidence, source — and manages the
persisted override block (``tuning.overrides`` in the profile store)
the policy honors across processes::

    python -m transmogrifai_tpu.cli tune                   # table
    python -m transmogrifai_tpu.cli tune --explain         # + reasons
    python -m transmogrifai_tpu.cli tune --format json
    python -m transmogrifai_tpu.cli tune --set serving.target_batch=32
    python -m transmogrifai_tpu.cli tune --reset serving.target_batch
    python -m transmogrifai_tpu.cli tune --reset           # all knobs
"""
from __future__ import annotations

import argparse
import json
from typing import List, Optional

__all__ = ["add_tune_parser", "run_tune"]


def add_tune_parser(sub) -> None:
    p = sub.add_parser(
        "tune",
        help="inspect/override telemetry-driven autotuning decisions")
    p.add_argument("--explain", action="store_true",
                   help="show each decision's prediction and reasoning")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--set", dest="assignments", action="append",
                   default=[], metavar="KNOB=VALUE",
                   help="persist an override the policy honors "
                        "(repeatable; value parses as JSON, e.g. "
                        "serving.prewarm=[8,64])")
    p.add_argument("--reset", nargs="?", const="*", default=None,
                   metavar="KNOB",
                   help="drop one persisted override (or all, with no "
                        "argument)")
    p.add_argument("--store", default=None,
                   help="profile-store path (default: TX_PROFILE_STORE "
                        "or the repo BENCH_STATE.json)")
    p.add_argument("--max-wait-ms", type=float, default=None,
                   help="serving wait budget the target-batch decision "
                        "assumes (default: ServeConfig default)")
    p.add_argument("--max-batch", type=int, default=None,
                   help="serving dispatch cap the decisions assume "
                        "(default: ServeConfig default)")


def _parse_assignment(text: str):
    if "=" not in text:
        raise ValueError(
            f"--set expects KNOB=VALUE, got {text!r}")
    knob, raw = text.split("=", 1)
    knob = knob.strip()
    try:
        value = json.loads(raw)
    except ValueError:
        value = raw.strip()
    return knob, value


def _fmt(v) -> str:
    if isinstance(v, float):
        return f"{v:.6g}"
    if isinstance(v, (tuple, list)):
        return "[" + ",".join(str(x) for x in v) + "]" if v else "[]"
    return str(v)


def _render_text(decisions: List, explain: bool,
                 overrides: dict) -> List[str]:
    rows = [("knob", "chosen", "default", "confidence", "source")]
    for d in decisions:
        rows.append((d.knob, _fmt(d.chosen), _fmt(d.default),
                     d.confidence, d.source))
    widths = [max(len(r[i]) for r in rows) for i in range(5)]
    lines = []
    for i, r in enumerate(rows):
        lines.append("  ".join(c.ljust(w) for c, w in zip(r, widths))
                     .rstrip())
        if i == 0:
            lines.append("  ".join("-" * w for w in widths))
            continue
        if explain:
            d = decisions[i - 1]
            pc = ("?" if d.predicted_chosen is None
                  else f"{d.predicted_chosen:.4f}s")
            pd = ("?" if d.predicted_default is None
                  else f"{d.predicted_default:.4f}s")
            lines.append(f"    predicted: chosen {pc} vs default {pd}")
            lines.append(f"    why: {d.reason}")
    if overrides:
        lines.append("")
        lines.append(f"persisted overrides: "
                     f"{json.dumps(overrides, sort_keys=True)}")
    return lines


def _lattice_report(policy, decisions) -> Optional[dict]:
    """Per-rung predicted-vs-recorded cost of the chosen bucket
    lattice (docs/ragged_batching.md): which tier answered each rung
    and both execute costs, so ``--explain`` shows WHY the lattice
    beat (or kept) the power-of-two ladder."""
    d = next((x for x in decisions
              if x.knob == "serving.bucket_lattice"), None)
    if d is None:
        return None
    recorded = (policy.model.recorded_buckets("score")
                if policy.enabled else {})
    rungs = []
    for b in (d.chosen or ()):
        est = policy.model.predict("score", bucket=int(b))
        rec = recorded.get(int(b))
        rungs.append({
            "bucket": int(b),
            "predicted_execute_s": (round(est.execute, 6)
                                    if est.execute is not None
                                    else None),
            "recorded_execute_s": (round(rec.execute, 6)
                                   if rec is not None
                                   and rec.execute is not None
                                   else None),
            "confidence": est.confidence,
        })
    return {"chosen": [int(b) for b in (d.chosen or ())],
            "default": [int(b) for b in (d.default or ())],
            "tuned": d.tuned(), "rungs": rungs}


def _render_lattice(report: dict) -> List[str]:
    lines = ["", "bucket lattice (per rung):",
             "  bucket  predicted    recorded     tier"]
    for r in report["rungs"]:
        pred = ("?" if r["predicted_execute_s"] is None
                else f"{r['predicted_execute_s']:.6f}s")
        rec = ("-" if r["recorded_execute_s"] is None
               else f"{r['recorded_execute_s']:.6f}s")
        lines.append(f"  {r['bucket']:>6}  {pred:<11}  {rec:<11}  "
                     f"{r['confidence']}")
    return lines


def run_tune(args: argparse.Namespace) -> int:
    from ..observability.store import ProfileStore
    from ..serving.server import ServeConfig
    from ..tuning.policy import TuningPolicy
    from ..tuning.registry import STATIC_DEFAULTS

    store = ProfileStore(args.store)
    rc = 0
    mutated = False
    for text in args.assignments:
        try:
            knob, value = _parse_assignment(text)
            if knob not in STATIC_DEFAULTS:
                raise ValueError(
                    f"unknown tunable knob {knob!r}; registered: "
                    f"{sorted(STATIC_DEFAULTS)}")
        except ValueError as e:
            print(f"error: {e}")
            return 2
        store.set_tuning_override(knob, value)
        print(f"set {knob} = {value!r} (store {store.path})")
        mutated = True
    if args.reset is not None:
        if args.reset == "*":
            store.clear_tuning_overrides()
            print(f"cleared all overrides (store {store.path})")
        else:
            if args.reset not in STATIC_DEFAULTS:
                print(f"error: unknown tunable knob {args.reset!r}; "
                      f"registered: {sorted(STATIC_DEFAULTS)}")
                return 2
            store.clear_tuning_overrides(args.reset)
            print(f"reset {args.reset} (store {store.path})")
        mutated = True

    cfg = ServeConfig()
    max_wait = (cfg.max_wait_ms if args.max_wait_ms is None
                else args.max_wait_ms)
    max_batch = cfg.max_batch if args.max_batch is None \
        else args.max_batch
    policy = TuningPolicy(path=store.path)
    decisions = policy.decisions(max_wait_ms=max_wait,
                                 max_batch=max_batch)
    if args.format == "json":
        doc = {
            "store": store.path,
            "enabled": policy.enabled,
            "overrides": policy.overrides,
            "decisions": [d.to_json() for d in decisions],
        }
        lattice = _lattice_report(policy, decisions)
        if lattice is not None:
            doc["lattice"] = lattice
        print(json.dumps(doc, indent=1, sort_keys=True))
        return rc
    if mutated:
        print("")
    if not policy.enabled:
        print("autotuning DISABLED (TX_TUNE=off) — every decision is "
              "the static default")
    for line in _render_text(decisions, args.explain, policy.overrides):
        print(line)
    if args.explain:
        lattice = _lattice_report(policy, decisions)
        if lattice is not None:
            for line in _render_lattice(lattice):
                print(line)
    return rc


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="transmogrifai_tpu.cli.tune",
        description="inspect/override autotuning decisions")
    sub = parser.add_subparsers(dest="command", required=True)
    add_tune_parser(sub)
    return run_tune(parser.parse_args(argv))


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
