"""Evaluators: model-quality metrics for every problem type.

Reference: core/src/main/scala/com/salesforce/op/evaluators/
(Evaluators.scala:40 factory, OpBinaryClassificationEvaluator.scala:56,
OpMultiClassificationEvaluator.scala:58, OpRegressionEvaluator.scala:50,
OpBinScoreEvaluator.scala:52).
"""
from .base import EvaluationMetrics, Evaluator, MultiMetrics, SingleMetric
from .binary import (BinaryClassificationEvaluator,
                     BinaryClassificationMetrics, BinScoreEvaluator,
                     BinScoreMetrics, au_pr, au_roc, binary_metrics,
                     pr_curve, roc_curve)
from .multiclass import (MultiClassificationEvaluator,
                         MultiClassificationMetrics, ThresholdMetrics,
                         multiclass_metrics)
from .logloss import LogLossEvaluator, LogLossMetrics, log_loss
from .regression import (RegressionEvaluator, RegressionMetrics,
                         regression_metrics)

__all__ = [
    "EvaluationMetrics", "Evaluator", "SingleMetric", "MultiMetrics",
    "BinaryClassificationEvaluator", "BinaryClassificationMetrics",
    "BinScoreEvaluator", "BinScoreMetrics", "binary_metrics", "au_pr",
    "au_roc", "roc_curve", "pr_curve",
    "MultiClassificationEvaluator", "MultiClassificationMetrics",
    "LogLossEvaluator", "LogLossMetrics", "log_loss",
    "ThresholdMetrics", "multiclass_metrics",
    "RegressionEvaluator", "RegressionMetrics", "regression_metrics",
    "Evaluators",
]


class Evaluators:
    """Factory namespace (reference Evaluators.scala:40):
    ``Evaluators.BinaryClassification.au_pr()`` etc."""

    class BinaryClassification:
        @staticmethod
        def au_pr(**kw) -> BinaryClassificationEvaluator:
            return BinaryClassificationEvaluator(default_metric="AuPR", **kw)

        @staticmethod
        def au_roc(**kw) -> BinaryClassificationEvaluator:
            return BinaryClassificationEvaluator(default_metric="AuROC", **kw)

        @staticmethod
        def precision(**kw) -> BinaryClassificationEvaluator:
            return BinaryClassificationEvaluator(
                default_metric="Precision", **kw)

        @staticmethod
        def recall(**kw) -> BinaryClassificationEvaluator:
            return BinaryClassificationEvaluator(default_metric="Recall", **kw)

        @staticmethod
        def f1(**kw) -> BinaryClassificationEvaluator:
            return BinaryClassificationEvaluator(default_metric="F1", **kw)

        @staticmethod
        def error(**kw) -> BinaryClassificationEvaluator:
            return BinaryClassificationEvaluator(default_metric="Error", **kw)

        @staticmethod
        def log_loss(**kw) -> LogLossEvaluator:
            return LogLossEvaluator(**kw)

    class MultiClassification:
        @staticmethod
        def f1(**kw) -> MultiClassificationEvaluator:
            return MultiClassificationEvaluator(default_metric="F1", **kw)

        @staticmethod
        def precision(**kw) -> MultiClassificationEvaluator:
            return MultiClassificationEvaluator(
                default_metric="Precision", **kw)

        @staticmethod
        def recall(**kw) -> MultiClassificationEvaluator:
            return MultiClassificationEvaluator(default_metric="Recall", **kw)

        @staticmethod
        def log_loss(**kw) -> LogLossEvaluator:
            return LogLossEvaluator(**kw)

        @staticmethod
        def error(**kw) -> MultiClassificationEvaluator:
            return MultiClassificationEvaluator(default_metric="Error", **kw)

    class Regression:
        @staticmethod
        def rmse(**kw) -> RegressionEvaluator:
            return RegressionEvaluator(
                default_metric="RootMeanSquaredError", **kw)

        @staticmethod
        def mse(**kw) -> RegressionEvaluator:
            return RegressionEvaluator(default_metric="MeanSquaredError", **kw)

        @staticmethod
        def mae(**kw) -> RegressionEvaluator:
            return RegressionEvaluator(
                default_metric="MeanAbsoluteError", **kw)

        @staticmethod
        def r2(**kw) -> RegressionEvaluator:
            return RegressionEvaluator(default_metric="R2", **kw)
