"""Evaluator bases and metric containers.

TPU-native port of the reference evaluator kernel
(core/src/main/scala/com/salesforce/op/evaluators/OpEvaluatorBase.scala:113,
EvaluationMetrics.scala). Evaluators consume dense label / prediction
arrays (the columnar ``PredictionColumn``) instead of Spark DataFrames;
every metric container serializes to a flat JSON dict for
``ModelSelectorSummary`` and saved metrics files.
"""
from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Any, Dict, Optional

import numpy as np

from ..features.columns import Dataset, PredictionColumn

__all__ = ["EvaluationMetrics", "Evaluator", "SingleMetric", "MultiMetrics"]


@dataclass
class EvaluationMetrics:
    """Base metric record (reference EvaluationMetrics.scala)."""

    def to_json(self) -> Dict[str, Any]:
        def conv(v):
            if isinstance(v, np.ndarray):
                return v.tolist()
            if isinstance(v, (np.floating, np.integer)):
                return v.item()
            if isinstance(v, EvaluationMetrics):
                return v.to_json()
            if isinstance(v, dict):
                return {k: conv(x) for k, x in v.items()}
            if isinstance(v, (list, tuple)):
                return [conv(x) for x in v]
            return v
        return {f.name: conv(getattr(self, f.name))
                for f in dataclasses.fields(self)}

    def to_map(self) -> Dict[str, Any]:
        return self.to_json()


def metrics_from_json(class_name: str, d: Dict[str, Any]
                      ) -> "Optional[EvaluationMetrics]":
    """Rebuild a metrics dataclass from ``to_json`` output by class
    name (model save/load of ModelSelectorSummary). Nested metric
    dataclass FIELDS (e.g. MultiClassificationMetrics.ThresholdMetrics)
    rebuild recursively from their annotations; a class that is not
    importable here comes back as a :class:`RawMetrics` holder carrying
    the full payload + original name (never None, nothing dropped);
    heterogeneous MultiMetrics dicts stay plain dicts (their leaf
    classes aren't recorded — consumers read leaf floats)."""
    def walk(cls):
        for sub in cls.__subclasses__():
            yield sub
            yield from walk(sub)

    def field_cls(f) -> "Optional[type]":
        t = f.type
        if isinstance(t, str):       # from __future__ annotations
            t = t.replace("Optional[", "").rstrip("]")
            return next((s for s in walk(EvaluationMetrics)
                         if s.__name__ == t), None)
        if isinstance(t, type):
            return t if issubclass(t, EvaluationMetrics) else None
        import typing
        for a in typing.get_args(t):     # Optional[X] and friends
            if isinstance(a, type) and issubclass(a, EvaluationMetrics):
                return a
        return None

    for sub in walk(EvaluationMetrics):
        if sub is RawMetrics:
            # never self-match: a recorded "RawMetrics" name would
            # rebuild as an EMPTY holder (payload keys aren't its
            # fields) — route it to the fallback below, which keeps
            # the full payload instead
            continue
        if sub.__name__ == class_name and dataclasses.is_dataclass(sub):
            kwargs = {}
            for f in dataclasses.fields(sub):
                if f.name not in d:
                    continue
                v = d[f.name]
                nested = field_cls(f)
                if nested is not None and isinstance(v, dict):
                    v = metrics_from_json(nested.__name__, v)
                kwargs[f.name] = v
            hook = getattr(sub, "_decode_json_kwargs", None)
            if hook is not None:
                kwargs = hook(kwargs)
            return sub(**kwargs)
    # class not importable here: hold the payload (and the original
    # name) rather than dropping it — re-save keeps everything
    return RawMetrics(class_name=class_name, data=dict(d))


@dataclass
class RawMetrics(EvaluationMetrics):
    """Fallback holder for a persisted metrics payload whose class is
    not importable at load time (e.g. a user's custom Evaluator metrics
    module absent from the loading process). Keeps the full dict — and
    the ORIGINAL class name, which the summary re-records on save — so
    nothing is lost across load/re-save cycles and a later load with
    the class available rebuilds the real type."""
    class_name: str = ""
    data: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def to_json(self) -> Dict[str, Any]:
        return dict(self.data)


@dataclass
class SingleMetric(EvaluationMetrics):
    """One named metric value (reference SingleMetric)."""
    name: str
    value: float


@dataclass
class MultiMetrics(EvaluationMetrics):
    """Named collection of metric records (reference MultiMetrics)."""
    metrics: Dict[str, EvaluationMetrics]


class Evaluator:
    """Base evaluator (reference OpEvaluatorBase.scala:113).

    ``evaluate_arrays`` is the kernel: label vector + prediction column in,
    metrics record out. ``evaluate`` / ``evaluate_all`` adapt a Dataset by
    column name.
    """

    #: name of the default metric returned by ``evaluate``
    default_metric: str = ""
    is_larger_better: bool = True

    def __init__(self, label_col: Optional[str] = None,
                 prediction_col: Optional[str] = None):
        self.label_col = label_col
        self.prediction_col = prediction_col

    # -- kernel ------------------------------------------------------------
    def evaluate_arrays(self, y: np.ndarray, pred: PredictionColumn
                        ) -> EvaluationMetrics:
        raise NotImplementedError

    # -- dataset adapters --------------------------------------------------
    def _extract(self, ds: Dataset):
        y = np.asarray(ds[self.label_col].data, dtype=np.float64)
        col = ds[self.prediction_col]
        if not isinstance(col, PredictionColumn):
            # object column of Prediction dicts (slow edge path); key
            # parsing is owned by Prediction (types/maps.py)
            from ..types import Prediction
            boxed = [Prediction(d) for d in col.data]
            pred = np.asarray([p.prediction for p in boxed])
            n_prob = max((len(p.probability) for p in boxed), default=0)
            n_raw = max((len(p.raw_prediction) for p in boxed), default=0)
            prob = np.zeros((len(pred), n_prob))
            raw = np.zeros((len(pred), n_raw))
            for i, p in enumerate(boxed):
                prob[i, :len(p.probability)] = p.probability
                raw[i, :len(p.raw_prediction)] = p.raw_prediction
            col = PredictionColumn.from_arrays(pred, probability=prob,
                                               raw_prediction=raw)
        return y, col

    def evaluate_all(self, ds: Dataset) -> EvaluationMetrics:
        y, pred = self._extract(ds)
        return self.evaluate_arrays(y, pred)

    def evaluate(self, ds: Dataset) -> float:
        metrics = self.evaluate_all(ds)
        return float(getattr(metrics, self.default_metric))

    def metric_from(self, metrics: EvaluationMetrics) -> float:
        return float(getattr(metrics, self.default_metric))

    def device_metric_spec(self):
        """(kind, metric_name) consumed by the on-device fold x grid
        metric kernels (evaluators/device_metrics.py), or None when the
        default metric can't be computed on device (custom evaluators,
        metrics outside the supported sets) — the validator then keeps
        the host per-candidate evaluation path."""
        return None

    def _device_spec(self, base_cls, supported, kind):
        """Shared device_metric_spec body for the library evaluators:
        subclasses that customize evaluation/metric extraction must keep
        the host path (the device kernels can't see overrides), and the
        default metric must be in the kernel-supported set."""
        cls = type(self)
        if (cls.metric_from is not Evaluator.metric_from
                or cls.evaluate_arrays is not base_cls.evaluate_arrays):
            return None
        if self.default_metric in supported:
            return (kind, self.default_metric)
        return None

    def set_columns(self, label_col: str, prediction_col: str) -> "Evaluator":
        self.label_col = label_col
        self.prediction_col = prediction_col
        return self

    @property
    def name(self) -> str:
        return type(self).__name__
