"""Binary classification metrics.

TPU-native port of the reference
(core/src/main/scala/com/salesforce/op/evaluators/
OpBinaryClassificationEvaluator.scala:56,179 and OpBinScoreEvaluator.scala:52).
Curve metrics follow Spark's ``BinaryClassificationMetrics`` semantics:
thresholds are the distinct scores, AuROC is the trapezoidal area over the
ROC curve with (0,0)/(1,1) endpoints, AuPR prepends (recall=0,
precision=first-point precision). Point metrics (precision/recall/F1/error)
are computed from the hard predicted labels.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple

import numpy as np

from ..features.columns import PredictionColumn
from .base import EvaluationMetrics, Evaluator

__all__ = ["BinaryClassificationMetrics", "BinaryClassificationEvaluator",
           "BinScoreMetrics", "BinScoreEvaluator", "binary_metrics",
           "roc_curve", "pr_curve", "au_roc", "au_pr",
           "positive_class_score"]


def positive_class_score(pred: PredictionColumn) -> Optional[np.ndarray]:
    """Positive-class ranking score from a prediction column: column 1 of a
    2+-class probability matrix, a single-column probability vector as-is,
    then the same over raw predictions (margins)."""
    for arr in (pred.probability, pred.raw_prediction):
        if arr.shape[1] >= 2:
            return arr[:, 1]
        if arr.shape[1] == 1:
            return arr[:, 0]
    return None


def _curve_points(y: np.ndarray, score: np.ndarray
                  ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, float, float]:
    """Cumulative TP/FP at each distinct score threshold (descending).

    Returns (thresholds, tp, fp, n_pos, n_neg) where tp[i]/fp[i] are counts
    predicted positive at threshold = thresholds[i] (the i-th distinct
    score). All curve arrays are empty for empty input.
    """
    if len(score) == 0:
        z = np.zeros(0, dtype=np.float64)
        return z, z, z, 0.0, 0.0
    order = np.argsort(-score, kind="stable")
    y_sorted = y[order]
    s_sorted = score[order]
    tp_cum = np.cumsum(y_sorted == 1)
    fp_cum = np.cumsum(y_sorted != 1)
    # last index of each distinct-score run
    last = np.r_[np.nonzero(np.diff(s_sorted))[0], len(s_sorted) - 1]
    return (s_sorted[last], tp_cum[last].astype(np.float64),
            fp_cum[last].astype(np.float64),
            float(np.sum(y == 1)), float(np.sum(y != 1)))


def roc_curve(y: np.ndarray, score: np.ndarray
              ) -> Tuple[np.ndarray, np.ndarray]:
    """(fpr, tpr) points including the (0,0) and (1,1) endpoints."""
    _, tp, fp, n_pos, n_neg = _curve_points(y, score)
    tpr = tp / max(n_pos, 1.0)
    fpr = fp / max(n_neg, 1.0)
    return (np.r_[0.0, fpr, 1.0], np.r_[0.0, tpr, 1.0])


def pr_curve(y: np.ndarray, score: np.ndarray
             ) -> Tuple[np.ndarray, np.ndarray]:
    """(recall, precision) points, prepending (0, first precision) as Spark
    BinaryClassificationMetrics.pr does."""
    _, tp, fp, n_pos, _ = _curve_points(y, score)
    recall = tp / max(n_pos, 1.0)
    precision = tp / np.maximum(tp + fp, 1.0)
    first_p = precision[0] if precision.size else 1.0
    return (np.r_[0.0, recall], np.r_[first_p, precision])


def _trapezoid(x: np.ndarray, ys: np.ndarray) -> float:
    return float(np.sum(np.diff(x) * (ys[1:] + ys[:-1]) / 2.0))


def au_roc(y: np.ndarray, score: np.ndarray) -> float:
    return _trapezoid(*roc_curve(y, score))


def au_pr(y: np.ndarray, score: np.ndarray) -> float:
    return _trapezoid(*pr_curve(y, score))


@dataclass
class BinaryClassificationMetrics(EvaluationMetrics):
    """Reference OpBinaryClassificationEvaluator metrics (``:56``)."""
    Precision: float = 0.0
    Recall: float = 0.0
    F1: float = 0.0
    AuROC: float = 0.0
    AuPR: float = 0.0
    Error: float = 0.0
    TP: float = 0.0
    TN: float = 0.0
    FP: float = 0.0
    FN: float = 0.0
    thresholds: List[float] = field(default_factory=list)
    precision_by_threshold: List[float] = field(default_factory=list)
    recall_by_threshold: List[float] = field(default_factory=list)
    false_positive_rate_by_threshold: List[float] = field(default_factory=list)


def binary_metrics(y: np.ndarray, pred_label: np.ndarray,
                   score: Optional[np.ndarray] = None,
                   record_curves: bool = False
                   ) -> BinaryClassificationMetrics:
    y = np.asarray(y, dtype=np.float64)
    pred_label = np.asarray(pred_label, dtype=np.float64)
    tp = float(np.sum((pred_label == 1) & (y == 1)))
    tn = float(np.sum((pred_label != 1) & (y != 1)))
    fp = float(np.sum((pred_label == 1) & (y != 1)))
    fn = float(np.sum((pred_label != 1) & (y == 1)))
    n = max(len(y), 1)
    precision = tp / (tp + fp) if tp + fp > 0 else 0.0
    recall = tp / (tp + fn) if tp + fn > 0 else 0.0
    f1 = (2 * precision * recall / (precision + recall)
          if precision + recall > 0 else 0.0)
    m = BinaryClassificationMetrics(
        Precision=precision, Recall=recall, F1=f1,
        Error=(fp + fn) / n, TP=tp, TN=tn, FP=fp, FN=fn)
    if score is not None and len(np.unique(y)) > 1:
        # one sort serves ROC, PR and the threshold curves
        thr, tp_c, fp_c, n_pos, n_neg = _curve_points(y, score)
        tpr = tp_c / max(n_pos, 1.0)
        fpr = fp_c / max(n_neg, 1.0)
        prec = tp_c / np.maximum(tp_c + fp_c, 1.0)
        first_p = prec[0] if prec.size else 1.0
        m.AuROC = _trapezoid(np.r_[0.0, fpr, 1.0], np.r_[0.0, tpr, 1.0])
        m.AuPR = _trapezoid(np.r_[0.0, tpr], np.r_[first_p, prec])
        if record_curves:
            m.thresholds = thr.tolist()
            m.precision_by_threshold = prec.tolist()
            m.recall_by_threshold = tpr.tolist()
            m.false_positive_rate_by_threshold = fpr.tolist()
    return m


class BinaryClassificationEvaluator(Evaluator):
    """Reference OpBinaryClassificationEvaluator.scala:56."""

    default_metric = "AuPR"
    is_larger_better = True

    def __init__(self, label_col: Optional[str] = None,
                 prediction_col: Optional[str] = None,
                 default_metric: str = "AuPR",
                 record_curves: bool = False):
        super().__init__(label_col, prediction_col)
        self.default_metric = default_metric
        self.is_larger_better = default_metric != "Error"
        self.record_curves = record_curves

    def evaluate_arrays(self, y: np.ndarray, pred: PredictionColumn
                        ) -> BinaryClassificationMetrics:
        score = positive_class_score(pred)
        return binary_metrics(y, pred.data, score,
                              record_curves=self.record_curves)

    def device_metric_spec(self):
        from .device_metrics import BINARY_METRICS
        return self._device_spec(BinaryClassificationEvaluator,
                                 BINARY_METRICS, "binary")


@dataclass
class BinScoreMetrics(EvaluationMetrics):
    """Calibration-bin metrics (reference OpBinScoreEvaluator.scala:52)."""
    BinCenters: List[float] = field(default_factory=list)
    NumberOfDataPoints: List[int] = field(default_factory=list)
    AverageScore: List[float] = field(default_factory=list)
    AverageConversionRate: List[float] = field(default_factory=list)
    BrierScore: float = 0.0


class BinScoreEvaluator(Evaluator):
    """Score-calibration evaluator (reference OpBinScoreEvaluator.scala:142):
    bins scores uniformly on [0, 1], reports per-bin average score vs label
    conversion rate plus the overall Brier score."""

    default_metric = "BrierScore"
    is_larger_better = False

    def __init__(self, num_bins: int = 100, label_col: Optional[str] = None,
                 prediction_col: Optional[str] = None):
        super().__init__(label_col, prediction_col)
        if num_bins <= 0:
            raise ValueError("num_bins must be positive")
        self.num_bins = num_bins

    def evaluate_arrays(self, y: np.ndarray, pred: PredictionColumn
                        ) -> BinScoreMetrics:
        score = positive_class_score(pred)
        if score is None:
            score = pred.data
        score = np.clip(np.asarray(score, dtype=np.float64), 0.0, 1.0)
        bins = np.minimum((score * self.num_bins).astype(int),
                          self.num_bins - 1)
        counts = np.bincount(bins, minlength=self.num_bins)
        sum_score = np.bincount(bins, weights=score, minlength=self.num_bins)
        sum_label = np.bincount(bins, weights=y, minlength=self.num_bins)
        nz = counts > 0
        centers = (np.arange(self.num_bins) + 0.5) / self.num_bins
        with np.errstate(invalid="ignore"):
            avg_score = np.where(nz, sum_score / np.maximum(counts, 1), 0.0)
            avg_conv = np.where(nz, sum_label / np.maximum(counts, 1), 0.0)
        return BinScoreMetrics(
            BinCenters=centers.tolist(),
            NumberOfDataPoints=counts.tolist(),
            AverageScore=avg_score.tolist(),
            AverageConversionRate=avg_conv.tolist(),
            BrierScore=float(np.mean((score - y) ** 2)) if len(y) else 0.0)
