"""On-device (XLA) validation metrics for the selector search.

The reference's CV grid loop evaluates every candidate on the driver
with a per-model ``evaluator.evaluate`` pass
(core/src/main/scala/com/salesforce/op/tuning/OpValidator.scala:295).
A literal port of that shape made the remote-TPU search *slower* than a
single CPU: every candidate's fitted parameters and predictions crossed
the host<->device tunnel. These kernels instead compute the metric IN
the same XLA program that fitted and predicted the candidates, so a
whole fold x grid search transfers one (folds, grid) float matrix per
family and nothing else.

Semantics match the host evaluators exactly (tie-aware Spark
``BinaryClassificationMetrics`` curves — see ``evaluators/binary.py``
``_curve_points`` — and label-frequency-weighted multiclass PRF):
the tie-grouped curve is reproduced with static shapes by REPLACING
every position's cumulative counts with the counts at its score-run's
end (computed by a reversed ``cummin`` over end-of-run indices); the
duplicated curve points then contribute zero-width trapezoids, which is
arithmetically the host's distinct-point curve plus exact zeros.

Everything here is pure ``jnp`` on traced values — safe to call inside
``jit`` / ``vmap`` / ``shard_map`` from the family fold x grid kernels.
"""
from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

__all__ = ["BINARY_METRICS", "MULTICLASS_METRICS", "REGRESSION_METRICS",
           "binary_metric", "multiclass_metric", "regression_metric",
           "metric_fn", "softmax_probability", "binary_from_raw_pair",
           "binary_from_sigmoid", "binary_from_votes"]

BINARY_METRICS = ("AuPR", "AuROC", "Precision", "Recall", "F1", "Error")
MULTICLASS_METRICS = ("F1", "Precision", "Recall", "Error")
REGRESSION_METRICS = ("RootMeanSquaredError", "MeanSquaredError", "R2",
                      "MeanAbsoluteError")


# ---------------------------------------------------------------------------
# host-twin score transforms
#
# The host evaluators rank by the model's POSITIVE-CLASS PROBABILITY
# (evaluators/binary.positive_class_score), not by raw margins. That
# distinction matters: sigmoid/softmax saturate in float, collapsing
# distinct margins into tied scores, and the tie-grouped Spark curve then
# differs from the margin curve. Each transform below reproduces its host
# model's raw->probability arithmetic operation for operation so the
# device metric sees bit-identical scores (same dtype caveats as the
# fit itself). Each returns (score, plabel): the ranking score and the
# 0/1 hard label (host = argmax of the probability vector).
# ---------------------------------------------------------------------------

def softmax_probability(raw):
    """(n, K) max-shifted softmax — ClassifierModel.raw_to_probability
    twin (models/base.py)."""
    shifted = raw - jnp.max(raw, axis=1, keepdims=True)
    e = jnp.exp(shifted)
    return e / jnp.sum(e, axis=1, keepdims=True)


def binary_from_raw_pair(raw):
    """(score, plabel) from an (n, 2) raw-prediction pair via the
    default softmax (LogisticRegression / NaiveBayes / MLP hosts)."""
    p = softmax_probability(raw)
    return p[:, 1], (p[:, 1] > p[:, 0]).astype(raw.dtype)


def binary_from_sigmoid(margin):
    """(score, plabel) from GBT margins — GBTClassifierModel
    raw_to_probability twin (p = sigmoid(margin), label = argmax of
    [1-p, p])."""
    p = 1.0 / (1.0 + jnp.exp(-margin))
    return p, (p > 1.0 - p).astype(margin.dtype)


def binary_from_votes(votes):
    """(score, plabel) from (n, 2) non-negative vote masses —
    TreeEnsembleClassifierModel raw_to_probability twin (normalize by
    the row sum)."""
    s = jnp.sum(votes, axis=1, keepdims=True)
    p = votes / jnp.where(s > 0, s, 1.0)
    return p[:, 1], (p[:, 1] > p[:, 0]).astype(votes.dtype)


def vote_probability(votes):
    """(n, K) normalized votes (multiclass forest host twin)."""
    s = jnp.sum(votes, axis=1, keepdims=True)
    return votes / jnp.where(s > 0, s, 1.0)


def _tie_grouped_curve(pos, margin):
    """Cumulative (tp, fp) per position with each position's counts
    taken at the END of its score-tie run (descending order), plus the
    positive/negative totals. ``pos`` is the 0/1 positive indicator."""
    n = margin.shape[0]
    order = jnp.argsort(-margin)
    ys = pos[order]
    ss = margin[order]
    tp = jnp.cumsum(ys)
    fp = jnp.cumsum(1.0 - ys)
    idx = jnp.arange(n)
    is_end = jnp.concatenate(
        [ss[1:] != ss[:-1], jnp.ones((1,), bool)])
    # smallest j >= i with is_end[j]: reversed running minimum
    run_end = jax.lax.associative_scan(
        jnp.minimum, jnp.where(is_end, idx, n - 1), reverse=True)
    return tp[run_end], fp[run_end], tp[-1], fp[-1]


def binary_metric(y, score, plabel, metric: str):
    """Scalar binary metric from the RANKING SCORE (the host's
    positive-class probability — see the transforms above) and the 0/1
    hard label.

    Matches ``evaluators.binary.binary_metrics``: curve metrics are 0
    for single-class ``y``; point metrics use the same guarded ratios.
    """
    if metric not in BINARY_METRICS:
        raise ValueError(f"unsupported binary device metric {metric!r}")
    pos = (y == 1).astype(score.dtype)
    n = y.shape[0]
    if metric in ("AuPR", "AuROC"):
        tp_a, fp_a, npos, nneg = _tie_grouped_curve(pos, score)
        tpr = tp_a / jnp.maximum(npos, 1.0)
        if metric == "AuROC":
            fpr = fp_a / jnp.maximum(nneg, 1.0)
            xs = jnp.concatenate([jnp.zeros(1, tpr.dtype), fpr,
                                  jnp.ones(1, tpr.dtype)])
            ys_ = jnp.concatenate([jnp.zeros(1, tpr.dtype), tpr,
                                   jnp.ones(1, tpr.dtype)])
        else:
            prec = tp_a / jnp.maximum(tp_a + fp_a, 1.0)
            xs = jnp.concatenate([jnp.zeros(1, tpr.dtype), tpr])
            ys_ = jnp.concatenate([prec[:1], prec])
        area = jnp.sum(jnp.diff(xs) * (ys_[1:] + ys_[:-1]) * 0.5)
        return jnp.where((npos > 0) & (nneg > 0), area,
                         jnp.zeros((), area.dtype))
    predicted = (plabel == 1).astype(score.dtype)
    tp = jnp.sum(predicted * pos)
    fp = jnp.sum(predicted * (1.0 - pos))
    fn = jnp.sum((1.0 - predicted) * pos)
    if metric == "Error":
        return (fp + fn) / max(n, 1)
    precision = jnp.where(tp + fp > 0, tp / jnp.maximum(tp + fp, 1.0), 0.0)
    recall = jnp.where(tp + fn > 0, tp / jnp.maximum(tp + fn, 1.0), 0.0)
    if metric == "Precision":
        return precision
    if metric == "Recall":
        return recall
    return jnp.where(precision + recall > 0,
                     2.0 * precision * recall
                     / jnp.maximum(precision + recall, 1e-300), 0.0)


def multiclass_metric(y, prob, metric: str):
    """Scalar multiclass metric from the (n, K) PROBABILITY matrix (use
    the host-twin transforms above; hard label = argmax, first index on
    ties — same as the host ``np.argmax``). Weighted PRF over all K
    classes; classes absent from ``y`` carry zero label-frequency
    weight, reproducing the host loop over ``np.unique(y)`` exactly."""
    if metric not in MULTICLASS_METRICS:
        raise ValueError(f"unsupported multiclass device metric {metric!r}")
    k = prob.shape[1]
    raw = prob
    pred = jnp.argmax(raw, axis=1)
    yi = y.astype(jnp.int32)
    n = max(y.shape[0], 1)
    if metric == "Error":
        return jnp.mean((pred != yi).astype(raw.dtype))
    y_oh = jax.nn.one_hot(yi, k, dtype=raw.dtype)
    p_oh = jax.nn.one_hot(pred, k, dtype=raw.dtype)
    tp = jnp.sum(y_oh * p_oh, axis=0)
    fp = jnp.sum(p_oh, axis=0) - tp
    fn = jnp.sum(y_oh, axis=0) - tp
    weight = jnp.sum(y_oh, axis=0) / n
    p = jnp.where(tp + fp > 0, tp / jnp.maximum(tp + fp, 1.0), 0.0)
    r = jnp.where(tp + fn > 0, tp / jnp.maximum(tp + fn, 1.0), 0.0)
    if metric == "Precision":
        return jnp.sum(weight * p)
    if metric == "Recall":
        return jnp.sum(weight * r)
    f = jnp.where(p + r > 0, 2.0 * p * r / jnp.maximum(p + r, 1e-300), 0.0)
    return jnp.sum(weight * f)


def regression_metric(y, pred, metric: str):
    """Scalar regression metric (``evaluators.regression`` parity)."""
    if metric not in REGRESSION_METRICS:
        raise ValueError(f"unsupported regression device metric {metric!r}")
    err = pred - y
    if metric == "MeanAbsoluteError":
        return jnp.mean(jnp.abs(err))
    mse = jnp.mean(err * err)
    if metric == "MeanSquaredError":
        return mse
    if metric == "RootMeanSquaredError":
        return jnp.sqrt(mse)
    ss_tot = jnp.sum((y - jnp.mean(y)) ** 2)
    return jnp.where(ss_tot > 0, 1.0 - jnp.sum(err * err) / ss_tot, 0.0)


def metric_fn(kind: str, metric: str) -> Callable:
    """(y_val, scores) -> scalar kernel for a validator metric spec.

    kind "binary"     : scores are a (score, plabel) pair from one of
                        the host-twin transforms above
    kind "multiclass" : scores are the (n, K) probability matrix
    kind "regression" : scores are (n,) predicted values
    """
    if kind == "binary":
        return lambda y, s: binary_metric(y, s[0], s[1], metric)
    if kind == "multiclass":
        return lambda y, s: multiclass_metric(y, s, metric)
    if kind == "regression":
        return lambda y, s: regression_metric(y, s, metric)
    raise ValueError(f"unknown metric kind {kind!r}")
