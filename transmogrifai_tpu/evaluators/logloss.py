"""Logarithmic-loss evaluator.

TPU-native port of the reference OPLogLoss
(core/src/main/scala/com/salesforce/op/stages/impl/evaluator/
OPLogLoss.scala:41-62): LogLoss = mean over rows of
``-log(probability[label])``, usable for both binary and multiclass
problems (the reference exposes binaryLogLoss and multiLogLoss built on
the same function).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

from ..features.columns import PredictionColumn
from .base import EvaluationMetrics, Evaluator

__all__ = ["LogLossEvaluator", "LogLossMetrics", "log_loss"]

_EPS = 1e-15


@dataclass
class LogLossMetrics(EvaluationMetrics):
    LogLoss: float = 0.0


def log_loss(y: np.ndarray, probabilities: np.ndarray) -> float:
    """mean(-log p_label); probabilities clipped away from 0 so a single
    confident miss doesn't return inf."""
    y = np.asarray(y)
    if len(y) == 0:
        raise ValueError("log loss cannot be calculated on no rows")
    idx = y.astype(int)
    if probabilities.ndim != 2 or probabilities.shape[1] == 0:
        raise ValueError("log loss requires class probabilities")
    if idx.min() < 0 or idx.max() >= probabilities.shape[1]:
        raise ValueError(
            f"label index out of range for {probabilities.shape[1]} "
            f"probability columns")
    p = np.clip(probabilities[np.arange(len(y)), idx], _EPS, 1.0)
    return float(np.mean(-np.log(p)))


class LogLossEvaluator(Evaluator):
    """(reference OPLogLoss binaryLogLoss / multiLogLoss)"""

    default_metric = "LogLoss"
    is_larger_better = False

    def __init__(self, label_col: Optional[str] = None,
                 prediction_col: Optional[str] = None):
        super().__init__(label_col, prediction_col)

    def evaluate_arrays(self, y: np.ndarray, pred: PredictionColumn
                        ) -> LogLossMetrics:
        return LogLossMetrics(LogLoss=log_loss(y, pred.probability))
