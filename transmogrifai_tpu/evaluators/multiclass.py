"""Multiclass classification metrics.

TPU-native port of the reference OpMultiClassificationEvaluator
(core/src/main/scala/com/salesforce/op/evaluators/
OpMultiClassificationEvaluator.scala:58,268,294): weighted
precision/recall/F1/error plus ``ThresholdMetrics`` — per top-N,
per-confidence-bin correct/incorrect counts used to study how model
confidence relates to top-N accuracy.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..features.columns import PredictionColumn
from .base import EvaluationMetrics, Evaluator

__all__ = ["MultiClassificationMetrics", "ThresholdMetrics",
           "MultiClassificationEvaluator", "multiclass_metrics"]


@dataclass
class ThresholdMetrics(EvaluationMetrics):
    """Per-topN, per-confidence-bin counts
    (reference OpMultiClassificationEvaluator.scala:294)."""
    topNs: List[int] = field(default_factory=list)
    thresholds: List[float] = field(default_factory=list)
    correct_counts: Dict[int, List[int]] = field(default_factory=dict)
    incorrect_counts: Dict[int, List[int]] = field(default_factory=dict)
    no_prediction_counts: Dict[int, List[int]] = field(default_factory=dict)

    @staticmethod
    def _decode_json_kwargs(kwargs: dict) -> dict:
        """JSON stringifies the int topN keys of the count dicts; undo
        that on rebuild (metrics_from_json hook) so save/load
        round-trips bit-exact."""
        for name in ("correct_counts", "incorrect_counts",
                     "no_prediction_counts"):
            v = kwargs.get(name)
            if isinstance(v, dict):
                kwargs[name] = {int(k): x for k, x in v.items()}
        return kwargs


@dataclass
class MultiClassificationMetrics(EvaluationMetrics):
    """Reference OpMultiClassificationEvaluator metrics (``:58``).
    Precision/Recall/F1 are label-frequency weighted, matching Spark's
    MulticlassMetrics weighted variants."""
    Precision: float = 0.0
    Recall: float = 0.0
    F1: float = 0.0
    Error: float = 0.0
    ThresholdMetrics: Optional[ThresholdMetrics] = None


def _weighted_prf(y: np.ndarray, pred: np.ndarray
                  ) -> Tuple[float, float, float]:
    labels = np.unique(y)
    n = len(y)
    w_p = w_r = w_f = 0.0
    for lbl in labels:
        weight = float(np.sum(y == lbl)) / n
        tp = float(np.sum((pred == lbl) & (y == lbl)))
        fp = float(np.sum((pred == lbl) & (y != lbl)))
        fn = float(np.sum((pred != lbl) & (y == lbl)))
        p = tp / (tp + fp) if tp + fp > 0 else 0.0
        r = tp / (tp + fn) if tp + fn > 0 else 0.0
        f = 2 * p * r / (p + r) if p + r > 0 else 0.0
        w_p += weight * p
        w_r += weight * r
        w_f += weight * f
    return w_p, w_r, w_f


def threshold_metrics(y: np.ndarray, prob: np.ndarray,
                      top_ns: Sequence[int] = (1, 3),
                      n_bins: int = 10) -> ThresholdMetrics:
    """For each topN and max-confidence threshold bin: counts of rows whose
    true label is within the top-N most-probable classes (correct), isn't
    (incorrect), or whose max confidence falls below the threshold
    (no prediction). Reference ``:268``."""
    thresholds = np.linspace(0.0, 1.0, n_bins, endpoint=False)
    max_conf = prob.max(axis=1) if prob.size else np.zeros(len(y))
    order = np.argsort(-prob, axis=1) if prob.size else \
        np.zeros((len(y), 1), dtype=int)
    correct: Dict[int, List[int]] = {}
    incorrect: Dict[int, List[int]] = {}
    nopred: Dict[int, List[int]] = {}
    for top_n in top_ns:
        in_top = np.any(order[:, :top_n] == y[:, None].astype(int), axis=1)
        c, i, np_ = [], [], []
        for t in thresholds:
            above = max_conf >= t
            c.append(int(np.sum(above & in_top)))
            i.append(int(np.sum(above & ~in_top)))
            np_.append(int(np.sum(~above)))
        correct[top_n], incorrect[top_n], nopred[top_n] = c, i, np_
    return ThresholdMetrics(
        topNs=list(top_ns), thresholds=thresholds.tolist(),
        correct_counts=correct, incorrect_counts=incorrect,
        no_prediction_counts=nopred)


def multiclass_metrics(y: np.ndarray, pred: np.ndarray,
                       prob: Optional[np.ndarray] = None,
                       top_ns: Sequence[int] = (1, 3),
                       n_bins: int = 10) -> MultiClassificationMetrics:
    y = np.asarray(y, dtype=np.float64)
    pred = np.asarray(pred, dtype=np.float64)
    p, r, f1 = _weighted_prf(y, pred)
    err = float(np.mean(pred != y)) if len(y) else 0.0
    tm = (threshold_metrics(y, prob, top_ns, n_bins)
          if prob is not None and prob.size else None)
    return MultiClassificationMetrics(Precision=p, Recall=r, F1=f1,
                                      Error=err, ThresholdMetrics=tm)


class MultiClassificationEvaluator(Evaluator):
    """Reference OpMultiClassificationEvaluator.scala:58."""

    default_metric = "F1"
    is_larger_better = True

    def __init__(self, label_col: Optional[str] = None,
                 prediction_col: Optional[str] = None,
                 default_metric: str = "F1",
                 top_ns: Sequence[int] = (1, 3), n_bins: int = 10):
        super().__init__(label_col, prediction_col)
        self.default_metric = default_metric
        self.is_larger_better = default_metric != "Error"
        self.top_ns = tuple(top_ns)
        self.n_bins = n_bins

    def evaluate_arrays(self, y: np.ndarray, pred: PredictionColumn
                        ) -> MultiClassificationMetrics:
        prob = pred.probability if pred.probability.shape[1] else None
        return multiclass_metrics(y, pred.data, prob,
                                  top_ns=self.top_ns, n_bins=self.n_bins)

    def device_metric_spec(self):
        from .device_metrics import MULTICLASS_METRICS
        return self._device_spec(MultiClassificationEvaluator,
                                 MULTICLASS_METRICS, "multiclass")
