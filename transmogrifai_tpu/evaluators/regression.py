"""Regression metrics.

TPU-native port of the reference OpRegressionEvaluator
(core/src/main/scala/com/salesforce/op/evaluators/
OpRegressionEvaluator.scala:50,101): RMSE, MSE, R², MAE.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

from ..features.columns import PredictionColumn
from .base import EvaluationMetrics, Evaluator

__all__ = ["RegressionMetrics", "RegressionEvaluator", "regression_metrics"]


@dataclass
class RegressionMetrics(EvaluationMetrics):
    RootMeanSquaredError: float = 0.0
    MeanSquaredError: float = 0.0
    R2: float = 0.0
    MeanAbsoluteError: float = 0.0


def regression_metrics(y: np.ndarray, pred: np.ndarray) -> RegressionMetrics:
    y = np.asarray(y, dtype=np.float64)
    pred = np.asarray(pred, dtype=np.float64)
    if len(y) == 0:
        return RegressionMetrics()
    err = pred - y
    mse = float(np.mean(err ** 2))
    ss_tot = float(np.sum((y - y.mean()) ** 2))
    r2 = 1.0 - float(np.sum(err ** 2)) / ss_tot if ss_tot > 0 else 0.0
    return RegressionMetrics(
        RootMeanSquaredError=float(np.sqrt(mse)), MeanSquaredError=mse,
        R2=r2, MeanAbsoluteError=float(np.mean(np.abs(err))))


class RegressionEvaluator(Evaluator):
    """Reference OpRegressionEvaluator.scala:50."""

    default_metric = "RootMeanSquaredError"
    is_larger_better = False

    def __init__(self, label_col: Optional[str] = None,
                 prediction_col: Optional[str] = None,
                 default_metric: str = "RootMeanSquaredError"):
        super().__init__(label_col, prediction_col)
        self.default_metric = default_metric
        self.is_larger_better = default_metric == "R2"

    def evaluate_arrays(self, y: np.ndarray, pred: PredictionColumn
                        ) -> RegressionMetrics:
        return regression_metrics(y, pred.data)

    def device_metric_spec(self):
        from .device_metrics import REGRESSION_METRICS
        return self._device_spec(RegressionEvaluator,
                                 REGRESSION_METRICS, "regression")
