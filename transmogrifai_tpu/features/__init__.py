from .columns import ColumnKind, Dataset, FeatureColumn, column_kind
from .feature import (Feature, FeatureCycleError, FeatureHistory,
                      parent_stages, topo_layers)
from .builder import FeatureBuilder, FeatureBuilderWithExtract, infer_schema
from .generator import FeatureGeneratorStage

__all__ = [
    "ColumnKind", "Dataset", "FeatureColumn", "column_kind",
    "Feature", "FeatureCycleError", "FeatureHistory", "parent_stages",
    "topo_layers", "FeatureBuilder", "FeatureBuilderWithExtract",
    "infer_schema", "FeatureGeneratorStage",
]
