"""Monoid aggregators for keyed/time-windowed feature extraction.

TPU-native port of the reference aggregator kernel
(features/src/main/scala/com/salesforce/op/aggregators/
{MonoidAggregatorDefaults.scala:41,52, Event.scala:44,
TimeBasedAggregator.scala:38,61,70, CustomMonoidAggregator} and the
CutOffTime types): every FeatureType has a default monoid used by
aggregate readers to fold a key's event stream into one value —
numerics sum, text concatenates, sets/lists union, maps merge,
geolocation takes the geographic midpoint.
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Callable, List, Optional, Type

from ..types import (Binary, FeatureType, Geolocation, MultiPickList,
                     OPList, OPMap, OPNumeric, OPSet, OPVector, Text)

__all__ = ["Event", "CutOffTime", "MonoidAggregator",
           "CustomMonoidAggregator", "SumNumeric", "MinNumeric",
           "MaxNumeric", "MeanNumeric", "LogicalOr", "LogicalAnd",
           "ConcatText", "UnionList", "UnionSet", "UnionMap",
           "GeolocationMidpoint", "LastAggregator", "FirstAggregator",
           "default_aggregator"]


@dataclass(frozen=True)
class Event:
    """A dated raw value (reference Event.scala:44)."""
    date_ms: int
    value: Any
    is_response: bool = False


@dataclass(frozen=True)
class CutOffTime:
    """Predictor/response cutoff (reference CutOffTime types): events at or
    before ``time_ms`` feed predictors; events after feed responses."""
    time_ms: Optional[int] = None

    @staticmethod
    def unix_ms(t: int) -> "CutOffTime":
        return CutOffTime(time_ms=t)

    @staticmethod
    def no_cutoff() -> "CutOffTime":
        return CutOffTime(time_ms=None)


class MonoidAggregator:
    """zero + plus over prepared values; ``prepare`` unboxes, ``present``
    reboxes (reference algebird MonoidAggregator usage)."""

    def prepare(self, value: Any) -> Any:
        return value

    def zero(self) -> Any:
        return None

    def plus(self, a: Any, b: Any) -> Any:
        raise NotImplementedError

    def present(self, acc: Any) -> Any:
        return acc

    def reduce(self, values: List[Any]) -> Any:
        acc = self.zero()
        for v in values:
            if v is None:
                continue
            acc = self.plus(acc, self.prepare(v))
        return self.present(acc)


class _NullSkipping(MonoidAggregator):
    def plus(self, a, b):
        if a is None:
            return b
        if b is None:
            return a
        return self.combine(a, b)

    def combine(self, a, b):
        raise NotImplementedError


class SumNumeric(_NullSkipping):
    """(reference SumNumeric / SumReal)"""

    def combine(self, a, b):
        return a + b


class MinNumeric(_NullSkipping):
    def combine(self, a, b):
        return min(a, b)


class MaxNumeric(_NullSkipping):
    def combine(self, a, b):
        return max(a, b)


class MeanNumeric(_NullSkipping):
    """(reference MeanDouble — tracked as (sum, count))"""

    def prepare(self, value):
        return (float(value), 1)

    def combine(self, a, b):
        return (a[0] + b[0], a[1] + b[1])

    def present(self, acc):
        return None if acc is None or acc[1] == 0 else acc[0] / acc[1]


class LogicalOr(_NullSkipping):
    def combine(self, a, b):
        return bool(a or b)


class LogicalAnd(_NullSkipping):
    def combine(self, a, b):
        return bool(a and b)


class ConcatText(_NullSkipping):
    """(reference ConcatTextWithSeparator)"""

    def __init__(self, separator: str = " "):
        self.separator = separator

    def combine(self, a, b):
        return f"{a}{self.separator}{b}"


class UnionList(_NullSkipping):
    def prepare(self, value):
        return list(value)

    def combine(self, a, b):
        return a + b


class UnionSet(_NullSkipping):
    def prepare(self, value):
        return set(value)

    def combine(self, a, b):
        return a | b


class UnionMap(_NullSkipping):
    """Map merge; numeric values under the same key sum, others keep the
    last (reference UnionMap semigroup semantics)."""

    def prepare(self, value):
        return dict(value)

    def combine(self, a, b):
        out = dict(a)
        for k, v in b.items():
            if k in out and isinstance(out[k], (int, float)) \
                    and isinstance(v, (int, float)) \
                    and not isinstance(out[k], bool):
                out[k] = out[k] + v
            else:
                out[k] = v
        return out


class GeolocationMidpoint(_NullSkipping):
    """Geographic midpoint via 3-D unit-vector average
    (reference Geolocation aggregator using lucene spatial3d)."""

    def prepare(self, value):
        lat, lon = math.radians(value[0]), math.radians(value[1])
        acc = value[2] if len(value) > 2 else 1.0
        return [math.cos(lat) * math.cos(lon),
                math.cos(lat) * math.sin(lon),
                math.sin(lat), 1.0, acc]

    def combine(self, a, b):
        return [x + y for x, y in zip(a, b)]

    def present(self, acc):
        if acc is None or acc[3] == 0:
            return None
        x, y, z = (c / acc[3] for c in acc[:3])
        lon = math.degrees(math.atan2(y, x))
        lat = math.degrees(math.atan2(z, math.hypot(x, y)))
        return [lat, lon, acc[4] / acc[3]]


class LastAggregator(MonoidAggregator):
    """Keep the latest non-null event value
    (reference TimeBasedAggregator.scala:61). Requires (date, value)
    prepared tuples — aggregate readers call ``reduce_events``."""

    def reduce_events(self, events: List[Event]) -> Any:
        dated = [e for e in events if e.value is not None]
        return max(dated, key=lambda e: e.date_ms).value if dated else None

    def reduce(self, values: List[Any]) -> Any:
        live = [v for v in values if v is not None]
        return live[-1] if live else None


class FirstAggregator(MonoidAggregator):
    """(reference TimeBasedAggregator.scala:70)"""

    def reduce_events(self, events: List[Event]) -> Any:
        dated = [e for e in events if e.value is not None]
        return min(dated, key=lambda e: e.date_ms).value if dated else None

    def reduce(self, values: List[Any]) -> Any:
        live = [v for v in values if v is not None]
        return live[0] if live else None


class CustomMonoidAggregator(MonoidAggregator):
    """(reference CustomMonoidAggregator)"""

    def __init__(self, zero: Any, combine: Callable[[Any, Any], Any]):
        self._zero = zero
        self._combine = combine

    def zero(self):
        return self._zero

    def plus(self, a, b):
        return self._combine(a, b)


def default_aggregator(ftype: Type[FeatureType]) -> MonoidAggregator:
    """Default monoid per feature type
    (reference MonoidAggregatorDefaults.scala:52)."""
    if issubclass(ftype, Binary):
        return LogicalOr()
    if issubclass(ftype, OPNumeric):
        return SumNumeric()
    if issubclass(ftype, Geolocation):
        return GeolocationMidpoint()
    if issubclass(ftype, (OPSet, MultiPickList)):
        return UnionSet()
    if issubclass(ftype, OPList):
        return UnionList()
    if issubclass(ftype, OPMap):
        return UnionMap()
    if issubclass(ftype, OPVector):
        return LastAggregator()
    if issubclass(ftype, Text):
        return ConcatText()
    return LastAggregator()
