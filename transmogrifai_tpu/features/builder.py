"""FeatureBuilder: the user-facing entry point for declaring raw features.

Reference: features/src/main/scala/com/salesforce/op/features/
FeatureBuilder.scala:47-217. Usage:

    age  = FeatureBuilder.real("age").extract(lambda r: r["age"]).as_predictor()
    y    = FeatureBuilder.real_nn("survived").extract(...).as_response()
    y, xs = FeatureBuilder.from_dataframe(df, response="survived")

``from_dataframe`` (reference FeatureBuilder.fromDataFrame:190-217) infers a
typed feature per column from a pandas DataFrame schema.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple, Type

import numpy as np

from .. import types as T
from ..types import FeatureType
from .feature import Feature
from .generator import FeatureGeneratorStage

__all__ = ["FeatureBuilder", "FeatureBuilderWithExtract", "infer_schema"]


class FeatureBuilderWithExtract:
    """Builder holding name + type + extract fn
    (reference FeatureBuilderWithExtract)."""

    def __init__(self, name: str, ftype: Type[FeatureType],
                 extract_fn: Callable[[Any], Any],
                 aggregator=None, window_ms: Optional[int] = None,
                 source_name: Optional[str] = None):
        self.name = name
        self.ftype = ftype
        self.extract_fn = extract_fn
        self.aggregator = aggregator
        self.window_ms = window_ms
        self.source_name = source_name

    def aggregate(self, aggregator) -> "FeatureBuilderWithExtract":
        """Set the monoid aggregator used by aggregate readers
        (reference FeatureBuilder.aggregate)."""
        self.aggregator = aggregator
        return self

    def window(self, window_ms: int) -> "FeatureBuilderWithExtract":
        self.window_ms = window_ms
        return self

    def from_source(self, source_name: str) -> "FeatureBuilderWithExtract":
        """Bind this feature to one side of a joined reader by name
        (the reference encodes this in FeatureBuilder[T]'s reader type
        parameter; see readers.joined.JoinedAggregateReaders)."""
        self.source_name = source_name
        return self

    def _build(self, is_response: bool) -> Feature:
        stage = FeatureGeneratorStage(
            name=self.name, ftype=self.ftype, extract_fn=self.extract_fn,
            is_response=is_response, aggregator=self.aggregator,
            aggregate_window_ms=self.window_ms,
            source_name=self.source_name)
        return stage.get_output()

    def as_predictor(self) -> Feature:
        return self._build(is_response=False)

    def as_response(self) -> Feature:
        return self._build(is_response=True)


class _FeatureBuilderFor:
    def __init__(self, name: str, ftype: Type[FeatureType]):
        self.name = name
        self.ftype = ftype

    def extract(self, fn: Callable[[Any], Any]) -> FeatureBuilderWithExtract:
        return FeatureBuilderWithExtract(self.name, self.ftype, fn)


def _snake(name: str) -> str:
    import re
    return re.sub(r"(?<=[a-z0-9])(?=[A-Z])|(?<=[A-Z])(?=[A-Z][a-z])",
                  "_", name).lower()


class _FeatureBuilderMeta(type):
    """Generates one entry point per feature type
    (reference FeatureBuilder.scala:51-130 lists all 45)."""

    _lookup: Optional[Dict[str, Type[FeatureType]]] = None

    _lookup_size: int = -1  # registry size when _lookup was built

    @staticmethod
    def _rebuild_lookup() -> Dict[str, Type[FeatureType]]:
        from ..types import all_feature_types
        types = all_feature_types()
        lk: Dict[str, Type[FeatureType]] = {}
        for ft in types:
            lk[_snake(ft.__name__)] = ft
            lk[ft.__name__.lower()] = ft
        _FeatureBuilderMeta._lookup = lk
        _FeatureBuilderMeta._lookup_size = len(types)
        return lk

    def __getattr__(cls, item: str):
        lk = _FeatureBuilderMeta._lookup
        if lk is None:
            lk = _FeatureBuilderMeta._rebuild_lookup()
        ftype = lk.get(item.lower())
        if ftype is None:
            # user-registered feature types may have landed since the cache
            # was built; rebuild only if the registry actually grew (misses
            # on an unchanged registry — hasattr probes, typos — stay cheap)
            from ..types import all_feature_types
            if len(all_feature_types()) != _FeatureBuilderMeta._lookup_size:
                ftype = _FeatureBuilderMeta._rebuild_lookup().get(item.lower())
        if ftype is None:
            raise AttributeError(f"FeatureBuilder has no builder {item!r}")
        return lambda name: _FeatureBuilderFor(name, ftype)


class FeatureBuilder(metaclass=_FeatureBuilderMeta):
    """``FeatureBuilder.<type>(name).extract(fn).as_predictor()``."""

    @staticmethod
    def of(name: str, ftype: Type[FeatureType]) -> _FeatureBuilderFor:
        return _FeatureBuilderFor(name, ftype)

    @staticmethod
    def from_dataframe(df, response: str,
                       response_type: Type[FeatureType] = T.RealNN,
                       schema: Optional[Dict[str, Type[FeatureType]]] = None,
                       ) -> Tuple[Feature, List[Feature]]:
        """Infer one typed feature per DataFrame column
        (reference FeatureBuilder.fromDataFrame:190-217)."""
        inferred = schema or infer_schema(df)
        if response not in df.columns:
            raise ValueError(f"Response column {response!r} not in DataFrame")
        feats: List[Feature] = []
        resp: Optional[Feature] = None
        for name in df.columns:
            ftype = response_type if name == response \
                else inferred.get(name, T.Text)
            builder = FeatureBuilderWithExtract(
                name, ftype, _make_column_extract(name))
            if name == response:
                resp = builder.as_response()
            else:
                feats.append(builder.as_predictor())
        return resp, feats


def _make_column_extract(name: str):
    return lambda rec: rec.get(name) if isinstance(rec, dict) \
        else getattr(rec, name, None)


def infer_schema(df, categorical_max_card: int = 100
                 ) -> Dict[str, Type[FeatureType]]:
    """Pandas dtype -> feature type inference. Low-cardinality strings map
    to PickList, integer {0,1} to Binary (mirrors the intent of the
    reference's CSV auto-readers, readers/.../CSVAutoReaders.scala)."""
    import pandas as pd
    out: Dict[str, Type[FeatureType]] = {}
    for name in df.columns:
        s = df[name]
        dt = s.dtype
        if pd.api.types.is_bool_dtype(dt):
            out[name] = T.Binary
        elif pd.api.types.is_integer_dtype(dt) or pd.api.types.is_float_dtype(dt):
            vals = s.dropna().unique()
            if len(vals) <= 2 and set(np.asarray(vals, dtype=float)) <= {0.0, 1.0}:
                out[name] = T.Binary
            elif pd.api.types.is_integer_dtype(dt):
                out[name] = T.Integral
            else:
                out[name] = T.Real
        elif pd.api.types.is_datetime64_any_dtype(dt):
            out[name] = T.DateTime
        else:
            non_null = s.dropna()
            nunique = non_null.nunique()
            if 0 < nunique <= min(categorical_max_card,
                                  max(2, len(non_null) // 2)):
                out[name] = T.PickList
            else:
                out[name] = T.Text
    return out
