"""Columnar batch representation of typed features.

This replaces the reference's Spark ``DataFrame`` + row-level
``OpTransformer.transformKeyValue`` design (features/src/main/scala/com/
salesforce/op/stages/OpPipelineStages.scala:592) with host-side columnar
buffers that map directly onto device arrays:

- numeric family  -> float64 numpy array, NaN encodes missing
- text family     -> object numpy array of ``str | None``
- list/set/map    -> object numpy array of tuples / frozensets / dicts
- OPVector        -> dense 2-D float array + ``VectorMetadata``

Row-at-a-time processing was Spark-shaped; columnar is both faster on host
and the only sane feed format for XLA. A boxed row view is still provided
for the local-scoring path (reference local module).
"""
from __future__ import annotations

import math
import numbers
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Type

import numpy as np

from ..types import (Binary, FeatureType, FeatureTypeError, Geolocation,
                     Integral, OPMap, OPNumeric, OPSet, OPList, OPVector,
                     Prediction, Text)
from ..types.maps import BinaryMap, IntegralMap, MultiPickListMap, NumericMap, \
    GeolocationMap, TextMap
from ..utils.vector_meta import VectorMetadata

__all__ = ["FeatureColumn", "PredictionColumn", "Dataset", "column_kind",
           "ColumnKind"]


class ColumnKind:
    NUMERIC = "numeric"
    TEXT = "text"
    OBJECT = "object"   # lists / sets / maps / geolocations
    VECTOR = "vector"


def column_kind(ftype: Type[FeatureType]) -> str:
    if issubclass(ftype, OPVector):
        return ColumnKind.VECTOR
    if issubclass(ftype, OPNumeric):
        return ColumnKind.NUMERIC
    if issubclass(ftype, Text):
        return ColumnKind.TEXT
    return ColumnKind.OBJECT


@dataclass
class FeatureColumn:
    """A column of ``n_rows`` values of one feature type."""
    ftype: Type[FeatureType]
    data: np.ndarray
    metadata: Optional[VectorMetadata] = None

    @property
    def kind(self) -> str:
        return column_kind(self.ftype)

    @property
    def n_rows(self) -> int:
        return int(self.data.shape[0])

    @property
    def width(self) -> int:
        if self.kind != ColumnKind.VECTOR:
            raise ValueError("width only defined for vector columns")
        return int(self.data.shape[1])

    # -- constructors ------------------------------------------------------
    @staticmethod
    def from_values(ftype: Type[FeatureType], values: Iterable[Any],
                    metadata: Optional[VectorMetadata] = None
                    ) -> "FeatureColumn":
        """Build a column from raw python values (each is boxed-converted
        through the feature type for validation/normalization)."""
        kind = column_kind(ftype)
        boxed = [v.value if isinstance(v, FeatureType) else ftype(v).value
                 for v in values]
        if kind == ColumnKind.NUMERIC:
            arr = np.asarray(
                [math.nan if b is None else float(b) for b in boxed],
                dtype=np.float64)
        elif kind == ColumnKind.TEXT:
            arr = np.empty(len(boxed), dtype=object)
            arr[:] = boxed
        elif kind == ColumnKind.VECTOR:
            if len(boxed) == 0:
                arr = np.zeros((0, 0), dtype=np.float64)
            else:
                arr = np.stack([np.asarray(b, dtype=np.float64)
                                for b in boxed])
        else:
            arr = np.empty(len(boxed), dtype=object)
            arr[:] = boxed
        return FeatureColumn(ftype=ftype, data=arr, metadata=metadata)

    @staticmethod
    def vector(data: np.ndarray, metadata: VectorMetadata) -> "FeatureColumn":
        data = np.asarray(data, dtype=np.float64)
        if data.ndim != 2:
            raise ValueError(f"vector column requires 2-D data, got {data.ndim}-D")
        if metadata.size != data.shape[1]:
            raise ValueError(
                f"metadata size {metadata.size} != vector width {data.shape[1]}")
        return FeatureColumn(ftype=OPVector, data=data, metadata=metadata)

    # -- access ------------------------------------------------------------
    def boxed(self, i: int) -> FeatureType:
        """Boxed value at row ``i`` (edge-of-system only)."""
        v = self.data[i]
        if self.kind == ColumnKind.NUMERIC:
            v = None if (v != v) else float(v)
            if issubclass(self.ftype, (Integral, Binary)) and v is not None:
                v = int(v) if issubclass(self.ftype, Integral) else bool(v)
        return self.ftype(v)

    def boxed_values(self) -> list:
        return [self.boxed(i) for i in range(self.n_rows)]

    def is_missing(self) -> np.ndarray:
        """Boolean mask of empty rows."""
        k = self.kind
        if k == ColumnKind.NUMERIC:
            return np.isnan(self.data)
        if k == ColumnKind.TEXT:
            # empty string is *present* (reference: Text(Some("")) non-empty)
            return np.asarray([v is None for v in self.data])
        if k == ColumnKind.VECTOR:
            return np.zeros(self.n_rows, dtype=bool)
        return np.asarray([v is None or len(v) == 0 for v in self.data])

    def take(self, idx: np.ndarray) -> "FeatureColumn":
        return FeatureColumn(self.ftype, self.data[idx], self.metadata)

    def __len__(self) -> int:
        return self.n_rows


@dataclass
class PredictionColumn(FeatureColumn):
    """Columnar batch of ``Prediction`` values.

    The reference materializes one ``Prediction`` map per row
    (Maps.scala:302); on TPU the model outputs stay dense: ``data`` holds
    the (n,) predicted values and ``probability`` / ``raw_prediction`` the
    (n, k) per-class arrays (k = 0 when absent). Boxed ``Prediction`` dicts
    are synthesized only at the row-level scoring edge."""
    probability: np.ndarray = field(
        default_factory=lambda: np.zeros((0, 0), dtype=np.float64))
    raw_prediction: np.ndarray = field(
        default_factory=lambda: np.zeros((0, 0), dtype=np.float64))

    @staticmethod
    def from_arrays(prediction: np.ndarray,
                    probability: Optional[np.ndarray] = None,
                    raw_prediction: Optional[np.ndarray] = None
                    ) -> "PredictionColumn":
        pred = np.asarray(prediction, dtype=np.float64).reshape(-1)
        n = pred.shape[0]
        prob = (np.zeros((n, 0)) if probability is None
                else np.asarray(probability, dtype=np.float64).reshape(n, -1))
        raw = (np.zeros((n, 0)) if raw_prediction is None
               else np.asarray(raw_prediction, dtype=np.float64).reshape(n, -1))
        return PredictionColumn(ftype=Prediction, data=pred,
                                probability=prob, raw_prediction=raw)

    def boxed(self, i: int) -> Prediction:
        return Prediction.build(
            float(self.data[i]),
            raw_prediction=self.raw_prediction[i]
            if self.raw_prediction.shape[1] else None,
            probability=self.probability[i]
            if self.probability.shape[1] else None)

    def is_missing(self) -> np.ndarray:
        return np.zeros(self.n_rows, dtype=bool)

    def take(self, idx: np.ndarray) -> "PredictionColumn":
        return PredictionColumn(
            ftype=self.ftype, data=self.data[idx], metadata=self.metadata,
            probability=self.probability[idx],
            raw_prediction=self.raw_prediction[idx])


class Dataset:
    """Named collection of equal-length feature columns — the framework's
    DataFrame equivalent (reference RichDataset, features/.../utils/spark/
    RichDataset.scala:60)."""

    def __init__(self, columns: Optional[Dict[str, FeatureColumn]] = None):
        self._columns: Dict[str, FeatureColumn] = dict(columns or {})
        lens = {c.n_rows for c in self._columns.values()}
        if len(lens) > 1:
            raise ValueError(f"Column length mismatch: {lens}")

    # -- core --------------------------------------------------------------
    @property
    def n_rows(self) -> int:
        for c in self._columns.values():
            return c.n_rows
        return 0

    @property
    def column_names(self) -> List[str]:
        return list(self._columns.keys())

    def __contains__(self, name: str) -> bool:
        return name in self._columns

    def __getitem__(self, name: str) -> FeatureColumn:
        try:
            return self._columns[name]
        except KeyError:
            raise KeyError(
                f"No column {name!r}; have {sorted(self._columns)}") from None

    def with_column(self, name: str, col: FeatureColumn) -> "Dataset":
        if self._columns and col.n_rows != self.n_rows:
            raise ValueError(
                f"Column {name!r} has {col.n_rows} rows, dataset has {self.n_rows}")
        new = dict(self._columns)
        new[name] = col
        return Dataset(new)

    def select(self, names: Sequence[str]) -> "Dataset":
        return Dataset({n: self[n] for n in names})

    def drop(self, names: Sequence[str]) -> "Dataset":
        drop = set(names)
        return Dataset({n: c for n, c in self._columns.items()
                        if n not in drop})

    def take(self, idx: np.ndarray) -> "Dataset":
        return Dataset({n: c.take(idx) for n, c in self._columns.items()})

    def rows(self, names: Optional[Sequence[str]] = None):
        """Iterate boxed row dicts — local-scoring edge only."""
        names = list(names) if names is not None else self.column_names
        for i in range(self.n_rows):
            yield {n: self._columns[n].boxed(i) for n in names}

    # -- conversion --------------------------------------------------------
    @staticmethod
    def from_pandas(df, schema: Dict[str, Type[FeatureType]]) -> "Dataset":
        import pandas as pd
        cols = {}
        for name, ftype in schema.items():
            values = [None if (v is None or (not isinstance(v, (list, tuple, set, frozenset, dict, np.ndarray))
                               and pd.isna(v))) else v
                      for v in df[name].tolist()]
            cols[name] = FeatureColumn.from_values(ftype, values)
        return Dataset(cols)

    def to_pandas(self, names: Optional[Sequence[str]] = None):
        import pandas as pd
        names = list(names) if names is not None else self.column_names
        out = {}
        for n in names:
            c = self._columns[n]
            if c.kind == ColumnKind.VECTOR:
                out[n] = [np.asarray(row) for row in c.data]
            else:
                out[n] = c.data
        return pd.DataFrame(out)

    def __repr__(self) -> str:
        parts = ", ".join(f"{n}: {c.ftype.__name__}"
                          for n, c in self._columns.items())
        return f"Dataset({self.n_rows} rows; {parts})"
