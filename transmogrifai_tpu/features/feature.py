"""The lazy, typed feature DAG.

TPU-native port of the reference Feature DAG
(features/src/main/scala/com/salesforce/op/features/{FeatureLike.scala:48,
Feature.scala:52}): a ``Feature`` is a lazy node naming the output of a
stage applied to parent features; nothing is materialized until a workflow
runs. Topological sorting (``parent_stages``, reference
FeatureLike.parentStages:363-430) assigns every origin stage its maximum
distance from the result features — the workflow fits/transforms layer by
layer in decreasing distance order.
"""
from __future__ import annotations

from typing import TYPE_CHECKING, Callable, Dict, List, Optional, Sequence, Tuple, Type

from ..types import FeatureType
from ..utils.uid import uid as make_uid

if TYPE_CHECKING:  # pragma: no cover
    from ..stages.base import PipelineStage

__all__ = ["Feature", "FeatureCycleError", "FeatureHistory", "topo_layers"]


class FeatureCycleError(ValueError):
    """Raised when the feature graph contains a cycle
    (reference FeatureCycleException)."""


class FeatureHistory:
    """Lineage record: origin raw features + stage operations applied
    (reference utils/.../FeatureHistory.scala)."""

    def __init__(self, origin_features: Sequence[str],
                 stages: Sequence[str]):
        self.origin_features = tuple(sorted(set(origin_features)))
        self.stages = tuple(stages)

    def to_json(self) -> dict:
        return {"originFeatures": list(self.origin_features),
                "stages": list(self.stages)}

    def __repr__(self) -> str:
        return (f"FeatureHistory(origin={list(self.origin_features)}, "
                f"stages={list(self.stages)})")


class Feature:
    """A node in the feature DAG (reference Feature.scala:52)."""

    __slots__ = ("name", "ftype", "is_response", "origin_stage", "parents",
                 "uid", "distributions")

    def __init__(self, name: str, ftype: Type[FeatureType],
                 is_response: bool = False,
                 origin_stage: Optional["PipelineStage"] = None,
                 parents: Sequence["Feature"] = (),
                 uid: Optional[str] = None,
                 distributions: tuple = ()):
        self.name = name
        self.ftype = ftype
        self.is_response = is_response
        self.origin_stage = origin_stage
        self.parents: Tuple[Feature, ...] = tuple(parents)
        self.uid = uid or make_uid("Feature")
        #: feature distributions recorded by RawFeatureFilter
        self.distributions = distributions

    # -- graph API ---------------------------------------------------------
    @property
    def is_raw(self) -> bool:
        return len(self.parents) == 0

    def transform_with(self, stage: "PipelineStage",
                       *others: "Feature") -> "Feature":
        """Apply a stage to this feature (+ optional co-inputs) and return
        its output feature (reference FeatureLike.transformWith)."""
        return stage.set_input(self, *others).get_output()

    def traverse(self, visit: Callable[["Feature"], None]) -> None:
        """DFS over the subgraph rooted here, with cycle detection
        (reference FeatureLike.traverse:309)."""
        on_path: set[str] = set()
        seen: set[str] = set()

        def go(f: "Feature"):
            if f.uid in on_path:
                raise FeatureCycleError(
                    f"Feature cycle detected at {f.name!r}")
            if f.uid in seen:
                return
            on_path.add(f.uid)
            visit(f)
            for p in f.parents:
                go(p)
            on_path.discard(f.uid)
            seen.add(f.uid)

        go(self)

    def raw_features(self) -> List["Feature"]:
        """All raw (leaf) ancestors (reference FeatureLike.rawFeatures:338)."""
        uniq: dict[str, Feature] = {}
        for f in _collect(self):
            if f.is_raw:
                uniq.setdefault(f.uid, f)
        return sorted(uniq.values(), key=lambda f: f.name)

    def parent_stages(self) -> Dict["PipelineStage", int]:
        """Map each ancestor origin stage to its max distance from this
        feature (reference FeatureLike.parentStages:363-430)."""
        return parent_stages([self])

    def history(self) -> FeatureHistory:
        """Origin features + stage lineage (reference FeatureLike.history)."""
        origins = [f.name for f in self.raw_features()]
        dist = self.parent_stages()
        ordered = sorted(dist.items(), key=lambda kv: -kv[1])
        return FeatureHistory(
            origin_features=origins,
            stages=[s.stage_name() for s, _ in ordered])

    def copy_with_new_stages(self, stage_map: Dict[str, "PipelineStage"]
                             ) -> "Feature":
        """Rebuild the DAG swapping origin stages by uid — used to replace
        estimators with their fitted models after training
        (reference Feature.copyWithNewStages:86)."""
        cache: dict[str, Feature] = {}

        def rebuild(f: "Feature") -> "Feature":
            if f.uid in cache:
                return cache[f.uid]
            new_parents = tuple(rebuild(p) for p in f.parents)
            stage = stage_map.get(f.origin_stage.uid, f.origin_stage) \
                if f.origin_stage is not None else None
            nf = Feature(name=f.name, ftype=f.ftype,
                         is_response=f.is_response, origin_stage=stage,
                         parents=new_parents, uid=f.uid,
                         distributions=f.distributions)
            swapped = (f.origin_stage is not None
                       and f.origin_stage.uid in stage_map)
            if swapped and new_parents:
                # wire the swapped-in fitted model to the rebuilt DAG so
                # execution derives the same column names; stages shared
                # with the source graph are left untouched
                stage.input_features = new_parents
                stage._output_feature = nf
            cache[f.uid] = nf
            return nf

        return rebuild(self)

    # -- DSL enrichments (reference core/.../dsl/RichNumericFeature.scala,
    # RichTextFeature.scala, RichFeature.scala) -----------------------------
    def _arith(self, other, op: str, swapped: bool = False) -> "Feature":
        from ..ops.dsl import NumericBinaryTransformer, NumericScalarTransformer
        if isinstance(other, Feature):
            a, b = (other, self) if swapped else (self, other)
            return NumericBinaryTransformer(op=op).set_input(a, b).get_output()
        return NumericScalarTransformer(
            op=op, scalar=float(other), swapped=swapped
        ).set_input(self).get_output()

    def __add__(self, other):
        return self._arith(other, "add")

    def __radd__(self, other):
        return self._arith(other, "add", swapped=True)

    def __sub__(self, other):
        return self._arith(other, "sub")

    def __rsub__(self, other):
        return self._arith(other, "sub", swapped=True)

    def __mul__(self, other):
        return self._arith(other, "mul")

    def __rmul__(self, other):
        return self._arith(other, "mul", swapped=True)

    def __truediv__(self, other):
        return self._arith(other, "div")

    def __rtruediv__(self, other):
        return self._arith(other, "div", swapped=True)

    def map(self, fn: Callable, output_type: Type[FeatureType]) -> "Feature":
        """Row-wise boxed map (reference RichFeature.map)."""
        from ..stages.base import LambdaTransformer
        return LambdaTransformer(fn=fn, output_type=output_type
                                 ).set_input(self).get_output()

    def fill_missing_with_mean(self) -> "Feature":
        """(reference RichNumericFeature.fillMissingWithMean)"""
        from ..ops.dsl import FillMissingWithMean
        return FillMissingWithMean().set_input(self).get_output()

    def z_normalize(self) -> "Feature":
        """(reference RichNumericFeature.zNormalize:325)"""
        from ..ops.dsl import StandardScaler
        return StandardScaler().set_input(self).get_output()

    def pivot(self, top_k: int = 20, min_support: int = 10) -> "Feature":
        """One-hot pivot of a categorical text feature
        (reference RichTextFeature.pivot)."""
        from ..ops.categorical import OneHotVectorizer
        return OneHotVectorizer(top_k=top_k, min_support=min_support
                                ).set_input(self).get_output()

    def sanity_check(self, label: "Feature", **params) -> "Feature":
        """Prune this feature vector against the label
        (reference RichNumericFeature.sanityCheck:479)."""
        from ..checkers import SanityChecker
        return SanityChecker(**params).set_input(label, self).get_output()

    def alias(self, name: str) -> "Feature":
        """Rename via an identity stage (reference RichFeature.alias /
        AliasTransformer)."""
        from ..ops.dsl import AliasTransformer
        return AliasTransformer(alias=name, output_type=self.ftype
                                ).set_input(self).get_output()

    def bucketize(self, split_points, bucket_labels=None,
                  track_nulls: bool = True) -> "Feature":
        """One-hot bucket membership for a numeric feature
        (reference RichNumericFeature.bucketize)."""
        from ..ops.bucketizers import NumericBucketizer
        return NumericBucketizer(split_points=split_points,
                                 bucket_labels=bucket_labels,
                                 track_nulls=track_nulls
                                 ).set_input(self).get_output()

    def auto_bucketize(self, label: "Feature", **params) -> "Feature":
        """Label-aware decision-tree buckets
        (reference RichNumericFeature.autoBucketize)."""
        from ..ops.bucketizers import DecisionTreeNumericBucketizer
        return DecisionTreeNumericBucketizer(**params).set_input(
            label, self).get_output()

    def tokenize(self, **params) -> "Feature":
        """Text -> TextList tokens (reference RichTextFeature.tokenize)."""
        from ..ops.text import TextTokenizer
        return TextTokenizer(**params).set_input(self).get_output()

    def vectorize(self, track_nulls: bool = True) -> "Feature":
        """Default numeric vectorization with null tracking
        (reference RichNumericFeature.vectorize:325)."""
        from ..ops.numeric import RealVectorizer
        return RealVectorizer(track_nulls=track_nulls
                              ).set_input(self).get_output()

    def smart_vectorize(self, max_cardinality: int = 30, top_k: int = 20,
                        min_support: int = 10, num_hashes: int = 512,
                        track_nulls: bool = True) -> "Feature":
        """Pivot-or-hash text vectorization
        (reference RichTextFeature.smartVectorize)."""
        from ..ops.text import SmartTextVectorizer
        return SmartTextVectorizer(
            max_cardinality=max_cardinality, top_k=top_k,
            min_support=min_support, num_hashes=num_hashes,
            track_nulls=track_nulls).set_input(self).get_output()

    def combine(self, *others: "Feature") -> "Feature":
        """Concatenate OPVector features
        (reference RichVectorFeature.combine)."""
        from ..ops.combiner import VectorsCombiner
        return VectorsCombiner().set_input(self, *others).get_output()

    def lda(self, k: int = 10, **params) -> "Feature":
        """Topic-distribution vector from a token list
        (reference RichVectorFeature.lda)."""
        from ..ops.text_advanced import LDA
        return LDA(k=k, **params).set_input(self).get_output()

    # -- dunder ------------------------------------------------------------
    def __repr__(self) -> str:
        kind = "response" if self.is_response else "predictor"
        return (f"Feature[{self.ftype.__name__}]({self.name!r}, {kind}, "
                f"raw={self.is_raw})")

    def __hash__(self) -> int:
        return hash(self.uid)

    def __eq__(self, other) -> bool:
        return isinstance(other, Feature) and self.uid == other.uid


def _collect(root: Feature) -> List[Feature]:
    out: list[Feature] = []
    seen: set[str] = set()
    stack = [root]
    while stack:
        f = stack.pop()
        if f.uid in seen:
            continue
        seen.add(f.uid)
        out.append(f)
        stack.extend(f.parents)
    return out


def parent_stages(result_features: Sequence[Feature]
                  ) -> Dict["PipelineStage", int]:
    """Stage -> max distance from any result feature, with cycle check
    (reference FeatureLike.parentStages:363-430). Longest-path DP over the
    feature DAG in topological order."""
    color: dict[str, int] = {}   # 0/absent=white, 1=gray, 2=black
    post: list[Feature] = []     # post-order: parents before children

    def dfs(f: Feature):
        c = color.get(f.uid, 0)
        if c == 1:
            raise FeatureCycleError(f"Feature cycle detected at {f.name!r}")
        if c == 2:
            return
        color[f.uid] = 1
        for p in f.parents:
            dfs(p)
        color[f.uid] = 2
        post.append(f)

    for rf in result_features:
        dfs(rf)

    dist: dict[str, int] = {rf.uid: 0 for rf in result_features}
    for f in reversed(post):  # children before their parents
        d = dist.get(f.uid, 0)
        for p in f.parents:
            dist[p.uid] = max(dist.get(p.uid, -1), d + 1)

    out: dict = {}
    for f in post:
        if f.origin_stage is not None:
            s = f.origin_stage
            out[s] = max(out.get(s, -1), dist.get(f.uid, 0))
    return out


def topo_layers(result_features: Sequence[Feature]
                ) -> List[List["PipelineStage"]]:
    """Stages grouped into layers by decreasing distance from the results —
    the fit/transform execution order (reference
    FitStagesUtil.computeDAG:173)."""
    dist = parent_stages(result_features)
    if not dist:
        return []
    by_d: dict[int, list] = {}
    for s, d in dist.items():
        by_d.setdefault(d, []).append(s)
    return [sorted(by_d[d], key=lambda s: s.uid)
            for d in sorted(by_d, reverse=True)]
