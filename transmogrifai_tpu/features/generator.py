"""Raw-feature origin stage.

Reference: features/src/main/scala/com/salesforce/op/stages/
FeatureGeneratorStage.scala:61 — a zero-input stage holding the extract
function applied to each raw record, plus an optional monoid aggregator and
time window used by aggregate readers.
"""
from __future__ import annotations

from typing import Any, Callable, Optional, Type

from ..types import FeatureType
from .columns import FeatureColumn
from .feature import Feature
from ..stages.base import PipelineStage, _ZeroInput

__all__ = ["FeatureGeneratorStage"]


class FeatureGeneratorStage(PipelineStage, _ZeroInput):
    """Holds ``extract_fn: record -> value`` for one raw feature."""

    def __init__(self, name: str, ftype: Type[FeatureType],
                 extract_fn: Optional[Callable[[Any], Any]] = None,
                 is_response: bool = False,
                 aggregator: Optional[object] = None,
                 aggregate_window_ms: Optional[int] = None,
                 source_name: Optional[str] = None,
                 uid: Optional[str] = None):
        super().__init__(operation_name=f"generate_{name}", uid=uid)
        self.name = name
        self.ftype = ftype
        self.extract_fn = extract_fn or (lambda rec: _dict_get(rec, name))
        self.is_response = is_response
        #: monoid aggregator for keyed/aggregate readers
        #: (reference aggregators/MonoidAggregatorDefaults.scala:41)
        self.aggregator = aggregator
        self.aggregate_window_ms = aggregate_window_ms
        #: which side of a joined reader this feature extracts from
        #: (reference: the reader type parameter of FeatureBuilder[T];
        #: used by readers.joined.JoinedAggregateReaders)
        self.source_name = source_name

    def get_output(self) -> Feature:
        if self._output_feature is None:
            self._output_feature = Feature(
                name=self.name, ftype=self.ftype,
                is_response=self.is_response, origin_stage=self, parents=())
        return self._output_feature

    def extract_column(self, records) -> FeatureColumn:
        """Apply the extract function over records into a column."""
        return FeatureColumn.from_values(
            self.ftype, [self.extract_fn(r) for r in records])


def _dict_get(rec, name):
    if isinstance(rec, dict):
        return rec.get(name)
    return getattr(rec, name, None)
