"""Explainability (SURVEY §2.11; core/.../ModelInsights.scala:72,
core/.../insights/RecordInsightsLOCO.scala:54)."""
from .loco import RecordInsightsLOCO
from .model_insights import (DerivedFeatureInsight, FeatureInsights,
                             LabelSummary, ModelInsights,
                             extract_model_insights)

__all__ = ["RecordInsightsLOCO", "ModelInsights", "LabelSummary",
           "FeatureInsights", "DerivedFeatureInsight",
           "extract_model_insights"]
