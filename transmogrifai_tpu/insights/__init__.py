"""Explainability (SURVEY §2.11; core/.../ModelInsights.scala:72,
core/.../insights/RecordInsightsLOCO.scala:54)."""
from .corr import (RecordInsightsCorr, RecordInsightsCorrModel,
                   parse_insights)
from .loco import RecordInsightsLOCO
from .model_insights import (DerivedFeatureInsight, FeatureInsights,
                             LabelSummary, ModelInsights,
                             extract_model_insights)

__all__ = ["RecordInsightsLOCO", "RecordInsightsCorr",
           "RecordInsightsCorrModel", "parse_insights", "ModelInsights", "LabelSummary",
           "FeatureInsights", "DerivedFeatureInsight",
           "extract_model_insights"]
