"""RecordInsightsCorr: correlation-based per-record insights + parser.

TPU-native port of the reference RecordInsightsCorr
(core/src/main/scala/com/salesforce/op/stages/impl/insights/
RecordInsightsCorr.scala:56-160) and RecordInsightsParser.scala:46-90:

- fit: correlate every feature-vector column against every prediction
  column (Pearson or Spearman) over the training batch, and record a
  per-column normalizer (min-max or z) from the column stats — one
  device matmul for the whole correlation block instead of the
  reference's RDD ``Statistics.corr`` pass;
- transform: importance[p, j] = corr[p, j] * normalized_feature[j]
  (NaN -> 0); the top-K per prediction column land in a TextMap keyed
  by the column-metadata JSON, valued by ``[[pred_index, importance]]``
  JSON — the exact parser-compatible wire format of the reference.
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..features.columns import FeatureColumn, PredictionColumn
from ..stages.base import AllowLabelAsInput, BinaryEstimator, BinaryModel
from ..types import OPVector, Prediction, TextMap
from ..utils.vector_meta import VectorColumnMetadata, VectorMetadata

__all__ = ["RecordInsightsCorr", "RecordInsightsCorrModel",
           "parse_insights"]


def _prediction_matrix(col: FeatureColumn) -> np.ndarray:
    """(n, p) score matrix from a Prediction/OPVector column: the class
    probabilities when available, else the raw predictions."""
    if isinstance(col, PredictionColumn):
        if col.probability.shape[1]:
            return np.asarray(col.probability, dtype=np.float64)
        return np.asarray(col.data, dtype=np.float64).reshape(-1, 1)
    arr = np.asarray(col.data, dtype=np.float64)
    return arr if arr.ndim == 2 else arr.reshape(-1, 1)


def _rankdata(X: np.ndarray) -> np.ndarray:
    """Column-wise average ranks (Spearman support)."""
    order = np.argsort(X, axis=0, kind="stable")
    ranks = np.empty_like(X)
    n = X.shape[0]
    rng = np.arange(n, dtype=np.float64)
    for j in range(X.shape[1]):
        r = np.empty(n)
        r[order[:, j]] = rng
        # average ties
        vals = X[:, j]
        uniq, inv = np.unique(vals, return_inverse=True)
        sums = np.bincount(inv, weights=r)
        counts = np.bincount(inv)
        r = (sums / counts)[inv]
        ranks[:, j] = r
    return ranks


def _corr_block(P: np.ndarray, F: np.ndarray) -> np.ndarray:
    """(p, d) Pearson correlations via one centered matmul on device."""
    import jax.numpy as jnp
    Pc = P - P.mean(axis=0)
    Fc = F - F.mean(axis=0)
    Pn = np.sqrt((Pc ** 2).sum(axis=0))
    Fn = np.sqrt((Fc ** 2).sum(axis=0))
    num = np.asarray(jnp.asarray(Pc.T) @ jnp.asarray(Fc))
    den = np.outer(Pn, Fn)
    with np.errstate(divide="ignore", invalid="ignore"):
        return np.where(den > 0, num / den, np.nan)


class RecordInsightsCorr(AllowLabelAsInput, BinaryEstimator):
    """(reference RecordInsightsCorr.scala:56). Input 1 the prediction
    (response side), input 2 the feature vector."""

    input_types = (Prediction, OPVector)
    output_type = TextMap

    def __init__(self, top_k: int = 20, norm_type: str = "minmax",
                 correlation_type: str = "pearson",
                 uid: Optional[str] = None):
        super().__init__(operation_name="recordInsightsCorr", uid=uid)
        if norm_type not in ("minmax", "znorm"):
            raise ValueError(f"norm_type must be minmax|znorm, "
                             f"got {norm_type!r}")
        if correlation_type not in ("pearson", "spearman"):
            raise ValueError(f"correlation_type must be pearson|spearman, "
                             f"got {correlation_type!r}")
        self.top_k = top_k
        self.norm_type = norm_type
        self.correlation_type = correlation_type

    def fit_columns(self, cols: List[FeatureColumn]
                    ) -> "RecordInsightsCorrModel":
        P = _prediction_matrix(cols[0])
        F = np.asarray(cols[1].data, dtype=np.float64)
        if self.correlation_type == "spearman":
            corr = _corr_block(_rankdata(P), _rankdata(F))
        else:
            corr = _corr_block(P, F)
        if self.norm_type == "minmax":
            lo, hi = F.min(axis=0), F.max(axis=0)
            shift, scale = lo, np.where(hi > lo, hi - lo, 1.0)
        else:
            mu, sd = F.mean(axis=0), F.std(axis=0)
            shift, scale = mu, np.where(sd > 0, sd, 1.0)
        return RecordInsightsCorrModel(
            score_corr=corr, norm_shift=shift, norm_scale=scale,
            top_k=self.top_k,
            metadata=cols[1].metadata)


class RecordInsightsCorrModel(AllowLabelAsInput, BinaryModel):
    input_types = (Prediction, OPVector)
    output_type = TextMap

    def __init__(self, score_corr=None, norm_shift=None, norm_scale=None,
                 top_k: int = 20, metadata: Optional[VectorMetadata] = None,
                 uid: Optional[str] = None):
        super().__init__(operation_name="recordInsightsCorr", uid=uid)
        self.score_corr = np.asarray(score_corr, dtype=np.float64)
        self.norm_shift = np.asarray(norm_shift, dtype=np.float64)
        self.norm_scale = np.asarray(norm_scale, dtype=np.float64)
        self.top_k = int(top_k)
        self.metadata = metadata

    def _column_keys(self, d: int) -> List[str]:
        meta = self.metadata
        if meta is not None and meta.size == d:
            return [json.dumps(c.to_json(), sort_keys=True)
                    for c in meta.columns]
        return [json.dumps({"index": j, "parentFeatureName": f"column_{j}"},
                           sort_keys=True) for j in range(d)]

    def transform_columns(self, cols: List[FeatureColumn]) -> FeatureColumn:
        F = np.asarray(cols[1].data, dtype=np.float64)
        n, d = F.shape
        corr = np.nan_to_num(self.score_corr, nan=0.0)    # (p, d)
        normed = (F - self.norm_shift) / self.norm_scale  # (n, d)
        keys = self._column_keys(d)
        values = []
        for i in range(n):
            # importance per (pred column, feature column)
            imp = corr * normed[i][None, :]               # (p, d)
            per_col: Dict[int, List[Tuple[int, float]]] = {}
            for p in range(imp.shape[0]):
                top = np.argsort(-np.abs(imp[p]))[:self.top_k]
                for j in top:
                    per_col.setdefault(int(j), []).append(
                        (p, float(imp[p, j])))
            row = {keys[j]: json.dumps([[p, round(v, 9)] for p, v in seq])
                   for j, seq in per_col.items()}
            values.append(TextMap(row))
        return FeatureColumn.from_values(TextMap, values)


def parse_insights(insights: TextMap) -> Dict[str, List[Tuple[int, float]]]:
    """Parse an insights TextMap back into
    {column-info-json: [(prediction_index, importance)]}
    (reference RecordInsightsParser.parseInsights)."""
    out: Dict[str, List[Tuple[int, float]]] = {}
    value = insights.value if hasattr(insights, "value") else insights
    for k, v in (value or {}).items():
        out[k] = [(int(p), float(s)) for p, s in json.loads(v)]
    return out
