"""RecordInsightsLOCO: per-row leave-one-feature-out attributions.

TPU-native port of the reference RecordInsightsLOCO
(core/src/main/scala/com/salesforce/op/stages/impl/insights/
RecordInsightsLOCO.scala:54,68): for every row, zero out each column
group of the feature vector (groups = columns sharing a parent raw
feature, from the vector metadata), re-run the model, and report the
top-K score deltas. Where the reference loops per record through the
model's transformFn, here each group's counterfactual is a full batch
re-prediction — one matrix op per group instead of n*k scalar calls.
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..features.columns import FeatureColumn
from ..models.base import PredictionModel
from ..stages.base import UnaryTransformer
from ..types import OPVector, TextMap
from ..utils.vector_meta import VectorMetadata

__all__ = ["RecordInsightsLOCO"]


class RecordInsightsLOCO(UnaryTransformer):
    """(reference RecordInsightsLOCO.scala:54)"""

    input_types = (OPVector,)
    output_type = TextMap

    def __init__(self, model: Optional[PredictionModel] = None,
                 top_k: int = 20, uid: Optional[str] = None):
        super().__init__(operation_name="recordInsightsLOCO", uid=uid)
        self.model = model
        self.top_k = top_k

    def _score(self, X: np.ndarray,
               base_cls: Optional[np.ndarray] = None) -> np.ndarray:
        """Scalar score per row: probability of class 1 for binary
        classifiers, predicted value otherwise (reference diffs the
        prediction vector). ``base_cls`` fixes the class index scored for
        multiclass so counterfactuals are compared at the BASE
        prediction's class, not their own argmax."""
        out = self.model.predict_arrays(X)
        if out.probability.shape[1] == 2:
            return out.probability[:, 1]
        if out.probability.shape[1] > 2:
            cls = (out.data if base_cls is None else base_cls).astype(int)
            return out.probability[np.arange(len(out.data)), cls]
        return out.data

    def _groups(self, meta: Optional[VectorMetadata], d: int
                ) -> List[Tuple[str, List[int]]]:
        if meta is not None and meta.size == d:
            return list(meta.parent_groups().items())
        return [(f"column_{j}", [j]) for j in range(d)]

    def transform_columns(self, cols: List[FeatureColumn]) -> FeatureColumn:
        if self.model is None:
            raise ValueError("RecordInsightsLOCO requires a fitted model")
        vec = cols[0]
        X = np.asarray(vec.data, dtype=np.float64)
        n, d = X.shape
        meta = vec.metadata or getattr(self.model, "vector_metadata", None)
        base_out = self.model.predict_arrays(X)
        base_cls = base_out.data if base_out.probability.shape[1] > 2 \
            else None
        base = self._score(X, base_cls)
        groups = self._groups(meta, d)
        diffs = np.zeros((n, len(groups)))
        Xz = X.copy()  # one buffer; zero + restore each group's slice
        for g, (name, idxs) in enumerate(groups):
            saved = Xz[:, idxs].copy()
            Xz[:, idxs] = 0.0
            diffs[:, g] = base - self._score(Xz, base_cls)
            Xz[:, idxs] = saved
        k = min(self.top_k, len(groups))
        # top-K by |diff| per row
        order = np.argsort(-np.abs(diffs), axis=1)[:, :k]
        values = []
        for i in range(n):
            row: Dict[str, str] = {}
            for g in order[i]:
                name = groups[g][0]
                row[name] = json.dumps(round(float(diffs[i, g]), 9))
            values.append(TextMap(row))
        return FeatureColumn.from_values(TextMap, values)
