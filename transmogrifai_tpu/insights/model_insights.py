"""ModelInsights: post-hoc explainability report for a fitted workflow.

TPU-native port of the reference ModelInsights
(core/src/main/scala/com/salesforce/op/ModelInsights.scala:72,291,336,
390,435): walks the fitted DAG extracting

- label summary (name, distinct values / moments),
- per derived feature column: provenance (parent feature, indicator),
  sanity-checker statistics (variance, label correlation, Cramér's V,
  dropped + reasons), and model contribution (feature importances or
  coefficient magnitudes),
- the selected model's summary (winner, params, every validation
  result) when a ModelSelector produced the prediction.

``WorkflowModel.model_insights()`` is the user entry point (reference
OpWorkflowModel.modelInsights:162).
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

__all__ = ["ModelInsights", "LabelSummary", "FeatureInsights",
           "DerivedFeatureInsight", "extract_model_insights"]


@dataclass
class LabelSummary:
    """(reference ModelInsights label summary)"""
    name: str = ""
    is_response: bool = True
    distinct_count: Optional[int] = None
    mean: Optional[float] = None
    variance: Optional[float] = None
    sample_size: Optional[int] = None

    def to_json(self) -> dict:
        return {"name": self.name, "distinctCount": self.distinct_count,
                "mean": self.mean, "variance": self.variance,
                "sampleSize": self.sample_size}


@dataclass
class DerivedFeatureInsight:
    """One column of the final feature vector
    (reference Insights per derived feature)."""
    name: str
    index: int
    grouping: Optional[str] = None
    indicator_value: Optional[str] = None
    variance: Optional[float] = None
    corr_label: Optional[float] = None
    cramers_v: Optional[float] = None
    contribution: Optional[float] = None
    is_dropped: bool = False
    dropped_reasons: List[str] = field(default_factory=list)

    def to_json(self) -> dict:
        return {"name": self.name, "index": self.index,
                "grouping": self.grouping,
                "indicatorValue": self.indicator_value,
                "variance": self.variance, "corrLabel": self.corr_label,
                "cramersV": self.cramers_v,
                "contribution": self.contribution,
                "isDropped": self.is_dropped,
                "droppedReasons": list(self.dropped_reasons)}


@dataclass
class FeatureInsights:
    """All derived columns of one raw parent feature
    (reference FeatureInsights)."""
    feature_name: str
    feature_type: str = ""
    derived: List[DerivedFeatureInsight] = field(default_factory=list)
    #: RawFeatureFilter train/score distributions for this raw feature
    #: (reference ModelInsights feature distributions)
    distributions: List[dict] = field(default_factory=list)
    #: RawFeatureFilter exclusion reasons (non-empty = feature was
    #: blacklisted before training)
    exclusion_reasons: List[str] = field(default_factory=list)

    @property
    def total_contribution(self) -> float:
        return float(sum(abs(d.contribution or 0.0) for d in self.derived))

    def to_json(self) -> dict:
        return {"featureName": self.feature_name,
                "featureType": self.feature_type,
                "derivedFeatures": [d.to_json() for d in self.derived],
                "distributions": list(self.distributions),
                "exclusionReasons": list(self.exclusion_reasons)}


@dataclass
class ModelInsights:
    """(reference ModelInsights.scala:72)"""
    label: LabelSummary = field(default_factory=LabelSummary)
    features: List[FeatureInsights] = field(default_factory=list)
    selected_model: Optional[dict] = None
    stage_info: Dict[str, dict] = field(default_factory=dict)

    def to_json(self) -> dict:
        return {"label": self.label.to_json(),
                "features": [f.to_json() for f in self.features],
                "selectedModelInfo": self.selected_model,
                "stageInfo": self.stage_info}

    def pretty(self) -> str:
        """(reference summaryPretty via Table)"""
        lines = [f"Label: {self.label.name} "
                 f"(distinct={self.label.distinct_count}, "
                 f"mean={self.label.mean})"]
        if self.selected_model:
            lines.append(
                f"Model: {self.selected_model.get('bestModelName', '?')} "
                f"params={self.selected_model.get('bestModelParams', {})}")
        ranked = sorted(self.features, key=lambda f: -f.total_contribution)
        lines.append("Top feature contributions:")
        for f in ranked[:20]:
            lines.append(f"  {f.feature_name}: "
                         f"{f.total_contribution:.4f}")
        return "\n".join(lines)


def _model_contributions(model) -> Optional[np.ndarray]:
    """Per-column contribution from the inner prediction model:
    feature importances for trees, |coefficients| for linear models
    (reference Insights contribution extraction)."""
    inner = getattr(model, "inner", model)
    imp = getattr(inner, "feature_importances", None)
    if imp is not None and np.size(imp):
        return np.asarray(imp, dtype=np.float64)
    coef = getattr(inner, "coefficients", None)
    if coef is not None:
        c = np.asarray(coef, dtype=np.float64)
        return np.abs(c) if c.ndim == 1 else np.abs(c).sum(axis=0)
    return None


def extract_model_insights(wf_model) -> ModelInsights:
    """(reference ModelInsights.extractFromStages:435)"""
    from ..checkers.sanity_checker import SanityCheckerModel
    from ..models.base import PredictionModel
    from ..selector.selector import SelectedModel

    insights = ModelInsights()
    stages = wf_model.stages()

    # label
    responses = [f for f in wf_model.raw_features() if f.is_response]
    if responses:
        lbl = responses[0]
        insights.label.name = lbl.name
        ds = getattr(wf_model, "train_dataset", None)
        if ds is not None and lbl.name in ds:
            y = np.asarray(ds[lbl.name].data, dtype=np.float64)
            y = y[np.isfinite(y)]
            if y.size:
                insights.label.distinct_count = int(len(np.unique(y)))
                insights.label.mean = float(np.mean(y))
                insights.label.variance = float(np.var(y))
                insights.label.sample_size = int(y.size)

    checker: Optional[SanityCheckerModel] = None
    pred_model: Optional[PredictionModel] = None
    for s in stages:
        if isinstance(s, SanityCheckerModel):
            checker = s
        if isinstance(s, PredictionModel):
            pred_model = s
        info = {"className": type(s).__name__, "uid": s.uid}
        summ = getattr(s, "summary", None)
        if summ is not None and hasattr(summ, "to_json"):
            info["summary"] = summ.to_json()
        insights.stage_info[s.stage_name()] = info

    # derived feature columns: metadata of the matrix the model trained on
    meta = getattr(pred_model, "vector_metadata", None) if pred_model \
        else None
    contributions = _model_contributions(pred_model) if pred_model else None
    # checker stats matched by provenance (parent/grouping/indicator/
    # descriptor), which is stable across the index renumbering that
    # pruning applies to the model-side metadata
    checker_by_prov = {}
    checker_cols = []
    if checker is not None and checker.summary is not None:
        checker_cols = checker.summary.column_stats
        checker_by_prov = {c.provenance_key(): c for c in checker_cols
                           if c.parent_feature_name is not None}

    by_parent: Dict[str, FeatureInsights] = {}
    if meta is not None:
        for col in meta.columns:
            fi = by_parent.setdefault(
                col.parent_feature_name,
                FeatureInsights(feature_name=col.parent_feature_name,
                                feature_type=col.parent_feature_type))
            d = DerivedFeatureInsight(
                name=col.column_name(meta.name), index=col.index,
                grouping=col.grouping, indicator_value=col.indicator_value)
            if contributions is not None and col.index < contributions.size:
                d.contribution = float(contributions[col.index])
            cs = checker_by_prov.get(
                (col.parent_feature_name, col.grouping,
                 col.indicator_value, col.descriptor_value))
            if cs is not None:
                d.variance = cs.variance
                d.corr_label = cs.corr_label
                d.cramers_v = cs.cramers_v
            fi.derived.append(d)
    # columns the checker dropped never reach the model matrix — record them
    for cs in checker_cols:
        if cs.is_dropped:
            parent = cs.parent_feature_name or cs.name
            fi = by_parent.setdefault(
                parent, FeatureInsights(feature_name=parent))
            fi.derived.append(DerivedFeatureInsight(
                name=cs.name, index=cs.column_index,
                grouping=cs.grouping, indicator_value=cs.indicator_value,
                variance=cs.variance, corr_label=cs.corr_label,
                cramers_v=cs.cramers_v, is_dropped=True,
                dropped_reasons=list(cs.reasons)))
    insights.features = list(by_parent.values())

    # RawFeatureFilter results (reference ModelInsights.scala:72 —
    # distributions + exclusion reasons per raw feature; excluded
    # features have no derived columns but still appear)
    rff = getattr(wf_model, "raw_feature_filter_results", None)
    if rff is not None:
        by_name = {fi.feature_name: fi for fi in insights.features}

        def entry(name: str) -> FeatureInsights:
            if name not in by_name:
                by_name[name] = FeatureInsights(feature_name=name)
                insights.features.append(by_name[name])
            return by_name[name]

        for dist in rff.train_distributions:
            entry(dist.name).distributions.append(
                dict(dist.to_json(), split="train"))
        for dist in rff.score_distributions:
            entry(dist.name).distributions.append(
                dict(dist.to_json(), split="score"))
        for exc in rff.exclusions:
            entry(exc.name).exclusion_reasons.append(exc.reason)

    if isinstance(pred_model, SelectedModel) and pred_model.summary:
        insights.selected_model = pred_model.summary.to_json()
    return insights
