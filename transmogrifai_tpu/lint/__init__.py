"""``tx lint`` — pre-flight static analysis for feature DAGs and the JAX
compile path.

The reference framework's headline pillar is compile-time safety: a
typed ``Feature[T]`` DAG that fails before cluster time is spent. This
package restores that guarantee for the TPU port — and extends it to the
JAX layer — WITHOUT tracing, compiling or allocating a single device
buffer:

- DAG rules (``rules_dag``): label-leakage paths, cycles, dead stages,
  input-edge type contracts, untrained-estimator-in-score, duplicate
  stage uids, vector-metadata/column-count drift.
- JAX rules (``rules_jax``): AST analysis of jitted functions (host
  transfers, recompilation hazards, non-hashable statics, float64
  creep, traced-value control flow) plus a ``jax.eval_shape`` abstract
  probe for dynamic confirmation.

Entry points: ``python -m transmogrifai_tpu.cli lint`` (source rules,
CI gate), ``Workflow.train(validate="strict"|"warn"|"off")`` (DAG rules,
pre-flight), and the programmatic API below. Rule catalog and
suppression syntax: docs/lint.md.
"""
from .baseline import Baseline, DEFAULT_BASELINE_NAME
from .callgraph import (CallGraph, analyze_file, analyze_source,
                        build_graph)
from .engine import (LintCache, build_project_graph, default_cache_path,
                     format_json, format_text, lint_model, lint_paths,
                     lint_workflow, summarize)
from .findings import ERROR, RULES, WARNING, LintError, LintFinding
from .rules_dag import lint_dag
from .rules_jax import abstract_probe, lint_file, lint_source
from .rules_xproc import lint_cross_procedure

__all__ = [
    "LintFinding", "LintError", "RULES", "ERROR", "WARNING",
    "Baseline", "DEFAULT_BASELINE_NAME",
    "lint_dag", "lint_source", "lint_file", "abstract_probe",
    "lint_paths", "lint_workflow", "lint_model",
    "format_text", "format_json", "summarize",
    "CallGraph", "analyze_source", "analyze_file", "build_graph",
    "lint_cross_procedure", "LintCache", "build_project_graph",
    "default_cache_path",
]
