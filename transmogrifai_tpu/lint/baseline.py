"""Baseline + inline suppression for lint findings.

Two mechanisms, mirroring mature analyzers:

- **Inline**: a ``# tx-lint: disable=TX-J01`` (or ``disable`` for all
  rules) comment on the offending line suppresses source findings there.
- **Baseline file** (``.txlint-baseline.json``): a recorded set of
  finding fingerprints (rule + file/subject + message, line-independent)
  that are accepted debt; ``cli lint --write-baseline`` records the
  current findings, subsequent runs report only NEW findings. An entry
  that no longer matches anything is reported by ``--format json`` as
  ``stale_baseline`` so the file can be re-tightened.
"""
from __future__ import annotations

import json
import os
import re
from typing import Iterable, List, Sequence, Tuple

from .findings import LintFinding

__all__ = ["Baseline", "is_suppressed_inline", "DEFAULT_BASELINE_NAME"]

DEFAULT_BASELINE_NAME = ".txlint-baseline.json"

_DISABLE_RE = re.compile(
    r"#\s*tx-lint:\s*disable(?:=(?P<rules>[A-Z0-9,\-\s]+))?")


def is_suppressed_inline(source_line: str, rule_id: str) -> bool:
    """True when the line carries a ``# tx-lint: disable[=RULES]``
    comment naming this rule (or naming no rule = all rules)."""
    m = _DISABLE_RE.search(source_line)
    if not m:
        return False
    rules = m.group("rules")
    if rules is None:
        return True
    return rule_id in {r.strip() for r in rules.split(",")}


class Baseline:
    """A set of accepted finding fingerprints."""

    def __init__(self, fingerprints: Iterable[str] = ()):
        self.fingerprints = set(fingerprints)

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not os.path.exists(path):
            return cls()
        with open(path) as fh:
            data = json.load(fh)
        return cls(data.get("suppressed", []))

    @staticmethod
    def write(path: str, findings: Sequence[LintFinding]) -> None:
        payload = {
            "version": 1,
            "comment": "accepted tx-lint findings; regenerate with "
                       "`python -m transmogrifai_tpu.cli lint "
                       "--write-baseline`",
            "suppressed": sorted({f.fingerprint() for f in findings}),
        }
        with open(path, "w") as fh:
            json.dump(payload, fh, indent=1)
            fh.write("\n")

    def split(self, findings: Sequence[LintFinding]
              ) -> Tuple[List[LintFinding], List[str]]:
        """(new findings not in the baseline, stale fingerprints no
        finding matched)."""
        seen = {f.fingerprint() for f in findings}
        fresh = [f for f in findings
                 if f.fingerprint() not in self.fingerprints]
        stale = sorted(self.fingerprints - seen)
        return fresh, stale
