"""Project-wide symbol table and call graph for the cross-procedure
lint rules (lint/rules_xproc.py).

The per-file rules in rules_jax.py see one function body at a time, so
a one-level helper defeats every "no X inside Y" rule.  This module
builds the whole-program view those rules need:

- **Symbol table** — every module-level ``def``/``async def`` and every
  method, under a dotted qualname (``pkg.mod.Class.method``; nested
  defs as ``outer.inner``).
- **Call graph** — edges resolved through imports (absolute, relative,
  one-hop ``__init__`` re-exports), ``self.method()``, receiver-class
  heuristics (parameter annotations, ``x = ClassName(...)`` locals,
  ``self.attr = ClassName(...)`` constructor hints, unique-method-name
  fallback), and ``functools.partial`` unwrapping.
- **Submission edges** — ``executor.submit(fn)``,
  ``loop.run_in_executor(pool, fn)`` and ``threading.Thread(target=fn)``
  mark ``fn`` as *executor-thread* work; ``call_soon_threadsafe(fn)``
  and ``create_task(coro())`` mark *event-loop* work.  These are the
  edges the TX-X03 race detector colors contexts with — a plain call
  crosses no thread boundary, a submission does.
- **Async/sync coloring + reachability** — BFS with parent pointers so
  every finding carries the full call chain that proves it.

Per-file analysis results are plain JSON-able dicts (``FileSummary``)
so the engine's incremental cache can persist them keyed by content
hash; the graph itself is relinked from summaries on every run (pure
dict work, milliseconds for this repo).
"""
from __future__ import annotations

import ast
import os
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

__all__ = ["analyze_source", "analyze_file", "build_graph", "CallGraph",
           "FuncInfo", "Edge", "module_name_for", "SUMMARY_SCHEMA"]

#: bump when the FileSummary shape changes — the cache invalidates itself
SUMMARY_SCHEMA = 1

#: ``obj.meth()`` unique-name fallback never applies to these: they are
#: overwhelmingly builtin-container / stdlib methods and would create
#: bogus edges into whichever project class happens to share the name.
_COMMON_METHODS = frozenset({
    "append", "add", "get", "put", "pop", "update", "extend", "close",
    "write", "read", "items", "keys", "values", "join", "start", "run",
    "result", "set", "clear", "copy", "submit", "send", "recv", "sort",
    "split", "strip", "format", "encode", "decode", "load", "loads",
    "dump", "dumps", "wait", "cancel", "done", "count", "index",
    "remove", "insert", "flush", "seek", "tell", "mkdir", "exists",
    "popleft", "appendleft", "acquire", "release", "setdefault",
})

#: writes inside these methods are the sanctioned hot-swap channel
#: (PlanCache.swap_entry/rollback/commit — lint rule TX-R03's contract)
_BLESSED_METHODS = frozenset({"swap_entry", "rollback", "commit"})

#: call targets that are themselves blessed sinks: reachability passes
#: stop at the call instead of descending into the implementation,
#: whose internals (tmp files, lock files, trace-time clock reads) ARE
#: the sanctioned machinery, not violations
BLESSED_PERSIST_SINKS = ("atomic_write_json",)
BLESSED_TRACE_SINKS = ("compile_time.section",)


def module_name_for(path: str) -> str:
    """Dotted module name, walking up while ``__init__.py`` exists so
    ``.../transmogrifai_tpu/serving/server.py`` maps to
    ``transmogrifai_tpu.serving.server`` regardless of the scan root.
    Loose files (test fixtures in a tmp dir) map to their stem."""
    path = os.path.abspath(path)
    parts = [os.path.splitext(os.path.basename(path))[0]]
    d = os.path.dirname(path)
    while os.path.isfile(os.path.join(d, "__init__.py")):
        parts.append(os.path.basename(d))
        parent = os.path.dirname(d)
        if parent == d:  # filesystem root
            break
        d = parent
    parts.reverse()
    if parts and parts[-1] == "__init__":
        parts.pop()
    return ".".join(parts) or "<anonymous>"


def _expr_text(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - malformed node
        return ""


def _mentions(node: ast.AST, needles: Tuple[str, ...]) -> bool:
    text = _expr_text(node).lower()
    return any(n in text for n in needles)


def _dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` attribute chain as a dotted string, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class _FileVisitor(ast.NodeVisitor):
    """One pass over a module: symbols, raw (unresolved) call specs,
    submission sites, self-attribute writes, blocking/host-call/open
    sites, and the receiver-type hints the linker resolves with."""

    def __init__(self, modname: str, relpath: str):
        self.mod = modname
        self.relpath = relpath
        self.imports: Dict[str, str] = {}
        self.classes: Dict[str, dict] = {}
        self.funcs: Dict[str, dict] = {}
        self.attr_types: Dict[str, Dict[str, str]] = {}
        self.jit_assigns: List[str] = []
        self._scope: List[str] = []       # enclosing def qualnames
        self._class: List[str] = []       # enclosing class names
        self._cur: Optional[dict] = None  # current func record
        self._awaited: Set[int] = set()
        self._lockdepth = 0
        self._compiletime = 0

    # -- imports -----------------------------------------------------------
    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            self.imports[a.asname or a.name.split(".")[0]] = a.name

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        base = node.module or ""
        if node.level:
            pkg = self.mod.split(".")
            # strip the module leaf, then (level-1) more packages
            keep = len(pkg) - node.level
            if self.relpath.endswith("__init__.py"):
                keep += 1
            pkg = pkg[:max(keep, 0)]
            base = ".".join(pkg + ([base] if base else []))
        for a in node.names:
            if a.name == "*":
                continue
            self.imports[a.asname or a.name] = (
                f"{base}.{a.name}" if base else a.name)

    # -- symbols -----------------------------------------------------------
    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        bases = [b for b in (_dotted(x) for x in node.bases) if b]
        self.classes[node.name] = {"bases": bases, "line": node.lineno}
        self.attr_types.setdefault(node.name, {})
        self._class.append(node.name)
        prev, self._cur = self._cur, None
        for c in node.body:
            self.visit(c)
        self._cur = prev
        self._class.pop()

    def _enter_func(self, node, is_async: bool) -> None:
        if self._scope:
            qual = f"{self._scope[-1]}.{node.name}"
        elif self._class:
            qual = f"{self._class[-1]}.{node.name}"
        else:
            qual = node.name
        jitted = self._is_jit_decorated(node)
        rec = {
            "line": node.lineno, "async": is_async,
            "cls": self._class[-1] if self._class else None,
            "jitted": jitted, "calls": [], "submits": [], "writes": [],
            "blocking": [], "hostcalls": [], "openw": [],
            "var_types": {}, "assigns": {},
        }
        for arg in (list(node.args.posonlyargs) + list(node.args.args)
                    + list(node.args.kwonlyargs)):
            if arg.annotation is not None:
                t = _annotation_class(arg.annotation)
                if t:
                    rec["var_types"][arg.arg] = t
        self.funcs[qual] = rec
        prev, self._cur = self._cur, rec
        self._scope.append(qual)
        pcls = self._class
        self._class = []  # a nested class inside a def: out of scope
        for c in node.body:
            self.visit(c)
        self._class = pcls
        self._scope.pop()
        self._cur = prev

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._enter_func(node, is_async=False)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._enter_func(node, is_async=True)

    def _is_jit_decorated(self, node) -> bool:
        for d in node.decorator_list:
            txt = _expr_text(d)
            if txt in ("jit", "jax.jit") or txt.startswith(
                    ("jax.jit(", "jit(", "partial(jax.jit",
                     "functools.partial(jax.jit")):
                return True
        return False

    # -- statements inside functions ---------------------------------------
    def visit_Await(self, node: ast.Await) -> None:
        if isinstance(node.value, ast.Call):
            self._awaited.add(id(node.value))
        self.generic_visit(node)

    def visit_With(self, node: ast.With) -> None:
        self._with(node)

    def visit_AsyncWith(self, node: ast.AsyncWith) -> None:
        self._with(node)

    def _with(self, node) -> None:
        locked = any(_mentions(i.context_expr, ("lock", "mutex"))
                     for i in node.items)
        ct = any(_mentions(i.context_expr, ("compile_time",))
                 for i in node.items)
        for i in node.items:
            self.visit(i.context_expr)  # `with helper():` is a call
        self._lockdepth += locked
        self._compiletime += ct
        for c in node.body:
            self.visit(c)
        self._lockdepth -= locked
        self._compiletime -= ct

    def _record_write(self, target: ast.AST, line: int) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for t in target.elts:
                self._record_write(t, line)
            return
        node = target
        if isinstance(node, ast.Subscript):
            node = node.value
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self" and self._cur is not None):
            method = self._scope[-1].rsplit(".", 1)[-1] if self._scope \
                else ""
            blessed = bool(self._lockdepth) or method in _BLESSED_METHODS \
                or method == "__init__"
            self._cur["writes"].append([node.attr, line, blessed])

    def visit_Assign(self, node: ast.Assign) -> None:
        if self._cur is not None:
            for t in node.targets:
                self._record_write(t, node.lineno)
            self._collect_type_hint(node)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        if self._cur is not None:
            self._record_write(node.target, node.lineno)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if self._cur is not None:
            self._record_write(node.target, node.lineno)
            if isinstance(node.target, ast.Name):
                t = _annotation_class(node.annotation)
                if t:
                    self._cur["var_types"][node.target.id] = t
        self.generic_visit(node)

    def _collect_type_hint(self, node: ast.Assign) -> None:
        """``x = ClassName(...)`` / ``self.a = ClassName(...)`` receiver
        hints for the linker's method resolution."""
        if not isinstance(node.value, ast.Call):
            return
        cname = _dotted(node.value.func)
        if not cname:
            return
        leaf = cname.rsplit(".", 1)[-1]
        if not leaf or not leaf[0].isupper():
            return
        for t in node.targets:
            if isinstance(t, ast.Name) and self._cur is not None:
                self._cur["var_types"][t.id] = leaf
            elif (isinstance(t, ast.Attribute)
                  and isinstance(t.value, ast.Name)
                  and t.value.id == "self" and self._class):
                self.attr_types[self._class[-1]][t.attr] = leaf

    # -- calls -------------------------------------------------------------
    def visit_Call(self, node: ast.Call) -> None:
        self.generic_visit(node)
        line = node.lineno
        dotted = _dotted(node.func)
        # any jax.jit(fn) call marks fn jitted — module level,
        # method body, or a nested-closure compile (`jax.jit(run)`)
        if dotted in ("jax.jit", "jit") and node.args:
            target = _dotted(node.args[0])
            if target:
                leaf = target.rsplit(".", 1)[-1]
                if self._scope:
                    self.jit_assigns.append(
                        f"{self._scope[-1]}.{leaf}")
                self.jit_assigns.append(leaf)
        if self._cur is None:
            return
        rec = self._cur
        self._classify_special(node, dotted, line)
        if self._is_submission(node, dotted, line):
            return
        # plain call edge specs, resolved by the linker
        if isinstance(node.func, ast.Name):
            rec["calls"].append(["n", node.func.id, line])
        elif isinstance(node.func, ast.Attribute):
            meth = node.func.attr
            recv = node.func.value
            if isinstance(recv, ast.Name) and recv.id == "self":
                rec["calls"].append(["s", meth, line])
            elif dotted and dotted.count(".") >= 1:
                rec["calls"].append(["d", dotted, line])
            else:
                rec["calls"].append(["m", meth, line])
        # functools.partial(fn, ...) binds fn — keep a plain edge to it
        if dotted in ("functools.partial", "partial") and node.args:
            inner = _dotted(node.args[0])
            if inner:
                self._spec_for_target(node.args[0], line, "calls", "call")

    def _spec_for_target(self, tnode: ast.AST, line: int,
                         into: str, channel: str) -> None:
        """Record a reference to a function OBJECT (submit target,
        partial subject) as a call/submit spec."""
        rec = self._cur
        if isinstance(tnode, ast.Call):  # create_task(self._foo(...))
            tnode = tnode.func
        if isinstance(tnode, ast.Call):  # pragma: no cover - nested
            return
        d = _dotted(tnode)
        if d in ("functools.partial", "partial"):
            return
        if isinstance(tnode, ast.Name):
            spec = ["n", tnode.id, line]
        elif isinstance(tnode, ast.Attribute):
            recv = tnode.value
            if isinstance(recv, ast.Name) and recv.id == "self":
                spec = ["s", tnode.attr, line]
            elif d and d.count(".") >= 1:
                spec = ["d", d, line]
            else:
                spec = ["m", tnode.attr, line]
        else:
            return
        if into == "calls":
            rec["calls"].append(spec)
        else:
            rec["submits"].append(spec + [channel])

    def _is_submission(self, node: ast.Call, dotted: Optional[str],
                       line: int) -> bool:
        """Executor/thread/loop submission sites become channel-tagged
        edges instead of plain calls."""
        func = node.func
        attr = func.attr if isinstance(func, ast.Attribute) else None
        target: Optional[ast.AST] = None
        channel = "thread"
        if attr == "run_in_executor" and len(node.args) >= 2:
            target = node.args[1]
            # partial(fn, ...) as the submitted callable
            if isinstance(target, ast.Call):
                target = target.args[0] if target.args else None
        elif attr == "submit" and node.args:
            target = node.args[0]
            if isinstance(target, ast.Call):
                target = target.args[0] if target.args else None
        elif dotted and dotted.rsplit(".", 1)[-1] == "Thread":
            for kw in node.keywords:
                if kw.arg == "target":
                    target = kw.value
        elif attr in ("call_soon_threadsafe", "call_soon", "call_later"):
            channel = "loop"
            target = node.args[0] if node.args else None
            if attr == "call_later" and len(node.args) >= 2:
                target = node.args[1]
        elif attr in ("create_task", "ensure_future",
                      "run_coroutine_threadsafe", "run_until_complete") \
                or dotted in ("asyncio.run",):
            channel = "loop"
            target = node.args[0] if node.args else None
        else:
            return False
        if target is not None:
            self._spec_for_target(target, line, "submits", channel)
        return True

    def _classify_special(self, node: ast.Call, dotted: Optional[str],
                          line: int) -> None:
        """Blocking / host-transfer / file-write site collection."""
        rec = self._cur
        awaited = id(node) in self._awaited
        leaf = (dotted or "").rsplit(".", 1)[-1]
        attr = node.func.attr if isinstance(node.func, ast.Attribute) \
            else None
        # blocking primitives (TX-X01)
        if dotted in ("time.sleep",) or (leaf == "sleep" and not awaited):
            rec["blocking"].append(["sleep", line])
        elif attr == "block_until_ready":
            rec["blocking"].append(["block_until_ready", line])
            rec["hostcalls"].append(["block_until_ready", line])
        elif dotted == "open" or (isinstance(node.func, ast.Name)
                                  and node.func.id == "open"):
            rec["blocking"].append(["open", line])
            self._classify_open(node, line)
        # host transfer / clock / telemetry (TX-X02)
        if self._compiletime:
            return
        if attr == "item" and not node.args:
            rec["hostcalls"].append(["item", line])
        elif dotted in ("time.time", "time.perf_counter",
                        "time.monotonic", "time.process_time"):
            rec["hostcalls"].append([dotted, line])
        elif attr in ("event", "count") and _mentions(
                node.func, ("telemetry",)):
            rec["hostcalls"].append([f"telemetry.{attr}", line])
        elif attr == "span" and _mentions(node.func, ("trace", "tracer")):
            rec["hostcalls"].append(["trace.span", line])

    def _classify_open(self, node: ast.Call, line: int) -> None:
        """Write-mode ``open()`` for TX-X04, with the tmp-/lock-marked
        exemptions (one level of local-assignment resolution)."""
        mode = None
        if len(node.args) >= 2 and isinstance(node.args[1], ast.Constant):
            mode = node.args[1].value
        for kw in node.keywords:
            if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                mode = kw.value.value
        if not (isinstance(mode, str) and any(c in mode for c in "waxWAX")):
            return
        path_arg = node.args[0] if node.args else None
        if path_arg is None:
            return
        exempt_markers = ("tmp", "temp", ".lock", "staging")
        if _mentions(path_arg, exempt_markers):
            return
        if isinstance(path_arg, ast.Name) and self._cur is not None:
            src = self._cur["assigns"].get(path_arg.id)
            if src and any(m in src.lower() for m in exempt_markers):
                return
        self._cur["openw"].append([line, mode])

    def generic_visit(self, node: ast.AST) -> None:
        # remember local `name = <expr>` text for the open() path
        # resolution above, before descending
        if (isinstance(node, ast.Assign) and self._cur is not None
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            self._cur["assigns"][node.targets[0].id] = \
                _expr_text(node.value)[:200]
        super().generic_visit(node)


def _annotation_class(node: ast.AST) -> Optional[str]:
    """'ClassName' from a parameter annotation (`x: Foo`, `x: "Foo"`,
    `x: Optional[Foo]`)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        name = node.value.strip().rsplit(".", 1)[-1]
        return name if name[:1].isupper() or name[:1] == "_" else None
    d = _dotted(node)
    if d:
        leaf = d.rsplit(".", 1)[-1]
        return leaf if leaf[:1].isupper() or leaf[:1] == "_" else None
    if isinstance(node, ast.Subscript):  # Optional[Foo] / List[Foo]
        return _annotation_class(node.slice)
    return None


def analyze_source(source: str, path: str,
                   relpath: Optional[str] = None) -> dict:
    """Parse one file into its JSON-able ``FileSummary``. A syntax
    error yields a summary with no symbols (rules_jax's TX-E00 already
    reports the parse failure)."""
    rel = relpath or path
    mod = module_name_for(path)
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError:
        return {"mod": mod, "path": rel, "imports": {}, "classes": {},
                "funcs": {}, "attr_types": {}, "jit_assigns": []}
    v = _FileVisitor(mod, rel)
    v.visit(tree)
    for rec in v.funcs.values():
        rec.pop("assigns", None)
    return {"mod": mod, "path": rel, "imports": v.imports,
            "classes": v.classes, "funcs": v.funcs,
            "attr_types": v.attr_types, "jit_assigns": v.jit_assigns}


def analyze_file(path: str, relpath: Optional[str] = None) -> dict:
    with open(path, encoding="utf-8") as fh:
        return analyze_source(fh.read(), path, relpath=relpath)


# ---------------------------------------------------------------------------
# the linked graph
# ---------------------------------------------------------------------------

class FuncInfo:
    __slots__ = ("gid", "mod", "qual", "path", "line", "is_async",
                 "cls", "jitted", "writes", "blocking", "hostcalls",
                 "openw")

    def __init__(self, gid: str, mod: str, qual: str, rec: dict,
                 path: str):
        self.gid = gid
        self.mod = mod
        self.qual = qual
        self.path = path
        self.line = rec["line"]
        self.is_async = rec["async"]
        self.cls = rec["cls"]
        self.jitted = rec["jitted"]
        self.writes = rec["writes"]
        self.blocking = rec["blocking"]
        self.hostcalls = rec["hostcalls"]
        self.openw = rec["openw"]

    @property
    def name(self) -> str:
        return self.qual.rsplit(".", 1)[-1]

    def label(self) -> str:
        kind = "async " if self.is_async else ""
        return f"{kind}{self.mod}.{self.qual} ({self.path}:{self.line})"


class Edge:
    __slots__ = ("src", "dst", "kind", "line")

    def __init__(self, src: str, dst: str, kind: str, line: int):
        self.src = src      # caller gid
        self.dst = dst      # callee gid
        self.kind = kind    # "call" | "thread" | "loop"
        self.line = line


class CallGraph:
    """Linked whole-program view over a set of ``FileSummary`` dicts."""

    def __init__(self) -> None:
        self.functions: Dict[str, FuncInfo] = {}
        self.out: Dict[str, List[Edge]] = {}
        self._by_method: Dict[str, List[str]] = {}
        self._class_mods: Dict[str, List[str]] = {}

    # -- queries -----------------------------------------------------------
    def edges_from(self, gid: str) -> List[Edge]:
        return self.out.get(gid, [])

    def lookup(self, needle: str) -> List[FuncInfo]:
        """Symbols whose dotted name contains ``needle`` (for
        ``tx lint --graph``)."""
        hits = [f for f in self.functions.values()
                if needle in f"{f.mod}.{f.qual}"]
        return sorted(hits, key=lambda f: (f.mod, f.qual))

    def reachable(self, roots: Sequence[str], *,
                  follow_async: bool = True,
                  kinds: Tuple[str, ...] = ("call",),
                  stop_at: Tuple[str, ...] = (),
                  ) -> Dict[str, List[str]]:
        """BFS over edges of the given kinds. Returns
        ``{gid: [root_gid, ..., gid]}`` — the shortest call chain that
        reaches each function.  ``stop_at`` names blessed sinks the
        walk refuses to enter (matched against the function name and
        ``module_leaf.name``)."""
        chains: Dict[str, List[str]] = {}
        frontier: List[str] = []
        for r in roots:
            if r in self.functions and r not in chains:
                chains[r] = [r]
                frontier.append(r)
        while frontier:
            nxt: List[str] = []
            for gid in frontier:
                for e in self.out.get(gid, ()):
                    if e.kind not in kinds or e.dst in chains:
                        continue
                    dst = self.functions.get(e.dst)
                    if dst is None:
                        continue
                    if not follow_async and dst.is_async:
                        continue
                    if stop_at and (
                            dst.name in stop_at
                            or f"{dst.mod.rsplit('.', 1)[-1]}"
                               f".{dst.name}" in stop_at):
                        continue
                    chains[e.dst] = chains[gid] + [e.dst]
                    nxt.append(e.dst)
            frontier = nxt
        return chains

    def contexts(self) -> Tuple[Dict[str, List[str]],
                                Dict[str, List[str]]]:
        """Execution-context coloring: ``(loop, thread)`` maps of
        ``gid -> chain``.

        *Event-loop context*: every ``async def`` (a coroutine only ever
        runs on a loop), everything plain-called from one, and targets
        of ``call_soon_threadsafe``/``create_task``.  *Executor-thread
        context*: sync targets of ``submit``/``run_in_executor``/
        ``Thread(target=)`` plus their sync transitive callees.  An
        async def never acquires thread context — submitting a
        coroutine builder to a thread runs the builder, not the body."""
        loop_roots = [g for g, f in self.functions.items() if f.is_async]
        loop_cb = [e.dst for es in self.out.values() for e in es
                   if e.kind == "loop"]
        loop = self.reachable(loop_roots + loop_cb, follow_async=True)
        thread_roots = [
            e.dst for es in self.out.values() for e in es
            if e.kind == "thread"
            and e.dst in self.functions
            and not self.functions[e.dst].is_async]
        thread = self.reachable(thread_roots, follow_async=False)
        return loop, thread

    def chain_labels(self, chain: Sequence[str]) -> List[str]:
        return [self.functions[g].label() for g in chain
                if g in self.functions]


def build_graph(summaries: Sequence[dict]) -> CallGraph:
    """Link per-file summaries into one :class:`CallGraph`."""
    g = CallGraph()
    by_mod: Dict[str, dict] = {}
    for s in summaries:
        by_mod[s["mod"]] = s
        for qual, rec in s["funcs"].items():
            gid = f"{s['mod']}.{qual}"
            g.functions[gid] = FuncInfo(gid, s["mod"], qual, rec,
                                        s["path"])
            g.out[gid] = []
        for cname in s["classes"]:
            g._class_mods.setdefault(cname, []).append(s["mod"])
    # `jax.jit(f)` anywhere marks f jitted — candidates are recorded
    # as both `enclosing_scope.f` (nested closures) and bare `f`
    for s in summaries:
        for target in s["jit_assigns"]:
            gid = f"{s['mod']}.{target}"
            if gid in g.functions:
                g.functions[gid].jitted = True
    # method-name index for the unique-name fallback
    for gid, f in g.functions.items():
        if f.cls is not None:
            g._by_method.setdefault(f.name, []).append(gid)

    def resolve_import(mod: str, sym: str, depth: int = 0
                       ) -> Optional[str]:
        """symbol target "pkg.mod.sym" -> gid, following one-hop
        __init__ re-exports."""
        s = by_mod.get(mod)
        if s is None:
            return None
        if sym in s["funcs"]:
            return f"{mod}.{sym}"
        if sym in s["classes"]:
            return None  # constructor call, not a function edge
        if depth < 4 and sym in s["imports"]:
            tgt = s["imports"][sym]
            m2, _, s2 = tgt.rpartition(".")
            return resolve_import(m2, s2, depth + 1) if m2 else None
        return None

    def class_method(cname: str, meth: str, seen: Optional[Set[str]]
                     = None) -> Optional[str]:
        seen = seen or set()
        if cname in seen:
            return None
        seen.add(cname)
        for mod in g._class_mods.get(cname, ()):
            gid = f"{mod}.{cname}.{meth}"
            if gid in g.functions:
                return gid
            bases = by_mod[mod]["classes"][cname]["bases"]
            for b in bases:
                hit = class_method(b.rsplit(".", 1)[-1], meth, seen)
                if hit:
                    return hit
        return None

    def unique_method(meth: str) -> Optional[str]:
        if meth.startswith("__") or meth in _COMMON_METHODS:
            return None
        hits = g._by_method.get(meth, ())
        return hits[0] if len(hits) == 1 else None

    def resolve(s: dict, qual: str, spec: List[Any]
                ) -> Optional[str]:
        kind = spec[0]
        rec = s["funcs"][qual]
        if kind == "n":
            name = spec[1]
            # nested def of this function, then enclosing scopes
            parts = qual.split(".")
            for i in range(len(parts), 0, -1):
                cand = ".".join(parts[:i] + [name])
                if cand in s["funcs"]:
                    return f"{s['mod']}.{cand}"
            if name in s["funcs"]:
                return f"{s['mod']}.{name}"
            if name in s["imports"]:
                tgt = s["imports"][name]
                mod, _, sym = tgt.rpartition(".")
                return resolve_import(mod, sym) if mod else None
            return None
        if kind == "s":
            cls = rec["cls"]
            if cls:
                hit = class_method(cls, spec[1])
                if hit:
                    return hit
            return unique_method(spec[1])
        if kind == "d":
            dotted = spec[1]
            head, rest = dotted.split(".", 1)
            if head == "self" and rec["cls"]:
                # self.attr.meth() via the constructor hints
                if rest.count(".") == 1:
                    attr, meth = rest.split(".")
                    t = s["attr_types"].get(rec["cls"], {}).get(attr)
                    if t:
                        hit = class_method(t, meth)
                        if hit:
                            return hit
                return unique_method(dotted.rsplit(".", 1)[-1])
            if head in rec["var_types"] and rest.count(".") == 0:
                hit = class_method(rec["var_types"][head], rest)
                if hit:
                    return hit
            if head in s["imports"]:
                base = s["imports"][head]
                mod, _, sym = (base + "." + rest).rpartition(".")
                hit = resolve_import(mod, sym)
                if hit:
                    return hit
            return unique_method(dotted.rsplit(".", 1)[-1])
        if kind == "m":
            return unique_method(spec[1])
        return None

    for s in summaries:
        for qual, rec in s["funcs"].items():
            src = f"{s['mod']}.{qual}"
            for spec in rec["calls"]:
                dst = resolve(s, qual, spec)
                if dst and dst != src:
                    g.out[src].append(Edge(src, dst, "call", spec[-1]))
            for spec in rec["submits"]:
                channel = spec[-1]
                dst = resolve(s, qual, spec[:-1])
                if dst and dst != src:
                    g.out[src].append(
                        Edge(src, dst, channel, spec[-2]))
    return g
