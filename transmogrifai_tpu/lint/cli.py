"""CLI glue for ``python -m transmogrifai_tpu.cli lint``.

Exit codes (stable contract, used by CI):

- **0** — clean (no findings after baseline/suppressions)
- **1** — findings reported
- **2** — internal error (bad paths, unreadable baseline, crash)
"""
from __future__ import annotations

import os
import sys

from .baseline import Baseline, DEFAULT_BASELINE_NAME
from .engine import format_json, format_text, lint_paths
from .findings import RULES

__all__ = ["add_lint_parser", "run_lint"]

#: default lint target: the package's own source tree
_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def add_lint_parser(sub) -> None:
    lint = sub.add_parser(
        "lint",
        help="static pre-flight analysis of the JAX compile path "
             "(exit 0 clean / 1 findings / 2 internal error)")
    lint.add_argument("paths", nargs="*", default=None,
                      help=f"files/directories to analyze "
                           f"(default: {os.path.basename(_PKG_ROOT)} "
                           f"package source)")
    lint.add_argument("--format", choices=["text", "json"], default="text",
                      help="output format (default: text)")
    lint.add_argument("--baseline", default=None,
                      help=f"baseline file of accepted findings "
                           f"(default: ./{DEFAULT_BASELINE_NAME} when "
                           f"present)")
    lint.add_argument("--write-baseline", action="store_true",
                      help="record current findings as the new baseline "
                           "and exit 0")
    lint.add_argument("--list-rules", action="store_true",
                      help="print the rule catalog and exit")


def run_lint(args) -> int:
    try:
        if args.list_rules:
            for rid, (sev, summary) in sorted(RULES.items()):
                print(f"{rid}  {sev:7s}  {summary}")
            return 0
        paths = args.paths or [_PKG_ROOT]
        baseline_path = args.baseline
        if baseline_path is None and os.path.exists(DEFAULT_BASELINE_NAME):
            baseline_path = DEFAULT_BASELINE_NAME
        baseline = Baseline.load(baseline_path) if baseline_path else None
        if args.write_baseline:
            findings, _ = lint_paths(paths, baseline=None)
            out = args.baseline or DEFAULT_BASELINE_NAME
            Baseline.write(out, findings)
            print(f"baseline written: {out} "
                  f"({len(findings)} finding(s) recorded)")
            return 0
        findings, stale = lint_paths(paths, baseline=baseline)
        if args.format == "json":
            print(format_json(findings, stale))
        else:
            print(format_text(findings, stale))
        return 1 if findings else 0
    except BrokenPipeError:  # pragma: no cover
        raise
    except Exception as e:
        print(f"tx-lint: internal error: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2
