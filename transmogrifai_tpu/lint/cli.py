"""CLI glue for ``python -m transmogrifai_tpu.cli lint``.

Exit codes (stable contract, used by CI):

- **0** — clean (no findings after baseline/suppressions)
- **1** — findings reported
- **2** — internal error (bad paths, unreadable baseline, crash)
"""
from __future__ import annotations

import os
import sys

from .baseline import Baseline, DEFAULT_BASELINE_NAME
from .engine import format_json, format_text, lint_paths
from .findings import RULES

__all__ = ["add_lint_parser", "run_lint"]

#: default lint target: the package's own source tree
_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def add_lint_parser(sub) -> None:
    lint = sub.add_parser(
        "lint",
        help="static pre-flight analysis of the JAX compile path "
             "(exit 0 clean / 1 findings / 2 internal error)")
    lint.add_argument("paths", nargs="*", default=None,
                      help=f"files/directories to analyze "
                           f"(default: {os.path.basename(_PKG_ROOT)} "
                           f"package source)")
    lint.add_argument("--format", choices=["text", "json"], default="text",
                      help="output format (default: text)")
    lint.add_argument("--baseline", default=None,
                      help=f"baseline file of accepted findings "
                           f"(default: ./{DEFAULT_BASELINE_NAME} when "
                           f"present)")
    lint.add_argument("--write-baseline", action="store_true",
                      help="record current findings as the new baseline "
                           "and exit 0")
    lint.add_argument("--list-rules", action="store_true",
                      help="print the rule catalog and exit")
    lint.add_argument("--graph", metavar="SYMBOL", default=None,
                      help="dump the call graph around SYMBOL "
                           "(substring match on the dotted qualname: "
                           "node, coloring, outgoing edges) and exit; "
                           "honors --format json (empty tags/calls "
                           "lists are omitted, like finding chains)")
    lint.add_argument("--changed", action="store_true",
                      help="report only findings touching files "
                           "changed vs git HEAD (+ untracked); the "
                           "whole-tree analysis still runs, through "
                           "the incremental cache, so cross-procedure "
                           "rules see every call edge")
    lint.add_argument("--cache", default=None, metavar="FILE",
                      help="incremental cache file (default: "
                           "TX_LINT_CACHE env or a per-target file "
                           "under the system tempdir; 'off' disables)")


def _git_changed_files() -> list:
    """Files changed vs HEAD plus untracked .py files — the PR-style
    lint scope for ``--changed``."""
    import subprocess
    out: list = []
    for cmd in (["git", "diff", "--name-only", "HEAD"],
                ["git", "ls-files", "--others", "--exclude-standard"]):
        try:
            res = subprocess.run(cmd, capture_output=True, text=True,
                                 timeout=30)
        except (OSError, subprocess.TimeoutExpired) as e:
            raise RuntimeError(
                f"--changed needs git ({' '.join(cmd)} failed: {e})")
        if res.returncode != 0:
            raise RuntimeError(
                f"--changed: {' '.join(cmd)} exited "
                f"{res.returncode}: {res.stderr.strip()}")
        out.extend(ln.strip() for ln in res.stdout.splitlines()
                   if ln.strip().endswith(".py"))
    return sorted(set(out))


def _graph_nodes(paths, symbol: str, cache_path):
    """(graph, matched nodes with tags/edges resolved) for --graph."""
    from .engine import build_project_graph
    g = build_project_graph(paths, cache_path=cache_path)
    hits = g.lookup(symbol)
    loop_ctx, thread_ctx = g.contexts()
    out = []
    for f in hits:
        tags = []
        if f.is_async:
            tags.append("async")
        if f.jitted:
            tags.append("jitted")
        if f.gid in loop_ctx:
            tags.append("event-loop")
        if f.gid in thread_ctx:
            tags.append("executor-thread")
        edges = []
        for e in g.edges_from(f.gid):
            dst = g.functions.get(e.dst)
            if dst is None:  # pragma: no cover - dangling edge
                continue
            kind = {"call": "calls", "thread": "submits-to-thread",
                    "loop": "schedules-on-loop"}[e.kind]
            edges.append((kind, dst, e.line))
        out.append((f, tags, edges))
    return out


def _dump_graph(paths, symbol: str, cache_path,
                fmt: str = "text") -> int:
    import json
    nodes = _graph_nodes(paths, symbol, cache_path)
    if not nodes:
        if fmt == "json":
            print(json.dumps({"symbol": symbol, "nodes": []}))
        else:
            print(f"no symbol matching {symbol!r}")
        return 1
    if fmt == "json":
        docs = []
        for f, tags, edges in nodes:
            # wire-format convention (matches LintFinding.to_json's
            # chain handling): empty collections are OMITTED, never
            # serialized as [] — leaf nodes carry no "calls" key, an
            # untagged node no "tags" key
            doc = {"name": f"{f.mod}.{f.qual}", "path": f.path,
                   "line": f.line}
            if tags:
                doc["tags"] = tags
            calls = [{"kind": kind, "target": f"{d.mod}.{d.qual}",
                      "line": line} for kind, d, line in edges]
            if calls:
                doc["calls"] = calls
            docs.append(doc)
        print(json.dumps({"symbol": symbol, "nodes": docs}, indent=1))
        return 0
    for f, tags, edges in nodes:
        print(f"{f.mod}.{f.qual}  ({f.path}:{f.line})"
              f"{'  [' + ', '.join(tags) + ']' if tags else ''}")
        for kind, dst, line in edges:
            print(f"    {kind:18s} {dst.mod}.{dst.qual} "
                  f"(line {line})")
    return 0


def run_lint(args) -> int:
    try:
        if args.list_rules:
            for rid, (sev, summary) in sorted(RULES.items()):
                print(f"{rid}  {sev:7s}  {summary}")
            return 0
        paths = args.paths or [_PKG_ROOT]
        cache_path = args.cache
        if cache_path == "off":
            cache_path = ""
        if args.graph:
            return _dump_graph(paths, args.graph, cache_path,
                               fmt=args.format)
        changed = _git_changed_files() if args.changed else None
        baseline_path = args.baseline
        if baseline_path is None and os.path.exists(DEFAULT_BASELINE_NAME):
            baseline_path = DEFAULT_BASELINE_NAME
        baseline = Baseline.load(baseline_path) if baseline_path else None
        if args.write_baseline:
            findings, _ = lint_paths(paths, baseline=None,
                                     cache_path=cache_path)
            out = args.baseline or DEFAULT_BASELINE_NAME
            Baseline.write(out, findings)
            print(f"baseline written: {out} "
                  f"({len(findings)} finding(s) recorded)")
            return 0
        findings, stale = lint_paths(paths, baseline=baseline,
                                     cache_path=cache_path,
                                     changed=changed)
        if args.format == "json":
            print(format_json(findings, stale))
        else:
            if changed is not None:
                print(f"changed-scope lint: {len(changed)} file(s) "
                      f"vs git HEAD")
            print(format_text(findings, stale))
        return 1 if findings else 0
    except BrokenPipeError:  # pragma: no cover
        raise
    except Exception as e:
        print(f"tx-lint: internal error: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2
