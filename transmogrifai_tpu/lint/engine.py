"""The lint engine: rule orchestration, suppression, output formats.

Front doors:

- :func:`lint_paths` — AST/JAX rules over source trees (what
  ``python -m transmogrifai_tpu.cli lint`` runs), followed by the
  whole-program cross-procedure pass (rules_xproc) over the linked
  call graph.
- :func:`lint_workflow` — DAG rules over a constructed (un-run)
  ``Workflow``; what ``Workflow.train(validate=...)`` calls pre-flight.
- :func:`lint_model` — DAG rules over a fitted ``WorkflowModel``
  (scoring contract: no unfitted estimators, metadata consistent).

All return plain ``LintFinding`` lists after applying inline
``# tx-lint: disable=...`` comments and the optional baseline file.

Incremental cache: per-file local findings and call-graph summaries
are persisted keyed by content hash (sha1), so a warm repo-wide run
re-parses only edited files — the graph relink and the cross-procedure
rules are pure dict work and rerun every time.  ``TX_LINT_CACHE``
overrides the cache file path; ``TX_LINT_CACHE=off`` disables it.
A cache document that fails schema or per-entry checksum validation
is treated as POISONED: it is discarded whole, the run falls back to
a full re-analysis, and the ``poisoned`` counter in the run stats is
raised loudly (stderr warning).
"""
from __future__ import annotations

import hashlib
import json
import os
import sys
import tempfile
from typing import Dict, List, Optional, Sequence, Tuple

from .baseline import Baseline, is_suppressed_inline
from .callgraph import SUMMARY_SCHEMA, analyze_source
from .findings import ERROR, LintFinding
from .rules_dag import lint_dag
from .rules_jax import lint_source
from .rules_xproc import lint_cross_procedure

__all__ = ["lint_paths", "lint_workflow", "lint_model", "iter_py_files",
           "format_text", "format_json", "summarize", "LintCache",
           "default_cache_path", "build_project_graph"]

_SKIP_DIRS = ("__pycache__", ".git", ".jax_cache", "node_modules")


def iter_py_files(paths: Sequence[str]) -> List[str]:
    """Expand files/directories into a sorted list of .py files.

    Follows directory symlinks but skips symlink LOOPS (a directory
    whose realpath was already visited) and deduplicates files reached
    through more than one link. A path that vanishes between listing
    and the existence check (deleted-file race) raises a clear
    ``FileNotFoundError`` instead of surfacing a low-level OSError
    later."""
    out: List[str] = []
    seen_real: set = set()
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p, followlinks=True):
                rp = os.path.realpath(root)
                if rp in seen_real:
                    dirs[:] = []  # symlink loop / revisit: skip subtree
                    continue
                seen_real.add(rp)
                dirs[:] = [d for d in dirs if d not in _SKIP_DIRS]
                out.extend(os.path.join(root, f) for f in files
                           if f.endswith(".py"))
        elif p.endswith(".py"):
            out.append(p)
        else:
            raise FileNotFoundError(f"not a .py file or directory: {p}")
    by_real: Dict[str, str] = {}
    for f in sorted(set(out)):
        by_real.setdefault(os.path.realpath(f), f)
    missing = [f for f in by_real.values() if not os.path.exists(f)]
    if missing:
        raise FileNotFoundError(
            f"file vanished while scanning (deleted mid-lint?): "
            f"{missing[0]}")
    return sorted(by_real.values())


# ---------------------------------------------------------------------------
# incremental cache
# ---------------------------------------------------------------------------

def default_cache_path(paths: Sequence[str]) -> str:
    """Stable per-target cache location under the system tempdir
    (``TX_LINT_CACHE`` overrides)."""
    env = os.environ.get("TX_LINT_CACHE")
    if env:
        return env
    key = "|".join(sorted(os.path.abspath(p) for p in paths))
    h = hashlib.sha1(key.encode()).hexdigest()[:12]
    return os.path.join(tempfile.gettempdir(), f"txlint-{h}.json")


def _entry_checksum(entry: dict) -> str:
    raw = json.dumps({k: entry[k] for k in ("hash", "summary",
                                            "findings")},
                     sort_keys=True)
    return hashlib.sha1(raw.encode()).hexdigest()[:16]


class LintCache:
    """On-disk per-file cache: content hash -> (local findings,
    call-graph summary). Self-invalidating on schema bumps; a
    checksum mismatch on ANY entry poisons the whole document."""

    SCHEMA = 1

    def __init__(self, path: Optional[str]):
        self.path = path  # None = disabled
        self.entries: Dict[str, dict] = {}
        self.stats = {"files": 0, "hits": 0, "misses": 0, "poisoned": 0}

    def load(self) -> None:
        if not self.path or not os.path.exists(self.path):
            return
        try:
            with open(self.path, encoding="utf-8") as fh:
                doc = json.load(fh)
        except (OSError, ValueError):
            self._poison("unreadable/corrupt JSON")
            return
        if (not isinstance(doc, dict)
                or doc.get("schema") != self.SCHEMA
                or doc.get("summary_schema") != SUMMARY_SCHEMA):
            # a schema bump is routine invalidation, not poisoning
            return
        entries = doc.get("files")
        if not isinstance(entries, dict):
            self._poison("missing file table")
            return
        for key, entry in entries.items():
            if (not isinstance(entry, dict)
                    or entry.get("sum") != _entry_checksum(entry)):
                self._poison(f"checksum mismatch for {key}")
                return
        self.entries = entries

    def _poison(self, why: str) -> None:
        self.entries = {}
        self.stats["poisoned"] += 1
        print(f"tx-lint: WARNING: cache poisoned ({why}) — "
              f"discarding {self.path} and re-analyzing everything",
              file=sys.stderr)

    def get(self, abspath: str, content_hash: str) -> Optional[dict]:
        entry = self.entries.get(abspath)
        if entry is not None and entry.get("hash") == content_hash:
            self.stats["hits"] += 1
            return entry
        self.stats["misses"] += 1
        return None

    def put(self, abspath: str, content_hash: str, summary: dict,
            findings: List[LintFinding]) -> dict:
        entry = {"hash": content_hash, "summary": summary,
                 "findings": [f.to_json() for f in findings]}
        entry["sum"] = _entry_checksum(entry)
        self.entries[abspath] = entry
        return entry

    def save(self, keep: Sequence[str]) -> None:
        if not self.path:
            return
        doc = {"schema": self.SCHEMA,
               "summary_schema": SUMMARY_SCHEMA,
               "files": {k: self.entries[k] for k in keep
                         if k in self.entries}}
        tmp = self.path + ".tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(doc, fh)
            os.replace(tmp, self.path)
        except OSError:  # pragma: no cover - read-only tempdir
            pass


def _apply_inline_suppressions(findings: List[LintFinding]
                               ) -> List[LintFinding]:
    """Drop findings whose source line opts out via ``# tx-lint:``."""
    kept: List[LintFinding] = []
    cache: dict = {}
    for f in findings:
        if f.path and f.line:
            lines = cache.get(f.path)
            if lines is None:
                try:
                    with open(f.path, encoding="utf-8") as fh:
                        lines = fh.readlines()
                except OSError:
                    lines = []
                cache[f.path] = lines
            if 0 < f.line <= len(lines) and is_suppressed_inline(
                    lines[f.line - 1], f.rule_id):
                continue
        kept.append(f)
    return kept


def _analyze_files(files: Sequence[str], cache: LintCache
                   ) -> Tuple[List[LintFinding], List[dict]]:
    """Per-file pass: local rules + call-graph summary, through the
    cache."""
    findings: List[LintFinding] = []
    summaries: List[dict] = []
    for path in files:
        abspath = os.path.abspath(path)
        try:
            with open(path, encoding="utf-8") as fh:
                source = fh.read()
        except OSError as e:
            raise FileNotFoundError(
                f"file vanished during lint (deleted mid-run?): "
                f"{path} ({e})") from e
        content_hash = hashlib.sha1(source.encode()).hexdigest()
        entry = cache.get(abspath, content_hash)
        if entry is None:
            local = lint_source(source, path)
            summary = analyze_source(source, path, relpath=path)
            entry = cache.put(abspath, content_hash, summary, local)
            findings.extend(local)
        else:
            findings.extend(LintFinding.from_json(d)
                            for d in entry["findings"])
        summaries.append(entry["summary"])
    cache.stats["files"] = len(files)
    return findings, summaries


def build_project_graph(paths: Sequence[str],
                        cache_path: Optional[str] = None):
    """Linked :class:`~.callgraph.CallGraph` for ``paths`` (what
    ``tx lint --graph`` inspects), through the incremental cache."""
    from .callgraph import build_graph
    files = iter_py_files(paths)
    cache = LintCache(_resolve_cache_path(paths, cache_path))
    cache.load()
    _, summaries = _analyze_files(files, cache)
    cache.save(keep=[os.path.abspath(f) for f in files])
    return build_graph(summaries)


def _resolve_cache_path(paths: Sequence[str],
                        cache_path: Optional[str]) -> Optional[str]:
    if cache_path is not None:
        return cache_path or None
    env = os.environ.get("TX_LINT_CACHE")
    if env in ("off", "0"):
        return None
    return default_cache_path(paths)


def lint_paths(paths: Sequence[str],
               baseline: Optional[Baseline] = None,
               *,
               cache_path: Optional[str] = None,
               changed: Optional[Sequence[str]] = None,
               stats_out: Optional[dict] = None,
               ) -> Tuple[List[LintFinding], List[str]]:
    """(findings, stale baseline fingerprints) for the source rules —
    the per-file AST rules plus the cross-procedure call-graph pass.

    ``cache_path``: explicit incremental-cache file ('' disables;
    default: ``TX_LINT_CACHE`` env or a per-target tempdir file).
    ``changed``: restrict REPORTING to these files (the analysis still
    covers the whole tree so call-graph rules see every edge): local
    findings in a changed file, plus cross-procedure findings whose
    call chain touches one.
    ``stats_out``: dict that receives the cache counters
    (files/hits/misses/poisoned).
    """
    files = iter_py_files(paths)
    cache = LintCache(_resolve_cache_path(paths, cache_path))
    cache.load()
    findings, summaries = _analyze_files(files, cache)
    findings.extend(lint_cross_procedure(summaries))
    cache.save(keep=[os.path.abspath(f) for f in files])
    if stats_out is not None:
        stats_out.update(cache.stats)
    if changed is not None:
        want = {os.path.abspath(c) for c in changed}

        def _touches(f: LintFinding) -> bool:
            if f.path and os.path.abspath(f.path) in want:
                return True
            return any(os.path.abspath(frame.rsplit("(", 1)[-1]
                                       .split(":")[0]) in want
                       for frame in f.chain if "(" in frame)
        findings = [f for f in findings if _touches(f)]
    findings = _apply_inline_suppressions(findings)
    if baseline is not None:
        return baseline.split(findings)
    return findings, []


def lint_workflow(workflow, extra_features: Sequence = ()
                  ) -> List[LintFinding]:
    """DAG rules over an un-trained workflow — pure graph walk, runs in
    milliseconds, touches no data and no device."""
    if not workflow.result_features:
        return [LintFinding(
            rule_id="TX-D03", severity=ERROR, subject="<workflow>",
            message="workflow has no result features",
            hint="call set_result_features(...) before train()")]
    return lint_dag(workflow.result_features,
                    extra_features=extra_features, scoring=False)


def lint_model(model, extra_features: Sequence = ()) -> List[LintFinding]:
    """DAG rules over a fitted WorkflowModel, scoring contract enforced
    (TX-D05: no unfitted estimator may remain)."""
    return lint_dag(model.result_features,
                    extra_features=extra_features, scoring=True)


# ---------------------------------------------------------------------------
# output formats
# ---------------------------------------------------------------------------

def summarize(findings: Sequence[LintFinding]) -> str:
    errors = sum(1 for f in findings if f.severity == ERROR)
    warnings = len(findings) - errors
    return f"{len(findings)} finding(s): {errors} error(s), " \
           f"{warnings} warning(s)"


def format_text(findings: Sequence[LintFinding],
                stale: Sequence[str] = ()) -> str:
    lines = [str(f) for f in findings]
    if findings:
        lines.append(summarize(findings))
    else:
        lines.append("clean: no lint findings")
    if stale:
        lines.append(f"note: {len(stale)} stale baseline entr"
                     f"{'y' if len(stale) == 1 else 'ies'} no longer "
                     f"match — regenerate with --write-baseline")
    return "\n".join(lines)


def format_json(findings: Sequence[LintFinding],
                stale: Sequence[str] = ()) -> str:
    errors = sum(1 for f in findings if f.severity == ERROR)
    return json.dumps({
        "findings": [f.to_json() for f in findings],
        "counts": {"total": len(findings), "errors": errors,
                   "warnings": len(findings) - errors},
        "stale_baseline": list(stale),
    }, indent=1)
