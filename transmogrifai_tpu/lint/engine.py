"""The lint engine: rule orchestration, suppression, output formats.

Front doors:

- :func:`lint_paths` — AST/JAX rules over source trees (what
  ``python -m transmogrifai_tpu.cli lint`` runs).
- :func:`lint_workflow` — DAG rules over a constructed (un-run)
  ``Workflow``; what ``Workflow.train(validate=...)`` calls pre-flight.
- :func:`lint_model` — DAG rules over a fitted ``WorkflowModel``
  (scoring contract: no unfitted estimators, metadata consistent).

All return plain ``LintFinding`` lists after applying inline
``# tx-lint: disable=...`` comments and the optional baseline file.
"""
from __future__ import annotations

import json
import os
from typing import List, Optional, Sequence, Tuple

from .baseline import Baseline, is_suppressed_inline
from .findings import ERROR, LintFinding
from .rules_dag import lint_dag
from .rules_jax import lint_file

__all__ = ["lint_paths", "lint_workflow", "lint_model", "iter_py_files",
           "format_text", "format_json", "summarize"]


def iter_py_files(paths: Sequence[str]) -> List[str]:
    """Expand files/directories into a sorted list of .py files."""
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = [d for d in dirs
                           if d not in ("__pycache__", ".git",
                                        ".jax_cache", "node_modules")]
                out.extend(os.path.join(root, f) for f in files
                           if f.endswith(".py"))
        elif p.endswith(".py"):
            out.append(p)
        else:
            raise FileNotFoundError(f"not a .py file or directory: {p}")
    missing = [p for p in out if not os.path.exists(p)]
    if missing:
        raise FileNotFoundError(f"no such file: {missing[0]}")
    return sorted(set(out))


def _apply_inline_suppressions(findings: List[LintFinding]
                               ) -> List[LintFinding]:
    """Drop findings whose source line opts out via ``# tx-lint:``."""
    kept: List[LintFinding] = []
    cache: dict = {}
    for f in findings:
        if f.path and f.line:
            lines = cache.get(f.path)
            if lines is None:
                try:
                    with open(f.path, encoding="utf-8") as fh:
                        lines = fh.readlines()
                except OSError:
                    lines = []
                cache[f.path] = lines
            if 0 < f.line <= len(lines) and is_suppressed_inline(
                    lines[f.line - 1], f.rule_id):
                continue
        kept.append(f)
    return kept


def lint_paths(paths: Sequence[str],
               baseline: Optional[Baseline] = None
               ) -> Tuple[List[LintFinding], List[str]]:
    """(findings, stale baseline fingerprints) for the source rules."""
    findings: List[LintFinding] = []
    for path in iter_py_files(paths):
        findings.extend(lint_file(path))
    findings = _apply_inline_suppressions(findings)
    if baseline is not None:
        return baseline.split(findings)
    return findings, []


def lint_workflow(workflow, extra_features: Sequence = ()
                  ) -> List[LintFinding]:
    """DAG rules over an un-trained workflow — pure graph walk, runs in
    milliseconds, touches no data and no device."""
    if not workflow.result_features:
        return [LintFinding(
            rule_id="TX-D03", severity=ERROR, subject="<workflow>",
            message="workflow has no result features",
            hint="call set_result_features(...) before train()")]
    return lint_dag(workflow.result_features,
                    extra_features=extra_features, scoring=False)


def lint_model(model, extra_features: Sequence = ()) -> List[LintFinding]:
    """DAG rules over a fitted WorkflowModel, scoring contract enforced
    (TX-D05: no unfitted estimator may remain)."""
    return lint_dag(model.result_features,
                    extra_features=extra_features, scoring=True)


# ---------------------------------------------------------------------------
# output formats
# ---------------------------------------------------------------------------

def summarize(findings: Sequence[LintFinding]) -> str:
    errors = sum(1 for f in findings if f.severity == ERROR)
    warnings = len(findings) - errors
    return f"{len(findings)} finding(s): {errors} error(s), " \
           f"{warnings} warning(s)"


def format_text(findings: Sequence[LintFinding],
                stale: Sequence[str] = ()) -> str:
    lines = [str(f) for f in findings]
    if findings:
        lines.append(summarize(findings))
    else:
        lines.append("clean: no lint findings")
    if stale:
        lines.append(f"note: {len(stale)} stale baseline entr"
                     f"{'y' if len(stale) == 1 else 'ies'} no longer "
                     f"match — regenerate with --write-baseline")
    return "\n".join(lines)


def format_json(findings: Sequence[LintFinding],
                stale: Sequence[str] = ()) -> str:
    errors = sum(1 for f in findings if f.severity == ERROR)
    return json.dumps({
        "findings": [f.to_json() for f in findings],
        "counts": {"total": len(findings), "errors": errors,
                   "warnings": len(findings) - errors},
        "stale_baseline": list(stale),
    }, indent=1)
