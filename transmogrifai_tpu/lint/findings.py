"""Structured lint findings — the analyzer's output model.

Every rule emits :class:`LintFinding` records: a stable rule id, a
severity, a location (``file:line`` for source findings, a feature/stage
uid for DAG findings), a human message and an actionable fix hint. The
records are JSON-serializable (``--format json``) and fingerprinted for
the baseline/suppression mechanism (lint/baseline.py).
"""
from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

__all__ = ["LintFinding", "LintError", "RULES", "ERROR", "WARNING",
           "rule_severity"]

ERROR = "error"
WARNING = "warning"

#: rule catalog: id -> (default severity, one-line summary). docs/lint.md
#: documents each in full; `cli lint --list-rules` prints this table.
RULES: Dict[str, tuple] = {
    # -- DAG rules (pure graph walk, no tracing) ---------------------------
    "TX-D01": (ERROR, "label-leakage path: a response feature reaches a "
                      "predictor's feature matrix"),
    "TX-D02": (ERROR, "feature DAG contains a cycle"),
    "TX-D03": (WARNING, "dead stage: a built feature does not contribute "
                        "to any result feature"),
    "TX-D04": (ERROR, "stage input edge violates the declared feature "
                      "type contract"),
    "TX-D05": (ERROR, "untrained estimator in a scoring DAG"),
    "TX-D06": (ERROR, "duplicate stage uid aliases fitted models"),
    "TX-D07": (ERROR, "vector metadata column count disagrees with the "
                      "model's feature dimension"),
    # -- JAX compile-path rules (AST + abstract eval, no device code) ------
    "TX-J01": (ERROR, "implicit host transfer inside a jitted function "
                      "(np.* call / .item() / float() on a traced value)"),
    "TX-J02": (WARNING, "recompilation hazard: jax.jit applied per call "
                        "instead of once"),
    "TX-J03": (ERROR, "non-hashable value passed for a static jit "
                      "argument"),
    "TX-J04": (WARNING, "float64 creep inside a jitted function"),
    "TX-J05": (ERROR, "Python control flow on a traced value inside a "
                      "jitted function (concrete-shape dependence)"),
    "TX-J06": (ERROR, "serving hot path: per-call jax.jit or a Python "
                      "per-row transform_value loop inside serving code"),
    "TX-J07": (WARNING, "hyperparameter-grid value flows into a static "
                        "jit argument or a memoized kernel-builder key "
                        "inside a fit kernel (G x F programs instead "
                        "of 1)"),
    "TX-J08": (WARNING, "shard_map/pjit body closes over an array-like "
                        "value instead of taking it through in_specs — "
                        "implicitly replicated in full to every device"),
    "TX-J09": (WARNING, "host feature materialization in the train hot "
                        "path: a transform_columns/transform_dataset "
                        "walk (or per-row transform_value loop) in "
                        "workflow/ code that the compiled PreparePlan "
                        "replaces; only the TX_PREPARE=host escape "
                        "hatch may stay, inline-suppressed"),
    "TX-J10": (ERROR, "blocking call inside a serving async handler: "
                      "time.sleep, a synchronous device "
                      ".block_until_ready()/np.asarray "
                      "materialization, or open() file I/O in an "
                      "async def under serving/ stalls the event loop "
                      "for every in-flight request — route blocking "
                      "work through an executor"),
    "TX-O01": (ERROR, "telemetry/trace emission inside a jitted "
                      "function body: telemetry.event/count, a span "
                      "enter/exit, or a wall-clock read (time.time/"
                      "perf_counter) runs at TRACE time, not run time "
                      "— it records compilation, fires once per "
                      "compile instead of once per call, and a "
                      "changing value bakes into the trace "
                      "(recompile); compile_time.section is the "
                      "blessed trace-cost probe"),
    # -- resilience rules (selector/serving hot paths only) ----------------
    "TX-R01": (ERROR, "except Exception / bare except in a selector or "
                      "serving hot path swallows XlaRuntimeError "
                      "without re-raise, quarantine or a recorded "
                      "fallback"),
    "TX-R02": (ERROR, "serving-path record drop without a recorded "
                      "reason: a silent continue / pass-only handler "
                      "on exception in serving/ or local/scoring.py "
                      "discards rows invisibly"),
    "TX-R03": (ERROR, "in-place mutation of a live serving cache entry "
                      "or model registry in serving/ — hot model "
                      "changes must go through PlanCache.swap_entry / "
                      "rollback / commit so in-flight batches keep a "
                      "consistent entry and rollback stays possible"),
    "TX-R04": (ERROR, "state-file write in serving/ that bypasses the "
                      "shared atomic writer: a bare open(path, 'w') to "
                      "a live (non-.tmp) path can leave a TORN "
                      "document if the process dies mid-write — write "
                      "through observability.store.atomic_write_json "
                      "(tmp file + os.replace)"),
    "TX-R05": (ERROR, "unbounded request queue in serving/: a bare "
                      "collections.deque() or asyncio.Queue() holding "
                      "requests grows without limit under overload — "
                      "first memory, then every queued request's "
                      "latency; bound it (maxlen=/maxsize=) and shed "
                      "overflow at the admission edge "
                      "(serving/admission.py) with a retry_after_ms "
                      "answer instead of queue-and-pray"),
    "TX-R06": (ERROR, "direct ScoringPlan(...).compile() in serving/ "
                      "or cli/ — bypasses the AOT artifact loader, so "
                      "a saved model's exported executables are "
                      "ignored and the serve process pays a cold XLA "
                      "compile per bucket; route through "
                      "artifacts.loader.load_or_compile "
                      "(docs/aot_artifacts.md)"),
    "TX-R07": (ERROR, "leaked connection writer in serving/: a "
                      "socket/stream writer stored in a dict with no "
                      "removal path (del/.pop/.popitem/.clear) "
                      "anywhere in the module — every client "
                      "disconnect leaks the entry and its socket fd "
                      "until the process exhausts file descriptors; "
                      "evict in the handler's finally "
                      "(serving/router.py FleetRouter.handle)"),
    # -- cross-procedure rules (whole-program call graph) ------------------
    "TX-X01": (ERROR, "blocking primitive (time.sleep, sync open() "
                      "file I/O, .block_until_ready(), un-awaited "
                      "sleep) reachable from a serving/ async handler "
                      "through any chain of sync helpers — "
                      "interprocedural TX-J10; the finding carries "
                      "the full call chain"),
    "TX-X02": (ERROR, "host transfer (.item(), .block_until_ready()) "
                      "or clock/telemetry emission reachable from "
                      "inside a jitted body through helper calls — "
                      "interprocedural TX-J01/TX-O01; it executes at "
                      "trace time and bakes into the program"),
    "TX-X03": (ERROR, "event-loop/thread race: an attribute of a "
                      "serving/ class written both from event-loop "
                      "context (coroutines + helpers they call) and "
                      "from executor-thread context (run_in_executor/"
                      "Thread/submit targets) without a blessed "
                      "channel (call_soon_threadsafe, the swap/"
                      "rollback/commit API, atomic_write_json, a "
                      "shared Lock) — both conflicting call chains "
                      "reported"),
    "TX-X04": (ERROR, "raw open(w/a/x) to a live path reachable from "
                      "a snapshot/fingerprint/profile persistence "
                      "entry point — interprocedural TX-R04: a crash "
                      "mid-write tears the document"),
    # -- tuning rules ------------------------------------------------------
    "TX-T01": (ERROR, "numeric literal default for a registered tunable "
                      "knob outside tuning/ — the knob's single source "
                      "of truth is the autotuning registry "
                      "(tuning/registry.py STATIC_DEFAULTS); read it "
                      "from there (or default the parameter to None "
                      "and resolve through TuningPolicy) so `tx tune` "
                      "overrides and the cost model actually govern "
                      "the knob"),
    "TX-T02": (ERROR, "hardcoded power-of-two bucket math (`1 << n`, "
                      "`2 ** n` with a computed exponent, `b *= 2` "
                      "grow loops) on row counts outside "
                      "plans/common.py / tuning/lattice.py — bucket "
                      "plans resolve through an explicit lattice now "
                      "(docs/ragged_batching.md), so local pow2 "
                      "arithmetic silently disagrees with a tuned "
                      "non-power-of-two ladder; call "
                      "plans.common.bucket_for/pad_rows (or the "
                      "tuning.lattice helpers) instead"),
    # -- plan IR rules (lowered StableHLO/HLO — analysis/rules.py) ---------
    "TX-P01": (ERROR, "host-transfer op (callback custom_call, infeed/"
                      "outfeed, send/recv) in a lowered scoring "
                      "program — the IR-level ground truth behind "
                      "TX-J01/TX-X02: every dispatch of this bucket "
                      "synchronizes with the host"),
    "TX-P02": (WARNING, "precision widening inside the lowered program: "
                        "the body computes at a wider float/int width "
                        "than any parameter carries (a kernel "
                        "composition upcast AST rule TX-J04 cannot "
                        "see) — memory + flops doubled for data the "
                        "inputs never had"),
    "TX-P03": (WARNING, "bucket-lattice coverage gap: recorded dispatch "
                        "occupancy at a bucket outside this plan's "
                        "ladder — that batch shape forces an unplanned "
                        "XLA compile at serve time"),
    "TX-P04": (ERROR, "padding-waste bound exceeded: per-bucket "
                      "padded_rows/real_rows against the ProfileStore "
                      "occupancy histogram is above the configured "
                      "waste ceiling (tuning knob audit.waste_ceiling) "
                      "— the bucket ladder burns device time scoring "
                      "padding"),
    "TX-P05": (WARNING, "stage classification drift: the plan's "
                        "lowering_reason classification disagrees with "
                        "the actual lowered IR (a 'device' stage whose "
                        "kernel no longer traces, or a 'no array "
                        "kernel' fallback whose stage now exposes "
                        "transform_arrays)"),
    # -- infrastructure ----------------------------------------------------
    "TX-E00": (ERROR, "source file does not parse"),
}


def rule_severity(rule_id: str) -> str:
    return RULES.get(rule_id, (ERROR,))[0]


@dataclass(frozen=True)
class LintFinding:
    """One defect found by one rule at one location."""
    rule_id: str
    message: str
    severity: str = ERROR
    #: source findings: repo-relative path + 1-based line
    path: Optional[str] = None
    line: int = 0
    #: DAG findings: the offending feature/stage uid (location stand-in)
    subject: Optional[str] = None
    hint: Optional[str] = None
    #: cross-procedure findings: the call chain that proves
    #: reachability, outermost entry point first, violating site last
    #: (a tuple of human-readable frames). Empty for local findings.
    chain: Tuple[str, ...] = ()

    def location(self) -> str:
        if self.path:
            return f"{self.path}:{self.line}" if self.line else self.path
        return self.subject or "<dag>"

    def fingerprint(self) -> str:
        """Stable identity for baseline suppression: rule + file/subject +
        message, deliberately excluding the line number so unrelated
        edits above a finding don't invalidate the baseline."""
        raw = "|".join((self.rule_id, self.path or self.subject or "",
                        self.message))
        return hashlib.sha1(raw.encode()).hexdigest()[:16]

    def to_json(self) -> dict:
        doc = {
            "rule": self.rule_id,
            "severity": self.severity,
            "location": self.location(),
            "path": self.path,
            "line": self.line,
            "subject": self.subject,
            "message": self.message,
            "hint": self.hint,
            "fingerprint": self.fingerprint(),
        }
        if self.chain:
            # only present for cross-procedure findings — existing
            # --format json consumers see an unchanged document
            doc["chain"] = list(self.chain)
        return doc

    @classmethod
    def from_json(cls, d: dict) -> "LintFinding":
        """Inverse of :meth:`to_json` (the incremental cache persists
        findings through this round trip)."""
        return cls(rule_id=d["rule"], message=d["message"],
                   severity=d.get("severity", ERROR),
                   path=d.get("path"), line=int(d.get("line") or 0),
                   subject=d.get("subject"), hint=d.get("hint"),
                   chain=tuple(d.get("chain") or ()))

    def __str__(self) -> str:
        hint = f"  [{self.hint}]" if self.hint else ""
        body = (f"{self.location()}: {self.severity}: "
                f"{self.rule_id}: {self.message}{hint}")
        if self.chain:
            body += "".join(f"\n    {'-> ' if i else 'via '}{frame}"
                            for i, frame in enumerate(self.chain))
        return body


class LintError(ValueError):
    """Raised by ``Workflow.train(validate='strict')`` when the pre-flight
    analyzer finds errors — BEFORE any data is read, any stage traced or
    any device buffer allocated."""

    def __init__(self, findings: List[LintFinding]):
        self.findings = list(findings)
        lines = "\n".join(f"  {f}" for f in self.findings)
        super().__init__(
            f"workflow failed pre-flight lint with "
            f"{len(self.findings)} finding(s):\n{lines}")
