"""DAG-level lint rules: pure graph walks over the Feature/stage DAG.

No data is read, no stage is traced, no device buffer is allocated —
every rule here works off the static metadata a constructed DAG already
carries (``Feature.parents`` / ``Feature.is_response`` /
``PipelineStage.static_input_types()``). This is the pre-flight
equivalent of the reference's compile-time type safety: the same defects
``train()`` would eventually hit after minutes of tracing are reported
in milliseconds.

Rules (catalog in lint/findings.py, prose in docs/lint.md):

- TX-D01 label leakage   - TX-D02 cycles        - TX-D03 dead stages
- TX-D04 type mismatch   - TX-D05 untrained     - TX-D06 duplicate uids
- TX-D07 vector metadata/model dimension mismatch
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..features.feature import Feature
from ..features.generator import FeatureGeneratorStage
from ..stages.base import AllowLabelAsInput, Estimator, PipelineStage
from .findings import ERROR, WARNING, LintFinding

__all__ = ["lint_dag", "collect_graph"]


def collect_graph(result_features: Sequence[Feature]
                  ) -> Tuple[List[Feature], List[PipelineStage],
                             List[Tuple[Feature, Feature]]]:
    """(features, stages, cycle back-edges) reachable from the results.

    Iterative DFS that records back edges instead of raising
    ``FeatureCycleError`` — the linter must report every problem, not
    die on the first."""
    feats: Dict[str, Feature] = {}
    stages: Dict[str, PipelineStage] = {}
    back_edges: List[Tuple[Feature, Feature]] = []
    color: Dict[str, int] = {}            # 1=on current path, 2=done

    for root in result_features:
        stack: List[Tuple[Feature, int]] = [(root, 0)]
        while stack:
            f, pi = stack.pop()
            if pi == 0:
                if color.get(f.uid) == 2:
                    continue
                color[f.uid] = 1
                feats[f.uid] = f
                if f.origin_stage is not None:
                    # uid collisions surface via TX-D06, keep the first
                    stages.setdefault(f.origin_stage.uid, f.origin_stage)
            if pi < len(f.parents):
                p = f.parents[pi]
                stack.append((f, pi + 1))
                if color.get(p.uid) == 1:
                    back_edges.append((f, p))    # cycle edge: skip descent
                elif color.get(p.uid) != 2:
                    stack.append((p, 0))
            else:
                color[f.uid] = 2
    return list(feats.values()), list(stages.values()), back_edges


def _is_predictor_like(stage: PipelineStage) -> bool:
    """Stages with the (label, feature-matrix) contract of the model
    layer — the sinks label leakage must never reach."""
    from ..models.base import PredictionModel, Predictor
    return isinstance(stage, (Predictor, PredictionModel))


def _taint(feats: List[Feature]) -> Dict[str, bool]:
    """feature uid -> True when a raw response is reachable upward
    WITHOUT crossing an ``AllowLabelAsInput`` stage (which consumes the
    label legitimately, e.g. SanityChecker). A tainted feature carries
    label information that a predictor must never see."""
    memo: Dict[str, bool] = {}

    def go(f: Feature, on_path: Set[str]) -> bool:
        if f.uid in memo:
            return memo[f.uid]
        if f.uid in on_path:        # cycle guard; TX-D02 reports it
            return False
        if f.is_raw:
            memo[f.uid] = bool(f.is_response)
            return memo[f.uid]
        if isinstance(f.origin_stage, AllowLabelAsInput):
            memo[f.uid] = False
            return False
        on_path = on_path | {f.uid}
        memo[f.uid] = any(go(p, on_path) for p in f.parents)
        return memo[f.uid]

    for f in feats:
        go(f, set())
    return memo


def _converter_hint(expected: type) -> Optional[str]:
    """Point at the matching ``types.conversions`` helper when one
    exists for the expected feature type (to_real, to_op_vector, ...)."""
    from ..types import conversions
    want = expected.__name__.lower()
    for name in conversions.__all__:
        if name.startswith("to_") and name[3:].replace("_", "") == want:
            return (f"convert the value in the extract/transform fn via "
                    f"types.conversions.{name}()")
    return None


def lint_dag(result_features: Sequence[Feature],
             extra_features: Sequence[Feature] = (),
             scoring: bool = False) -> List[LintFinding]:
    """Run every DAG rule; returns findings (empty = clean).

    ``extra_features`` are features the caller built that SHOULD
    contribute to the results — any that don't are dead stages (TX-D03),
    the classic "sanity-checked the vector but wired the unchecked one
    into the selector" bug. ``scoring=True`` additionally requires every
    estimator to be fitted (TX-D05) — the contract of a scoring DAG."""
    findings: List[LintFinding] = []
    feats, stages, back_edges = collect_graph(result_features)

    # TX-D02: cycles -------------------------------------------------------
    for child, ancestor in back_edges:
        findings.append(LintFinding(
            rule_id="TX-D02", severity=ERROR,
            subject=child.uid,
            message=f"feature cycle: {child.name!r} depends on "
                    f"{ancestor.name!r} which is also its descendant",
            hint="a stage output cannot be (transitively) its own input; "
                 "rebuild the offending feature instead of rewiring it "
                 "into its own ancestry"))

    # TX-D06: duplicate stage uids ----------------------------------------
    by_uid: Dict[str, PipelineStage] = {}
    for f in feats:
        s = f.origin_stage
        if s is None:
            continue
        other = by_uid.setdefault(s.uid, s)
        if other is not s:
            findings.append(LintFinding(
                rule_id="TX-D06", severity=ERROR, subject=s.uid,
                message=f"duplicate stage uid {s.uid!r}: "
                        f"{type(other).__name__} and {type(s).__name__} "
                        f"share it — fitted-model rewiring would alias "
                        f"them",
                hint="give each stage instance its own uid; don't reuse "
                     "one stage object with different inputs"))

    # TX-D04: input-edge type contract ------------------------------------
    for s in stages:
        if isinstance(s, FeatureGeneratorStage) or not s.input_features:
            continue
        expected = s.static_input_types()
        if expected is None:
            continue
        if len(expected) != len(s.input_features):
            findings.append(LintFinding(
                rule_id="TX-D04", severity=ERROR, subject=s.uid,
                message=f"{type(s).__name__} declares {len(expected)} "
                        f"inputs but is wired with "
                        f"{len(s.input_features)}",
                hint="re-wire the stage with set_input(...) matching its "
                     "arity"))
            continue
        for i, (f, t) in enumerate(zip(s.input_features, expected)):
            if t is not None and not issubclass(f.ftype, t):
                findings.append(LintFinding(
                    rule_id="TX-D04", severity=ERROR, subject=s.uid,
                    message=f"{type(s).__name__} input {i} "
                            f"({f.name!r}) must be {t.__name__}, got "
                            f"{f.ftype.__name__}",
                    hint=_converter_hint(t) or
                         f"produce a {t.__name__} feature upstream"))

    # TX-D01: label leakage into predictor feature matrices ----------------
    tainted = _taint(feats)
    for s in stages:
        if not _is_predictor_like(s) or len(s.input_features) != 2:
            continue
        label_f, matrix_f = s.input_features
        if matrix_f.is_response:
            findings.append(LintFinding(
                rule_id="TX-D01", severity=ERROR, subject=s.uid,
                message=f"{type(s).__name__} feature-matrix input "
                        f"{matrix_f.name!r} is itself a response — the "
                        f"model would train on the label",
                hint="wire the predictor matrix, not the label, as "
                     "input 2"))
        elif tainted.get(matrix_f.uid):
            findings.append(LintFinding(
                rule_id="TX-D01", severity=ERROR, subject=s.uid,
                message=f"label-leakage path: response feature(s) reach "
                        f"{type(s).__name__}'s feature matrix "
                        f"{matrix_f.name!r} without passing through a "
                        f"label-aware stage",
                hint="route label-consuming derivations through an "
                     "AllowLabelAsInput stage (e.g. sanity_check) or "
                     "drop the response from the matrix"))

    # TX-D05: untrained estimator in a scoring DAG -------------------------
    if scoring:
        for s in stages:
            if isinstance(s, Estimator):
                findings.append(LintFinding(
                    rule_id="TX-D05", severity=ERROR, subject=s.uid,
                    message=f"unfitted estimator {type(s).__name__} "
                            f"({s.uid}) in a scoring DAG — score() would "
                            f"fail after materializing the raw data",
                    hint="train the workflow first; score through the "
                         "WorkflowModel returned by train()"))

    # TX-D07: vector metadata vs model feature dimension -------------------
    for s in stages:
        meta = getattr(s, "vector_metadata", None)
        coef = getattr(s, "coefficients", None)
        if meta is None or coef is None or not hasattr(coef, "shape"):
            continue
        if not coef.shape:        # scalar coefficient: nothing to check
            continue
        dim = coef.shape[-1]
        if meta.size and dim != meta.size:
            findings.append(LintFinding(
                rule_id="TX-D07", severity=ERROR, subject=s.uid,
                message=f"{type(s).__name__} was fitted on {dim} "
                        f"columns but its vector metadata describes "
                        f"{meta.size} — insights/LOCO would mis-attribute "
                        f"every column",
                hint="regenerate the metadata with the matrix that "
                     "actually trained the model (vector surgery must "
                     "update both)"))

    # TX-D03: dead stages (declared features that feed nothing) ------------
    if extra_features:
        reachable = {f.uid for f in feats}
        for f in extra_features:
            if f.uid in reachable:
                continue
            origin = type(f.origin_stage).__name__ if f.origin_stage \
                else "raw feature"
            findings.append(LintFinding(
                rule_id="TX-D03", severity=WARNING, subject=f.uid,
                message=f"dead stage: feature {f.name!r} ({origin}) is "
                        f"built but does not contribute to any result "
                        f"feature",
                hint="wire it into the result DAG or delete it — a "
                     "common form is sanity-checking a vector but "
                     "feeding the UNchecked vector to the selector"))
    return findings
