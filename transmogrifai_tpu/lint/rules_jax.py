"""JAX compile-path lint rules: AST analysis + abstract shape probing.

Static analysis of the device hot path — the defects XLA only surfaces
after minutes of tracing (or never surfaces, silently recompiling every
call) are caught here in milliseconds:

- TX-J01 implicit host transfer inside a jitted function: ``np.*`` calls,
  ``.item()`` / ``.tolist()``, or ``float()/int()/bool()`` applied to a
  traced value — each forces a device->host sync per call.
- TX-J02 recompilation hazard: ``jax.jit`` applied inside a loop or a
  plain (non-memoized) function body builds a FRESH jitted callable per
  call, so XLA recompiles every time. The blessed repo idiom — a
  ``functools.lru_cache``'d builder returning ``jax.jit(...)`` — is
  recognized and allowed.
- TX-J03 non-hashable static argument: a list/dict/set passed for a
  parameter the jit declares static — TypeError at trace time, or (for
  a tuple-of-list) a silent cache miss per call.
- TX-J04 float64 creep: float64 dtypes requested inside a jitted
  function — on TPU this means silent f32 downcast (x64 off) or a 2x
  memory/bandwidth tax (x64 on).
- TX-J05 Python control flow on a traced value: ``if``/``while`` on a
  non-static parameter concretizes the tracer -> TracerBoolConversionError
  at trace time, i.e. concrete-shape dependence.
- TX-J06 serving hot path (``serving/`` files only): per-call
  ``jax.jit`` — a trace/compile per REQUEST — or a Python per-row loop
  over ``transform_value``, the exact pattern the compiled ScoringPlan
  exists to replace. The J02 per-call-jit patterns report as J06 (error
  severity) there.
- TX-R01 swallowed backend error (``selector/`` + ``serving/`` files
  only): an ``except Exception`` / bare ``except`` whose body neither
  re-raises nor routes the error through the fault runtime's recovery
  vocabulary (quarantine / classify_error / a recorded fallback /
  maybe_inject) — hides XlaRuntimeErrors, silently degrading searches
  to the slow path (docs/resilience.md).
- TX-R02 silent record drop (``serving/`` files + ``local/scoring.py``
  only): an except handler that drops the current record — a
  ``continue`` out of the scoring loop, or a ``pass``-only body inside
  a loop — without recording a reason (quarantine / telemetry count or
  event / a ``note_*``/``record*`` call / logging). Rows discarded on
  exception with no machine-readable trace are the serving twin of a
  swallowed XlaRuntimeError: traffic silently disappears
  (docs/serving_guardrails.md).
- TX-J07 grid value into a compile key: inside a fit kernel (a function
  with a ``grid`` parameter / a ``fold_grid`` name), a value derived
  from the hyperparameter grid passed for a ``static_argnames``
  parameter of a jitted function, or into an ``lru_cache``'d kernel
  builder — every grid point then keys a fresh XLA program (G x F
  compiles instead of 1). Grid values must flow as TRACED vectors
  (vmapped candidate lanes); only aggregate predicates over the whole
  grid (``any``/``all``/``len``/...) may shape statics, and the taint
  tracking deliberately stops at them and at non-trivial calls so the
  repo's grouped-statics idiom (trees/mlp static shape groups) stays
  legal.
- TX-J09 train hot path (``workflow/`` files only): host feature
  materialization reachable from ``Workflow.train()`` — a direct
  ``.transform_columns(...)`` call (the per-stage host walk the
  compiled PreparePlan replaces; stages with ``transform_arrays``
  kernels should execute fused on device, plans/prepare.py) or a
  Python per-row loop over ``transform_value``. The TX_PREPARE=host
  escape hatch is the ONLY blessed host walk and carries an inline
  suppression so the exemption is visible and reviewable.
- TX-J10 blocking call inside a serving ASYNC handler (``serving/``
  files only): ``time.sleep`` (the loop stalls for every in-flight
  request — ``await asyncio.sleep`` exists), a synchronous device
  materialization (``.block_until_ready()``, ``np.asarray``/
  ``np.array`` on device output), or file I/O (``open``) directly in
  an ``async def`` body. The serving loop (serving/server.py) routes
  ALL blocking work through named executors; an inline blocking call
  in a coroutine wedges the coalescer for every tenant at once.
  Nested SYNC functions inside an async def are exempt — that is
  exactly the run_in_executor idiom.
- TX-R04 torn state-file write (``serving/`` files only): an
  ``open(path, "w"|"a"|...)`` whose target is a LIVE path — not a
  ``*.tmp`` staging file — bypasses the repo's shared atomic writer
  (``observability/store.atomic_write_json``: temp file +
  ``os.replace``). A process killed mid-write (the exact event the
  preemption-tolerance stack exists for, docs/serving_restart.md)
  leaves a torn half-document where a snapshot/fingerprint used to
  be. Paths that mention ``tmp`` (a ``.tmp`` suffix concatenation, a
  ``tmp``-named variable, tempfile machinery) are the sanctioned
  staging idiom and stay legal; reads are untouched.
- TX-R05 unbounded request queue (``serving/`` files only): a bare
  ``collections.deque()`` / ``asyncio.Queue()`` (no ``maxlen=`` /
  ``maxsize=``, or an explicit unbounded ``maxlen=None`` /
  ``maxsize=0``) assigned to a request-queue-shaped name (``*queue*``,
  ``*backlog*``, ``*pending*``). An unbounded lane queue is the
  overload failure mode admission control exists to close
  (docs/admission.md): a burst above capacity grows it without limit —
  first memory, then every queued request's latency. Bound the
  container and shed overflow at the enqueue edge with a
  machine-readable ``retry_after_ms`` answer (serving/admission.py);
  bounded constructions and non-queue names are untouched.
- TX-R07 leaked connection writer (``serving/`` files only): a
  socket / stream writer / transport stored into a dict-like
  container (``self._writers[key] = writer``) in a module with NO
  removal path for that container anywhere — no ``del c[...]``, no
  ``.pop(...)``/``.popitem()``/``.clear()``/``.discard(...)``. Every
  client disconnect then leaks one writer entry (and its socket fd):
  the table only grows, and a long-lived server exhausts fds under
  nothing but ordinary connection churn. The fix is structural — the
  handler's ``finally`` must evict the entry when the connection
  dies (serving/router.py's ``_client_writers`` is the reference
  shape). Stores of non-connection values and containers with any
  observed cleanup call are untouched.
- TX-O01 telemetry/trace emission inside a jitted function body:
  ``telemetry.event(...)``/``telemetry.count(...)``, a tracer span
  enter/exit (``trace.span``/``add_span``/``add_event``), or a
  wall-clock read (``time.time``/``perf_counter``/``monotonic``).
  The body of a jitted function runs at TRACE time — such a call
  records compilation (not execution), fires once per compile instead
  of once per call, and a value derived from it baked into the trace
  forces recompiles. ``compile_time.section`` is deliberately exempt:
  measuring trace cost inside a traced body is its documented job
  (plans/prepare.py per-stage sections).
- TX-J08 implicit replication under ``shard_map``/``pjit``: the body
  function closes over an array-like value from the enclosing scope
  instead of receiving it through ``in_specs``. A closed-over operand
  gets no PartitionSpec, so XLA replicates it IN FULL to every device —
  the fold matrix paid once per chip, silently (the sharded search's
  HBM budget assumes one copy across the ``data`` axis). Arrays must
  enter the body as arguments with explicit specs (``P()`` when
  replication is the intent — then it is visible and reviewable).
  Config scalars (``cfg``/``spec``/``statics``/axis names...) may close
  over freely; the rule keys on array-ish names only.
- TX-T01 numeric literal default for a registered tunable knob outside
  ``tuning/``: assigning a number to one of the registry's blessed
  constant names (``_DEFAULT_TARGET``, ``DEFAULT_MIN_BUCKET``, ...) at
  module/class level, or giving a registered knob PARAMETER (``eta``,
  ``min_fidelity``, ``placement_margin``) a numeric literal default,
  re-introduces a second source of truth the autotuning layer cannot
  govern — ``tx tune --set`` and the cost model would silently stop
  applying to that call path. The single source of truth is
  ``tuning/registry.py``'s ``STATIC_DEFAULTS``; consumers read the
  registry (or default the parameter to ``None`` and resolve through
  ``TuningPolicy``). Files under ``tuning/`` are exempt — that IS the
  registry.
- TX-T02 hardcoded power-of-two bucket math in the bucketing layers
  (``serving/``, ``plans/``, ``tuning/``, ``artifacts/``,
  ``analysis/``): ``1 << n``, ``2 ** n`` with a computed exponent, or
  a ``b *= 2`` / ``b <<= 1`` grow loop re-derives the bucket ladder
  locally. Plans resolve batch sizes through an EXPLICIT lattice now
  (docs/ragged_batching.md) — a tuned non-power-of-two ladder makes
  every local pow2 computation silently wrong. ``plans/common.py``
  (the ``bucket_for``/``pad_rows`` entry points) and
  ``tuning/lattice.py`` (the lattice math itself) are the two files
  where that arithmetic legally lives and are exempt.

Scope discipline keeps the rules precise: J01/J04/J05 only fire INSIDE
functions statically known to be jitted (decorated with ``jax.jit`` or
``functools.partial(jax.jit, ...)``); host-side numpy orchestration code
is untouched. ``abstract_probe`` complements the AST with
``jax.eval_shape`` — tracing a callable with abstract values only, so
host-transfer and concretization defects hidden behind dynamic dispatch
are confirmed without executing a single device instruction.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from .findings import ERROR, WARNING, LintFinding

__all__ = ["lint_source", "lint_file", "abstract_probe"]

#: attribute accesses on a traced value that stay abstract (shape/dtype
#: are static at trace time — reading them is free and safe)
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "at"}

#: np.<fn> calls that are trace-time constants, not host transfers
_NP_SAFE_CALLS = {"iinfo", "finfo", "dtype"}

#: methods that force a device->host transfer / concretization
_HOST_METHODS = {"item", "tolist", "block_until_ready", "to_py"}

_F64_NAMES = {"float64", "f64", "double"}

#: calls that REDUCE over the whole grid — their result is one value
#: per search, not one per grid point, so TX-J07 taint stops there
#: (``use_l1 = bool(np.any(grid[:, 0] * grid[:, 1] > 0))`` is the
#: blessed aggregate-static idiom)
_AGGREGATE_CALLS = {"len", "any", "all", "bool", "max", "min", "sum",
                    "set", "frozenset"}

#: calls that merely re-wrap a sequence — taint flows THROUGH them
#: (``for p in list(grid)``, ``for gi, p in enumerate(grid)``)
_PASSTHROUGH_CALLS = {"list", "tuple", "dict", "enumerate", "zip",
                      "reversed", "sorted", "iter"}

#: TX-J08: free variables of a shard_map/pjit body that LOOK like data
#: arrays (the values whose implicit replication costs HBM per chip).
#: Deliberately name-based: config scalars (cfg/spec/statics/axis
#: names) close over shard bodies legitimately throughout the repo.
import re as _re

_ARRAYISH_FREE = _re.compile(
    r"(?i)^(x|y|w|b|xs|ys|xv|yv|wmat|masks?|grid|labels?|features?|"
    r"rows|cols|data|batch|inputs?|outputs?|onehot|weights?|biases)"
    r"(_[a-z0-9_]+)?$"
    r"|^.*_(mat|matrix|arrays?|st|val|train)$")

#: names that never carry a data array into a shard body (kernel
#: configuration, mesh/axis plumbing, callables)
_SHARD_CONFIG_NAMES = {"mesh", "spec", "cfg", "statics", "axis",
                       "axis_name", "data_ax", "model_ax", "kind",
                       "self", "cls", "fn", "core", "body", "one",
                       "batched"}


# ---------------------------------------------------------------------------
# import/alias resolution
# ---------------------------------------------------------------------------

class _Aliases:
    """Names the module binds to numpy / jax / jax.numpy / functools."""

    def __init__(self):
        self.numpy: Set[str] = set()
        self.jax: Set[str] = set()
        self.jnp: Set[str] = set()
        self.jit: Set[str] = set()        # `from jax import jit [as j]`
        self.partial: Set[str] = set()    # `from functools import partial`
        self.functools: Set[str] = set()
        self.lru: Set[str] = set()        # `from functools import lru_cache`

    @classmethod
    def collect(cls, tree: ast.Module) -> "_Aliases":
        al = cls()
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    name = a.asname or a.name
                    if a.name == "numpy":
                        al.numpy.add(name)
                    elif a.name == "jax":
                        al.jax.add(name)
                    elif a.name == "jax.numpy":
                        al.jnp.add(name)
                    elif a.name == "functools":
                        al.functools.add(name)
            elif isinstance(node, ast.ImportFrom):
                for a in node.names:
                    name = a.asname or a.name
                    if node.module == "jax" and a.name == "jit":
                        al.jit.add(name)
                    elif node.module == "jax" and a.name == "numpy":
                        al.jnp.add(name)
                    elif node.module == "functools":
                        if a.name == "partial":
                            al.partial.add(name)
                        elif a.name in ("lru_cache", "cache"):
                            al.lru.add(name)
        return al

    def is_jax_jit(self, node: ast.AST) -> bool:
        """``jax.jit`` / bare ``jit`` reference."""
        if isinstance(node, ast.Attribute) and node.attr == "jit" \
                and isinstance(node.value, ast.Name) \
                and node.value.id in self.jax:
            return True
        return isinstance(node, ast.Name) and node.id in self.jit

    def is_partial(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name) and node.id in self.partial:
            return True
        return (isinstance(node, ast.Attribute) and node.attr == "partial"
                and isinstance(node.value, ast.Name)
                and node.value.id in self.functools)

    def is_lru_cache(self, node: ast.AST) -> bool:
        target = node.func if isinstance(node, ast.Call) else node
        if isinstance(target, ast.Name) and target.id in self.lru:
            return True
        return (isinstance(target, ast.Attribute)
                and target.attr in ("lru_cache", "cache")
                and isinstance(target.value, ast.Name)
                and target.value.id in self.functools)


def _static_names_from_call(call: ast.Call,
                            fn: Optional[ast.FunctionDef]) -> Set[str]:
    """Parameter names declared static via static_argnames/static_argnums
    keywords of a ``jax.jit`` / ``partial(jax.jit, ...)`` call."""
    static: Set[str] = set()
    params = []
    if fn is not None:
        params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    for kw in call.keywords:
        v = kw.value
        if kw.arg == "static_argnames":
            vals = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            for e in vals:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    static.add(e.value)
        elif kw.arg == "static_argnums":
            vals = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            for e in vals:
                if isinstance(e, ast.Constant) and isinstance(e.value, int) \
                        and 0 <= e.value < len(params):
                    static.add(params[e.value])
    return static


def _jit_decoration(fn: ast.FunctionDef, al: _Aliases
                    ) -> Optional[Set[str]]:
    """None when ``fn`` is not statically jitted; otherwise the set of
    static parameter names. Recognizes ``@jax.jit``, ``@jit``,
    ``@jax.jit(...)`` and ``@functools.partial(jax.jit, ...)``."""
    for dec in fn.decorator_list:
        if al.is_jax_jit(dec):
            return set()
        if isinstance(dec, ast.Call):
            if al.is_jax_jit(dec.func):
                return _static_names_from_call(dec, fn)
            if al.is_partial(dec.func) and dec.args \
                    and al.is_jax_jit(dec.args[0]):
                return _static_names_from_call(dec, fn)
    return None


# ---------------------------------------------------------------------------
# traced-value reachability inside an expression
# ---------------------------------------------------------------------------

def _mentions_traced(node: ast.AST, traced: Set[str]) -> bool:
    """Does the expression reference a traced name in a way that needs a
    concrete value? Reads of static attributes (``x.shape``...) and
    ``len(x)`` are trace-time constants and don't count."""
    if isinstance(node, ast.Name):
        return node.id in traced
    if isinstance(node, ast.Attribute) and node.attr in _STATIC_ATTRS:
        return False                       # x.shape / x.dtype: static
    if isinstance(node, ast.Call):
        if isinstance(node.func, ast.Name) and node.func.id == "len":
            return False                   # len(traced) is static
        if isinstance(node.func, ast.Attribute):
            # x.astype(...) etc: the CALL result is still traced, but
            # deciding that needs type inference; the test below treats
            # the receiver as the signal
            return any(_mentions_traced(a, traced)
                       for a in [node.func.value] + list(node.args))
        return any(_mentions_traced(a, traced)
                   for a in list(node.args)
                   + [kw.value for kw in node.keywords])
    if isinstance(node, ast.Compare):
        # `x is None` / `x is not None` never concretizes a tracer
        if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
            return False
    return any(_mentions_traced(c, traced)
               for c in ast.iter_child_nodes(node))


# ---------------------------------------------------------------------------
# the per-file visitor
# ---------------------------------------------------------------------------

def _is_serving_path(path: str) -> bool:
    """serving/ package files get the TX-J06 hot-path rules."""
    import re
    return "serving" in re.split(r"[/\\]", path)


def _is_artifact_path(path: str) -> bool:
    """serving/ and cli/ files get the TX-R06 artifact-bypass rule:
    these trees score saved models, where a direct
    ``ScoringPlan(...).compile()`` ignores the model dir's exported
    AOT executables and pays a cold XLA compile per bucket
    (artifacts/loader.py is the sanctioned entry point)."""
    import re
    parts = re.split(r"[/\\]", path)
    return "serving" in parts or "cli" in parts


def _is_train_path(path: str) -> bool:
    """workflow/ package files get the TX-J09 train-hot-path rule: the
    code ``Workflow.train()`` executes between raw data and the fitted
    model, where host transform_columns walks bypass the compiled
    prepare path (plans/prepare.py)."""
    import re
    return "workflow" in re.split(r"[/\\]", path)


def _is_resilience_path(path: str) -> bool:
    """selector/ and serving/ files get the TX-R01 exception-swallow
    rule: these are the hot paths where a swallowed XlaRuntimeError
    silently degrades a whole search/request instead of being retried,
    quarantined or surfaced."""
    import re
    parts = re.split(r"[/\\]", path)
    return "selector" in parts or "serving" in parts


#: a broad except handler is acceptable when its body does one of
#: these: re-raise, or route the error through the runtime's recovery
#: vocabulary (quarantine/classify/fallback/inject) so the degradation
#: is RECORDED rather than swallowed
_RECOVERY_NAME_PARTS = ("quarantine", "classify", "fallback",
                        "maybe_inject")

#: TX-R02 accepts a wider recording vocabulary than TX-R01: dropping a
#: record is sometimes the right call (malformed row), but the drop
#: must leave a trace — a quarantine reason, a telemetry counter/event,
#: a ``note_*``/``record*`` bookkeeping call, or at least a log line
_DROP_RECORD_NAME_PARTS = _RECOVERY_NAME_PARTS + (
    "record", "note", "count", "event", "warn", "log", "error")

#: TX-R03: the load-bearing attributes of a live cache entry — writing
#: one IN PLACE on an entry you did not just build races every
#: in-flight batch holding a reference to it (and forfeits rollback:
#: there is no previous value to pin). ``self.<attr> = ...`` inside the
#: owning class (entry construction, the PlanCache helpers themselves)
#: stays legal.
_R03_ENTRY_ATTRS = frozenset({"plan", "model", "result_names"})
#: the registries TX-R03 guards against out-of-band subscript writes:
#: mutating another object's ``_entries``/``_overrides``/``_pinned``/
#: ``_loaders`` bypasses swap_entry/rollback/commit's pin bookkeeping
_R03_REGISTRY_ATTRS = frozenset({"_entries", "_loaders",
                                 "_overrides", "_pinned"})


def _is_self_name(node: ast.AST) -> bool:
    return isinstance(node, ast.Name) and node.id == "self"


def _is_tuning_path(path: str) -> bool:
    """tuning/ package files are exempt from TX-T01 — the registry
    itself is where the literal defaults legally live."""
    import re
    return "tuning" in re.split(r"[/\\]", path)


#: the packages whose row/bucket arithmetic TX-T02 polices — the
#: layers a tuned non-pow2 lattice flows through
_T02_PACKAGES = frozenset(
    {"serving", "plans", "tuning", "artifacts", "analysis"})


def _is_bucket_math_path(path: str) -> bool:
    """TX-T02 scope: the bucketing layers, MINUS the two files where
    power-of-two arithmetic legally lives — ``plans/common.py``
    (bucket_for/pad_rows, the entry points everyone should call) and
    ``tuning/lattice.py`` (the lattice/pow2 math itself)."""
    import re
    parts = re.split(r"[/\\]", path)
    if not _T02_PACKAGES & set(parts):
        return False
    if len(parts) >= 2 and (parts[-2], parts[-1]) in (
            ("plans", "common.py"), ("tuning", "lattice.py")):
        return False
    return True


def _tunable_names() -> tuple:
    """(const names, param name -> consumer-package scopes) registered
    in tuning/registry.py — lazy so the lint package imports standalone
    (and so a stubbed registry degrades to 'rule never fires', not an
    ImportError)."""
    try:
        from ..tuning.registry import (TUNABLE_CONST_NAMES,
                                       TUNABLE_PARAM_SCOPES)
        return TUNABLE_CONST_NAMES, TUNABLE_PARAM_SCOPES
    except ImportError:  # pragma: no cover - registry always present
        return frozenset(), {}


def _is_numeric_literal(node: ast.AST) -> bool:
    """A literal number: ``64``, ``1.5``, ``-3``, ``1.0 / 9`` — the
    shapes a hardcoded knob default takes. bools are not numbers here,
    and any Name/Call/Attribute breaks literal-ness (reading the
    registry is exactly the sanctioned fix)."""
    if isinstance(node, ast.Constant):
        return isinstance(node.value, (int, float)) \
            and not isinstance(node.value, bool)
    if isinstance(node, ast.UnaryOp) \
            and isinstance(node.op, (ast.USub, ast.UAdd)):
        return _is_numeric_literal(node.operand)
    if isinstance(node, ast.BinOp):
        return _is_numeric_literal(node.left) \
            and _is_numeric_literal(node.right)
    return False


def _is_record_drop_path(path: str) -> bool:
    """serving/ files + local/scoring.py get the TX-R02 silent-record-
    drop rule: the code paths rows flow through on their way to or
    from a model."""
    import re
    parts = re.split(r"[/\\]", path)
    return "serving" in parts or (
        len(parts) >= 2 and parts[-2] == "local"
        and parts[-1] == "scoring.py")


def _handler_drops_silently(h: ast.ExceptHandler,
                            in_loop: bool) -> bool:
    """Does the handler drop the current record with no recorded
    reason — a ``continue``, or (inside a loop) a ``pass``-only body —
    and neither re-raise nor call anything from the recording
    vocabulary?"""
    has_continue = any(isinstance(sub, ast.Continue)
                       for sub in ast.walk(h))
    pass_only = in_loop and all(isinstance(s, ast.Pass) for s in h.body)
    if not has_continue and not pass_only:
        return False
    for sub in ast.walk(h):
        if isinstance(sub, ast.Raise):
            return False
        if isinstance(sub, ast.Call):
            fn = sub.func
            name = (fn.id if isinstance(fn, ast.Name)
                    else fn.attr if isinstance(fn, ast.Attribute) else "")
            if any(p in name for p in _DROP_RECORD_NAME_PARTS):
                return False
    return True


def _handler_is_broad(h: ast.ExceptHandler) -> bool:
    """Bare ``except:`` or ``except Exception`` (possibly in a
    tuple)."""
    t = h.type
    if t is None:
        return True
    names = []
    if isinstance(t, ast.Name):
        names = [t.id]
    elif isinstance(t, ast.Tuple):
        names = [e.id for e in t.elts if isinstance(e, ast.Name)]
    return "Exception" in names


def _handler_recovers(h: ast.ExceptHandler) -> bool:
    """Does the handler body re-raise, or call into the recovery
    vocabulary (``quarantine``/``classify_error``/``*fallback*``/
    ``maybe_inject``)?"""
    for sub in ast.walk(h):
        if isinstance(sub, ast.Raise):
            return True
        if isinstance(sub, ast.Call):
            fn = sub.func
            name = (fn.id if isinstance(fn, ast.Name)
                    else fn.attr if isinstance(fn, ast.Attribute) else "")
            if any(p in name for p in _RECOVERY_NAME_PARTS):
                return True
    return False


def _calls_transform_value(node: ast.AST) -> bool:
    """Does the subtree call ``<x>.transform_value(...)``?"""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) \
                and isinstance(sub.func, ast.Attribute) \
                and sub.func.attr == "transform_value":
            return True
    return False


class _Visitor(ast.NodeVisitor):
    def __init__(self, path: str, al: _Aliases):
        self.path = path
        self.serving = _is_serving_path(path)
        self.artifact_path = _is_artifact_path(path)
        self.train_path = _is_train_path(path)
        self.resilience = _is_resilience_path(path)
        self.record_drop = _is_record_drop_path(path)
        #: TX-T01: files under tuning/ may hold the literal defaults
        self.tuning_path = _is_tuning_path(path)
        #: TX-T02: bucketing layers where local pow2 ladder math is
        #: banned (plans/common.py + tuning/lattice.py exempt)
        self.bucket_math_path = _is_bucket_math_path(path)
        self._tunable_consts, self._tunable_params = _tunable_names()
        self.al = al
        self.findings: List[LintFinding] = []
        #: stack of enclosing FunctionDefs, innermost last
        self.fn_stack: List[ast.FunctionDef] = []
        #: TX-J10: directly inside an `async def` body (a nested SYNC
        #: def resets this — that's the run_in_executor idiom)
        self.in_async = False
        #: stack of "inside a loop" flags per function level
        self.loop_depth = 0
        #: when non-None we are inside a jitted function: set of traced
        #: (non-static) parameter names accumulated over nested scopes
        self.jit_ctx: Optional[Set[str]] = None
        self.jit_fn_name = ""
        #: module-level registry: jitted fn name -> static argnames
        self.jitted_statics: Dict[str, Set[str]] = {}
        #: TX-J07: when non-None we are inside a fit-kernel function
        #: (a ``grid`` parameter / ``fold_grid`` name): names tainted
        #: by per-grid-point values
        self.grid_ctx: Optional[Set[str]] = None
        self.grid_fn_name = ""
        #: module-level registry: lru_cache'd builder names (the
        #: memoized jit-builder idiom — their ARGUMENTS are compile
        #: cache keys)
        self.memoized_builders: Set[str] = set()
        #: TX-R07 (module-wide, resolved in :meth:`finalize`):
        #: container name -> first node that stored a connection
        #: writer into it, and the set of containers with ANY
        #: observed removal path
        self._writer_stores: Dict[str, ast.AST] = {}
        self._writer_cleanups: Set[str] = set()

    # -- helpers -----------------------------------------------------------
    def add(self, rule: str, node: ast.AST, message: str,
            severity: str, hint: str = None) -> None:
        self.findings.append(LintFinding(
            rule_id=rule, severity=severity, path=self.path,
            line=getattr(node, "lineno", 0), message=message, hint=hint))

    def _in_memoized_builder(self) -> bool:
        """True when any enclosing function is an lru_cache'd builder —
        the jit-once idiom (build + cache the jitted callable per static
        config)."""
        return any(
            any(self.al.is_lru_cache(d) for d in fn.decorator_list)
            for fn in self.fn_stack)

    # -- TX-J07 grid-taint helpers -----------------------------------------
    def _is_grid_alias(self, v: ast.AST) -> bool:
        """Does this VALUE carry per-grid-point taint through a trivial
        re-wrapping only? Deliberately narrow: taint flows through
        aliases, subscripts and list()/dict()-style re-wraps, but stops
        at aggregates and at any non-trivial call — so the repo's
        grouped-statics idiom (grid -> with_params -> static groups,
        one compile per GROUP) stays untainted, while ``p["max_depth"]``
        of a per-point loop is caught."""
        if self.grid_ctx is None:
            return False
        if isinstance(v, ast.Name):
            return v.id in self.grid_ctx
        if isinstance(v, ast.Subscript):
            return self._is_grid_alias(v.value)
        if isinstance(v, ast.Call):
            fn = v.func
            if isinstance(fn, ast.Name) and fn.id in _PASSTHROUGH_CALLS:
                return any(self._is_grid_alias(a) for a in v.args)
            return False
        if isinstance(v, (ast.ListComp, ast.GeneratorExp)):
            return any(self._is_grid_alias(g.iter) for g in v.generators)
        if isinstance(v, ast.BoolOp):      # list(grid) or [{}]
            return any(self._is_grid_alias(x) for x in v.values)
        return False

    def _mentions_grid(self, node: ast.AST) -> bool:
        """Does this CALL-SITE expression reference a tainted name —
        descending through arithmetic and non-aggregate calls, stopping
        at whole-grid aggregates?"""
        if isinstance(node, ast.Name):
            return node.id in self.grid_ctx
        if isinstance(node, ast.Call):
            fn = node.func
            name = (fn.id if isinstance(fn, ast.Name)
                    else fn.attr if isinstance(fn, ast.Attribute) else "")
            if name in _AGGREGATE_CALLS:
                return False
            parts = list(node.args) + [kw.value for kw in node.keywords]
            if isinstance(fn, ast.Attribute):
                parts.append(fn.value)     # p.get(...) taints via p
            return any(self._mentions_grid(p) for p in parts)
        return any(self._mentions_grid(c)
                   for c in ast.iter_child_nodes(node))

    def _taint_targets(self, target: ast.AST) -> None:
        if isinstance(target, ast.Name):
            self.grid_ctx.add(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._taint_targets(elt)

    @staticmethod
    def _is_grid_kernel(node: ast.FunctionDef) -> bool:
        params = {a.arg for a in (node.args.posonlyargs + node.args.args
                                  + node.args.kwonlyargs)}
        return "grid" in params or "fold_grid" in node.name

    # -- TX-T01 ------------------------------------------------------------
    def _check_tunable_const(self, target: ast.AST,
                             value: Optional[ast.AST]) -> None:
        """Module/class-level ``<BLESSED_CONST> = <number>`` outside
        tuning/ — a second source of truth for a registered knob."""
        if self.tuning_path or self.fn_stack or value is None:
            return
        if isinstance(target, ast.Name) \
                and target.id in self._tunable_consts \
                and _is_numeric_literal(value):
            self.add(
                "TX-T01", target,
                f"numeric literal default for tunable knob constant "
                f"{target.id!r} outside tuning/ — tx tune overrides "
                f"and the cost model no longer govern this value",
                ERROR,
                hint="read it from the registry: from ..tuning.registry "
                     "import STATIC_DEFAULTS (tuning/registry.py is the "
                     "single source of truth)")

    def _check_tunable_defaults(self, node: ast.FunctionDef) -> None:
        """``def f(eta=3)`` in the knob's consumer package: a
        registered knob parameter with a hardcoded numeric default
        bypasses the TuningPolicy resolution path. Scope discipline:
        the spelling only means the knob in its consumer layer
        (``eta`` in models/trees.py is a GBT learning rate, legal)."""
        if self.tuning_path:
            return
        import re
        parts = set(re.split(r"[/\\]", self.path))
        pos = node.args.posonlyargs + node.args.args
        pairs = list(zip(pos[len(pos) - len(node.args.defaults):],
                         node.args.defaults))
        pairs += [(a, d) for a, d in zip(node.args.kwonlyargs,
                                         node.args.kw_defaults)
                  if d is not None]
        for arg, default in pairs:
            if parts & self._tunable_params.get(arg.arg, frozenset()) \
                    and _is_numeric_literal(default):
                self.add(
                    "TX-T01", default,
                    f"parameter {arg.arg!r} of {node.name!r} is a "
                    f"registered tunable knob with a numeric literal "
                    f"default — callers that omit it silently pin the "
                    f"knob, so tx tune overrides and the cost model "
                    f"never apply",
                    ERROR,
                    hint="default it to None and resolve through "
                         "TuningPolicy (or read tuning/registry.py's "
                         "STATIC_DEFAULTS)")

    # -- function defs -----------------------------------------------------
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._check_tunable_defaults(node)
        statics = _jit_decoration(node, self.al)
        outer_ctx, outer_name = self.jit_ctx, self.jit_fn_name
        outer_grid, outer_grid_name = self.grid_ctx, self.grid_fn_name
        if self._is_grid_kernel(node):
            self.grid_ctx = {"grid"}
            self.grid_fn_name = node.name
        elif self.grid_ctx is None:
            # a nested helper outside any grid kernel resets nothing;
            # inside one, the enclosing taint set stays visible
            self.grid_ctx = None
        outer_loops = self.loop_depth
        if statics is not None:
            # a jitted function: params minus statics are traced values
            if not self.fn_stack:
                self.jitted_statics[node.name] = statics
            elif not self._in_memoized_builder():
                self.add(
                    "TX-J02", node,
                    f"@jit function {node.name!r} is (re)defined per call "
                    f"of {self.fn_stack[-1].name!r} — every call builds a "
                    f"fresh jitted callable and recompiles",
                    WARNING,
                    hint="hoist the @jit function to module level, or "
                         "memoize the builder with functools.lru_cache")
            params = {a.arg for a in (node.args.posonlyargs + node.args.args
                                      + node.args.kwonlyargs)}
            self.jit_ctx = (params - statics) | (outer_ctx or set())
            self.jit_fn_name = node.name
        elif self.jit_ctx is not None:
            # nested helper inside a jit body: its params are traced too
            # (they receive traced values from scan/vmap/call sites)
            params = {a.arg for a in (node.args.posonlyargs + node.args.args
                                      + node.args.kwonlyargs)}
            self.jit_ctx = self.jit_ctx | params
        self.fn_stack.append(node)
        self.loop_depth = 0
        outer_async = self.in_async
        self.in_async = isinstance(node, ast.AsyncFunctionDef)
        self.generic_visit(node)
        self.in_async = outer_async
        self.fn_stack.pop()
        self.loop_depth = outer_loops
        self.jit_ctx, self.jit_fn_name = outer_ctx, outer_name
        self.grid_ctx, self.grid_fn_name = outer_grid, outer_grid_name

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Await(self, node: ast.Await) -> None:
        # an awaited call is by definition not a BLOCKING call (e.g.
        # `await sleep(...)` from asyncio) — mark it so TX-J10 skips it
        setattr(node.value, "_tx_awaited", True)
        self.generic_visit(node)

    # -- loops -------------------------------------------------------------
    def visit_For(self, node: ast.For) -> None:
        self._check_serving_row_loop(node)
        if self.grid_ctx is not None and self._is_grid_alias(node.iter):
            # `for p in grid:` / `for gi, p in enumerate(grid):` —
            # the loop variable is one grid point
            self._taint_targets(node.target)
        self.loop_depth += 1
        self.generic_visit(node)
        self.loop_depth -= 1

    def _check_serving_row_loop(self, node) -> None:
        # TX-J06: per-row transform_value loops have no place in
        # serving code — that is exactly the Python hot loop the
        # compiled ScoringPlan replaces (batch through
        # transform_columns / transform_arrays instead)
        if self.serving and _calls_transform_value(node):
            self.add(
                "TX-J06", node,
                "Python loop over transform_value in serving code — "
                "per-row scoring instead of one batched/compiled "
                "program",
                ERROR,
                hint="route the batch through ScoringPlan (or at least "
                     "transform_columns); transform_value is the "
                     "single-record edge only")
        # TX-J09: the train-time twin — a per-row transform_value loop
        # in the workflow executor is the hot loop the compiled
        # PreparePlan replaces
        if self.train_path and _calls_transform_value(node):
            self.add(
                "TX-J09", node,
                "Python loop over transform_value in the train hot "
                "path — per-row feature materialization instead of "
                "the compiled prepare program",
                ERROR,
                hint="route prepare through PreparePlan "
                     "(plans/prepare.py); transform_value is the "
                     "single-record edge only")

    def visit_ListComp(self, node: ast.ListComp) -> None:
        self._check_serving_row_loop(node)
        self._taint_comprehension(node)
        self.generic_visit(node)

    def _taint_comprehension(self, node) -> None:
        # `[kern(..., p[k]) for p in grid]` — comprehension targets
        # carry per-grid-point taint exactly like for-loop targets
        if self.grid_ctx is None:
            return
        for gen in node.generators:
            if self._is_grid_alias(gen.iter):
                self._taint_targets(gen.target)

    def visit_SetComp(self, node: ast.SetComp) -> None:
        self._taint_comprehension(node)
        self.generic_visit(node)

    def visit_DictComp(self, node: ast.DictComp) -> None:
        self._taint_comprehension(node)
        self.generic_visit(node)

    def visit_GeneratorExp(self, node: ast.GeneratorExp) -> None:
        self._taint_comprehension(node)
        self.generic_visit(node)

    def visit_Try(self, node: ast.Try) -> None:
        # TX-R01: a broad except in a selector/serving hot path that
        # swallows the error (no re-raise, no quarantine/classify/
        # fallback routing) hides XlaRuntimeErrors — real kernel bugs
        # silently degrade every search to the slow path (the exact
        # defect r4's satellite fixed at selector/validator.py:138)
        if self.resilience:
            for h in node.handlers:
                if _handler_is_broad(h) and not _handler_recovers(h):
                    what = ("bare except" if h.type is None
                            else "except Exception")
                    self.add(
                        "TX-R01", h,
                        f"{what} in a selector/serving hot path "
                        f"swallows backend errors (XlaRuntimeError "
                        f"included) without re-raise, quarantine or a "
                        f"recorded fallback",
                        ERROR,
                        hint="narrow the except, re-raise classified "
                             "bugs (runtime.errors.classify_error), or "
                             "route the family through "
                             "RuntimeContext.quarantine / a recorded "
                             "fallback reason")
        # TX-R02: a serving-path handler that drops the current record
        # (continue / pass-only inside a loop) without recording WHY —
        # rows vanishing from scored traffic with no quarantine reason,
        # no counter, no log line (docs/serving_guardrails.md)
        if self.record_drop:
            for h in node.handlers:
                if _handler_drops_silently(h, in_loop=self.loop_depth > 0):
                    self.add(
                        "TX-R02", h,
                        "record dropped on exception with no recorded "
                        "reason in a serving path (silent "
                        "continue/pass) — scored traffic shrinks "
                        "invisibly",
                        ERROR,
                        hint="quarantine the row with a "
                             "machine-readable reason (serving/guard"
                             ".py GuardReason), bump a telemetry "
                             "counter/event, or at minimum log the "
                             "drop before skipping")
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        self._check_control_flow(node)
        self.loop_depth += 1
        self.generic_visit(node)
        self.loop_depth -= 1

    def visit_If(self, node: ast.If) -> None:
        self._check_control_flow(node)
        self.generic_visit(node)

    def _check_control_flow(self, node) -> None:
        # TX-J05: Python branching on a traced value inside jit
        if self.jit_ctx is None:
            return
        if _mentions_traced(node.test, self.jit_ctx):
            kind = "while" if isinstance(node, ast.While) else "if"
            self.add(
                "TX-J05", node,
                f"`{kind}` on a traced value inside jitted "
                f"{self.jit_fn_name!r} — concretizes the tracer "
                f"(TracerBoolConversionError at trace time)",
                ERROR,
                hint="use jnp.where / lax.cond / lax.while_loop, or "
                     "declare the parameter static via static_argnames")

    # -- TX-J08: shard_map/pjit closure analysis ---------------------------
    @staticmethod
    def _is_shard_call(fn: ast.AST) -> bool:
        name = (fn.id if isinstance(fn, ast.Name)
                else fn.attr if isinstance(fn, ast.Attribute) else "")
        return name in ("shard_map", "pjit")

    def _resolve_local_funcdef(self, name: str):
        """The FunctionDef a shard_map call's first argument names,
        searched through the enclosing function bodies (the repo's
        kernel-builder idiom defines the shard body locally)."""
        for fn in reversed(self.fn_stack):
            for sub in ast.walk(fn):
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)) \
                        and sub.name == name:
                    return sub
        return None

    @staticmethod
    def _free_names(body: ast.AST) -> Set[str]:
        """Names a function body loads but never binds — its closure.
        Bound: its own (and nested) params, assignment/for/
        comprehension targets, nested def names."""
        bound: Set[str] = set()
        loads: Set[str] = set()
        for sub in ast.walk(body):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                if not isinstance(sub, ast.Lambda):
                    bound.add(sub.name)
                a = sub.args
                bound.update(p.arg for p in
                             a.posonlyargs + a.args + a.kwonlyargs)
                if a.vararg:
                    bound.add(a.vararg.arg)
                if a.kwarg:
                    bound.add(a.kwarg.arg)
            elif isinstance(sub, ast.Name):
                if isinstance(sub.ctx, ast.Store):
                    bound.add(sub.id)
                elif isinstance(sub.ctx, ast.Load):
                    loads.add(sub.id)
        return loads - bound

    def _check_shard_closure(self, node: ast.Call) -> None:
        """TX-J08: a shard_map/pjit body closing over an array-like
        value — no PartitionSpec, so XLA replicates it in full to
        every device. Arrays must enter through in_specs (P() when
        replication is intended — explicit and reviewable)."""
        if not self._is_shard_call(node.func) or not node.args:
            return
        target = node.args[0]
        if isinstance(target, ast.Name):
            body = self._resolve_local_funcdef(target.id)
        elif isinstance(target, ast.Lambda):
            body = target
        else:
            body = None
        if body is None:
            return
        where = (f" (in {self.fn_stack[-1].name!r})"
                 if self.fn_stack else "")
        for free in sorted(self._free_names(body)):
            # module CONSTANTS are config, not data — but a single
            # capital letter (X, the feature matrix) is data
            if free in _SHARD_CONFIG_NAMES \
                    or (len(free) > 1 and free.isupper()) \
                    or free in self.al.jax | self.al.jnp | self.al.numpy:
                continue
            if not _ARRAYISH_FREE.match(free):
                continue
            self.add(
                "TX-J08", node,
                f"shard_map/pjit body closes over array-like "
                f"{free!r} from the enclosing scope{where} — the "
                f"operand has no PartitionSpec, so XLA replicates it "
                f"IN FULL to every device (a fold matrix paid once "
                f"per chip)",
                WARNING,
                hint="pass it as a body argument with an explicit "
                     "entry in in_specs — P('data') to shard rows, "
                     "P() when replication is genuinely intended")

    # -- TX-J10: blocking calls in serving async handlers ------------------
    def _check_async_blocking(self, node: ast.Call) -> None:
        """Inside an ``async def`` in a serving/ file, a blocking call
        stalls the event loop — every queued request of every tenant
        waits behind it. The serving loop's contract is that blocking
        work runs in named executors (serving/server.py)."""
        if getattr(node, "_tx_awaited", False):
            return
        where = (f" in async handler {self.fn_stack[-1].name!r}"
                 if self.fn_stack else "")
        fn = node.func
        if isinstance(fn, ast.Attribute):
            root = fn.value
            if fn.attr == "sleep" and isinstance(root, ast.Name) \
                    and root.id == "time":
                self.add(
                    "TX-J10", node,
                    f"blocking time.sleep(...){where} — the serving "
                    f"event loop (and every in-flight request) stalls "
                    f"for the duration",
                    ERROR,
                    hint="await asyncio.sleep(...) instead")
            elif fn.attr == "block_until_ready":
                self.add(
                    "TX-J10", node,
                    f"synchronous device sync .block_until_ready()"
                    f"{where} — blocks the event loop on device "
                    f"completion",
                    ERROR,
                    hint="submit the dispatch to an executor "
                         "(loop.run_in_executor) and await it")
            elif isinstance(root, ast.Name) and root.id in self.al.numpy \
                    and fn.attr in ("asarray", "array", "concatenate"):
                self.add(
                    "TX-J10", node,
                    f"np.{fn.attr}(...) host materialization{where} — "
                    f"a device-output copy (and a blocking sync) on "
                    f"the event loop",
                    ERROR,
                    hint="run host encode/materialization in an "
                         "executor (the serving loop's encode pool "
                         "idiom, serving/server.py)")
        elif isinstance(fn, ast.Name):
            if fn.id == "open":
                self.add(
                    "TX-J10", node,
                    f"file I/O (open){where} — disk latency on the "
                    f"serving event loop",
                    ERROR,
                    hint="do file I/O in an executor, or outside the "
                         "async hot path")
            elif fn.id == "sleep":
                self.add(
                    "TX-J10", node,
                    f"blocking sleep(...){where} (un-awaited, so this "
                    f"is time.sleep, not asyncio's)",
                    ERROR,
                    hint="await asyncio.sleep(...) instead")

    # -- TX-R04: torn state-file writes in serving/ ------------------------
    _WRITE_MODES = ("w", "a", "x")

    @staticmethod
    def _mentions_tmp(expr: ast.AST) -> bool:
        """True when the path expression's AST carries a tmp marker —
        a ``tmp``-named variable, a ``.tmp``/tempfile attribute, or a
        string constant containing ``tmp``. That is the sanctioned
        staging idiom (write ``path + ".tmp"``, then ``os.replace``):
        a torn temp file is harmless, the live path flips atomically."""
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Name) and "tmp" in sub.id.lower():
                return True
            if isinstance(sub, ast.Attribute) \
                    and "tmp" in sub.attr.lower():
                return True
            if isinstance(sub, ast.Constant) \
                    and isinstance(sub.value, str) \
                    and "tmp" in sub.value.lower():
                return True
        return False

    def _check_state_file_write(self, node: ast.Call) -> None:
        """A bare ``open(path, "w")`` to a live path in serving/ code
        is a torn-state hazard: kill the process mid-write (the exact
        event the preemption stack exists for) and the snapshot or
        fingerprint file it was replacing is now half a JSON document.
        The shared writer (observability/store.atomic_write_json)
        stages to ``*.tmp`` and ``os.replace``s — the live path is
        always either the old doc or the new one, never a torn one."""
        fn = node.func
        if not (isinstance(fn, ast.Name) and fn.id == "open"):
            return
        if not node.args:
            return
        mode: Optional[str] = None
        if len(node.args) >= 2 and isinstance(node.args[1], ast.Constant) \
                and isinstance(node.args[1].value, str):
            mode = node.args[1].value
        for kw in node.keywords:
            if kw.arg == "mode" and isinstance(kw.value, ast.Constant) \
                    and isinstance(kw.value.value, str):
                mode = kw.value.value
        if mode is None or not any(m in mode for m in self._WRITE_MODES):
            return  # read (or unknown) mode: not a state write
        if self._mentions_tmp(node.args[0]):
            return  # staging file for an atomic replace — the idiom
        where = (f" in {self.fn_stack[-1].name!r}"
                 if self.fn_stack else "")
        self.add(
            "TX-R04", node,
            f"state-file write open(..., {mode!r}){where} targets a "
            f"live path — a process killed mid-write (preemption, "
            f"OOM, supervisor restart) leaves a TORN document where "
            f"a readable one used to be",
            ERROR,
            hint="write through observability.store.atomic_write_json "
                 "(stages to *.tmp, then os.replace — the live path "
                 "is never half-written)")

    # -- TX-R06: AOT-artifact-loader bypass in serving//cli/ ---------------
    def _check_plan_compile_bypass(self, node: ast.Call) -> None:
        """``ScoringPlan(...).compile()`` chained directly in serving/
        or cli/ code ignores the saved model's exported AOT executables
        (docs/aot_artifacts.md): the serve process pays a cold XLA
        compile per bucket that ``save_model`` already paid for it.
        ``artifacts.loader.load_or_compile`` is the one sanctioned
        constructor — it attaches the artifacts when the validity key
        matches and falls back LOUDLY (counted) when it doesn't."""
        fn = node.func
        if not (isinstance(fn, ast.Attribute) and fn.attr == "compile"):
            return
        inner = fn.value
        if not isinstance(inner, ast.Call):
            return
        ctor = inner.func
        name = None
        if isinstance(ctor, ast.Name):
            name = ctor.id
        elif isinstance(ctor, ast.Attribute):
            name = ctor.attr
        if name != "ScoringPlan":
            return
        where = (f" in {self.fn_stack[-1].name!r}"
                 if self.fn_stack else "")
        self.add(
            "TX-R06", node,
            f"ScoringPlan(...).compile(){where} bypasses the AOT "
            f"artifact loader — a saved model's exported executables "
            f"are ignored and every bucket pays a cold in-band XLA "
            f"compile",
            ERROR,
            hint="route through artifacts.loader.load_or_compile "
                 "(loads the model dir's serialized executables, "
                 "counted loud fallback to live compile otherwise)")

    # -- TX-R05: unbounded request queues in serving/ ----------------------
    _QUEUE_NAME_HINTS = ("queue", "backlog", "pending")

    @staticmethod
    def _queueish_name(target: ast.AST) -> Optional[str]:
        """The request-queue-shaped name a store targets, or None —
        a plain name or attribute (``self.queue = ...``) whose
        lowercase spelling mentions queue/backlog/pending."""
        name = None
        if isinstance(target, ast.Name):
            name = target.id
        elif isinstance(target, ast.Attribute):
            name = target.attr
        if name is None:
            return None
        low = name.lower()
        return name if any(h in low for h in
                           _Visitor._QUEUE_NAME_HINTS) else None

    def _check_unbounded_queue(self, targets, value) -> None:
        """TX-R05: a bare ``deque()``/``Queue()`` bound to a request-
        queue name in serving/ grows without limit under overload —
        the exact failure mode the admission edge exists to close
        (docs/admission.md). Bounded constructions (``maxlen=``, a
        positive ``maxsize=``) pass."""
        if not isinstance(value, ast.Call):
            return
        fn = value.func
        ctor = fn.id if isinstance(fn, ast.Name) else (
            fn.attr if isinstance(fn, ast.Attribute) else None)
        if ctor == "deque":
            # deque(iterable, maxlen) — second positional IS the bound
            cap = value.args[1] if len(value.args) >= 2 else None
            for kw in value.keywords:
                if kw.arg == "maxlen":
                    cap = kw.value
            unbounded = cap is None or (
                isinstance(cap, ast.Constant) and cap.value is None)
        elif ctor == "Queue":
            # asyncio.Queue(maxsize=0) and Queue() are unbounded
            cap = value.args[0] if value.args else None
            for kw in value.keywords:
                if kw.arg == "maxsize":
                    cap = kw.value
            unbounded = cap is None or (
                isinstance(cap, ast.Constant) and cap.value in (0, None))
        else:
            return
        if not unbounded:
            return
        for target in targets:
            name = self._queueish_name(target)
            if name is None:
                continue
            where = (f" in {self.fn_stack[-1].name!r}"
                     if self.fn_stack else "")
            self.add(
                "TX-R05", value,
                f"unbounded {ctor}() assigned to request queue "
                f"{name!r}{where} — under overload it grows without "
                f"limit: first memory, then every queued request's "
                f"latency (no backpressure ever fires)",
                ERROR,
                hint="bound it (collections.deque(maxlen=...) / "
                     "asyncio.Queue(maxsize=...)) and shed overflow "
                     "at the admission edge with a retry_after_ms "
                     "answer (serving/admission.py)")
            return

    # -- TX-R07: leaked connection writers in serving/ ---------------------
    _WRITER_NAME_HINTS = ("writer", "sock", "conn", "transport",
                          "stream")

    @staticmethod
    def _r07_container_name(node: ast.AST) -> Optional[str]:
        """The name of a dict-like container — a plain name or a
        ``self.<attr>``; anything else is out of scope."""
        if isinstance(node, ast.Name):
            return node.id
        if isinstance(node, ast.Attribute) and _is_self_name(node.value):
            return node.attr
        return None

    @classmethod
    def _r07_writerish(cls, value: ast.AST) -> bool:
        """Is the stored VALUE connection-shaped — a name/attribute
        (or a tuple holding one) whose spelling mentions a writer/
        socket/transport? Deliberately shallow: a call result like
        ``make_stream_handler(...)`` is not tracked (too many false
        positives), a plain ``writer`` variable is."""
        if isinstance(value, ast.Tuple):
            return any(cls._r07_writerish(e) for e in value.elts)
        name = None
        if isinstance(value, ast.Name):
            name = value.id
        elif isinstance(value, ast.Attribute):
            name = value.attr
        return bool(name) and any(h in name.lower()
                                  for h in cls._WRITER_NAME_HINTS)

    def _check_writer_store(self, targets, value) -> None:
        for target in targets:
            if not isinstance(target, ast.Subscript):
                continue
            cname = self._r07_container_name(target.value)
            if cname is not None and self._r07_writerish(value):
                self._writer_stores.setdefault(cname, target)

    def _check_writer_cleanup_call(self, node: ast.Call) -> None:
        fn = node.func
        if isinstance(fn, ast.Attribute) \
                and fn.attr in ("pop", "popitem", "clear", "discard"):
            cname = self._r07_container_name(fn.value)
            if cname is not None:
                self._writer_cleanups.add(cname)

    def finalize(self) -> None:
        """Module-wide verdicts that need the WHOLE tree seen first.
        TX-R07: every container that received a connection-writer
        store but shows no removal path anywhere in the module leaks
        one entry (and one socket fd) per client disconnect."""
        for cname, node in sorted(self._writer_stores.items(),
                                  key=lambda kv: kv[1].lineno):
            if cname in self._writer_cleanups:
                continue
            self.add(
                "TX-R07", node,
                f"connection writer stored in {cname!r} with no "
                f"disconnect-cleanup path anywhere in this module — "
                f"every client disconnect leaks the entry (and its "
                f"socket fd); the table only grows until the process "
                f"runs out of file descriptors",
                ERROR,
                hint=f"evict the entry when the connection dies: "
                     f"`finally: {cname}.pop(key, None)` in the "
                     f"connection handler (see FleetRouter.handle, "
                     f"serving/router.py)")

    # -- TX-O01: telemetry/trace emission inside a jitted body -------------
    _CLOCK_ATTRS = {"time", "perf_counter", "monotonic", "time_ns",
                    "perf_counter_ns", "monotonic_ns"}
    _TELEMETRY_ATTRS = {"event", "count", "note_dispatch"}
    _TRACER_ATTRS = {"span", "add_span", "add_event"}

    def _check_traced_telemetry(self, node: ast.Call) -> None:
        """Inside a jitted function the body executes once per TRACE:
        a telemetry counter/event, a tracer span, or a wall-clock read
        there records compile-time behavior as if it were run-time —
        and a changing value baked into the trace recompiles. Emit
        telemetry AROUND the dispatch, never inside the traced body.
        (``compile_time.section`` is exempt: measuring trace cost
        inside the body is exactly its job.)"""
        fn = node.func
        if not isinstance(fn, ast.Attribute) \
                or not isinstance(fn.value, ast.Name):
            return
        root, attr = fn.value.id, fn.attr
        what = None
        if root == "time" and attr in self._CLOCK_ATTRS:
            what = (f"wall-clock read time.{attr}() — measures trace "
                    f"time once per compile, not run time per call")
        elif "telemetry" in root.lower() \
                and attr in self._TELEMETRY_ATTRS:
            what = (f"telemetry emission {root}.{attr}(...) — fires "
                    f"once per COMPILE, not once per call")
        elif root in ("trace", "_trace") and attr in self._TRACER_ATTRS:
            what = (f"tracer call {root}.{attr}(...) — a span opened "
                    f"inside a traced body records tracing, not "
                    f"execution")
        if what is not None:
            self.add(
                "TX-O01", node,
                f"{what} (inside jitted {self.jit_fn_name!r})",
                ERROR,
                hint="move the telemetry/clock to the host code "
                     "around the jitted call; compile_time.section is "
                     "the blessed probe for trace-time cost")

    # -- calls -------------------------------------------------------------
    def visit_Call(self, node: ast.Call) -> None:
        al = self.al
        # TX-J10: blocking calls inside serving async handlers --------------
        if self.serving and self.in_async:
            self._check_async_blocking(node)
        # TX-R04: torn state-file writes anywhere under serving/ ------------
        if self.serving:
            self._check_state_file_write(node)
            # TX-R07: any pop/clear on a container counts as a
            # disconnect-cleanup path for that container
            self._check_writer_cleanup_call(node)
        # TX-R06: AOT-artifact-loader bypass in serving//cli/ ----------------
        if self.artifact_path:
            self._check_plan_compile_bypass(node)
        # TX-O01: telemetry/trace/clock inside a jitted body ----------------
        if self.jit_ctx is not None:
            self._check_traced_telemetry(node)
        # TX-J08: shard_map/pjit closing over unsharded arrays --------------
        self._check_shard_closure(node)
        # TX-J09: host materialization in the train hot path ----------------
        if self.train_path and isinstance(node.func, ast.Attribute) \
                and node.func.attr in ("transform_columns",
                                       "transform_dataset"):
            self.add(
                "TX-J09", node,
                f"host {node.func.attr} walk in the train hot path — "
                f"stages with transform_arrays kernels should execute "
                f"fused on device via the compiled prepare plan",
                WARNING,
                hint="route prepare through PreparePlan "
                     "(plans/prepare.py); the TX_PREPARE=host escape "
                     "hatch is the only blessed host walk and must "
                     "carry an inline suppression")
        # TX-J02 (TX-J06 inside serving/): jax.jit applied at call time ----
        if al.is_jax_jit(node.func):
            per_call_rule = "TX-J06" if self.serving else "TX-J02"
            if self.loop_depth > 0:
                self.add(
                    per_call_rule, node,
                    "jax.jit(...) called inside a loop — a fresh jitted "
                    "callable (and a full XLA recompile) per iteration",
                    ERROR,
                    hint="hoist the jit call out of the loop; the loop "
                         "should call ONE jitted function")
            elif self.fn_stack and not self._in_memoized_builder():
                self.add(
                    per_call_rule, node,
                    f"jax.jit(...) called per invocation of "
                    f"{self.fn_stack[-1].name!r} — the returned callable "
                    f"is rebuilt (and recompiled) every call",
                    ERROR if self.serving else WARNING,
                    hint="compile once per plan/model and cache the "
                         "jitted callable (serving must never pay a "
                         "per-request trace)" if self.serving else
                         "decorate the enclosing builder with "
                         "functools.lru_cache (the memoized-builder "
                         "idiom) or jit once at module level")
            # register module-level `name = jax.jit(fn, static_...)`
        # TX-J03: non-hashable static args at a call site ------------------
        if isinstance(node.func, ast.Name) \
                and node.func.id in self.jitted_statics:
            statics = self.jitted_statics[node.func.id]
            for kw in node.keywords:
                if kw.arg in statics and isinstance(
                        kw.value, (ast.List, ast.Dict, ast.Set,
                                   ast.ListComp, ast.DictComp,
                                   ast.SetComp, ast.GeneratorExp)):
                    kind = type(kw.value).__name__.lower()
                    self.add(
                        "TX-J03", node,
                        f"static argument {kw.arg!r} of jitted "
                        f"{node.func.id!r} receives a non-hashable "
                        f"{kind} — TypeError at trace time",
                        ERROR,
                        hint="pass a tuple (hashable) instead; static "
                             "args key the compilation cache")
        # TX-J07: grid values flowing into compile cache keys --------------
        if self.grid_ctx is not None and isinstance(node.func, ast.Name):
            callee = node.func.id
            if callee in self.jitted_statics:
                statics = self.jitted_statics[callee]
                for kw in node.keywords:
                    if kw.arg in statics and self._mentions_grid(kw.value):
                        self.add(
                            "TX-J07", node,
                            f"grid-derived value reaches static "
                            f"argument {kw.arg!r} of jitted {callee!r} "
                            f"inside {self.grid_fn_name!r} — one XLA "
                            f"compile per grid point (G x F programs "
                            f"instead of 1)",
                            WARNING,
                            hint="make the hyperparameter a traced "
                                 "array and vmap the candidate axis; "
                                 "only whole-grid aggregates (any/all/"
                                 "len) may shape statics")
            if callee in self.memoized_builders:
                parts = list(node.args) + [kw.value
                                           for kw in node.keywords]
                if any(self._mentions_grid(p) for p in parts):
                    self.add(
                        "TX-J07", node,
                        f"grid-derived value keys the memoized kernel "
                        f"builder {callee!r} inside "
                        f"{self.grid_fn_name!r} — a fresh jitted "
                        f"program per grid point (G x F compiles "
                        f"instead of 1)",
                        WARNING,
                        hint="key the builder by family config only; "
                             "pass grid values as traced vmapped "
                             "vectors into ONE kernel")
        # TX-J01: host transfers inside jit --------------------------------
        if self.jit_ctx is not None:
            self._check_host_transfer(node)
        # TX-J04: float64 creep inside jit ---------------------------------
        # Only dtype REQUESTS count (dtype= kwarg, .astype(f64),
        # jnp.float64(x), positional dtype of a jnp/np constructor) — a
        # `x.dtype == jnp.float64` comparison is a guard, not creep.
        if self.jit_ctx is not None:
            f64_args = [kw.value for kw in node.keywords
                        if kw.arg == "dtype" and self._is_f64(kw.value)]
            fn = node.func
            is_cast = (isinstance(fn, ast.Attribute)
                       and fn.attr == "astype") or self._is_f64(fn)
            is_array_ctor = (isinstance(fn, ast.Attribute)
                             and isinstance(fn.value, ast.Name)
                             and fn.value.id in (self.al.jnp
                                                 | self.al.numpy))
            if is_cast or is_array_ctor:
                f64_args += [a for a in node.args if self._is_f64(a)]
            if self._is_f64(fn):
                f64_args.append(fn)
            if f64_args:
                self.add(
                    "TX-J04", node,
                    f"float64 dtype requested inside jitted "
                    f"{self.jit_fn_name!r}",
                    WARNING,
                    hint="TPUs execute f32/bf16; with x64 disabled this "
                         "silently downcasts, with x64 enabled it "
                         "doubles memory traffic — use float32 or an "
                         "explicit bf16 policy")
        self.generic_visit(node)

    def _is_f64(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Constant):
            return node.value in _F64_NAMES
        if isinstance(node, ast.Attribute):
            return node.attr in _F64_NAMES
        return False

    def _check_host_transfer(self, node: ast.Call) -> None:
        fn = node.func
        if isinstance(fn, ast.Attribute):
            root = fn.value
            # np.<anything>(...) — numpy executes on host; feeding it a
            # tracer raises, feeding it a device array syncs + copies
            if isinstance(root, ast.Name) and root.id in self.al.numpy \
                    and fn.attr not in _NP_SAFE_CALLS:
                self.add(
                    "TX-J01", node,
                    f"numpy call np.{fn.attr}(...) inside jitted "
                    f"{self.jit_fn_name!r} — numpy executes on the host "
                    f"(TracerArrayConversionError or an implicit "
                    f"device->host transfer)",
                    ERROR,
                    hint=f"use jnp.{fn.attr} (or a lax primitive) so the "
                         f"op stays in the XLA program")
            # chained module: np.linalg.solve etc.
            elif isinstance(root, ast.Attribute) \
                    and isinstance(root.value, ast.Name) \
                    and root.value.id in self.al.numpy:
                self.add(
                    "TX-J01", node,
                    f"numpy call np.{root.attr}.{fn.attr}(...) inside "
                    f"jitted {self.jit_fn_name!r} — host execution",
                    ERROR,
                    hint=f"use jnp.{root.attr}.{fn.attr}")
            elif fn.attr in _HOST_METHODS and _mentions_traced(
                    fn.value, self.jit_ctx):
                self.add(
                    "TX-J01", node,
                    f".{fn.attr}() on a traced value inside jitted "
                    f"{self.jit_fn_name!r} — forces a device->host "
                    f"transfer and a blocking sync",
                    ERROR,
                    hint="keep the value on device; materialize results "
                         "only OUTSIDE the jitted function")
        elif isinstance(fn, ast.Name) and fn.id in ("float", "int", "bool") \
                and node.args and _mentions_traced(node.args[0],
                                                   self.jit_ctx):
            self.add(
                "TX-J01", node,
                f"{fn.id}(...) applied to a traced value inside jitted "
                f"{self.jit_fn_name!r} — concretizes the tracer "
                f"(ConcretizationTypeError at trace time)",
                ERROR,
                hint="use .astype(...) for dtype casts; scalar reads "
                     "belong outside the jitted function")

    # -- module-level jit assignments for TX-J03 ---------------------------
    def visit_Assign(self, node: ast.Assign) -> None:
        if not self.fn_stack and isinstance(node.value, ast.Call) \
                and self.al.is_jax_jit(node.value.func) \
                and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            self.jitted_statics[node.targets[0].id] = \
                _static_names_from_call(node.value, None)
        # TX-J07: per-grid-point taint flows through plain aliasing
        # (`p = grid[i]`, `depth = p["max_depth"]`, `cfg = dict(p)`) but
        # stops at aggregates and non-trivial calls (grouped statics)
        if self.grid_ctx is not None \
                and self._is_grid_alias(node.value):
            for target in node.targets:
                self._taint_targets(target)
        if self.serving:
            for target in node.targets:
                self._check_live_mutation(target)
            self._check_unbounded_queue(node.targets, node.value)
            self._check_writer_store(node.targets, node.value)
        for target in node.targets:
            self._check_tunable_const(target, node.value)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        # TX-T01 also covers the annotated form
        # (`DEFAULT_ETA: int = 3`) — same knob, same second source
        self._check_tunable_const(node.target, node.value)
        if self.serving and node.value is not None:
            # TX-R05 covers the annotated spelling too
            # (`self.queue: deque = deque()`)
            self._check_unbounded_queue([node.target], node.value)
        self.generic_visit(node)

    # -- TX-T02 ------------------------------------------------------------
    def _t02(self, node: ast.AST, spelled: str) -> None:
        self.add(
            "TX-T02", node,
            f"hardcoded power-of-two bucket math ({spelled}) outside "
            f"plans/common.py / tuning/lattice.py — a tuned "
            f"non-power-of-two lattice (docs/ragged_batching.md) makes "
            f"this locally re-derived ladder disagree with the plan's "
            f"actual buckets",
            ERROR,
            hint="resolve batch shapes through plans.common.bucket_for/"
                 "pad_rows (lattice-aware) or the tuning.lattice "
                 "helpers instead of local pow2 arithmetic")

    @staticmethod
    def _const_int(node: ast.AST, value: int) -> bool:
        return isinstance(node, ast.Constant) \
            and type(node.value) is int and node.value == value

    def visit_BinOp(self, node: ast.BinOp) -> None:
        # TX-T02: `1 << n` / `2 ** n` with a COMPUTED exponent is a
        # locally re-derived pow2 bucket ladder. A literal exponent
        # (`2 ** 30`, a plain size constant) is just a number — exempt.
        if self.bucket_math_path and not _is_numeric_literal(node.right):
            if isinstance(node.op, ast.LShift) \
                    and self._const_int(node.left, 1):
                self._t02(node, "1 << <computed>")
            elif isinstance(node.op, ast.Pow) \
                    and self._const_int(node.left, 2):
                self._t02(node, "2 ** <computed>")
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        if self.serving:
            self._check_live_mutation(node.target)
        # TX-T02: `b *= 2` / `b <<= 1` doubling loops grow a pow2
        # ladder in place — same forked-ladder hazard as `1 << n`
        if self.bucket_math_path and (
                (isinstance(node.op, ast.Mult)
                 and self._const_int(node.value, 2))
                or (isinstance(node.op, ast.LShift)
                    and self._const_int(node.value, 1))):
            self._t02(node, "<row count> *= 2")
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        if self.serving:
            for target in node.targets:
                self._check_live_mutation(target, deleting=True)
                # TX-R07: `del container[key]` is a cleanup path
                if isinstance(target, ast.Subscript):
                    cname = self._r07_container_name(target.value)
                    if cname is not None:
                        self._writer_cleanups.add(cname)
        self.generic_visit(node)

    def _check_live_mutation(self, target: ast.AST,
                             deleting: bool = False) -> None:
        """TX-R03: a store (or del) that rewrites a live serving cache
        entry or a plan registry in place, outside the owning object's
        own methods. Legal hot changes go through the atomic helpers
        (``PlanCache.swap_entry`` pins the previous entry and replaces
        the reference in ONE assignment; ``rollback``/``commit``
        resolve the pin) so concurrent readers only ever see a
        complete entry."""
        verb = "del of" if deleting else "write to"
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._check_live_mutation(elt, deleting)
            return
        if isinstance(target, ast.Attribute) \
                and target.attr in _R03_ENTRY_ATTRS \
                and not _is_self_name(target.value):
            self.add(
                "TX-R03", target,
                f"in-place {verb} '.{target.attr}' on a live serving "
                f"cache entry — in-flight batches hold a reference to "
                f"this object and there is no pinned previous value "
                f"to roll back to",
                ERROR,
                hint="build a fresh entry and replace it atomically "
                     "with PlanCache.swap_entry(...); rollback()/"
                     "commit() resolve the pinned predecessor")
            return
        if isinstance(target, ast.Subscript) \
                and isinstance(target.value, ast.Attribute) \
                and target.value.attr in _R03_REGISTRY_ATTRS \
                and not _is_self_name(target.value.value):
            self.add(
                "TX-R03", target,
                f"direct {verb} '.{target.value.attr}[...]' on another "
                f"object's plan registry bypasses the swap/rollback "
                f"pin bookkeeping",
                ERROR,
                hint="use PlanCache.swap_entry(name, entry, "
                     "tenant=...) / rollback(...) / commit(...)")


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------

def _register_module_jits(tree: ast.Module, al: _Aliases,
                          visitor: _Visitor) -> None:
    """Pre-pass: collect every module-level jitted function and its
    static argnames BEFORE the main walk, so call sites earlier in the
    file still get TX-J03 coverage."""
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            statics = _jit_decoration(node, al)
            if statics is not None:
                visitor.jitted_statics[node.name] = statics
            if any(al.is_lru_cache(d) for d in node.decorator_list):
                # memoized kernel builders: their ARGUMENTS key the
                # compile cache, so grid taint reaching them is TX-J07
                visitor.memoized_builders.add(node.name)
        elif isinstance(node, ast.Assign) \
                and isinstance(node.value, ast.Call) \
                and al.is_jax_jit(node.value.func) \
                and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            visitor.jitted_statics[node.targets[0].id] = \
                _static_names_from_call(node.value, None)


def lint_source(source: str, path: str = "<string>") -> List[LintFinding]:
    """Run every JAX AST rule over one source text."""
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [LintFinding(
            rule_id="TX-E00", severity=ERROR, path=path,
            line=e.lineno or 0,
            message=f"source does not parse: {e.msg}")]
    al = _Aliases.collect(tree)
    visitor = _Visitor(path, al)
    _register_module_jits(tree, al, visitor)
    visitor.visit(tree)
    visitor.finalize()
    return visitor.findings


def lint_file(path: str) -> List[LintFinding]:
    with open(path, encoding="utf-8") as fh:
        return lint_source(fh.read(), path)


def abstract_probe(fn, *arg_specs) -> List[LintFinding]:
    """Confirm compile-path defects by ABSTRACT tracing — ``jax.eval_shape``
    runs the function with shape/dtype-only values: no device buffer is
    allocated, no XLA program compiled, no kernel executed. Defects the
    AST can't see statically (host transfers / concretization behind
    dynamic dispatch) surface as typed exceptions here; float64 results
    surface in the output aval dtypes.

    ``arg_specs`` are ``jax.ShapeDtypeStruct``s (or arrays, used only
    for their avals)."""
    import jax
    import jax.numpy as jnp  # noqa: F401  (fn under probe usually needs it)

    name = getattr(fn, "__name__", repr(fn))
    findings: List[LintFinding] = []
    try:
        out = jax.eval_shape(fn, *arg_specs)
    except jax.errors.TracerArrayConversionError as e:
        findings.append(LintFinding(
            rule_id="TX-J01", severity=ERROR, subject=name,
            message=f"abstract probe of {name!r}: traced value converted "
                    f"to a host numpy array ({type(e).__name__})",
            hint="replace np.* with jnp.* inside the device function"))
        return findings
    except (jax.errors.TracerBoolConversionError,
            jax.errors.ConcretizationTypeError) as e:
        findings.append(LintFinding(
            rule_id="TX-J05", severity=ERROR, subject=name,
            message=f"abstract probe of {name!r}: Python control flow "
                    f"required a concrete traced value "
                    f"({type(e).__name__})",
            hint="use lax.cond / lax.while_loop / jnp.where, or mark the "
                 "argument static"))
        return findings
    import jax.tree_util as jtu
    for leaf in jtu.tree_leaves(out):
        dtype = getattr(leaf, "dtype", None)
        if dtype is not None and str(dtype) == "float64":
            findings.append(LintFinding(
                rule_id="TX-J04", severity=WARNING, subject=name,
                message=f"abstract probe of {name!r}: output has dtype "
                        f"float64",
                hint="cast to float32 before returning; TPUs have no "
                     "native f64 path"))
    return findings
