"""Cross-procedure lint rules over the whole-program call graph.

Where rules_jax.py asks "does this call appear *directly* inside the
guarded scope", these rules ask "is it reachable *on any call path*":

- **TX-X01** — a blocking primitive (``time.sleep``, sync ``open()``
  file I/O, ``.block_until_ready()``, an un-awaited ``sleep``)
  reachable from a ``serving/`` async handler through any chain of
  sync helpers.  Interprocedural TX-J10.
- **TX-X02** — a host transfer (``.item()``,
  ``.block_until_ready()``) or clock/telemetry emission reachable
  from inside a jitted body through helper calls.  Interprocedural
  TX-J01/TX-O01.
- **TX-X03** — the event-loop/thread race detector: an attribute of a
  ``serving/`` class written both from event-loop context and from
  executor-thread context without a blessed channel
  (``call_soon_threadsafe``, the ``swap_entry``/``rollback``/
  ``commit`` hot-swap API, ``atomic_write_json``, an explicit
  ``Lock`` guard).  The finding carries BOTH conflicting chains.
- **TX-X04** — a raw ``open(w/a/x)`` to a live (non-tmp, non-lock)
  path reachable from any snapshot/fingerprint/profile-persist entry
  point.  Interprocedural TX-R04.

Findings anchor at the violating call site (so inline
``# tx-lint: disable=TX-X0n`` works there) and carry the full call
chain in ``LintFinding.chain``.
"""
from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

from .callgraph import (BLESSED_PERSIST_SINKS, BLESSED_TRACE_SINKS,
                        CallGraph, FuncInfo, build_graph)
from .findings import LintFinding, rule_severity

__all__ = ["lint_cross_procedure", "PERSIST_ENTRY_MARKERS"]

#: a function whose dotted name contains one of these is a persistence
#: entry point for TX-X04 (ServingStateSnapshot.capture,
#: save_fingerprints, persist_process_profiles, ...)
PERSIST_ENTRY_MARKERS = ("snapshot", "fingerprint", "persist")


def _is_serving(path: str) -> bool:
    return "serving" in path.replace("\\", "/").split("/")


def _finding(rule: str, f: FuncInfo, line: int, message: str,
             chain: Sequence[str], hint: str) -> LintFinding:
    return LintFinding(
        rule_id=rule, severity=rule_severity(rule), path=f.path,
        line=line, message=message, hint=hint, chain=tuple(chain))


def _site_chain(g: CallGraph, chain: List[str], f: FuncInfo,
                desc: str, line: int) -> List[str]:
    return g.chain_labels(chain) + [f"{desc} ({f.path}:{line})"]


# ---------------------------------------------------------------------------
# TX-X01 — blocking work reachable from a serving async handler
# ---------------------------------------------------------------------------

def _rule_x01(g: CallGraph) -> List[LintFinding]:
    roots = [gid for gid, f in g.functions.items()
             if f.is_async and _is_serving(f.path)]
    chains = g.reachable(roots, follow_async=True, kinds=("call",))
    out: List[LintFinding] = []
    for gid, chain in chains.items():
        f = g.functions[gid]
        if f.is_async or len(chain) < 2:
            continue  # direct sites in the handler are TX-J10's
        root = g.functions[chain[0]]
        for desc, line in f.blocking:
            out.append(_finding(
                "TX-X01", f, line,
                f"blocking {desc}() in {f.qual} is reachable from "
                f"serving async handler {root.qual} through "
                f"{len(chain) - 1} call(s) — it stalls the event loop "
                f"for every in-flight request",
                _site_chain(g, chain, f, desc, line),
                "route the blocking work through "
                "loop.run_in_executor(...) or make the chain async"))
    return out


# ---------------------------------------------------------------------------
# TX-X02 — host transfer / clock / telemetry reachable from a jitted body
# ---------------------------------------------------------------------------

def _rule_x02(g: CallGraph) -> List[LintFinding]:
    roots = [gid for gid, f in g.functions.items() if f.jitted]
    chains = g.reachable(roots, follow_async=False, kinds=("call",),
                         stop_at=BLESSED_TRACE_SINKS)
    out: List[LintFinding] = []
    for gid, chain in chains.items():
        f = g.functions[gid]
        if len(chain) < 2 or f.jitted:
            continue  # local sites are TX-J01/TX-O01's
        root = g.functions[chain[0]]
        for desc, line in f.hostcalls:
            out.append(_finding(
                "TX-X02", f, line,
                f"{desc} in {f.qual} executes at TRACE time of jitted "
                f"{root.qual} ({len(chain) - 1} call(s) away): a host "
                f"transfer forces a device sync per trace, a clock or "
                f"telemetry emission records compilation and bakes "
                f"into the program",
                _site_chain(g, chain, f, desc, line),
                "hoist the host work out of the traced call tree (or "
                "wrap a deliberate trace-cost probe in "
                "compile_time.section)"))
    return out


# ---------------------------------------------------------------------------
# TX-X03 — event-loop vs executor-thread attribute races
# ---------------------------------------------------------------------------

def _rule_x03(g: CallGraph) -> List[LintFinding]:
    loop_ctx, thread_ctx = g.contexts()
    # (class, attr) -> [(func, line, blessed, context, chain)]
    writes: Dict[Tuple[str, str], List[tuple]] = {}
    for gid, f in g.functions.items():
        if f.cls is None or not _is_serving(f.path) or not f.writes:
            continue
        in_loop = gid in loop_ctx
        in_thread = gid in thread_ctx
        if not (in_loop or in_thread):
            continue
        for attr, line, blessed in f.writes:
            sites = writes.setdefault((f.cls, attr), [])
            if in_loop:
                sites.append((f, line, blessed, "loop", loop_ctx[gid]))
            if in_thread:
                sites.append((f, line, blessed, "thread",
                              thread_ctx[gid]))
    out: List[LintFinding] = []
    for (cls, attr), sites in sorted(writes.items()):
        loops = [s for s in sites if s[3] == "loop"]
        threads = [s for s in sites if s[3] == "thread"]
        if not loops or not threads:
            continue
        if all(s[2] for s in sites):
            continue  # every write is lock-guarded / blessed — safe
        # anchor at an unblessed site, preferring the event-loop side
        anchor = next((s for s in loops if not s[2]),
                      next((s for s in threads if not s[2]), loops[0]))
        lf, lline = loops[0][0], loops[0][1]
        tf, tline = threads[0][0], threads[0][1]
        chain = (["[event-loop path]"]
                 + _site_chain(g, loops[0][4], lf,
                               f"write {cls}.{attr}", lline)
                 + ["[executor-thread path]"]
                 + _site_chain(g, threads[0][4], tf,
                               f"write {cls}.{attr}", tline))
        out.append(_finding(
            "TX-X03", anchor[0], anchor[1],
            f"attribute {cls}.{attr} is written from event-loop "
            f"context ({lf.qual}, {lf.path}:{lline}) AND from "
            f"executor-thread context ({tf.qual}, {tf.path}:{tline}) "
            f"without a blessed channel — a torn/stale read is a "
            f"matter of scheduling",
            chain,
            "marshal the write through loop.call_soon_threadsafe, "
            "the PlanCache swap/rollback/commit API, or guard BOTH "
            "sides with the same threading.Lock"))
    return out


# ---------------------------------------------------------------------------
# TX-X04 — raw open(w/a/x) reachable from a persistence entry point
# ---------------------------------------------------------------------------

def _rule_x04(g: CallGraph) -> List[LintFinding]:
    roots = [gid for gid, f in g.functions.items()
             if any(m in f.qual.lower() for m in PERSIST_ENTRY_MARKERS)]
    chains = g.reachable(roots, follow_async=True, kinds=("call",),
                         stop_at=BLESSED_PERSIST_SINKS)
    out: List[LintFinding] = []
    for gid, chain in chains.items():
        f = g.functions[gid]
        root = g.functions[chain[0]]
        for line, mode in f.openw:
            out.append(_finding(
                "TX-X04", f, line,
                f"raw open(mode={mode!r}) in {f.qual} is reachable "
                f"from persistence entry point {root.qual}"
                + (f" through {len(chain) - 1} call(s)"
                   if len(chain) > 1 else "")
                + " — a crash mid-write leaves a TORN document",
                _site_chain(g, chain, f, f"open(..., {mode!r})", line),
                "write through observability.store.atomic_write_json "
                "(tmp file + os.replace), or stage into a "
                "tmp-marked path"))
    return out


def lint_cross_procedure(summaries: Sequence[dict]
                         ) -> List[LintFinding]:
    """Run TX-X01..TX-X04 over the linked call graph of per-file
    summaries (callgraph.analyze_file). Deterministic order: rule id,
    then path, then line."""
    g = build_graph(summaries)
    findings = (_rule_x01(g) + _rule_x02(g) + _rule_x03(g)
                + _rule_x04(g))
    findings.sort(key=lambda f: (f.rule_id, f.path or "", f.line))
    return findings
