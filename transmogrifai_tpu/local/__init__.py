"""Engine-free local scoring (SURVEY §2.13; local/src/main/scala/com/
salesforce/op/local/OpWorkflowModelLocal.scala:52)."""
from .scoring import ScoreFunction, load_score_function, score_function_for

__all__ = ["ScoreFunction", "load_score_function", "score_function_for"]
