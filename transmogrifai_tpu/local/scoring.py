"""Local (engine-free) scoring: model -> plain ``dict -> dict`` function.

TPU-native port of the reference local module
(local/src/main/scala/com/salesforce/op/local/
{OpWorkflowModelLocal.scala:52,88-120, OpWorkflowRunnerLocal.scala:41}):
a saved workflow model becomes a pure-Python scoring closure that folds
one record's values through every stage's row-level ``transform_value``
path in DAG order — no Spark/MLeap (reference) and no batch engine
here; models already predict from plain arrays so nothing needs
conversion.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..features.feature import Feature, topo_layers
from ..features.generator import FeatureGeneratorStage
from ..types import FeatureType, Prediction

__all__ = ["ScoreFunction", "load_score_function", "score_function_for"]


def _unbox(value: Any) -> Any:
    if isinstance(value, Prediction):
        return dict(value.value)
    if isinstance(value, FeatureType):
        v = value.value
        if isinstance(v, np.ndarray):
            return v.tolist()
        if isinstance(v, (set, frozenset)):
            return sorted(v)
        return v
    return value


class ScoreFunction:
    """``fn(record: dict) -> dict`` over the fitted DAG
    (reference model.scoreFunction, OpWorkflowModelLocal.scala:88)."""

    def __init__(self, model, result_features: Optional[Sequence[Feature]]
                 = None):
        self.model = model
        self.result_features = list(result_features
                                    or model.result_features)
        self.raw_features = model.raw_features()
        self._plan = [s for layer in topo_layers(self.result_features)
                      for s in layer
                      if not isinstance(s, FeatureGeneratorStage)]

    def __call__(self, record: Dict[str, Any]) -> Dict[str, Any]:
        values: Dict[str, FeatureType] = {}
        for f in self.raw_features:
            gen = f.origin_stage
            if isinstance(gen, FeatureGeneratorStage):
                try:
                    raw = gen.extract_fn(record)
                except Exception:
                    raw = None
            else:
                raw = record.get(f.name)
            if raw is None and f.is_response:
                # label-free scoring: prediction stages ignore the label
                # value, so any placeholder works (NaN for non-nullables)
                try:
                    values[f.name] = f.ftype.from_any(None)
                except Exception:
                    values[f.name] = f.ftype(0.0)  # ignored by predictors
                continue
            values[f.name] = raw if isinstance(raw, FeatureType) \
                else f.ftype.from_any(raw)
        for stage in self._plan:
            ins = [values[f.name] for f in stage.input_features]
            out = stage.get_output()
            values[out.name] = stage.transform_value(*ins)
        return {f.name: _unbox(values[f.name])
                for f in self.result_features}

    def score_batch(self, records: Sequence[Dict[str, Any]]
                    ) -> List[Dict[str, Any]]:
        return [self(r) for r in records]


def score_function_for(model) -> ScoreFunction:
    """Build a local scoring closure from an in-memory fitted model."""
    return ScoreFunction(model)


def load_score_function(path: str) -> ScoreFunction:
    """Load a saved model directory into a scoring closure
    (reference OpWorkflowRunnerLocal:41)."""
    from ..workflow.persistence import load_model
    return ScoreFunction(load_model(path))
