"""Local (engine-free) scoring: model -> plain ``dict -> dict`` function.

TPU-native port of the reference local module
(local/src/main/scala/com/salesforce/op/local/
{OpWorkflowModelLocal.scala:52,88-120, OpWorkflowRunnerLocal.scala:41}):
a saved workflow model becomes a pure-Python scoring closure that folds
one record's values through every stage's row-level ``transform_value``
path in DAG order — no Spark/MLeap (reference) and no batch engine for
single records; models already predict from plain arrays so nothing
needs conversion.

Batch scoring (``score_batch``) routes through the compiled
:class:`~transmogrifai_tpu.serving.ScoringPlan` — the fitted DAG fused
into shape-bucketed XLA programs (docs/serving.md) — instead of looping
the per-record path, and falls back to that loop only if the plan
cannot compile.
"""
from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..features.feature import Feature, topo_layers
from ..features.generator import FeatureGeneratorStage
from ..types import FeatureType, Prediction

_log = logging.getLogger(__name__)

__all__ = ["ScoreFunction", "load_score_function", "score_function_for"]


def _unbox(value: Any) -> Any:
    if isinstance(value, Prediction):
        return dict(value.value)
    if isinstance(value, FeatureType):
        v = value.value
        if isinstance(v, np.ndarray):
            return v.tolist()
        if isinstance(v, (set, frozenset)):
            try:
                return sorted(v)
            except TypeError:
                # mixed-type members (e.g. {1, "a"}) are unorderable in
                # py3 — fall back to a deterministic repr ordering
                return sorted(v, key=repr)
        return v
    return value


class ScoreFunction:
    """``fn(record: dict) -> dict`` over the fitted DAG
    (reference model.scoreFunction, OpWorkflowModelLocal.scala:88)."""

    def __init__(self, model, result_features: Optional[Sequence[Feature]]
                 = None, guardrails: Any = False):
        self.model = model
        self.result_features = list(result_features
                                    or model.result_features)
        self.raw_features = model.raw_features()
        self._plan = [s for layer in topo_layers(self.result_features)
                      for s in layer
                      if not isinstance(s, FeatureGeneratorStage)]
        #: extraction failures observed so far (an extract fn raising on
        #: a record nulls that field instead of failing the request —
        #: but silently-nulled fields destroy scores invisibly, so the
        #: count and the per-feature breakdown are exposed here)
        self.extract_errors = 0
        self.extract_error_fields: Dict[str, int] = {}
        #: serving guardrails (docs/serving_guardrails.md): False = off
        #: (byte-identical legacy behavior), True = defaults, or a dict
        #: of ``ScoringPlan.with_guardrails`` kwargs. Guarded batches
        #: attach a ``"_guard"`` entry to quarantined/invalidated rows
        #: and stash the full ledger on ``last_guard_result``.
        self.guardrails = guardrails
        self.last_guard_result = None
        self._compiled_plan = None
        self._compiled_plan_error = None

    def _extract_raw(self, record: Dict[str, Any]
                     ) -> Dict[str, FeatureType]:
        """One record -> boxed raw feature values, with the serving
        edge's error policy: a raising extract fn nulls the field (and
        is counted), a missing response gets an ignored placeholder."""
        values: Dict[str, FeatureType] = {}
        for f in self.raw_features:
            gen = f.origin_stage
            if isinstance(gen, FeatureGeneratorStage):
                try:
                    raw = gen.extract_fn(record)
                except Exception:
                    raw = None
                    self._note_extract_error(f.name)
            else:
                raw = record.get(f.name)
            if raw is None and f.is_response:
                # label-free scoring: prediction stages ignore the label
                # value, so any placeholder works (NaN for non-nullables)
                try:
                    values[f.name] = f.ftype.from_any(None)
                except Exception:
                    values[f.name] = f.ftype(0.0)  # ignored by predictors
                continue
            values[f.name] = raw if isinstance(raw, FeatureType) \
                else f.ftype.from_any(raw)
        return values

    def _note_extract_error(self, feature_name: str) -> None:
        self.extract_errors += 1
        count = self.extract_error_fields.get(feature_name, 0) + 1
        self.extract_error_fields[feature_name] = count
        if count == 1:  # one warning per feature, not per record
            _log.warning(
                "extract fn for raw feature %r raised; the field is "
                "scored as missing (see ScoreFunction.extract_errors)",
                feature_name)

    def __call__(self, record: Dict[str, Any]) -> Dict[str, Any]:
        values = self._extract_raw(record)
        for stage in self._plan:
            ins = [values[f.name] for f in stage.input_features]
            out = stage.get_output()
            values[out.name] = stage.transform_value(*ins)
        return {f.name: _unbox(values[f.name])
                for f in self.result_features}

    # -- batch path --------------------------------------------------------
    def _scoring_plan(self):
        """The compiled ScoringPlan for this model (built once; a plan
        that cannot compile is remembered so every batch does not
        re-attempt and re-log)."""
        if self._compiled_plan is None and self._compiled_plan_error is None:
            from ..serving import ScoringPlan
            try:
                if self.guardrails:
                    # guarded scoring mutates plan state (breaker,
                    # sentinel sketches): use a DEDICATED plan, never
                    # the model's shared cached one
                    kwargs = (self.guardrails
                              if isinstance(self.guardrails, dict) else {})
                    self._compiled_plan = ScoringPlan(
                        self.model).compile().with_guardrails(**kwargs)
                else:
                    builder = getattr(self.model, "scoring_plan", None)
                    # share the model's cached plan when it has one
                    self._compiled_plan = builder() if callable(builder) \
                        else ScoringPlan(self.model).compile()
            except Exception as e:
                self._compiled_plan_error = e
                _log.warning(
                    "compiled scoring plan unavailable (%r); score_batch "
                    "falls back to the per-record loop", e)
        return self._compiled_plan

    def score_batch(self, records: Sequence[Dict[str, Any]],
                    engine: str = "compiled") -> List[Dict[str, Any]]:
        """Score many records in one shot. ``engine="compiled"``
        (default) runs the whole batch through the fused XLA plan —
        one host->device->host round-trip per shape bucket;
        ``engine="records"`` keeps the legacy per-record loop."""
        if engine not in ("compiled", "records"):
            raise ValueError(
                f"engine must be 'compiled' or 'records', got {engine!r}")
        records = list(records)
        if engine == "records" or not records:
            return [self(r) for r in records]
        plan = self._scoring_plan()
        if plan is None:
            return [self(r) for r in records]
        if self.guardrails:
            return self._score_batch_guarded(plan, records)
        from ..features.columns import Dataset, FeatureColumn
        boxed = [self._extract_raw(r) for r in records]
        ds = Dataset({
            f.name: FeatureColumn.from_values(
                f.ftype, [b[f.name] for b in boxed])
            for f in self.raw_features})
        scored = plan.score_raw_dataset(ds)
        cols = [scored[f.name] for f in self.result_features]
        return [{f.name: _unbox(col.boxed(i))
                 for f, col in zip(self.result_features, cols)}
                for i in range(len(records))]

    def _score_batch_guarded(self, plan, records
                             ) -> List[Dict[str, Any]]:
        """Guarded batch path: admission + output guards + breaker
        (docs/serving_guardrails.md). Quarantined/invalidated rows
        carry a ``"_guard"`` entry with machine-readable reasons
        instead of silently emitting NaN scores."""
        result = plan.score_guarded(records)
        self.last_guard_result = result
        cols = [result.scored[f.name] for f in self.result_features]
        by_row: Dict[int, List] = {}
        for r in result.quarantined:
            by_row.setdefault(r.row, []).append(
                {"kind": "quarantined", **r.to_json()})
        for r in result.invalidated:
            by_row.setdefault(r.row, []).append(
                {"kind": "invalidated", **r.to_json()})
        out = []
        for i in range(len(records)):
            if i in by_row:
                # no garbage scores for guarded-out rows: the reasons
                # ARE the payload (NaN predictions don't box anyway)
                row = {f.name: None for f in self.result_features}
                row["_guard"] = by_row[i]
            else:
                row = {f.name: _unbox(col.boxed(i))
                       for f, col in zip(self.result_features, cols)}
            out.append(row)
        return out


def score_function_for(model) -> ScoreFunction:
    """Build a local scoring closure from an in-memory fitted model."""
    return ScoreFunction(model)


def load_score_function(path: str) -> ScoreFunction:
    """Load a saved model directory into a scoring closure
    (reference OpWorkflowRunnerLocal:41)."""
    from ..workflow.persistence import load_model
    return ScoreFunction(load_model(path))
