"""Model zoo: JAX-native estimators replacing the reference's Spark MLlib
wrappers (SURVEY §2.8; core/.../sparkwrappers/specific/OpPredictorWrapper.scala:67).
"""
from .base import (ClassifierModel, PredictionModel, Predictor,
                   RegressionModel, check_is_response_values)
from .linear import (LinearRegression, LinearRegressionModel, LinearSVC,
                     LinearSVCModel, LogisticRegression,
                     LogisticRegressionModel)

__all__ = [
    "Predictor", "PredictionModel", "ClassifierModel", "RegressionModel",
    "check_is_response_values",
    "LogisticRegression", "LogisticRegressionModel",
    "LinearRegression", "LinearRegressionModel",
    "LinearSVC", "LinearSVCModel",
]
