"""Model zoo: JAX-native estimators replacing the reference's Spark MLlib
wrappers (SURVEY §2.8; core/.../sparkwrappers/specific/OpPredictorWrapper.scala:67).
"""
from .base import (ClassifierModel, PredictionModel, Predictor,
                   RegressionModel, check_is_response_values)
from .linear import (LinearRegression, LinearRegressionModel, LinearSVC,
                     LinearSVCModel, LogisticRegression,
                     LogisticRegressionModel)
from .bayes import NaiveBayes, NaiveBayesModel
from .external import ExternalEstimator, ExternalModel, wrap_estimator
from .glm import (GeneralizedLinearRegression,
                  GeneralizedLinearRegressionModel)
from .isotonic import (IsotonicRegressionCalibrator,
                       IsotonicRegressionCalibratorModel, pava)
from .mlp import (MultilayerPerceptronClassifier,
                  MultilayerPerceptronClassifierModel)
from .trees import (DecisionTreeClassifier, DecisionTreeRegressor,
                    GBTClassifier, GBTClassifierModel, GBTRegressor,
                    GBTRegressorModel, RandomForestClassifier,
                    RandomForestRegressor, TreeEnsembleClassifierModel,
                    GBTMulticlassClassifierModel,
                    TreeEnsembleRegressorModel, XGBoostClassifier,
                    XGBoostRegressor)

__all__ = [
    "Predictor", "PredictionModel", "ClassifierModel", "RegressionModel",
    "check_is_response_values",
    "LogisticRegression", "LogisticRegressionModel",
    "LinearRegression", "LinearRegressionModel",
    "LinearSVC", "LinearSVCModel",
    "DecisionTreeClassifier", "DecisionTreeRegressor",
    "RandomForestClassifier", "RandomForestRegressor",
    "GBTClassifier", "GBTClassifierModel",
    "GBTRegressor", "GBTRegressorModel",
    "IsotonicRegressionCalibrator", "IsotonicRegressionCalibratorModel",
    "pava",
    "XGBoostClassifier", "XGBoostRegressor",
    "GBTMulticlassClassifierModel",
    "TreeEnsembleClassifierModel", "TreeEnsembleRegressorModel",
    "NaiveBayes", "NaiveBayesModel",
    "ExternalEstimator", "ExternalModel", "wrap_estimator",
    "GeneralizedLinearRegression", "GeneralizedLinearRegressionModel",
    "MultilayerPerceptronClassifier", "MultilayerPerceptronClassifierModel",
]
