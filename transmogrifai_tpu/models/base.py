"""Predictor / PredictionModel stage bases — the model-zoo kernel.

TPU-native re-design of the reference predictor wrapper layer
(core/src/main/scala/com/salesforce/op/stages/sparkwrappers/specific/
OpPredictorWrapper.scala:67 and OpPredictorWrapperModel /
OpProbabilisticClassifierModel in the same directory). Where the reference
wraps a Spark MLlib ``Predictor`` and converts the fitted Spark model into
a row-level ``transformFn``, here each model family is implemented
natively in JAX: ``fit_arrays`` consumes dense device arrays (the
label vector and the feature matrix) and ``predict_arrays`` is an
XLA-compiled batch function returning dense predictions — the
``Prediction`` map objects of the reference (features/.../types/
Maps.scala:302) are synthesized only at the row-scoring edge by
``PredictionColumn``.

Input contract matches the reference exactly: input 1 is the RealNN label
(must be a response), input 2 the OPVector feature matrix (must not be) —
core/src/main/scala/com/salesforce/op/stages/impl/CheckIsResponseValues.scala.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ..features.columns import FeatureColumn, PredictionColumn
from ..stages.base import BinaryEstimator, BinaryModel
from ..types import OPVector, Prediction, RealNN

__all__ = ["Predictor", "PredictionModel", "ClassifierModel",
           "RegressionModel", "check_is_response_values",
           "FamilyPreconditionError", "subset_grid", "pad_cand_idx"]


class FamilyPreconditionError(ValueError):
    """The data violates a model family's preconditions (e.g.
    NaiveBayes on negative features). Subclasses ValueError so the
    sequential per-fold handler still drops the candidate with NaN
    metrics; the batched/device kernel entry points raise THIS type so
    the validator can distinguish 'family not applicable' from a
    genuine kernel bug (which must propagate, not silently fall back
    to the slow host path)."""


def check_is_response_values(label, features) -> None:
    """Reference CheckIsResponseValues: in1 must be a response, in2 must
    not be."""
    if not label.is_response:
        raise ValueError(
            f"Label input {label.name!r} must be a response feature")
    if features.is_response:
        raise ValueError(
            f"Feature-vector input {features.name!r} must not be a response")


def num_classes(y) -> int:
    """Class count for integer-coded labels: max+1 with a floor of 2
    (binary) — the single definition of the idiom every classifier
    family uses."""
    return max(2, int(np.max(y)) + 1 if len(y) else 2)


def subset_grid(grid, cand_idx):
    """Candidate-subset selection for the racing scheduler
    (selector/racing.py): ``cand_idx`` is an index vector into ``grid``;
    the batched fold x grid kernels then evaluate only those candidates
    — the returned metric matrix column order follows ``cand_idx``.
    None selects the whole grid. Subsetting happens at the grid-dict
    level BEFORE hyperparameters become traced vectors, so fidelity
    stays a dynamic-value/shape change, never a new static."""
    grid = list(grid) or [{}]
    if cand_idx is None:
        return grid
    return [grid[int(i)] for i in np.asarray(cand_idx).ravel()]


def pad_cand_idx(cand_idx, shards: int):
    """Pad a racing rung's candidate-index vector to a multiple of the
    mesh's ``models`` shard count: the last index is repeated (a
    duplicate evaluation whose metric column is discarded), and the
    caller slices the returned matrix back to ``n_valid`` columns.

    Two properties the sharded search leans on:

    - **shape stability** — every rung's candidate axis lands on the
      ``multiple-of-shards`` lattice, so alive counts that differ only
      by the pruning trajectory reuse the same compiled rung program
      (the serving plan's shape-bucket idiom applied to ``cand_idx``),
    - **decision invariance** — padding happens BEFORE dispatch and is
      sliced off before any metric is journaled or ranked, so the
      pruning decision (and the journal) see the identical candidate
      set on every device count.

    Returns ``(padded index list, n_valid)``; the validity mask is
    implicit — exactly the first ``n_valid`` columns are real.
    """
    idx = [int(i) for i in np.asarray(cand_idx).ravel()]
    if not idx:
        raise ValueError("cand_idx must not be empty")
    shards = max(1, int(shards))
    pad = (-len(idx)) % shards
    return idx + [idx[-1]] * pad, len(idx)


def check_fold_classes(y, masks) -> None:
    """Batched-CV parity precondition: the sequential fallback sizes
    class-dependent parameters from each fold's OWN train labels, so a
    fold whose train mask misses a class would get a different
    architecture than the batched lane. Raise NotImplementedError (the
    validator then falls back to sequential fits) in that case."""
    y = np.asarray(y)
    n_all = len(np.unique(y))
    for row in np.asarray(masks):
        if len(np.unique(y[row > 0])) != n_all:
            raise NotImplementedError(
                "a fold's train split lacks a label class; per-fold "
                "architectures would differ")


class Predictor(BinaryEstimator):
    """Estimator over (RealNN label, OPVector features) -> Prediction."""

    input_types = (RealNN, OPVector)
    output_type = Prediction

    def check_input_constraints(self, features) -> None:
        check_is_response_values(*features)

    def fit_columns(self, cols: List[FeatureColumn]) -> "PredictionModel":
        y = np.asarray(cols[0].data, dtype=np.float64)
        X = np.asarray(cols[1].data, dtype=np.float64)
        model = self.fit_arrays(X, y)
        model.vector_metadata = cols[1].metadata
        return model

    def fit_arrays(self, X: np.ndarray, y: np.ndarray) -> "PredictionModel":
        raise NotImplementedError

    def fit_arrays_guarded(self, X: np.ndarray, y: np.ndarray
                           ) -> "PredictionModel":
        """``fit_arrays`` behind the runtime fault-injection site
        (runtime/faults.py, scope ``family`` / site ``fit``). The
        sequential validation paths dispatch candidates through here so
        host-path fits are deterministically fault-injectable — and
        hence quarantine-testable — exactly like device dispatches.
        Free when no injector is active."""
        from ..runtime.faults import maybe_inject
        maybe_inject("family", type(self).__name__, "fit")
        return self.fit_arrays(X, y)

    # -- hyperparameter grid support ---------------------------------------
    def with_params(self, **params) -> "Predictor":
        """A copy of this estimator with ctor params overridden — the
        grid-point expansion primitive (reference ParamMap copies,
        tuning/OpValidator.scala:293)."""
        kwargs = self.get_params()
        kwargs.pop("uid", None)
        kwargs.update(params)
        return type(self)(**kwargs)


class PredictionModel(BinaryModel):
    """Fitted model: OPVector batch -> PredictionColumn.

    Scoring uses only the feature-vector input; the label column (wired
    for uid/DAG symmetry with the estimator) is ignored, so score-time
    data without real labels works (reference OpPredictionModel
    transforms only the features column).
    """

    input_types = (RealNN, OPVector)
    output_type = Prediction
    #: vector metadata of the training feature matrix (for insights/LOCO)
    vector_metadata = None

    def check_input_constraints(self, features) -> None:
        check_is_response_values(*features)

    def transform_columns(self, cols: List[FeatureColumn]) -> PredictionColumn:
        X = np.asarray(cols[-1].data, dtype=np.float64)
        return self.predict_arrays(X)

    def predict_arrays(self, X: np.ndarray) -> PredictionColumn:
        raise NotImplementedError

    def transform_value(self, *values: Any) -> Prediction:
        vec = values[-1]
        arr = np.asarray(vec.value if hasattr(vec, "value") else vec,
                         dtype=np.float64).reshape(1, -1)
        return self.predict_arrays(arr).boxed(0)

    # -- compiled-serving lowering (serving/plan.py) -----------------------
    def raw_arrays(self, X):
        """jnp kernel producing this model's RAW output (margins for
        classifiers, values for regressors) from the feature matrix —
        the array-level predict lowering. The plan funnels the result
        through ``prediction_from_raw`` host-side, so wrapper semantics
        (probabilities, argmax/threshold) stay the model's own. Models
        without a kernel keep this default and fall back to numpy."""
        raise NotImplementedError(
            f"{type(self).__name__} has no array predict kernel")

    def supports_arrays(self) -> bool:
        return (type(self).raw_arrays is not PredictionModel.raw_arrays)

    def transform_arrays(self, arrays):
        return self.raw_arrays(arrays[-1])


class ClassifierModel(PredictionModel):
    """Probabilistic classifier: produces prediction + rawPrediction +
    probability (reference OpProbabilisticClassifierModel)."""

    def predict_raw(self, X: np.ndarray) -> np.ndarray:
        """(n, k) raw margins/scores."""
        raise NotImplementedError

    def raw_to_probability(self, raw: np.ndarray) -> np.ndarray:
        """Default: max-shifted softmax over the raw margins."""
        raw = raw - np.max(raw, axis=1, keepdims=True)
        e = np.exp(raw)
        return e / np.sum(e, axis=1, keepdims=True)

    def prediction_from_raw(self, raw: np.ndarray) -> PredictionColumn:
        """Assemble the Prediction column from precomputed raw margins
        (the batched validator evaluation path computes raw for many
        candidates in one device program, then funnels each through
        here so wrapper semantics stay the model's own)."""
        raw = np.asarray(raw, dtype=np.float64)
        prob = np.asarray(self.raw_to_probability(raw), dtype=np.float64)
        pred = np.argmax(prob, axis=1).astype(np.float64)
        return PredictionColumn.from_arrays(pred, probability=prob,
                                            raw_prediction=raw)

    def predict_arrays(self, X: np.ndarray) -> PredictionColumn:
        return self.prediction_from_raw(self.predict_raw(X))


class RegressionModel(PredictionModel):
    """Regressor: prediction only (reference OpPredictionModel)."""

    def predict_values(self, X: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def prediction_from_raw(self, raw: np.ndarray) -> PredictionColumn:
        """See ClassifierModel.prediction_from_raw — here ``raw`` is the
        predicted values vector."""
        return PredictionColumn.from_arrays(np.asarray(raw,
                                                       dtype=np.float64))

    def predict_arrays(self, X: np.ndarray) -> PredictionColumn:
        return self.prediction_from_raw(self.predict_values(X))
