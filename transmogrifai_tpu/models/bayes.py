"""Naive Bayes classifier.

TPU-native replacement for the reference's OpNaiveBayes
(core/.../classification/OpNaiveBayes.scala), wrapping MLlib NaiveBayes
(multinomial or bernoulli model type, additive smoothing). The fit is a
pair of segment-sums over class labels — one XLA program, no iteration.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.jax_setup import shard_map
from .base import (ClassifierModel, FamilyPreconditionError,
                   Predictor, check_fold_classes, num_classes,
                   subset_grid)

__all__ = ["NaiveBayes", "NaiveBayesModel"]


def _nb_closed_form(X, labels, mask, sm, num_classes: int,
                    model_type: str):
    """The one definition of the NB closed form (MLlib formulas):
    mask-weighted class counts + feature sums; ``mask`` of ones is the
    plain (sequential) fit. ``X`` must already be binarized for the
    bernoulli model type."""
    counts = jax.ops.segment_sum(mask, labels, num_segments=num_classes)
    pi = jnp.log(counts) - jnp.log(jnp.sum(counts))
    feat = jax.ops.segment_sum(X * mask[:, None], labels,
                               num_segments=num_classes)       # (K, d)
    if model_type == "bernoulli":
        theta = (jnp.log(feat + sm)
                 - jnp.log(counts[:, None] + 2.0 * sm))
    else:  # multinomial
        theta = (jnp.log(feat + sm)
                 - jnp.log(jnp.sum(feat, axis=1, keepdims=True)
                           + sm * X.shape[1]))
    return pi, theta


@functools.partial(jax.jit, static_argnames=("num_classes", "model_type"))
def _fit_nb(X, y, smoothing, *, num_classes: int, model_type: str):
    labels = y.astype(jnp.int32)
    if model_type == "bernoulli":
        X = (X != 0).astype(X.dtype)
    return _nb_closed_form(X, labels, jnp.ones_like(y), smoothing,
                           num_classes, model_type)


def _nb_masked_body(X, y, masks, smoothing, *, num_classes: int,
                    model_type: str):
    """Fold x grid candidates as one vmapped program: candidate =
    (fold mask, traced smoothing); mask-weighted class/feature sums
    equal the per-fold subset sums, so each lane reproduces the
    sequential fit up to summation order."""
    labels = y.astype(jnp.int32)
    if model_type == "bernoulli":
        X = (X != 0).astype(X.dtype)

    def one(mask, sm):
        return _nb_closed_form(X, labels, mask, sm, num_classes,
                               model_type)

    return jax.vmap(one)(masks, smoothing)


@functools.partial(jax.jit, static_argnames=("num_classes", "model_type"))
def _fit_nb_masked(X, y, masks, smoothing, *, num_classes: int,
                   model_type: str):
    return _nb_masked_body(X, y, masks, smoothing,
                           num_classes=num_classes, model_type=model_type)


def _nb_raw(pi, theta, Xv, model_type: str):
    """(nv, K) log-joint scores — the device twin of
    NaiveBayesModel.predict_raw."""
    if model_type == "bernoulli":
        Xb = (Xv != 0).astype(theta.dtype)
        neg = jnp.log1p(-jnp.minimum(jnp.exp(theta), 1 - 1e-12))
        return pi + Xb @ theta.T + (1.0 - Xb) @ neg.T
    return pi + Xv @ theta.T


def _nb_eval_body(X, y, masks, smoothing, fidx, Xv, yv, *,
                  num_classes: int, model_type: str, spec: tuple):
    """Fused fit + validation metric per candidate (device-resident
    search — see evaluators/device_metrics.py). Binary margins are the
    log-joint difference (argmax parity with the host softmax)."""
    from ..evaluators.device_metrics import (binary_from_raw_pair,
                                             metric_fn,
                                             softmax_probability)
    mfn = metric_fn(*spec)
    labels = y.astype(jnp.int32)
    Xf = (X != 0).astype(X.dtype) if model_type == "bernoulli" else X

    def one(mask, sm, fi):
        pi, theta = _nb_closed_form(Xf, labels, mask, sm, num_classes,
                                    model_type)
        raw = _nb_raw(pi, theta, Xv[fi], model_type)
        # host NaiveBayesModel ranks by the softmax of the log-joints
        scores = (binary_from_raw_pair(raw) if spec[0] == "binary"
                  else softmax_probability(raw))
        return mfn(yv[fi], scores)

    return jax.vmap(one)(masks, smoothing, fidx)


@functools.partial(jax.jit, static_argnames=("num_classes", "model_type",
                                             "spec"))
def _eval_nb_masked(X, y, masks, smoothing, fidx, Xv, yv, *,
                    num_classes: int, model_type: str, spec: tuple):
    return _nb_eval_body(X, y, masks, smoothing, fidx, Xv, yv,
                         num_classes=num_classes, model_type=model_type,
                         spec=spec)


@functools.lru_cache(maxsize=32)
def _nb_eval_mesh_kernel(num_classes: int, model_type: str, spec: tuple,
                         mesh):
    from jax.sharding import PartitionSpec as P

    def batched(masks, smoothing, fidx, X, y, Xv, yv):
        return _nb_eval_body(X, y, masks, smoothing, fidx, Xv, yv,
                             num_classes=num_classes,
                             model_type=model_type, spec=spec)

    return jax.jit(shard_map(
        batched, mesh=mesh,
        in_specs=(P("models", None), P("models"), P("models"),
                  P(), P(), P(), P()),
        out_specs=P("models"), check_vma=False))


@functools.lru_cache(maxsize=32)
def _nb_mesh_kernel(num_classes: int, model_type: str, mesh):
    """Candidate axis sharded over the mesh ``models`` axis (same
    mapping as the other family kernels); X/y replicate."""
    from jax.sharding import PartitionSpec as P

    def batched(masks, smoothing, X, y):
        return _nb_masked_body(X, y, masks, smoothing,
                               num_classes=num_classes,
                               model_type=model_type)

    return jax.jit(shard_map(
        batched, mesh=mesh,
        in_specs=(P("models", None), P("models"), P(), P()),
        out_specs=(P("models", None), P("models", None, None)),
        check_vma=False))


class NaiveBayes(Predictor):
    """Multinomial/Bernoulli naive Bayes (reference OpNaiveBayes.scala).
    Requires non-negative features, as in MLlib."""

    def __init__(self, smoothing: float = 1.0,
                 model_type: str = "multinomial",
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.smoothing = smoothing
        self.model_type = model_type

    def fit_fold_grid_arrays(self, X, y, masks, grid, mesh=None):
        """Validator fast path (see _ValidatorBase.validate): smoothing
        is traced, model_type groups statically; fold x grid candidates
        shard over the mesh ``models`` axis when a mesh is supplied
        (padded with all-ones masks)."""
        if (np.asarray(X) < 0).any():
            raise FamilyPreconditionError(
                "NaiveBayes requires non-negative features")
        grid = [dict(p) for p in (list(grid) or [{}])]
        allowed = {"smoothing", "model_type"}
        for p in grid:
            extra = set(p) - allowed
            if extra:
                raise NotImplementedError(
                    f"batched NaiveBayes kernel cannot vary {sorted(extra)}")
        masks = np.asarray(masks, dtype=np.float64)
        check_fold_classes(y, masks)
        k = num_classes(y)
        F = masks.shape[0]
        models = [[None] * len(grid) for _ in range(F)]
        groups = {}
        for gi, p in enumerate(grid):
            cand = self.with_params(**p)
            groups.setdefault(cand.model_type, []).append((gi, cand))
        X_j, y_j = jnp.asarray(X), jnp.asarray(y)
        from ..parallel.mesh import to_host
        from .trees import _pad_candidates
        for model_type, members in groups.items():
            gk = len(members)
            sm = np.tile([float(c.smoothing) for _, c in members], F)
            masks_c = np.repeat(masks, gk, axis=0)   # fold-major
            (masks_c, sm), _ = _pad_candidates(
                mesh, [masks_c, sm], masks_c.shape[1])
            if mesh is not None:
                fn = _nb_mesh_kernel(k, model_type, mesh)
                pi, theta = fn(jnp.asarray(masks_c), jnp.asarray(sm),
                               X_j, y_j)
            else:
                pi, theta = _fit_nb_masked(
                    X_j, y_j, jnp.asarray(masks_c), jnp.asarray(sm),
                    num_classes=k, model_type=model_type)
            pi, theta = to_host(pi), to_host(theta)
            for f in range(F):
                for j, (gi, _) in enumerate(members):
                    c = f * gk + j
                    models[f][gi] = NaiveBayesModel(
                        pi=pi[c], theta=theta[c], model_type=model_type)
        return models

    def eval_fold_grid_arrays(self, X, y, masks, grid, X_val, y_val,
                              spec, mesh=None, cand_idx=None):
        """Device-resident search: fused fit + validation metric, (F, G)
        matrix out (candidate grouping mirrors fit_fold_grid_arrays)."""
        if spec[0] not in ("binary", "multiclass"):
            raise NotImplementedError(
                "NaiveBayes device eval needs a classification metric")
        if (np.asarray(X) < 0).any():
            raise FamilyPreconditionError(
                "NaiveBayes requires non-negative features")
        k = num_classes(y)
        if spec[0] == "binary" and k != 2:
            raise NotImplementedError(
                "binary device eval needs binary labels")
        grid = [dict(p) for p in subset_grid(grid, cand_idx)]
        allowed = {"smoothing", "model_type"}
        for p in grid:
            extra = set(p) - allowed
            if extra:
                raise NotImplementedError(
                    f"batched NaiveBayes kernel cannot vary {sorted(extra)}")
        masks = np.asarray(masks, dtype=np.float64)
        check_fold_classes(y, masks)
        F = masks.shape[0]
        metric_mat = np.full((F, len(grid)), np.nan)
        groups = {}
        for gi, p in enumerate(grid):
            cand = self.with_params(**p)
            groups.setdefault(cand.model_type, []).append((gi, cand))
        X_j, y_j = jnp.asarray(X), jnp.asarray(y)
        Xv_j = jnp.asarray(np.asarray(X_val, dtype=np.float64))
        yv_j = jnp.asarray(np.asarray(y_val, dtype=np.float64))
        from ..parallel.mesh import to_host
        from .trees import _pad_candidates
        for model_type, members in groups.items():
            gk = len(members)
            sm = np.tile([float(c.smoothing) for _, c in members], F)
            masks_c = np.repeat(masks, gk, axis=0)   # fold-major
            fidx = np.repeat(np.arange(F, dtype=np.int32), gk)
            (masks_c, sm), count = _pad_candidates(
                mesh, [masks_c, sm], masks_c.shape[1])
            fidx = np.concatenate(
                [fidx, np.zeros(len(sm) - count, dtype=np.int32)])
            if mesh is not None:
                fn = _nb_eval_mesh_kernel(k, model_type, spec, mesh)
                mm = fn(jnp.asarray(masks_c), jnp.asarray(sm),
                        jnp.asarray(fidx), X_j, y_j, Xv_j, yv_j)
            else:
                mm = _eval_nb_masked(
                    X_j, y_j, jnp.asarray(masks_c), jnp.asarray(sm),
                    jnp.asarray(fidx), Xv_j, yv_j, num_classes=k,
                    model_type=model_type, spec=spec)
            mm = to_host(mm)[:count]
            for f in range(F):
                for j, (gi, _) in enumerate(members):
                    metric_mat[f, gi] = mm[f * gk + j]
        return metric_mat

    def fit_arrays(self, X: np.ndarray, y: np.ndarray) -> "NaiveBayesModel":
        if (X < 0).any():
            raise FamilyPreconditionError(
                "NaiveBayes requires non-negative features")
        k = num_classes(y)
        pi, theta = _fit_nb(jnp.asarray(X), jnp.asarray(y),
                            jnp.asarray(self.smoothing, dtype=jnp.float64),
                            num_classes=k, model_type=self.model_type)
        return NaiveBayesModel(pi=np.asarray(pi), theta=np.asarray(theta),
                               model_type=self.model_type)


class NaiveBayesModel(ClassifierModel):
    def __init__(self, pi, theta, model_type: str = "multinomial",
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.pi = np.asarray(pi, dtype=np.float64)          # (K,)
        self.theta = np.asarray(theta, dtype=np.float64)    # (K, d)
        self.model_type = model_type

    def predict_raw(self, X: np.ndarray) -> np.ndarray:
        if self.model_type == "bernoulli":
            Xb = (X != 0).astype(np.float64)
            neg = np.log1p(-np.minimum(np.exp(self.theta), 1 - 1e-12))
            return (self.pi + Xb @ self.theta.T
                    + (1.0 - Xb) @ neg.T)
        return self.pi + X @ self.theta.T

    def raw_arrays(self, X):
        import jax.numpy as jnp
        pi = jnp.asarray(self.pi, X.dtype)
        theta = jnp.asarray(self.theta, X.dtype)
        if self.model_type == "bernoulli":
            Xb = (X != 0).astype(X.dtype)
            neg = jnp.log1p(-jnp.minimum(jnp.exp(theta), 1 - 1e-12))
            return pi + Xb @ theta.T + (1.0 - Xb) @ neg.T
        return pi + X @ theta.T
