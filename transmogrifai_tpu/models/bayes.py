"""Naive Bayes classifier.

TPU-native replacement for the reference's OpNaiveBayes
(core/.../classification/OpNaiveBayes.scala), wrapping MLlib NaiveBayes
(multinomial or bernoulli model type, additive smoothing). The fit is a
pair of segment-sums over class labels — one XLA program, no iteration.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .base import ClassifierModel, Predictor, num_classes

__all__ = ["NaiveBayes", "NaiveBayesModel"]


@functools.partial(jax.jit, static_argnames=("num_classes", "model_type"))
def _fit_nb(X, y, smoothing, *, num_classes: int, model_type: str):
    labels = y.astype(jnp.int32)
    counts = jax.ops.segment_sum(jnp.ones_like(y), labels,
                                 num_segments=num_classes)
    pi = jnp.log(counts) - jnp.log(jnp.sum(counts))
    if model_type == "bernoulli":
        X = (X != 0).astype(X.dtype)
    feat = jax.ops.segment_sum(X, labels, num_segments=num_classes)  # (K, d)
    if model_type == "bernoulli":
        theta = (jnp.log(feat + smoothing)
                 - jnp.log(counts[:, None] + 2.0 * smoothing))
    else:  # multinomial
        theta = (jnp.log(feat + smoothing)
                 - jnp.log(jnp.sum(feat, axis=1, keepdims=True)
                           + smoothing * X.shape[1]))
    return pi, theta


class NaiveBayes(Predictor):
    """Multinomial/Bernoulli naive Bayes (reference OpNaiveBayes.scala).
    Requires non-negative features, as in MLlib."""

    def __init__(self, smoothing: float = 1.0,
                 model_type: str = "multinomial",
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.smoothing = smoothing
        self.model_type = model_type

    def fit_arrays(self, X: np.ndarray, y: np.ndarray) -> "NaiveBayesModel":
        if (X < 0).any():
            raise ValueError("NaiveBayes requires non-negative features")
        k = num_classes(y)
        pi, theta = _fit_nb(jnp.asarray(X), jnp.asarray(y),
                            jnp.asarray(self.smoothing, dtype=jnp.float64),
                            num_classes=k, model_type=self.model_type)
        return NaiveBayesModel(pi=np.asarray(pi), theta=np.asarray(theta),
                               model_type=self.model_type)


class NaiveBayesModel(ClassifierModel):
    def __init__(self, pi, theta, model_type: str = "multinomial",
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.pi = np.asarray(pi, dtype=np.float64)          # (K,)
        self.theta = np.asarray(theta, dtype=np.float64)    # (K, d)
        self.model_type = model_type

    def predict_raw(self, X: np.ndarray) -> np.ndarray:
        if self.model_type == "bernoulli":
            Xb = (X != 0).astype(np.float64)
            neg = np.log1p(-np.minimum(np.exp(self.theta), 1 - 1e-12))
            return (self.pi + Xb @ self.theta.T
                    + (1.0 - Xb) @ neg.T)
        return self.pi + X @ self.theta.T
