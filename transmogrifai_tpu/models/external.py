"""External-estimator adapter: plug ANY host estimator into the DAG.

The reference's generic Spark-wrapper layer lets arbitrary third-party
``Transformer``/``Estimator`` objects ride the pipeline as typed,
persistable stages (features/src/main/scala/com/salesforce/op/stages/
sparkwrappers/generic/{SparkWrapperParams.scala:43, SwUnaryTransformer,
SwBinaryEstimator}). This module is that bridge for the TPU-native
stack: :func:`wrap_estimator` turns a pair of plain functions — or any
object with the fit/predict duck type — into a :class:`Predictor` that
works with the ModelSelector (grids via ``with_params``), the workflow
DAG, and model save/load.

Persistence contract: the fitted *state* must be a dict of numpy arrays
and JSON-able scalars (exactly what ``persistence.encode_value``
round-trips), and the fit/predict functions must be importable
(``module:qualname``) — the same rule the rest of the framework applies
to lambdas. No pickle anywhere.
"""
from __future__ import annotations

from typing import Callable, Dict, Optional

import numpy as np

from ..features.columns import PredictionColumn
from .base import PredictionModel, Predictor

__all__ = ["ExternalEstimator", "ExternalModel", "wrap_estimator"]


class ExternalModel(PredictionModel):
    """Fitted external model: ``predict_fn(state, X)`` drives scoring.

    ``kind``:
    - "classification": predict_fn returns (n, k) class probabilities
      (rows need not be normalized; they are clipped + renormalized);
    - "regression": predict_fn returns (n,) values.
    """

    def __init__(self, state: Dict = None, predict_fn: Callable = None,
                 kind: str = "classification",
                 uid: Optional[str] = None):
        super().__init__(operation_name="externalModel", uid=uid)
        self.state = dict(state or {})
        self.predict_fn = predict_fn
        self.kind = kind

    def predict_arrays(self, X: np.ndarray) -> PredictionColumn:
        if self.predict_fn is None:
            raise ValueError(
                "ExternalModel has no predict_fn (was it importable at "
                "save time? see workflow/persistence.py encode_value)")
        out = np.asarray(self.predict_fn(self.state, np.asarray(X)),
                         dtype=np.float64)
        if self.kind == "regression":
            return PredictionColumn.from_arrays(out.reshape(-1))
        if out.ndim != 2:
            raise ValueError(
                f"classification predict_fn must return (n, k) "
                f"probabilities; got shape {out.shape}")
        prob = np.clip(out, 0.0, None)
        prob = prob / np.maximum(prob.sum(axis=1, keepdims=True), 1e-12)
        pred = np.argmax(prob, axis=1).astype(np.float64)
        # raw = log-probabilities (monotone in prob, finite)
        raw = np.log(np.maximum(prob, 1e-12))
        return PredictionColumn.from_arrays(pred, probability=prob,
                                            raw_prediction=raw)


class ExternalEstimator(Predictor):
    """See module docstring. ``params`` are the hyperparameters handed
    to ``fit_fn`` — the selector's grid points override them via
    ``with_params`` (merged, not replaced), so an external family
    competes in the model race exactly like a native one."""

    def __init__(self, fit_fn: Callable = None,
                 predict_fn: Callable = None,
                 kind: str = "classification",
                 params: Dict = None,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        if kind not in ("classification", "regression"):
            raise ValueError(f"kind must be classification|regression, "
                             f"got {kind!r}")
        self.fit_fn = fit_fn
        self.predict_fn = predict_fn
        self.kind = kind
        self.params = dict(params or {})

    def with_params(self, **params) -> "ExternalEstimator":
        merged = dict(self.params)
        merged.update(params)
        return type(self)(fit_fn=self.fit_fn, predict_fn=self.predict_fn,
                          kind=self.kind, params=merged)

    def fit_arrays(self, X: np.ndarray, y: np.ndarray) -> ExternalModel:
        if self.fit_fn is None:
            raise ValueError("ExternalEstimator requires fit_fn")
        state = self.fit_fn(np.asarray(X), np.asarray(y), **self.params)
        if not isinstance(state, dict):
            raise ValueError(
                f"external fit_fn must return a dict state (got "
                f"{type(state).__name__}) — arrays + JSON-able scalars, "
                f"the persistable contract")
        return ExternalModel(state=state, predict_fn=self.predict_fn,
                             kind=self.kind)


def wrap_estimator(fit_fn: Callable, predict_fn: Callable,
                   kind: str = "classification",
                   **params) -> ExternalEstimator:
    """Wrap ``fit_fn(X, y, **params) -> state`` and
    ``predict_fn(state, X) -> scores`` into a typed, persistable
    Predictor stage (the SwUnaryTransformer role).

    >>> est = wrap_estimator(my_fit, my_predict, kind="regression",
    ...                      alpha=0.1)
    >>> pred = est.set_input(label, features).get_output()

    Duck-typed objects adapt in one line each::

        wrap_estimator(lambda X, y, **p: {"est": ...},  # NOT persistable
                       ...)

    — but note the persistence rule: only *importable* functions and
    dict-of-array states survive save/load."""
    return ExternalEstimator(fit_fn=fit_fn, predict_fn=predict_fn,
                             kind=kind, params=params)
