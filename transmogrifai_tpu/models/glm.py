"""Generalized linear regression via IRLS.

TPU-native replacement for the reference's OpGeneralizedLinearRegression
(core/.../regression/OpGeneralizedLinearRegression.scala), wrapping
MLlib GeneralizedLinearRegression (families gaussian/binomial/poisson/
gamma/tweedie, canonical + explicit links, IRLS solver, L2 penalty).

IRLS here is a ``lax.fori_loop`` of weighted ridge solves — each
iteration is one (d+1, d+1) MXU solve, so the whole fit is a single
static-shape XLA program.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .base import Predictor, RegressionModel

__all__ = ["GeneralizedLinearRegression",
           "GeneralizedLinearRegressionModel"]

_DEFAULT_LINK = {"gaussian": "identity", "binomial": "logit",
                 "poisson": "log", "gamma": "inverse", "tweedie": "log"}

_EPS = 1e-10


def _link_fns(link: str):
    """(g(mu), g^{-1}(eta), g'(mu))"""
    if link == "identity":
        return (lambda mu: mu, lambda eta: eta, lambda mu: jnp.ones_like(mu))
    if link == "log":
        return (lambda mu: jnp.log(jnp.maximum(mu, _EPS)),
                lambda eta: jnp.exp(eta),
                lambda mu: 1.0 / jnp.maximum(mu, _EPS))
    if link == "logit":
        return (lambda mu: jnp.log(mu / (1 - mu)),
                jax.nn.sigmoid,
                lambda mu: 1.0 / jnp.maximum(mu * (1 - mu), _EPS))
    if link == "inverse":
        return (lambda mu: 1.0 / jnp.maximum(mu, _EPS),
                lambda eta: 1.0 / jnp.where(jnp.abs(eta) > _EPS, eta, _EPS),
                lambda mu: -1.0 / jnp.maximum(mu * mu, _EPS))
    if link == "sqrt":
        return (lambda mu: jnp.sqrt(jnp.maximum(mu, 0.0)),
                lambda eta: eta * eta,
                lambda mu: 0.5 / jnp.sqrt(jnp.maximum(mu, _EPS)))
    raise ValueError(f"Unknown link {link!r}")


def _variance_fn(family: str, var_power: float):
    if family == "gaussian":
        return lambda mu: jnp.ones_like(mu)
    if family == "binomial":
        return lambda mu: jnp.maximum(mu * (1 - mu), _EPS)
    if family == "poisson":
        return lambda mu: jnp.maximum(mu, _EPS)
    if family == "gamma":
        return lambda mu: jnp.maximum(mu * mu, _EPS)
    if family == "tweedie":
        return lambda mu: jnp.maximum(mu, _EPS) ** var_power
    raise ValueError(f"Unknown family {family!r}")


def _init_mu(family: str, y):
    if family == "binomial":
        return (y + 0.5) / 2.0
    if family in ("poisson", "gamma", "tweedie"):
        return jnp.maximum(y, 0.1)
    return y


@functools.partial(jax.jit, static_argnames=("family", "link", "max_iter",
                                             "fit_intercept"))
def _fit_glm_irls(X, y, reg, var_power, tol, *, family: str, link: str,
                  max_iter: int, fit_intercept: bool):
    n, d = X.shape
    g, ginv, gprime = _link_fns(link)
    var = _variance_fn(family, var_power)
    if fit_intercept:
        Xa = jnp.concatenate([X, jnp.ones((n, 1), X.dtype)], axis=1)
        pen = jnp.concatenate([jnp.full((d,), reg, X.dtype),
                               jnp.zeros((1,), X.dtype)])
    else:
        Xa, pen = X, jnp.full((d,), reg, X.dtype)
    p = Xa.shape[1]

    def irls_step(beta):
        eta = Xa @ beta
        mu = ginv(eta)
        gp = gprime(mu)
        z = eta + (y - mu) * gp
        w = 1.0 / jnp.maximum(var(mu) * gp * gp, _EPS)
        A = (Xa * w[:, None]).T @ Xa / n + jnp.diag(pen)
        b = (Xa * w[:, None]).T @ z / n
        return jnp.linalg.solve(A, b)

    def body(carry):
        beta, _, it = carry
        beta_next = irls_step(beta)
        delta = jnp.linalg.norm(beta_next - beta) \
            / jnp.maximum(jnp.linalg.norm(beta), 1.0)
        return beta_next, delta, it + 1

    def continuing(carry):
        _, delta, it = carry
        return (it == 0) | ((it < max_iter) & (delta >= tol))

    mu0 = _init_mu(family, y)
    eta0 = g(mu0)
    # start from the weighted LS fit of eta0
    beta0 = jnp.linalg.solve(Xa.T @ Xa / n + jnp.diag(pen + _EPS),
                             Xa.T @ eta0 / n)
    beta, _, _ = jax.lax.while_loop(
        continuing, body,
        (beta0, jnp.asarray(jnp.inf, X.dtype), jnp.asarray(0)))
    if fit_intercept:
        return beta[:d], beta[d]
    return beta, jnp.asarray(0.0, X.dtype)


class GeneralizedLinearRegression(Predictor):
    """GLM with IRLS (reference OpGeneralizedLinearRegression.scala)."""

    def __init__(self, family: str = "gaussian", link: Optional[str] = None,
                 reg_param: float = 0.0, max_iter: int = 25,
                 tol: float = 1e-6, fit_intercept: bool = True,
                 variance_power: float = 1.5, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.family = family
        self.link = link or _DEFAULT_LINK[family]
        self.reg_param = reg_param
        self.max_iter = max_iter
        self.tol = tol
        self.fit_intercept = fit_intercept
        self.variance_power = variance_power

    def fit_arrays(self, X: np.ndarray, y: np.ndarray
                   ) -> "GeneralizedLinearRegressionModel":
        w, b = _fit_glm_irls(
            jnp.asarray(X), jnp.asarray(y), self.reg_param,
            self.variance_power, self.tol, family=self.family,
            link=self.link, max_iter=self.max_iter,
            fit_intercept=self.fit_intercept)
        return GeneralizedLinearRegressionModel(
            coefficients=np.asarray(w), intercept=float(b), link=self.link)


class GeneralizedLinearRegressionModel(RegressionModel):
    def __init__(self, coefficients, intercept: float = 0.0,
                 link: str = "identity", uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.coefficients = np.asarray(coefficients, dtype=np.float64)
        self.intercept = float(intercept)
        self.link = link

    def predict_values(self, X: np.ndarray) -> np.ndarray:
        eta = X @ self.coefficients + self.intercept
        if self.link == "identity":
            return eta
        if self.link == "log":
            return np.exp(eta)
        if self.link == "logit":
            return 1.0 / (1.0 + np.exp(-eta))
        if self.link == "inverse":
            return 1.0 / np.where(np.abs(eta) > _EPS, eta, _EPS)
        if self.link == "sqrt":
            return eta * eta
        raise ValueError(f"Unknown link {self.link!r}")
