"""Generalized linear regression via IRLS.

TPU-native replacement for the reference's OpGeneralizedLinearRegression
(core/.../regression/OpGeneralizedLinearRegression.scala), wrapping
MLlib GeneralizedLinearRegression (families gaussian/binomial/poisson/
gamma/tweedie, canonical + explicit links, IRLS solver, L2 penalty).

IRLS here is a ``lax.fori_loop`` of weighted ridge solves — each
iteration is one (d+1, d+1) MXU solve, so the whole fit is a single
static-shape XLA program.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.jax_setup import shard_map
from .base import Predictor, RegressionModel, subset_grid

__all__ = ["GeneralizedLinearRegression",
           "GeneralizedLinearRegressionModel"]

_DEFAULT_LINK = {"gaussian": "identity", "binomial": "logit",
                 "poisson": "log", "gamma": "inverse", "tweedie": "log"}

_EPS = 1e-10


def _link_fns(link: str):
    """(g(mu), g^{-1}(eta), g'(mu))"""
    if link == "identity":
        return (lambda mu: mu, lambda eta: eta, lambda mu: jnp.ones_like(mu))
    if link == "log":
        return (lambda mu: jnp.log(jnp.maximum(mu, _EPS)),
                lambda eta: jnp.exp(eta),
                lambda mu: 1.0 / jnp.maximum(mu, _EPS))
    if link == "logit":
        return (lambda mu: jnp.log(mu / (1 - mu)),
                jax.nn.sigmoid,
                lambda mu: 1.0 / jnp.maximum(mu * (1 - mu), _EPS))
    if link == "inverse":
        return (lambda mu: 1.0 / jnp.maximum(mu, _EPS),
                lambda eta: 1.0 / jnp.where(jnp.abs(eta) > _EPS, eta, _EPS),
                lambda mu: -1.0 / jnp.maximum(mu * mu, _EPS))
    if link == "sqrt":
        return (lambda mu: jnp.sqrt(jnp.maximum(mu, 0.0)),
                lambda eta: eta * eta,
                lambda mu: 0.5 / jnp.sqrt(jnp.maximum(mu, _EPS)))
    raise ValueError(f"Unknown link {link!r}")


def _variance_fn(family: str, var_power: float):
    if family == "gaussian":
        return lambda mu: jnp.ones_like(mu)
    if family == "binomial":
        return lambda mu: jnp.maximum(mu * (1 - mu), _EPS)
    if family == "poisson":
        return lambda mu: jnp.maximum(mu, _EPS)
    if family == "gamma":
        return lambda mu: jnp.maximum(mu * mu, _EPS)
    if family == "tweedie":
        return lambda mu: jnp.maximum(mu, _EPS) ** var_power
    raise ValueError(f"Unknown family {family!r}")


def _init_mu(family: str, y):
    if family == "binomial":
        return (y + 0.5) / 2.0
    if family in ("poisson", "gamma", "tweedie"):
        return jnp.maximum(y, 0.1)
    return y


def _glm_irls_core(X, y, mask, reg, var_power, tol, *, family: str,
                   link: str, max_iter: int, fit_intercept: bool):
    """Masked weighted IRLS (the one GLM fit definition): ``mask`` of
    ones is the plain fit; 0/1 fold masks batch through vmap (each lane
    fits exactly its fold's rows — masked rows carry zero IRLS weight).
    Vmapped lanes run the while_loop in lockstep until all converge;
    each iteration is one tiny (d+1, d+1) solve, so lockstep is cheap
    (unlike L-BFGS line searches)."""
    n, d = X.shape
    g, ginv, gprime = _link_fns(link)
    var = _variance_fn(family, var_power)
    msum = jnp.maximum(jnp.sum(mask), 1.0)
    if fit_intercept:
        Xa = jnp.concatenate([X, jnp.ones((n, 1), X.dtype)], axis=1)
        pen = jnp.concatenate([jnp.full((d,), reg, X.dtype),
                               jnp.zeros((1,), X.dtype)])
    else:
        Xa, pen = X, jnp.full((d,), reg, X.dtype)

    def irls_step(beta):
        eta = Xa @ beta
        mu = ginv(eta)
        gp = gprime(mu)
        z = eta + (y - mu) * gp
        w = mask / jnp.maximum(var(mu) * gp * gp, _EPS)
        # masked (held-out) rows still flow through the nonlinearities
        # above and can produce inf/NaN (e.g. exp overflow under a log
        # link); 0 * NaN = NaN would poison the gram matrix, so zero
        # them EXPLICITLY. ONLY masked rows: a non-finite TRAIN row
        # must keep poisoning the lane, because the sequential per-fold
        # fit sees that row too — parity both ways
        w = jnp.where(mask > 0, w, 0.0)
        z = jnp.where(mask > 0, z, 0.0)
        A = (Xa * w[:, None]).T @ Xa / msum + jnp.diag(pen)
        b = (Xa * w[:, None]).T @ z / msum
        return jnp.linalg.solve(A, b)

    def body(carry):
        beta, _, it = carry
        beta_next = irls_step(beta)
        delta = jnp.linalg.norm(beta_next - beta) \
            / jnp.maximum(jnp.linalg.norm(beta), 1.0)
        return beta_next, delta, it + 1

    def continuing(carry):
        _, delta, it = carry
        return (it == 0) | ((it < max_iter) & (delta >= tol))

    mu0 = _init_mu(family, y)
    eta0 = g(mu0)
    eta0 = jnp.where(mask > 0, eta0, 0.0)
    # start from the masked weighted LS fit of eta0
    beta0 = jnp.linalg.solve(
        (Xa * mask[:, None]).T @ Xa / msum + jnp.diag(pen + _EPS),
        (Xa * mask[:, None]).T @ eta0 / msum)
    beta, _, _ = jax.lax.while_loop(
        continuing, body,
        (beta0, jnp.asarray(jnp.inf, X.dtype), jnp.asarray(0)))
    if fit_intercept:
        return beta[:d], beta[d]
    return beta, jnp.asarray(0.0, X.dtype)


@functools.partial(jax.jit, static_argnames=("family", "link", "max_iter",
                                             "fit_intercept"))
def _fit_glm_irls(X, y, reg, var_power, tol, *, family: str, link: str,
                  max_iter: int, fit_intercept: bool):
    return _glm_irls_core(X, y, jnp.ones_like(y), reg, var_power, tol,
                          family=family, link=link, max_iter=max_iter,
                          fit_intercept=fit_intercept)


def _glm_predict(beta, intercept, link: str, Xv):
    """Device twin of GeneralizedLinearRegressionModel.predict_values."""
    _, ginv, _ = _link_fns(link)
    return ginv(Xv @ beta + intercept)


@functools.partial(jax.jit, static_argnames=("family", "link", "max_iter",
                                             "fit_intercept"))
def _fit_glm_folds(X, y, masks, regs, var_powers, tol, *, family: str,
                   link: str, max_iter: int, fit_intercept: bool):
    return jax.vmap(
        lambda m, r, vp: _glm_irls_core(
            X, y, m, r, vp, tol, family=family, link=link,
            max_iter=max_iter, fit_intercept=fit_intercept)
    )(masks, regs, var_powers)


@functools.partial(jax.jit, static_argnames=("family", "link", "max_iter",
                                             "fit_intercept", "spec"))
def _eval_glm_folds(X, y, masks, regs, var_powers, fidx, Xv, yv, tol, *,
                    family: str, link: str, max_iter: int,
                    fit_intercept: bool, spec: tuple):
    from ..evaluators.device_metrics import metric_fn
    mfn = metric_fn(*spec)

    def one(m, r, vp, fi):
        beta, b0 = _glm_irls_core(
            X, y, m, r, vp, tol, family=family, link=link,
            max_iter=max_iter, fit_intercept=fit_intercept)
        return mfn(yv[fi], _glm_predict(beta, b0, link, Xv[fi]))

    return jax.vmap(one)(masks, regs, var_powers, fidx)


@functools.lru_cache(maxsize=32)
def _glm_fit_mesh_kernel(family: str, link: str, max_iter: int,
                         fit_intercept: bool, mesh):
    """Candidate axis sharded over the mesh ``models`` axis (same
    mapping as the sibling family kernels); X/y replicate."""
    from jax.sharding import PartitionSpec as P

    def batched(masks, regs, vps, X, y, tol):
        return jax.vmap(
            lambda m, r, vp: _glm_irls_core(
                X, y, m, r, vp, tol, family=family, link=link,
                max_iter=max_iter, fit_intercept=fit_intercept)
        )(masks, regs, vps)

    return jax.jit(shard_map(
        batched, mesh=mesh,
        in_specs=(P("models", None), P("models"), P("models"),
                  P(), P(), P()),
        out_specs=(P("models", None), P("models")), check_vma=False))


@functools.lru_cache(maxsize=32)
def _glm_eval_mesh_kernel(family: str, link: str, max_iter: int,
                          fit_intercept: bool, spec: tuple, mesh):
    from jax.sharding import PartitionSpec as P
    from ..evaluators.device_metrics import metric_fn
    mfn = metric_fn(*spec)

    def batched(masks, regs, vps, fidx, X, y, Xv, yv, tol):
        def one(m, r, vp, fi):
            beta, b0 = _glm_irls_core(
                X, y, m, r, vp, tol, family=family, link=link,
                max_iter=max_iter, fit_intercept=fit_intercept)
            return mfn(yv[fi], _glm_predict(beta, b0, link, Xv[fi]))
        return jax.vmap(one)(masks, regs, vps, fidx)

    return jax.jit(shard_map(
        batched, mesh=mesh,
        in_specs=(P("models", None), P("models"), P("models"),
                  P("models"), P(), P(), P(), P(), P()),
        out_specs=P("models"), check_vma=False))


class GeneralizedLinearRegression(Predictor):
    """GLM with IRLS (reference OpGeneralizedLinearRegression.scala)."""

    def __init__(self, family: str = "gaussian", link: Optional[str] = None,
                 reg_param: float = 0.0, max_iter: int = 25,
                 tol: float = 1e-6, fit_intercept: bool = True,
                 variance_power: float = 1.5, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.family = family
        self.link = link or _DEFAULT_LINK[family]
        self.reg_param = reg_param
        self.max_iter = max_iter
        self.tol = tol
        self.fit_intercept = fit_intercept
        self.variance_power = variance_power

    def fit_arrays(self, X: np.ndarray, y: np.ndarray
                   ) -> "GeneralizedLinearRegressionModel":
        w, b = _fit_glm_irls(
            jnp.asarray(X), jnp.asarray(y), self.reg_param,
            self.variance_power, self.tol, family=self.family,
            link=self.link, max_iter=self.max_iter,
            fit_intercept=self.fit_intercept)
        return GeneralizedLinearRegressionModel(
            coefficients=np.asarray(w), intercept=float(b), link=self.link)

    _GRID_ALLOWED = {"family", "link", "reg_param", "variance_power"}

    def _grid_groups(self, grid):
        """Group grid points by their static (family/link/intercept)
        config; reg/var_power trace. NotImplementedError on params the
        kernels can't handle (validator falls back sequential)."""
        grid = [dict(p) for p in (list(grid) or [{}])]
        for p in grid:
            extra = set(p) - self._GRID_ALLOWED
            if extra:
                raise NotImplementedError(
                    f"batched GLM kernel cannot vary {sorted(extra)}")
        groups = {}
        for gi, p in enumerate(grid):
            cand = self.with_params(**p)
            key = (cand.family, cand.link, cand.fit_intercept,
                   cand.max_iter)
            groups.setdefault(key, []).append((gi, cand))
        return grid, groups

    def _batched_groups(self, grid, masks, mesh):
        """One definition of the fold-major candidate layout shared by
        the fit and eval paths (change together): yields per static
        group (key, members, masks_c, regs, vps, fidx, count) with the
        candidate axis padded to the mesh shard count when sharding."""
        from .trees import _pad_candidates
        grid, groups = self._grid_groups(grid)
        masks = np.asarray(masks, dtype=np.float64)
        F = masks.shape[0]
        out = []
        for key, members in groups.items():
            gk = len(members)
            regs = np.tile([float(c.reg_param) for _, c in members], F)
            vps = np.tile([float(c.variance_power) for _, c in members],
                          F)
            masks_c = np.repeat(masks, gk, axis=0)   # fold-major
            fidx = np.repeat(np.arange(F, dtype=np.int32), gk)
            (masks_c, regs, vps), count = _pad_candidates(
                mesh, [masks_c, regs, vps], masks_c.shape[1])
            fidx = np.concatenate(
                [fidx, np.zeros(len(regs) - count, dtype=np.int32)])
            out.append((key, members, masks_c, regs, vps, fidx, count))
        return grid, F, out

    def fit_fold_grid_arrays(self, X, y, masks, grid, mesh=None):
        """Validator fast path: fold x grid candidates of each
        (family, link) group as one vmapped IRLS program, shardable
        over a mesh ``models`` axis."""
        from ..parallel.mesh import to_host
        X_j, y_j = jnp.asarray(X), jnp.asarray(y)
        grid, F, batches = self._batched_groups(grid, masks, mesh)
        models = [[None] * len(grid) for _ in range(F)]
        for (family, link, fit_int, mi), members, masks_c, regs, vps, \
                _, count in batches:
            gk = len(members)
            if mesh is not None:
                fn = _glm_fit_mesh_kernel(family, link, mi, fit_int,
                                          mesh)
                W, B = fn(jnp.asarray(masks_c), jnp.asarray(regs),
                          jnp.asarray(vps), X_j, y_j,
                          jnp.asarray(self.tol))
            else:
                W, B = _fit_glm_folds(
                    X_j, y_j, jnp.asarray(masks_c), jnp.asarray(regs),
                    jnp.asarray(vps), self.tol, family=family,
                    link=link, max_iter=mi, fit_intercept=fit_int)
            W, B = to_host(W)[:count], to_host(B)[:count]
            for f in range(F):
                for j, (gi, _) in enumerate(members):
                    c = f * gk + j
                    models[f][gi] = GeneralizedLinearRegressionModel(
                        coefficients=W[c], intercept=float(B[c]),
                        link=link)
        return models

    def eval_fold_grid_arrays(self, X, y, masks, grid, X_val, y_val,
                              spec, mesh=None, cand_idx=None):
        """Device-resident search: fused IRLS fit + validation metric,
        (F, G) matrix out."""
        from ..parallel.mesh import to_host
        if spec[0] != "regression":
            raise NotImplementedError(
                "GLM device eval needs a regression metric")
        X_j, y_j = jnp.asarray(X), jnp.asarray(y)
        Xv_j = jnp.asarray(np.asarray(X_val, dtype=np.float64))
        yv_j = jnp.asarray(np.asarray(y_val, dtype=np.float64))
        grid, F, batches = self._batched_groups(
            subset_grid(grid, cand_idx), masks, mesh)
        metric_mat = np.full((F, len(grid)), np.nan)
        for (family, link, fit_int, mi), members, masks_c, regs, vps, \
                fidx, count in batches:
            gk = len(members)
            if mesh is not None:
                fn = _glm_eval_mesh_kernel(family, link, mi, fit_int,
                                           spec, mesh)
                mm = fn(jnp.asarray(masks_c), jnp.asarray(regs),
                        jnp.asarray(vps), jnp.asarray(fidx), X_j, y_j,
                        Xv_j, yv_j, jnp.asarray(self.tol))
            else:
                mm = _eval_glm_folds(
                    X_j, y_j, jnp.asarray(masks_c), jnp.asarray(regs),
                    jnp.asarray(vps), jnp.asarray(fidx), Xv_j, yv_j,
                    self.tol, family=family, link=link, max_iter=mi,
                    fit_intercept=fit_int, spec=spec)
            mm = to_host(mm)[:count]
            for f in range(F):
                for j, (gi, _) in enumerate(members):
                    metric_mat[f, gi] = mm[f * gk + j]
        return metric_mat


class GeneralizedLinearRegressionModel(RegressionModel):
    def __init__(self, coefficients, intercept: float = 0.0,
                 link: str = "identity", uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.coefficients = np.asarray(coefficients, dtype=np.float64)
        self.intercept = float(intercept)
        self.link = link

    def predict_values(self, X: np.ndarray) -> np.ndarray:
        eta = X @ self.coefficients + self.intercept
        if self.link == "identity":
            return eta
        if self.link == "log":
            return np.exp(eta)
        if self.link == "logit":
            return 1.0 / (1.0 + np.exp(-eta))
        if self.link == "inverse":
            return 1.0 / np.where(np.abs(eta) > _EPS, eta, _EPS)
        if self.link == "sqrt":
            return eta * eta
        raise ValueError(f"Unknown link {self.link!r}")

    def raw_arrays(self, X):
        eta = X @ jnp.asarray(self.coefficients, X.dtype) + self.intercept
        if self.link == "identity":
            return eta
        if self.link == "log":
            return jnp.exp(eta)
        if self.link == "logit":
            return 1.0 / (1.0 + jnp.exp(-eta))
        if self.link == "inverse":
            return 1.0 / jnp.where(jnp.abs(eta) > _EPS, eta, _EPS)
        if self.link == "sqrt":
            return eta * eta
        raise ValueError(f"Unknown link {self.link!r}")
