"""IsotonicRegressionCalibrator: monotone score calibration.

TPU-native port of the reference IsotonicRegressionCalibrator
(core/src/main/scala/com/salesforce/op/stages/impl/regression/
IsotonicRegressionCalibrator.scala — a thin wrapper over Spark MLlib
IsotonicRegression): fit runs Pool-Adjacent-Violators (PAVA) over
(score, label) pairs and keeps the compressed (boundary, prediction)
pairs; prediction linearly interpolates between boundaries exactly as
MLlib's IsotonicRegressionModel does (clamped at the ends).

PAVA itself is the classic stack algorithm on sorted scores — O(n) on
host after an O(n log n) device-friendly sort; the fitted calibrator's
transform is a pure ``searchsorted`` + lerp, trivially jittable.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ..features.columns import FeatureColumn, PredictionColumn
from .base import Predictor, RegressionModel

__all__ = ["IsotonicRegressionCalibrator",
           "IsotonicRegressionCalibratorModel", "pava"]


def pava(x: np.ndarray, y: np.ndarray,
         w: Optional[np.ndarray] = None,
         increasing: bool = True) -> Tuple[np.ndarray, np.ndarray]:
    """Weighted isotonic fit: returns (boundaries, predictions), the
    compressed representation MLlib stores (adjacent equal fitted values
    merged; duplicate x pooled by weight)."""
    x = np.asarray(x, dtype=np.float64)
    y = np.asarray(y, dtype=np.float64)
    w = np.ones_like(y) if w is None else np.asarray(w, dtype=np.float64)
    if not increasing:
        b, p = pava(-x, y, w, increasing=True)
        return -b[::-1], p[::-1]
    order = np.lexsort((y, x))
    xs, ys, ws = x[order], y[order], w[order]
    # pool duplicate scores first (one block per distinct x)
    ux, inv = np.unique(xs, return_inverse=True)
    wsum = np.bincount(inv, weights=ws)
    ysum = np.bincount(inv, weights=ws * ys)
    means = ysum / wsum
    # PAVA stack: blocks of (weight, weighted-mean, x_lo, x_hi)
    blocks: List[List[float]] = []
    for i in range(len(ux)):
        blocks.append([wsum[i], means[i], ux[i], ux[i]])
        while len(blocks) > 1 and blocks[-2][1] >= blocks[-1][1]:
            w2, m2, lo2, hi2 = blocks.pop()
            w1, m1, lo1, hi1 = blocks.pop()
            wt = w1 + w2
            blocks.append([wt, (w1 * m1 + w2 * m2) / wt, lo1, hi2])
    boundaries: List[float] = []
    preds: List[float] = []
    for wt, m, lo, hi in blocks:
        if preds and preds[-1] == m:
            boundaries[-1] = hi       # merge equal-valued neighbors
            continue
        if lo == hi:
            boundaries.append(lo)
            preds.append(m)
        else:
            boundaries.extend([lo, hi])
            preds.extend([m, m])
    return np.asarray(boundaries), np.asarray(preds)


class IsotonicRegressionCalibrator(Predictor):
    """Calibrate a score against a label monotonically
    (reference IsotonicRegressionCalibrator.scala; input 1 the RealNN
    label, input 2 an OPVector whose ``feature_index`` column carries
    the score — MLlib's featureIndex param)."""

    def __init__(self, isotonic: bool = True, feature_index: int = 0,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.isotonic = isotonic
        self.feature_index = feature_index

    def fit_arrays(self, X: np.ndarray, y: np.ndarray
                   ) -> "IsotonicRegressionCalibratorModel":
        scores = np.asarray(X, dtype=np.float64)
        if scores.ndim == 2:
            scores = scores[:, self.feature_index]
        b, p = pava(scores, y, increasing=self.isotonic)
        return IsotonicRegressionCalibratorModel(
            boundaries=b, predictions=p,
            feature_index=self.feature_index)


class IsotonicRegressionCalibratorModel(RegressionModel):
    """Piecewise-linear monotone map (reference/MLlib
    IsotonicRegressionModel semantics: linear interpolation between
    boundaries, clamping outside)."""

    def __init__(self, boundaries=None, predictions=None,
                 feature_index: int = 0, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.boundaries = np.asarray(boundaries, dtype=np.float64)
        self.predictions = np.asarray(predictions, dtype=np.float64)
        self.feature_index = int(feature_index)

    def calibrate(self, scores: np.ndarray) -> np.ndarray:
        b, p = self.boundaries, self.predictions
        if b.size == 0:
            return np.zeros_like(scores)
        if b.size == 1:
            return np.full_like(scores, p[0])
        out = np.interp(scores, b, p)
        return np.clip(out, min(p[0], p[-1]), max(p[0], p[-1]))

    def predict_values(self, X: np.ndarray) -> np.ndarray:
        scores = np.asarray(X, dtype=np.float64)
        if scores.ndim == 2:
            scores = scores[:, self.feature_index]
        return self.calibrate(scores)

    def raw_arrays(self, X):
        import jax.numpy as jnp
        scores = X[:, self.feature_index] if X.ndim == 2 else X
        b = jnp.asarray(self.boundaries, scores.dtype)
        p = jnp.asarray(self.predictions, scores.dtype)
        if self.boundaries.size == 0:
            return jnp.zeros_like(scores)
        if self.boundaries.size == 1:
            return jnp.full_like(scores, self.predictions[0])
        out = jnp.interp(scores, b, p)
        lo = min(self.predictions[0], self.predictions[-1])
        hi = max(self.predictions[0], self.predictions[-1])
        return jnp.clip(out, lo, hi)
