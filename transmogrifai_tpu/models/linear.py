"""Linear model family: logistic regression, linear regression, linear SVC.

TPU-native replacements for the reference's Spark MLlib wrappers:
- OpLogisticRegression  (core/.../classification/OpLogisticRegression.scala:45)
- OpLinearRegression    (core/.../regression/OpLinearRegression.scala)
- OpLinearSVC           (core/.../classification/OpLinearSVC.scala)

Semantics follow MLlib where it matters for metric parity:
- optional internal standardization of features (penalty applied in the
  standardized space, coefficients mapped back),
- elastic-net penalty  regParam * (a*L1 + (1-a)/2 * L2),
- binary problems use binomial logistic loss, multiclass uses multinomial
  softmax (MLlib family="auto").

The optimizer is optax L-BFGS (or FISTA when L1 > 0) fully inside XLA —
see models/solvers.py. Fitting is a single jitted program per (shape)
so a hyperparameter grid can ``vmap`` over (reg_param, elastic_net).
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..features.columns import PredictionColumn
from .base import ClassifierModel, Predictor, RegressionModel, subset_grid
from .solvers import design_lipschitz, fista_minimize, lbfgs_minimize

__all__ = ["LogisticRegression", "LogisticRegressionModel",
           "LinearRegression", "LinearRegressionModel",
           "LinearSVC", "LinearSVCModel"]


# ---------------------------------------------------------------------------
# shared weighted-fit cores
#
# Every linear-family fit is expressed over ROW WEIGHTS ``w`` (1 for
# training rows, 0 otherwise) with reductions routed through ``_psum``:
# - single fit: w = ones — identical math to a plain fit;
# - fold x grid CV: w = fold masks, the whole grid batched with vmap
#   (parallel/cv.py uses exactly these cores, so the mesh path selects
#   the same winner as the sequential path);
# - multi-chip: ``axis_name`` set inside shard_map — row reductions
#   cross the mesh data axis via psum over ICI.
# ---------------------------------------------------------------------------

def _psum(x, axis_name: Optional[str]):
    return jax.lax.psum(x, axis_name) if axis_name else x


def _weighted_standardize(X, w, axis_name=None):
    """Weighted mean/std standardization (subset stats when w is a 0/1
    mask — matches fitting on the gathered rows exactly)."""
    wsum = jnp.maximum(_psum(jnp.sum(w), axis_name), 1e-12)
    mu = _psum(jnp.sum(X * w[:, None], axis=0), axis_name) / wsum
    var = _psum(jnp.sum(w[:, None] * (X - mu) ** 2, axis=0),
                axis_name) / wsum
    sigma = jnp.sqrt(var)
    # constant columns must be treated as such: float reduction noise
    # makes their variance ~1e-32 rather than exactly 0, and dividing
    # by sigma~1e-16 back-transforms into a gigantic coefficient whose
    # cancellation against the intercept quantizes every margin (seen
    # as 1/256-grid logits on one-hot OTHER columns). A RELATIVE floor
    # catches them; genuinely informative columns sit far above it.
    floor = 1e-9 * jnp.maximum(jnp.abs(mu), 1.0)
    safe = jnp.where(sigma > floor, sigma, 1.0)
    return (X - mu) / safe, mu, safe, wsum


def _unstandardize_coefs(w: jnp.ndarray, b: jnp.ndarray, mu: jnp.ndarray,
                         sigma: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Map coefficients fitted on standardized X back to the original
    feature space: w/sigma, b - (w/sigma).mu  (works for (d,) and (k,d))."""
    w_orig = w / sigma
    b_orig = b - w_orig @ mu if w.ndim == 1 else b - w_orig @ mu
    return w_orig, b_orig


def _prep(X, w, standardize: bool, axis_name):
    n, d = X.shape
    if standardize:
        return _weighted_standardize(X, w, axis_name)
    wsum = jnp.maximum(_psum(jnp.sum(w), axis_name), 1e-12)
    return X, jnp.zeros(d, X.dtype), jnp.ones(d, X.dtype), wsum


def binary_logistic_core(X, y, w, reg, alpha, *, fit_intercept: bool,
                         standardize: bool, max_iter: int, use_l1: bool,
                         axis_name: Optional[str] = None,
                         solver: str = "auto"):
    """Weighted binomial logistic fit -> (coefficients, intercept).

    solver="auto" uses L-BFGS for smooth penalties and FISTA when L1 is
    active; under a mesh (``axis_name``) or solver="fista" everything
    runs FISTA with a STATIC trip count — optax L-BFGS's data-dependent
    linesearch loops de-sync collective rendezvous across shards.
    """
    d = X.shape[1]
    Xs, mu, sigma, wsum = _prep(X, w, standardize, axis_name)
    s = 2.0 * y - 1.0  # {0,1} -> {-1,+1}
    l2 = reg * (1.0 - alpha)
    l1 = reg * alpha
    # SHARD-LOCAL objective: the data term sums local rows only (global
    # wsum), the reg term is divided across shards — so an explicit psum
    # of the gradient reconstructs the exact global gradient. Autodiff
    # therefore never transposes a collective (see fista_minimize).
    nshards = _psum(jnp.asarray(1.0, Xs.dtype), axis_name)

    def smooth(params):
        wv, b = params[:d], params[d]
        m = Xs @ wv + (b if fit_intercept else 0.0)
        return (jnp.sum(w * jnp.logaddexp(0.0, -s * m)) / wsum
                + 0.5 * l2 * jnp.sum(wv * wv) / nshards)

    w0 = jnp.zeros(d + 1, Xs.dtype)
    force_fista = solver == "fista" or axis_name is not None
    if use_l1 or force_fista:
        mask = jnp.concatenate([jnp.ones(d, Xs.dtype),
                                jnp.zeros(1, Xs.dtype)])
        lip = design_lipschitz(Xs, l2, curvature_bound=0.25, w=w,
                               axis_name=axis_name) + 0.25
        params = fista_minimize(smooth, l1, w0, lip, max_iter=max_iter * 5,
                                tol=0.0 if force_fista else 1e-7,
                                l1_mask=mask, grad_psum_axis=axis_name)
    else:
        params = lbfgs_minimize(smooth, w0, max_iter=max_iter)
    wv, b = params[:d], jnp.where(fit_intercept, params[d], 0.0)
    return _unstandardize_coefs(wv, b, mu, sigma)


def linear_regression_core(X, y, w, reg, alpha, *, fit_intercept: bool,
                           standardize: bool, max_iter: int, use_l1: bool,
                           axis_name: Optional[str] = None,
                           solver: str = "auto"):
    """Weighted OLS/ridge/elastic-net fit -> (coefficients, intercept).
    Non-L1 solves closed-form normal equations (loop-free, mesh-safe);
    L1 runs FISTA with a static trip count under a mesh."""
    d = X.shape[1]
    Xs, mu, sigma, wsum = _prep(X, w, standardize, axis_name)
    ybar = (_psum(jnp.sum(w * y), axis_name) / wsum if fit_intercept
            else jnp.asarray(0.0, Xs.dtype))
    yc = y - ybar
    l2 = reg * (1.0 - alpha)
    l1 = reg * alpha

    if not use_l1:
        # ridge normal equations on the MXU (reference: MLlib "normal"
        # solver / breeze L-BFGS; one (d,d) psum-reduced solve here)
        A = (_psum(Xs.T @ (w[:, None] * Xs), axis_name) / wsum
             + l2 * jnp.eye(d, dtype=Xs.dtype))
        wv = jnp.linalg.solve(A, _psum(Xs.T @ (w * yc), axis_name) / wsum)
    else:
        nshards = _psum(jnp.asarray(1.0, Xs.dtype), axis_name)

        def smooth(wv):     # shard-local; solver psums the gradient
            r = Xs @ wv - yc
            return (jnp.sum(w * r * r) / (2.0 * wsum)
                    + 0.5 * l2 * jnp.sum(wv * wv) / nshards)
        lip = design_lipschitz(Xs, l2, curvature_bound=1.0, w=w,
                               axis_name=axis_name) + 1e-3
        wv = fista_minimize(smooth, l1, jnp.zeros(d, Xs.dtype), lip,
                            max_iter=max_iter * 5,
                            tol=0.0 if (solver == "fista"
                                        or axis_name is not None) else 1e-7,
                            grad_psum_axis=axis_name)
    w_orig = wv / sigma
    b = ybar - w_orig @ mu if fit_intercept else jnp.asarray(0.0, Xs.dtype)
    return w_orig, b


def linear_svc_core(X, y, w, reg, alpha, *, fit_intercept: bool,
                    standardize: bool, max_iter: int, use_l1: bool = False,
                    axis_name: Optional[str] = None, solver: str = "auto"):
    """Weighted L2 squared-hinge SVM fit -> (coefficients, intercept).
    The reference's LinearSVC uses hinge + OWL-QN; squared hinge is the
    smooth TPU-friendly variant with near-identical decision boundaries
    (documented deviation). ``alpha``/``use_l1`` accepted for kernel-
    signature uniformity; L1 is not part of MLlib LinearSVC."""
    d = X.shape[1]
    Xs, mu, sigma, wsum = _prep(X, w, standardize, axis_name)
    s = 2.0 * y - 1.0
    nshards = _psum(jnp.asarray(1.0, Xs.dtype), axis_name)

    def loss(params):       # shard-local; solver psums the gradient
        wv, b = params[:d], params[d]
        m = Xs @ wv + (b if fit_intercept else 0.0)
        viol = jnp.maximum(0.0, 1.0 - s * m)
        return (jnp.sum(w * viol * viol) / wsum
                + 0.5 * reg * jnp.sum(wv * wv) / nshards)

    w0 = jnp.zeros(d + 1, Xs.dtype)
    if solver == "fista" or axis_name is not None:
        # squared hinge has phi'' <= 2
        lip = design_lipschitz(Xs, reg, curvature_bound=2.0, w=w,
                               axis_name=axis_name) + 2.0
        params = fista_minimize(loss, 0.0, w0, lip, max_iter=max_iter * 5,
                                tol=0.0, grad_psum_axis=axis_name)
    else:
        params = lbfgs_minimize(loss, w0, max_iter=max_iter)
    wv, b = params[:d], jnp.where(fit_intercept, params[d], 0.0)
    return _unstandardize_coefs(wv, b, mu, sigma)


# ---------------------------------------------------------------------------
# logistic regression
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("fit_intercept", "standardize",
                                             "max_iter", "use_l1"))
def _fit_binary_logistic(X, y, reg, alpha, *, fit_intercept: bool,
                         standardize: bool, max_iter: int, use_l1: bool):
    return binary_logistic_core(
        X, y, jnp.ones(X.shape[0], X.dtype), reg, alpha,
        fit_intercept=fit_intercept, standardize=standardize,
        max_iter=max_iter, use_l1=use_l1)


@functools.partial(jax.jit, static_argnames=("fit_intercept", "standardize",
                                             "max_iter", "use_l1", "k"))
def _fit_multinomial_logistic(X, y, reg, alpha, *, k: int,
                              fit_intercept: bool, standardize: bool,
                              max_iter: int, use_l1: bool):
    n, d = X.shape
    Xs, mu, sigma, _ = _prep(X, jnp.ones(n, X.dtype), standardize, None)
    onehot = jax.nn.one_hot(y.astype(jnp.int32), k, dtype=Xs.dtype)
    l2 = reg * (1.0 - alpha)
    l1 = reg * alpha

    def smooth(params):
        W = params[:, :d]
        b = params[:, d] if fit_intercept else 0.0
        logits = Xs @ W.T + b
        ll = jnp.mean(jnp.sum(onehot * jax.nn.log_softmax(logits), axis=1))
        return -ll + 0.5 * l2 * jnp.sum(W * W)

    W0 = jnp.zeros((k, d + 1), Xs.dtype)
    if use_l1:
        mask = jnp.concatenate(
            [jnp.ones((k, d), Xs.dtype), jnp.zeros((k, 1), Xs.dtype)], axis=1)
        lip = design_lipschitz(Xs, l2, curvature_bound=0.5) + 0.5
        params = fista_minimize(smooth, l1, W0, lip, max_iter=max_iter * 5,
                                l1_mask=mask)
    else:
        params = lbfgs_minimize(smooth, W0, max_iter=max_iter)
    W = params[:, :d]
    b = params[:, d] if fit_intercept else jnp.zeros(k, Xs.dtype)
    return _unstandardize_coefs(W, b, mu, sigma)


def _grid_to_reg_alpha(estimator, grid,
                       allowed=("reg_param", "elastic_net_param")):
    """(G, 2) [reg, alpha] array from grid dicts; params a dict omits
    fall back to the ESTIMATOR's configured values — matching what the
    sequential path's ``with_params`` produces. NotImplementedError for
    params the batched kernel can't trace (validator falls back to the
    sequential per-candidate path)."""
    out = np.zeros((len(grid), 2))
    for i, params in enumerate(grid):
        extra = set(params) - set(allowed)
        if extra:
            raise NotImplementedError(
                f"batched kernel cannot vary {sorted(extra)}")
        out[i, 0] = params.get("reg_param", getattr(estimator, "reg_param",
                                                    0.0))
        out[i, 1] = params.get("elastic_net_param",
                               getattr(estimator, "elastic_net_param", 0.0))
    return out


class LogisticRegression(Predictor):
    """Binomial/multinomial logistic regression
    (reference OpLogisticRegression.scala:45)."""

    def __init__(self, reg_param: float = 0.0, elastic_net_param: float = 0.0,
                 max_iter: int = 100, tol: float = 1e-6,
                 fit_intercept: bool = True, standardization: bool = True,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.reg_param = reg_param
        self.elastic_net_param = elastic_net_param
        self.max_iter = max_iter
        self.tol = tol
        self.fit_intercept = fit_intercept
        self.standardization = standardization

    def fit_arrays(self, X: np.ndarray, y: np.ndarray
                   ) -> "LogisticRegressionModel":
        Xj = jnp.asarray(X)
        yj = jnp.asarray(y)
        k = int(np.max(y)) + 1 if len(y) else 2
        use_l1 = self.reg_param * self.elastic_net_param > 0
        if k <= 2:
            w, b = _fit_binary_logistic(
                Xj, yj, self.reg_param, self.elastic_net_param,
                fit_intercept=self.fit_intercept,
                standardize=self.standardization,
                max_iter=self.max_iter, use_l1=use_l1)
        else:
            w, b = _fit_multinomial_logistic(
                Xj, yj, self.reg_param, self.elastic_net_param, k=k,
                fit_intercept=self.fit_intercept,
                standardize=self.standardization,
                max_iter=self.max_iter, use_l1=use_l1)
        return LogisticRegressionModel(coefficients=np.asarray(w),
                                       intercept=np.asarray(b))

    def fit_fold_grid_arrays(self, X, y, masks, grid, mesh=None):
        """All (fold, grid point) candidates in one batched XLA program
        (optionally sharded over a ("models", "data") mesh) — reference
        OpValidator.scala:270-310 task parallelism. Binary only."""
        if len(y) and int(np.max(y)) + 1 > 2:
            raise NotImplementedError("batched kernel is binary-only")
        from ..parallel.cv import fit_linear_fold_grid
        ga = _grid_to_reg_alpha(self, grid)
        params = fit_linear_fold_grid(
            "logistic", X, y, masks, ga, mesh=mesh,
            fit_intercept=self.fit_intercept,
            standardize=self.standardization, max_iter=self.max_iter)
        d = X.shape[1]
        return [[LogisticRegressionModel(p[:d], p[d]) for p in row]
                for row in params]

    def eval_fold_grid_arrays(self, X, y, masks, grid, X_val, y_val,
                              spec, mesh=None, cand_idx=None):
        """Device-resident search: fit + validation metric for every
        candidate in one program, (F, G) metric matrix out (see
        parallel/cv.eval_linear_fold_grid). Binary margins.
        ``cand_idx`` (racing rungs) restricts to a candidate subset —
        the (reg, alpha) vectors stay traced values, so subsetting is a
        shape change, never a retrace of new statics."""
        if spec[0] != "binary":
            raise NotImplementedError("logistic device eval is binary-only")
        if len(y) and int(np.max(y)) + 1 > 2:
            raise NotImplementedError("batched kernel is binary-only")
        from ..parallel.cv import eval_linear_fold_grid
        ga = _grid_to_reg_alpha(self, subset_grid(grid, cand_idx))
        return eval_linear_fold_grid(
            "logistic", X, y, masks, ga, X_val, y_val, spec, mesh=mesh,
            fit_intercept=self.fit_intercept,
            standardize=self.standardization, max_iter=self.max_iter)


class LogisticRegressionModel(ClassifierModel):
    def __init__(self, coefficients, intercept, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.coefficients = np.asarray(coefficients, dtype=np.float64)
        self.intercept = np.asarray(intercept, dtype=np.float64)

    def predict_raw(self, X: np.ndarray) -> np.ndarray:
        if self.coefficients.ndim == 1:
            m = X @ self.coefficients + float(self.intercept)
            return np.stack([-m, m], axis=1)
        return X @ self.coefficients.T + self.intercept

    def raw_arrays(self, X):
        c = jnp.asarray(self.coefficients, X.dtype)
        if self.coefficients.ndim == 1:
            m = X @ c + float(self.intercept)
            return jnp.stack([-m, m], axis=1)
        return X @ c.T + jnp.asarray(self.intercept, X.dtype)


# ---------------------------------------------------------------------------
# linear regression
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("fit_intercept", "standardize",
                                             "max_iter", "use_l1"))
def _fit_linear_regression(X, y, reg, alpha, *, fit_intercept: bool,
                           standardize: bool, max_iter: int, use_l1: bool):
    return linear_regression_core(
        X, y, jnp.ones(X.shape[0], X.dtype), reg, alpha,
        fit_intercept=fit_intercept, standardize=standardize,
        max_iter=max_iter, use_l1=use_l1)


class LinearRegression(Predictor):
    """OLS / ridge / elastic-net linear regression
    (reference OpLinearRegression.scala)."""

    def __init__(self, reg_param: float = 0.0, elastic_net_param: float = 0.0,
                 max_iter: int = 100, tol: float = 1e-6,
                 fit_intercept: bool = True, standardization: bool = True,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.reg_param = reg_param
        self.elastic_net_param = elastic_net_param
        self.max_iter = max_iter
        self.tol = tol
        self.fit_intercept = fit_intercept
        self.standardization = standardization

    def fit_arrays(self, X: np.ndarray, y: np.ndarray
                   ) -> "LinearRegressionModel":
        use_l1 = self.reg_param * self.elastic_net_param > 0
        w, b = _fit_linear_regression(
            jnp.asarray(X), jnp.asarray(y), self.reg_param,
            self.elastic_net_param, fit_intercept=self.fit_intercept,
            standardize=self.standardization, max_iter=self.max_iter,
            use_l1=use_l1)
        return LinearRegressionModel(coefficients=np.asarray(w),
                                     intercept=float(b))

    def fit_fold_grid_arrays(self, X, y, masks, grid, mesh=None):
        """All (fold, grid point) candidates in one batched XLA program
        (optionally mesh-sharded); same core as fit_arrays."""
        from ..parallel.cv import fit_linear_fold_grid
        ga = _grid_to_reg_alpha(self, grid)
        params = fit_linear_fold_grid(
            "squared", X, y, masks, ga, mesh=mesh,
            fit_intercept=self.fit_intercept,
            standardize=self.standardization, max_iter=self.max_iter)
        d = X.shape[1]
        return [[LinearRegressionModel(p[:d], float(p[d])) for p in row]
                for row in params]

    def eval_fold_grid_arrays(self, X, y, masks, grid, X_val, y_val,
                              spec, mesh=None, cand_idx=None):
        """Device-resident search (see LogisticRegression); predicted
        values feed the regression metric kernel."""
        if spec[0] != "regression":
            raise NotImplementedError(
                "linear-regression device eval needs a regression metric")
        from ..parallel.cv import eval_linear_fold_grid
        ga = _grid_to_reg_alpha(self, subset_grid(grid, cand_idx))
        return eval_linear_fold_grid(
            "squared", X, y, masks, ga, X_val, y_val, spec, mesh=mesh,
            fit_intercept=self.fit_intercept,
            standardize=self.standardization, max_iter=self.max_iter)


class LinearRegressionModel(RegressionModel):
    def __init__(self, coefficients, intercept: float = 0.0,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.coefficients = np.asarray(coefficients, dtype=np.float64)
        self.intercept = float(intercept)

    def predict_values(self, X: np.ndarray) -> np.ndarray:
        return X @ self.coefficients + self.intercept

    def raw_arrays(self, X):
        return X @ jnp.asarray(self.coefficients, X.dtype) + self.intercept


# ---------------------------------------------------------------------------
# linear SVC
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("fit_intercept", "standardize",
                                             "max_iter"))
def _fit_linear_svc(X, y, reg, *, fit_intercept: bool, standardize: bool,
                    max_iter: int):
    return linear_svc_core(
        X, y, jnp.ones(X.shape[0], X.dtype), reg, 0.0,
        fit_intercept=fit_intercept, standardize=standardize,
        max_iter=max_iter)


class LinearSVC(Predictor):
    """Linear support-vector classifier (reference OpLinearSVC.scala)."""

    def __init__(self, reg_param: float = 0.0, max_iter: int = 100,
                 tol: float = 1e-6, fit_intercept: bool = True,
                 standardization: bool = True, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.reg_param = reg_param
        self.max_iter = max_iter
        self.tol = tol
        self.fit_intercept = fit_intercept
        self.standardization = standardization

    def fit_arrays(self, X: np.ndarray, y: np.ndarray) -> "LinearSVCModel":
        w, b = _fit_linear_svc(
            jnp.asarray(X), jnp.asarray(y), self.reg_param,
            fit_intercept=self.fit_intercept,
            standardize=self.standardization, max_iter=self.max_iter)
        return LinearSVCModel(coefficients=np.asarray(w), intercept=float(b))

    def fit_fold_grid_arrays(self, X, y, masks, grid, mesh=None):
        """All (fold, grid point) candidates in one batched XLA program
        (optionally mesh-sharded); same core as fit_arrays."""
        from ..parallel.cv import fit_linear_fold_grid
        ga = _grid_to_reg_alpha(self, grid, allowed=("reg_param",))
        params = fit_linear_fold_grid(
            "svc", X, y, masks, ga, mesh=mesh,
            fit_intercept=self.fit_intercept,
            standardize=self.standardization, max_iter=self.max_iter)
        d = X.shape[1]
        return [[LinearSVCModel(p[:d], float(p[d])) for p in row]
                for row in params]

    def eval_fold_grid_arrays(self, X, y, masks, grid, X_val, y_val,
                              spec, mesh=None, cand_idx=None):
        """Device-resident search (see LogisticRegression); SVC margins
        rank identically to the host raw-prediction score."""
        if spec[0] != "binary":
            raise NotImplementedError("SVC device eval is binary-only")
        from ..parallel.cv import eval_linear_fold_grid
        ga = _grid_to_reg_alpha(self, subset_grid(grid, cand_idx),
                                allowed=("reg_param",))
        return eval_linear_fold_grid(
            "svc", X, y, masks, ga, X_val, y_val, spec, mesh=mesh,
            fit_intercept=self.fit_intercept,
            standardize=self.standardization, max_iter=self.max_iter)


class LinearSVCModel(ClassifierModel):
    """SVC model: rawPrediction only, no probability (as in MLlib)."""

    def __init__(self, coefficients, intercept: float = 0.0,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.coefficients = np.asarray(coefficients, dtype=np.float64)
        self.intercept = float(intercept)

    def predict_raw(self, X: np.ndarray) -> np.ndarray:
        m = X @ self.coefficients + self.intercept
        return np.stack([-m, m], axis=1)

    def raw_arrays(self, X):
        m = X @ jnp.asarray(self.coefficients, X.dtype) + self.intercept
        return jnp.stack([-m, m], axis=1)

    def prediction_from_raw(self, raw: np.ndarray) -> PredictionColumn:
        raw = np.asarray(raw, dtype=np.float64)
        pred = (raw[:, 1] > 0).astype(np.float64)
        return PredictionColumn.from_arrays(pred, raw_prediction=raw)

    def predict_arrays(self, X: np.ndarray) -> PredictionColumn:
        return self.prediction_from_raw(self.predict_raw(X))
