"""Linear model family: logistic regression, linear regression, linear SVC.

TPU-native replacements for the reference's Spark MLlib wrappers:
- OpLogisticRegression  (core/.../classification/OpLogisticRegression.scala:45)
- OpLinearRegression    (core/.../regression/OpLinearRegression.scala)
- OpLinearSVC           (core/.../classification/OpLinearSVC.scala)

Semantics follow MLlib where it matters for metric parity:
- optional internal standardization of features (penalty applied in the
  standardized space, coefficients mapped back),
- elastic-net penalty  regParam * (a*L1 + (1-a)/2 * L2),
- binary problems use binomial logistic loss, multiclass uses multinomial
  softmax (MLlib family="auto").

The optimizer is optax L-BFGS (or FISTA when L1 > 0) fully inside XLA —
see models/solvers.py. Fitting is a single jitted program per (shape)
so a hyperparameter grid can ``vmap`` over (reg_param, elastic_net).
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..features.columns import PredictionColumn
from .base import ClassifierModel, Predictor, RegressionModel
from .solvers import design_lipschitz, fista_minimize, lbfgs_minimize

__all__ = ["LogisticRegression", "LogisticRegressionModel",
           "LinearRegression", "LinearRegressionModel",
           "LinearSVC", "LinearSVCModel"]


# ---------------------------------------------------------------------------
# shared standardization helpers
# ---------------------------------------------------------------------------

def _standardize(X: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    mu = jnp.mean(X, axis=0)
    sigma = jnp.std(X, axis=0)
    safe = jnp.where(sigma > 0, sigma, 1.0)
    return (X - mu) / safe, mu, safe


def _unstandardize_coefs(w: jnp.ndarray, b: jnp.ndarray, mu: jnp.ndarray,
                         sigma: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Map coefficients fitted on standardized X back to the original
    feature space: w/sigma, b - (w/sigma).mu  (works for (d,) and (k,d))."""
    w_orig = w / sigma
    b_orig = b - w_orig @ mu if w.ndim == 1 else b - w_orig @ mu
    return w_orig, b_orig


# ---------------------------------------------------------------------------
# logistic regression
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("fit_intercept", "standardize",
                                             "max_iter", "use_l1"))
def _fit_binary_logistic(X, y, reg, alpha, *, fit_intercept: bool,
                         standardize: bool, max_iter: int, use_l1: bool):
    n, d = X.shape
    if standardize:
        Xs, mu, sigma = _standardize(X)
    else:
        Xs, mu, sigma = X, jnp.zeros(d, X.dtype), jnp.ones(d, X.dtype)
    s = 2.0 * y - 1.0  # {0,1} -> {-1,+1}
    l2 = reg * (1.0 - alpha)
    l1 = reg * alpha

    def smooth(params):
        w, b = params[:d], params[d]
        m = Xs @ w + (b if fit_intercept else 0.0)
        return (jnp.mean(jnp.logaddexp(0.0, -s * m))
                + 0.5 * l2 * jnp.sum(w * w))

    w0 = jnp.zeros(d + 1, Xs.dtype)
    if use_l1:
        mask = jnp.concatenate([jnp.ones(d, Xs.dtype),
                                jnp.zeros(1, Xs.dtype)])
        lip = design_lipschitz(Xs, l2, curvature_bound=0.25) + 0.25
        params = fista_minimize(smooth, l1, w0, lip, max_iter=max_iter * 5,
                                l1_mask=mask)
    else:
        params = lbfgs_minimize(smooth, w0, max_iter=max_iter)
    w, b = params[:d], jnp.where(fit_intercept, params[d], 0.0)
    return _unstandardize_coefs(w, b, mu, sigma)


@functools.partial(jax.jit, static_argnames=("fit_intercept", "standardize",
                                             "max_iter", "use_l1", "k"))
def _fit_multinomial_logistic(X, y, reg, alpha, *, k: int,
                              fit_intercept: bool, standardize: bool,
                              max_iter: int, use_l1: bool):
    n, d = X.shape
    if standardize:
        Xs, mu, sigma = _standardize(X)
    else:
        Xs, mu, sigma = X, jnp.zeros(d, X.dtype), jnp.ones(d, X.dtype)
    onehot = jax.nn.one_hot(y.astype(jnp.int32), k, dtype=Xs.dtype)
    l2 = reg * (1.0 - alpha)
    l1 = reg * alpha

    def smooth(params):
        W = params[:, :d]
        b = params[:, d] if fit_intercept else 0.0
        logits = Xs @ W.T + b
        ll = jnp.mean(jnp.sum(onehot * jax.nn.log_softmax(logits), axis=1))
        return -ll + 0.5 * l2 * jnp.sum(W * W)

    W0 = jnp.zeros((k, d + 1), Xs.dtype)
    if use_l1:
        mask = jnp.concatenate(
            [jnp.ones((k, d), Xs.dtype), jnp.zeros((k, 1), Xs.dtype)], axis=1)
        lip = design_lipschitz(Xs, l2, curvature_bound=0.5) + 0.5
        params = fista_minimize(smooth, l1, W0, lip, max_iter=max_iter * 5,
                                l1_mask=mask)
    else:
        params = lbfgs_minimize(smooth, W0, max_iter=max_iter)
    W = params[:, :d]
    b = params[:, d] if fit_intercept else jnp.zeros(k, Xs.dtype)
    return _unstandardize_coefs(W, b, mu, sigma)


class LogisticRegression(Predictor):
    """Binomial/multinomial logistic regression
    (reference OpLogisticRegression.scala:45)."""

    def __init__(self, reg_param: float = 0.0, elastic_net_param: float = 0.0,
                 max_iter: int = 100, tol: float = 1e-6,
                 fit_intercept: bool = True, standardization: bool = True,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.reg_param = reg_param
        self.elastic_net_param = elastic_net_param
        self.max_iter = max_iter
        self.tol = tol
        self.fit_intercept = fit_intercept
        self.standardization = standardization

    def fit_arrays(self, X: np.ndarray, y: np.ndarray
                   ) -> "LogisticRegressionModel":
        Xj = jnp.asarray(X)
        yj = jnp.asarray(y)
        k = int(np.max(y)) + 1 if len(y) else 2
        use_l1 = self.reg_param * self.elastic_net_param > 0
        if k <= 2:
            w, b = _fit_binary_logistic(
                Xj, yj, self.reg_param, self.elastic_net_param,
                fit_intercept=self.fit_intercept,
                standardize=self.standardization,
                max_iter=self.max_iter, use_l1=use_l1)
        else:
            w, b = _fit_multinomial_logistic(
                Xj, yj, self.reg_param, self.elastic_net_param, k=k,
                fit_intercept=self.fit_intercept,
                standardize=self.standardization,
                max_iter=self.max_iter, use_l1=use_l1)
        return LogisticRegressionModel(coefficients=np.asarray(w),
                                       intercept=np.asarray(b))


class LogisticRegressionModel(ClassifierModel):
    def __init__(self, coefficients, intercept, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.coefficients = np.asarray(coefficients, dtype=np.float64)
        self.intercept = np.asarray(intercept, dtype=np.float64)

    def predict_raw(self, X: np.ndarray) -> np.ndarray:
        if self.coefficients.ndim == 1:
            m = X @ self.coefficients + float(self.intercept)
            return np.stack([-m, m], axis=1)
        return X @ self.coefficients.T + self.intercept


# ---------------------------------------------------------------------------
# linear regression
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("fit_intercept", "standardize",
                                             "max_iter", "use_l1"))
def _fit_linear_regression(X, y, reg, alpha, *, fit_intercept: bool,
                           standardize: bool, max_iter: int, use_l1: bool):
    n, d = X.shape
    if standardize:
        Xs, mu, sigma = _standardize(X)
    else:
        Xs, mu, sigma = X, jnp.zeros(d, X.dtype), jnp.ones(d, X.dtype)
    ybar = jnp.mean(y) if fit_intercept else 0.0
    yc = y - ybar
    l2 = reg * (1.0 - alpha)
    l1 = reg * alpha

    if not use_l1:
        # ridge normal equations on the MXU (reference: MLlib "normal"
        # solver / breeze L-BFGS; one (d,d) solve here)
        A = Xs.T @ Xs / n + l2 * jnp.eye(d, dtype=Xs.dtype)
        w = jnp.linalg.solve(A, Xs.T @ yc / n)
    else:
        def smooth(w):
            r = Xs @ w - yc
            return 0.5 * jnp.mean(r * r) + 0.5 * l2 * jnp.sum(w * w)
        lip = design_lipschitz(Xs, l2, curvature_bound=1.0) + 1e-3
        w = fista_minimize(smooth, l1, jnp.zeros(d, Xs.dtype), lip,
                           max_iter=max_iter * 5)
    w_orig = w / sigma
    b = ybar - w_orig @ mu if fit_intercept else jnp.asarray(0.0, Xs.dtype)
    return w_orig, b


class LinearRegression(Predictor):
    """OLS / ridge / elastic-net linear regression
    (reference OpLinearRegression.scala)."""

    def __init__(self, reg_param: float = 0.0, elastic_net_param: float = 0.0,
                 max_iter: int = 100, tol: float = 1e-6,
                 fit_intercept: bool = True, standardization: bool = True,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.reg_param = reg_param
        self.elastic_net_param = elastic_net_param
        self.max_iter = max_iter
        self.tol = tol
        self.fit_intercept = fit_intercept
        self.standardization = standardization

    def fit_arrays(self, X: np.ndarray, y: np.ndarray
                   ) -> "LinearRegressionModel":
        use_l1 = self.reg_param * self.elastic_net_param > 0
        w, b = _fit_linear_regression(
            jnp.asarray(X), jnp.asarray(y), self.reg_param,
            self.elastic_net_param, fit_intercept=self.fit_intercept,
            standardize=self.standardization, max_iter=self.max_iter,
            use_l1=use_l1)
        return LinearRegressionModel(coefficients=np.asarray(w),
                                     intercept=float(b))


class LinearRegressionModel(RegressionModel):
    def __init__(self, coefficients, intercept: float = 0.0,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.coefficients = np.asarray(coefficients, dtype=np.float64)
        self.intercept = float(intercept)

    def predict_values(self, X: np.ndarray) -> np.ndarray:
        return X @ self.coefficients + self.intercept


# ---------------------------------------------------------------------------
# linear SVC
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("fit_intercept", "standardize",
                                             "max_iter"))
def _fit_linear_svc(X, y, reg, *, fit_intercept: bool, standardize: bool,
                    max_iter: int):
    """L2-regularized squared-hinge SVM. The reference's LinearSVC uses
    hinge + OWL-QN; squared hinge is the smooth TPU-friendly variant with
    near-identical decision boundaries (documented deviation)."""
    n, d = X.shape
    if standardize:
        Xs, mu, sigma = _standardize(X)
    else:
        Xs, mu, sigma = X, jnp.zeros(d, X.dtype), jnp.ones(d, X.dtype)
    s = 2.0 * y - 1.0

    def loss(params):
        w, b = params[:d], params[d]
        m = Xs @ w + (b if fit_intercept else 0.0)
        viol = jnp.maximum(0.0, 1.0 - s * m)
        return jnp.mean(viol * viol) + 0.5 * reg * jnp.sum(w * w)

    params = lbfgs_minimize(loss, jnp.zeros(d + 1, Xs.dtype),
                            max_iter=max_iter)
    w, b = params[:d], jnp.where(fit_intercept, params[d], 0.0)
    return _unstandardize_coefs(w, b, mu, sigma)


class LinearSVC(Predictor):
    """Linear support-vector classifier (reference OpLinearSVC.scala)."""

    def __init__(self, reg_param: float = 0.0, max_iter: int = 100,
                 tol: float = 1e-6, fit_intercept: bool = True,
                 standardization: bool = True, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.reg_param = reg_param
        self.max_iter = max_iter
        self.tol = tol
        self.fit_intercept = fit_intercept
        self.standardization = standardization

    def fit_arrays(self, X: np.ndarray, y: np.ndarray) -> "LinearSVCModel":
        w, b = _fit_linear_svc(
            jnp.asarray(X), jnp.asarray(y), self.reg_param,
            fit_intercept=self.fit_intercept,
            standardize=self.standardization, max_iter=self.max_iter)
        return LinearSVCModel(coefficients=np.asarray(w), intercept=float(b))


class LinearSVCModel(ClassifierModel):
    """SVC model: rawPrediction only, no probability (as in MLlib)."""

    def __init__(self, coefficients, intercept: float = 0.0,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.coefficients = np.asarray(coefficients, dtype=np.float64)
        self.intercept = float(intercept)

    def predict_raw(self, X: np.ndarray) -> np.ndarray:
        m = X @ self.coefficients + self.intercept
        return np.stack([-m, m], axis=1)

    def predict_arrays(self, X: np.ndarray) -> PredictionColumn:
        raw = self.predict_raw(X)
        pred = (raw[:, 1] > 0).astype(np.float64)
        return PredictionColumn.from_arrays(pred, raw_prediction=raw)
