"""Multilayer perceptron classifier.

TPU-native replacement for the reference's
OpMultilayerPerceptronClassifier (core/.../classification/
OpMultilayerPerceptronClassifier.scala:48), which wraps MLlib's
feed-forward network (sigmoid hidden layers, softmax output, L-BFGS
solver on the stacked-weights vector). Here the network is a direct JAX
pytree of per-layer (W, b), the loss is cross-entropy, and the solver is
the shared optax L-BFGS program (models/solvers.py) — the whole fit is
one XLA program, all matmuls on the MXU.
"""
from __future__ import annotations

import functools
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .base import (ClassifierModel, Predictor,
                   check_fold_classes, num_classes)
from .solvers import lbfgs_minimize

__all__ = ["MultilayerPerceptronClassifier",
           "MultilayerPerceptronClassifierModel"]


def _init_params(key, sizes: Tuple[int, ...], dtype):
    """MLlib-style scaled uniform init per layer."""
    params = []
    for i, (fan_in, fan_out) in enumerate(zip(sizes[:-1], sizes[1:])):
        key, sub = jax.random.split(key)
        scale = jnp.sqrt(6.0 / (fan_in + fan_out)).astype(dtype)
        W = jax.random.uniform(sub, (fan_in, fan_out), dtype,
                               minval=-scale, maxval=scale)
        params.append((W, jnp.zeros((fan_out,), dtype)))
    return params


def _forward(params, X):
    """Sigmoid hidden layers, raw logits at the top (MLlib topology)."""
    h = X
    for W, b in params[:-1]:
        h = jax.nn.sigmoid(h @ W + b)
    W, b = params[-1]
    return h @ W + b


@functools.partial(jax.jit, static_argnames=("sizes", "max_iter", "tol"))
def _fit_mlp(X, y, key, *, sizes: Tuple[int, ...], max_iter: int,
             tol: float):
    onehot = jax.nn.one_hot(y.astype(jnp.int32), sizes[-1], dtype=X.dtype)

    def loss(params):
        logits = _forward(params, X)
        return -jnp.mean(jnp.sum(onehot * jax.nn.log_softmax(logits), axis=1))

    params0 = _init_params(key, sizes, X.dtype)
    return lbfgs_minimize(loss, params0, max_iter=max_iter, tol=tol)


def _mlp_fold_body(X, y, masks, key, *, sizes: Tuple[int, ...],
                   max_iter: int, tol: float):
    """All folds of one MLP config as ONE vmapped L-BFGS program: the
    mask-weighted mean cross-entropy over the full matrix equals the
    plain mean over that fold's train rows, so each vmap lane IS the
    per-fold sequential fit (same init — the sequential path seeds every
    fold identically too) up to summation order."""
    onehot = jax.nn.one_hot(y.astype(jnp.int32), sizes[-1], dtype=X.dtype)

    def one_fold(mask):
        wsum = jnp.maximum(jnp.sum(mask), 1.0)

        def loss(params):
            logits = _forward(params, X)
            ll = jnp.sum(onehot * jax.nn.log_softmax(logits), axis=1)
            return -jnp.sum(mask * ll) / wsum

        params0 = _init_params(key, sizes, X.dtype)
        return lbfgs_minimize(loss, params0, max_iter=max_iter, tol=tol)

    return jax.vmap(one_fold)(masks)


@functools.partial(jax.jit, static_argnames=("sizes", "max_iter", "tol"))
def _fit_mlp_folds(X, y, masks, key, *, sizes: Tuple[int, ...],
                   max_iter: int, tol: float):
    return _mlp_fold_body(X, y, masks, key, sizes=sizes,
                          max_iter=max_iter, tol=tol)


@functools.lru_cache(maxsize=None)
def _mlp_mesh_kernel(sizes: Tuple[int, ...], max_iter: int, tol: float,
                     mesh):
    """Fold kernel sharded over the mesh ``models`` axis (same mapping
    as the tree/linear fold x grid kernels): each shard trains its
    slice of fold candidates; X/y/key replicate."""
    from jax.sharding import PartitionSpec as P
    n_layers = len(sizes) - 1
    out_specs = [(P("models", None, None), P("models", None))
                 for _ in range(n_layers)]

    def batched(masks, X, y, key):
        return _mlp_fold_body(X, y, masks, key, sizes=sizes,
                              max_iter=max_iter, tol=tol)

    return jax.jit(jax.shard_map(
        batched, mesh=mesh,
        in_specs=(P("models", None), P(), P(), P()),
        out_specs=out_specs, check_vma=False))


class MultilayerPerceptronClassifier(Predictor):
    """Feed-forward classifier (reference
    OpMultilayerPerceptronClassifier.scala:48). ``hidden_layers`` are the
    intermediate layer widths; input/output widths come from the data."""

    #: the fold-batched kernel vmaps L-BFGS, forcing every fold into
    #: lockstep line searches — a measured ~4x single-device slowdown
    #: (BASELINE config 5). It pays off only when a mesh actually
    #: spreads the candidates, so the validator uses it mesh-only.
    fold_grid_needs_mesh = True

    def __init__(self, hidden_layers: Sequence[int] = (10,),
                 max_iter: int = 100, tol: float = 1e-6, seed: int = 42,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.hidden_layers = tuple(int(h) for h in hidden_layers)
        self.max_iter = max_iter
        self.tol = tol
        self.seed = seed

    def fit_fold_grid_arrays(self, X, y, masks, grid, mesh=None):
        """Validator fast path (see _ValidatorBase.validate): grid
        points group by their (all static) params, and each group's
        folds train as one vmapped program — sharded over the mesh
        ``models`` axis when a ("models", ...) mesh is supplied (fold
        candidates padded to the shard count with all-ones masks)."""
        grid = [dict(p) for p in (list(grid) or [{}])]
        allowed = {"hidden_layers", "max_iter", "tol", "seed"}
        for p in grid:
            extra = set(p) - allowed
            if extra:
                raise NotImplementedError(
                    f"batched MLP kernel cannot vary {sorted(extra)}")
        k = num_classes(y)
        masks = np.asarray(masks, dtype=np.float64)
        check_fold_classes(y, masks)
        F = masks.shape[0]
        models = [[None] * len(grid) for _ in range(F)]
        groups = {}
        for gi, p in enumerate(grid):
            cand = self.with_params(**p)
            key = (cand.hidden_layers, cand.max_iter, cand.tol, cand.seed)
            groups.setdefault(key, []).append(gi)
        X_j = jnp.asarray(X)
        y_j = jnp.asarray(y)
        from ..parallel.mesh import to_host
        from .trees import _pad_candidates
        (masks_p,), _ = _pad_candidates(mesh, [masks], masks.shape[1])
        m_j = jnp.asarray(masks_p).astype(X_j.dtype)
        for (hidden, mi, tol, seed), gis in groups.items():
            sizes = (X.shape[1],) + tuple(hidden) + (k,)
            if mesh is not None:
                fn = _mlp_mesh_kernel(sizes, mi, tol, mesh)
                params = fn(m_j, X_j, y_j, jax.random.PRNGKey(seed))
            else:
                params = _fit_mlp_folds(X_j, y_j, m_j,
                                        jax.random.PRNGKey(seed),
                                        sizes=sizes, max_iter=mi, tol=tol)
            params_h = [(to_host(W), to_host(b)) for W, b in params]
            for f in range(F):
                ws = [W[f] for W, _ in params_h]
                bs = [b[f] for _, b in params_h]
                mdl = MultilayerPerceptronClassifierModel(weights=ws,
                                                          biases=bs)
                for gi in gis:      # identical configs share the fit
                    models[f][gi] = mdl
        return models

    def fit_arrays(self, X: np.ndarray, y: np.ndarray
                   ) -> "MultilayerPerceptronClassifierModel":
        k = num_classes(y)
        sizes = (X.shape[1],) + self.hidden_layers + (k,)
        params = _fit_mlp(jnp.asarray(X), jnp.asarray(y),
                          jax.random.PRNGKey(self.seed), sizes=sizes,
                          max_iter=self.max_iter, tol=self.tol)
        weights = [np.asarray(W) for W, _ in params]
        biases = [np.asarray(b) for _, b in params]
        return MultilayerPerceptronClassifierModel(weights=weights,
                                                   biases=biases)


class MultilayerPerceptronClassifierModel(ClassifierModel):
    def __init__(self, weights: List[np.ndarray], biases: List[np.ndarray],
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.weights = [np.asarray(W, dtype=np.float64) for W in weights]
        self.biases = [np.asarray(b, dtype=np.float64) for b in biases]

    def predict_raw(self, X: np.ndarray) -> np.ndarray:
        h = X
        for W, b in zip(self.weights[:-1], self.biases[:-1]):
            h = 1.0 / (1.0 + np.exp(-(h @ W + b)))
        return h @ self.weights[-1] + self.biases[-1]
