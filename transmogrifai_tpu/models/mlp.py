"""Multilayer perceptron classifier.

TPU-native replacement for the reference's
OpMultilayerPerceptronClassifier (core/.../classification/
OpMultilayerPerceptronClassifier.scala:48), which wraps MLlib's
feed-forward network (sigmoid hidden layers, softmax output, L-BFGS
solver on the stacked-weights vector). Here the network is a direct JAX
pytree of per-layer (W, b), the loss is cross-entropy, and the solver is
the shared optax L-BFGS program (models/solvers.py) — the whole fit is
one XLA program, all matmuls on the MXU.
"""
from __future__ import annotations

import functools
import logging
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .base import (ClassifierModel, Predictor,
                   check_fold_classes, num_classes, subset_grid)
from .solvers import lbfgs_minimize
from ..utils.jax_setup import shard_map

__all__ = ["MultilayerPerceptronClassifier",
           "MultilayerPerceptronClassifierModel"]

_log = logging.getLogger(__name__)


def _group_mlp_grid(grid, with_params):
    """Group grid points whose batched-solver-relevant params coincide.
    ``tol`` is inert for the fixed-trip batched solver (a documented
    deviation from the sequential L-BFGS path — see
    docs/MIGRATION.md); points differing only in tol share one fit,
    and the collapse is logged so it never happens silently."""
    groups = {}
    for gi, p in enumerate(grid):
        cand = with_params(**p)
        key = (cand.hidden_layers, cand.max_iter, cand.seed)
        groups.setdefault(key, []).append(gi)
    for key, gis in groups.items():
        if len(gis) > 1:
            _log.info(
                "MLP batched CV: grid points %s differ only in tol and "
                "share one fixed-trip fit (hidden=%s, max_iter=%s)",
                gis, key[0], key[1])
    return groups


def _init_params(key, sizes: Tuple[int, ...], dtype):
    """MLlib-style scaled uniform init per layer."""
    params = []
    for i, (fan_in, fan_out) in enumerate(zip(sizes[:-1], sizes[1:])):
        key, sub = jax.random.split(key)
        scale = jnp.sqrt(6.0 / (fan_in + fan_out)).astype(dtype)
        W = jax.random.uniform(sub, (fan_in, fan_out), dtype,
                               minval=-scale, maxval=scale)
        params.append((W, jnp.zeros((fan_out,), dtype)))
    return params


def _forward(params, X):
    """Sigmoid hidden layers, raw logits at the top (MLlib topology)."""
    h = X
    for W, b in params[:-1]:
        h = jax.nn.sigmoid(h @ W + b)
    W, b = params[-1]
    return h @ W + b


@functools.partial(jax.jit, static_argnames=("sizes", "max_iter", "tol"))
def _fit_mlp(X, y, key, *, sizes: Tuple[int, ...], max_iter: int,
             tol: float):
    onehot = jax.nn.one_hot(y.astype(jnp.int32), sizes[-1], dtype=X.dtype)

    def loss(params):
        logits = _forward(params, X)
        return -jnp.mean(jnp.sum(onehot * jax.nn.log_softmax(logits), axis=1))

    params0 = _init_params(key, sizes, X.dtype)
    return lbfgs_minimize(loss, params0, max_iter=max_iter, tol=tol)


#: mini-batch size / steps-per-max_iter for the batched fold solver
_MB_BATCH = 512
_MB_STEPS_PER_ITER = 6


def _mlp_batched_fit(X, onehot, mask, key, sizes: Tuple[int, ...],
                     max_iter: int):
    """One fold's fit for the BATCHED kernels: fixed-trip MINI-BATCH
    Adam (cosine decay) instead of the sequential path's L-BFGS.

    Deviation, on purpose: vmapped L-BFGS runs every lane through the
    worst lane's zoom-linesearch iterations (a measured ~4x single-
    device regression, r3), and full-batch fixed-trip solvers do
    O(steps x rows) work where L-BFGS stops early. Mini-batching bounds
    the work to O(steps x batch) row-visits REGARDLESS of n — measured
    comparable validation error to per-fold L-BFGS at a fraction of the
    wall-clock for wide/tall designs (BASELINE.md config 5). The
    sequential fit_arrays keeps MLlib-parity L-BFGS; the CV search only
    uses these fits to RANK hyperparameters."""
    n = X.shape[0]
    batch = min(_MB_BATCH, n)
    steps = _MB_STEPS_PER_ITER * max_iter
    span = max(n - batch + 1, 1)
    pkey, ikey = jax.random.split(key)
    perm = jax.random.permutation(pkey, n)
    Xp, ohp, mp = X[perm], onehot[perm], mask[perm]
    import optax
    opt = optax.adam(optax.cosine_decay_schedule(0.03, steps))
    params0 = _init_params(ikey, sizes, X.dtype)

    def loss_b(params, xb, ob, mb):
        logits = _forward(params, xb)
        ll = jnp.sum(ob * jax.nn.log_softmax(logits), axis=1)
        return -jnp.sum(mb * ll) / jnp.maximum(jnp.sum(mb), 1.0)

    def step(carry, i):
        params, state = carry
        start = (i * batch) % span
        xb = jax.lax.dynamic_slice_in_dim(Xp, start, batch)
        ob = jax.lax.dynamic_slice_in_dim(ohp, start, batch)
        mb = jax.lax.dynamic_slice_in_dim(mp, start, batch)
        g = jax.grad(loss_b)(params, xb, ob, mb)
        updates, state = opt.update(g, state, params)
        return (optax.apply_updates(params, updates), state), None

    (params, _), _ = jax.lax.scan(step, (params0, opt.init(params0)),
                                  jnp.arange(steps))
    return params


def _mlp_fold_body(X, y, masks, key, *, sizes: Tuple[int, ...],
                   max_iter: int):
    """All folds of one MLP config as ONE vmapped program (fixed-trip
    mini-batch Adam — see _mlp_batched_fit for why not L-BFGS; ``tol``
    does not apply to the fixed-trip solver and is only honored by the
    sequential L-BFGS path); the mask weights make each lane fit
    exactly its fold's train rows."""
    onehot = jax.nn.one_hot(y.astype(jnp.int32), sizes[-1], dtype=X.dtype)
    return jax.vmap(
        lambda mask: _mlp_batched_fit(X, onehot, mask, key, sizes,
                                      max_iter))(masks)


@functools.partial(jax.jit, static_argnames=("sizes", "max_iter"))
def _fit_mlp_folds(X, y, masks, key, *, sizes: Tuple[int, ...],
                   max_iter: int):
    return _mlp_fold_body(X, y, masks, key, sizes=sizes,
                          max_iter=max_iter)


def _mlp_eval_body(X, y, masks, key, fidx, Xv, yv, *,
                   sizes: Tuple[int, ...], max_iter: int,
                   spec: tuple):
    """Fused fold fit + validation metric (device-resident search):
    each lane trains its fold and scores its own validation rows;
    binary margins are the logit difference (argmax parity with the
    host softmax probability)."""
    from ..evaluators.device_metrics import (binary_from_raw_pair,
                                             metric_fn,
                                             softmax_probability)
    mfn = metric_fn(*spec)
    onehot = jax.nn.one_hot(y.astype(jnp.int32), sizes[-1], dtype=X.dtype)

    def one_fold(mask, fi):
        params = _mlp_batched_fit(X, onehot, mask, key, sizes, max_iter)
        logits = _forward(params, Xv[fi])
        # host MLP model ranks by the softmax of the logits
        scores = (binary_from_raw_pair(logits) if spec[0] == "binary"
                  else softmax_probability(logits))
        return mfn(yv[fi], scores)

    return jax.vmap(one_fold)(masks, fidx)


@functools.partial(jax.jit, static_argnames=("sizes", "max_iter", "spec"))
def _eval_mlp_folds(X, y, masks, key, fidx, Xv, yv, *,
                    sizes: Tuple[int, ...], max_iter: int,
                    spec: tuple):
    return _mlp_eval_body(X, y, masks, key, fidx, Xv, yv, sizes=sizes,
                          max_iter=max_iter, spec=spec)


@functools.lru_cache(maxsize=32)
def _mlp_eval_mesh_kernel(sizes: Tuple[int, ...], max_iter: int,
                          spec: tuple, mesh):
    from jax.sharding import PartitionSpec as P

    def batched(masks, fidx, X, y, key, Xv, yv):
        return _mlp_eval_body(X, y, masks, key, fidx, Xv, yv,
                              sizes=sizes, max_iter=max_iter,
                              spec=spec)

    return jax.jit(shard_map(
        batched, mesh=mesh,
        in_specs=(P("models", None), P("models"), P(), P(), P(), P(),
                  P()),
        out_specs=P("models"), check_vma=False))


@functools.lru_cache(maxsize=32)
def _mlp_mesh_kernel(sizes: Tuple[int, ...], max_iter: int, mesh):
    """Fold kernel sharded over the mesh ``models`` axis (same mapping
    as the tree/linear fold x grid kernels): each shard trains its
    slice of fold candidates; X/y/key replicate."""
    from jax.sharding import PartitionSpec as P
    n_layers = len(sizes) - 1
    out_specs = [(P("models", None, None), P("models", None))
                 for _ in range(n_layers)]

    def batched(masks, X, y, key):
        return _mlp_fold_body(X, y, masks, key, sizes=sizes,
                              max_iter=max_iter)

    return jax.jit(shard_map(
        batched, mesh=mesh,
        in_specs=(P("models", None), P(), P(), P()),
        out_specs=out_specs, check_vma=False))


class MultilayerPerceptronClassifier(Predictor):
    """Feed-forward classifier (reference
    OpMultilayerPerceptronClassifier.scala:48). ``hidden_layers`` are the
    intermediate layer widths; input/output widths come from the data."""

    def __init__(self, hidden_layers: Sequence[int] = (10,),
                 max_iter: int = 100, tol: float = 1e-6, seed: int = 42,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.hidden_layers = tuple(int(h) for h in hidden_layers)
        self.max_iter = max_iter
        self.tol = tol
        self.seed = seed

    def fit_fold_grid_arrays(self, X, y, masks, grid, mesh=None):
        """Validator fast path (see _ValidatorBase.validate): grid
        points group by their (all static) params, and each group's
        folds train as one vmapped program — sharded over the mesh
        ``models`` axis when a ("models", ...) mesh is supplied (fold
        candidates padded to the shard count with all-ones masks)."""
        grid = [dict(p) for p in (list(grid) or [{}])]
        allowed = {"hidden_layers", "max_iter", "tol", "seed"}
        for p in grid:
            extra = set(p) - allowed
            if extra:
                raise NotImplementedError(
                    f"batched MLP kernel cannot vary {sorted(extra)}")
        k = num_classes(y)
        masks = np.asarray(masks, dtype=np.float64)
        check_fold_classes(y, masks)
        F = masks.shape[0]
        models = [[None] * len(grid) for _ in range(F)]
        groups = _group_mlp_grid(grid, self.with_params)
        X_j = jnp.asarray(X)
        y_j = jnp.asarray(y)
        from ..parallel.mesh import to_host
        from .trees import _pad_candidates
        (masks_p,), _ = _pad_candidates(mesh, [masks], masks.shape[1])
        m_j = jnp.asarray(masks_p).astype(X_j.dtype)
        for (hidden, mi, seed), gis in groups.items():
            sizes = (X.shape[1],) + tuple(hidden) + (k,)
            if mesh is not None:
                fn = _mlp_mesh_kernel(sizes, mi, mesh)
                params = fn(m_j, X_j, y_j, jax.random.PRNGKey(seed))
            else:
                params = _fit_mlp_folds(X_j, y_j, m_j,
                                        jax.random.PRNGKey(seed),
                                        sizes=sizes, max_iter=mi)
            params_h = [(to_host(W), to_host(b)) for W, b in params]
            for f in range(F):
                ws = [W[f] for W, _ in params_h]
                bs = [b[f] for _, b in params_h]
                mdl = MultilayerPerceptronClassifierModel(weights=ws,
                                                          biases=bs)
                for gi in gis:      # identical configs share the fit
                    models[f][gi] = mdl
        return models

    def eval_fold_grid_arrays(self, X, y, masks, grid, X_val, y_val,
                              spec, mesh=None, cand_idx=None):
        """Device-resident search: fused fold fit + validation metric,
        (F, G) matrix out (grouping mirrors fit_fold_grid_arrays)."""
        if spec[0] not in ("binary", "multiclass"):
            raise NotImplementedError(
                "MLP device eval needs a classification metric")
        k = num_classes(y)
        if spec[0] == "binary" and k != 2:
            raise NotImplementedError(
                "binary device eval needs binary labels")
        grid = [dict(p) for p in subset_grid(grid, cand_idx)]
        allowed = {"hidden_layers", "max_iter", "tol", "seed"}
        for p in grid:
            extra = set(p) - allowed
            if extra:
                raise NotImplementedError(
                    f"batched MLP kernel cannot vary {sorted(extra)}")
        masks = np.asarray(masks, dtype=np.float64)
        check_fold_classes(y, masks)
        F = masks.shape[0]
        metric_mat = np.full((F, len(grid)), np.nan)
        groups = _group_mlp_grid(grid, self.with_params)
        X_j, y_j = jnp.asarray(X), jnp.asarray(y)
        Xv_j = jnp.asarray(np.asarray(X_val, dtype=np.float64))
        yv_j = jnp.asarray(np.asarray(y_val, dtype=np.float64))
        from ..parallel.mesh import to_host
        from .trees import _pad_candidates
        fidx0 = np.arange(F, dtype=np.int32)
        (masks_p,), count = _pad_candidates(mesh, [masks], masks.shape[1])
        fidx = np.concatenate(
            [fidx0, np.zeros(len(masks_p) - count, dtype=np.int32)])
        m_j = jnp.asarray(masks_p).astype(X_j.dtype)
        fi_j = jnp.asarray(fidx)
        for (hidden, mi, seed), gis in groups.items():
            sizes = (X.shape[1],) + tuple(hidden) + (k,)
            if mesh is not None:
                fn = _mlp_eval_mesh_kernel(sizes, mi, spec, mesh)
                mm = fn(m_j, fi_j, X_j, y_j, jax.random.PRNGKey(seed),
                        Xv_j, yv_j)
            else:
                mm = _eval_mlp_folds(X_j, y_j, m_j,
                                     jax.random.PRNGKey(seed), fi_j,
                                     Xv_j, yv_j, sizes=sizes,
                                     max_iter=mi, spec=spec)
            mm = to_host(mm)[:count]
            for f in range(F):
                for gi in gis:      # identical configs share the fit
                    metric_mat[f, gi] = mm[f]
        return metric_mat

    def fit_arrays(self, X: np.ndarray, y: np.ndarray
                   ) -> "MultilayerPerceptronClassifierModel":
        k = num_classes(y)
        sizes = (X.shape[1],) + self.hidden_layers + (k,)
        params = _fit_mlp(jnp.asarray(X), jnp.asarray(y),
                          jax.random.PRNGKey(self.seed), sizes=sizes,
                          max_iter=self.max_iter, tol=self.tol)
        weights = [np.asarray(W) for W, _ in params]
        biases = [np.asarray(b) for _, b in params]
        return MultilayerPerceptronClassifierModel(weights=weights,
                                                   biases=biases)


class MultilayerPerceptronClassifierModel(ClassifierModel):
    def __init__(self, weights: List[np.ndarray], biases: List[np.ndarray],
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.weights = [np.asarray(W, dtype=np.float64) for W in weights]
        self.biases = [np.asarray(b, dtype=np.float64) for b in biases]

    def predict_raw(self, X: np.ndarray) -> np.ndarray:
        h = X
        for W, b in zip(self.weights[:-1], self.biases[:-1]):
            h = 1.0 / (1.0 + np.exp(-(h @ W + b)))
        return h @ self.weights[-1] + self.biases[-1]

    def raw_arrays(self, X):
        import jax.numpy as jnp
        h = X
        for W, b in zip(self.weights[:-1], self.biases[:-1]):
            h = 1.0 / (1.0 + jnp.exp(-(h @ jnp.asarray(W, X.dtype)
                                       + jnp.asarray(b, X.dtype))))
        return h @ jnp.asarray(self.weights[-1], X.dtype) \
            + jnp.asarray(self.biases[-1], X.dtype)
