"""Pallas TPU kernel: fused level-histogram accumulation for tree fits.

Computes the per-level split-search tensor

    hist[c, b, s] = sum_r [slot_r == c] * stats[r, s] * bin_oh[r, b]

— the hot op of histogram tree growth (SURVEY.md §2.9: the reference's
XGBoost dependency builds the same (node, bin, stat) tensor with native
C++ scatter-adds inside libxgboost; here it is a TPU kernel instead).

The XLA "matmul" strategy in ``models/trees.py`` expresses this as one
einsum, which materializes the (n, C*S) slot-weighted stats intermediate
in HBM every level and streams the (n, TB) bin indicator past it. This
kernel fuses both contractions into a single pass over row blocks:

  - the (S * C_pad, TB_tile) accumulator lives in VMEM for the whole
    row loop (grid iterates row blocks fastest, so the revisited
    output block never leaves the chip);
  - each step builds the slot one-hot for its row block on the VPU
    (iota compare — no scatter) and issues one MXU contraction per
    statistic: ``(slot_oh * stats[:, s])^T @ bin_oh_block`` into the
    s-th accumulator row block (the S axis is statically unrolled —
    see _hist_kernel for why no (R, C*S) interleaved operand exists);
  - nothing of size O(n * C) ever touches HBM.

Numerics match the einsum: float32 operands, float32 MXU accumulation,
identical row-major summation order per (c, b, s) cell up to XLA's own
dot reassociation (same guarantee the matmul strategy gives).

On non-TPU backends the kernel runs in Pallas interpret mode, so the
strategy stays available (and testable) everywhere.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu is importable without TPU hardware; guard for safety
    from jax.experimental.pallas import tpu as pltpu
    _HAVE_PLTPU = True
except Exception:  # pragma: no cover - exotic builds
    pltpu = None
    _HAVE_PLTPU = False

__all__ = ["pallas_level_hist"]

#: rows per grid step — one (R, TB_tile) indicator block + two
#: (R, C_pad) temporaries (slot one-hot, per-s product) in VMEM per step
_ROW_BLOCK = 512
#: packed-bin tile width (lane-aligned); TB above this adds grid steps
_TB_TILE = 2048
#: VMEM working-set budget (bytes): accumulator + double-buffered input
#: blocks must fit well under the ~16 MB/core VMEM
_VMEM_BUDGET = 8 * 1024 * 1024


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def _plan_tiles(CS_pad: int, S: int, TB: int):
    """(R, TB_tile) such that the VMEM working set
    acc(CS_pad x TB_tile) + 2x double-buffered inputs
    (R x TB_tile indicator, R x CS_pad/S one-hot + per-s product,
    R x (S+1) stats+slot) stays under _VMEM_BUDGET; None if no tiling
    fits (huge C*S — the caller falls back to the XLA einsum, which
    HBM-streams instead). ``CS_pad`` is the accumulator height
    S * C_pad (s-major row blocks, see _hist_kernel)."""
    R, TB_tile = _ROW_BLOCK, min(_round_up(TB, 128), _TB_TILE)
    C_pad = CS_pad // S

    def fits(r, tbt):
        # acc + double-buffered inputs (indicator, stats+slot) + the
        # kernel's two (R, C_pad) temporaries (slot one-hot, per-s
        # product) — the unrolled kernel never materializes (R, CS_pad)
        return 4 * (CS_pad * tbt + 2 * r * (tbt + S + 1)
                    + 2 * r * C_pad) <= _VMEM_BUDGET

    while not fits(R, TB_tile) and TB_tile > 128:
        TB_tile //= 2
    while not fits(R, TB_tile) and R > 128:
        R //= 2
    return (R, TB_tile) if fits(R, TB_tile) else None


def _hist_kernel(slot_ref, stats_ref, binoh_ref, out_ref, *,
                 C_pad: int):
    """One (TB tile, row block) grid step; row blocks iterate fastest so
    ``out_ref`` stays VMEM-resident while a tile accumulates.

    The per-stat contractions are unrolled over the (tiny, static) S
    axis: ``comb_s = slot_oh * stats[:, s]`` then one MXU dot per s
    into the ``[s*C_pad, (s+1)*C_pad)`` row block of the accumulator.
    An earlier draft built one (R, C*S) interleaved operand via a 3D
    broadcast-multiply + reshape; that lowering requires a Mosaic
    relayout compiled through a secondary TPU compile service, which
    the axon tunnel's env-scrubbed helper cannot run (observed HTTP
    500 `tpu_compile_helper` failures on real v5e) — the unrolled form
    compiles inline everywhere and runs the same MXU contractions."""
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    stats = stats_ref[:]                       # (R, S) f32
    R, S = stats.shape
    cls = jax.lax.broadcasted_iota(jnp.int32, (R, C_pad), 1)
    # slots are < C, so the C..C_pad padding columns are zero for free
    slot_oh = (cls == slot_ref[:]).astype(stats.dtype)      # (R, C_pad)
    binoh = binoh_ref[:]
    for s in range(S):                         # static unroll (S <= 4)
        comb = slot_oh * stats[:, s][:, None]               # (R, C_pad)
        out_ref[s * C_pad:(s + 1) * C_pad, :] += jax.lax.dot_general(
            comb, binoh,
            (((0,), (0,)), ((), ())),          # contract over rows
            preferred_element_type=jnp.float32)


@functools.partial(jax.jit, static_argnames=("num_slots", "interpret"))
def pallas_level_hist(bin_oh: jnp.ndarray, slot: jnp.ndarray,
                      stats: jnp.ndarray, num_slots: int,
                      interpret: bool | None = None) -> jnp.ndarray:
    """(num_slots, TB, S) histograms from a (n, TB) 0/1 bin indicator,
    (n,) slot ids and (n, S) per-row statistics.

    Drop-in replacement for the einsum in
    ``models.trees._level_histograms`` (matmul strategy); selected there
    via ``TX_TREE_HIST=pallas``.
    """
    n, TB = bin_oh.shape
    S = stats.shape[1]
    C = int(num_slots)
    if stats.dtype == jnp.float64:
        # the kernel accumulates in f32 (MXU-native); under
        # jax_enable_x64 that would silently downgrade split-search
        # precision vs the scatter/matmul strategies, breaking the
        # "mathematically identical strategies" contract of
        # _level_histograms — stream the f64 case via the XLA einsum
        slot_oh = jax.nn.one_hot(slot, C, dtype=stats.dtype)
        return jnp.einsum("nc,ns,nb->cbs", slot_oh, stats,
                          bin_oh.astype(stats.dtype))
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    C_pad = _round_up(C, 8)
    CS_pad = C_pad * S
    plan = _plan_tiles(CS_pad, S, TB)
    if plan is None:  # pragma: no cover - needs enormous C*S
        # accumulator cannot fit VMEM at any tile size: stream via the
        # mathematically identical XLA einsum instead of failing Mosaic
        slot_oh = jax.nn.one_hot(slot, C, dtype=stats.dtype)
        return jnp.einsum("nc,ns,nb->cbs", slot_oh, stats, bin_oh)
    R, TB_tile = plan
    if n < R:
        R = _round_up(max(n, 8), 8)
    n_pad = _round_up(n, R)
    TB_pad = _round_up(_round_up(TB, 128), TB_tile)

    f32 = jnp.float32
    bin_oh = bin_oh.astype(f32)
    stats = stats.astype(f32)
    if TB_pad != TB:
        bin_oh = jnp.pad(bin_oh, ((0, 0), (0, TB_pad - TB)))
    if n_pad != n:
        # zero stats rows contribute nothing whatever their slot/bin
        bin_oh = jnp.pad(bin_oh, ((0, n_pad - n), (0, 0)))
        stats = jnp.pad(stats, ((0, n_pad - n), (0, 0)))
        slot = jnp.pad(slot, (0, n_pad - n))
    slot2d = slot.astype(jnp.int32)[:, None]               # (n_pad, 1)

    grid = (TB_pad // TB_tile, n_pad // R)
    vmem = (pltpu.VMEM if (_HAVE_PLTPU and not interpret)
            else pl.ANY)
    out = pl.pallas_call(
        functools.partial(_hist_kernel, C_pad=C_pad),
        grid=grid,
        in_specs=[
            pl.BlockSpec((R, 1), lambda i, j: (j, 0), memory_space=vmem),
            pl.BlockSpec((R, S), lambda i, j: (j, 0), memory_space=vmem),
            pl.BlockSpec((R, TB_tile), lambda i, j: (j, i),
                         memory_space=vmem),
        ],
        out_specs=pl.BlockSpec((CS_pad, TB_tile), lambda i, j: (0, i),
                               memory_space=vmem),
        out_shape=jax.ShapeDtypeStruct((CS_pad, TB_pad), f32),
        interpret=interpret,
    )(slot2d, stats, bin_oh)
    # rows are laid out s-major: block s holds slots [0, C_pad), of
    # which the first C are real
    return (out[:, :TB].reshape(S, C_pad, TB)[:, :C, :]
            .transpose(1, 2, 0))
