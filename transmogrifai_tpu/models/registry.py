"""Default model pools for the selector factories.

Tree families (RF/GBT) join these pools as they land in the zoo —
centralizing here keeps selector/factories.py free of conditional
imports (reference: the modelsAndParameters defaults in
BinaryClassificationModelSelector.scala:68-128).
"""
from __future__ import annotations

from typing import Dict, List, Tuple

from .base import Predictor

__all__ = ["default_binary_tree_models", "default_multiclass_models",
           "default_regression_tree_models"]


def default_binary_tree_models() -> List[Tuple[Predictor, List[Dict]]]:
    try:
        from .trees import GBTClassifier, RandomForestClassifier
    except ImportError:
        return []
    return [
        (RandomForestClassifier(),
         [{"max_depth": d, "num_trees": t, "min_instances_per_node": m}
          for d in (3, 6, 12) for t in (10, 50) for m in (10, 100)]),
        (GBTClassifier(),
         [{"max_depth": d, "num_rounds": r}
          for d in (3, 6) for r in (50, 100)]),
    ]


def default_multiclass_models() -> List[Tuple[Predictor, List[Dict]]]:
    try:
        from .trees import RandomForestClassifier
    except ImportError:
        return []
    return [
        (RandomForestClassifier(),
         [{"max_depth": d, "num_trees": t}
          for d in (3, 6, 12) for t in (10, 50)]),
    ]


def default_regression_tree_models() -> List[Tuple[Predictor, List[Dict]]]:
    try:
        from .trees import GBTRegressor, RandomForestRegressor
    except ImportError:
        return []
    return [
        (RandomForestRegressor(),
         [{"max_depth": d, "num_trees": t}
          for d in (3, 6, 12) for t in (10, 50)]),
        (GBTRegressor(),
         [{"max_depth": d, "num_rounds": r}
          for d in (3, 6) for r in (50, 100)]),
    ]
