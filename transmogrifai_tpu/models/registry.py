"""Default model pools for the selector factories.

Centralizes the per-problem-type candidate pools + hyperparameter grids
(reference: the modelsAndParameters defaults in
BinaryClassificationModelSelector.scala:68-128,
MultiClassificationModelSelector.scala:138-183,
RegressionModelSelector.scala:150-193, grid values from
DefaultSelectorParams.scala:38-60).
"""
from __future__ import annotations

from typing import Dict, List, Tuple

from .base import Predictor

__all__ = ["default_binary_extra_models", "default_multiclass_extra_models",
           "default_regression_extra_models"]


def default_binary_extra_models() -> List[Tuple[Predictor, List[Dict]]]:
    from .bayes import NaiveBayes
    from .trees import (DecisionTreeClassifier, GBTClassifier,
                        RandomForestClassifier)
    return [
        (RandomForestClassifier(),
         [{"max_depth": d, "num_trees": t, "min_instances_per_node": m}
          for d in (3, 6, 12) for t in (10, 50) for m in (10, 100)]),
        (GBTClassifier(),
         [{"max_depth": d, "num_rounds": r}
          for d in (3, 6) for r in (50, 100)]),
        (DecisionTreeClassifier(),
         [{"max_depth": d, "min_instances_per_node": m}
          for d in (3, 6, 12) for m in (10, 100)]),
        (NaiveBayes(), [{"smoothing": 1.0}]),
    ]


def default_multiclass_extra_models() -> List[Tuple[Predictor, List[Dict]]]:
    from .bayes import NaiveBayes
    from .trees import DecisionTreeClassifier, RandomForestClassifier
    return [
        (RandomForestClassifier(),
         [{"max_depth": d, "num_trees": t}
          for d in (3, 6, 12) for t in (10, 50)]),
        (DecisionTreeClassifier(),
         [{"max_depth": d, "min_instances_per_node": m}
          for d in (3, 6, 12) for m in (10, 100)]),
        (NaiveBayes(), [{"smoothing": 1.0}]),
    ]


def default_regression_extra_models() -> List[Tuple[Predictor, List[Dict]]]:
    from .glm import GeneralizedLinearRegression
    from .trees import (DecisionTreeRegressor, GBTRegressor,
                        RandomForestRegressor)
    return [
        (RandomForestRegressor(),
         [{"max_depth": d, "num_trees": t}
          for d in (3, 6, 12) for t in (10, 50)]),
        (GBTRegressor(),
         [{"max_depth": d, "num_rounds": r}
          for d in (3, 6) for r in (50, 100)]),
        (DecisionTreeRegressor(),
         [{"max_depth": d, "min_instances_per_node": m}
          for d in (3, 6, 12) for m in (10, 100)]),
        (GeneralizedLinearRegression(),
         [{"family": "gaussian", "reg_param": r} for r in (0.001, 0.01, 0.1)]),
    ]
