"""Default model pools for the selector factories.

Centralizes the per-problem-type candidate pools + hyperparameter grids,
mirroring the reference's defaults
(BinaryClassificationModelSelector.scala:57-128 `defaultModelsToUse` =
LR / RandomForest / GBT / LinearSVC — NaiveBayes, DecisionTree and
XGBoost are declared but opt-in via `modelTypesToUse`;
MultiClassificationModelSelector.scala:138-183;
RegressionModelSelector.scala:150-193; grid values from
DefaultSelectorParams.scala:36-59).

Documented deviation: the reference's RF/DT grids sweep minInfoGain over
(0.001, 0.01, 0.1); we pin minInfoGain=0.001 (the Spark-near-default
end) and sweep depth x minInstancesPerNode, keeping the search's
shape-distinct compile count low — the dominant quality factors for
these families on tabular data are depth and leaf-size regularization.
"""
from __future__ import annotations

from typing import Dict, List, Tuple

from .base import Predictor

__all__ = ["default_binary_models", "default_multiclass_models",
           "default_regression_models", "default_binary_extra_models",
           "default_multiclass_extra_models",
           "default_regression_extra_models"]

#: DefaultSelectorParams.Regularization
_REG = (0.001, 0.01, 0.1, 0.2)
#: DefaultSelectorParams.ElasticNet
_ELASTIC = (0.1, 0.5)
#: DefaultSelectorParams.MaxDepth
_DEPTH = (3, 6, 12)
#: DefaultSelectorParams.MinInstancesPerNode
_MIN_INST = (10, 100)
#: DefaultSelectorParams.{MaxTrees, MaxIterTree, MaxIterLin}
_NUM_TREES, _GBT_ROUNDS, _MAX_ITER_LIN = 50, 20, 50


def default_binary_models() -> List[Tuple[Predictor, List[Dict]]]:
    """Reference defaultModelsToUse: LR, RF, GBT, SVC
    (BinaryClassificationModelSelector.scala:57-60)."""
    from .linear import LinearSVC, LogisticRegression
    from .trees import GBTClassifier, RandomForestClassifier
    return [
        (LogisticRegression(max_iter=_MAX_ITER_LIN),
         [{"reg_param": r, "elastic_net_param": e}
          for r in _REG for e in _ELASTIC]),
        (RandomForestClassifier(num_trees=_NUM_TREES,
                                min_info_gain=0.001),
         [{"max_depth": d, "min_instances_per_node": m}
          for d in _DEPTH for m in _MIN_INST]),
        (GBTClassifier(num_rounds=_GBT_ROUNDS),
         [{"max_depth": d, "min_child_weight": float(m)}
          for d in _DEPTH for m in (1, 10)]),
        (LinearSVC(max_iter=_MAX_ITER_LIN),
         [{"reg_param": r} for r in _REG]),
    ]


def default_binary_extra_models() -> List[Tuple[Predictor, List[Dict]]]:
    """Opt-in families (reference modelsAndParams minus
    defaultModelsToUse): NaiveBayes, DecisionTree, XGBoost."""
    from .bayes import NaiveBayes
    from .trees import DecisionTreeClassifier, XGBoostClassifier
    return [
        (NaiveBayes(), [{"smoothing": 1.0}]),
        (DecisionTreeClassifier(min_info_gain=0.001),
         [{"max_depth": d, "min_instances_per_node": m}
          for d in _DEPTH for m in _MIN_INST]),
        (XGBoostClassifier(),
         [{"max_depth": d, "eta": e}
          for d in _DEPTH for e in (0.1, 0.3)]),
    ]


def default_multiclass_models() -> List[Tuple[Predictor, List[Dict]]]:
    """Reference MultiClassificationModelSelector defaults: LR, RF, NB,
    DT (MultiClassificationModelSelector.scala:138-183)."""
    from .bayes import NaiveBayes
    from .linear import LogisticRegression
    from .trees import DecisionTreeClassifier, RandomForestClassifier
    return [
        (LogisticRegression(max_iter=_MAX_ITER_LIN),
         [{"reg_param": r, "elastic_net_param": e}
          for r in _REG for e in _ELASTIC]),
        (RandomForestClassifier(num_trees=_NUM_TREES,
                                min_info_gain=0.001),
         [{"max_depth": d, "min_instances_per_node": m}
          for d in _DEPTH for m in _MIN_INST]),
        (NaiveBayes(), [{"smoothing": 1.0}]),
        (DecisionTreeClassifier(min_info_gain=0.001),
         [{"max_depth": d, "min_instances_per_node": m}
          for d in _DEPTH for m in _MIN_INST]),
    ]


def default_multiclass_extra_models() -> List[Tuple[Predictor, List[Dict]]]:
    """Opt-in multiclass families: softmax XGBoost (the reference's
    xgboost4j handles K classes via multi:softprob,
    OpXGBoostClassifier.scala:47) and the MLP."""
    from .mlp import MultilayerPerceptronClassifier
    from .trees import XGBoostClassifier
    return [
        (XGBoostClassifier(num_round=_GBT_ROUNDS),
         [{"max_depth": d, "min_child_weight": float(m)}
          for d in _DEPTH for m in _MIN_INST[:1]]),
        (MultilayerPerceptronClassifier(),
         [{"hidden_layers": h} for h in ((10,), (32, 16))]),
    ]


def default_regression_models() -> List[Tuple[Predictor, List[Dict]]]:
    """Reference RegressionModelSelector defaults: LinReg, RF, GBT, GLM
    + DT in modelsAndParams (RegressionModelSelector.scala:150-193,
    DistFamily gaussian/poisson)."""
    from .glm import GeneralizedLinearRegression
    from .linear import LinearRegression
    from .trees import (DecisionTreeRegressor, GBTRegressor,
                        RandomForestRegressor)
    return [
        (LinearRegression(max_iter=_MAX_ITER_LIN),
         [{"reg_param": r, "elastic_net_param": e}
          for r in _REG for e in _ELASTIC]),
        (RandomForestRegressor(num_trees=_NUM_TREES, min_info_gain=0.001),
         [{"max_depth": d, "min_instances_per_node": m}
          for d in _DEPTH for m in _MIN_INST]),
        (GBTRegressor(num_rounds=_GBT_ROUNDS),
         [{"max_depth": d, "min_child_weight": float(m)}
          for d in _DEPTH for m in (1, 10)]),
        (GeneralizedLinearRegression(),
         [{"family": f, "reg_param": r}
          for f in ("gaussian", "poisson") for r in (0.001, 0.01, 0.1)]),
    ]


def default_regression_extra_models() -> List[Tuple[Predictor, List[Dict]]]:
    from .trees import DecisionTreeRegressor, XGBoostRegressor
    return [
        (DecisionTreeRegressor(min_info_gain=0.001),
         [{"max_depth": d, "min_instances_per_node": m}
          for d in _DEPTH for m in _MIN_INST]),
        (XGBoostRegressor(),
         [{"max_depth": d, "eta": e} for d in _DEPTH for e in (0.1, 0.3)]),
    ]
