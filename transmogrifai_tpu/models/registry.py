"""Default model pools for the selector factories.

Centralizes the per-problem-type candidate pools + hyperparameter grids,
mirroring the reference's defaults
(BinaryClassificationModelSelector.scala:57-128 `defaultModelsToUse` =
LR / RandomForest / GBT / LinearSVC — NaiveBayes, DecisionTree and
XGBoost are declared but opt-in via `modelTypesToUse`;
MultiClassificationModelSelector.scala:138-183;
RegressionModelSelector.scala:150-193; grid values from
DefaultSelectorParams.scala:36-59).

minInfoGain is swept over (0.001, 0.01, 0.1) exactly as
DefaultSelectorParams.MinInfoGain prescribes: it is a *traced* scalar
in the batched fold x grid kernels (`trees._FOREST_TRACED`), so the
sweep adds vmapped candidate lanes, not compiles. For the XGB-style
GBT booster the analog is ``gamma`` (min split-loss reduction), swept
over the same values; ``min_child_weight`` (1, 10) plays the
minInstancesPerNode (10, 100) role on the hessian scale.
"""
from __future__ import annotations

from typing import Dict, List, Tuple

from .base import Predictor

__all__ = ["default_binary_models", "default_multiclass_models",
           "default_regression_models", "default_binary_extra_models",
           "default_multiclass_extra_models",
           "default_regression_extra_models"]

#: DefaultSelectorParams.Regularization
_REG = (0.001, 0.01, 0.1, 0.2)
#: DefaultSelectorParams.ElasticNet
_ELASTIC = (0.1, 0.5)
#: DefaultSelectorParams.MaxDepth
_DEPTH = (3, 6, 12)
#: DefaultSelectorParams.MinInstancesPerNode
_MIN_INST = (10, 100)
#: DefaultSelectorParams.MinInfoGain
_MIN_GAIN = (0.001, 0.01, 0.1)
#: DefaultSelectorParams.MinChildWeight (xgboost)
_MIN_CHILD = (1.0, 5.0, 10.0)
#: DefaultSelectorParams.{MaxTrees, MaxIterTree, MaxIterLin}
_NUM_TREES, _GBT_ROUNDS, _MAX_ITER_LIN = 50, 20, 50


def default_binary_models() -> List[Tuple[Predictor, List[Dict]]]:
    """Reference defaultModelsToUse: LR, RF, GBT, SVC
    (BinaryClassificationModelSelector.scala:57-60)."""
    from .linear import LinearSVC, LogisticRegression
    from .trees import GBTClassifier, RandomForestClassifier
    return [
        (LogisticRegression(max_iter=_MAX_ITER_LIN),
         [{"reg_param": r, "elastic_net_param": e}
          for r in _REG for e in _ELASTIC]),
        (RandomForestClassifier(num_trees=_NUM_TREES),
         [{"max_depth": d, "min_instances_per_node": m, "min_info_gain": g}
          for d in _DEPTH for m in _MIN_INST for g in _MIN_GAIN]),
        (GBTClassifier(num_rounds=_GBT_ROUNDS),
         [{"max_depth": d, "min_child_weight": float(m), "gamma": g}
          for d in _DEPTH for m in (1, 10) for g in _MIN_GAIN]),
        (LinearSVC(max_iter=_MAX_ITER_LIN),
         [{"reg_param": r} for r in _REG]),
    ]


def default_binary_extra_models() -> List[Tuple[Predictor, List[Dict]]]:
    """Opt-in families (reference modelsAndParams minus
    defaultModelsToUse): NaiveBayes, DecisionTree, XGBoost."""
    from .bayes import NaiveBayes
    from .trees import DecisionTreeClassifier, XGBoostClassifier
    return [
        (NaiveBayes(), [{"smoothing": 1.0}]),
        (DecisionTreeClassifier(),
         [{"max_depth": d, "min_instances_per_node": m, "min_info_gain": g}
          for d in _DEPTH for m in _MIN_INST for g in _MIN_GAIN]),
        (XGBoostClassifier(),
         [{"max_depth": d, "eta": e, "min_child_weight": m}
          for d in _DEPTH for e in (0.1, 0.3) for m in _MIN_CHILD]),
    ]


def default_multiclass_models() -> List[Tuple[Predictor, List[Dict]]]:
    """Reference MultiClassificationModelSelector defaults: LR, RF, NB,
    DT (MultiClassificationModelSelector.scala:138-183)."""
    from .bayes import NaiveBayes
    from .linear import LogisticRegression
    from .trees import DecisionTreeClassifier, RandomForestClassifier
    return [
        (LogisticRegression(max_iter=_MAX_ITER_LIN),
         [{"reg_param": r, "elastic_net_param": e}
          for r in _REG for e in _ELASTIC]),
        (RandomForestClassifier(num_trees=_NUM_TREES),
         [{"max_depth": d, "min_instances_per_node": m, "min_info_gain": g}
          for d in _DEPTH for m in _MIN_INST for g in _MIN_GAIN]),
        (NaiveBayes(), [{"smoothing": 1.0}]),
        (DecisionTreeClassifier(),
         [{"max_depth": d, "min_instances_per_node": m, "min_info_gain": g}
          for d in _DEPTH for m in _MIN_INST for g in _MIN_GAIN]),
    ]


def default_multiclass_extra_models() -> List[Tuple[Predictor, List[Dict]]]:
    """Opt-in multiclass families: softmax XGBoost (the reference's
    xgboost4j handles K classes via multi:softprob,
    OpXGBoostClassifier.scala:47) and the MLP."""
    from .mlp import MultilayerPerceptronClassifier
    from .trees import XGBoostClassifier
    return [
        (XGBoostClassifier(num_round=_GBT_ROUNDS),
         [{"max_depth": d, "min_child_weight": m}
          for d in _DEPTH for m in _MIN_CHILD]),
        (MultilayerPerceptronClassifier(),
         [{"hidden_layers": h} for h in ((10,), (32, 16))]),
    ]


def default_regression_models() -> List[Tuple[Predictor, List[Dict]]]:
    """Reference RegressionModelSelector defaults: LinReg, RF, GBT, GLM
    + DT in modelsAndParams (RegressionModelSelector.scala:150-193,
    DistFamily gaussian/poisson)."""
    from .glm import GeneralizedLinearRegression
    from .linear import LinearRegression
    from .trees import (DecisionTreeRegressor, GBTRegressor,
                        RandomForestRegressor)
    return [
        (LinearRegression(max_iter=_MAX_ITER_LIN),
         [{"reg_param": r, "elastic_net_param": e}
          for r in _REG for e in _ELASTIC]),
        (RandomForestRegressor(num_trees=_NUM_TREES),
         [{"max_depth": d, "min_instances_per_node": m, "min_info_gain": g}
          for d in _DEPTH for m in _MIN_INST for g in _MIN_GAIN]),
        (GBTRegressor(num_rounds=_GBT_ROUNDS),
         [{"max_depth": d, "min_child_weight": float(m), "gamma": g}
          for d in _DEPTH for m in (1, 10) for g in _MIN_GAIN]),
        (GeneralizedLinearRegression(),
         [{"family": f, "reg_param": r}
          for f in ("gaussian", "poisson") for r in (0.001, 0.01, 0.1)]),
    ]


def default_regression_extra_models() -> List[Tuple[Predictor, List[Dict]]]:
    from .trees import DecisionTreeRegressor, XGBoostRegressor
    return [
        (DecisionTreeRegressor(),
         [{"max_depth": d, "min_instances_per_node": m, "min_info_gain": g}
          for d in _DEPTH for m in _MIN_INST for g in _MIN_GAIN]),
        (XGBoostRegressor(),
         [{"max_depth": d, "eta": e, "min_child_weight": m}
          for d in _DEPTH for e in (0.1, 0.3) for m in _MIN_CHILD]),
    ]
