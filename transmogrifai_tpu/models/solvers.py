"""Full-batch convex solvers shared by the linear model family.

The reference delegates optimization to Spark MLlib's breeze L-BFGS /
OWL-QN (e.g. LogisticRegression inside
core/src/main/scala/com/salesforce/op/stages/impl/classification/
OpLogisticRegression.scala:45). TPU-native equivalents:

- :func:`lbfgs_minimize` — optax L-BFGS with zoom linesearch inside a
  ``lax.while_loop``; fully jittable and vmappable (grid points of a
  hyperparameter sweep batch through ``vmap``), so a whole regularization
  path fits in one XLA program on the MXU.
- :func:`fista_minimize` — proximal gradient with Nesterov acceleration
  for elastic-net (L1) penalties, replacing breeze OWL-QN.

(The non-convex MLP's BATCHED fold x grid path uses a fixed-trip
mini-batch Adam loop instead — it needs per-step data slicing, so it
lives next to the model in models/mlp.py:_mlp_batched_fit.)

Everything is static-shape: no data-dependent Python control flow, only
``lax.while_loop`` with scalar convergence predicates (or fixed-length
``lax.scan``).
"""
from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp
import optax
import optax.tree_utils as otu

__all__ = ["lbfgs_minimize", "fista_minimize"]

#: optax < 0.2.4 ships only tree_l2_norm; tree_norm is its later alias
_tree_norm = getattr(otu, "tree_norm", None) or otu.tree_l2_norm


def lbfgs_minimize(loss_fn: Callable, w0, max_iter: int = 100,
                   tol: float = 1e-6):
    """Minimize a smooth loss with L-BFGS; returns the final params.

    ``loss_fn`` must be a pure scalar function of the params pytree.
    """
    opt = optax.lbfgs()
    value_and_grad = optax.value_and_grad_from_state(loss_fn)

    def step(carry):
        params, state = carry
        value, grad = value_and_grad(params, state=state)
        updates, state = opt.update(grad, state, params, value=value,
                                    grad=grad, value_fn=loss_fn)
        params = optax.apply_updates(params, updates)
        return params, state

    def continuing(carry):
        _, state = carry
        count = otu.tree_get(state, "count")
        grad = otu.tree_get(state, "grad")
        err = _tree_norm(grad)
        return (count == 0) | ((count < max_iter) & (err >= tol))

    final_params, _ = jax.lax.while_loop(
        continuing, step, (w0, opt.init(w0)))
    return final_params


def _power_iteration_sq_norm(X: jnp.ndarray, iters: int = 16,
                             w: jnp.ndarray | None = None,
                             axis_name: str | None = None) -> jnp.ndarray:
    """Largest eigenvalue of X^T diag(w) X / sum(w) (Lipschitz constant
    scale) via power iteration — static iteration count for XLA. With
    ``axis_name`` set, X/w are row shards of a mesh data axis and the
    matvec reductions cross it via psum."""
    n, d = X.shape
    v0 = jnp.ones((d,), X.dtype) / jnp.sqrt(d)

    def psum(x):
        return jax.lax.psum(x, axis_name) if axis_name else x

    if w is None:
        wsum = psum(jnp.asarray(float(n), X.dtype))

        def matvec(v):
            return psum(X.T @ (X @ v)) / wsum
    else:
        wsum = jnp.maximum(psum(jnp.sum(w)), 1e-12)

        def matvec(v):
            return psum(X.T @ (w * (X @ v))) / wsum

    def body(_, v):
        u = matvec(v)       # u is replicated across the data axis
        return u / (jnp.linalg.norm(u) + 1e-12)

    v = jax.lax.fori_loop(0, iters, body, v0)
    return jnp.vdot(v, matvec(v))


def fista_minimize(smooth_loss: Callable, l1: float, w0: jnp.ndarray,
                   lipschitz: jnp.ndarray, max_iter: int = 500,
                   tol: float = 1e-7,
                   l1_mask: jnp.ndarray | None = None,
                   grad_psum_axis: str | None = None) -> jnp.ndarray:
    """FISTA: minimize ``smooth_loss(w) + l1 * ||mask * w||_1``.

    ``lipschitz`` bounds the smooth gradient's Lipschitz constant (use
    :func:`_power_iteration_sq_norm` on the design matrix plus the L2
    penalty strength). ``l1_mask`` excludes entries (e.g. the intercept)
    from the penalty.

    Mesh execution (shard_map data axis): pass a SHARD-LOCAL loss plus
    ``grad_psum_axis`` — the gradient is psum'd explicitly across the
    axis, so autodiff never has to transpose a collective (which is
    silently wrong under check_vma=False). ``tol <= 0`` runs EXACTLY
    ``max_iter`` iterations via ``fori_loop`` — required under a mesh so
    every shard hits the same collectives in lockstep.
    """
    mask = jnp.ones_like(w0) if l1_mask is None else l1_mask
    step = 1.0 / jnp.maximum(lipschitz, 1e-12)
    local_grad = jax.grad(smooth_loss)
    if grad_psum_axis is None:
        grad_fn = local_grad
    else:
        def grad_fn(w):
            return jax.lax.psum(local_grad(w), grad_psum_axis)

    def prox(w):
        return jnp.where(
            mask > 0,
            jnp.sign(w) * jnp.maximum(jnp.abs(w) - step * l1, 0.0), w)

    def body(carry):
        w, z, t, _, it = carry
        w_next = prox(z - step * grad_fn(z))
        t_next = (1.0 + jnp.sqrt(1.0 + 4.0 * t * t)) / 2.0
        z_next = w_next + ((t - 1.0) / t_next) * (w_next - w)
        delta = jnp.linalg.norm(w_next - w)
        return w_next, z_next, t_next, delta, it + 1

    init = (w0, w0, jnp.asarray(1.0, w0.dtype),
            jnp.asarray(jnp.inf, w0.dtype), jnp.asarray(0))
    if tol <= 0:
        w, *_ = jax.lax.fori_loop(0, max_iter, lambda _, c: body(c), init)
        return w

    def continuing(carry):
        _, _, _, delta, it = carry
        return (it == 0) | ((it < max_iter) & (delta >= tol))

    w, *_ = jax.lax.while_loop(continuing, body, init)
    return w


def design_lipschitz(X: jnp.ndarray, l2: float,
                     curvature_bound: float = 0.25,
                     w: jnp.ndarray | None = None,
                     axis_name: str | None = None) -> jnp.ndarray:
    """Lipschitz bound for losses of the form
    sum(w*phi(x.b))/sum(w) + l2/2 ||b||^2 where phi'' <= curvature_bound
    (0.25 for logistic, 1.0 for squared). ``w`` are optional row weights
    (fold masks); ``axis_name`` enables mesh data-axis psum."""
    return (curvature_bound
            * _power_iteration_sq_norm(X, w=w, axis_name=axis_name) + l2)
