"""Tree model family: decision tree, random forest, gradient-boosted trees.

TPU-native replacements for the reference's Spark MLlib / XGBoost wrappers:
- OpDecisionTreeClassifier / OpDecisionTreeRegressor
  (core/.../classification/OpDecisionTreeClassifier.scala,
   core/.../regression/OpDecisionTreeRegressor.scala)
- OpRandomForestClassifier / OpRandomForestRegressor
  (core/.../classification/OpRandomForestClassifier.scala)
- OpGBTClassifier / OpGBTRegressor
  (core/.../classification/OpGBTClassifier.scala)
- OpXGBoostClassifier / OpXGBoostRegressor
  (core/.../classification/OpXGBoostClassifier.scala:47 — xgboost4j JNI,
   the reference's only native-C++ compute; see SURVEY.md §2.9)

Design (histogram GBDT, XLA-first — no CUDA/Rabit translation):

- Features are quantile-binned once into <= ``max_bins`` integer bins
  (MLlib ``maxBins``/XGBoost ``tree_method=hist`` equivalent).
- Trees grow **level-wise** with ACTIVE-NODE SLOT COMPRESSION (deep
  levels of a complete tree are mostly empty; histograms cover only
  occupied nodes) over PACKED variable-width bins: every level computes
  per-(slot, packed-bin) statistic histograms via fused ``segment_sum``
  scatters (chunked over feature blocks to bound memory), turns them
  into split gains with one segmented cumulative sum over the packed
  axis, and advances every row one level. No data-dependent shapes
  anywhere, so the whole builder jits into one XLA program; a forest is
  a ``lax.scan`` of that program over bootstrap keys (with per-tree
  feature pools bounding histogram width) and boosting is a
  ``lax.scan`` of it over rounds with margin updates.
- Nodes that fail the gain/min-weight checks emit a +inf threshold
  ("everything goes left"), which makes dead branches self-propagating
  without ragged control flow.
- Split histograms sum 2nd-order grad/hess stats (XGBoost objective)
  or class-count/variance stats (MLlib gini/variance impurity).

Distributed fit: histograms are linear in rows, so data-parallel
multi-chip training is a ``psum`` of per-shard histograms over ICI —
the TPU equivalent of XGBoost's Rabit allreduce (see parallel/cv.py for
the mesh machinery). The builders here take already-materialized
device arrays and are safe to call inside ``shard_map``.
"""
from __future__ import annotations

import functools
import logging
import os
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

_log = logging.getLogger(__name__)

from ..features.columns import PredictionColumn
from .base import (ClassifierModel, Predictor, RegressionModel,
                   check_fold_classes, num_classes, subset_grid)
from ..parallel.mesh import to_host
from ..utils.jax_setup import shard_map

__all__ = [
    "DecisionTreeClassifier", "DecisionTreeRegressor",
    "RandomForestClassifier", "RandomForestRegressor",
    "GBTClassifier", "GBTRegressor",
    "XGBoostClassifier", "XGBoostRegressor",
    "TreeEnsembleClassifierModel", "TreeEnsembleRegressorModel",
    "GBTClassifierModel", "GBTRegressorModel",
    "GBTMulticlassClassifierModel",
]


# ---------------------------------------------------------------------------
# binning — packed variable-width bins
# ---------------------------------------------------------------------------
#
# Transmogrified feature matrices are dominated by one-hot columns with
# only two distinct values; giving every feature a uniform ``max_bins``-
# wide histogram wastes ~max_bins/2 x HBM traffic on them. Instead each
# feature gets its own bin count (pow2-quantized so fold-to-fold
# cardinality jitter doesn't change compiled shapes) and all features'
# bins are PACKED into one flat axis of ``total_bins`` entries. Per-level
# histograms are then (slots, total_bins, S) — one fused scatter-add —
# and split gains come from a single segmented cumulative sum over the
# packed axis.


#: edge-matrix element count (edge rows x features) above which the
#: auto binning mode moves quantile binning onto the accelerator: the
#: per-feature host loop (np.unique + np.quantile + searchsorted, all
#: f64 sorts) measured ~48 s at 1M x 100 vs ~4.5 s for the entire warm
#: device GBT fit it feeds (BASELINE.md r5)
_DEVICE_BIN_MIN_ELEMS = int(os.environ.get("TX_DEVICE_BIN_MIN_ELEMS",
                                           "4000000"))


def _binning_mode() -> str:
    """Where quantile bin edges + digitization run: "host" (the exact
    f64 numpy per-feature loop), "device" (f32 column sorts + quantile
    gathers + compare-sum digitize, one XLA program set), or "auto"
    (default): device when an accelerator backend is active and the
    edge matrix is >= _DEVICE_BIN_MIN_ELEMS elements. The device path
    deviates from host only in f32 arithmetic (edges can shift ~1 ulp
    around ties); small fits and CPU runs keep host binning bit-exact.
    TX_TREE_BINNING overrides."""
    mode = os.environ.get("TX_TREE_BINNING", "auto")
    return mode if mode in ("host", "device") else "auto"


@jax.jit
def _device_sort_stats(E: jnp.ndarray):
    """Column-sorted copy + per-column unique count of the edge-row
    matrix — the device half of width/edge estimation."""
    s = jnp.sort(E, axis=0)
    uniq = 1 + jnp.sum(jnp.diff(s, axis=0) != 0, axis=0)
    return s, uniq


@jax.jit
def _device_edge_gather(sT: jnp.ndarray, lo: jnp.ndarray,
                        frac: jnp.ndarray) -> jnp.ndarray:
    """np.quantile's linear interpolation, vectorized: value at sorted
    position ``lo + frac`` per (feature, interior-quantile)."""
    m = sT.shape[1]
    vlo = jnp.take_along_axis(sT, lo, axis=1)
    vhi = jnp.take_along_axis(sT, jnp.minimum(lo + 1, m - 1), axis=1)
    return vlo + frac * (vhi - vlo)


@functools.partial(jax.jit, static_argnames=("chunk",))
def _device_digitize(Xp: jnp.ndarray, edges: jnp.ndarray,
                     chunk: int) -> jnp.ndarray:
    """searchsorted(edges_f, x, side="left") for every feature column:
    the bin index is the count of that feature's edges strictly below
    x (+inf padding never counts). Row-chunked via lax.map so the
    (chunk, d, max_width) compare transient stays bounded."""
    k = Xp.shape[0] // chunk

    def one(xb):
        return jnp.sum(xb[:, :, None] > edges[None], axis=-1,
                       dtype=jnp.int32)
    return jax.lax.map(one, Xp.reshape(k, chunk, -1)).reshape(
        Xp.shape[0], -1)


class _PackedDesign:
    """Host-prepared binning of a feature matrix (one per fit).

    Attributes (n rows, d features, TB = sum of per-feature bin counts):
      packed    (n, d) int32 — packed bin index of row i, feature f
                (feature f's block spans [offset_f, offset_f + B_f))
      feat_of   (TB,) int32 — original feature id per packed bin
      block_start (TB,) int32 — packed index of the owning block's start
      packed_thr (TB,) float — split threshold "x <= thr" when splitting
                at this bin; +inf marks last/padded bins (not a split)
    """

    __slots__ = ("packed", "feat_of", "block_start", "packed_thr",
                 "binned", "col_thr", "widths", "max_width", "n", "d",
                 "total_bins")

    def __init__(self, X: np.ndarray, max_bins: int,
                 edge_rows: Optional[np.ndarray] = None):
        """``edge_rows`` restricts QUANTILE-EDGE estimation to those
        rows (the fold-train rows under ``TX_TREE_EDGES=fold``) while
        still binning every row of ``X`` — out-of-fold rows never
        influence where the splits can fall."""
        n, d = X.shape          # numpy or device array — never download
        e_rows = n if edge_rows is None else len(edge_rows)
        mode = _binning_mode()
        use_device = mode == "device" or (
            mode == "auto" and e_rows * d >= _DEVICE_BIN_MIN_ELEMS
            and jax.default_backend() != "cpu")
        if use_device:
            thr_parts, widths, binned = self._bin_device(
                X, max_bins, edge_rows)
        else:
            thr_parts, widths, binned = self._bin_host(
                X, max_bins, edge_rows)
        offsets = np.concatenate([[0], np.cumsum(widths)[:-1]]).astype(np.int32)
        self.n, self.d = n, d
        self.total_bins = int(np.sum(widths))
        #: (n, d) per-feature bin ids (uniform addressing for feature-
        #: pool gathers) and (d, max_width) per-feature thresholds
        #: (+inf padded = not-a-split). Device-binned designs keep the
        #: two (n, d) matrices as DEVICE arrays — their only consumer
        #: (_design_args) re-uploads host copies otherwise, and a
        #: 1M x 100 int32 round-trip through a remote-TPU tunnel is
        #: pure waste.
        self.binned = binned
        self.widths = np.asarray(widths, dtype=np.int64)
        self.max_width = int(max(widths))
        self.col_thr = np.full((d, self.max_width), np.inf)
        for f in range(d):
            t = thr_parts[f]
            self.col_thr[f, :len(t)] = t
        self.packed = binned + (jnp.asarray(offsets[None, :])
                                if isinstance(binned, jnp.ndarray)
                                else offsets[None, :])
        self.feat_of = np.repeat(np.arange(d, dtype=np.int32), widths)
        self.block_start = np.repeat(offsets, widths)
        self.packed_thr = np.concatenate(thr_parts)

    @staticmethod
    def _bin_host(X: np.ndarray, max_bins: int,
                  edge_rows: Optional[np.ndarray]):
        """Exact f64 per-feature binning (the reference semantics)."""
        X = np.asarray(X, dtype=np.float64)
        E = X if edge_rows is None else X[edge_rows]
        binned_cols, thr_parts, widths = [], [], []
        for f in range(X.shape[1]):
            col = E[:, f]
            uniq = np.unique(col)
            if uniq.size <= 2:
                edges = uniq[:1]                     # one edge, two bins
                width = 2
            else:
                width = int(min(max_bins,
                                1 << int(np.ceil(np.log2(uniq.size)))))
                width = max(width, 4)
                qs = np.linspace(0.0, 1.0, width + 1)[1:-1]
                edges = np.unique(np.quantile(col, qs))
                if edges.size < width - 1:           # dedup left empty bins
                    edges = np.concatenate(
                        [edges, np.full(width - 1 - edges.size, np.inf)])
            binned_cols.append(
                np.searchsorted(edges, X[:, f],
                                side="left").astype(np.int32))
            thr_parts.append(np.concatenate([edges, [np.inf]]))
            widths.append(width)
        return thr_parts, widths, np.stack(binned_cols, axis=1)

    @staticmethod
    def _bin_device(X, max_bins: int, edge_rows: Optional[np.ndarray]):
        """f32 device binning: one column sort + unique count, one
        quantile-interpolation gather, one chunked compare-sum
        digitize — same width/edge/dedup semantics as _bin_host, with
        only small (d,)-shaped metadata crossing to the host."""
        Xd = jnp.asarray(X, jnp.float32)
        n, d = Xd.shape
        Ed = Xd if edge_rows is None else Xd[jnp.asarray(edge_rows)]
        m = int(Ed.shape[0])
        s, uniq_d = _device_sort_stats(Ed)
        uniq = np.asarray(uniq_d)
        widths = np.where(
            uniq <= 2, 2,
            np.clip(np.exp2(np.ceil(np.log2(np.maximum(uniq, 2)))),
                    4, max_bins)).astype(np.int64)
        maxw = int(widths.max())
        # interior quantile positions (host f64 math on (d, maxw-1)
        # metadata; only the value gather runs in f32)
        j = np.arange(max(maxw - 1, 1))
        q = (j[None, :] + 1) / widths[:, None].astype(np.float64)
        h = np.clip(q, 0.0, 1.0) * (m - 1)
        lo = np.floor(h).astype(np.int32)
        edges = np.asarray(_device_edge_gather(
            s.T, jnp.asarray(lo),
            jnp.asarray((h - lo).astype(np.float32))), np.float64)
        colmin = np.asarray(s[0])
        thr_parts: List[np.ndarray] = []
        for f in range(d):
            w = int(widths[f])
            if uniq[f] <= 2:
                e = colmin[f:f + 1]
            else:
                e = np.unique(edges[f, :w - 1])
                if e.size < w - 1:                   # dedup left empty bins
                    e = np.concatenate(
                        [e, np.full(w - 1 - e.size, np.inf)])
            thr_parts.append(np.concatenate([e, [np.inf]]))
        col_edges = np.full((d, maxw), np.inf)
        for f in range(d):
            t = thr_parts[f][:-1]                    # real edges only
            col_edges[f, :len(t)] = t
        chunk = max(256, min(n, _HIST_CHUNK_ELEMS // max(d * maxw, 1)))
        n_pad = -(-n // chunk) * chunk
        Xp = (jnp.pad(Xd, ((0, n_pad - n), (0, 0)))
              if n_pad != n else Xd)
        binned = _device_digitize(
            Xp, jnp.asarray(col_edges, jnp.float32), chunk)[:n]
        return thr_parts, list(widths), binned


# ---------------------------------------------------------------------------
# generic level-wise tree builder
# ---------------------------------------------------------------------------

def _compress_nodes(node: jnp.ndarray, cap: int):
    """Rank-compress true node ids (n,) into dense slots [0, cap).

    Deep levels of a level-wise tree are mostly empty (at most ``n`` of
    the ``2^level`` nodes can hold rows, and min-instances constraints
    shrink that further), so histograms/gains are computed per *active
    slot*, not per node. Sort-based ranking is O(n log n), all static
    shapes. Returns (slot_per_row (n,), node_of_slot (cap,) int32 with
    ``_SLOT_SENTINEL`` for unused slots, active_count scalar).
    """
    snode, order = jax.lax.sort_key_val(node, jnp.arange(node.shape[0],
                                                         dtype=jnp.int32))
    is_new = jnp.concatenate(
        [jnp.ones((1,), jnp.int32),
         (snode[1:] != snode[:-1]).astype(jnp.int32)])
    rank = jnp.cumsum(is_new) - 1                       # slot of sorted rows
    slot = jnp.zeros_like(node).at[order].set(rank.astype(node.dtype))
    node_of_slot = jnp.full((cap,), _SLOT_SENTINEL, jnp.int32).at[
        rank].set(snode.astype(jnp.int32), mode="drop")
    return slot, node_of_slot, rank[-1] + 1


def _compress_nodes_global(node: jnp.ndarray, cap: int, level_size: int,
                           axis_name: str):
    """Rank-compress node ids CONSISTENTLY across row shards.

    The sort-based :func:`_compress_nodes` ranks whatever nodes the
    local rows happen to occupy — under row sharding different shards
    would assign different slots to the same node, and the psum'd
    histograms would mix nodes. This variant ranks against the GLOBAL
    occupancy bitmap (one psum of a (2^level,) int vector — the same
    ICI hop the histograms take), producing the identical
    ascending-node-id slot order the sort produces on one device.
    """
    occ = jnp.zeros((level_size,), jnp.int32).at[node].set(1, mode="drop")
    occ = (jax.lax.psum(occ, axis_name) > 0).astype(jnp.int32)
    rank = jnp.cumsum(occ) - 1                      # slot per node id
    slot = rank[node].astype(node.dtype)
    node_of_slot = jnp.full((cap,), _SLOT_SENTINEL, jnp.int32).at[
        jnp.where(occ > 0, rank, cap)].set(
        jnp.arange(level_size, dtype=jnp.int32), mode="drop")
    return slot, node_of_slot, jnp.sum(occ)


_SLOT_SENTINEL = jnp.iinfo(jnp.int32).max

#: default per-level active-node slot cap (see _grow_tree docstring)
_DEFAULT_NODE_CAP = 256


#: cap on the (rows x features x stats) scatter-input materialized per
#: histogram call; larger designs chunk over feature blocks (the memory
#: bound the pre-packed per-feature scan used to provide)
_HIST_CHUNK_ELEMS = 32_000_000


def _hist_mode(n: int = 0, total_bins: int = 0) -> str:
    """Histogram strategy: "scatter" (fused segment_sum), "matmul"
    (one-hot contractions that ride the MXU), or "pallas" (fused VMEM-
    resident accumulation kernel, models/pallas_hist.py). Auto: matmul
    on accelerators (XLA scatters serialize there); scatter on CPU —
    r4 re-measured the flagship search ~10% faster under scatter even
    at small n*TB, retiring r3's small-problem matmul threshold (the
    fused eval kernels changed the balance). "matmul_bf16" is the
    MXU-native variant: both one-hot indicators AND the per-row stats
    cast to bfloat16, contraction accumulates in float32
    (preferred_element_type) — 0/1 indicators are exact in bf16, so the
    only approximation is ~3-decimal-digit rounding of individual
    grad/hess/count contributions before the fp32 accumulation; split
    decisions can flip on near-ties, which is why it is opt-in rather
    than the TPU default until measured (VERDICT r4 #2).
    "matmul_chunk" is exact like "matmul" but rebuilds the bin
    indicator per bin block (gather+compare, scatter-free) every level
    instead of holding the whole (n, TB) matrix — the big-n mode where
    that matrix would blow HBM.
    A ``+sub`` suffix (any base mode) additionally enables LightGBM-
    style histogram SUBTRACTION inside the level loop: identity levels
    > 0 build histograms for LEFT children only (half the slots) and
    derive each right child as parent - left — the parent histogram is
    the previous level's, and the per-row stats are level-invariant
    within a tree. Mathematically identical; float cancellation can
    move near-tie splits, so it is opt-in (TX_TREE_SUB=1) until the
    accuracy audit at scale. The suffix rides the SAME static
    ``hist_mode`` string every jitted entry pins, so toggling it
    retraces exactly like a base-mode switch.

    TX_TREE_HIST overrides. Decided at trace time (platform only for
    now — the n/total_bins parameters stay in the signature so a
    size-based policy can return without touching every call site), so
    all modes stay available side by side."""
    base_modes = ("scatter", "matmul", "pallas", "matmul_bf16",
                  "matmul_chunk")
    sub = os.environ.get("TX_TREE_SUB", "0") == "1"
    mode = os.environ.get("TX_TREE_HIST")
    if mode:
        base, plus, suffix = mode.partition("+")
        if base in base_modes:
            if plus and suffix != "sub":
                # a typo'd suffix ("pallas+sb") must not silently throw
                # away the user's explicit, valid base-mode choice
                _log.warning(
                    "TX_TREE_HIST=%r has unrecognized suffix %r "
                    "(only '+sub' exists); honoring base mode %r",
                    mode, suffix, base)
                return base + "+sub" if sub else base
            # TX_TREE_SUB composes with an explicit base mode too
            return mode if suffix == "sub" or not sub else mode + "+sub"
        _log.warning(
            "TX_TREE_HIST=%r is not a recognized histogram mode %s; "
            "falling back to the platform default", mode, base_modes)
    try:
        platform = jax.default_backend()
    except Exception:
        platform = "cpu"
    mode = "matmul" if platform != "cpu" else "scatter"
    return mode + "+sub" if sub else mode


def _bin_indicator(packed: jnp.ndarray, total_bins: int, dtype,
                   feat_of: jnp.ndarray,
                   lo: int = 0, hi: Optional[int] = None) -> jnp.ndarray:
    """(n, hi-lo) 0/1 bin-membership matrix for packed bins [lo, hi)
    (default: all TB bins): feature bin ranges are DISJOINT in the
    packed axis, so each row has exactly one 1 per feature block.

    The build is a GATHER + COMPARE — ``packed[:, feat_of[b]] == b`` —
    which is scatter-free (XLA serializes scatters on TPU; a column
    gather + VPU compare is not). Built once per tree for the
    whole-matrix modes, or per (level, bin-block) under
    ``matmul_chunk`` where the full (n, TB) matrix would blow HBM (the
    12.8 GB case of the BASELINE roofline)."""
    hi = total_bins if hi is None else hi
    cols = packed[:, feat_of[lo:hi]]                # (n, hi-lo) gather
    return (cols == jnp.arange(lo, hi, dtype=packed.dtype)[None, :]
            ).astype(dtype)


def _level_histograms(packed: jnp.ndarray, slot: jnp.ndarray,
                      stats: jnp.ndarray, num_slots: int,
                      total_bins: int,
                      bin_oh: Optional[jnp.ndarray] = None,
                      mode: str = "scatter",
                      axis_name: Optional[str] = None,
                      feat_of: Optional[jnp.ndarray] = None
                      ) -> jnp.ndarray:
    """(num_slots, total_bins, S) histograms. Mathematically identical
    strategies (see _hist_mode):

    - scatter (bin_oh None): fused segment_sum per feature block
      (segment id = slot*TB + packed bin), blocks bounding the
      broadcasted (n x d_block x S) scatter input to _HIST_CHUNK_ELEMS;
    - matmul / matmul_bf16 (bin_oh given): hist[c,b,s] =
      sum_i 1[slot_i=c] * binOH[i,b] * stats[i,s] — S dense
      contractions on the MXU, no per-level scatters. Peak memory is
      the (n, TB) indicator built once per tree;
    - matmul_chunk (bin_oh None, feat_of given): the same MXU
      contraction with the indicator REBUILT per bin block by gather +
      compare, bounding the transient to ~_HIST_CHUNK_ELEMS — the
      big-n mode where the whole (n, TB) indicator would blow HBM
      (BASELINE.md roofline);
    - pallas (bin_oh given): same contraction as one fused Pallas
      kernel with the accumulator VMEM-resident (models/pallas_hist.py).
    """
    n, d = packed.shape
    s_dim = stats.shape[1]
    if mode == "matmul_chunk":
        slot_oh = jax.nn.one_hot(slot, num_slots, dtype=stats.dtype)
        # per-block transient ≈ n * step elements; the floor of 8 bins
        # keeps blocks from degenerating, so the true bound is
        # max(_HIST_CHUNK_ELEMS, 8n) elements — still linear in n, the
        # unavoidable cost of materializing any (n, block) indicator
        step = max(8, min(total_bins,
                          _HIST_CHUNK_ELEMS // max(n, 1)))
        parts = []
        for lo in range(0, total_bins, step):
            hi = min(lo + step, total_bins)
            oh = _bin_indicator(packed, total_bins, stats.dtype,
                                feat_of, lo, hi)
            parts.append(jnp.einsum("nc,ns,nb->cbs", slot_oh, stats, oh))
        hist = jnp.concatenate(parts, axis=1)
        return (jax.lax.psum(hist, axis_name) if axis_name else hist)
    if bin_oh is not None:
        if mode == "pallas":
            from transmogrifai_tpu.models.pallas_hist import (
                pallas_level_hist)
            hist = pallas_level_hist(bin_oh, slot, stats, num_slots)
        elif mode == "matmul_bf16":
            # MXU-native: bf16 operands, fp32 accumulation. bin_oh is
            # already bf16 (built once per tree); the per-row stats
            # round to bf16 here — the one approximation of this mode
            # (see _hist_mode docstring).
            slot_oh = jax.nn.one_hot(slot, num_slots, dtype=jnp.bfloat16)
            hist = jnp.einsum(
                "nc,ns,nb->cbs", slot_oh, stats.astype(jnp.bfloat16),
                bin_oh, preferred_element_type=jnp.float32
            ).astype(stats.dtype)
        else:
            slot_oh = jax.nn.one_hot(slot, num_slots, dtype=stats.dtype)
            hist = jnp.einsum("nc,ns,nb->cbs", slot_oh, stats, bin_oh)
        # histograms are linear in rows: the data-parallel reduction is
        # one psum over ICI — the Rabit-allreduce role (SURVEY §2.9)
        return (jax.lax.psum(hist, axis_name) if axis_name else hist)
    n_chunks = max(1, -(- (n * d * s_dim) // _HIST_CHUNK_ELEMS))
    step = -(-d // n_chunks)
    segs = num_slots * total_bins
    out = None
    for lo in range(0, d, step):
        blk = packed[:, lo:lo + step]
        db = blk.shape[1]
        seg = slot[:, None] * total_bins + blk
        part = jax.ops.segment_sum(
            jnp.broadcast_to(stats[:, None, :], (n, db, s_dim)
                             ).reshape(n * db, s_dim),
            seg.reshape(-1), num_segments=segs)
        out = part if out is None else out + part
    if axis_name:
        out = jax.lax.psum(out, axis_name)
    return out.reshape(num_slots, total_bins, s_dim)


def _grow_tree(packed: jnp.ndarray, feat_of: jnp.ndarray,
               block_start: jnp.ndarray, packed_thr: jnp.ndarray,
               stats: jnp.ndarray, *, depth: int, gain_fn,
               min_info_gain: float,
               feat_key: Optional[jnp.ndarray] = None,
               max_features: Optional[int] = None,
               node_cap: Optional[int] = None,
               feat_map: Optional[jnp.ndarray] = None,
               hist_mode: Optional[str] = None,
               axis_name: Optional[str] = None,
               row_total: Optional[int] = None,
               depth_limit=None):
    """Grow one complete tree of static ``depth`` over a packed binned
    design (see :class:`_PackedDesign`).

    ``depth_limit`` (optional TRACED scalar <= depth) truncates growth:
    levels >= depth_limit are denied splits, so one compiled program at
    the grid's max depth serves every depth candidate as a vmapped lane
    (TX_TREE_DEPTH=mask — the compile-count reduction; a denied split
    routes all rows left, so shallower trees are exact, just stored in
    a deeper heap of +inf thresholds).

    gain_fn(left, right, total) -> (..., ) gains with -inf where a split
    is invalid; ``left/right`` are (C, TB, S) and ``total`` (C, 1, S).

    ``node_cap`` bounds the per-level active-node slot count (default
    ``_DEFAULT_NODE_CAP``, further clamped by the row count — a node
    with no rows is never split). If a level would overflow the cap,
    the highest-numbered nodes are denied splits (budget mask below) so
    the bound stays sound — the analogue of MLlib's maxMemoryInMB
    node-batch limiting. With default min-instances grids (>= 10) the
    cap never binds; it only limits very deep unregularized trees.

    With ``axis_name`` set (row-sharded fit inside shard_map), every
    cross-row reduction — per-level histograms, node totals, leaf stats
    and the slot-compression occupancy — goes through ``psum`` over that
    mesh axis, so each shard holds only its rows yet every shard makes
    identical split decisions (the TPU equivalent of XGBoost's Rabit
    allreduce, SURVEY §2.9). ``row_total`` must then carry the GLOBAL
    row count (slot caps must not depend on the shard-local count).

    Returns (feat_heap (2^depth - 1,), thr_heap (2^depth - 1,),
    leaf_stats (2^depth, S), final node assignment (n,)).
    """
    n, d = packed.shape
    TB = feat_of.shape[0]
    cap = min(row_total if row_total is not None else n,
              _DEFAULT_NODE_CAP if node_cap is None else node_cap)
    node = jnp.zeros((n,), jnp.int32)
    heap_len = max(2 ** depth - 1, 1)
    feat_heap = jnp.zeros((heap_len,), jnp.int32)[:2 ** depth - 1]
    thr_heap = jnp.full((heap_len,), jnp.inf, stats.dtype)[:2 ** depth - 1]
    not_a_split = ~jnp.isfinite(packed_thr)     # last + padded bins
    # resolved here only when the caller did not pin it; jitted entry
    # points MUST pin it (static arg) or mode switches won't retrace
    hist_mode = hist_mode or _hist_mode(n, TB)
    sub_enabled = hist_mode.endswith("+sub")
    if sub_enabled:
        hist_mode = hist_mode[:-len("+sub")]
    if hist_mode == "matmul_bf16":
        bin_oh = _bin_indicator(packed, TB, jnp.bfloat16, feat_of)
    elif hist_mode in ("matmul", "pallas"):
        ind_gb = n * TB * jnp.dtype(stats.dtype).itemsize / 2 ** 30
        if ind_gb > 4.0:
            # the (n, TB) indicator is re-read every level; at this
            # size it dominates HBM (BASELINE.md roofline) —
            # matmul_chunk rebuilds it per bin block instead, and bf16
            # operands halve it
            _log.warning(
                "matmul histogram indicator is %.1f GiB (%d rows x %d "
                "packed bins, %s); consider TX_TREE_HIST=matmul_chunk "
                "or matmul_bf16", ind_gb, n, TB,
                jnp.dtype(stats.dtype).name)
        bin_oh = _bin_indicator(packed, TB, stats.dtype, feat_of)
    else:
        bin_oh = None                # scatter / matmul_chunk modes
    key = feat_key
    prev_hist = None        # previous level's (C_prev, TB, S) histogram
    prev_identity = False
    for level in range(depth):
        # identity fast path: while every within-level node id fits the
        # slot cap AND the next level's budget mask cannot bind
        # (2^(level+1) <= cap, or this is the last level), slots ARE
        # node ids — the O(n log n) rank-compression sort is skipped
        # entirely. Empty nodes produce all-zero histograms -> -inf
        # gains -> they write the already-initialized (0, inf) heap
        # entries, so results are bit-identical to the compressed path.
        # With the default cap (256) this covers every level of trees up
        # to depth 9; only deeper trees pay for compression.
        identity = 2 ** level <= cap and (
            level + 1 == depth or 2 ** (level + 1) <= cap)
        if identity:
            C = 2 ** level
            slot = node
            node_of_slot = jnp.arange(C, dtype=jnp.int32)
            active = None
        else:
            C = min(2 ** level, cap)               # static slots this level
            if axis_name:
                slot, node_of_slot, active = _compress_nodes_global(
                    node, C, 2 ** level, axis_name)
            else:
                slot, node_of_slot, active = _compress_nodes(node, C)
        if (sub_enabled and identity and prev_identity
                and prev_hist is not None):
            # histogram subtraction (the LightGBM trick): rows routed
            # left stayed even-numbered (`node = 2*node + (1-go_left)`),
            # so build ONLY the left-child histograms — half the
            # contraction — indexed by parent (slot >> 1); each right
            # child is parent - left. Stats are level-invariant within
            # a tree and bins never change, so prev_hist[p] IS the
            # parent's full histogram. Odd-slot rows park on sentinel
            # slot C (== 2*C_half): one_hot zeroes it, scatter drops
            # it, and the Pallas [:num_slots] slice discards it.
            C_half = C // 2
            slot_sub = jnp.where((slot & 1) == 0, slot >> 1, C)
            hist_even = _level_histograms(
                packed, slot_sub, stats, C_half, TB, bin_oh,
                mode=hist_mode, axis_name=axis_name, feat_of=feat_of)
            hist = jnp.stack([hist_even, prev_hist - hist_even],
                             axis=1).reshape(C, TB, stats.shape[1])
        else:
            hist = _level_histograms(packed, slot, stats, C, TB, bin_oh,
                                     mode=hist_mode, axis_name=axis_name,
                                     feat_of=feat_of)
        prev_hist, prev_identity = hist, identity
        cs = jnp.cumsum(hist, axis=1)              # packed-axis running sum
        # per-feature segmented cumsum: subtract the running sum at the
        # owning block's start; splitting at bin b sends bins<=b left
        base = jnp.where((block_start > 0)[None, :, None],
                         cs[:, jnp.maximum(block_start - 1, 0), :], 0.0)
        left = cs - base
        if identity:
            # unlike compression (which only materializes non-empty
            # slots), identity slots include empty nodes; their all-zero
            # histograms yield -inf/zero gains under every default gain,
            # but a user-set gamma<0 with min_child_weight<=0 could make
            # an empty node's XGB gain positive — so count rows per slot
            # (folded into the total reduction as an extra ones column)
            # and mask empty slots out of split_ok below
            aug = jax.ops.segment_sum(
                jnp.concatenate(
                    [stats, jnp.ones((n, 1), stats.dtype)], axis=1),
                slot, num_segments=C)
            if axis_name:
                aug = jax.lax.psum(aug, axis_name)
            total = aug[:, None, :-1]
            nonempty = aug[:, -1] > 0
        else:
            total = jax.ops.segment_sum(stats, slot,
                                        num_segments=C)[:, None, :]
            if axis_name:
                total = jax.lax.psum(total, axis_name)
        right = total - left
        gain = gain_fn(left, right, total)         # (C, TB)
        gain = jnp.where(not_a_split[None, :], -jnp.inf, gain)
        if max_features is not None and max_features < d:
            key, sub = jax.random.split(key)
            if identity:
                # node_of_slot is arange(C) here — the node-keyed
                # gather below would be a no-op
                u = jax.random.uniform(sub, (C, d))
            elif 2 ** level <= cap:
                # node-keyed draw: invariant to slot numbering, so the
                # identity and compressed paths pick identical per-node
                # feature subsets. A sentinel (empty) slot clamps onto
                # the last node's row — safe not because that row is
                # unused but because sentinel-slot outputs never reach
                # the heap (mode="drop") or routing
                u = jax.random.uniform(sub, (2 ** level, d))[
                    jnp.clip(node_of_slot, 0, 2 ** level - 1)]
            else:
                u = jax.random.uniform(sub, (C, d))
            kth = jnp.sort(u, axis=1)[:, max_features - 1:max_features]
            gain = jnp.where((u <= kth)[:, feat_of], gain, -jnp.inf)
        best = jnp.argmax(gain, axis=1)            # (C,) packed bin index
        best_gain = jnp.take_along_axis(gain, best[:, None], axis=1)[:, 0]
        split_ok = best_gain >= jnp.maximum(min_info_gain, 1e-12)
        if depth_limit is not None:
            split_ok &= level < depth_limit
        if identity:
            split_ok &= nonempty
        if level + 1 < depth and not identity:
            # budget mask: next level holds at most min(2^(level+1), cap)
            # slots; each split adds one net node, so only the first
            # (budget - active) slots may split. Binds only near capacity
            # (the identity fast path above is taken exactly when it
            # cannot bind).
            budget = min(2 ** (level + 1), cap)
            split_ok &= jnp.arange(C) < (budget - active)
        bfeat = jnp.where(split_ok, feat_of[best], 0)
        thr = jnp.where(split_ok, packed_thr[best], jnp.inf)
        heap_pos = jnp.where(node_of_slot == _SLOT_SENTINEL,
                             _SLOT_SENTINEL, 2 ** level - 1 + node_of_slot)
        # feat_map translates design-local feature ids (e.g. a per-tree
        # feature pool) back to ORIGINAL column ids for the heap
        heap_feat = (bfeat if feat_map is None
                     else jnp.where(split_ok, feat_map[bfeat], 0))
        feat_heap = feat_heap.at[heap_pos].set(heap_feat, mode="drop")
        thr_heap = thr_heap.at[heap_pos].set(thr.astype(thr_heap.dtype),
                                             mode="drop")
        # route rows: packed[i, f*] <= best_packed  <=>  bin <= b; a
        # denied split routes everything left via the TB sentinel
        best_r = jnp.where(split_ok, best, TB)
        go_left = packed[jnp.arange(n), bfeat[slot]] <= best_r[slot]
        node = 2 * node + (1 - go_left.astype(jnp.int32))  # within-level idx
    leaf_stats = jax.ops.segment_sum(stats, node, num_segments=2 ** depth)
    if axis_name:
        leaf_stats = jax.lax.psum(leaf_stats, axis_name)
    return feat_heap, thr_heap, leaf_stats, node


def _traverse(X: jnp.ndarray, feat_heap: jnp.ndarray, thr_heap: jnp.ndarray,
              depth: int) -> jnp.ndarray:
    """Leaf index in [0, 2^depth) for every row; static-depth descent."""
    n = X.shape[0]
    node = jnp.zeros((n,), jnp.int32)
    rows = jnp.arange(n)
    for level in range(depth):
        heap = 2 ** level - 1 + node     # levels concatenate into the heap
        f = feat_heap[heap]
        t = thr_heap[heap]
        go_left = X[rows, f] <= t
        node = 2 * node + (1 - go_left.astype(jnp.int32))
    return node


# ---------------------------------------------------------------------------
# split criteria
# ---------------------------------------------------------------------------

def _xgb_gain(reg_lambda: float, gamma: float, min_child_weight: float):
    """Second-order gain (stats = [grad, hess]); XGBoost objective."""
    def gain(left, right, total):
        def score(s):
            return s[..., 0] ** 2 / (s[..., 1] + reg_lambda)
        g = 0.5 * (score(left) + score(right) - score(total)) - gamma
        ok = ((left[..., 1] >= min_child_weight)
              & (right[..., 1] >= min_child_weight))
        return jnp.where(ok, g, -jnp.inf)
    return gain


def _gini_gain(min_instances: float):
    """Weighted gini impurity gain (stats = per-class weights); MLlib
    'gini' impurity, tree/impurity/Gini in Spark MLlib."""
    def impurity_weighted(s):               # sum_c s_c - sum_c s_c^2 / w
        w = jnp.sum(s, axis=-1)
        return w - jnp.sum(s * s, axis=-1) / jnp.maximum(w, 1e-12)
    def gain(left, right, total):
        wl = jnp.sum(left, axis=-1)
        wr = jnp.sum(right, axis=-1)
        wp = jnp.maximum(jnp.sum(total, axis=-1), 1e-12)
        g = (impurity_weighted(total) - impurity_weighted(left)
             - impurity_weighted(right)) / wp
        ok = (wl >= min_instances) & (wr >= min_instances)
        return jnp.where(ok, g, -jnp.inf)
    return gain


def _entropy_gain(min_instances: float):
    def impurity_weighted(s):
        w = jnp.maximum(jnp.sum(s, axis=-1, keepdims=True), 1e-12)
        p = s / w
        ent = -jnp.sum(jnp.where(s > 0, p * jnp.log(p), 0.0), axis=-1)
        return w[..., 0] * ent
    def gain(left, right, total):
        wl = jnp.sum(left, axis=-1)
        wr = jnp.sum(right, axis=-1)
        wp = jnp.maximum(jnp.sum(total, axis=-1), 1e-12)
        g = (impurity_weighted(total) - impurity_weighted(left)
             - impurity_weighted(right)) / wp
        ok = (wl >= min_instances) & (wr >= min_instances)
        return jnp.where(ok, g, -jnp.inf)
    return gain


def _variance_gain(min_instances: float):
    """SSE-reduction gain (stats = [w, wy, wyy]); MLlib 'variance'."""
    def sse(s):
        return s[..., 2] - s[..., 1] ** 2 / jnp.maximum(s[..., 0], 1e-12)
    def gain(left, right, total):
        wp = jnp.maximum(total[..., 0], 1e-12)
        g = (sse(total) - sse(left) - sse(right)) / wp
        ok = ((left[..., 0] >= min_instances)
              & (right[..., 0] >= min_instances))
        return jnp.where(ok, g, -jnp.inf)
    return gain


# ---------------------------------------------------------------------------
# jitted fit programs
# ---------------------------------------------------------------------------

#: feature widths <= this form the "narrow" pool class (one-hot-ish
#: columns); wider columns form the other. Stratified per-tree pools
#: then use per-class bin widths instead of the global max, cutting
#: pooled-histogram width ~(global_max / 2) x on one-hot-heavy data
_NARROW_WIDTH = 4


def _pool_classes(widths: np.ndarray, pool_size: int, max_features: int):
    """Host-side stratified pool plan from per-feature bin widths:
    ((narrow_idx, wide_idx) host arrays, (Pn, Pw, Bn, Bw) static ints,
    effective per-node max_features)."""
    narrow = np.nonzero(widths <= _NARROW_WIDTH)[0].astype(np.int32)
    wide = np.nonzero(widths > _NARROW_WIDTH)[0].astype(np.int32)
    d = len(widths)
    # proportional split, but every NON-EMPTY class keeps >= 1 slot so no
    # feature is deterministically unreachable across the whole forest
    p_n = min(len(narrow), int(round(pool_size * len(narrow) / d)))
    if len(narrow):
        p_n = max(p_n, 1)
    p_w = min(len(wide), pool_size - p_n)
    if len(wide):
        p_w = max(p_w, 1)
    p_n = min(len(narrow), max(pool_size - p_w, 1 if len(narrow) else 0))
    b_n = int(widths[narrow].max()) if len(narrow) and p_n else 0
    b_w = int(widths[wide].max()) if len(wide) and p_w else 0
    return ((narrow, wide), (p_n, p_w, b_n, b_w),
            min(max_features, p_n + p_w))


def _tree_pool(pkey, binned, col_thr, narrow_idx, wide_idx, pool_cfg):
    """Per-tree STRATIFIED feature pool: sample narrow and wide columns
    separately (proportional to their population) and pack them with
    per-class bin widths. Histogram work then scales with the pooled
    bins, not feature_count x global_max_bins — per-node max_features
    sampling applies WITHIN the pool (documented deviation from MLlib's
    per-node-over-all-features sampling; across a 50-tree forest the
    pools cover the full feature set many times over)."""
    p_n, p_w, b_n, b_w = pool_cfg
    kn, kw = jax.random.split(pkey)
    parts_pool, parts_packed, parts_thr = [], [], []
    parts_feat, parts_block = [], []
    base_bin = 0
    base_feat = 0
    for key, idx, p, b in ((kn, narrow_idx, p_n, b_n),
                           (kw, wide_idx, p_w, b_w)):
        if p == 0:
            continue
        sel = idx[jax.random.choice(key, idx.shape[0], (p,),
                                    replace=False)]
        offs = base_bin + jnp.arange(p, dtype=jnp.int32) * b
        parts_pool.append(sel)
        parts_packed.append(jnp.take(binned, sel, axis=1) + offs[None, :])
        parts_thr.append(col_thr[sel][:, :b].reshape(p * b))
        parts_feat.append(base_feat
                          + jnp.repeat(jnp.arange(p, dtype=jnp.int32), b))
        parts_block.append(jnp.repeat(offs, b))
        base_bin += p * b
        base_feat += p
    return (jnp.concatenate(parts_pool),
            jnp.concatenate(parts_packed, axis=1),
            jnp.concatenate(parts_feat),
            jnp.concatenate(parts_block),
            jnp.concatenate(parts_thr))


def _row_draw(draw_fn, wkey, n: int, axis_name: Optional[str],
              row_total: Optional[int]):
    """Per-row random draw that is SHARD-POSITION-STABLE: under row
    sharding the draw is generated over the GLOBAL row count (identical
    on every shard — the key replicates) and each shard slices its own
    contiguous block, so a sharded fit resamples exactly the rows the
    single-device fit would (mesh ≡ local parity). The global vector is
    O(rows) scalars — negligible next to the (rows, features) design."""
    if not axis_name:
        return draw_fn(wkey, n)
    full = draw_fn(wkey, row_total)
    start = jax.lax.axis_index(axis_name) * n
    return jax.lax.dynamic_slice(full, (start,), (n,))


#: transient-memory budget for batching independent forest trees with
#: vmap (bytes); TX_TREE_BLOCK_MB overrides. Trees of a bagged forest
#: are embarrassingly parallel — a lax.scan over them serializes
#: hundreds of tiny per-level ops (the dominant cost of small-data
#: selector searches, where dispatch/latency beats FLOPs), so trees are
#: fit in vmapped BLOCKS as large as the budget allows: small data ->
#: the whole forest in one program step; huge data -> block size 1,
#: which is exactly the old scan.
_TREE_BLOCK_BUDGET_MB = 256


def _tree_budget_mb() -> Optional[int]:
    """Resolved tree-block budget in MB, or None for platform-auto
    (accelerators: default budget; CPU: no tree batching — measured a
    ~9% Titanic regression from batching on one core, where the blocks'
    dispatch-latency win doesn't exist). Callers must thread this into
    their kernel cache keys / jit statics — reading the env var inside
    an already-compiled program would silently ignore changes."""
    import os
    v = int(os.environ.get("TX_TREE_BLOCK_MB", "0"))
    return v or None


def _tree_block_size(n: int, total_bins: int, depth: int, s_dim: int,
                     num_trees: int, hist_mode: str, pooled: bool,
                     outer_batch: int = 1,
                     budget_mb: Optional[int] = None) -> int:
    if budget_mb is None:
        # platform-auto (decided at trace time, like _hist_mode): vmap
        # blocks pay on accelerators where a lax.scan of tiny per-level
        # ops is launch-latency-bound; on CPU the scan wins
        try:
            platform = jax.default_backend()
        except Exception:  # pragma: no cover - defensive
            platform = "cpu"
        if platform == "cpu":
            return 1
        budget_mb = _TREE_BLOCK_BUDGET_MB
    budget = budget_mb * 1024 * 1024
    cap = min(n, _DEFAULT_NODE_CAP)
    c_max = min(2 ** max(depth - 1, 0), cap)
    per_tree = 2 * n * 8 + 2 * c_max * total_bins * s_dim * 8
    if hist_mode and hist_mode.split("+")[0] in (
            "matmul", "pallas", "matmul_bf16", "matmul_chunk"):
        # the (n, c_max) slot one-hot is the dominant per-tree transient
        # of the einsum strategy at depth
        per_tree += n * c_max * 8
        if pooled:
            per_tree += n * total_bins * 8  # per-tree pooled bin indicator
    if pooled:
        per_tree += 3 * n * 8               # per-tree gathered design cols
    b = max(1, int(budget // max(per_tree * outer_batch, 1)))
    return min(b, num_trees)


def _forest_body(packed, feat_of, block_start, packed_thr,
                 binned, col_thr, narrow_idx, wide_idx, y, key, mask,
                 min_instances, min_info_gain, subsample, *, kind: str,
                 depth: int, num_classes: int, num_trees: int,
                 max_features: Optional[int], pool_cfg: Optional[tuple],
                 impurity: str, bootstrap: bool,
                 hist_mode: Optional[str],
                 axis_name: Optional[str] = None,
                 row_total: Optional[int] = None,
                 outer_batch: int = 1,
                 budget_mb: Optional[int] = None,
                 depth_limit=None):
    """Shared forest program: ``mask`` (n,) row weights let one body
    serve the single fit (mask=ones), the fold x grid batched kernel
    (mask = fold membership, traced per-candidate hyperparams), and the
    "models"-axis mesh path — masked rows contribute nothing to
    histograms or leaves, which is exactly fitting on the subset.
    ``axis_name`` row-shards the fit: every cross-row reduction psums
    over that mesh axis (see _grow_tree) and bootstrap draws slice a
    global-shaped sample (_row_draw). Independent trees are fit in
    vmapped blocks (see _tree_block_size); ``outer_batch`` tells the
    budget how many of these bodies an enclosing vmap runs at once."""
    n, d = packed.shape
    dtype = packed_thr.dtype
    if kind == "cls":
        onehot = jax.nn.one_hot(y.astype(jnp.int32), num_classes,
                                dtype=dtype)
        gain_fn = (_gini_gain(min_instances) if impurity == "gini"
                   else _entropy_gain(min_instances))
    else:
        gain_fn = _variance_gain(min_instances)

    def one_tree(tkey):
        pkey, wkey, fkey = jax.random.split(tkey, 3)
        if bootstrap:
            w = _row_draw(
                lambda k, m: jax.random.poisson(k, subsample,
                                                (m,)).astype(dtype),
                wkey, n, axis_name, row_total)
        else:
            w = jnp.ones((n,), dtype)
        w = w * mask
        stats = (onehot * w[:, None] if kind == "cls"
                 else jnp.stack([w, w * y, w * y * y], axis=1))
        if pool_cfg is not None:
            pool, p_sub, fo_sub, bs_sub, thr_sub = _tree_pool(
                pkey, binned, col_thr, narrow_idx, wide_idx, pool_cfg)
            feat, thr, leaf_stats, _ = _grow_tree(
                p_sub, fo_sub, bs_sub, thr_sub, stats, depth=depth,
                gain_fn=gain_fn, min_info_gain=min_info_gain,
                feat_key=fkey, max_features=max_features, feat_map=pool,
                hist_mode=hist_mode, axis_name=axis_name,
                row_total=row_total, depth_limit=depth_limit)
        else:
            feat, thr, leaf_stats, _ = _grow_tree(
                packed, feat_of, block_start, packed_thr, stats,
                depth=depth, gain_fn=gain_fn,
                min_info_gain=min_info_gain, feat_key=fkey,
                max_features=max_features, hist_mode=hist_mode,
                axis_name=axis_name, row_total=row_total,
                depth_limit=depth_limit)
        if kind == "cls":
            lw = jnp.sum(leaf_stats, axis=-1, keepdims=True)
            leaf = jnp.where(lw > 0, leaf_stats / jnp.maximum(lw, 1e-12),
                             1.0 / num_classes)
        else:
            leaf = leaf_stats[:, 1] / jnp.maximum(leaf_stats[:, 0], 1e-12)
        return feat, thr, leaf

    keys = jax.random.split(key, num_trees)
    # full-design TB is a safe upper bound for the pooled design's
    tb = _tree_block_size(
        row_total if row_total is not None else n,
        int(feat_of.shape[0]), depth,
        num_classes if kind == "cls" else 3, num_trees,
        hist_mode or "scatter", pool_cfg is not None, outer_batch,
        budget_mb=budget_mb)
    if tb >= num_trees:
        return jax.vmap(one_tree)(keys)
    if tb == 1:
        _, outs = jax.lax.scan(lambda c, k: (c, one_tree(k)), None, keys)
        return outs
    pad = (-num_trees) % tb
    keys_p = jnp.concatenate([keys, keys[:pad]], axis=0)
    _, (feats, thrs, leaves) = jax.lax.scan(
        lambda c, kb: (c, jax.vmap(one_tree)(kb)), None,
        keys_p.reshape(-1, tb, *keys.shape[1:]))
    flat = lambda a: a.reshape((-1,) + a.shape[2:])[:num_trees]
    return flat(feats), flat(thrs), flat(leaves)


@functools.partial(
    jax.jit, static_argnames=("depth", "num_classes", "num_trees",
                              "max_features", "pool_cfg", "impurity",
                              "bootstrap", "hist_mode", "budget_mb"))
def _fit_forest_classifier(packed, feat_of, block_start, packed_thr,
                           binned, col_thr, narrow_idx, wide_idx, y, key,
                           *, depth: int, num_classes: int, num_trees: int,
                           max_features: Optional[int],
                           pool_cfg: Optional[tuple], impurity: str,
                           min_instances: float, min_info_gain: float,
                           subsample: float, bootstrap: bool,
                           hist_mode: Optional[str],
                           budget_mb: Optional[int] = None):
    return _forest_body(
        packed, feat_of, block_start, packed_thr, binned, col_thr,
        narrow_idx, wide_idx, y, key, jnp.ones_like(y), min_instances,
        min_info_gain, subsample, kind="cls", depth=depth,
        num_classes=num_classes, num_trees=num_trees,
        max_features=max_features, pool_cfg=pool_cfg, impurity=impurity,
        bootstrap=bootstrap, hist_mode=hist_mode, budget_mb=budget_mb)


@functools.partial(
    jax.jit, static_argnames=("depth", "num_trees", "max_features",
                              "pool_cfg", "bootstrap", "hist_mode",
                              "budget_mb"))
def _fit_forest_regressor(packed, feat_of, block_start, packed_thr,
                          binned, col_thr, narrow_idx, wide_idx, y, key,
                          *, depth: int, num_trees: int,
                          max_features: Optional[int],
                          pool_cfg: Optional[tuple],
                          min_instances: float, min_info_gain: float,
                          subsample: float, bootstrap: bool,
                          hist_mode: Optional[str],
                          budget_mb: Optional[int] = None):
    return _forest_body(
        packed, feat_of, block_start, packed_thr, binned, col_thr,
        narrow_idx, wide_idx, y, key, jnp.ones_like(y), min_instances,
        min_info_gain, subsample, kind="reg", depth=depth, num_classes=0,
        num_trees=num_trees, max_features=max_features, pool_cfg=pool_cfg,
        impurity="", bootstrap=bootstrap, hist_mode=hist_mode,
        budget_mb=budget_mb)


def _gbt_body(packed, feat_of, block_start, packed_thr, y, key, mask,
              step_size, reg_lambda, gamma, min_child_weight, subsample,
              *, depth: int, num_rounds: int, objective: str,
              hist_mode: Optional[str],
              axis_name: Optional[str] = None,
              row_total: Optional[int] = None,
              depth_limit=None):
    """Shared boosting program with row-mask semantics (see
    _forest_body): masked rows get zero grad/hess weight; the base
    margin is the mask-weighted mean. ``axis_name`` row-shards the fit
    (psum'd histograms/means, global-sliced subsampling)."""
    n, d = packed.shape
    dtype = packed_thr.dtype
    gain_fn = _xgb_gain(reg_lambda, gamma, min_child_weight)

    def _gsum(v):
        return jax.lax.psum(v, axis_name) if axis_name else v

    msum = jnp.maximum(_gsum(jnp.sum(mask)), 1.0)
    mean_y = _gsum(jnp.sum(mask * y)) / msum
    if objective == "logistic":
        p0 = jnp.clip(mean_y, 1e-6, 1 - 1e-6)
        base = jnp.log(p0 / (1 - p0))
    else:
        base = mean_y
    margins0 = jnp.broadcast_to(base.astype(dtype), (n,))

    def one_round(carry, rkey):
        margins = carry
        if objective == "logistic":
            p = jax.nn.sigmoid(margins)
            g, h = p - y, jnp.maximum(p * (1 - p), 1e-12)
        else:
            g, h = margins - y, jnp.ones_like(y)
        m = _row_draw(
            lambda k, mm: jax.random.bernoulli(k, subsample,
                                               (mm,)).astype(dtype),
            rkey, n, axis_name, row_total) * mask
        g, h = g * m, h * m
        feat, thr, leaf_stats, node = _grow_tree(
            packed, feat_of, block_start, packed_thr,
            jnp.stack([g, h], axis=1), depth=depth,
            gain_fn=gain_fn, min_info_gain=0.0, hist_mode=hist_mode,
            axis_name=axis_name, row_total=row_total,
            depth_limit=depth_limit)
        vals = -step_size * leaf_stats[:, 0] / (leaf_stats[:, 1] + reg_lambda)
        vals = jnp.where(jnp.sum(jnp.abs(leaf_stats), axis=1) > 0, vals, 0.0)
        margins = margins + vals[node]
        return margins, (feat, thr, vals)
    _, (feats, thrs, leaves) = jax.lax.scan(
        one_round, margins0, jax.random.split(key, num_rounds))
    return feats, thrs, leaves, base


@functools.partial(
    jax.jit, static_argnames=("depth", "num_rounds", "objective",
                              "hist_mode"))
def _fit_gbt(packed, feat_of, block_start, packed_thr, y, key, *, depth: int,
             num_rounds: int, step_size: float, reg_lambda: float,
             gamma: float, min_child_weight: float, subsample: float,
             objective: str, hist_mode: Optional[str]):
    return _gbt_body(packed, feat_of, block_start, packed_thr, y, key,
                     jnp.ones_like(y), step_size, reg_lambda, gamma,
                     min_child_weight, subsample, depth=depth,
                     num_rounds=num_rounds, objective=objective,
                     hist_mode=hist_mode)


def _gbt_softmax_body(packed, feat_of, block_start, packed_thr, y, key,
                      mask, step_size, reg_lambda, gamma,
                      min_child_weight, subsample, *, depth: int,
                      num_rounds: int, num_classes: int,
                      hist_mode: Optional[str],
                      axis_name: Optional[str] = None,
                      row_total: Optional[int] = None,
                      depth_limit=None):
    """K-class softmax boosting: each round fits one tree PER CLASS on
    the softmax gradients/hessians (g_k = p_k - 1[y=k],
    h_k = p_k(1-p_k)) — the ``multi:softprob`` objective the reference
    reaches through xgboost4j (OpXGBoostClassifier.scala:47; MLlib GBT
    itself has no multiclass mode). The K trees of a round see the same
    fixed margins, so they vmap as one batched program (histogram width
    x K, sequential depth unchanged). Base margins are the log class
    priors. Returns (feats (R,K,H), thrs (R,K,H), leaves (R,K,L),
    base (K,))."""
    n, d = packed.shape
    dtype = packed_thr.dtype
    gain_fn = _xgb_gain(reg_lambda, gamma, min_child_weight)

    def _gsum(v):
        return jax.lax.psum(v, axis_name) if axis_name else v

    onehot = jax.nn.one_hot(y.astype(jnp.int32), num_classes, dtype=dtype)
    counts = _gsum(jnp.sum(mask[:, None] * onehot, axis=0))
    priors = jnp.clip(counts / jnp.maximum(jnp.sum(counts), 1.0),
                      1e-6, 1.0)
    base = jnp.log(priors)
    margins0 = jnp.broadcast_to(base, (n, num_classes)).astype(dtype)

    def one_round(margins, rkey):
        p = jax.nn.softmax(margins, axis=1)
        g = p - onehot                                  # (n, K)
        h = jnp.maximum(p * (1.0 - p), 1e-12)
        m = _row_draw(
            lambda k, mm: jax.random.bernoulli(k, subsample,
                                               (mm,)).astype(dtype),
            rkey, n, axis_name, row_total) * mask

        def per_class(gk, hk):
            feat, thr, leaf_stats, node = _grow_tree(
                packed, feat_of, block_start, packed_thr,
                jnp.stack([gk * m, hk * m], axis=1), depth=depth,
                gain_fn=gain_fn, min_info_gain=0.0, hist_mode=hist_mode,
                axis_name=axis_name, row_total=row_total,
                depth_limit=depth_limit)
            vals = (-step_size * leaf_stats[:, 0]
                    / (leaf_stats[:, 1] + reg_lambda))
            vals = jnp.where(
                jnp.sum(jnp.abs(leaf_stats), axis=1) > 0, vals, 0.0)
            return feat, thr, vals, vals[node]

        feats, thrs, vals, delta = jax.vmap(per_class, in_axes=(1, 1)
                                            )(g, h)     # over classes
        return margins + delta.T, (feats, thrs, vals)

    _, (feats, thrs, leaves) = jax.lax.scan(
        one_round, margins0, jax.random.split(key, num_rounds))
    return feats, thrs, leaves, base


@functools.partial(
    jax.jit, static_argnames=("depth", "num_rounds", "num_classes",
                              "hist_mode"))
def _fit_gbt_softmax(packed, feat_of, block_start, packed_thr, y, key, *,
                     depth: int, num_rounds: int, num_classes: int,
                     step_size: float, reg_lambda: float, gamma: float,
                     min_child_weight: float, subsample: float,
                     hist_mode: Optional[str]):
    return _gbt_softmax_body(
        packed, feat_of, block_start, packed_thr, y, key,
        jnp.ones_like(y), step_size, reg_lambda, gamma, min_child_weight,
        subsample, depth=depth, num_rounds=num_rounds,
        num_classes=num_classes, hist_mode=hist_mode)


@functools.partial(jax.jit, static_argnames=("depth",))
def _predict_leaves(X, feats, thrs, depth: int):
    """(T, n) leaf index per tree via vmapped static-depth traversal."""
    return jax.vmap(lambda f, t: _traverse(X, f, t, depth))(feats, thrs)


# ---------------------------------------------------------------------------
# fold x grid batched kernels (validator fast path + "models" mesh axis)
# ---------------------------------------------------------------------------
#
# The reference's per-fold/per-grid Future pool (OpValidator.scala:270)
# maps for tree families onto ONE vmapped program per static shape group
# (depth/trees/rounds/bins): each candidate = (fold mask, traced
# hyperparams). With a ("models", "data") mesh the candidate axis shards
# over chips (data replicated — trees are task-parallel here, like the
# reference's executor model). Documented deviation from the sequential
# path: bin edges come from the WHOLE prepared matrix rather than each
# fold's train rows (feature-distribution information only — standard
# for histogram-GBM cross-validation).

@functools.lru_cache(maxsize=32)
def _forest_fg_kernel(statics: tuple, mesh=None):
    (kind, depth, num_classes, num_trees, max_features, pool_cfg,
     impurity, bootstrap, hist_mode, budget_mb) = statics

    def one(ob, mask, mi, mg, sr, dl, packed, feat_of, block_start,
            packed_thr, binned, col_thr, narrow, wide, y, key):
        return _forest_body(
            packed, feat_of, block_start, packed_thr, binned, col_thr,
            narrow, wide, y, key, mask, mi, mg, sr, kind=kind,
            depth=depth, num_classes=num_classes, num_trees=num_trees,
            max_features=max_features, pool_cfg=pool_cfg,
            impurity=impurity, bootstrap=bootstrap, hist_mode=hist_mode,
            outer_batch=ob, budget_mb=budget_mb, depth_limit=dl)

    def batched(masks, mi, mg, sr, dl, *rest):
        ob = masks.shape[0]     # candidate lanes share the block budget
        return jax.vmap(functools.partial(one, ob),
                        in_axes=(0, 0, 0, 0, 0) + (None,) * 10
                        )(masks, mi, mg, sr, dl, *rest)

    if mesh is None:
        return jax.jit(batched)
    from jax.sharding import PartitionSpec as P
    leaves_spec = (P("models", None, None, None) if kind == "cls"
                   else P("models", None, None))
    return jax.jit(shard_map(
        batched, mesh=mesh,
        in_specs=(P("models", None), P("models"), P("models"),
                  P("models"), P("models")) + (P(),) * 10,
        out_specs=(P("models", None, None), P("models", None, None),
                   leaves_spec), check_vma=False))


@functools.lru_cache(maxsize=32)
def _gbt_fg_kernel(statics: tuple, mesh=None):
    depth, num_rounds, objective, hist_mode = statics

    def one(mask, ss, rl, ga, mcw, sub, dl, packed, feat_of, block_start,
            packed_thr, y, key):
        return _gbt_body(packed, feat_of, block_start, packed_thr, y,
                         key, mask, ss, rl, ga, mcw, sub, depth=depth,
                         num_rounds=num_rounds, objective=objective,
                         hist_mode=hist_mode, depth_limit=dl)

    def batched(masks, ss, rl, ga, mcw, sub, dl, *rest):
        return jax.vmap(one, in_axes=(0,) * 7 + (None,) * 6
                        )(masks, ss, rl, ga, mcw, sub, dl, *rest)

    if mesh is None:
        return jax.jit(batched)
    from jax.sharding import PartitionSpec as P
    return jax.jit(shard_map(
        batched, mesh=mesh,
        in_specs=(P("models", None),) + (P("models"),) * 6 + (P(),) * 6,
        out_specs=(P("models", None, None), P("models", None, None),
                   P("models", None, None), P("models")),
        check_vma=False))


def _candidate_scores(kind, spec_kind, depth, feats, thrs, leaves, base,
                      Xv):
    """Validation scores for ONE fitted tree candidate, on device:
    traversal + leaf gather + tree reduction, then the HOST model's
    exact score transform (evaluators/device_metrics.py host twins:
    vote normalization for forests, sigmoid for GBT classifiers) so the
    device metric ranks candidates identically to the host evaluator."""
    from ..evaluators.device_metrics import (binary_from_sigmoid,
                                             binary_from_votes,
                                             vote_probability)
    leaf = jax.vmap(lambda fh, th: _traverse(Xv, fh, th, depth))(feats, thrs)
    vals = leaves[jnp.arange(leaves.shape[0])[:, None], leaf]
    if kind == "gbt":
        margin = base + jnp.sum(vals, axis=0)
        if spec_kind == "binary":
            return binary_from_sigmoid(margin)
        return margin                       # regression values
    agg = jnp.mean(vals, axis=0)            # (nv, K) votes or (nv,) values
    if spec_kind == "binary":
        return binary_from_votes(agg)
    if spec_kind == "multiclass":
        return vote_probability(agg)
    return agg


@functools.lru_cache(maxsize=32)
def _forest_eval_kernel(statics: tuple, spec: tuple, mesh=None):
    """Fit + validation-metric fusion of _forest_fg_kernel: candidates
    never materialize on host — the program returns one metric scalar
    per candidate (see evaluators/device_metrics.py for why)."""
    (kind, depth, num_classes, num_trees, max_features, pool_cfg,
     impurity, bootstrap, hist_mode, budget_mb) = statics
    from ..evaluators.device_metrics import metric_fn
    mfn = metric_fn(*spec)

    def one(ob, mask, mi, mg, sr, dl, fi, Xv, yv, packed, feat_of,
            block_start, packed_thr, binned, col_thr, narrow, wide, y,
            key):
        feats, thrs, leaves = _forest_body(
            packed, feat_of, block_start, packed_thr, binned, col_thr,
            narrow, wide, y, key, mask, mi, mg, sr, kind=kind,
            depth=depth, num_classes=num_classes, num_trees=num_trees,
            max_features=max_features, pool_cfg=pool_cfg,
            impurity=impurity, bootstrap=bootstrap, hist_mode=hist_mode,
            outer_batch=ob, budget_mb=budget_mb, depth_limit=dl)
        scores = _candidate_scores("forest", spec[0], depth, feats, thrs,
                                   leaves, 0.0, Xv[fi])
        return mfn(yv[fi], scores)

    def batched(masks, mi, mg, sr, dl, fi, Xv, yv, *rest):
        ob = masks.shape[0]
        return jax.vmap(functools.partial(one, ob),
                        in_axes=(0, 0, 0, 0, 0, 0, None, None)
                        + (None,) * 10
                        )(masks, mi, mg, sr, dl, fi, Xv, yv, *rest)

    if mesh is None:
        return jax.jit(batched)
    from jax.sharding import PartitionSpec as P
    return jax.jit(shard_map(
        batched, mesh=mesh,
        in_specs=(P("models", None), P("models"), P("models"),
                  P("models"), P("models"), P("models")) + (P(),) * 12,
        out_specs=P("models"), check_vma=False))


@functools.lru_cache(maxsize=32)
def _gbt_eval_kernel(statics: tuple, spec: tuple, mesh=None):
    """Fit + validation-metric fusion of _gbt_fg_kernel."""
    depth, num_rounds, objective, hist_mode = statics
    from ..evaluators.device_metrics import metric_fn
    mfn = metric_fn(*spec)

    def one(mask, ss, rl, ga, mcw, sub, dl, fi, Xv, yv, packed, feat_of,
            block_start, packed_thr, y, key):
        feats, thrs, leaves, base = _gbt_body(
            packed, feat_of, block_start, packed_thr, y, key, mask, ss,
            rl, ga, mcw, sub, depth=depth, num_rounds=num_rounds,
            objective=objective, hist_mode=hist_mode, depth_limit=dl)
        scores = _candidate_scores("gbt", spec[0], depth, feats, thrs,
                                   leaves, base, Xv[fi])
        return mfn(yv[fi], scores)

    def batched(masks, ss, rl, ga, mcw, sub, dl, fi, Xv, yv, *rest):
        return jax.vmap(one, in_axes=(0,) * 8 + (None, None)
                        + (None,) * 6
                        )(masks, ss, rl, ga, mcw, sub, dl, fi, Xv, yv,
                          *rest)

    if mesh is None:
        return jax.jit(batched)
    from jax.sharding import PartitionSpec as P
    return jax.jit(shard_map(
        batched, mesh=mesh,
        in_specs=(P("models", None),) + (P("models"),) * 7 + (P(),) * 8,
        out_specs=P("models"), check_vma=False))


@functools.lru_cache(maxsize=32)
def _gbt_softmax_fg_kernel(statics: tuple, mesh=None):
    """Fold×grid kernel for K-class softmax boosting (the multiclass
    XGBoost path, _gbt_softmax_body) — mirrors _gbt_fg_kernel's
    candidate contract."""
    depth, num_rounds, num_classes, hist_mode = statics

    def one(mask, ss, rl, ga, mcw, sub, dl, packed, feat_of, block_start,
            packed_thr, y, key):
        return _gbt_softmax_body(
            packed, feat_of, block_start, packed_thr, y, key, mask, ss,
            rl, ga, mcw, sub, depth=depth, num_rounds=num_rounds,
            num_classes=num_classes, hist_mode=hist_mode, depth_limit=dl)

    def batched(masks, ss, rl, ga, mcw, sub, dl, *rest):
        return jax.vmap(one, in_axes=(0,) * 7 + (None,) * 6
                        )(masks, ss, rl, ga, mcw, sub, dl, *rest)

    if mesh is None:
        return jax.jit(batched)
    from jax.sharding import PartitionSpec as P
    return jax.jit(shard_map(
        batched, mesh=mesh,
        in_specs=(P("models", None),) + (P("models"),) * 6 + (P(),) * 6,
        out_specs=(P("models", None, None, None),
                   P("models", None, None, None),
                   P("models", None, None, None), P("models", None)),
        check_vma=False))


def _softmax_margins(feats, thrs, leaves, base, depth: int, Xv):
    """(nv, K) margins of one softmax-boosted candidate on device —
    the exact twin of GBTMulticlassClassifierModel.predict_raw."""
    R, K, H = feats.shape
    flat_f = feats.reshape(R * K, H)
    flat_t = thrs.reshape(R * K, H)
    leaf = jax.vmap(lambda fh, th: _traverse(Xv, fh, th, depth)
                    )(flat_f, flat_t)                     # (R*K, nv)
    flat_l = leaves.reshape(R * K, -1)
    vals = flat_l[jnp.arange(R * K)[:, None], leaf]
    return base + vals.reshape(R, K, -1).sum(axis=0).T    # (nv, K)


@functools.lru_cache(maxsize=32)
def _gbt_softmax_eval_kernel(statics: tuple, spec: tuple, mesh=None):
    """Fit + validation-metric fusion of _gbt_softmax_fg_kernel: the
    multiclass metric consumes softmax probabilities, matching the host
    ClassifierModel.raw_to_probability ranking exactly."""
    depth, num_rounds, num_classes, hist_mode = statics
    from ..evaluators.device_metrics import metric_fn
    mfn = metric_fn(*spec)

    def one(mask, ss, rl, ga, mcw, sub, dl, fi, Xv, yv, packed, feat_of,
            block_start, packed_thr, y, key):
        feats, thrs, leaves, base = _gbt_softmax_body(
            packed, feat_of, block_start, packed_thr, y, key, mask, ss,
            rl, ga, mcw, sub, depth=depth, num_rounds=num_rounds,
            num_classes=num_classes, hist_mode=hist_mode, depth_limit=dl)
        margins = _softmax_margins(feats, thrs, leaves, base, depth,
                                   Xv[fi])
        return mfn(yv[fi], jax.nn.softmax(margins, axis=1))

    def batched(masks, ss, rl, ga, mcw, sub, dl, fi, Xv, yv, *rest):
        return jax.vmap(one, in_axes=(0,) * 8 + (None, None)
                        + (None,) * 6
                        )(masks, ss, rl, ga, mcw, sub, dl, fi, Xv, yv,
                          *rest)

    if mesh is None:
        return jax.jit(batched)
    from jax.sharding import PartitionSpec as P
    return jax.jit(shard_map(
        batched, mesh=mesh,
        in_specs=(P("models", None),) + (P("models"),) * 7 + (P(),) * 8,
        out_specs=P("models"), check_vma=False))


def _gbt_softmax_fold_grid(est, X, y, masks, grid, mesh, num_classes_k,
                           eval_ctx=None, edge_rows=None):
    # mirrors _gbt_fold_grid's candidate contract for the K-class
    # softmax objective — change all three drivers together
    masks = np.asarray(masks, dtype=np.float64)
    if edge_rows is None and _fold_edges_mode():
        return _fold_edge_recurse(
            _gbt_softmax_fold_grid, est, X, y, masks, grid, mesh,
            eval_ctx, num_classes_k=num_classes_k)
    grid = [dict(p) for p in (list(grid) or [{}])]
    allowed = set(_GBT_TRACED) | set(_GBT_STATIC)
    for p in grid:
        extra = set(p) - allowed
        if extra:
            raise NotImplementedError(
                f"batched softmax-GBT kernel cannot vary {sorted(extra)}")
    F, n = masks.shape
    G = len(grid)
    d = X.shape[1]
    y_j = jnp.asarray(y)
    models = [[None] * G for _ in range(F)]
    metric_mat = np.full((F, G), np.nan)
    if eval_ctx is not None:
        Xv_j = jnp.asarray(np.asarray(eval_ctx[0], dtype=np.float64))
        yv_j = jnp.asarray(np.asarray(eval_ctx[1], dtype=np.float64))
        spec = eval_ctx[2]
    for members, cand0, depth_cap, vecs, masks_p, fidx, count, gk in \
            _candidate_groups(est, grid, masks, mesh, _GBT_TILED,
                              _GBT_SKEY):
        design, _ = _design_args(X, cand0.max_bins, edge_rows=edge_rows)
        statics = (depth_cap, cand0.num_rounds, num_classes_k,
                   _hist_mode(n, int(design[1].shape[0])))
        _note_compile("gbt_softmax", statics, masks_p.shape)
        vecs_j = [jnp.asarray(v) for v in vecs]
        if eval_ctx is not None:
            fn = _gbt_softmax_eval_kernel(statics, spec, mesh)
            mm = to_host(fn(
                jnp.asarray(masks_p), *vecs_j, jnp.asarray(fidx),
                Xv_j, yv_j, *design[:4], y_j,
                jax.random.PRNGKey(cand0.seed)))[:count]
            _scatter_group_metrics(metric_mat, mm, members, F, gk)
            continue
        fn = _gbt_softmax_fg_kernel(statics, mesh)
        feats, thrs, leaves, base = fn(
            jnp.asarray(masks_p), *vecs_j, *design[:4], y_j,
            jax.random.PRNGKey(cand0.seed))
        feats = to_host(feats)[:count]
        thrs = to_host(thrs)[:count]
        leaves = to_host(leaves)[:count]
        base = to_host(base)[:count]
        for f in range(F):
            for j, (gi, cand) in enumerate(members):
                c = f * gk + j
                fe, th, le = _trim_tree_arrays(
                    feats[c], thrs[c], leaves[c], depth_cap,
                    cand.max_depth, leaf_axis=2)
                models[f][gi] = GBTMulticlassClassifierModel(
                    fe, th, le, depth=cand.max_depth, base=base[c],
                    n_features=d)
    return metric_mat if eval_ctx is not None else models


# ---------------------------------------------------------------------------
# row-sharded (data-parallel) single fits — the Rabit-allreduce role
# ---------------------------------------------------------------------------
#
# The fold x grid kernels above shard CANDIDATES (task parallelism); the
# kernels here shard ROWS of one fit over a mesh axis: each chip holds a
# contiguous block of the binned design and psums per-level histograms
# over ICI (see _grow_tree axis_name). This is the promised data-parallel
# path of the module docstring — how one model's training scales past a
# single chip's HBM/FLOPs, the role Rabit allreduce plays for the
# reference's XGBoost (core/build.gradle:27, SURVEY §2.9).

@functools.lru_cache(maxsize=32)
def _forest_sharded_kernel(statics: tuple, mesh, axis: str):
    (kind, depth, num_classes, num_trees, max_features, pool_cfg,
     impurity, bootstrap, hist_mode, row_total, budget_mb) = statics
    from jax.sharding import PartitionSpec as P

    def body(packed, binned, y, mask, feat_of, block_start, packed_thr,
             col_thr, narrow, wide, key, mi, mg, sr):
        return _forest_body(
            packed, feat_of, block_start, packed_thr, binned, col_thr,
            narrow, wide, y, key, mask, mi, mg, sr, kind=kind,
            depth=depth, num_classes=num_classes, num_trees=num_trees,
            max_features=max_features, pool_cfg=pool_cfg,
            impurity=impurity, bootstrap=bootstrap, hist_mode=hist_mode,
            axis_name=axis, row_total=row_total, budget_mb=budget_mb)

    # outputs replicate: every shard reaches identical split decisions
    # from the psum'd reductions
    return jax.jit(shard_map(
        body, mesh=mesh,
        in_specs=(P(axis, None), P(axis, None), P(axis), P(axis))
        + (P(),) * 10,
        out_specs=(P(), P(), P()), check_vma=False))


@functools.lru_cache(maxsize=32)
def _gbt_sharded_kernel(statics: tuple, mesh, axis: str):
    depth, num_rounds, objective, hist_mode, row_total = statics
    from jax.sharding import PartitionSpec as P

    def body(packed, y, mask, feat_of, block_start, packed_thr, key,
             ss, rl, ga, mcw, sub):
        return _gbt_body(packed, feat_of, block_start, packed_thr, y,
                         key, mask, ss, rl, ga, mcw, sub, depth=depth,
                         num_rounds=num_rounds, objective=objective,
                         hist_mode=hist_mode, axis_name=axis,
                         row_total=row_total)

    return jax.jit(shard_map(
        body, mesh=mesh,
        in_specs=(P(axis, None), P(axis), P(axis)) + (P(),) * 9,
        out_specs=(P(), P(), P(), P()), check_vma=False))


def _gbt_fit_sharded(est, X, y, mesh, axis: str, objective: str):
    """Shared driver for the row-sharded GBT fits (see
    _forest_sharded_kernel notes on replication and padding)."""
    shards = mesh.shape[axis]
    design, _ = _design_args(X, est.max_bins)
    packed, feat_of, block_start, packed_thr = design[:4]
    (packed_p, y_p), mask = _pad_rows(
        [np.asarray(packed), np.asarray(y)], shards)
    row_total = len(mask)
    statics = (est.max_depth, est.num_rounds, objective,
               _hist_mode(row_total, int(feat_of.shape[0])), row_total)
    fn = _gbt_sharded_kernel(statics, mesh, axis)
    feats, thrs, leaves, base = fn(
        jnp.asarray(packed_p), jnp.asarray(y_p), jnp.asarray(mask),
        feat_of, block_start, packed_thr,
        jax.random.PRNGKey(est.seed),
        jnp.asarray(float(est.step_size)),
        jnp.asarray(float(est.reg_lambda)),
        jnp.asarray(float(est.gamma)),
        jnp.asarray(float(est.min_child_weight)),
        jnp.asarray(float(est.subsample)))
    model_cls = (GBTClassifierModel if objective == "logistic"
                 else GBTRegressorModel)
    return model_cls(to_host(feats), to_host(thrs), to_host(leaves),
                     depth=est.max_depth, base=float(to_host(base)),
                     n_features=X.shape[1])


def _pad_rows(arrays, shards: int):
    """Pad each array's leading (row) axis to a multiple of ``shards``
    by repeating row 0 (padded rows carry mask 0, so they contribute
    nothing — repeating a real row keeps every bin index in range).
    Returns (padded arrays, mask (n_padded,))."""
    n = arrays[0].shape[0]
    pad = (-n) % shards
    mask = np.concatenate([np.ones(n), np.zeros(pad)])
    if not pad:
        return list(arrays), mask
    # padding changes the global bootstrap-draw vector length, so a
    # sharded fit is no longer bit-identical to the local fit (both
    # remain valid draws) — surface it instead of silently diverging
    _log.debug("_pad_rows: %d rows padded to %d for %d shards; sharded "
               "bootstrap draws will differ from an unpadded local fit",
               n, n + pad, shards)
    out = []
    for a in arrays:
        a = np.asarray(a)
        fill = np.repeat(a[:1], pad, axis=0)
        out.append(np.concatenate([a, fill], axis=0))
    return out, mask


@functools.partial(jax.jit, static_argnames=("depth", "kind"))
def _batched_tree_raw(X, feats, thrs, leaves, bases, *, depth: int,
                      kind: str):
    """(C, ...) raw outputs for C same-shape fitted tree models against
    one matrix: vmapped static-depth traversal + leaf gather + tree
    reduction, ONE program instead of C dispatch/sync round trips (the
    per-candidate path costs a full host<->device round trip per model,
    which dominates small-data selector searches on a remote TPU)."""
    def per_candidate(f, t, l, b):
        leaf = jax.vmap(lambda fh, th: _traverse(X, fh, th, depth))(f, t)
        vals = l[jnp.arange(l.shape[0])[:, None], leaf]   # (T, n[, K])
        if kind == "forest":
            return jnp.mean(vals, axis=0)                 # probs or values
        return b + jnp.sum(vals, axis=0)                  # GBT margin
    return jax.vmap(per_candidate)(feats, thrs, leaves, bases)


def batch_predict_raw(models, X) -> dict:
    """Batched validator evaluation: raw predictions for every tree-
    family model in ``models`` (list entries of other families are
    skipped), grouped by static shape so each group is one XLA call.

    Returns {index in models: raw ndarray} matching each model's own
    ``predict_raw``/``predict_values`` contract, to be fed through its
    ``prediction_from_raw``.
    """
    groups: Dict[tuple, list] = {}
    for i, m in enumerate(models):
        if isinstance(m, (TreeEnsembleClassifierModel,
                          TreeEnsembleRegressorModel)):
            key = ("forest", m.depth, m.feats.shape, m.leaves.shape)
        elif isinstance(m, (GBTClassifierModel, GBTRegressorModel)):
            key = ("gbt", m.depth, m.feats.shape, m.leaves.shape)
        else:
            continue
        groups.setdefault(key, []).append(i)
    out: dict = {}
    if not groups:          # no tree-family models: no device transfer
        return out
    X_j = jnp.asarray(np.asarray(X, dtype=np.float64))
    for (kind, depth, _, _), idxs in groups.items():
        feats = jnp.asarray(np.stack([models[i].feats for i in idxs]))
        thrs = jnp.asarray(np.stack([models[i].thrs for i in idxs]))
        leaves = jnp.asarray(np.stack([models[i].leaves for i in idxs]))
        bases = jnp.asarray(np.array(
            [getattr(models[i], "base", 0.0) for i in idxs]))
        res = np.asarray(_batched_tree_raw(
            X_j, feats, thrs, leaves, bases, depth=depth, kind=kind))
        for j, i in enumerate(idxs):
            r = res[j]
            if isinstance(models[i], GBTClassifierModel):
                r = models[i].raw_from_margin(r)
            out[i] = r
    return out


def _pad_candidates(mesh, arrays, n_rows):
    """Pad the flattened candidate axis to a multiple of the mesh's
    ``models`` shard count (padded slots fit on all-ones masks and are
    discarded). Returns (padded arrays, original count)."""
    count = arrays[0].shape[0]
    if mesh is None:
        return arrays, count
    shards = mesh.shape["models"]
    pad = (-count) % shards
    if not pad:
        return arrays, count
    out = []
    for a in arrays:
        fill = np.ones((pad, n_rows)) if a.ndim == 2 else np.ones(pad)
        out.append(np.concatenate([a, fill.astype(a.dtype)], axis=0))
    return out, count


# ---------------------------------------------------------------------------
# fitted models
# ---------------------------------------------------------------------------

class TreeEnsembleClassifierModel(ClassifierModel):
    """RF/DT classifier model: averages per-tree leaf class distributions
    (reference RandomForestClassificationModel normalized vote averaging)."""

    def __init__(self, feats, thrs, leaves, depth: int,
                 n_features: int = 0, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.feats = np.asarray(feats, dtype=np.int32)
        self.thrs = np.asarray(thrs, dtype=np.float64)
        self.leaves = np.asarray(leaves, dtype=np.float64)  # (T, L, K)
        self.depth = int(depth)
        self.n_features = int(n_features)

    def predict_raw(self, X: np.ndarray) -> np.ndarray:
        leaf_idx = np.asarray(_predict_leaves(
            jnp.asarray(X), jnp.asarray(self.feats),
            jnp.asarray(self.thrs), self.depth))              # (T, n)
        probs = self.leaves[np.arange(len(self.feats))[:, None], leaf_idx]
        return np.mean(probs, axis=0)                          # (n, K)

    def raw_arrays(self, X):
        leaf_idx = _predict_leaves(X, jnp.asarray(self.feats),
                                   jnp.asarray(self.thrs, X.dtype),
                                   self.depth)
        probs = jnp.asarray(self.leaves, X.dtype)[
            jnp.arange(len(self.feats))[:, None], leaf_idx]
        return jnp.mean(probs, axis=0)

    def raw_to_probability(self, raw: np.ndarray) -> np.ndarray:
        s = np.sum(raw, axis=1, keepdims=True)
        return raw / np.where(s > 0, s, 1.0)

    @property
    def feature_importances(self) -> np.ndarray:
        return _split_count_importances(self.feats, self.thrs, self.n_features)


class TreeEnsembleRegressorModel(RegressionModel):
    def __init__(self, feats, thrs, leaves, depth: int,
                 n_features: int = 0, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.feats = np.asarray(feats, dtype=np.int32)
        self.thrs = np.asarray(thrs, dtype=np.float64)
        self.leaves = np.asarray(leaves, dtype=np.float64)  # (T, L)
        self.depth = int(depth)
        self.n_features = int(n_features)

    def predict_values(self, X: np.ndarray) -> np.ndarray:
        leaf_idx = np.asarray(_predict_leaves(
            jnp.asarray(X), jnp.asarray(self.feats),
            jnp.asarray(self.thrs), self.depth))
        vals = self.leaves[np.arange(len(self.feats))[:, None], leaf_idx]
        return np.mean(vals, axis=0)

    def raw_arrays(self, X):
        leaf_idx = _predict_leaves(X, jnp.asarray(self.feats),
                                   jnp.asarray(self.thrs, X.dtype),
                                   self.depth)
        vals = jnp.asarray(self.leaves, X.dtype)[
            jnp.arange(len(self.feats))[:, None], leaf_idx]
        return jnp.mean(vals, axis=0)

    @property
    def feature_importances(self) -> np.ndarray:
        return _split_count_importances(self.feats, self.thrs, self.n_features)


class GBTClassifierModel(ClassifierModel):
    """Boosted binary classifier: sigmoid over summed leaf margins."""

    def __init__(self, feats, thrs, leaves, depth: int, base: float = 0.0,
                 n_features: int = 0, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.feats = np.asarray(feats, dtype=np.int32)
        self.thrs = np.asarray(thrs, dtype=np.float64)
        self.leaves = np.asarray(leaves, dtype=np.float64)
        self.depth = int(depth)
        self.base = float(base)
        self.n_features = int(n_features)

    def margins(self, X: np.ndarray) -> np.ndarray:
        leaf_idx = np.asarray(_predict_leaves(
            jnp.asarray(X), jnp.asarray(self.feats),
            jnp.asarray(self.thrs), self.depth))
        vals = self.leaves[np.arange(len(self.feats))[:, None], leaf_idx]
        return self.base + np.sum(vals, axis=0)

    def raw_from_margin(self, m: np.ndarray) -> np.ndarray:
        """Margin vector -> raw-prediction pair; the single place that
        defines this model's raw layout (batch_predict_raw reuses it so
        the batched path cannot diverge from predict_raw)."""
        return np.stack([-m, m], axis=1)

    def predict_raw(self, X: np.ndarray) -> np.ndarray:
        return self.raw_from_margin(self.margins(X))

    def raw_arrays(self, X):
        leaf_idx = _predict_leaves(X, jnp.asarray(self.feats),
                                   jnp.asarray(self.thrs, X.dtype),
                                   self.depth)
        vals = jnp.asarray(self.leaves, X.dtype)[
            jnp.arange(len(self.feats))[:, None], leaf_idx]
        m = self.base + jnp.sum(vals, axis=0)
        return jnp.stack([-m, m], axis=1)

    def raw_to_probability(self, raw: np.ndarray) -> np.ndarray:
        p = 1.0 / (1.0 + np.exp(-raw[:, 1]))
        return np.stack([1 - p, p], axis=1)

    @property
    def feature_importances(self) -> np.ndarray:
        return _split_count_importances(self.feats, self.thrs, self.n_features)


class GBTMulticlassClassifierModel(ClassifierModel):
    """K-class softmax booster model (see _gbt_softmax_body): raw
    predictions are the per-class margins; the default max-shifted
    softmax of ClassifierModel turns them into ``multi:softprob``
    probabilities (parity with xgboost4j's multiclass output,
    OpXGBoostClassifier.scala:47)."""

    def __init__(self, feats, thrs, leaves, depth: int, base,
                 n_features: int = 0, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.feats = np.asarray(feats, dtype=np.int32)     # (R, K, H)
        self.thrs = np.asarray(thrs, dtype=np.float64)
        self.leaves = np.asarray(leaves, dtype=np.float64)  # (R, K, L)
        self.depth = int(depth)
        self.base = np.asarray(base, dtype=np.float64)      # (K,)
        self.n_features = int(n_features)

    def predict_raw(self, X: np.ndarray) -> np.ndarray:
        rounds, k, heap = self.feats.shape
        flat_f = self.feats.reshape(rounds * k, heap)
        flat_t = self.thrs.reshape(rounds * k, heap)
        leaf_idx = np.asarray(_predict_leaves(
            jnp.asarray(X), jnp.asarray(flat_f), jnp.asarray(flat_t),
            self.depth))                                   # (R*K, n)
        flat_l = self.leaves.reshape(rounds * k, -1)
        vals = flat_l[np.arange(rounds * k)[:, None], leaf_idx]
        margins = vals.reshape(rounds, k, -1).sum(axis=0).T  # (n, K)
        return self.base + margins

    def raw_arrays(self, X):
        rounds, k, heap = self.feats.shape
        leaf_idx = _predict_leaves(
            X, jnp.asarray(self.feats.reshape(rounds * k, heap)),
            jnp.asarray(self.thrs.reshape(rounds * k, heap), X.dtype),
            self.depth)                                      # (R*K, n)
        flat_l = jnp.asarray(self.leaves.reshape(rounds * k, -1), X.dtype)
        vals = flat_l[jnp.arange(rounds * k)[:, None], leaf_idx]
        margins = vals.reshape(rounds, k, -1).sum(axis=0).T  # (n, K)
        return jnp.asarray(self.base, X.dtype) + margins

    @property
    def feature_importances(self) -> np.ndarray:
        rounds, k, heap = self.feats.shape
        return _split_count_importances(
            self.feats.reshape(rounds * k, heap),
            self.thrs.reshape(rounds * k, heap), self.n_features)


class GBTRegressorModel(RegressionModel):
    def __init__(self, feats, thrs, leaves, depth: int, base: float = 0.0,
                 n_features: int = 0, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.feats = np.asarray(feats, dtype=np.int32)
        self.thrs = np.asarray(thrs, dtype=np.float64)
        self.leaves = np.asarray(leaves, dtype=np.float64)
        self.depth = int(depth)
        self.base = float(base)
        self.n_features = int(n_features)

    def predict_values(self, X: np.ndarray) -> np.ndarray:
        leaf_idx = np.asarray(_predict_leaves(
            jnp.asarray(X), jnp.asarray(self.feats),
            jnp.asarray(self.thrs), self.depth))
        vals = self.leaves[np.arange(len(self.feats))[:, None], leaf_idx]
        return self.base + np.sum(vals, axis=0)

    def raw_arrays(self, X):
        leaf_idx = _predict_leaves(X, jnp.asarray(self.feats),
                                   jnp.asarray(self.thrs, X.dtype),
                                   self.depth)
        vals = jnp.asarray(self.leaves, X.dtype)[
            jnp.arange(len(self.feats))[:, None], leaf_idx]
        return self.base + jnp.sum(vals, axis=0)

    @property
    def feature_importances(self) -> np.ndarray:
        return _split_count_importances(self.feats, self.thrs, self.n_features)


def _split_count_importances(feats: np.ndarray, thrs: np.ndarray,
                             n_features: int) -> np.ndarray:
    """Normalized real-split counts per feature, aligned with the training
    feature columns (a threshold of +inf marks a dead/no-split node)."""
    real = np.isfinite(thrs)
    if feats.size == 0 or not real.any():
        return np.zeros(n_features)
    counts = np.bincount(feats[real].ravel(),
                         minlength=n_features).astype(np.float64)
    total = counts.sum()
    return counts / total if total > 0 else counts


# ---------------------------------------------------------------------------
# estimators
# ---------------------------------------------------------------------------

def _resolve_max_features(strategy: str, d: int, classification: bool
                          ) -> Optional[int]:
    """MLlib featureSubsetStrategy (RandomForestParams)."""
    s = str(strategy).lower()
    if s == "auto":
        s = "sqrt" if classification else "onethird"
    if s == "all":
        return None
    if s == "sqrt":
        return max(1, int(np.sqrt(d)))
    if s == "log2":
        return max(1, int(np.log2(d)))
    if s == "onethird":
        return max(1, d // 3)
    return max(1, min(d, int(float(s) * d) if "." in s else int(s)))


#: binning memo: the validator holds each fold's matrix with stable
#: identity across the whole grid, so one O(d) host binning pass serves
#: every grid point of every tree family on that fold. Strong refs to
#: the keyed arrays keep their id()s valid while cached.
_DESIGN_CACHE: "OrderedDict[tuple, tuple]" = OrderedDict()
_DESIGN_CACHE_SIZE = 8
#: the validator dispatches tree families from separate threads
#: (TX_ASYNC_FAMILIES); one lock makes the memo race-free AND keeps a
#: shared matrix binned once instead of once per family
_DESIGN_LOCK = threading.Lock()


def clear_design_cache() -> None:
    """Drop every memoized binned design (and the device buffers each
    pins). Benchmarks re-measuring binning on fresh uploads of the same
    matrix call this between passes so stale passes' working sets don't
    accumulate in HBM."""
    with _DESIGN_LOCK:
        _DESIGN_CACHE.clear()


def _design_args(X: np.ndarray, max_bins: int,
                 edge_rows: Optional[np.ndarray] = None):
    """Host-bin X and return ((packed, feat_of, block_start, packed_thr,
    binned, col_thr) device arrays, widths host array). ``edge_rows``
    restricts quantile-edge estimation (TX_TREE_EDGES=fold)."""
    # the binning-mode env var joins the key: a TX_TREE_BINNING toggle
    # between fits on the same matrix must not serve the other mode's
    # cached design (the auto decision is pure in X/backend, so the
    # env value is the only extra degree of freedom)
    key = (id(X), getattr(X, "shape", None), max_bins,
           None if edge_rows is None else id(edge_rows),
           _binning_mode())
    with _DESIGN_LOCK:
        hit = _DESIGN_CACHE.get(key)
        if hit is not None and hit[0] is X and hit[1] is edge_rows:
            _DESIGN_CACHE.move_to_end(key)
            return hit[2]
        design = _PackedDesign(X, max_bins, edge_rows=edge_rows)
        args = ((jnp.asarray(design.packed), jnp.asarray(design.feat_of),
                 jnp.asarray(design.block_start),
                 jnp.asarray(design.packed_thr),
                 jnp.asarray(design.binned), jnp.asarray(design.col_thr)),
                design.widths)
        _DESIGN_CACHE[key] = (X, edge_rows, args)
        while len(_DESIGN_CACHE) > _DESIGN_CACHE_SIZE:
            _DESIGN_CACHE.popitem(last=False)
        return args


def _fold_edges_mode() -> bool:
    """Whether fold×grid searches compute bin edges from each fold's
    train rows only (TX_TREE_EDGES=fold) instead of the whole prepared
    matrix (default; standard histogram-GBM CV practice — the edges
    carry feature-distribution information only, audited at scale in
    BASELINE.md)."""
    return os.environ.get("TX_TREE_EDGES", "matrix") == "fold"


def _depth_mode() -> str:
    """How the fold×grid search handles the max_depth sweep:

    - "static" (default): one program per distinct depth — lanes do
      exactly their own work.
    - "mask": ONE compiled program per tree family at the grid's
      deepest depth; each candidate's depth is a traced per-lane limit
      (_grow_tree depth_limit). Cuts tree-family compile count 3x on
      the default grids (flagship: 6 -> 2 programs) at the price of
      shallow lanes running the deep lane's masked levels.

    Measured (BASELINE.md r5): identical metrics on both backends, but
    the winner flips with the platform. Single-core CPU flagship: 97 s
    static vs 380 s mask warm — the masked-level compute inflation
    swamps the saved compiles. REAL TPU v5e flagship: 38.3 s static vs
    **18.2 s mask warm (7.9 vs 3.8 models×folds/s)** — the TPU search
    is dispatch-bound (device busy <10% under static), so folding the
    whole depth sweep into one fat program per family wins 2.1× on top
    of cutting compiles 3× (6 -> 2). Hence the auto default: mask on
    accelerators, static on CPU (same split _hist_mode uses).
    TX_TREE_DEPTH overrides."""
    mode = os.environ.get("TX_TREE_DEPTH")
    if mode in ("mask", "static"):
        return mode
    try:
        platform = jax.default_backend()
    except Exception:  # pragma: no cover - defensive
        platform = "cpu"
    return "static" if platform == "cpu" else "mask"


#: (kernel kind, statics, call shape) triples seen — each is one XLA
#: compile (the jit caches on shapes too, so factory-cache hits with new
#: lane counts still compile)
_COMPILE_KEYS: set = set()


def _note_compile(kind: str, statics: tuple, shape: tuple) -> None:
    _COMPILE_KEYS.add((kind, statics, shape))


def tree_kernel_compiles() -> int:
    """Distinct compiled fold×grid tree programs so far in this process
    (the compile-count diagnostic bench.py reports)."""
    return len(_COMPILE_KEYS)


def _pool_size(d: int, mf: Optional[int]) -> Optional[int]:
    """Per-tree feature-pool size: 4x the per-node sample (floored at 8)
    keeps per-node choice diversity while bounding histogram work."""
    if mf is None or mf >= d:
        return None
    return min(d, max(4 * mf, 8))


def _pool_plan(widths: np.ndarray, mf: Optional[int]):
    """((narrow_idx, wide_idx) device arrays, pool_cfg static tuple,
    effective max_features) — or (dummies, None, mf) when no pooling."""
    d = len(widths)
    pool = _pool_size(d, mf)
    empty = jnp.zeros((0,), jnp.int32)
    if pool is None or pool >= d:
        # pool covers everything: the shared pre-packed design is both
        # exact and free of per-tree gather/pad work
        return (empty, empty), None, mf
    (narrow, wide), cfg, mf_eff = _pool_classes(widths, pool, mf)
    return ((jnp.asarray(narrow), jnp.asarray(wide)), cfg, mf_eff)


#: grid params the batched forest kernel traces per candidate vs the
#: statics that partition the grid into shape groups
_FOREST_TRACED = ("min_instances_per_node", "min_info_gain",
                  "subsampling_rate")
_FOREST_STATIC = ("max_depth", "num_trees", "max_bins", "impurity",
                  "feature_subset_strategy", "seed")
_GBT_TRACED = ("step_size", "reg_lambda", "gamma", "min_child_weight",
               "subsample", "eta")
_GBT_STATIC = ("max_depth", "num_rounds", "max_bins", "seed", "num_round")
#: the kernel-facing subsets ("eta"/"num_round" are facade aliases of
#: step_size/num_rounds — valid in grids, not separate lanes/keys)
_GBT_TILED = ("step_size", "reg_lambda", "gamma", "min_child_weight",
              "subsample")
_GBT_SKEY = ("max_depth", "num_rounds", "max_bins", "seed")


def _trim_tree_arrays(feats, thrs, leaves, depth_cap: int, depth: int,
                      leaf_axis: int = 1):
    """Slice a depth_cap-shaped (heap, leaves) candidate back to its own
    ``depth`` (TX_TREE_DEPTH=mask materialization): levels >= depth hold
    only (0, +inf) denied splits, and a truncated node ``l``'s rows all
    sit in its leftmost descendant leaf ``l << (cap - depth)`` — so the
    heap prefix plus a strided leaf gather reproduce the static-depth
    model bit-exactly (up to 512x less host memory for a depth-3 lane
    in a depth-12 group).

    Heaps put H last everywhere ((T, H) forests, (R, K, H) softmax);
    the LEAF axis varies — (T, L[, K]) forests/GBT vs (R, K, L) softmax
    — hence ``leaf_axis``."""
    if depth == depth_cap:
        return feats, thrs, leaves
    h = 2 ** depth - 1
    sl = [slice(None)] * leaves.ndim
    sl[leaf_axis] = slice(None, None, 2 ** (depth_cap - depth))
    return feats[..., :h], thrs[..., :h], leaves[tuple(sl)]


def _candidate_groups(est, grid, masks, mesh, traced_fields, skey_fields):
    """The shared fold-major candidate-batching contract of the three
    fold×grid drivers (forest / binary-GBT / softmax-GBT): partition
    grid points into static shape groups, flatten (fold, candidate)
    lanes fold-major, tile the traced hyperparameter vectors (plus the
    trailing depth-limit lane for TX_TREE_DEPTH=mask), and pad to the
    mesh shard count.

    Yields (members, cand0, depth_cap, traced_vecs, masks_p, fidx,
    count, gk) per group; ``traced_vecs`` follows ``traced_fields``
    order with the depth-limit vector appended."""
    mask_depth = _depth_mode() == "mask"
    F, n = masks.shape
    groups: Dict[tuple, list] = {}
    for gi, p in enumerate(grid):
        cand = est.with_params(**p)
        key = tuple(None if f == "max_depth" and mask_depth
                    else getattr(cand, f, "") for f in skey_fields)
        groups.setdefault(key, []).append((gi, cand))
    for members in groups.values():
        cand0 = members[0][1]
        depth_cap = max(c.max_depth for _, c in members)
        gk = len(members)
        vecs = [np.tile([float(getattr(c, f)) for _, c in members], F)
                for f in traced_fields]
        vecs.append(np.tile([float(c.max_depth) for _, c in members], F))
        masks_c = np.repeat(masks, gk, axis=0)
        fidx = np.repeat(np.arange(F, dtype=np.int32), gk)
        (masks_p, *vecs), count = _pad_candidates(
            mesh, [masks_c, *vecs], n)
        fidx = np.concatenate(
            [fidx, np.zeros(len(masks_p) - count, dtype=np.int32)])
        yield members, cand0, depth_cap, vecs, masks_p, fidx, count, gk


def _scatter_group_metrics(metric_mat, mm, members, F: int, gk: int):
    """Write one group's (padded, fold-major) metric vector back into
    the (F, G) matrix."""
    for f in range(F):
        for j, (gi, _) in enumerate(members):
            metric_mat[f, gi] = mm[f * gk + j]


def _fold_edge_recurse(fold_grid_fn, est, X, y, masks, grid, mesh,
                       eval_ctx, **kw):
    """TX_TREE_EDGES=fold driver: one recursive single-fold call per
    fold, each binning with edges from THAT fold's train rows only.
    Returns the same (F, G) matrix / per-fold model lists the fold-major
    call would. Costs one extra compile per static group (single-fold
    candidate shape) but removes the only place validation rows could
    influence training (quantile edges)."""
    F = masks.shape[0]
    outs = []
    for f in range(F):
        rows = np.nonzero(masks[f] > 0)[0]
        sub_eval = None
        if eval_ctx is not None:
            sub_eval = (eval_ctx[0][f:f + 1], eval_ctx[1][f:f + 1],
                        eval_ctx[2])
        outs.append(fold_grid_fn(est, X, y, masks[f:f + 1], grid, mesh,
                                 eval_ctx=sub_eval, edge_rows=rows, **kw))
    if eval_ctx is not None:
        return np.concatenate(outs, axis=0)
    return [o[0] for o in outs]


def _forest_fold_grid(est, X, y, masks, grid, mesh, classification: bool,
                      eval_ctx=None, edge_rows=None):
    """All (fold, grid point) forest candidates in vmapped programs (one
    per static shape group), optionally sharded over a mesh ``models``
    axis — see the kernel docstrings for the bin-edge deviation.

    With ``eval_ctx = (X_val (F,nv,d), y_val (F,nv), spec)`` the fused
    fit+metric kernels run instead and the return value is the (F, G)
    validation-metric matrix — fitted trees never reach the host."""
    masks = np.asarray(masks, dtype=np.float64)
    if edge_rows is None and _fold_edges_mode():
        return _fold_edge_recurse(
            _forest_fold_grid, est, X, y, masks, grid, mesh, eval_ctx,
            classification=classification)
    grid = [dict(p) for p in (list(grid) or [{}])]
    allowed = set(_FOREST_TRACED) | set(_FOREST_STATIC)
    for p in grid:
        extra = set(p) - allowed
        if extra:
            raise NotImplementedError(
                f"batched tree kernel cannot vary {sorted(extra)}")
    F, n = masks.shape
    G = len(grid)
    d = X.shape[1]
    k = num_classes(y)
    y_j = jnp.asarray(y)
    models = [[None] * G for _ in range(F)]
    metric_mat = np.full((F, G), np.nan)
    if eval_ctx is not None:
        Xv_j = jnp.asarray(np.asarray(eval_ctx[0], dtype=np.float64))
        yv_j = jnp.asarray(np.asarray(eval_ctx[1], dtype=np.float64))
        spec = eval_ctx[2]
    for members, cand0, depth_cap, vecs, masks_p, fidx, count, gk in \
            _candidate_groups(est, grid, masks, mesh, _FOREST_TRACED,
                              _FOREST_STATIC):
        design, widths = _design_args(X, cand0.max_bins,
                                      edge_rows=edge_rows)
        mf = _resolve_max_features(cand0.feature_subset_strategy, d,
                                   classification) \
            if cand0.bootstrap else None
        (narrow, wide), pool_cfg, mf = _pool_plan(widths, mf)
        statics = ("cls" if classification else "reg", depth_cap,
                   k if classification else 0, cand0.num_trees, mf,
                   pool_cfg, getattr(cand0, "impurity", ""),
                   cand0.bootstrap,
                   _hist_mode(n, int(design[1].shape[0])),
                   _tree_budget_mb())
        _note_compile("forest", statics, masks_p.shape)
        vecs_j = [jnp.asarray(v) for v in vecs]
        if eval_ctx is not None:
            fn = _forest_eval_kernel(statics, spec, mesh)
            mm = to_host(fn(
                jnp.asarray(masks_p), *vecs_j, jnp.asarray(fidx),
                Xv_j, yv_j, *design, narrow, wide, y_j,
                jax.random.PRNGKey(cand0.seed)))[:count]
            _scatter_group_metrics(metric_mat, mm, members, F, gk)
            continue
        fn = _forest_fg_kernel(statics, mesh)
        feats, thrs, leaves = fn(
            jnp.asarray(masks_p), *vecs_j, *design, narrow, wide,
            y_j, jax.random.PRNGKey(cand0.seed))
        feats = to_host(feats)[:count]
        thrs = to_host(thrs)[:count]
        leaves = to_host(leaves)[:count]
        model_cls = (TreeEnsembleClassifierModel if classification
                     else TreeEnsembleRegressorModel)
        for f in range(F):
            for j, (gi, cand) in enumerate(members):
                c = f * gk + j
                fe, th, le = _trim_tree_arrays(
                    feats[c], thrs[c], leaves[c], depth_cap,
                    cand.max_depth)
                models[f][gi] = model_cls(
                    fe, th, le, depth=cand.max_depth, n_features=d)
    return metric_mat if eval_ctx is not None else models


def _gbt_fold_grid(est, X, y, masks, grid, mesh, objective: str,
                   eval_ctx=None, edge_rows=None):
    # mirrors _forest_fold_grid's candidate contract (fold-major
    # flattening, static-group partitioning, padding, eval_ctx fusion,
    # TX_TREE_EDGES=fold recursion) — change both together
    masks = np.asarray(masks, dtype=np.float64)
    if edge_rows is None and _fold_edges_mode():
        return _fold_edge_recurse(
            _gbt_fold_grid, est, X, y, masks, grid, mesh, eval_ctx,
            objective=objective)
    grid = [dict(p) for p in (list(grid) or [{}])]
    allowed = set(_GBT_TRACED) | set(_GBT_STATIC)
    for p in grid:
        extra = set(p) - allowed
        if extra:
            raise NotImplementedError(
                f"batched GBT kernel cannot vary {sorted(extra)}")
    F, n = masks.shape
    G = len(grid)
    d = X.shape[1]
    y_j = jnp.asarray(y)
    models = [[None] * G for _ in range(F)]
    metric_mat = np.full((F, G), np.nan)
    if eval_ctx is not None:
        Xv_j = jnp.asarray(np.asarray(eval_ctx[0], dtype=np.float64))
        yv_j = jnp.asarray(np.asarray(eval_ctx[1], dtype=np.float64))
        spec = eval_ctx[2]
    model_cls = (GBTClassifierModel if objective == "logistic"
                 else GBTRegressorModel)
    for members, cand0, depth_cap, vecs, masks_p, fidx, count, gk in \
            _candidate_groups(est, grid, masks, mesh, _GBT_TILED,
                              _GBT_SKEY):
        design, _ = _design_args(X, cand0.max_bins,
                                 edge_rows=edge_rows)
        statics = (depth_cap, cand0.num_rounds, objective,
                   _hist_mode(n, int(design[1].shape[0])))
        _note_compile("gbt", statics, masks_p.shape)
        vecs_j = [jnp.asarray(v) for v in vecs]
        if eval_ctx is not None:
            fn = _gbt_eval_kernel(statics, spec, mesh)
            mm = to_host(fn(
                jnp.asarray(masks_p), *vecs_j, jnp.asarray(fidx),
                Xv_j, yv_j, *design[:4], y_j,
                jax.random.PRNGKey(cand0.seed)))[:count]
            _scatter_group_metrics(metric_mat, mm, members, F, gk)
            continue
        fn = _gbt_fg_kernel(statics, mesh)
        feats, thrs, leaves, base = fn(
            jnp.asarray(masks_p), *vecs_j, *design[:4], y_j,
            jax.random.PRNGKey(cand0.seed))
        feats = to_host(feats)[:count]
        thrs = to_host(thrs)[:count]
        leaves = to_host(leaves)[:count]
        base = to_host(base)[:count]
        for f in range(F):
            for j, (gi, cand) in enumerate(members):
                c = f * gk + j
                fe, th, le = _trim_tree_arrays(
                    feats[c], thrs[c], leaves[c], depth_cap,
                    cand.max_depth)
                models[f][gi] = model_cls(
                    fe, th, le, depth=cand.max_depth,
                    base=float(base[c]), n_features=d)
    return metric_mat if eval_ctx is not None else models


class _ForestClassifierBase(Predictor):
    num_trees = 1
    bootstrap = False

    def fit_fold_grid_arrays(self, X, y, masks, grid, mesh=None):
        """Validator fast path: all (fold, grid) candidates in one
        vmapped program per static group, mesh-shardable over the
        candidate axis (reference OpValidator.scala:270 parallelism)."""
        return _forest_fold_grid(self, X, y, masks, grid, mesh, True)

    def eval_fold_grid_arrays(self, X, y, masks, grid, X_val, y_val,
                              spec, mesh=None, cand_idx=None):
        """Device-resident search: fused fit + validation metric, (F, G)
        matrix out (see _forest_fold_grid eval_ctx). ``cand_idx``
        (racing rungs) restricts to a candidate subset — traced
        hyperparameters stay dynamic lanes; static groups a rung prunes
        entirely simply stop being compiled."""
        if spec[0] == "binary" and num_classes(y) != 2:
            raise NotImplementedError(
                "binary device eval needs binary labels")
        if spec[0] not in ("binary", "multiclass"):
            raise NotImplementedError(
                "forest-classifier device eval needs a classification "
                "metric")
        return _forest_fold_grid(self, X, y, masks,
                                 subset_grid(grid, cand_idx), mesh, True,
                                 eval_ctx=(X_val, y_val, spec))

    def fit_arrays_sharded(self, X, y, mesh, axis: str = "data"
                           ) -> TreeEnsembleClassifierModel:
        """Row-sharded (data-parallel) fit: each ``mesh[axis]`` shard
        holds a contiguous row block; per-level histograms psum over
        ICI (_grow_tree axis_name — the Rabit-allreduce role, SURVEY
        §2.9). Identical trees to fit_arrays when the row count divides
        the shard count (same bootstrap draws via _row_draw)."""
        k = num_classes(y)
        d = X.shape[1]
        shards = mesh.shape[axis]
        mf = _resolve_max_features(self.feature_subset_strategy, d, True) \
            if self.bootstrap else None
        design, widths = _design_args(X, self.max_bins)
        (narrow, wide), pool_cfg, mf = _pool_plan(widths, mf)
        packed, feat_of, block_start, packed_thr, binned, col_thr = design
        (packed_p, binned_p, y_p), mask = _pad_rows(
            [np.asarray(packed), np.asarray(binned), np.asarray(y)],
            shards)
        row_total = len(mask)
        statics = ("cls", self.max_depth, k, self.num_trees, mf,
                   pool_cfg, self.impurity, self.bootstrap,
                   _hist_mode(row_total, int(feat_of.shape[0])),
                   row_total, _tree_budget_mb())
        fn = _forest_sharded_kernel(statics, mesh, axis)
        feats, thrs, leaves = fn(
            jnp.asarray(packed_p), jnp.asarray(binned_p),
            jnp.asarray(y_p), jnp.asarray(mask), feat_of, block_start,
            packed_thr, col_thr, narrow, wide,
            jax.random.PRNGKey(self.seed),
            jnp.asarray(float(self.min_instances_per_node)),
            jnp.asarray(float(self.min_info_gain)),
            jnp.asarray(float(self.subsampling_rate)))
        return TreeEnsembleClassifierModel(
            to_host(feats), to_host(thrs), to_host(leaves),
            depth=self.max_depth, n_features=d)

    def fit_arrays(self, X: np.ndarray, y: np.ndarray
                   ) -> TreeEnsembleClassifierModel:
        k = num_classes(y)
        d = X.shape[1]
        mf = _resolve_max_features(self.feature_subset_strategy, d, True) \
            if self.bootstrap else None
        design, widths = _design_args(X, self.max_bins)
        (narrow, wide), pool_cfg, mf = _pool_plan(widths, mf)
        feats, thrs, leaves = _fit_forest_classifier(
            *design, narrow, wide, jnp.asarray(y),
            jax.random.PRNGKey(self.seed), depth=self.max_depth,
            num_classes=k, num_trees=self.num_trees, max_features=mf,
            pool_cfg=pool_cfg, impurity=self.impurity,
            min_instances=float(self.min_instances_per_node),
            min_info_gain=self.min_info_gain,
            subsample=self.subsampling_rate, bootstrap=self.bootstrap,
            hist_mode=_hist_mode(X.shape[0], int(design[1].shape[0])),
            budget_mb=_tree_budget_mb())
        return TreeEnsembleClassifierModel(feats, thrs, leaves,
                                           depth=self.max_depth,
                                           n_features=d)


class _ForestRegressorBase(Predictor):
    num_trees = 1
    bootstrap = False

    def fit_fold_grid_arrays(self, X, y, masks, grid, mesh=None):
        """See _ForestClassifierBase.fit_fold_grid_arrays."""
        return _forest_fold_grid(self, X, y, masks, grid, mesh, False)

    def eval_fold_grid_arrays(self, X, y, masks, grid, X_val, y_val,
                              spec, mesh=None, cand_idx=None):
        """See _ForestClassifierBase.eval_fold_grid_arrays."""
        if spec[0] != "regression":
            raise NotImplementedError(
                "forest-regressor device eval needs a regression metric")
        return _forest_fold_grid(self, X, y, masks,
                                 subset_grid(grid, cand_idx), mesh, False,
                                 eval_ctx=(X_val, y_val, spec))

    def fit_arrays_sharded(self, X, y, mesh, axis: str = "data"
                           ) -> TreeEnsembleRegressorModel:
        """See _ForestClassifierBase.fit_arrays_sharded."""
        d = X.shape[1]
        shards = mesh.shape[axis]
        mf = _resolve_max_features(self.feature_subset_strategy, d,
                                   False) if self.bootstrap else None
        design, widths = _design_args(X, self.max_bins)
        (narrow, wide), pool_cfg, mf = _pool_plan(widths, mf)
        packed, feat_of, block_start, packed_thr, binned, col_thr = design
        (packed_p, binned_p, y_p), mask = _pad_rows(
            [np.asarray(packed), np.asarray(binned), np.asarray(y)],
            shards)
        row_total = len(mask)
        statics = ("reg", self.max_depth, 0, self.num_trees, mf,
                   pool_cfg, "", self.bootstrap,
                   _hist_mode(row_total, int(feat_of.shape[0])),
                   row_total, _tree_budget_mb())
        fn = _forest_sharded_kernel(statics, mesh, axis)
        feats, thrs, leaves = fn(
            jnp.asarray(packed_p), jnp.asarray(binned_p),
            jnp.asarray(y_p), jnp.asarray(mask), feat_of, block_start,
            packed_thr, col_thr, narrow, wide,
            jax.random.PRNGKey(self.seed),
            jnp.asarray(float(self.min_instances_per_node)),
            jnp.asarray(float(self.min_info_gain)),
            jnp.asarray(float(self.subsampling_rate)))
        return TreeEnsembleRegressorModel(
            to_host(feats), to_host(thrs), to_host(leaves),
            depth=self.max_depth, n_features=d)

    def fit_arrays(self, X: np.ndarray, y: np.ndarray
                   ) -> TreeEnsembleRegressorModel:
        d = X.shape[1]
        mf = _resolve_max_features(self.feature_subset_strategy, d, False) \
            if self.bootstrap else None
        design, widths = _design_args(X, self.max_bins)
        (narrow, wide), pool_cfg, mf = _pool_plan(widths, mf)
        feats, thrs, leaves = _fit_forest_regressor(
            *design, narrow, wide, jnp.asarray(y),
            jax.random.PRNGKey(self.seed), depth=self.max_depth,
            num_trees=self.num_trees, max_features=mf,
            pool_cfg=pool_cfg,
            min_instances=float(self.min_instances_per_node),
            min_info_gain=self.min_info_gain,
            subsample=self.subsampling_rate, bootstrap=self.bootstrap,
            hist_mode=_hist_mode(X.shape[0], int(design[1].shape[0])),
            budget_mb=_tree_budget_mb())
        return TreeEnsembleRegressorModel(feats, thrs, leaves,
                                          depth=self.max_depth,
                                          n_features=d)


class DecisionTreeClassifier(_ForestClassifierBase):
    """Single CART tree, gini/entropy impurity
    (reference OpDecisionTreeClassifier.scala)."""

    def __init__(self, max_depth: int = 5, max_bins: int = 32,
                 min_instances_per_node: int = 1, min_info_gain: float = 0.0,
                 impurity: str = "gini", seed: int = 42,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.max_depth = max_depth
        self.max_bins = max_bins
        self.min_instances_per_node = min_instances_per_node
        self.min_info_gain = min_info_gain
        self.impurity = impurity
        self.seed = seed
        self.num_trees = 1
        self.bootstrap = False
        self.subsampling_rate = 1.0
        self.feature_subset_strategy = "all"


class DecisionTreeRegressor(_ForestRegressorBase):
    """(reference OpDecisionTreeRegressor.scala)"""

    def __init__(self, max_depth: int = 5, max_bins: int = 32,
                 min_instances_per_node: int = 1, min_info_gain: float = 0.0,
                 seed: int = 42, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.max_depth = max_depth
        self.max_bins = max_bins
        self.min_instances_per_node = min_instances_per_node
        self.min_info_gain = min_info_gain
        self.seed = seed
        self.num_trees = 1
        self.bootstrap = False
        self.subsampling_rate = 1.0
        self.feature_subset_strategy = "all"


class RandomForestClassifier(_ForestClassifierBase):
    """Bagged gini trees with per-node feature subsampling
    (reference OpRandomForestClassifier.scala). Bootstrap resampling uses
    Poisson(subsamplingRate) row weights — the same approximation Spark
    MLlib's BaggedPoint uses for sampling with replacement."""

    def __init__(self, num_trees: int = 20, max_depth: int = 5,
                 max_bins: int = 32, min_instances_per_node: int = 1,
                 min_info_gain: float = 0.0, subsampling_rate: float = 1.0,
                 feature_subset_strategy: str = "auto", impurity: str = "gini",
                 seed: int = 42, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.num_trees = num_trees
        self.max_depth = max_depth
        self.max_bins = max_bins
        self.min_instances_per_node = min_instances_per_node
        self.min_info_gain = min_info_gain
        self.subsampling_rate = subsampling_rate
        self.feature_subset_strategy = feature_subset_strategy
        self.impurity = impurity
        self.seed = seed
        self.bootstrap = True


class RandomForestRegressor(_ForestRegressorBase):
    """(reference OpRandomForestRegressor.scala)"""

    def __init__(self, num_trees: int = 20, max_depth: int = 5,
                 max_bins: int = 32, min_instances_per_node: int = 1,
                 min_info_gain: float = 0.0, subsampling_rate: float = 1.0,
                 feature_subset_strategy: str = "auto", seed: int = 42,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.num_trees = num_trees
        self.max_depth = max_depth
        self.max_bins = max_bins
        self.min_instances_per_node = min_instances_per_node
        self.min_info_gain = min_info_gain
        self.subsampling_rate = subsampling_rate
        self.feature_subset_strategy = feature_subset_strategy
        self.seed = seed
        self.bootstrap = True


class GBTClassifier(Predictor):
    """Gradient-boosted binary classifier with second-order (XGBoost-style)
    split gains on the logistic objective (reference OpGBTClassifier.scala;
    MLlib GBT uses first-order residual fitting — the second-order variant
    strictly dominates and is the XGBoost parity path, SURVEY §2.9)."""

    def __init__(self, num_rounds: int = 20, max_depth: int = 5,
                 step_size: float = 0.1, max_bins: int = 32,
                 reg_lambda: float = 1.0, gamma: float = 0.0,
                 min_child_weight: float = 1.0, subsample: float = 1.0,
                 seed: int = 42, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.num_rounds = num_rounds
        self.max_depth = max_depth
        self.step_size = step_size
        self.max_bins = max_bins
        self.reg_lambda = reg_lambda
        self.gamma = gamma
        self.min_child_weight = min_child_weight
        self.subsample = subsample
        self.seed = seed

    def fit_fold_grid_arrays(self, X, y, masks, grid, mesh=None):
        """See _ForestClassifierBase.fit_fold_grid_arrays."""
        bad = np.setdiff1d(np.unique(y), [0.0, 1.0])
        if bad.size:
            # NotImplementedError (not ValueError): the validator then
            # takes the sequential fallback, where the per-fold handler
            # drops this family out of the race instead of killing the
            # whole search
            raise NotImplementedError(
                "batched GBT kernel requires binary labels {0, 1}")
        return _gbt_fold_grid(self, X, y, masks, grid, mesh, "logistic")

    def eval_fold_grid_arrays(self, X, y, masks, grid, X_val, y_val,
                              spec, mesh=None, cand_idx=None):
        """Device-resident search: fused fit + validation metric, (F, G)
        matrix out (see _gbt_fold_grid eval_ctx)."""
        if spec[0] != "binary":
            raise NotImplementedError(
                "GBT-classifier device eval is binary-only")
        bad = np.setdiff1d(np.unique(y), [0.0, 1.0])
        if bad.size:
            raise NotImplementedError(
                "batched GBT kernel requires binary labels {0, 1}")
        return _gbt_fold_grid(self, X, y, masks,
                              subset_grid(grid, cand_idx), mesh,
                              "logistic", eval_ctx=(X_val, y_val, spec))

    def fit_arrays_sharded(self, X, y, mesh, axis: str = "data"
                           ) -> GBTClassifierModel:
        """Row-sharded (data-parallel) boosting — see
        _ForestClassifierBase.fit_arrays_sharded."""
        bad = np.setdiff1d(np.unique(y), [0.0, 1.0])
        if bad.size:
            raise ValueError(
                "GBTClassifier supports binary labels {0, 1} only")
        return _gbt_fit_sharded(self, X, y, mesh, axis, "logistic")

    def fit_arrays(self, X: np.ndarray, y: np.ndarray) -> GBTClassifierModel:
        bad = np.setdiff1d(np.unique(y), [0.0, 1.0])
        if bad.size:
            raise ValueError(
                f"GBTClassifier supports binary labels {{0, 1}} only "
                f"(as MLlib GBTClassifier does); got extra labels "
                f"{bad.tolist()} — use RandomForestClassifier or "
                f"LogisticRegression for multiclass")
        design, _ = _design_args(X, self.max_bins)
        feats, thrs, leaves, base = _fit_gbt(
            *design[:4], jnp.asarray(y),
            jax.random.PRNGKey(self.seed), depth=self.max_depth,
            num_rounds=self.num_rounds,
            step_size=self.step_size, reg_lambda=self.reg_lambda,
            gamma=self.gamma, min_child_weight=self.min_child_weight,
            subsample=self.subsample, objective="logistic",
            hist_mode=_hist_mode(X.shape[0], int(design[1].shape[0])))
        return GBTClassifierModel(feats, thrs, leaves, depth=self.max_depth,
                                  base=float(base), n_features=X.shape[1])


class GBTRegressor(Predictor):
    """Gradient-boosted regressor, squared loss
    (reference OpGBTRegressor.scala)."""

    def __init__(self, num_rounds: int = 20, max_depth: int = 5,
                 step_size: float = 0.1, max_bins: int = 32,
                 reg_lambda: float = 1.0, gamma: float = 0.0,
                 min_child_weight: float = 1.0, subsample: float = 1.0,
                 seed: int = 42, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.num_rounds = num_rounds
        self.max_depth = max_depth
        self.step_size = step_size
        self.max_bins = max_bins
        self.reg_lambda = reg_lambda
        self.gamma = gamma
        self.min_child_weight = min_child_weight
        self.subsample = subsample
        self.seed = seed

    def fit_fold_grid_arrays(self, X, y, masks, grid, mesh=None):
        """See _ForestClassifierBase.fit_fold_grid_arrays."""
        return _gbt_fold_grid(self, X, y, masks, grid, mesh, "squared")

    def eval_fold_grid_arrays(self, X, y, masks, grid, X_val, y_val,
                              spec, mesh=None, cand_idx=None):
        """See GBTClassifier.eval_fold_grid_arrays."""
        if spec[0] != "regression":
            raise NotImplementedError(
                "GBT-regressor device eval needs a regression metric")
        return _gbt_fold_grid(self, X, y, masks,
                              subset_grid(grid, cand_idx), mesh,
                              "squared", eval_ctx=(X_val, y_val, spec))

    def fit_arrays_sharded(self, X, y, mesh, axis: str = "data"
                           ) -> GBTRegressorModel:
        """See GBTClassifier.fit_arrays_sharded."""
        return _gbt_fit_sharded(self, X, y, mesh, axis, "squared")

    def fit_arrays(self, X: np.ndarray, y: np.ndarray) -> GBTRegressorModel:
        design, _ = _design_args(X, self.max_bins)
        feats, thrs, leaves, base = _fit_gbt(
            *design[:4], jnp.asarray(y),
            jax.random.PRNGKey(self.seed), depth=self.max_depth,
            num_rounds=self.num_rounds,
            step_size=self.step_size, reg_lambda=self.reg_lambda,
            gamma=self.gamma, min_child_weight=self.min_child_weight,
            subsample=self.subsample, objective="squared",
            hist_mode=_hist_mode(X.shape[0], int(design[1].shape[0])))
        return GBTRegressorModel(feats, thrs, leaves, depth=self.max_depth,
                                 base=float(base), n_features=X.shape[1])


class XGBoostClassifier(GBTClassifier):
    """XGBoost-parameter-named facade over the same histogram booster
    (reference OpXGBoostClassifier.scala:47 — the reference's only native
    C++ component, xgboost4j + Rabit; here the booster IS the second-order
    histogram GBT above, with multi-chip reduction via psum, SURVEY §2.9).
    Unlike GBTClassifier (MLlib parity: binary-only), this facade also
    fits K-class problems via the softmax objective — the
    ``multi:softprob`` path xgboost4j takes."""

    def __init__(self, eta: float = 0.3, max_depth: int = 6,
                 num_round: int = 100, reg_lambda: float = 1.0,
                 gamma: float = 0.0, min_child_weight: float = 1.0,
                 subsample: float = 1.0, max_bins: int = 256,
                 seed: int = 42, uid: Optional[str] = None):
        GBTClassifier.__init__(
            self, num_rounds=num_round, max_depth=max_depth, step_size=eta,
            max_bins=max_bins, reg_lambda=reg_lambda, gamma=gamma,
            min_child_weight=min_child_weight, subsample=subsample,
            seed=seed, uid=uid)
        self.eta = eta
        self.num_round = num_round

    @staticmethod
    def _check_multiclass_labels(y, k: int) -> None:
        bad = np.setdiff1d(np.unique(y), np.arange(k, dtype=np.float64))
        if bad.size:
            raise NotImplementedError(
                f"softmax booster needs integer class labels 0..{k - 1};"
                f" got {bad.tolist()}")

    def fit_fold_grid_arrays(self, X, y, masks, grid, mesh=None):
        """Multiclass grids run the fused softmax fold×grid kernel
        (binary falls through to the GBT driver)."""
        k = num_classes(y)
        if k <= 2:
            return GBTClassifier.fit_fold_grid_arrays(
                self, X, y, masks, grid, mesh=mesh)
        self._check_multiclass_labels(y, k)
        check_fold_classes(y, masks)
        return _gbt_softmax_fold_grid(self, X, y, masks, grid, mesh, k)

    def eval_fold_grid_arrays(self, X, y, masks, grid, X_val, y_val,
                              spec, mesh=None, cand_idx=None):
        """Device-resident multiclass search: fused softmax fit +
        metric, (F, G) matrix out (_gbt_softmax_eval_kernel)."""
        k = num_classes(y)
        if k <= 2:
            return GBTClassifier.eval_fold_grid_arrays(
                self, X, y, masks, grid, X_val, y_val, spec, mesh=mesh,
                cand_idx=cand_idx)
        if spec[0] != "multiclass":
            raise NotImplementedError(
                "softmax-GBT device eval needs a multiclass metric")
        self._check_multiclass_labels(y, k)
        check_fold_classes(y, masks)
        return _gbt_softmax_fold_grid(self, X, y, masks,
                                      subset_grid(grid, cand_idx), mesh,
                                      k, eval_ctx=(X_val, y_val, spec))

    def fit_arrays(self, X: np.ndarray, y: np.ndarray):
        k = num_classes(y)
        if k <= 2:
            return GBTClassifier.fit_arrays(self, X, y)
        bad = np.setdiff1d(np.unique(y), np.arange(k, dtype=np.float64))
        if bad.size:
            raise ValueError(
                f"XGBoostClassifier needs integer class labels 0..{k - 1};"
                f" got {bad.tolist()}")
        design, _ = _design_args(X, self.max_bins)
        feats, thrs, leaves, base = _fit_gbt_softmax(
            *design[:4], jnp.asarray(y), jax.random.PRNGKey(self.seed),
            depth=self.max_depth, num_rounds=self.num_rounds,
            num_classes=k, step_size=self.step_size,
            reg_lambda=self.reg_lambda, gamma=self.gamma,
            min_child_weight=self.min_child_weight,
            subsample=self.subsample,
            hist_mode=_hist_mode(X.shape[0], int(design[1].shape[0])))
        return GBTMulticlassClassifierModel(
            to_host(feats), to_host(thrs), to_host(leaves),
            depth=self.max_depth, base=to_host(base),
            n_features=X.shape[1])


class XGBoostRegressor(GBTRegressor):
    """(reference OpXGBoostRegressor.scala)"""

    def __init__(self, eta: float = 0.3, max_depth: int = 6,
                 num_round: int = 100, reg_lambda: float = 1.0,
                 gamma: float = 0.0, min_child_weight: float = 1.0,
                 subsample: float = 1.0, max_bins: int = 256,
                 seed: int = 42, uid: Optional[str] = None):
        GBTRegressor.__init__(
            self, num_rounds=num_round, max_depth=max_depth, step_size=eta,
            max_bins=max_bins, reg_lambda=reg_lambda, gamma=gamma,
            min_child_weight=min_child_weight, subsample=subsample,
            seed=seed, uid=uid)
        self.eta = eta
        self.num_round = num_round
