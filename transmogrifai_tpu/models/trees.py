"""Tree model family: decision tree, random forest, gradient-boosted trees.

TPU-native replacements for the reference's Spark MLlib / XGBoost wrappers:
- OpDecisionTreeClassifier / OpDecisionTreeRegressor
  (core/.../classification/OpDecisionTreeClassifier.scala,
   core/.../regression/OpDecisionTreeRegressor.scala)
- OpRandomForestClassifier / OpRandomForestRegressor
  (core/.../classification/OpRandomForestClassifier.scala)
- OpGBTClassifier / OpGBTRegressor
  (core/.../classification/OpGBTClassifier.scala)
- OpXGBoostClassifier / OpXGBoostRegressor
  (core/.../classification/OpXGBoostClassifier.scala:47 — xgboost4j JNI,
   the reference's only native-C++ compute; see SURVEY.md §2.9)

Design (histogram GBDT, XLA-first — no CUDA/Rabit translation):

- Features are quantile-binned once into <= ``max_bins`` integer bins
  (MLlib ``maxBins``/XGBoost ``tree_method=hist`` equivalent).
- Trees grow **level-wise over a dense complete binary tree** of static
  depth: every level computes per-(node, feature, bin) statistic
  histograms via ``segment_sum`` (a ``lax.scan`` over features keeps
  memory at O(n*S)), turns them into split gains with one cumulative
  sum over bins, and advances every row one level. No data-dependent
  shapes anywhere, so the whole builder jits into one XLA program;
  a forest is a ``lax.scan`` of that program over bootstrap keys and
  boosting is a ``lax.scan`` of it over rounds with margin updates.
- Nodes that fail the gain/min-weight checks emit a +inf threshold
  ("everything goes left"), which makes dead branches self-propagating
  without ragged control flow.
- Split histograms sum 2nd-order grad/hess stats (XGBoost objective)
  or class-count/variance stats (MLlib gini/variance impurity).

Distributed fit: histograms are linear in rows, so data-parallel
multi-chip training is a ``psum`` of per-shard histograms over ICI —
the TPU equivalent of XGBoost's Rabit allreduce (see parallel/cv.py for
the mesh machinery). The builders here take already-materialized
device arrays and are safe to call inside ``shard_map``.
"""
from __future__ import annotations

import functools
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..features.columns import PredictionColumn
from .base import ClassifierModel, Predictor, RegressionModel

__all__ = [
    "DecisionTreeClassifier", "DecisionTreeRegressor",
    "RandomForestClassifier", "RandomForestRegressor",
    "GBTClassifier", "GBTRegressor",
    "XGBoostClassifier", "XGBoostRegressor",
    "TreeEnsembleClassifierModel", "TreeEnsembleRegressorModel",
    "GBTClassifierModel", "GBTRegressorModel",
]


# ---------------------------------------------------------------------------
# binning
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("max_bins",))
def _quantile_edges(X: jnp.ndarray, max_bins: int) -> jnp.ndarray:
    """Per-feature quantile cut points, shape (d, B-1). Duplicated edges
    (constant features) just leave some bins empty."""
    qs = jnp.linspace(0.0, 1.0, max_bins + 1)[1:-1]
    return jnp.quantile(X, qs, axis=0).T


@jax.jit
def _bin_matrix(X: jnp.ndarray, edges: jnp.ndarray) -> jnp.ndarray:
    """bin(x) = #{edges < x} so that bin(x) <= b  <=>  x <= edges[b]."""
    def col(xc, ec):
        return jnp.searchsorted(ec, xc, side="left")
    return jax.vmap(col, in_axes=(1, 0), out_axes=1)(X, edges).astype(jnp.int32)


# ---------------------------------------------------------------------------
# generic level-wise tree builder
# ---------------------------------------------------------------------------

def _level_histograms(binned_T: jnp.ndarray, node: jnp.ndarray,
                      stats: jnp.ndarray, num_nodes: int,
                      max_bins: int) -> jnp.ndarray:
    """(d, num_nodes, B, S) histograms; scan over features bounds memory."""
    def per_feat(_, bcol):
        seg = node * max_bins + bcol
        h = jax.ops.segment_sum(stats, seg,
                                num_segments=num_nodes * max_bins)
        return None, h.reshape(num_nodes, max_bins, -1)
    _, hists = jax.lax.scan(per_feat, None, binned_T)
    return hists


def _grow_tree(binned: jnp.ndarray, stats: jnp.ndarray, edges: jnp.ndarray,
               *, depth: int, max_bins: int, gain_fn, min_info_gain: float,
               feat_key: Optional[jnp.ndarray] = None,
               max_features: Optional[int] = None):
    """Grow one complete tree of static ``depth``.

    gain_fn(left, right, total) -> (..., ) gains with -inf where a split
    is invalid; ``left/right/total`` are stat tensors with trailing dim S.

    Returns (feat_heap (2^depth - 1,), thr_heap (2^depth - 1,),
    leaf_stats (2^depth, S), final node assignment (n,)).
    """
    n, d = binned.shape
    binned_T = binned.T
    node = jnp.zeros((n,), jnp.int32)
    feats_levels, thr_levels = [], []
    key = feat_key
    for level in range(depth):
        num_nodes = 2 ** level
        hist = _level_histograms(binned_T, node, stats, num_nodes, max_bins)
        hist = jnp.moveaxis(hist, 0, 1)          # (nodes, d, B, S)
        left = jnp.cumsum(hist, axis=2)           # split at b: bins<=b left
        total = left[:, 0:1, -1:, :]              # (nodes,1,1,S)
        right = total - left
        gain = gain_fn(left, right, total)        # (nodes, d, B)
        # the last bin puts everything left — not a split
        gain = gain.at[:, :, -1].set(-jnp.inf)
        if max_features is not None and max_features < d:
            key, sub = jax.random.split(key)
            u = jax.random.uniform(sub, (num_nodes, d))
            kth = jnp.sort(u, axis=1)[:, max_features - 1:max_features]
            gain = jnp.where((u <= kth)[:, :, None], gain, -jnp.inf)
        flat = gain.reshape(num_nodes, d * max_bins)
        best = jnp.argmax(flat, axis=1)
        best_gain = jnp.take_along_axis(flat, best[:, None], axis=1)[:, 0]
        bfeat = (best // max_bins).astype(jnp.int32)
        bbin = (best % max_bins).astype(jnp.int32)
        split_ok = best_gain >= jnp.maximum(min_info_gain, 1e-12)
        bfeat = jnp.where(split_ok, bfeat, 0)
        bbin = jnp.where(split_ok, bbin, max_bins - 1)
        thr = jnp.where(bbin >= max_bins - 1, jnp.inf, edges[bfeat, jnp.minimum(bbin, max_bins - 2)])
        feats_levels.append(bfeat)
        thr_levels.append(thr)
        go_left = binned[jnp.arange(n), bfeat[node]] <= bbin[node]
        node = 2 * node + (1 - go_left.astype(jnp.int32))  # within-level idx
    leaf_stats = jax.ops.segment_sum(stats, node, num_segments=2 ** depth)
    feat_heap = jnp.concatenate(feats_levels) if depth else jnp.zeros((0,), jnp.int32)
    thr_heap = jnp.concatenate(thr_levels) if depth else jnp.zeros((0,))
    return feat_heap, thr_heap, leaf_stats, node


def _traverse(X: jnp.ndarray, feat_heap: jnp.ndarray, thr_heap: jnp.ndarray,
              depth: int) -> jnp.ndarray:
    """Leaf index in [0, 2^depth) for every row; static-depth descent."""
    n = X.shape[0]
    node = jnp.zeros((n,), jnp.int32)
    rows = jnp.arange(n)
    for level in range(depth):
        heap = 2 ** level - 1 + node     # levels concatenate into the heap
        f = feat_heap[heap]
        t = thr_heap[heap]
        go_left = X[rows, f] <= t
        node = 2 * node + (1 - go_left.astype(jnp.int32))
    return node


# ---------------------------------------------------------------------------
# split criteria
# ---------------------------------------------------------------------------

def _xgb_gain(reg_lambda: float, gamma: float, min_child_weight: float):
    """Second-order gain (stats = [grad, hess]); XGBoost objective."""
    def gain(left, right, total):
        def score(s):
            return s[..., 0] ** 2 / (s[..., 1] + reg_lambda)
        g = 0.5 * (score(left) + score(right) - score(total)) - gamma
        ok = ((left[..., 1] >= min_child_weight)
              & (right[..., 1] >= min_child_weight))
        return jnp.where(ok, g, -jnp.inf)
    return gain


def _gini_gain(min_instances: float):
    """Weighted gini impurity gain (stats = per-class weights); MLlib
    'gini' impurity, tree/impurity/Gini in Spark MLlib."""
    def impurity_weighted(s):               # sum_c s_c - sum_c s_c^2 / w
        w = jnp.sum(s, axis=-1)
        return w - jnp.sum(s * s, axis=-1) / jnp.maximum(w, 1e-12)
    def gain(left, right, total):
        wl = jnp.sum(left, axis=-1)
        wr = jnp.sum(right, axis=-1)
        wp = jnp.maximum(jnp.sum(total, axis=-1), 1e-12)
        g = (impurity_weighted(total) - impurity_weighted(left)
             - impurity_weighted(right)) / wp
        ok = (wl >= min_instances) & (wr >= min_instances)
        return jnp.where(ok, g, -jnp.inf)
    return gain


def _entropy_gain(min_instances: float):
    def impurity_weighted(s):
        w = jnp.maximum(jnp.sum(s, axis=-1, keepdims=True), 1e-12)
        p = s / w
        ent = -jnp.sum(jnp.where(s > 0, p * jnp.log(p), 0.0), axis=-1)
        return w[..., 0] * ent
    def gain(left, right, total):
        wl = jnp.sum(left, axis=-1)
        wr = jnp.sum(right, axis=-1)
        wp = jnp.maximum(jnp.sum(total, axis=-1), 1e-12)
        g = (impurity_weighted(total) - impurity_weighted(left)
             - impurity_weighted(right)) / wp
        ok = (wl >= min_instances) & (wr >= min_instances)
        return jnp.where(ok, g, -jnp.inf)
    return gain


def _variance_gain(min_instances: float):
    """SSE-reduction gain (stats = [w, wy, wyy]); MLlib 'variance'."""
    def sse(s):
        return s[..., 2] - s[..., 1] ** 2 / jnp.maximum(s[..., 0], 1e-12)
    def gain(left, right, total):
        wp = jnp.maximum(total[..., 0], 1e-12)
        g = (sse(total) - sse(left) - sse(right)) / wp
        ok = ((left[..., 0] >= min_instances)
              & (right[..., 0] >= min_instances))
        return jnp.where(ok, g, -jnp.inf)
    return gain


# ---------------------------------------------------------------------------
# jitted fit programs
# ---------------------------------------------------------------------------

@functools.partial(
    jax.jit, static_argnames=("depth", "max_bins", "num_classes", "num_trees",
                              "max_features", "impurity", "bootstrap"))
def _fit_forest_classifier(X, y, key, *, depth: int, max_bins: int,
                           num_classes: int, num_trees: int,
                           max_features: Optional[int], impurity: str,
                           min_instances: float, min_info_gain: float,
                           subsample: float, bootstrap: bool):
    n, d = X.shape
    edges = _quantile_edges(X, max_bins)
    binned = _bin_matrix(X, edges)
    onehot = jax.nn.one_hot(y.astype(jnp.int32), num_classes, dtype=X.dtype)
    gain_fn = (_gini_gain(min_instances) if impurity == "gini"
               else _entropy_gain(min_instances))

    def one_tree(carry, tkey):
        wkey, fkey = jax.random.split(tkey)
        if bootstrap:
            w = jax.random.poisson(wkey, subsample, (n,)).astype(X.dtype)
        else:
            w = jnp.ones((n,), X.dtype)
        feat, thr, leaf_stats, _ = _grow_tree(
            binned, onehot * w[:, None], edges, depth=depth,
            max_bins=max_bins, gain_fn=gain_fn,
            min_info_gain=min_info_gain, feat_key=fkey,
            max_features=max_features)
        lw = jnp.sum(leaf_stats, axis=-1, keepdims=True)
        probs = jnp.where(lw > 0, leaf_stats / jnp.maximum(lw, 1e-12),
                          1.0 / num_classes)
        return carry, (feat, thr, probs)
    _, (feats, thrs, leaves) = jax.lax.scan(
        one_tree, None, jax.random.split(key, num_trees))
    return feats, thrs, leaves


@functools.partial(
    jax.jit, static_argnames=("depth", "max_bins", "num_trees",
                              "max_features", "bootstrap"))
def _fit_forest_regressor(X, y, key, *, depth: int, max_bins: int,
                          num_trees: int, max_features: Optional[int],
                          min_instances: float, min_info_gain: float,
                          subsample: float, bootstrap: bool):
    n, d = X.shape
    edges = _quantile_edges(X, max_bins)
    binned = _bin_matrix(X, edges)
    gain_fn = _variance_gain(min_instances)

    def one_tree(carry, tkey):
        wkey, fkey = jax.random.split(tkey)
        if bootstrap:
            w = jax.random.poisson(wkey, subsample, (n,)).astype(X.dtype)
        else:
            w = jnp.ones((n,), X.dtype)
        stats = jnp.stack([w, w * y, w * y * y], axis=1)
        feat, thr, leaf_stats, _ = _grow_tree(
            binned, stats, edges, depth=depth, max_bins=max_bins,
            gain_fn=gain_fn, min_info_gain=min_info_gain, feat_key=fkey,
            max_features=max_features)
        vals = leaf_stats[:, 1] / jnp.maximum(leaf_stats[:, 0], 1e-12)
        return carry, (feat, thr, vals)
    _, (feats, thrs, leaves) = jax.lax.scan(
        one_tree, None, jax.random.split(key, num_trees))
    return feats, thrs, leaves


@functools.partial(
    jax.jit, static_argnames=("depth", "max_bins", "num_rounds", "objective",
                              "subsample"))
def _fit_gbt(X, y, key, *, depth: int, max_bins: int, num_rounds: int,
             step_size: float, reg_lambda: float, gamma: float,
             min_child_weight: float, subsample: float, objective: str):
    n, d = X.shape
    edges = _quantile_edges(X, max_bins)
    binned = _bin_matrix(X, edges)
    gain_fn = _xgb_gain(reg_lambda, gamma, min_child_weight)
    if objective == "logistic":
        p0 = jnp.clip(jnp.mean(y), 1e-6, 1 - 1e-6)
        base = jnp.log(p0 / (1 - p0))
    else:
        base = jnp.mean(y)
    margins0 = jnp.full((n,), base, X.dtype)

    def one_round(carry, rkey):
        margins = carry
        if objective == "logistic":
            p = jax.nn.sigmoid(margins)
            g, h = p - y, jnp.maximum(p * (1 - p), 1e-12)
        else:
            g, h = margins - y, jnp.ones_like(y)
        if subsample < 1.0:
            m = jax.random.bernoulli(rkey, subsample, (n,)).astype(X.dtype)
            g, h = g * m, h * m
        feat, thr, leaf_stats, node = _grow_tree(
            binned, jnp.stack([g, h], axis=1), edges, depth=depth,
            max_bins=max_bins, gain_fn=gain_fn, min_info_gain=0.0)
        vals = -step_size * leaf_stats[:, 0] / (leaf_stats[:, 1] + reg_lambda)
        vals = jnp.where(jnp.sum(jnp.abs(leaf_stats), axis=1) > 0, vals, 0.0)
        margins = margins + vals[node]
        return margins, (feat, thr, vals)
    _, (feats, thrs, leaves) = jax.lax.scan(
        one_round, margins0, jax.random.split(key, num_rounds))
    return feats, thrs, leaves, base


@functools.partial(jax.jit, static_argnames=("depth",))
def _predict_leaves(X, feats, thrs, depth: int):
    """(T, n) leaf index per tree via vmapped static-depth traversal."""
    return jax.vmap(lambda f, t: _traverse(X, f, t, depth))(feats, thrs)


# ---------------------------------------------------------------------------
# fitted models
# ---------------------------------------------------------------------------

class TreeEnsembleClassifierModel(ClassifierModel):
    """RF/DT classifier model: averages per-tree leaf class distributions
    (reference RandomForestClassificationModel normalized vote averaging)."""

    def __init__(self, feats, thrs, leaves, depth: int,
                 n_features: int = 0, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.feats = np.asarray(feats, dtype=np.int32)
        self.thrs = np.asarray(thrs, dtype=np.float64)
        self.leaves = np.asarray(leaves, dtype=np.float64)  # (T, L, K)
        self.depth = int(depth)
        self.n_features = int(n_features)

    def predict_raw(self, X: np.ndarray) -> np.ndarray:
        leaf_idx = np.asarray(_predict_leaves(
            jnp.asarray(X), jnp.asarray(self.feats),
            jnp.asarray(self.thrs), self.depth))              # (T, n)
        probs = self.leaves[np.arange(len(self.feats))[:, None], leaf_idx]
        return np.mean(probs, axis=0)                          # (n, K)

    def raw_to_probability(self, raw: np.ndarray) -> np.ndarray:
        s = np.sum(raw, axis=1, keepdims=True)
        return raw / np.where(s > 0, s, 1.0)

    @property
    def feature_importances(self) -> np.ndarray:
        return _split_count_importances(self.feats, self.thrs, self.n_features)


class TreeEnsembleRegressorModel(RegressionModel):
    def __init__(self, feats, thrs, leaves, depth: int,
                 n_features: int = 0, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.feats = np.asarray(feats, dtype=np.int32)
        self.thrs = np.asarray(thrs, dtype=np.float64)
        self.leaves = np.asarray(leaves, dtype=np.float64)  # (T, L)
        self.depth = int(depth)
        self.n_features = int(n_features)

    def predict_values(self, X: np.ndarray) -> np.ndarray:
        leaf_idx = np.asarray(_predict_leaves(
            jnp.asarray(X), jnp.asarray(self.feats),
            jnp.asarray(self.thrs), self.depth))
        vals = self.leaves[np.arange(len(self.feats))[:, None], leaf_idx]
        return np.mean(vals, axis=0)

    @property
    def feature_importances(self) -> np.ndarray:
        return _split_count_importances(self.feats, self.thrs, self.n_features)


class GBTClassifierModel(ClassifierModel):
    """Boosted binary classifier: sigmoid over summed leaf margins."""

    def __init__(self, feats, thrs, leaves, depth: int, base: float = 0.0,
                 n_features: int = 0, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.feats = np.asarray(feats, dtype=np.int32)
        self.thrs = np.asarray(thrs, dtype=np.float64)
        self.leaves = np.asarray(leaves, dtype=np.float64)
        self.depth = int(depth)
        self.base = float(base)
        self.n_features = int(n_features)

    def margins(self, X: np.ndarray) -> np.ndarray:
        leaf_idx = np.asarray(_predict_leaves(
            jnp.asarray(X), jnp.asarray(self.feats),
            jnp.asarray(self.thrs), self.depth))
        vals = self.leaves[np.arange(len(self.feats))[:, None], leaf_idx]
        return self.base + np.sum(vals, axis=0)

    def predict_raw(self, X: np.ndarray) -> np.ndarray:
        m = self.margins(X)
        return np.stack([-m, m], axis=1)

    def raw_to_probability(self, raw: np.ndarray) -> np.ndarray:
        p = 1.0 / (1.0 + np.exp(-raw[:, 1]))
        return np.stack([1 - p, p], axis=1)

    @property
    def feature_importances(self) -> np.ndarray:
        return _split_count_importances(self.feats, self.thrs, self.n_features)


class GBTRegressorModel(RegressionModel):
    def __init__(self, feats, thrs, leaves, depth: int, base: float = 0.0,
                 n_features: int = 0, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.feats = np.asarray(feats, dtype=np.int32)
        self.thrs = np.asarray(thrs, dtype=np.float64)
        self.leaves = np.asarray(leaves, dtype=np.float64)
        self.depth = int(depth)
        self.base = float(base)
        self.n_features = int(n_features)

    def predict_values(self, X: np.ndarray) -> np.ndarray:
        leaf_idx = np.asarray(_predict_leaves(
            jnp.asarray(X), jnp.asarray(self.feats),
            jnp.asarray(self.thrs), self.depth))
        vals = self.leaves[np.arange(len(self.feats))[:, None], leaf_idx]
        return self.base + np.sum(vals, axis=0)

    @property
    def feature_importances(self) -> np.ndarray:
        return _split_count_importances(self.feats, self.thrs, self.n_features)


def _split_count_importances(feats: np.ndarray, thrs: np.ndarray,
                             n_features: int) -> np.ndarray:
    """Normalized real-split counts per feature, aligned with the training
    feature columns (a threshold of +inf marks a dead/no-split node)."""
    real = np.isfinite(thrs)
    if feats.size == 0 or not real.any():
        return np.zeros(n_features)
    counts = np.bincount(feats[real].ravel(),
                         minlength=n_features).astype(np.float64)
    total = counts.sum()
    return counts / total if total > 0 else counts


# ---------------------------------------------------------------------------
# estimators
# ---------------------------------------------------------------------------

def _resolve_max_features(strategy: str, d: int, classification: bool
                          ) -> Optional[int]:
    """MLlib featureSubsetStrategy (RandomForestParams)."""
    s = str(strategy).lower()
    if s == "auto":
        s = "sqrt" if classification else "onethird"
    if s == "all":
        return None
    if s == "sqrt":
        return max(1, int(np.sqrt(d)))
    if s == "log2":
        return max(1, int(np.log2(d)))
    if s == "onethird":
        return max(1, d // 3)
    return max(1, min(d, int(float(s) * d) if "." in s else int(s)))


class _ForestClassifierBase(Predictor):
    num_trees = 1
    bootstrap = False

    def fit_arrays(self, X: np.ndarray, y: np.ndarray
                   ) -> TreeEnsembleClassifierModel:
        k = max(2, int(np.max(y)) + 1 if len(y) else 2)
        d = X.shape[1]
        mf = _resolve_max_features(self.feature_subset_strategy, d, True) \
            if self.bootstrap else None
        feats, thrs, leaves = _fit_forest_classifier(
            jnp.asarray(X), jnp.asarray(y),
            jax.random.PRNGKey(self.seed), depth=self.max_depth,
            max_bins=self.max_bins, num_classes=k,
            num_trees=self.num_trees, max_features=mf,
            impurity=self.impurity,
            min_instances=float(self.min_instances_per_node),
            min_info_gain=self.min_info_gain,
            subsample=self.subsampling_rate, bootstrap=self.bootstrap)
        return TreeEnsembleClassifierModel(feats, thrs, leaves,
                                           depth=self.max_depth,
                                           n_features=d)


class _ForestRegressorBase(Predictor):
    num_trees = 1
    bootstrap = False

    def fit_arrays(self, X: np.ndarray, y: np.ndarray
                   ) -> TreeEnsembleRegressorModel:
        d = X.shape[1]
        mf = _resolve_max_features(self.feature_subset_strategy, d, False) \
            if self.bootstrap else None
        feats, thrs, leaves = _fit_forest_regressor(
            jnp.asarray(X), jnp.asarray(y),
            jax.random.PRNGKey(self.seed), depth=self.max_depth,
            max_bins=self.max_bins, num_trees=self.num_trees,
            max_features=mf,
            min_instances=float(self.min_instances_per_node),
            min_info_gain=self.min_info_gain,
            subsample=self.subsampling_rate, bootstrap=self.bootstrap)
        return TreeEnsembleRegressorModel(feats, thrs, leaves,
                                          depth=self.max_depth,
                                          n_features=d)


class DecisionTreeClassifier(_ForestClassifierBase):
    """Single CART tree, gini/entropy impurity
    (reference OpDecisionTreeClassifier.scala)."""

    def __init__(self, max_depth: int = 5, max_bins: int = 32,
                 min_instances_per_node: int = 1, min_info_gain: float = 0.0,
                 impurity: str = "gini", seed: int = 42,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.max_depth = max_depth
        self.max_bins = max_bins
        self.min_instances_per_node = min_instances_per_node
        self.min_info_gain = min_info_gain
        self.impurity = impurity
        self.seed = seed
        self.num_trees = 1
        self.bootstrap = False
        self.subsampling_rate = 1.0
        self.feature_subset_strategy = "all"


class DecisionTreeRegressor(_ForestRegressorBase):
    """(reference OpDecisionTreeRegressor.scala)"""

    def __init__(self, max_depth: int = 5, max_bins: int = 32,
                 min_instances_per_node: int = 1, min_info_gain: float = 0.0,
                 seed: int = 42, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.max_depth = max_depth
        self.max_bins = max_bins
        self.min_instances_per_node = min_instances_per_node
        self.min_info_gain = min_info_gain
        self.seed = seed
        self.num_trees = 1
        self.bootstrap = False
        self.subsampling_rate = 1.0
        self.feature_subset_strategy = "all"


class RandomForestClassifier(_ForestClassifierBase):
    """Bagged gini trees with per-node feature subsampling
    (reference OpRandomForestClassifier.scala). Bootstrap resampling uses
    Poisson(subsamplingRate) row weights — the same approximation Spark
    MLlib's BaggedPoint uses for sampling with replacement."""

    def __init__(self, num_trees: int = 20, max_depth: int = 5,
                 max_bins: int = 32, min_instances_per_node: int = 1,
                 min_info_gain: float = 0.0, subsampling_rate: float = 1.0,
                 feature_subset_strategy: str = "auto", impurity: str = "gini",
                 seed: int = 42, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.num_trees = num_trees
        self.max_depth = max_depth
        self.max_bins = max_bins
        self.min_instances_per_node = min_instances_per_node
        self.min_info_gain = min_info_gain
        self.subsampling_rate = subsampling_rate
        self.feature_subset_strategy = feature_subset_strategy
        self.impurity = impurity
        self.seed = seed
        self.bootstrap = True


class RandomForestRegressor(_ForestRegressorBase):
    """(reference OpRandomForestRegressor.scala)"""

    def __init__(self, num_trees: int = 20, max_depth: int = 5,
                 max_bins: int = 32, min_instances_per_node: int = 1,
                 min_info_gain: float = 0.0, subsampling_rate: float = 1.0,
                 feature_subset_strategy: str = "auto", seed: int = 42,
                 uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.num_trees = num_trees
        self.max_depth = max_depth
        self.max_bins = max_bins
        self.min_instances_per_node = min_instances_per_node
        self.min_info_gain = min_info_gain
        self.subsampling_rate = subsampling_rate
        self.feature_subset_strategy = feature_subset_strategy
        self.seed = seed
        self.bootstrap = True


class GBTClassifier(Predictor):
    """Gradient-boosted binary classifier with second-order (XGBoost-style)
    split gains on the logistic objective (reference OpGBTClassifier.scala;
    MLlib GBT uses first-order residual fitting — the second-order variant
    strictly dominates and is the XGBoost parity path, SURVEY §2.9)."""

    def __init__(self, num_rounds: int = 20, max_depth: int = 5,
                 step_size: float = 0.1, max_bins: int = 32,
                 reg_lambda: float = 1.0, gamma: float = 0.0,
                 min_child_weight: float = 1.0, subsample: float = 1.0,
                 seed: int = 42, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.num_rounds = num_rounds
        self.max_depth = max_depth
        self.step_size = step_size
        self.max_bins = max_bins
        self.reg_lambda = reg_lambda
        self.gamma = gamma
        self.min_child_weight = min_child_weight
        self.subsample = subsample
        self.seed = seed

    def fit_arrays(self, X: np.ndarray, y: np.ndarray) -> GBTClassifierModel:
        bad = np.setdiff1d(np.unique(y), [0.0, 1.0])
        if bad.size:
            raise ValueError(
                f"GBTClassifier supports binary labels {{0, 1}} only "
                f"(as MLlib GBTClassifier does); got extra labels "
                f"{bad.tolist()} — use RandomForestClassifier or "
                f"LogisticRegression for multiclass")
        feats, thrs, leaves, base = _fit_gbt(
            jnp.asarray(X), jnp.asarray(y),
            jax.random.PRNGKey(self.seed), depth=self.max_depth,
            max_bins=self.max_bins, num_rounds=self.num_rounds,
            step_size=self.step_size, reg_lambda=self.reg_lambda,
            gamma=self.gamma, min_child_weight=self.min_child_weight,
            subsample=self.subsample, objective="logistic")
        return GBTClassifierModel(feats, thrs, leaves, depth=self.max_depth,
                                  base=float(base), n_features=X.shape[1])


class GBTRegressor(Predictor):
    """Gradient-boosted regressor, squared loss
    (reference OpGBTRegressor.scala)."""

    def __init__(self, num_rounds: int = 20, max_depth: int = 5,
                 step_size: float = 0.1, max_bins: int = 32,
                 reg_lambda: float = 1.0, gamma: float = 0.0,
                 min_child_weight: float = 1.0, subsample: float = 1.0,
                 seed: int = 42, uid: Optional[str] = None):
        super().__init__(uid=uid)
        self.num_rounds = num_rounds
        self.max_depth = max_depth
        self.step_size = step_size
        self.max_bins = max_bins
        self.reg_lambda = reg_lambda
        self.gamma = gamma
        self.min_child_weight = min_child_weight
        self.subsample = subsample
        self.seed = seed

    def fit_arrays(self, X: np.ndarray, y: np.ndarray) -> GBTRegressorModel:
        feats, thrs, leaves, base = _fit_gbt(
            jnp.asarray(X), jnp.asarray(y),
            jax.random.PRNGKey(self.seed), depth=self.max_depth,
            max_bins=self.max_bins, num_rounds=self.num_rounds,
            step_size=self.step_size, reg_lambda=self.reg_lambda,
            gamma=self.gamma, min_child_weight=self.min_child_weight,
            subsample=self.subsample, objective="squared")
        return GBTRegressorModel(feats, thrs, leaves, depth=self.max_depth,
                                 base=float(base), n_features=X.shape[1])


class XGBoostClassifier(GBTClassifier):
    """XGBoost-parameter-named facade over the same histogram booster
    (reference OpXGBoostClassifier.scala:47 — the reference's only native
    C++ component, xgboost4j + Rabit; here the booster IS the second-order
    histogram GBT above, with multi-chip reduction via psum, SURVEY §2.9)."""

    def __init__(self, eta: float = 0.3, max_depth: int = 6,
                 num_round: int = 100, reg_lambda: float = 1.0,
                 gamma: float = 0.0, min_child_weight: float = 1.0,
                 subsample: float = 1.0, max_bins: int = 256,
                 seed: int = 42, uid: Optional[str] = None):
        GBTClassifier.__init__(
            self, num_rounds=num_round, max_depth=max_depth, step_size=eta,
            max_bins=max_bins, reg_lambda=reg_lambda, gamma=gamma,
            min_child_weight=min_child_weight, subsample=subsample,
            seed=seed, uid=uid)
        self.eta = eta
        self.num_round = num_round


class XGBoostRegressor(GBTRegressor):
    """(reference OpXGBoostRegressor.scala)"""

    def __init__(self, eta: float = 0.3, max_depth: int = 6,
                 num_round: int = 100, reg_lambda: float = 1.0,
                 gamma: float = 0.0, min_child_weight: float = 1.0,
                 subsample: float = 1.0, max_bins: int = 256,
                 seed: int = 42, uid: Optional[str] = None):
        GBTRegressor.__init__(
            self, num_rounds=num_round, max_depth=max_depth, step_size=eta,
            max_bins=max_bins, reg_lambda=reg_lambda, gamma=gamma,
            min_child_weight=min_child_weight, subsample=subsample,
            seed=seed, uid=uid)
        self.eta = eta
        self.num_round = num_round
