"""Native (C++) runtime kernels with numpy fallbacks (SURVEY §2.9)."""
from .build import histogram_merge_kernel, load_kernel

__all__ = ["load_kernel", "histogram_merge_kernel"]
