"""Build-on-demand loader for the native kernels.

The image ships a full C++ toolchain but no pybind11, so native pieces
are plain ``extern "C"`` shared objects compiled with g++ at first use
(cached by source hash) and bound with ctypes — the same
runtime-native-code posture the reference gets from its JNI
dependencies (SURVEY §2.9), without a build step for pure-Python users:
every native kernel has a numpy fallback and ``TX_NO_NATIVE=1``
disables compilation entirely.
"""
from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
import sys
from typing import Optional

_log = logging.getLogger(__name__)

_SRC_DIR = os.path.dirname(os.path.abspath(__file__))
_CACHE_DIR = os.environ.get(
    "TX_NATIVE_CACHE",
    os.path.join(os.path.dirname(os.path.dirname(_SRC_DIR)),
                 ".native_cache"))

_loaded: dict = {}


def load_kernel(source_name: str) -> Optional[ctypes.CDLL]:
    """Compile (if needed) and dlopen a kernel source from this package;
    returns None when native is disabled or the build fails (callers
    fall back to their numpy paths)."""
    if os.environ.get("TX_NO_NATIVE") == "1":
        return None
    if source_name in _loaded:
        return _loaded[source_name]
    src = os.path.join(_SRC_DIR, source_name)
    try:
        with open(src, "rb") as fh:
            digest = hashlib.sha1(fh.read()).hexdigest()[:16]
        so_path = os.path.join(
            _CACHE_DIR, f"{os.path.splitext(source_name)[0]}-{digest}.so")
        if not os.path.exists(so_path):
            os.makedirs(_CACHE_DIR, exist_ok=True)
            tmp = f"{so_path}.tmp.{os.getpid()}"
            cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
                   src, "-o", tmp]
            subprocess.run(cmd, check=True, capture_output=True,
                           timeout=120)
            os.replace(tmp, so_path)   # atomic vs concurrent builders
        lib = ctypes.CDLL(so_path)
    except Exception as e:
        _log.warning("native kernel %s unavailable (%s); using numpy "
                     "fallback", source_name, e)
        lib = None
    _loaded[source_name] = lib
    return lib


def histogram_merge_kernel():
    """ctypes binding for hist_merge (streaming_histogram.cpp), or None."""
    lib = load_kernel("streaming_histogram.cpp")
    if lib is None:
        return None
    fn = lib.hist_merge
    fn.restype = ctypes.c_int64
    fn.argtypes = [ctypes.POINTER(ctypes.c_double),
                   ctypes.POINTER(ctypes.c_double),
                   ctypes.c_int64, ctypes.c_int64]
    return fn
