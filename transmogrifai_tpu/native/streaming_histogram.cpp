// Streaming-histogram bin merging — native kernel.
//
// TPU-native counterpart of the reference's one in-tree native-path
// source (utils/src/main/java/com/salesforce/op/utils/stats/
// StreamingHistogram.java:36, Ben-Haim/Tom-Tov): given SORTED
// (centroid, count) bins, repeatedly merge the closest adjacent pair
// until at most max_bins remain. The Java reference (and the numpy
// fallback in utils/histogram.py) rescans for the minimum gap each
// round — O(k^2); here a lazy-deletion min-heap over gap candidates
// with doubly-linked neighbor indices gives O(k log k), which is what
// makes batch inserts of ~1e6 raw points per feature practical in
// RawFeatureFilter.
//
// Built on demand by transmogrifai_tpu/native/build.py via
//   g++ -O2 -shared -fPIC; loaded with ctypes (no pybind11 in image).

#include <cstdint>
#include <queue>
#include <utility>
#include <vector>

extern "C" {

// In-place merge; returns the new bin count. c and n are length `size`,
// sorted ascending by c; results are compacted into the array prefix.
int64_t hist_merge(double* c, double* n, int64_t size, int64_t max_bins) {
    if (size <= max_bins || size < 2) return size;

    std::vector<int64_t> prev(size), next(size);
    std::vector<bool> dead(size, false);
    for (int64_t i = 0; i < size; ++i) {
        prev[i] = i - 1;
        next[i] = (i + 1 < size) ? i + 1 : -1;
    }

    // min-heap of (gap, left-index); stale entries are skipped lazily by
    // re-checking the CURRENT gap when popped. Ties break on the lower
    // index, matching numpy argmin's first-occurrence rule.
    using Entry = std::pair<double, int64_t>;
    std::priority_queue<Entry, std::vector<Entry>, std::greater<Entry>> heap;
    for (int64_t i = 0; i + 1 < size; ++i)
        heap.push({c[i + 1] - c[i], i});

    int64_t remaining = size;
    while (remaining > max_bins && !heap.empty()) {
        auto [gap, i] = heap.top();
        heap.pop();
        if (dead[i]) continue;
        int64_t j = next[i];
        if (j < 0 || dead[j]) continue;
        if (c[j] - c[i] != gap) continue;          // stale gap entry
        // merge j into i (weighted centroid)
        double tot = n[i] + n[j];
        c[i] = (c[i] * n[i] + c[j] * n[j]) / tot;
        n[i] = tot;
        dead[j] = true;
        int64_t k = next[j];
        next[i] = k;
        if (k >= 0) {
            prev[k] = i;
            heap.push({c[k] - c[i], i});
        }
        int64_t p = prev[i];
        if (p >= 0) heap.push({c[i] - c[p], p});
        --remaining;
    }

    // compact live bins into the prefix
    int64_t w = 0;
    for (int64_t i = 0; i >= 0; i = next[i]) {
        c[w] = c[i];
        n[w] = n[i];
        ++w;
    }
    return w;
}

}  // extern "C"
