"""End-to-end observability: span tracing, live serving metrics, and
the persisted performance-profile store (docs/observability.md).

- :mod:`.trace` — Dapper-style spans across train / search / serve
  with near-zero disabled cost (``TX_TRACE=1|/path.jsonl``), JSONL
  export, Perfetto conversion.
- :mod:`.metrics` — streaming per-tenant latency histograms + the
  metrics-endpoint snapshot schema.
- :mod:`.store` — the atomic-merge ``BENCH_STATE.json`` writer:
  per-(stage, family, bucket) cost records and the bench probe
  verdict, accumulated across runs for the telemetry-autotuning
  roadmap item.
"""
from . import trace
from .metrics import (METRICS_SCHEMA_VERSION, LatencyHistogram,
                      ServeMetrics)
from .store import (ProfileStore, default_store_path,
                    gather_process_profiles, persist_process_profiles)

__all__ = ["trace", "LatencyHistogram", "ServeMetrics",
           "METRICS_SCHEMA_VERSION", "ProfileStore",
           "default_store_path", "gather_process_profiles",
           "persist_process_profiles"]
