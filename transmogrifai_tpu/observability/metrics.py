"""Live serving metrics: streaming latency histograms + the snapshot
schema the metrics endpoint serves.

The serving loop (serving/server.py) was observable only POST-MORTEM —
``describe()`` after ``stop()``. This module gives it a live view:
per-tenant request-latency histograms built on the same fixed-memory
:class:`~..utils.histogram.StreamingHistogram` the drift sentinel uses
(bounded bins, so a month-long serve process holds constant memory),
plus one :func:`snapshot` shape answered by the ``{"metrics": true}``
TCP control request and the ``tx serve --metrics-port`` HTTP endpoint
(docs/observability.md documents the schema).
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from ..utils.histogram import StreamingHistogram

__all__ = ["METRICS_SCHEMA_VERSION", "LatencyHistogram", "ServeMetrics"]

#: bump when the snapshot shape changes (the endpoint's contract)
#: v2: per-tenant "sentinels" drift state + the "lifecycle" slice
#: v3: top-level "process" block (uptime, restart generation,
#:     draining/ready flags, in-flight count, last snapshot age) +
#:     "plan_compiles" — the restart-drill contract
#:     (docs/serving_restart.md)
#: v4: top-level "admission" block (overload admission state: brownout
#:     state + transitions, pressure, lane bound / DRR quantum,
#:     measured drain rate, per-tenant weight/admitted/shed counts,
#:     knob decisions) — {"enabled": false} when the controller is off
#:     (docs/admission.md)
METRICS_SCHEMA_VERSION = 4


class LatencyHistogram:
    """Streaming latency sketch: fixed-size bins, exact count/min/max,
    interpolated quantiles — observe() is O(log bins) amortized and
    the memory never grows with traffic."""

    def __init__(self, max_bins: int = 64):
        self._hist = StreamingHistogram(max_bins=max_bins)
        self.count = 0
        self.min = float("inf")
        self.max = 0.0

    def observe(self, seconds: float) -> None:
        self.observe_many([seconds])

    def observe_many(self, seconds_batch) -> None:
        """One histogram merge for a whole batch of latencies — the
        serving loop observes per DISPATCH, not per request, so the
        numpy merge cost amortizes over the batch."""
        ms = [s * 1000.0 for s in seconds_batch]
        if not ms:
            return
        self._hist.update(ms)
        self.count += len(ms)
        self.min = min(self.min, min(ms))
        self.max = max(self.max, max(ms))

    def to_json(self) -> dict:
        if self.count == 0:
            return {"count": 0}
        return {
            "count": self.count,
            "p50_ms": round(self._hist.quantile(0.50), 3),
            "p95_ms": round(self._hist.quantile(0.95), 3),
            "p99_ms": round(self._hist.quantile(0.99), 3),
            "min_ms": round(self.min, 3),
            "max_ms": round(self.max, 3),
        }


class ServeMetrics:
    """The serving loop's live accumulators: per-tenant latency
    histograms + answered/failed counts. One instance per
    :class:`~..serving.server.ServingServer`; updated at request
    resolution (the executor side, never the event loop)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._latency: Dict[str, LatencyHistogram] = {}
        self.started_at = time.time()
        self.answered = 0
        self.failed = 0

    def observe(self, tenant: str, seconds: float) -> None:
        self.observe_batch(tenant, [seconds])

    def observe_batch(self, tenant: str, seconds_batch) -> None:
        with self._lock:
            hist = self._latency.get(tenant)
            if hist is None:
                hist = self._latency[tenant] = LatencyHistogram()
            hist.observe_many(seconds_batch)
            self.answered += len(seconds_batch)

    def note_failure(self) -> None:
        with self._lock:
            self.failed += 1

    def latency_json(self) -> Dict[str, dict]:
        with self._lock:
            return {t: h.to_json() for t, h in
                    sorted(self._latency.items())}

    def uptime_seconds(self) -> float:
        return time.time() - self.started_at
