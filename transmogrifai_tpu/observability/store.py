"""Persisted performance-profile store: the queryable cost record the
telemetry-autotuning roadmap item consumes.

One atomic-merge JSON writer over the repo-level ``BENCH_STATE.json``
(the only file that survives across bench rounds — /tmp does not): the
bench's ambient-backend probe verdict (+ transcript), and the
per-(stage, family, bucket) wall/compile/execute records that
``utils/compile_time`` sections and the validator's family profile
observe, all merge through the same read-modify-write (temp file +
``os.replace``) so concurrent writers never tear the store and repeated
runs ACCUMULATE cost history instead of overwriting it.

Layout (top-level keys are independent namespaces)::

    {
      "probe":    {"<jax>-<platform>": {healthy, note, time,
                                        transcript?}},
      "profiles": {"score:b64":        {calls, wall_seconds,
                                        compile_seconds,
                                        execute_seconds, rows,
                                        updated},
                   "family:GBT":       {...},
                   "prepare:seg:...":  {...}}
    }

``TX_PROFILE_STORE`` overrides the path (tests point it at a tmp dir).
"""
from __future__ import annotations

import json
import os
import time
from typing import Dict, Optional

__all__ = ["ProfileStore", "atomic_write_json", "default_store_path",
           "gather_process_profiles", "persist_process_profiles"]

#: accumulating numeric fields of one profile record; everything else
#: (``updated``, foreign keys) overwrites on merge
_ACCUMULATE = ("calls", "wall_seconds", "compile_seconds",
               "execute_seconds", "rows")


def atomic_write_json(path: str, doc: dict, *, indent: int = 1,
                      fsync: bool = False) -> bool:
    """THE shared state-file writer (lint rule TX-R04 enforces its use
    in ``serving/``): serialize ``doc`` to ``path + ".tmp"``, then
    ``os.replace`` onto the live path, so a concurrent reader never
    sees a torn document and a crashed writer leaves the previous
    state intact. Returns False (after cleaning up the temp file)
    instead of raising on an unwritable target."""
    tmp = path + ".tmp"
    try:
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(doc, fh, indent=indent, sort_keys=True)
            fh.write("\n")
            if fsync:
                fh.flush()
                os.fsync(fh.fileno())
        os.replace(tmp, path)
        return True
    except OSError:  # pragma: no cover - read-only checkout
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def default_store_path() -> str:
    """``TX_PROFILE_STORE`` if set, else the repo-level
    ``BENCH_STATE.json`` next to bench.py."""
    env = os.environ.get("TX_PROFILE_STORE")
    if env:
        return env
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.join(os.path.dirname(pkg), "BENCH_STATE.json")


class ProfileStore:
    """Atomic read-merge-write over one JSON file. Every mutation is a
    whole-file rewrite through a temp file + ``os.replace`` (the
    save_model idiom) so a concurrent reader never sees a torn store
    and a crashed writer leaves the previous state intact."""

    def __init__(self, path: Optional[str] = None):
        self.path = path or default_store_path()

    def load(self) -> dict:
        try:
            with open(self.path, encoding="utf-8") as fh:
                d = json.load(fh)
            return d if isinstance(d, dict) else {}
        except (OSError, ValueError):
            return {}

    def _write(self, state: dict) -> bool:
        return atomic_write_json(self.path, state)

    # -- probe verdicts (bench ambient-backend health) ---------------------
    def record_probe(self, key: str, healthy: bool, note: str,
                     transcript: Optional[list] = None) -> bool:
        """Merge one probe verdict under ``probe[key]`` — bench.py's
        writer, now shared with the profile records (the ROADMAP
        "hidden prerequisite": the probe's verdict AND its transcript
        persist across rounds in the same store)."""
        state = self.load()
        verdict = {"healthy": bool(healthy), "note": str(note),
                   "time": time.time()}
        if transcript is not None:
            verdict["transcript"] = list(transcript)
        state.setdefault("probe", {})[key] = verdict
        return self._write(state)

    def probe_verdict(self, key: str) -> Optional[dict]:
        return self.load().get("probe", {}).get(key)

    # -- cost profiles -----------------------------------------------------
    def record_profiles(self, records: Dict[str, dict]) -> bool:
        """Accumulate ``{key: {calls, wall_seconds, compile_seconds,
        execute_seconds, rows}}`` into ``profiles`` — numeric fields
        SUM (repeated runs build history), ``updated`` stamps the last
        contribution."""
        if not records:
            return True
        state = self.load()
        profiles = state.setdefault("profiles", {})
        now = time.time()
        for key, rec in records.items():
            cur = profiles.setdefault(key, {})
            for f in _ACCUMULATE:
                if f in rec:
                    total = round(float(cur.get(f, 0.0))
                                  + float(rec[f] or 0.0), 6)
                    cur[f] = int(total) if f in ("calls", "rows") \
                        else total
            cur["updated"] = now
        return self._write(state)

    def profiles(self, prefix: str = "") -> Dict[str, dict]:
        return {k: dict(v) for k, v in
                self.load().get("profiles", {}).items()
                if k.startswith(prefix)}


def gather_process_profiles() -> Dict[str, dict]:
    """Everything this process has measured so far, keyed for the
    store:

    - ``utils/compile_time`` sections (``prepare:*`` fit/segment
      labels, ``score:<plan>:b<bucket>`` dispatch labels — plan ids
      are process-local, so bucket labels normalize to
      ``score:b<bucket>``),
    - the validator's per-family compile/wall profile
      (``family:<Name>``).
    """
    from ..utils.compile_time import seconds_by_section
    out: Dict[str, dict] = {}

    def _acc(key: str, wall: float, compile_s: float, calls: int,
             rows: int = 0) -> None:
        rec = out.setdefault(key, {"calls": 0, "wall_seconds": 0.0,
                                   "compile_seconds": 0.0,
                                   "execute_seconds": 0.0, "rows": 0})
        rec["calls"] += int(calls)
        rec["wall_seconds"] += float(wall)
        rec["compile_seconds"] += float(compile_s)
        rec["execute_seconds"] += max(float(wall) - float(compile_s),
                                      0.0)
        rec["rows"] += int(rows)

    for label, rec in seconds_by_section().items():
        parts = label.split(":")
        if len(parts) == 3 and parts[2].startswith("b") \
                and parts[1].isdigit():
            label = f"{parts[0]}:{parts[2]}"     # strip the plan id
        _acc(label, rec["seconds"], rec["compile"], rec["calls"])

    try:
        from ..selector.validator import family_profile
        for row in family_profile():
            _acc(f"family:{row['family']}", row["seconds"],
                 row["compileSeconds"], row["calls"])
    except Exception:  # pragma: no cover - selector not imported yet
        pass
    return out


def persist_process_profiles(path: Optional[str] = None
                             ) -> Dict[str, dict]:
    """Gather + merge this process's cost records into the store; the
    bench modes call this after measuring, and a traced ``tx serve``
    session calls it at shutdown. Returns what was merged."""
    records = gather_process_profiles()
    ProfileStore(path).record_profiles(records)
    return records
