"""Persisted performance-profile store: the queryable cost record the
telemetry-autotuning roadmap item consumes.

One atomic-merge JSON writer over the repo-level ``BENCH_STATE.json``
(the only file that survives across bench rounds — /tmp does not): the
bench's ambient-backend probe verdict (+ transcript), and the
per-(stage, family, bucket) wall/compile/execute records that
``utils/compile_time`` sections and the validator's family profile
observe, all merge through the same read-modify-write (temp file +
``os.replace``) so concurrent writers never tear the store and repeated
runs ACCUMULATE cost history instead of overwriting it.

Layout (top-level keys are independent namespaces)::

    {
      "probe":    {"<jax>-<platform>": {healthy, note, time,
                                        transcript?}},
      "profiles": {"_schema":          1,
                   "_compacted":       {keys, calls, ...},   # if capped
                   "score:b64":        {calls, wall_seconds,
                                        compile_seconds,
                                        execute_seconds, rows,
                                        updated},
                   "family:GBT":       {...},
                   "placement:...":    {...},
                   "prepare:seg:...":  {...}},
      "tuning":   {"overrides": {"serving.target_batch": 32, ...}},
      "autotune": {...}    # TX_BENCH_MODE=autotune decision trail
    }

Reserved ``profiles`` keys start with ``_`` (real labels are
colon-namespaced section names): ``_schema`` versions the block, and
``_compacted`` is the loud marker + merged remainder the key cap
leaves behind. Concurrent writers serialize their read-merge-write
through an advisory ``flock`` on ``<path>.lock`` (best-effort — the
atomic replace alone already prevents torn documents; the lock
prevents LOST records when two processes merge at once).

``TX_PROFILE_STORE`` overrides the path (tests point it at a tmp dir);
``TX_PROFILE_KEY_CAP`` overrides the growth cap (default 512 keys).
"""
from __future__ import annotations

import contextlib
import json
import os
import time
from typing import Any, Dict, Optional

__all__ = ["ProfileStore", "atomic_write_json", "default_store_path",
           "gather_process_profiles", "persist_process_profiles",
           "PROFILES_SCHEMA"]

#: accumulating numeric fields of one profile record; everything else
#: (``updated``, foreign keys) overwrites on merge
_ACCUMULATE = ("calls", "wall_seconds", "compile_seconds",
               "execute_seconds", "rows")

#: version stamp written into ``profiles["_schema"]`` on every merge
PROFILES_SCHEMA = 1

#: growth cap on real profile keys before deterministic merge-out
_DEFAULT_KEY_CAP = 512


@contextlib.contextmanager
def _merge_lock(path: str):
    """Advisory cross-process lock for the read-merge-write cycle —
    two concurrent ``record_profiles`` calls must not both read the
    same base state and have the second ``os.replace`` erase the
    first's merge. Best-effort: platforms/paths without ``flock``
    degrade to the unlocked (still torn-free, possibly lossy)
    behavior."""
    try:
        import fcntl
    except ImportError:  # pragma: no cover - non-posix
        yield
        return
    try:
        fh = open(path + ".lock", "a+")
    except OSError:  # pragma: no cover - read-only checkout
        yield
        return
    try:
        fcntl.flock(fh.fileno(), fcntl.LOCK_EX)
        yield
    finally:
        with contextlib.suppress(OSError):
            fcntl.flock(fh.fileno(), fcntl.LOCK_UN)
        fh.close()


def atomic_write_json(path: str, doc: dict, *, indent: int = 1,
                      fsync: bool = False) -> bool:
    """THE shared state-file writer (lint rule TX-R04 enforces its use
    in ``serving/``): serialize ``doc`` to ``path + ".tmp"``, then
    ``os.replace`` onto the live path, so a concurrent reader never
    sees a torn document and a crashed writer leaves the previous
    state intact. Returns False (after cleaning up the temp file)
    instead of raising on an unwritable target."""
    tmp = path + ".tmp"
    try:
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(doc, fh, indent=indent, sort_keys=True)
            fh.write("\n")
            if fsync:
                fh.flush()
                os.fsync(fh.fileno())
        os.replace(tmp, path)
        return True
    except OSError:  # pragma: no cover - read-only checkout
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def default_store_path() -> str:
    """``TX_PROFILE_STORE`` if set, else the repo-level
    ``BENCH_STATE.json`` next to bench.py."""
    env = os.environ.get("TX_PROFILE_STORE")
    if env:
        return env
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.join(os.path.dirname(pkg), "BENCH_STATE.json")


class ProfileStore:
    """Atomic read-merge-write over one JSON file. Every mutation is a
    whole-file rewrite through a temp file + ``os.replace`` (the
    save_model idiom) so a concurrent reader never sees a torn store
    and a crashed writer leaves the previous state intact."""

    def __init__(self, path: Optional[str] = None):
        self.path = path or default_store_path()

    def load(self) -> dict:
        try:
            with open(self.path, encoding="utf-8") as fh:
                d = json.load(fh)
            return d if isinstance(d, dict) else {}
        except (OSError, ValueError):
            return {}

    def _write(self, state: dict) -> bool:
        return atomic_write_json(self.path, state)

    # -- probe verdicts (bench ambient-backend health) ---------------------
    def record_probe(self, key: str, healthy: bool, note: str,
                     transcript: Optional[list] = None) -> bool:
        """Merge one probe verdict under ``probe[key]`` — bench.py's
        writer, now shared with the profile records (the ROADMAP
        "hidden prerequisite": the probe's verdict AND its transcript
        persist across rounds in the same store)."""
        with _merge_lock(self.path):
            state = self.load()
            verdict = {"healthy": bool(healthy), "note": str(note),
                       "time": time.time()}
            if transcript is not None:
                verdict["transcript"] = list(transcript)
            state.setdefault("probe", {})[key] = verdict
            return self._write(state)

    def probe_verdict(self, key: str) -> Optional[dict]:
        return self.load().get("probe", {}).get(key)

    # -- cost profiles -----------------------------------------------------
    def record_profiles(self, records: Dict[str, dict]) -> bool:
        """Accumulate ``{key: {calls, wall_seconds, compile_seconds,
        execute_seconds, rows}}`` into ``profiles`` — numeric fields
        SUM (repeated runs build history), ``updated`` stamps the last
        contribution."""
        if not records:
            return True
        with _merge_lock(self.path):
            state = self.load()
            profiles = state.setdefault("profiles", {})
            now = time.time()
            for key, rec in records.items():
                if key.startswith("_"):     # reserved namespace
                    continue
                cur = profiles.setdefault(key, {})
                for f in _ACCUMULATE:
                    if f in rec:
                        total = round(float(cur.get(f, 0.0))
                                      + float(rec[f] or 0.0), 6)
                        cur[f] = int(total) if f in ("calls", "rows") \
                            else total
                cur["updated"] = now
            profiles["_schema"] = PROFILES_SCHEMA
            self._compact(profiles, now)
            return self._write(state)

    @staticmethod
    def _compact(profiles: Dict[str, Any], now: float) -> None:
        """Growth hardening: when real keys exceed the cap
        (``TX_PROFILE_KEY_CAP``, default 512), merge out the
        oldest/lowest-calls records — deterministic order (updated
        ascending, calls ascending, key) — into the loud
        ``_compacted`` marker, so ``BENCH_STATE.json`` stays bounded
        as bench modes and tenants multiply but no cost mass is ever
        silently dropped."""
        try:
            cap = int(os.environ.get("TX_PROFILE_KEY_CAP",
                                     _DEFAULT_KEY_CAP))
        except ValueError:
            cap = _DEFAULT_KEY_CAP
        if cap <= 0:
            return
        real = [k for k in profiles if not k.startswith("_")]
        excess = len(real) - cap
        if excess <= 0:
            return
        order = sorted(real, key=lambda k: (
            float(profiles[k].get("updated", 0.0)),
            int(profiles[k].get("calls", 0) or 0), k))
        merged = profiles.setdefault("_compacted", {
            "keys": 0, "calls": 0, "wall_seconds": 0.0,
            "compile_seconds": 0.0, "execute_seconds": 0.0,
            "rows": 0})
        for key in order[:excess]:
            rec = profiles.pop(key)
            merged["keys"] = int(merged.get("keys", 0)) + 1
            for f in _ACCUMULATE:
                total = round(float(merged.get(f, 0.0))
                              + float(rec.get(f, 0.0) or 0.0), 6)
                merged[f] = int(total) if f in ("calls", "rows") \
                    else total
        merged["updated"] = now
        try:
            from ..runtime import telemetry
            telemetry.count("profiles_compacted", excess)
            telemetry.event("profiles_compacted", evicted=excess,
                            cap=cap)
        except Exception:  # pragma: no cover - telemetry optional
            pass

    def record_ir_features(self, features: Dict[str, dict]) -> bool:
        """Attach the plan auditor's per-bucket lowered-IR features
        (op count, fusion count, byte sizes, canonical fingerprint —
        analysis/audit.py) under each profile record's ``ir`` field.
        OVERWRITE semantics, unlike the accumulating cost fields: the
        IR of a (plan, bucket) program is a fact about the current
        build, not a running total — re-auditing replaces it. Keys
        match the cost records (``score:b8``, ``prepare:seg0:b512``)
        so cost-model-v2 reads features and targets off one row."""
        if not features:
            return True
        with _merge_lock(self.path):
            state = self.load()
            profiles = state.setdefault("profiles", {})
            now = time.time()
            for key, doc in features.items():
                if key.startswith("_"):     # reserved namespace
                    continue
                cur = profiles.setdefault(key, {})
                cur["ir"] = dict(doc)
                cur["updated"] = now
            profiles["_schema"] = PROFILES_SCHEMA
            self._compact(profiles, now)
            return self._write(state)

    # -- occupancy histograms (rows per dispatch, pre-padding) -------------
    def record_occupancy(self, hists: Dict[str, Dict[int, int]]) -> bool:
        """Accumulate ``{namespace: {real_rows: dispatches}}`` under
        the ``occupancy`` block — the padded cost records can never
        recover the real batch-size distribution, and the lattice
        chooser (tuning/lattice.py) needs exactly that."""
        if not any(h for h in (hists or {}).values()):
            return True
        with _merge_lock(self.path):
            state = self.load()
            occ = state.setdefault("occupancy", {})
            for ns, hist in hists.items():
                dst = occ.setdefault(str(ns), {})
                for size, count in hist.items():
                    key = str(int(size))
                    dst[key] = int(dst.get(key, 0)) + int(count)
            return self._write(state)

    def occupancy(self, namespace: str = "score") -> Dict[int, int]:
        """Cross-run rows-per-dispatch histogram for one namespace."""
        block = self.load().get("occupancy", {}).get(namespace, {})
        out: Dict[int, int] = {}
        if isinstance(block, dict):
            for size, count in block.items():
                try:
                    out[int(size)] = int(count)
                except (TypeError, ValueError):
                    continue
        return out

    def profiles(self, prefix: str = "") -> Dict[str, dict]:
        """Real (non-reserved) profile records; ``_schema`` and
        ``_compacted`` are internal — read them via :meth:`meta`."""
        return {k: dict(v) for k, v in
                self.load().get("profiles", {}).items()
                if k.startswith(prefix) and not k.startswith("_")}

    def meta(self) -> Dict[str, Any]:
        """The reserved bookkeeping of the ``profiles`` block: schema
        version and (when the key cap has triggered) the compaction
        marker."""
        block = self.load().get("profiles", {})
        return {"schema": block.get("_schema"),
                "compacted": block.get("_compacted")}

    # -- tuning overrides (tx tune --set / --reset) ------------------------
    def tuning_overrides(self) -> Dict[str, Any]:
        """The persisted override block the TuningPolicy honors."""
        block = self.load().get("tuning", {})
        ov = block.get("overrides", {})
        return dict(ov) if isinstance(ov, dict) else {}

    def set_tuning_override(self, knob: str, value: Any) -> bool:
        with _merge_lock(self.path):
            state = self.load()
            block = state.setdefault("tuning", {})
            block.setdefault("overrides", {})[knob] = value
            block["updated"] = time.time()
            return self._write(state)

    def clear_tuning_overrides(self, knob: Optional[str] = None
                               ) -> bool:
        """Drop one override (or all, ``knob=None``)."""
        with _merge_lock(self.path):
            state = self.load()
            block = state.get("tuning", {})
            if knob is None:
                block.pop("overrides", None)
            else:
                block.get("overrides", {}).pop(knob, None)
            block["updated"] = time.time()
            state["tuning"] = block
            return self._write(state)

    # -- named bench blocks (TX_BENCH_MODE=restart_aot, ...) ---------------
    def record_section(self, name: str, doc: dict) -> bool:
        """Persist one named, timestamped bench/diagnostic block (e.g.
        ``aot_restart``) wholesale. Callers own the namespace — pick a
        name that is not one of the structural blocks (``profiles``,
        ``tuning``, ``autotune``, ``probes``)."""
        with _merge_lock(self.path):
            state = self.load()
            out = dict(doc)
            out["time"] = time.time()
            state[str(name)] = out
            return self._write(state)

    # -- autotune bench trail (TX_BENCH_MODE=autotune) ---------------------
    def record_autotune(self, doc: dict) -> bool:
        """Persist the bench's full TuningDecision list + tuned-vs-
        static deltas, so the perf trajectory records WHY a knob moved,
        not just that it did."""
        with _merge_lock(self.path):
            state = self.load()
            out = dict(doc)
            out["time"] = time.time()
            state["autotune"] = out
            return self._write(state)


def gather_process_profiles() -> Dict[str, dict]:
    """Everything this process has measured so far, keyed for the
    store:

    - ``utils/compile_time`` sections (``prepare:*`` fit/segment
      labels, ``score:<plan>:b<bucket>`` dispatch labels — plan ids
      are process-local, so bucket labels normalize to
      ``score:b<bucket>``),
    - the validator's per-family compile/wall profile
      (``family:<Name>``),
    - the fit-placement policy's measured (stage class, host|device)
      records (``placement:<Class>:<where>`` — what this process
      MEASURED, never the cross-run seeds it loaded), so the cost
      model and future processes see placement history.
    """
    from ..utils.compile_time import seconds_by_section
    out: Dict[str, dict] = {}

    def _acc(key: str, wall: float, compile_s: float, calls: int,
             rows: int = 0) -> None:
        rec = out.setdefault(key, {"calls": 0, "wall_seconds": 0.0,
                                   "compile_seconds": 0.0,
                                   "execute_seconds": 0.0, "rows": 0})
        rec["calls"] += int(calls)
        rec["wall_seconds"] += float(wall)
        rec["compile_seconds"] += float(compile_s)
        rec["execute_seconds"] += max(float(wall) - float(compile_s),
                                      0.0)
        rec["rows"] += int(rows)

    for label, rec in seconds_by_section().items():
        parts = label.split(":")
        if len(parts) == 3 and parts[2].startswith("b") \
                and parts[1].isdigit():
            label = f"{parts[0]}:{parts[2]}"     # strip the plan id
        _acc(label, rec["seconds"], rec["compile"], rec["calls"])

    try:
        from ..selector.validator import family_profile
        for row in family_profile():
            _acc(f"family:{row['family']}", row["seconds"],
                 row["compileSeconds"], row["calls"])
    except Exception:  # pragma: no cover - selector not imported yet
        pass

    try:
        from ..plans.placement import placement_report
        for row in placement_report():
            _acc(f"placement:{row['stage']}:{row['placement']}",
                 row["seconds"], row["compileSeconds"], row["calls"],
                 row["rows"])
    except Exception:  # pragma: no cover - plans not imported yet
        pass
    return out


def persist_process_profiles(path: Optional[str] = None
                             ) -> Dict[str, dict]:
    """Gather + merge this process's cost records into the store; the
    bench modes call this after measuring, and a traced ``tx serve``
    session calls it at shutdown. Returns what was merged."""
    records = gather_process_profiles()
    store = ProfileStore(path)
    store.record_profiles(records)
    try:
        # plan-auditor IR features (analysis/audit.py): any audit run
        # in this process leaves per-bucket op/fusion/bytes features —
        # merge them onto the same rows so cost-model-v2 has training
        # features next to the recorded costs from day one
        from ..analysis.audit import process_ir_features
        store.record_ir_features(process_ir_features())
    except Exception:  # pragma: no cover - analysis layer optional
        pass
    try:
        # real rows-per-dispatch histograms (plans/common.py
        # record_rows): the occupancy side of the lattice decision
        from ..plans.common import row_histograms
        store.record_occupancy(row_histograms())
    except Exception:  # pragma: no cover - plans not imported yet
        pass
    return records
