"""Structured span tracing: the Dapper-style correlation layer over the
whole train/search/serve pipeline (docs/observability.md).

The system's telemetry was write-only and fragmented: runtime counters
(runtime/telemetry.py), compile-time sections (utils/compile_time.py)
and bench-only profiles never correlated into "what did THIS request /
THIS train spend its time on". This module is the correlation
substrate:

- **Spans.** A span is one timed operation (``train``, ``search.rung``,
  ``serve.request``, ``score.dispatch``) with a parent, a trace id, and
  attributes. Parentage is a context-var stack per thread, so nested
  ``with span(...)`` blocks build the tree for free; cross-thread work
  (the validator's family pool, the serving executors) passes an
  explicit ``parent=current_ref()`` instead — context vars do not cross
  executor threads, and implicit inheritance there would lie.
- **Off by default, near-zero when off.** ``enabled()`` is one bool
  read; ``span()`` returns a shared no-op context manager and
  allocates NOTHING when tracing is disabled — the serving hot path
  pays one predicate per call site. Enable with ``TX_TRACE=1``
  (in-memory ring) or ``TX_TRACE=/path/trace.jsonl`` (also streamed to
  a schema-versioned JSONL file).
- **Monotonic clocks.** All span times are ``time.monotonic()``; the
  file header records an (epoch, monotonic) anchor pair so exporters
  (Perfetto) can place spans on the wall clock without any span paying
  a ``time.time()`` call.
- **Integration points.** ``utils/compile_time`` sections report into
  the CURRENT span as child spans carrying their compile/execute split
  (registered via :func:`configure`); ``runtime/telemetry.event``
  fault/retry/quarantine events attach to the current span as span
  events. Neither module imports this one at module level in reverse —
  the dependency is one-way (observability imports nothing from the
  pipeline).

In-memory spans live in a bounded ring (``TX_TRACE_BUFFER``, default
20000) so a long-lived traced server cannot grow without bound; the
JSONL stream is the durable record. ``python -m transmogrifai_tpu.cli
trace`` summarizes and converts a trace file (cli/trace.py).
"""
from __future__ import annotations

import contextvars
import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, Iterable, List, Optional, Tuple

__all__ = ["SCHEMA_VERSION", "configure", "configure_from_env",
           "enabled", "trace_path", "span", "add_span", "add_event",
           "current_ref", "new_request_id", "spans", "reset", "flush",
           "read_trace", "to_perfetto", "span_tree", "coverage"]

#: bump when the JSONL span record shape changes; the header line and
#: every span record carry it so readers can refuse foreign files
SCHEMA_VERSION = 1

_LOCK = threading.Lock()
_ENABLED = False
_PATH: Optional[str] = None
_FILE = None
_SPAN_IDS = itertools.count(1)
_REQ_IDS = itertools.count(1)
#: (epoch seconds, monotonic seconds) captured together: exporters map
#: monotonic span times onto the wall clock via this anchor
_ANCHOR = (time.time(), time.monotonic())

def _buffer_cap() -> int:
    try:
        return max(16, int(os.environ.get("TX_TRACE_BUFFER", "20000")))
    except ValueError:
        return 20000


_SPANS: "deque[dict]" = deque(maxlen=_buffer_cap())

#: per-thread/task stack of OPEN span records (contextvars: coroutines
#: on one loop each see their own stack; worker threads start empty)
_STACK: contextvars.ContextVar = contextvars.ContextVar(
    "tx_trace_stack", default=())


# ---------------------------------------------------------------------------
# configuration
# ---------------------------------------------------------------------------

def configure(enabled: bool, path: Optional[str] = None) -> None:
    """Turn tracing on/off at runtime. ``path`` additionally streams
    every finished span to a JSONL file (header line first). Also
    (un)registers the compile-time section observer so section
    wall/compile splits land as child spans of whatever span is open."""
    global _ENABLED, _PATH, _FILE, _SPANS
    if _PATH is not None and (not enabled or path != _PATH):
        _drain_pending()            # pending spans land before close
    with _LOCK:
        if _FILE is not None and (not enabled or path != _PATH):
            try:
                _FILE.close()
            except OSError:  # pragma: no cover - best effort
                pass
            _FILE = None
        _ENABLED = bool(enabled)
        _PATH = path if enabled else None
        if enabled and _SPANS.maxlen != _buffer_cap():
            _SPANS = deque(_SPANS, maxlen=_buffer_cap())
    from ..utils import compile_time
    compile_time.set_section_observer(_note_section if enabled else None)


def configure_from_env() -> bool:
    """Read ``TX_TRACE``: unset/``0``/empty disables, ``1`` enables the
    in-memory ring, anything else is a JSONL output path. Returns the
    resulting enabled state."""
    raw = os.environ.get("TX_TRACE", "").strip()
    if raw in ("", "0", "off", "false"):
        configure(False)
    elif raw in ("1", "on", "true"):
        configure(True)
    else:
        configure(True, path=raw)
    return _ENABLED


def enabled() -> bool:
    """One bool read — the hot-path predicate."""
    return _ENABLED


def trace_path() -> Optional[str]:
    return _PATH


def new_request_id() -> str:
    """Process-unique request id, generated at serving admission and
    propagated enqueue -> coalesce -> encode -> dispatch -> reply
    (serving/server.py); echoed in the JSON-lines response."""
    return f"req-{os.getpid():x}-{next(_REQ_IDS):x}"


# ---------------------------------------------------------------------------
# span emission
# ---------------------------------------------------------------------------

class _NoopSpan:
    """The shared disabled-path context manager: no allocation, no
    record, identity across calls (asserted in tests)."""
    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NOOP = _NoopSpan()


class _Span:
    __slots__ = ("rec", "_token")

    def __init__(self, rec: dict):
        self.rec = rec
        self._token = None

    def __enter__(self):
        stack = _STACK.get()
        rec = self.rec
        if rec["parent"] is None and stack:
            top = stack[-1]
            rec["parent"] = top["sid"]
            rec["trace"] = rec["trace"] or top["trace"]
        if rec["trace"] is None:
            rec["trace"] = f"t{rec['sid']}"
        self._token = _STACK.set(stack + (rec,))
        rec["t0"] = time.monotonic()
        return rec

    def __exit__(self, exc_type, exc, tb):
        rec = self.rec
        rec["dur"] = time.monotonic() - rec["t0"]
        if exc_type is not None:
            rec["attrs"]["status"] = "error"
            rec["attrs"]["error"] = f"{exc_type.__name__}: {exc}"
        if self._token is not None:
            _STACK.reset(self._token)
        _emit(rec)
        return False


def _new_rec(name: str, parent: Optional[int], trace_id: Optional[str],
             attrs: Dict[str, Any]) -> dict:
    return {"v": SCHEMA_VERSION, "sid": next(_SPAN_IDS), "parent": parent,
            "trace": trace_id, "name": name, "t0": 0.0, "dur": None,
            "attrs": attrs, "events": []}


def span(name: str, parent: Optional[Tuple[str, int]] = None,
         trace_id: Optional[str] = None, **attrs):
    """Context manager for one timed operation. With no explicit
    ``parent``, the innermost open span on this thread/task is the
    parent; pass ``parent=current_ref()`` captured BEFORE handing work
    to an executor to keep cross-thread spans in the tree."""
    if not _ENABLED:
        return _NOOP
    pid = None
    if parent is not None:
        trace_id = trace_id or parent[0]
        pid = parent[1]
    return _Span(_new_rec(name, pid, trace_id, attrs))


def add_span(name: str, start: float, end: float,
             parent: Optional[Tuple[str, int]] = None,
             trace_id: Optional[str] = None,
             attrs: Optional[dict] = None,
             events: Optional[List[dict]] = None) -> Optional[int]:
    """Retrospective span emission over an already-measured monotonic
    window — the serving loop reconstructs each request's
    wait/encode/dispatch/guard segments this way at resolve time
    instead of holding context managers open across async hops.
    Returns the span id (None when tracing is off)."""
    if not _ENABLED:
        return None
    pid = parent[1] if parent is not None else None
    if parent is not None and trace_id is None:
        trace_id = parent[0]
    rec = _new_rec(name, pid, trace_id, dict(attrs or {}))
    if rec["trace"] is None:
        rec["trace"] = f"t{rec['sid']}"
    rec["t0"] = float(start)
    rec["dur"] = max(float(end) - float(start), 0.0)
    if events:
        rec["events"] = list(events)
    _emit(rec)
    return rec["sid"]


def add_event(name: str, **fields) -> None:
    """Attach one timestamped event to the CURRENT open span (no-op
    when tracing is off or no span is open) — how runtime/telemetry
    fault/retry/quarantine events land inside the span that was doing
    the work when they fired."""
    if not _ENABLED:
        return
    stack = _STACK.get()
    if not stack:
        return
    stack[-1]["events"].append(
        {"name": name, "t": time.monotonic(), **fields})


def current_ref() -> Optional[Tuple[str, int]]:
    """(trace_id, span_id) of the innermost open span on this
    thread/task, or None — capture it before submitting work to an
    executor and pass it as ``span(parent=...)``."""
    if not _ENABLED:
        return None
    stack = _STACK.get()
    if not stack:
        return None
    top = stack[-1]
    return (top["trace"], top["sid"])


def _note_section(label: str, wall: float, compile_s: float) -> None:
    """utils/compile_time section observer: a closed section becomes a
    child span of the current span, carrying the compile/execute split
    (``execute = wall - compile``). Sections outside any span are
    dropped — a section is attribution detail, not a root operation."""
    if not _ENABLED:
        return
    stack = _STACK.get()
    if not stack:
        return
    top = stack[-1]
    now = time.monotonic()
    add_span(f"section:{label}", now - wall, now,
             parent=(top["trace"], top["sid"]),
             attrs={"compile_seconds": round(compile_s, 6),
                    "execute_seconds": round(max(wall - compile_s, 0.0),
                                             6)})


#: spans awaiting JSONL serialization — the hot path pays two atomic
#: deque appends; json.dumps + file I/O happen on the writer thread
#: (serialization on the serving EVENT LOOP cost 20% throughput and
#: 4x p99 in the serve_loop bench before this split)
_PENDING: "deque[dict]" = deque()
_WRITER = {"thread": None}


def _emit(rec: dict) -> None:
    _SPANS.append(rec)          # deque appends are atomic under the GIL
    if _PATH is not None:
        _PENDING.append(rec)
        th = _WRITER["thread"]
        if th is None or not th.is_alive():
            _start_writer()


def _start_writer() -> None:
    with _LOCK:
        th = _WRITER["thread"]
        if th is not None and th.is_alive():
            return
        th = threading.Thread(target=_writer_loop, daemon=True,
                              name="tx-trace-writer")
        _WRITER["thread"] = th
        th.start()


def _writer_loop() -> None:
    while _PATH is not None:
        time.sleep(0.05)
        _drain_pending()


def _open_file():
    """Call with _LOCK held."""
    global _FILE
    if _FILE is None and _PATH is not None:
        fresh = (not os.path.exists(_PATH)
                 or os.path.getsize(_PATH) == 0)
        _FILE = open(_PATH, "a", encoding="utf-8")
        if fresh:
            _FILE.write(json.dumps(
                {"kind": "header", "schema": SCHEMA_VERSION,
                 "anchor_epoch": _ANCHOR[0],
                 "anchor_monotonic": _ANCHOR[1],
                 "pid": os.getpid()}) + "\n")
    return _FILE


def _drain_pending() -> None:
    batch: List[dict] = []
    while True:
        try:
            batch.append(_PENDING.popleft())
        except IndexError:
            break
    if not batch:
        return
    with _LOCK:
        fh = _open_file()
        if fh is None:
            return
        fh.write("".join(
            json.dumps({"kind": "span", **r}, default=str) + "\n"
            for r in batch))


def flush() -> None:
    """Serialize every pending span to the JSONL file and fsync-level
    flush it — call before reading the file back."""
    _drain_pending()
    with _LOCK:
        if _FILE is not None:
            _FILE.flush()


def spans() -> List[dict]:
    """Snapshot of the in-memory span ring (finished spans only)."""
    with _LOCK:
        return [dict(s) for s in _SPANS]


def reset() -> None:
    """Drop buffered spans (test/bench isolation); the JSONL file, the
    id counters and the enabled state are untouched."""
    with _LOCK:
        _SPANS.clear()


# ---------------------------------------------------------------------------
# reading + analysis (tx trace, tests, bench)
# ---------------------------------------------------------------------------

def read_trace(path: str) -> Tuple[dict, List[dict]]:
    """(header meta, span records) from a JSONL trace file. Torn final
    lines (a killed writer) are dropped, same as the journal reader.

    A file may hold APPENDED segments from several traced processes
    (each starts with its own header); span ids are process-local, so
    sids/parents are rescoped per segment (seg * 1e9 + sid) and
    anonymous ``t<sid>`` trace ids get a segment prefix — spans from
    different runs never alias."""
    meta: dict = {"schema": SCHEMA_VERSION,
                  "anchor_epoch": _ANCHOR[0],
                  "anchor_monotonic": _ANCHOR[1]}
    out: List[dict] = []
    seg = 0
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue                    # torn tail
            kind = rec.pop("kind", "span")
            if kind == "header":
                if rec.get("schema", SCHEMA_VERSION) > SCHEMA_VERSION:
                    raise ValueError(
                        f"{path}: trace schema {rec.get('schema')} is "
                        f"newer than this reader ({SCHEMA_VERSION})")
                seg += 1
                meta.update(rec)
                meta["segments"] = seg
            elif kind == "span":
                base = max(seg - 1, 0) * 1_000_000_000
                if base:
                    rec["sid"] = rec.get("sid", 0) + base
                    if rec.get("parent") is not None:
                        rec["parent"] += base
                    tr = rec.get("trace")
                    if isinstance(tr, str) and tr.startswith("t") \
                            and tr[1:].isdigit():
                        rec["trace"] = f"s{seg}:{tr}"
                out.append(rec)
    return meta, out


def span_tree(records: Iterable[dict], trace_id: str) -> List[dict]:
    """The spans of one trace (request/train) as a nested tree:
    ``[{span, children: [...]}, ...]`` roots in start order."""
    recs = [r for r in records if r.get("trace") == trace_id]
    by_sid = {r["sid"]: {"span": r, "children": []} for r in recs}
    roots = []
    for r in sorted(recs, key=lambda r: r.get("t0", 0.0)):
        node = by_sid[r["sid"]]
        parent = by_sid.get(r.get("parent"))
        (parent["children"] if parent else roots).append(node)
    return roots


def coverage(records: Iterable[dict], trace_id: str) -> float:
    """Fraction of the trace's root span wall-clock covered by its
    direct child spans (overlaps merged) — the acceptance metric for
    request attribution (>= 0.95 for a traced serve request)."""
    roots = span_tree(records, trace_id)
    if not roots:
        return 0.0
    root = roots[0]["span"]
    total = root.get("dur") or 0.0
    if total <= 0:
        return 0.0
    windows = sorted(
        (c["span"]["t0"], c["span"]["t0"] + (c["span"]["dur"] or 0.0))
        for c in roots[0]["children"])
    covered, cur0, cur1 = 0.0, None, None
    for w0, w1 in windows:
        if cur0 is None:
            cur0, cur1 = w0, w1
        elif w0 <= cur1:
            cur1 = max(cur1, w1)
        else:
            covered += cur1 - cur0
            cur0, cur1 = w0, w1
    if cur0 is not None:
        covered += cur1 - cur0
    return min(covered / total, 1.0)


def to_perfetto(meta: dict, records: Iterable[dict]) -> dict:
    """Chrome/Perfetto ``trace_event`` JSON: complete ("X") events per
    span (one tid lane per trace id) + instant ("i") events for span
    events — load the result straight into ui.perfetto.dev."""
    base = meta.get("anchor_monotonic", _ANCHOR[1])
    lanes: Dict[str, int] = {}
    events: List[dict] = []
    for r in records:
        tid = lanes.setdefault(r.get("trace") or "?", len(lanes) + 1)
        ts_us = (r.get("t0", 0.0) - base) * 1e6
        events.append({
            "name": r.get("name", "?"), "cat": "span", "ph": "X",
            "ts": round(ts_us, 3),
            "dur": round((r.get("dur") or 0.0) * 1e6, 3),
            "pid": meta.get("pid", os.getpid()), "tid": tid,
            "args": {**(r.get("attrs") or {}),
                     "trace": r.get("trace"), "sid": r.get("sid")},
        })
        for ev in r.get("events", ()):
            events.append({
                "name": ev.get("name", "event"), "cat": "event",
                "ph": "i", "s": "t",
                "ts": round((ev.get("t", r.get("t0", 0.0)) - base) * 1e6,
                            3),
                "pid": meta.get("pid", os.getpid()), "tid": tid,
                "args": {k: v for k, v in ev.items()
                         if k not in ("name", "t")},
            })
    return {"traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {"schema": meta.get("schema", SCHEMA_VERSION)}}


# import-time default: a process started with TX_TRACE set traces from
# its first span without any explicit configure call (tx serve, bench)
if os.environ.get("TX_TRACE", "").strip() not in ("", "0", "off",
                                                  "false"):
    configure_from_env()
