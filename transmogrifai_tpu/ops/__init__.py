"""Automated feature engineering: vectorizers + Transmogrifier (SURVEY §2.5;
core/.../stages/impl/feature/)."""
from .categorical import (MultiPickListVectorizer, MultiPickListVectorizerModel,
                          OneHotVectorizer, OneHotVectorizerModel)
from .combiner import VectorsCombiner
from .date import DateToUnitCircleVectorizer
from .dsl import (AliasTransformer, FillMissingWithMean,
                  NumericBinaryTransformer, NumericScalarTransformer,
                  StandardScaler)
from .numeric import (BinaryVectorizer, IntegralVectorizer, RealVectorizer,
                      RealVectorizerModel)
from .text import (SmartTextVectorizer, SmartTextVectorizerModel,
                   TextHashVectorizer, TextTokenizer, tokenize)
from .transmogrify import TransmogrifierDefaults, transmogrify

__all__ = [
    "RealVectorizer", "RealVectorizerModel", "IntegralVectorizer",
    "BinaryVectorizer",
    "OneHotVectorizer", "OneHotVectorizerModel",
    "MultiPickListVectorizer", "MultiPickListVectorizerModel",
    "SmartTextVectorizer", "SmartTextVectorizerModel", "TextHashVectorizer",
    "TextTokenizer", "tokenize",
    "DateToUnitCircleVectorizer", "VectorsCombiner",
    "TransmogrifierDefaults", "transmogrify",
    "AliasTransformer", "FillMissingWithMean", "NumericBinaryTransformer",
    "NumericScalarTransformer", "StandardScaler",
]
