"""Automated feature engineering: vectorizers + Transmogrifier (SURVEY §2.5;
core/.../stages/impl/feature/)."""
from .bucketizers import (DecisionTreeNumericBucketizer,
                           DecisionTreeNumericMapBucketizer,
                          DecisionTreeNumericBucketizerModel,
                          DescalerTransformer, NumericBucketizer,
                          PercentileCalibrator, PercentileCalibratorModel,
                          ScalerTransformer, ScalingType)
from .categorical import (MultiPickListVectorizer, MultiPickListVectorizerModel,
                          OneHotVectorizer, OneHotVectorizerModel)
from .combiner import VectorsCombiner
from .date import (DateListPivot, DateListVectorizer,
                   DateToUnitCircleVectorizer)
from .derived import (DropIndicesByTransformer, EmailToPickList,
                      JaccardSimilarity, LangDetector, MimeTypeDetector,
                      NGramSimilarity, PhoneNumberParser, TextLenTransformer,
                      ToOccurTransformer, UrlToPickList)
from .dsl import (AliasTransformer, FillMissingWithMean,
                  NumericBinaryTransformer, NumericScalarTransformer,
                  StandardScaler)
from .geo import GeolocationVectorizer, GeolocationVectorizerModel
from .index import (IndexToString, PredictionDeIndexer, StringIndexer,
                    StringIndexerModel)
from .maps import (BinaryMapVectorizer, DateMapToUnitCircleVectorizer,
                   DateMapToUnitCircleVectorizerModel,
                   GeolocationMapVectorizer,
                   GeolocationMapVectorizerModel, MultiPickListMapVectorizer,
                   FilterMap,
                   RealMapVectorizer, RealMapVectorizerModel,
                   SmartTextMapVectorizer, SmartTextMapVectorizerModel,
                   TextMapLenEstimator, TextMapNullEstimator,
                   TextMapPivotVectorizer, TextMapPivotVectorizerModel)
from .derived import CollectionTransformer
from .ner import NameEntityRecognizer
from .numeric import (BinaryVectorizer, IntegralVectorizer, RealVectorizer,
                      RealVectorizerModel)
from .text import (SmartTextVectorizer, SmartTextVectorizerModel,
                   TextHashVectorizer, TextListHashVectorizer,
                   TextListNullTransformer, TextTokenizer,
                   tokenize)
from .text_advanced import (LDA, LDAModel, CountVectorizer,
                            CountVectorizerModel, TfIdfVectorizer,
                            TfIdfVectorizerModel, Word2Vec, Word2VecModel)
from .transmogrify import TransmogrifierDefaults, transmogrify

__all__ = [
    "RealVectorizer", "RealVectorizerModel", "IntegralVectorizer",
    "BinaryVectorizer",
    "OneHotVectorizer", "OneHotVectorizerModel",
    "MultiPickListVectorizer", "MultiPickListVectorizerModel",
    "SmartTextVectorizer", "SmartTextVectorizerModel", "TextHashVectorizer",
    "TextListHashVectorizer", "TextTokenizer", "tokenize",
    "DateToUnitCircleVectorizer", "DateListVectorizer", "DateListPivot",
    "VectorsCombiner",
    "TransmogrifierDefaults", "transmogrify",
    "AliasTransformer", "FillMissingWithMean", "NumericBinaryTransformer",
    "NumericScalarTransformer", "StandardScaler",
    "RealMapVectorizer", "RealMapVectorizerModel", "BinaryMapVectorizer",
    "TextMapPivotVectorizer", "TextMapPivotVectorizerModel",
    "MultiPickListMapVectorizer", "GeolocationMapVectorizer",
    "FilterMap", "TextMapLenEstimator", "TextMapNullEstimator",
    "GeolocationMapVectorizerModel",
    "GeolocationVectorizer", "GeolocationVectorizerModel",
    "NumericBucketizer", "NameEntityRecognizer", "DecisionTreeNumericBucketizer",
    "DecisionTreeNumericBucketizerModel",
    "DecisionTreeNumericMapBucketizer", "PercentileCalibrator",
    "PercentileCalibratorModel", "ScalerTransformer", "DescalerTransformer",
    "ScalingType",
    "StringIndexer", "StringIndexerModel", "IndexToString",
    "PredictionDeIndexer",
    "PhoneNumberParser", "EmailToPickList", "UrlToPickList",
    "MimeTypeDetector", "LangDetector", "TextLenTransformer",
    "NGramSimilarity", "JaccardSimilarity", "ToOccurTransformer",
    "TextListNullTransformer", "CollectionTransformer",
    "DropIndicesByTransformer",
    "CountVectorizer", "CountVectorizerModel", "TfIdfVectorizer",
    "TfIdfVectorizerModel", "Word2Vec", "Word2VecModel", "LDA", "LDAModel",
]
