"""Numeric bucketizers, percentile calibration, and scaling.

TPU-native ports of the reference numeric transforms
(core/src/main/scala/com/salesforce/op/stages/impl/feature/
{NumericBucketizer.scala, DecisionTreeNumericBucketizer.scala,
PercentileCalibrator.scala, ScalerTransformer.scala}):

- :class:`NumericBucketizer` — fixed split points -> one-hot bucket
  membership (+ optional null/invalid tracking).
- :class:`DecisionTreeNumericBucketizer` — label-aware buckets from the
  split thresholds of a single-feature decision tree (the reference
  fits a Spark DecisionTree; here it's the histogram tree builder from
  models/trees.py, so the whole fit is one XLA program).
- :class:`PercentileCalibrator` — maps values onto [0, buckets-1] by
  training-set quantiles (reference PercentileCalibrator with
  ``expectedDistribution`` uniform).
- :class:`ScalerTransformer` / :class:`DescalerTransformer` — invertible
  linear/log scaling; the descaler looks up the scaler's params through
  its input feature's origin stage (reference ScalerMetadata dance).
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..features.columns import FeatureColumn
from ..stages.base import (AllowLabelAsInput, BinaryEstimator, BinaryModel,
                           BinaryTransformer, UnaryEstimator, UnaryModel,
                           UnaryTransformer)
from ..types import OPNumeric, OPVector, Real, RealNN
from .vector_utils import NULL_INDICATOR, VectorColumnMetadata, vector_output

__all__ = ["NumericBucketizer", "DecisionTreeNumericBucketizer",
           "DecisionTreeNumericBucketizerModel", "PercentileCalibrator",
           "PercentileCalibratorModel", "ScalerTransformer",
           "DescalerTransformer", "ScalingType"]


class ScalingType:
    LINEAR = "linear"
    LOGARITHMIC = "logarithmic"


def _bucket_block(vals: np.ndarray, splits: Sequence[float],
                  feature, track_nulls: bool,
                  bucket_labels: Optional[Sequence[str]] = None,
                  grouping: Optional[str] = None
                  ) -> Tuple[List[np.ndarray], List[VectorColumnMetadata]]:
    """One-hot bucket membership columns for ascending ``splits``
    (buckets are [s_i, s_{i+1}) as in the reference/Spark Bucketizer)."""
    splits = list(splits)
    n_buckets = len(splits) - 1
    isnan = np.isnan(vals)
    idx = np.clip(np.searchsorted(splits, vals, side="right") - 1,
                  0, n_buckets - 1)
    block = np.zeros((len(vals), n_buckets))
    block[np.arange(len(vals))[~isnan], idx[~isnan]] = 1.0
    labels = list(bucket_labels) if bucket_labels else [
        f"{splits[i]}-{splits[i + 1]}" for i in range(n_buckets)]
    group = grouping if grouping is not None else feature.name
    metas = [VectorColumnMetadata(
        parent_feature_name=feature.name,
        parent_feature_type=feature.ftype.__name__,
        grouping=group, indicator_value=lab) for lab in labels]
    blocks = [block]
    if track_nulls:
        blocks.append(isnan.astype(np.float64))
        metas.append(VectorColumnMetadata(
            parent_feature_name=feature.name,
            parent_feature_type=feature.ftype.__name__,
            grouping=group, indicator_value=NULL_INDICATOR))
    return blocks, metas


class NumericBucketizer(UnaryTransformer):
    """(reference NumericBucketizer.scala)"""

    input_types = (OPNumeric,)
    output_type = OPVector

    def __init__(self, split_points: Sequence[float],
                 bucket_labels: Optional[Sequence[str]] = None,
                 track_nulls: bool = True, uid: Optional[str] = None):
        super().__init__(operation_name="numBucket", uid=uid)
        splits = [float(s) for s in split_points]
        if sorted(splits) != splits or len(splits) < 2:
            raise ValueError("split_points must be >= 2 ascending values")
        self.split_points = splits
        self.bucket_labels = list(bucket_labels) if bucket_labels else None
        if self.bucket_labels is not None and \
                len(self.bucket_labels) != len(splits) - 1:
            raise ValueError("need one label per bucket")
        self.track_nulls = track_nulls

    def transform_columns(self, cols: List[FeatureColumn]) -> FeatureColumn:
        vals = np.asarray(cols[0].data, dtype=np.float64)
        blocks, metas = _bucket_block(
            vals, self.split_points, self.input_features[0],
            self.track_nulls, self.bucket_labels)
        return vector_output(self.get_output().name, blocks, metas)


class DecisionTreeNumericBucketizerModel(AllowLabelAsInput, BinaryModel):
    input_types = (RealNN, OPNumeric)
    output_type = OPVector

    def __init__(self, split_points: Sequence[float],
                 track_nulls: bool = True, uid: Optional[str] = None):
        super().__init__(operation_name="dtNumBucket", uid=uid)
        self.split_points = [float(s) for s in split_points]
        self.track_nulls = track_nulls

    @property
    def should_split(self) -> bool:
        return len(self.split_points) > 2

    def transform_columns(self, cols: List[FeatureColumn]) -> FeatureColumn:
        vals = np.asarray(cols[-1].data, dtype=np.float64)
        blocks, metas = _bucket_block(
            vals, self.split_points, self.input_features[-1],
            self.track_nulls)
        return vector_output(self.get_output().name, blocks, metas)

    def transform_value(self, *values):
        vals = np.asarray([
            float("nan") if values[-1].value is None
            else float(values[-1].value)])
        blocks, metas = _bucket_block(
            vals, self.split_points, self.input_features[-1],
            self.track_nulls)
        out = vector_output("row", blocks, metas)
        return out.boxed(0)


class DecisionTreeNumericBucketizer(AllowLabelAsInput, BinaryEstimator):
    """Label-aware buckets from single-feature decision-tree thresholds
    (reference DecisionTreeNumericBucketizer.scala)."""

    input_types = (RealNN, OPNumeric)
    output_type = OPVector

    def __init__(self, max_depth: int = 2, max_bins: int = 32,
                 min_info_gain: float = 0.01,
                 min_instances_per_node: int = 1,
                 track_nulls: bool = True, uid: Optional[str] = None):
        super().__init__(operation_name="dtNumBucket", uid=uid)
        self.max_depth = max_depth
        self.max_bins = max_bins
        self.min_info_gain = min_info_gain
        self.min_instances_per_node = min_instances_per_node
        self.track_nulls = track_nulls

    def fit_columns(self, cols: List[FeatureColumn]
                    ) -> DecisionTreeNumericBucketizerModel:
        from ..models.trees import DecisionTreeClassifier
        y = np.asarray(cols[0].data, dtype=np.float64)
        x = np.asarray(cols[1].data, dtype=np.float64)
        ok = ~np.isnan(x) & ~np.isnan(y)
        splits: List[float] = []
        if ok.sum() >= 2 and len(np.unique(y[ok])) >= 2:
            tree = DecisionTreeClassifier(
                max_depth=self.max_depth, max_bins=self.max_bins,
                min_info_gain=self.min_info_gain,
                min_instances_per_node=self.min_instances_per_node,
            ).fit_arrays(x[ok].reshape(-1, 1), y[ok])
            thresholds = tree.thrs[np.isfinite(tree.thrs)]
            splits = sorted(set(float(t) for t in thresholds.ravel()))
        return DecisionTreeNumericBucketizerModel(
            split_points=[-math.inf] + splits + [math.inf],
            track_nulls=self.track_nulls)


class PercentileCalibratorModel(UnaryModel):
    input_types = (OPNumeric,)
    output_type = RealNN

    def __init__(self, quantiles: Sequence[float], buckets: int = 100,
                 uid: Optional[str] = None):
        super().__init__(operation_name="percentileCalibrator", uid=uid)
        self.quantiles = [float(q) for q in quantiles]
        self.buckets = buckets

    def transform_columns(self, cols: List[FeatureColumn]) -> FeatureColumn:
        vals = np.asarray(cols[0].data, dtype=np.float64)
        q = np.asarray(self.quantiles)
        ranks = np.searchsorted(q, np.nan_to_num(vals, nan=q[0]),
                                side="right") - 1
        out = np.clip(ranks, 0, self.buckets - 1).astype(np.float64)
        return FeatureColumn(ftype=RealNN, data=out)


class PercentileCalibrator(UnaryEstimator):
    """Map values to their training-set percentile bucket [0, buckets-1]
    (reference PercentileCalibrator.scala)."""

    input_types = (OPNumeric,)
    output_type = RealNN

    def __init__(self, buckets: int = 100, uid: Optional[str] = None):
        super().__init__(operation_name="percentileCalibrator", uid=uid)
        self.buckets = buckets

    def fit_columns(self, cols: List[FeatureColumn]
                    ) -> PercentileCalibratorModel:
        vals = np.asarray(cols[0].data, dtype=np.float64)
        ok = vals[~np.isnan(vals)]
        if ok.size == 0:
            qs = np.zeros(self.buckets)
        else:
            qs = np.quantile(ok, np.linspace(0, 1, self.buckets,
                                             endpoint=False))
        return PercentileCalibratorModel(quantiles=list(qs),
                                         buckets=self.buckets)


class ScalerTransformer(UnaryTransformer):
    """Invertible scaling (reference ScalerTransformer.scala +
    ScalingType enum): linear ``slope * x + intercept`` or logarithmic
    ``log(x)``."""

    input_types = (OPNumeric,)
    output_type = Real

    def __init__(self, scaling_type: str = ScalingType.LINEAR,
                 slope: float = 1.0, intercept: float = 0.0,
                 uid: Optional[str] = None):
        super().__init__(operation_name="scaler", uid=uid)
        if scaling_type not in (ScalingType.LINEAR,
                                ScalingType.LOGARITHMIC):
            raise ValueError(f"Unknown scaling type {scaling_type!r}")
        self.scaling_type = scaling_type
        self.slope = slope
        self.intercept = intercept

    def _scale(self, vals: np.ndarray) -> np.ndarray:
        if self.scaling_type == ScalingType.LINEAR:
            return self.slope * vals + self.intercept
        return np.log(vals)

    def _descale(self, vals: np.ndarray) -> np.ndarray:
        if self.scaling_type == ScalingType.LINEAR:
            return (vals - self.intercept) / self.slope
        return np.exp(vals)

    def transform_columns(self, cols: List[FeatureColumn]) -> FeatureColumn:
        vals = np.asarray(cols[0].data, dtype=np.float64)
        return FeatureColumn(ftype=Real, data=self._scale(vals))


class DescalerTransformer(BinaryTransformer):
    """Invert a ScalerTransformer: input 1 is the value to descale,
    input 2 any feature produced by the scaler whose transform to invert
    (reference DescalerTransformer.scala reads ScalerMetadata)."""

    input_types = (OPNumeric, OPNumeric)
    output_type = Real

    def __init__(self, uid: Optional[str] = None):
        super().__init__(operation_name="descaler", uid=uid)

    def _scaler(self) -> ScalerTransformer:
        origin = self.input_features[1].origin_stage
        if not isinstance(origin, ScalerTransformer):
            raise ValueError(
                "DescalerTransformer input 2 must be the output of a "
                "ScalerTransformer")
        return origin

    def transform_columns(self, cols: List[FeatureColumn]) -> FeatureColumn:
        vals = np.asarray(cols[0].data, dtype=np.float64)
        return FeatureColumn(ftype=Real, data=self._scaler()._descale(vals))


class DecisionTreeNumericMapBucketizerModel(AllowLabelAsInput, BinaryModel):
    from ..types import NumericMap as _NM
    input_types = (RealNN, _NM)
    output_type = OPVector

    def __init__(self, keys: Sequence[str],
                 split_points: Dict[str, Sequence[float]],
                 track_nulls: bool = True, uid: Optional[str] = None):
        super().__init__(operation_name="dtNumMapBucket", uid=uid)
        self.keys = list(keys)
        self.split_points = {k: [float(s) for s in v]
                             for k, v in split_points.items()}
        self.track_nulls = track_nulls

    def transform_columns(self, cols: List[FeatureColumn]) -> FeatureColumn:
        col = cols[-1]
        n = col.n_rows
        blocks, metas = [], []
        for k in self.keys:
            vals = np.full(n, np.nan)
            for i, m in enumerate(col.data):
                if m and k in m and m[k] is not None:
                    vals[i] = float(m[k])
            b, me = _bucket_block(vals, self.split_points[k],
                                  self.input_features[-1],
                                  self.track_nulls, grouping=k)
            blocks.extend(b)
            metas.extend(me)
        return vector_output(self.get_output().name, blocks, metas)


class DecisionTreeNumericMapBucketizer(AllowLabelAsInput, BinaryEstimator):
    """Per-KEY label-aware buckets for numeric maps
    (reference DecisionTreeNumericMapBucketizer.scala) — each key gets
    its own single-feature decision-tree split points."""

    from ..types import NumericMap as _NM
    input_types = (RealNN, _NM)
    output_type = OPVector

    def __init__(self, max_depth: int = 2, max_bins: int = 32,
                 min_info_gain: float = 0.01,
                 min_instances_per_node: int = 1,
                 track_nulls: bool = True,
                 allow_keys: Optional[Sequence[str]] = None,
                 uid: Optional[str] = None):
        super().__init__(operation_name="dtNumMapBucket", uid=uid)
        self.max_depth = max_depth
        self.max_bins = max_bins
        self.min_info_gain = min_info_gain
        self.min_instances_per_node = min_instances_per_node
        self.track_nulls = track_nulls
        self.allow_keys = list(allow_keys) if allow_keys else None

    def fit_columns(self, cols: List[FeatureColumn]
                    ) -> DecisionTreeNumericMapBucketizerModel:
        from .maps import _sorted_keys
        y = np.asarray(cols[0].data, dtype=np.float64)
        keys = _sorted_keys([cols[1]], self.allow_keys)[0]
        scalar = DecisionTreeNumericBucketizer(
            max_depth=self.max_depth, max_bins=self.max_bins,
            min_info_gain=self.min_info_gain,
            min_instances_per_node=self.min_instances_per_node,
            track_nulls=self.track_nulls)
        scalar.input_features = self.input_features
        splits: Dict[str, List[float]] = {}
        for k in keys:
            vals = np.full(len(y), np.nan)
            for i, m in enumerate(cols[1].data):
                if m and k in m and m[k] is not None:
                    vals[i] = float(m[k])
            vcol = FeatureColumn(ftype=self.input_types[1], data=vals)
            sub = scalar.fit_columns([cols[0], vcol])
            splits[k] = sub.split_points
        return DecisionTreeNumericMapBucketizerModel(
            keys=keys, split_points=splits, track_nulls=self.track_nulls)
